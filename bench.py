"""Benchmark: TPU wavefront engine vs the CPU BFS baseline.

Protocol (mirrors the reference's ``bench.sh`` wall-clock discipline, measured
from the checker's own run, reference ``src/checker.rs:230-233``):

 1. Parity gate on ``2pc check 5``: the TPU engine and the CPU oracle must
    agree on unique-state counts and discoveries (reference parity bar,
    ``examples/2pc.rs:125-140``).
 2. CPU baseline: multithreaded BFS on ``2pc check 6`` -> states/sec.
 3. TPU engine: wavefront check on ``2pc check 7`` (~2.7M generated states)
    -> states/sec.  A warm-up run amortizes jit compilation, as recommended
    for XLA benchmarking; the timed run uses the cached executable.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"states" counts generated states including duplicates, matching the
reference's ``states=`` counter semantics (``bfs.rs:235``).
"""

import json
import os
import sys
import time


def _time_run(spawn):
    t0 = time.monotonic()
    checker = spawn()
    checker.join()
    dt = max(time.monotonic() - t0, 1e-9)
    return checker, dt


def main():
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    # -- 1. parity gate ------------------------------------------------------
    sys5 = TwoPhaseSys(5)
    cpu5 = sys5.checker().spawn_bfs().join()
    tpu5 = sys5.checker().spawn_tpu(sync=True, capacity=1 << 17)
    parity = (
        cpu5.unique_state_count() == tpu5.unique_state_count() == 8832
        and set(cpu5.discoveries()) == set(tpu5.discoveries())
    )
    if not parity:
        print(
            json.dumps(
                {
                    "metric": "2pc states/sec (TPU wavefront)",
                    "value": 0.0,
                    "unit": "states/sec",
                    "vs_baseline": 0.0,
                    "error": "parity gate failed",
                    "cpu_unique": cpu5.unique_state_count(),
                    "tpu_unique": tpu5.unique_state_count(),
                }
            )
        )
        return 1

    # -- 2. CPU baseline (multithreaded BFS, reference's baseline shape) -----
    sys6 = TwoPhaseSys(6)
    cpu6, cpu_dt = _time_run(
        lambda: sys6.checker().threads(os.cpu_count() or 1).spawn_bfs()
    )
    cpu_sps = cpu6.state_count() / cpu_dt

    # -- 3. TPU wavefront on the large workload ------------------------------
    sys7 = TwoPhaseSys(7)
    caps = dict(capacity=1 << 21, frontier_capacity=1 << 15)
    # warm-up: compile (cached on the tensor model keyed by capacities)
    sys7.checker().spawn_tpu(sync=True, **caps)
    tpu7, tpu_dt = _time_run(lambda: sys7.checker().spawn_tpu(sync=True, **caps))
    tpu_sps = tpu7.state_count() / tpu_dt

    print(
        json.dumps(
            {
                "metric": "2pc check 7 states/sec (TPU wavefront)",
                "value": round(tpu_sps, 1),
                "unit": "states/sec",
                "vs_baseline": round(tpu_sps / cpu_sps, 3),
                "tpu_states": tpu7.state_count(),
                "tpu_unique": tpu7.unique_state_count(),
                "tpu_sec": round(tpu_dt, 3),
                "cpu_states_per_sec": round(cpu_sps, 1),
                "cpu_states": cpu6.state_count(),
                "cpu_sec": round(cpu_dt, 3),
                "parity": "2pc check 5: unique=8832 + discoveries match",
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
