"""Benchmark: TPU wavefront engine vs the CPU BFS baseline.

Driver metric (BASELINE.md): **states/sec on ``paxos check 3`` + ``2pc
check 4``, with discovery-count parity**; north-star ≥20× the multithreaded
CPU BfsChecker on ``paxos check 3``.  Protocol (mirrors the reference's
``bench.sh`` wall-clock discipline, reference ``src/checker.rs:230-233``):

 1. CPU phase (pure host Python, no device contact): pinned-count parity
    runs on ``paxos check 2`` (16,668, ``examples/paxos.rs:291``) and ``2pc
    check 5`` (8,832, ``examples/2pc.rs:133``), then baseline states/sec on
    a bounded prefix of ``paxos check 3`` (states/sec is rate-like, so a
    prefix measures it fairly without a multi-hour full Python run), ``2pc
    check 4`` full, and ``2pc check 6`` full.
 2. TPU phase, run in SUBPROCESSES with a hard wall-clock budget: the
    axon backend has been observed to hang indefinitely inside PJRT client
    creation, and a hang in-process would mean no benchmark line at all
    (round 1's failure mode; round 2 lost the whole phase to ONE 600s init
    hang).  The orchestration is therefore hang-hostile:
      - a tiny init-only PROBE child (120s, then 240s) fails fast when the
        backend is wedged, so full attempts only start against a backend
        that has proven it can come up;
      - the full child is retried in FRESH processes until the whole
        ``BENCH_TPU_TIMEOUT`` budget is spent — a transient init hang costs
        one watchdog window, not the phase;
      - the child appends its cumulative results to a stage file after
        EVERY completed milestone, so a watchdog kill salvages the parity
        and throughput numbers that did land instead of only stderr marks.
    The child re-runs the parity configs on device, then times ``paxos
    check 3`` and ``2pc check 7`` after a warm-up run each (cached XLA
    executable, standard XLA benchmarking practice).  Transient
    ``UNAVAILABLE`` backend errors are retried once in-process.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}
— ALWAYS.  On TPU failure/timeout the line still carries the CPU numbers
plus an ``error`` field.  "states" counts generated states including
duplicates, matching the reference's ``states=`` counter (``bfs.rs:235``).

Env knobs: ``BENCH_TPU_TIMEOUT`` (secs, default 1800) bounds the whole TPU
phase; ``BENCH_TPU_TARGET`` caps the paxos-3 device run's unique states
(default: empty = FULL enumeration — the complete space is 1,194,428
unique states, which the wavefront engine finishes in ~10s warm, so the
primary metric is a complete check with its count pinned, not a prefix).
"""

import json
import os
import subprocess
import sys
import time
import traceback

PAXOS2_UNIQUE = 16_668  # examples/paxos.rs:291
TPC5_UNIQUE = 8_832  # examples/2pc.rs:133
CPU_TARGET = 12_000  # unique-state cap for the CPU paxos-3 baseline prefix

RESULT = {
    "metric": "paxos check 3 states/sec (TPU wavefront)",
    "value": 0.0,
    "unit": "states/sec",
    "vs_baseline": 0.0,
}


def emit(**extras) -> None:
    RESULT.update(extras)
    print(json.dumps(RESULT))


def timed(spawn):
    t0 = time.monotonic()
    checker = spawn()
    checker.join()
    dt = max(time.monotonic() - t0, 1e-9)
    return checker, dt


def with_tpu_retry(fn, retries: int = 1, delay: float = 30.0):
    """Run ``fn``; retry once on a transient backend failure (a stale chip
    lock from a crashed predecessor process manifests as UNAVAILABLE)."""
    for attempt in range(retries + 1):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - classified below
            transient = "UNAVAILABLE" in str(e) or "ALREADY_EXISTS" in str(e)
            if attempt >= retries or not transient:
                raise
            sys.stderr.write(
                f"bench: transient backend error, retrying in {delay}s: {e}\n"
            )
            time.sleep(delay)


# ---------------------------------------------------------------------------
# CPU phase (parent process; never touches a device backend)
# ---------------------------------------------------------------------------


def cpu_phase() -> dict:
    from stateright_tpu.models.paxos import paxos_model
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    threads = os.cpu_count() or 1
    out: dict = {
        # honesty note (VERDICT r2 weak #3): the "multithreaded" CPU
        # baseline is CPython, so threads(N) shares the GIL and the
        # effective baseline is ~single-core Python — a weaker bar than the
        # reference's all-cores Rust BfsChecker, which publishes no absolute
        # numbers to compare against (SURVEY §6)
        "cpu_baseline_note": (
            f"threads({threads}) under the CPython GIL ~= single-core"
        ),
    }

    cpu_p2 = paxos_model(2).checker().threads(threads).spawn_bfs().join()
    cpu_t5 = TwoPhaseSys(5).checker().threads(threads).spawn_bfs().join()
    if cpu_p2.unique_state_count() != PAXOS2_UNIQUE:
        raise AssertionError(
            f"cpu paxos2 unique {cpu_p2.unique_state_count()} != {PAXOS2_UNIQUE}"
        )
    if cpu_t5.unique_state_count() != TPC5_UNIQUE:
        raise AssertionError(
            f"cpu 2pc5 unique {cpu_t5.unique_state_count()} != {TPC5_UNIQUE}"
        )
    out["cpu_paxos2_discoveries"] = sorted(cpu_p2.discoveries())
    out["cpu_2pc5_discoveries"] = sorted(cpu_t5.discoveries())

    cpu_p3, dt = timed(
        lambda: paxos_model(3)
        .checker()
        .threads(threads)
        .target_states(CPU_TARGET)
        .spawn_bfs()
    )
    out["cpu_paxos3_states_per_sec"] = round(cpu_p3.state_count() / dt, 1)
    out["cpu_paxos3_states"] = cpu_p3.state_count()
    out["cpu_paxos3_sec"] = round(dt, 3)
    out["cpu_paxos3_note"] = f"prefix run, target_states={CPU_TARGET}"

    cpu_t4, dt4 = timed(
        lambda: TwoPhaseSys(4).checker().threads(threads).spawn_bfs()
    )
    out["cpu_2pc4_states_per_sec"] = round(cpu_t4.state_count() / dt4, 1)
    cpu_t6, dt6 = timed(
        lambda: TwoPhaseSys(6).checker().threads(threads).spawn_bfs()
    )
    out["cpu_2pc6_states_per_sec"] = round(cpu_t6.state_count() / dt6, 1)

    # the reference's full bench protocol (bench.sh:27-34): 2pc 10, paxos 6,
    # single-copy 4, lin-reg 2, lin-reg 3 ordered.  Python CPU BFS cannot
    # finish the big ones in bench budget, so rate-like prefix runs are used
    # (same treatment as paxos 3 above); each config is individually guarded.
    for tag, build, target in _bench_protocol():
        try:
            c, dt = timed(
                lambda: _capped(build().checker().threads(threads), target)
                .spawn_bfs()
            )
            out[f"cpu_{tag}_states_per_sec"] = round(c.state_count() / dt, 1)
            out[f"cpu_{tag}_unique"] = c.unique_state_count()
        except Exception as e:  # noqa: BLE001 - secondary configs never void
            out[f"cpu_{tag}_error"] = f"{type(e).__name__}: {e}"
    return out


def _capped(builder, target):
    return builder.target_states(target) if target else builder


def _bench_protocol():
    """(tag, model builder, unique-state cap or None=full) for the reference
    bench configs not already covered by the primary metrics."""
    from stateright_tpu.models.linearizable_register import abd_model
    from stateright_tpu.models.paxos import paxos_model
    from stateright_tpu.models.single_copy_register import single_copy_model
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys
    from stateright_tpu.actor import Network

    return [
        ("2pc10", lambda: TwoPhaseSys(10), 30_000),
        ("paxos6", lambda: paxos_model(6), 20_000),
        ("singlecopy4", lambda: single_copy_model(4, 1), 30_000),
        ("linreg2", lambda: abd_model(2, 2), None),  # full: 544 unique
        (
            "linreg3_ordered",
            lambda: abd_model(3, 2, Network.new_ordered()),
            10_000,
        ),
    ]


# ---------------------------------------------------------------------------
# TPU phase (child process; may touch / hang on the device backend)
# ---------------------------------------------------------------------------


def _mark(stage: str) -> None:
    """Progress mark on stderr: when the parent kills a hung child, the
    last mark pinpoints the stage that never returned."""
    sys.stderr.write(f"bench-tpu-stage: {stage}\n")
    sys.stderr.flush()


def _persist(out: dict) -> None:
    """Append the cumulative result dict to the stage file (if the parent
    provided one).  A watchdog kill then salvages every number that landed
    before the hang instead of only stderr stage marks — round 2 lost a
    whole phase's worth of completed work to exactly that."""
    path = os.environ.get("BENCH_STAGE_FILE")
    if not path:
        return
    try:
        with open(path, "a") as f:
            f.write(json.dumps(out) + "\n")
            f.flush()
            os.fsync(f.fileno())
    except OSError:
        pass


def tpu_phase() -> dict:
    import threading

    from stateright_tpu.models.paxos import paxos_model
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    t_start = time.monotonic()
    budget = float(os.environ.get("BENCH_TPU_TIMEOUT", "1800"))
    out: dict = {}
    tpu_phase.partial = out  # surfaced on mid-phase failure (see main)

    def heartbeat():
        # keeps the parent's stall watchdog fed during long silent sections
        # (device runs emit no stderr; only a truly hung child goes quiet)
        while True:
            time.sleep(60)
            _mark(f"alive t+{time.monotonic() - t_start:.0f}s")

    threading.Thread(target=heartbeat, daemon=True).start()

    _mark("backend-init (jax.devices)")
    out["tpu_devices"] = with_tpu_retry(_device_names)
    _mark("backend-up")
    _persist(out)

    # parity gates on device (capacities sized so no growth event interrupts)
    tpu_p2 = with_tpu_retry(
        lambda: paxos_model(2).checker().spawn_tpu(sync=True, capacity=1 << 18)
    )
    _mark("paxos2 parity done")
    tpu_t5 = TwoPhaseSys(5).checker().spawn_tpu(sync=True, capacity=1 << 17)
    _mark("2pc5 parity done")
    if tpu_p2.unique_state_count() != PAXOS2_UNIQUE:
        raise AssertionError(
            f"tpu paxos2 unique {tpu_p2.unique_state_count()} != {PAXOS2_UNIQUE}"
        )
    if tpu_t5.unique_state_count() != TPC5_UNIQUE:
        raise AssertionError(
            f"tpu 2pc5 unique {tpu_t5.unique_state_count()} != {TPC5_UNIQUE}"
        )
    out["tpu_paxos2_discoveries"] = sorted(tpu_p2.discoveries())
    out["tpu_2pc5_discoveries"] = sorted(tpu_t5.discoveries())
    _persist(out)

    # primary: paxos check 3 (same model instance across warm-up + timed run
    # so the compiled-run cache on the tensor twin is reused)
    target = os.environ.get("BENCH_TPU_TARGET", "")
    m3 = paxos_model(3)
    # tuned on v5e (r3 sweep): batch 2048 beat 1024/3072/4096/8192, and
    # 1024 device steps per host sync amortizes the ~100ms tunnel RTT
    caps = dict(capacity=1 << 23, queue_capacity=1 << 21, batch=2048,
                steps_per_call=1024)

    def spawn3():
        b = m3.checker()
        if target:
            b = b.target_states(int(target))
        return b.spawn_tpu(sync=True, **caps)

    with_tpu_retry(spawn3)  # warm-up (compile)
    _mark("paxos3 warm-up done")
    tpu_p3, dt = timed(spawn3)
    _mark("paxos3 timed run done")
    out["tpu_paxos3_states_per_sec"] = round(tpu_p3.state_count() / dt, 1)
    out["tpu_paxos3_states"] = tpu_p3.state_count()
    out["tpu_paxos3_unique"] = tpu_p3.unique_state_count()
    out["tpu_paxos3_sec"] = round(dt, 3)
    out["tpu_paxos3_discoveries"] = sorted(tpu_p3.discoveries())
    if target:
        out["tpu_paxos3_note"] = f"prefix run, target_states={target}"
    else:
        out["tpu_paxos3_note"] = (
            "FULL enumeration: the complete paxos-3 space, pinned by "
            "tests/test_paxos_tensor.py (slow tier) at 1,194,428 unique"
        )
    _persist(out)

    # A/B the Pallas visited-set insert kernel (ops/pallas_insert.py) on the
    # same primary config; count parity is asserted so a miscompiled kernel
    # can't silently report a win.
    try:
        def spawn3p():
            b = m3.checker()
            if target:
                b = b.target_states(int(target))
            return b.spawn_tpu(sync=True, pallas=True, **caps)

        spawn3p()  # warm-up (compile)
        tpu_p3p, dtp = timed(spawn3p)
        if tpu_p3p.unique_state_count() != tpu_p3.unique_state_count():
            raise AssertionError(
                f"pallas path unique {tpu_p3p.unique_state_count()} != "
                f"{tpu_p3.unique_state_count()}"
            )
        out["tpu_paxos3_pallas_states_per_sec"] = round(
            tpu_p3p.state_count() / dtp, 1
        )
        _mark("paxos3 pallas A/B done")
    except Exception as e:  # noqa: BLE001
        out["tpu_paxos3_pallas_error"] = f"{type(e).__name__}: {e}"
    _persist(out)

    # secondary: 2pc check 7; failure must not void the primary metric, and
    # it is skipped when the phase budget is mostly spent (the parent kills
    # the whole child at the deadline, primary results and all)
    try:
        if time.monotonic() - t_start > 0.6 * budget:
            raise TimeoutError("phase budget mostly spent; skipping 2pc7")
        t7 = TwoPhaseSys(7)
        # cand pre-sized for 2pc's ~9x fanout: growth would work but each
        # doubling recompiles the engine, wasting warm-up budget
        caps7 = dict(capacity=1 << 21, queue_capacity=1 << 19, batch=2048,
                     steps_per_call=256, cand=1 << 15)
        t7.checker().spawn_tpu(sync=True, **caps7)  # warm-up
        tpu_t7, dt7 = timed(lambda: t7.checker().spawn_tpu(sync=True, **caps7))
        out["tpu_2pc7_states_per_sec"] = round(tpu_t7.state_count() / dt7, 1)
        out["tpu_2pc7_states"] = tpu_t7.state_count()
        out["tpu_2pc7_unique"] = tpu_t7.unique_state_count()
        out["tpu_2pc7_sec"] = round(dt7, 3)
    except Exception as e:  # noqa: BLE001
        out["tpu_2pc7_error"] = f"{type(e).__name__}: {e}"
    _persist(out)

    # reference bench protocol on device.  All five configs compile — the
    # actor compiler gained ordered-FIFO network support in round 2
    # (parallel/actor_compiler.py), so lin-reg-3-ordered runs on device too
    # (pinned by tests/test_network_matrix.py); a failure on any config is
    # recorded per-tag without voiding the primary metric.  Device runs use
    # 10x the CPU prefix target: at 100k-1M states/s a CPU-sized prefix
    # finishes in well under a second and the measured "rate" is mostly
    # fixed overhead (tunnel RTT, growth rehashes), not engine throughput —
    # states/sec is rate-like, so a longer prefix measures it more fairly.
    for tag, build, target in _bench_protocol():
        try:
            if time.monotonic() - t_start > 0.75 * budget:
                raise TimeoutError("phase budget mostly spent")
            mm = build()
            target = target * 10 if target else None
            kw = dict(sync=True, capacity=1 << 23, queue_capacity=1 << 21,
                      batch=2048, steps_per_call=256, cand=1 << 15)
            _capped(mm.checker(), target).spawn_tpu(**kw)  # warm-up
            c, dt = timed(
                lambda: _capped(mm.checker(), target).spawn_tpu(**kw)
            )
            out[f"tpu_{tag}_states_per_sec"] = round(c.state_count() / dt, 1)
            out[f"tpu_{tag}_unique"] = c.unique_state_count()
            _mark(f"{tag} done")
        except Exception as e:  # noqa: BLE001
            out[f"tpu_{tag}_error"] = f"{type(e).__name__}: {e}"
        _persist(out)

    return out


def _device_names() -> list:
    import jax

    return [str(d) for d in jax.devices()]


def _salvage(stage_path: str) -> dict:
    """Last cumulative result dict the killed child persisted, if any."""
    try:
        with open(stage_path) as f:
            lines = [l for l in f.read().splitlines() if l.strip()]
        for line in reversed(lines):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    except OSError:
        pass
    return {}


def run_probe(timeout_s: float) -> tuple:
    """Init-only child: ``import jax; jax.devices()`` and exit.  Proves the
    backend can come up WITHOUT committing a long watchdog window to a full
    attempt.  Returns (ok, seconds, detail)."""
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--tpu-probe"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        dt = time.monotonic() - t0
        ok = proc.returncode == 0 and "probe-ok" in proc.stdout
        detail = (
            proc.stdout.strip().splitlines()[-1:]
            + proc.stderr.strip().splitlines()[-2:]
        )
        return ok, dt, detail[-1] if detail else ""
    except subprocess.TimeoutExpired:
        return False, time.monotonic() - t0, f"probe hung {timeout_s:.0f}s"


def run_tpu_subprocess(timeout_s: float, init_s: float = None) -> dict:
    """Run ``tpu_phase`` in a child; a backend hang cannot take down the
    parent's JSON line.  Child stderr goes to a temp file (not a pipe) so
    that even after a timeout-kill the staged progress marks survive and
    the JSON can say exactly which stage hung.  The child also persists its
    cumulative results to a stage file after every milestone; a kill merges
    that salvage into the returned dict so completed numbers survive."""
    import tempfile

    if init_s is None:
        init_s = float(os.environ.get("BENCH_TPU_INIT_TIMEOUT", "300"))
    stage_fd, stage_path = tempfile.mkstemp(suffix=".bench-stages")
    os.close(stage_fd)
    env = dict(os.environ, BENCH_STAGE_FILE=stage_path)
    try:
        return _run_tpu_child(timeout_s, init_s, stage_path, env)
    finally:
        try:
            os.unlink(stage_path)
        except OSError:
            pass


def _run_tpu_child(
    timeout_s: float, init_s: float, stage_path: str, env: dict
) -> dict:
    import tempfile

    with tempfile.TemporaryFile(mode="w+", errors="replace") as errf:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--tpu-child"],
            stdout=subprocess.PIPE,
            stderr=errf,
            text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env=env,
        )

        def read_err() -> list:
            # os.pread: the child writes through the same file description,
            # so seeking the shared offset mid-run would corrupt its output
            size = os.fstat(errf.fileno()).st_size
            data = os.pread(errf.fileno(), size, 0).decode(errors="replace")
            return data.strip().splitlines()

        def err_tail(n: int = 8) -> list:
            # heartbeat lines would flood out the stage marks this exists
            # to surface
            return [l for l in read_err() if "stage: alive" not in l][-n:]

        def last_stage() -> str:
            stage = ""
            for line in read_err():
                if line.startswith("bench-tpu-stage:") and "alive" not in line:
                    stage = line.split(":", 1)[1].strip()
            return stage

        # Backend-init watchdog on top of the per-attempt budget: the axon
        # backend has been observed to block 25+ minutes inside PJRT client
        # creation before failing UNAVAILABLE.  If the child is still in
        # backend-init after ``init_s``, kill it early — the caller's retry
        # loop relaunches a fresh child with the remaining phase budget
        # (a healthy init is <60s; later stages run long legitimately, so
        # only init gets this limit).
        deadline = time.monotonic() + timeout_s
        t0 = time.monotonic()
        init_passed = False
        while True:
            try:
                stdout, _ = proc.communicate(timeout=5)
                break
            except subprocess.TimeoutExpired:
                now = time.monotonic()
                stuck_init = False
                if not init_passed:
                    stage = last_stage()
                    # "" = hung before the first mark (imports/interpreter):
                    # the same early-init hang class, treated identically
                    init_passed = stage not in (
                        "", "backend-init (jax.devices)"
                    )
                    stuck_init = not init_passed and now - t0 > init_s
                if now > deadline or stuck_init:
                    why = (
                        f"stuck in backend init for {init_s:.0f}s"
                        if stuck_init
                        else f"timed out after {timeout_s:.0f}s"
                    )
                    proc.kill()
                    proc.communicate()
                    res = _salvage(stage_path)
                    res.update(
                        error=f"TPU phase {why}",
                        tpu_stuck_init=stuck_init,
                        tpu_trace_tail=err_tail(),
                    )
                    return res
        for line in reversed(stdout.strip().splitlines()):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
        res = _salvage(stage_path)
        res.update(
            error=f"TPU phase exited rc={proc.returncode} without JSON",
            tpu_trace_tail=err_tail() or stdout.strip().splitlines()[-8:],
        )
        return res


def run_tpu_with_budget(budget_s: float) -> dict:
    """Spend the ENTIRE TPU budget trying to land numbers — never one
    attempt.  Phase A: cheap init-only probes (120s, escalating) until the
    backend proves it can come up (bounded to ~40% of budget).  Phase B:
    full attempts in fresh child processes, each under an init watchdog,
    relaunching on init hangs until the budget is spent.  Results from a
    killed attempt are salvaged from its stage file and merged, so the
    best partial data across all attempts survives.  ``tpu_attempts``
    records every attempt for the log-of-evidence case where the backend
    never comes up at all."""
    t0 = time.monotonic()
    attempts: list = []
    merged: dict = {}

    def remaining() -> float:
        return budget_s - (time.monotonic() - t0)

    # Phase A: probes.  An init hang costs one probe window, not 600s.
    probe_s = float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "120"))
    probe_budget = 0.4 * budget_s
    while time.monotonic() - t0 < probe_budget and remaining() > 90:
        ok, dt, detail = run_probe(min(probe_s, remaining() - 60))
        attempts.append(
            {"kind": "probe", "ok": ok, "sec": round(dt, 1),
             "detail": str(detail)}
        )
        sys.stderr.write(f"bench: probe ok={ok} in {dt:.0f}s: {detail}\n")
        if ok:
            break
        probe_s = min(probe_s * 2, 480.0)
        time.sleep(10)  # let a stale chip lock from the killed probe clear

    # Phase B: full attempts until the budget is spent (or a deterministic
    # failure makes retrying pointless).
    transient = ("init", "UNAVAILABLE", "ALREADY_EXISTS", "hung",
                 "without JSON")
    while remaining() > 60 and len(attempts) < 24:
        res = run_tpu_subprocess(remaining())
        stuck = bool(res.pop("tpu_stuck_init", False))
        err = res.get("error")
        attempts.append(
            {"kind": "full", "ok": err is None, "stuck_init": stuck,
             "error": err}
        )
        sys.stderr.write(f"bench: full attempt ok={err is None}: {err}\n")
        if err is None:
            merged.pop("error", None)
            merged.pop("tpu_trace_tail", None)
        merged.update(res)
        if err is None or "tpu_paxos3_states_per_sec" in merged:
            break  # success, or the primary metric already landed
        if not (stuck or any(t in err for t in transient)):
            break  # deterministic failure — a fresh child won't differ
        time.sleep(10)

    merged["tpu_attempts"] = attempts
    if not any(a["kind"] == "full" for a in attempts):
        merged.setdefault(
            "error",
            "TPU backend never initialized: all probe attempts hung "
            "(see tpu_attempts)",
        )
    return merged


def main() -> int:
    if "--tpu-probe" in sys.argv:
        import jax

        print("probe-ok", [str(d) for d in jax.devices()])
        return 0
    if "--tpu-child" in sys.argv:
        try:
            print(json.dumps(tpu_phase()))
            return 0
        except Exception as e:  # noqa: BLE001
            tb = traceback.format_exc().strip().splitlines()
            # whatever sections completed before the failure still count
            partial = getattr(tpu_phase, "partial", {})
            partial.update({"error": f"{type(e).__name__}: {e}",
                            "tpu_trace_tail": tb[-6:]})
            print(json.dumps(partial))
            return 1

    extras = cpu_phase()
    timeout_s = float(os.environ.get("BENCH_TPU_TIMEOUT", "1800"))
    extras.update(run_tpu_with_budget(timeout_s))

    for w in ("paxos2", "2pc5"):
        cpu_d = extras.get(f"cpu_{w}_discoveries")
        tpu_d = extras.get(f"tpu_{w}_discoveries")
        if tpu_d is not None and cpu_d != tpu_d:
            extras["error"] = (
                f"discovery parity failed on {w}: cpu={cpu_d} tpu={tpu_d}"
            )
            emit(**extras)
            return 1

    cpu_sps = extras.get("cpu_paxos3_states_per_sec", 0.0)
    tpu_sps = extras.get("tpu_paxos3_states_per_sec")
    # the Pallas-insert variant is the same engine behind a flag and its
    # rate is only recorded after count parity with the XLA run — report
    # whichever insert path is faster on this hardware as the framework's
    # number, and name the winner
    pallas_sps = extras.get("tpu_paxos3_pallas_states_per_sec")
    if tpu_sps is not None and pallas_sps is not None:
        extras["insert_path"] = (
            "pallas" if pallas_sps > tpu_sps else "xla-scatter"
        )
        tpu_sps = max(tpu_sps, pallas_sps)
    if tpu_sps is not None and cpu_sps:
        emit(
            value=tpu_sps,
            vs_baseline=round(tpu_sps / cpu_sps, 3),
            parity="paxos check 2 (16668) + 2pc check 5 (8832) on CPU and TPU",
            **extras,
        )
        # a partial TPU phase can carry the primary metric AND a phase-level
        # error (e.g. the backend died after the timed run): report the
        # number but exit nonzero so automation sees the broken run
        return 1 if "error" in extras else 0
    emit(**extras)
    return 1


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:  # noqa: BLE001 - the one JSON line must still appear
        tb = traceback.format_exc().strip().splitlines()
        emit(error=f"{type(e).__name__}: {e}", trace_tail=tb[-6:])
        sys.exit(1)
