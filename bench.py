"""Benchmark: TPU wavefront engine vs the CPU BFS baseline.

Driver metric (BASELINE.md): **states/sec on ``paxos check 3`` + ``2pc
check 4``, with discovery-count parity**; north-star ≥20× the multithreaded
CPU BfsChecker on ``paxos check 3``.  Protocol mirrors the reference's
``bench.sh`` wall-clock discipline (reference ``src/checker.rs:230-233``).

Output contract: this script prints complete JSON lines — the LAST line is
the result.  Earlier rounds emitted exactly once, at the very end, and
round 3's artifact was ``rc=124, parsed=null`` because the driver's outer
timeout fired first.  Round 4 emitted **incrementally** (one line per
milestone) but packed every detail — attempt records, probe stack dumps —
into each line; the driver stores only a ~2KB *tail* of stdout
(BENCH_r04.json's ``tail`` starts mid-line), so the oversized final line
could never parse.  Round 5's contract therefore has two more rules:

 - **Every stdout line is small** (hard cap ``MAX_LINE_BYTES``): only
   scalar headline keys.  Full details go to stderr and a side file
   (``docs/bench-last-details.json``), never stdout.
 - **A stale number is never the headline.**  ``BENCH_VALIDATED.json``
   (repo root, committed) stores the most recent chip-validated result
   with provenance.  When the tunnel is dead the emitted line keeps
   ``value: 0.0, fresh: false`` and carries the stored number ONLY inside
   an explicit ``stale`` annotation (``"STALE (fresh=false, carried from
   ...)"``) plus ``validated_at`` — round 5's artifact put the carried
   number in ``value`` itself and the round-4 headline silently survived
   a round in which the chip never ran.  The script exits non-zero
   whenever the fallback fires.  A fresh successful run rewrites the
   file (and clears the annotation).

Telemetry: the primary paxos-3 and 2pc-7 device runs record a flight-
recorder summary (``stateright_tpu/telemetry/``) embedded as
``tpu_paxos3_telemetry`` / ``tpu_2pc7_telemetry`` in the details artifact
— per-step throughput, dedup ratio, growth events, occupancy, transfer
volume — so every future perf claim has its time series on record.
Both legs run with the search-cartography counters AND the HBM memory
ledger on, embedding their post-run report (``telemetry/report.py``) as
``tpu_paxos3_report`` / ``tpu_2pc7_report`` plus the raw
``*_cartography`` and ``*_memory`` blocks, so the numbers arrive with
the search shape (depth/action mix, property coverage, shard balance)
and the memory story (per-buffer footprint, growth-transient forecast,
device watermark) that explain them.  ``regress.py`` gates a fresh
run's summary against BENCH_VALIDATED.json (``--cartography`` /
``--memory`` for the blocks' well-formedness).

``BENCH_SPILL=1`` adds the flag-gated spill leg (docs/spill.md): the
same 2pc-7 under a SIMULATED device budget smaller than its
steady-state footprint (``tpu_2pc7_spill_*`` keys + the per-tier byte
breakdown in ``tpu_2pc7_spill``); ``regress.py --spill`` gates its
well-formedness and count parity.  ``BENCH_SPILL_BUDGET`` overrides
the computed budget.

``BENCH_MXU=1`` adds the flag-gated MXU-recast legs (docs/roofline.md
"Executing the hot-spot list"): the same paxos-3 and 2pc-7 configs
with ``CheckerBuilder.mxu()`` armed, count parity ASSERTED, and the
flagged roofline ledgers embedded as ``tpu_paxos3_mxu_roofline`` /
``tpu_2pc7_mxu_roofline`` next to the same run's unflagged blocks —
``regress.py --mxu`` gates the before/after pair (expand+queue charged
bytes drop >=30% on paxos-3; a dot-class dedup-insert op on 2pc-7).

``BENCH_SWEEP=1`` adds the flag-gated hyper-batched sweep leg
(docs/sweep.md): the paxos default family (``BENCH_SWEEP_N`` instances,
alternating lossiness) as ONE sweep vs the same instances sequentially
— per-instance count parity ASSERTED, compile amortization recorded
(``tpu_sweep.engine_compiles`` vs ``sequential_engine_compiles``), and
the ``tpu_sweep_states_per_sec`` /
``tpu_sweep_sequential_states_per_sec`` aggregate-throughput pair;
``regress.py --sweep`` gates the block's well-formedness and parity.

``BENCH_LIVE=1`` adds the flag-gated live-observability leg
(docs/observability.md): paxos-3 with plain telemetry vs telemetry +
metrics bus + armed progress heartbeat — count parity ASSERTED, the
measured bus-sampling + heartbeat-write overhead fraction recorded as
``tpu_live.overhead_frac`` next to the published family list and the
terminal heartbeat; ``regress.py --live`` gates the block.

Run ledger (docs/telemetry.md "Comparing runs"): with
``STATERIGHT_TPU_RUN_DIR`` set, EVERY device leg bench runs is archived
into the persistent run registry (``telemetry/registry.py``) — one
report + ``config_key``-indexed headline record per leg, under
``run_registry`` in the details artifact — so A/Bs become
``_cli compare`` invocations instead of transcript archaeology.  Fresh
runs additionally emit ``trend``: every measured ``tpu_*_states_per_sec``
against the BENCH_VALIDATED.json history with its ratio (``regressed``
is the below-tolerance subset), and a validated full run embeds its
``tpu_paxos3_report`` into BENCH_VALIDATED.json for ``regress.py
--diff``.

``value``/``vs_baseline`` are recomputed on every emit from whatever
numbers exist so far.

Baseline definition (the ONE honest story — README, BASELINE.md and this
script agree): ``vs_baseline`` = TPU paxos-3 states/s ÷ **uncontended
single-core CPU BFS states/s of this framework's own engine** (the Rust
reference cannot be built here — no cargo toolchain — so the reference's
multithreaded CPU BfsChecker is approximated by this framework's CPU
engine; see BASELINE.md).  The same-invocation CPU run is used only when
it is actually uncontended (within 80% of the stored uncontended rate);
otherwise the stored uncontended rate is used and the contention is
recorded (``cpu_baseline_src``, ``cpu_load1``).

Phase structure (see docs/axon-init-hang.md for the diagnosis that shaped
it — the historical "init hang" is the loopback tunnel's far end being
unresponsive at driver-bench time; nothing bench does to its own children
can wedge the backend, which was round 3's disproven hypothesis):

 1. A tiny init-only PROBE child starts FIRST, concurrently with the CPU
    phase.  It arms ``faulthandler`` so a hang dumps the blocking stack.
 2. CPU phase (pure host Python, no device contact): pinned-count parity
    runs + baseline states/sec (bounded prefixes where a full Python run
    would take hours).  Emit.
 3. TPU phase in a child process under a watchdog: parity configs, then
    the primary ``paxos check 3`` timed run FIRST (so a later kill cannot
    lose it), then ``2pc check 4``, the Pallas A/B, and the remaining
    reference bench configs.  The child appends cumulative results to a
    stage file after every milestone; the parent merges + emits on change.
    Retries in fresh children while budget remains.

Env knobs: ``BENCH_DEADLINE`` (secs, default 1500) bounds the WHOLE script;
``BENCH_TPU_TIMEOUT`` (secs, default: remaining deadline) bounds the TPU
phase; ``BENCH_TPU_TARGET`` caps the paxos-3 device run's unique states
(default: empty = FULL enumeration — 1,194,428 unique states, ~10 s warm).
"""

import json
import os
import signal
import subprocess
import sys
import time
import traceback

PAXOS2_UNIQUE = 16_668  # examples/paxos.rs:291
TPC5_UNIQUE = 8_832  # examples/2pc.rs:133
TPC4_UNIQUE = 1_568  # 2pc at 4 RMs (pinned in tests/test_models.py)
CPU_TARGET = 12_000  # unique-state cap for the CPU paxos-3 baseline prefix

T0 = time.monotonic()
DEADLINE_S = float(os.environ.get("BENCH_DEADLINE", "1500"))

_HERE = os.path.dirname(os.path.abspath(__file__))
VALIDATED_PATH = os.environ.get(
    "BENCH_VALIDATED_FILE", os.path.join(_HERE, "BENCH_VALIDATED.json")
)
DETAILS_PATH = os.environ.get(
    "BENCH_DETAILS_FILE", os.path.join(_HERE, "docs", "bench-last-details.json")
)
# the driver keeps only a ~2KB tail of stdout; a line longer than that
# window can never parse (the BENCH_r04 failure mode).  Stay far under it.
MAX_LINE_BYTES = 1000


def _load_validated() -> dict:
    try:
        with open(VALIDATED_PATH) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


VALIDATED = _load_validated()

# run ledger (docs/telemetry.md "Comparing runs"): bench registers each
# leg EXPLICITLY (leg-tagged, with the already-built report body where
# one exists), so main() CONSUMES the env knob into this global — left
# in the environment it would also trigger every checker's join-time
# auto-record and double-archive each leg (plus warm-ups and the CPU
# baseline) as untagged noise.  run_tpu_attempt re-injects it into the
# child's env; the child's main() consumes it again the same way.
RUN_LEDGER_DIR = None


def remaining() -> float:
    return DEADLINE_S - (time.monotonic() - T0)


EXTRAS: dict = {}
_last_emitted = None
_last_details = None

# stdout whitelist, highest-priority first: when the line would exceed
# MAX_LINE_BYTES, keys are dropped from the END of this list until it fits
# (the first four are the driver's contract and are never dropped).
_LINE_KEYS = (
    "metric", "value", "unit", "vs_baseline",
    "fresh", "stale", "validated_at", "error", "regressed",
    "tpu_paxos3_states_per_sec", "tpu_paxos3_unique", "tpu_paxos3_sec",
    "cpu_baseline_states_per_sec", "cpu_baseline_src",
    "cpu_baseline_engine", "cpu_cores",
    "cpu_load1", "baseline_def", "insert_path", "parity", "details",
)


def _cpu_baseline() -> tuple:
    """(rate, src, uncontended): the single source of the baseline-selection
    rule.  The same-invocation CPU run counts as uncontended when the box
    was idle at phase start (load1 < 0.7 — the probe child no longer
    overlaps the primary CPU run, see main()) or when it reaches 80% of
    the stored uncontended rate.  Replace-not-ratchet: an idle same-run
    measurement may legitimately be LOWER than the stored rate (slower
    box, slower engine) and still wins."""
    cpu_same = EXTRAS.get("cpu_paxos3_states_per_sec")
    cpu_stored = VALIDATED.get("cpu_paxos3_uncontended_states_per_sec")
    if not cpu_same:
        if cpu_stored:
            return cpu_stored, "stored-uncontended (cpu phase failed)", False
        return None, None, False
    load1 = EXTRAS.get("cpu_load1")
    uncontended = (load1 is not None and load1 < 0.7) or (
        bool(cpu_stored) and cpu_same >= 0.8 * cpu_stored
    )
    if uncontended or not cpu_stored:
        src = "same-run" if uncontended else (
            f"same-run (unverified: load1={load1}, nothing stored)"
        )
        return cpu_same, src, uncontended
    return (
        cpu_stored,
        f"stored-uncontended (same-run contended: {cpu_same:.0f}/s, "
        f"load1={load1})",
        False,
    )


# perf-regression guard (ADVICE item 8): a FRESH run's per-config rates
# against the BENCH_VALIDATED.json history.  ONE tolerance with
# regress.py's throughput gate (the r4 sweep put same-config spread
# within ±5%, so −15% is a regression, not noise) — imported so a
# retune there cannot silently diverge from the guard here; the
# fallback only covers running bench.py from outside the repo root.
try:
    from regress import DEFAULT_TOLERANCE as REGRESS_TOLERANCE
except ImportError:  # pragma: no cover - bench copied out of the repo
    REGRESS_TOLERANCE = 0.85


def _trend_deltas() -> list:
    """Per-config ``{config, run, baseline, ratio}`` entries for EVERY
    freshly measured ``tpu_*_states_per_sec`` with a stored validated
    history value — the full trend view against BENCH_VALIDATED.json
    (improvements and regressions alike; ``regressed`` is the
    below-tolerance subset).  Compares only keys present in BOTH — a
    carried/stale number never enters (the caller additionally gates on
    the run being fresh), and configs the baseline never validated have
    no trend."""
    out = []
    for key, base in sorted(VALIDATED.items()):
        if not key.endswith("_states_per_sec") or not key.startswith("tpu_"):
            continue
        cur = EXTRAS.get(key)
        if (
            not isinstance(cur, (int, float))
            or not isinstance(base, (int, float))
            or not base
        ):
            continue
        out.append({
            "config": key,
            "run": cur,
            "baseline": base,
            "ratio": round(cur / base, 3),
        })
    return out


def _perf_regressions(trend=None) -> list:
    """The below-``REGRESS_TOLERANCE`` subset of :func:`_trend_deltas`
    (ADVICE item 8's guard)."""
    return [
        e for e in (_trend_deltas() if trend is None else trend)
        if e["run"] < REGRESS_TOLERANCE * e["baseline"]
    ]


def _compute_headline() -> dict:
    """value/vs_baseline + provenance fields from EXTRAS ∪ VALIDATED.
    Returned keys OVERRIDE the raw extras in the emitted record (merge
    order in emit()), so when the Pallas path wins, the describing fields
    (sec) are replaced by the Pallas run's own — value, sec and unique
    must stay mutually consistent on every line."""
    out: dict = {}
    cpu_base, cpu_src, _ = _cpu_baseline()
    if cpu_base is not None:
        out["cpu_baseline_states_per_sec"] = cpu_base
        out["cpu_baseline_src"] = cpu_src
    out["baseline_def"] = "uncontended single-core CPU BFS (this framework)"
    if EXTRAS.get("cpu_baseline_engine"):
        out["cpu_baseline_engine"] = EXTRAS["cpu_baseline_engine"]
    # -- value: fresh chip number if we have one, else last validated --
    tpu_sps = EXTRAS.get("tpu_paxos3_states_per_sec")
    pallas_sps = EXTRAS.get("tpu_paxos3_pallas_states_per_sec")
    if tpu_sps is not None and pallas_sps is not None:
        if pallas_sps > tpu_sps:
            out["insert_path"] = "pallas"
            tpu_sps = pallas_sps
            out["tpu_paxos3_states_per_sec"] = pallas_sps
            if EXTRAS.get("tpu_paxos3_pallas_sec") is not None:
                out["tpu_paxos3_sec"] = EXTRAS["tpu_paxos3_pallas_sec"]
        else:
            out["insert_path"] = "xla-scatter"
    if tpu_sps is not None:
        out["value"], out["fresh"] = tpu_sps, True
        # trend deltas vs the BENCH_VALIDATED history (details artifact)
        # + the perf-regression guard (ADVICE 8): only FRESH measurements
        # are compared — a stale/carried artifact has nothing to regress
        out["trend"] = _trend_deltas()
        out["regressed"] = _perf_regressions(out["trend"])
    elif VALIDATED.get("tpu_paxos3_states_per_sec") is not None:
        # validated fallback: the stored number is evidence, not a result.
        # It rides ONLY the explicit STALE annotation — value stays 0.0 so
        # no artifact consumer can mistake a dead-tunnel round for a
        # measurement (the round-5 silent carry-forward), and main() exits
        # non-zero.
        out["value"], out["fresh"] = 0.0, False
        v_at = VALIDATED.get("validated_at") or "unknown date"
        out["validated_at"] = VALIDATED.get("validated_at")
        out["stale"] = (
            f"STALE (fresh=false, carried from {v_at}): "
            f"{VALIDATED['tpu_paxos3_states_per_sec']} states/s"
        )
    else:
        out["value"], out["fresh"] = 0.0, False
    out["vs_baseline"] = (
        round(out["value"] / cpu_base, 3) if cpu_base and out["value"] else 0.0
    )
    return out


def emit(_clear=(), **updates) -> None:
    """Print a COMPLETE, SMALL result line (the driver parses the last
    stdout line out of a ~2KB tail window, so every line must stay under
    MAX_LINE_BYTES).  Full cumulative details go to DETAILS_PATH and
    stderr instead.  value/vs_baseline are recomputed every time, so every
    line is a valid final answer for everything known so far; when no
    fresh chip number exists yet, ``value`` stays 0.0 (``fresh: false``)
    and the last chip-validated number rides ONLY the explicit ``stale``
    annotation + ``validated_at`` — a carried-forward number must never
    headline (the round-5 silent carry-forward).  ``_clear`` names keys
    to REMOVE from the cumulative extras — a stale ``error`` from a failed
    attempt must not survive a later successful retry."""
    global _last_emitted, _last_details
    for k in _clear:
        EXTRAS.pop(k, None)
    EXTRAS.update(updates)
    full = {
        "metric": "paxos check 3 states/sec (TPU wavefront)",
        "unit": "states/sec",
        **{k: v for k, v in EXTRAS.items() if k not in ("value", "unit")},
        **_compute_headline(),  # AFTER extras: headline fields override
        "details": os.path.relpath(DETAILS_PATH, _HERE),
    }
    # full detail record: side file, never stdout.  Deduped on the full
    # dict (not the headline line): the ~5s watchdog re-emits of unchanged
    # salvage must not rewrite the file, but a milestone that only adds a
    # secondary config number still must.
    blob = json.dumps(full, indent=1)
    if blob != _last_details:
        try:
            with open(DETAILS_PATH, "w") as f:
                f.write(blob)
            _last_details = blob
        except OSError as e:
            # the side file is the details' only home; if it is unwritable
            # they survive on stderr instead (docstring contract)
            full.pop("details", None)
            sys.stderr.write(f"bench: details file unwritable ({e}); "
                             f"details follow:\n{blob}\n")
            _last_details = blob
    small = {k: full[k] for k in _LINE_KEYS if full.get(k) is not None}
    if "error" in small:
        small["error"] = str(small["error"])[:140]
    line = json.dumps(small)
    drop = len(_LINE_KEYS) - 1
    while len(line.encode()) > MAX_LINE_BYTES and drop >= 4:
        small.pop(_LINE_KEYS[drop], None)
        drop -= 1
        line = json.dumps(small)
    if line != _last_emitted:
        print(line, flush=True)
        sys.stderr.write(f"bench: emitted {len(line)}B headline line\n")
        _last_emitted = line


def record_validated() -> None:
    """Persist the freshly chip-validated result (+ the uncontended CPU
    baseline when this run's CPU phase was uncontended) so future
    invocations under a dead tunnel can still emit a real number.

    A BENCH_TPU_TARGET prefix run is NOT persisted: its rate is dominated
    by fixed overhead and is not comparable to the full-enumeration
    headline — overwriting the stored full-run number with it would poison
    every later dead-tunnel emission."""
    if os.environ.get("BENCH_TPU_TARGET", ""):
        sys.stderr.write(
            "bench: prefix run (BENCH_TPU_TARGET set) — not persisting to "
            "BENCH_VALIDATED.json\n"
        )
        return
    # "parity gates passed" must mean the DEVICE gates actually ran: a
    # salvaged partial (killed after the timed run, before the 2pc5 gate)
    # or an errored phase is a real number but not a validated one
    if (
        "error" in EXTRAS
        or not EXTRAS.get("tpu_paxos2_discoveries")
        or not EXTRAS.get("tpu_2pc5_discoveries")
    ):
        sys.stderr.write(
            "bench: partial/errored TPU phase (device parity gates "
            "incomplete) — not persisting to BENCH_VALIDATED.json\n"
        )
        return
    doc = {
        "tpu_paxos3_states_per_sec": EXTRAS.get("tpu_paxos3_states_per_sec"),
        "tpu_paxos3_unique": EXTRAS.get("tpu_paxos3_unique"),
        "tpu_paxos3_sec": EXTRAS.get("tpu_paxos3_sec"),
        "tpu_devices": EXTRAS.get("tpu_devices"),
        "validated_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "provenance": "bench.py full run, parity gates passed",
    }
    # per-stage attribution travels with the validated number so
    # ``regress.py --stages`` can compare like against like
    if EXTRAS.get("tpu_paxos3_stages"):
        doc["tpu_paxos3_stages"] = EXTRAS["tpu_paxos3_stages"]
    # ...and the cartography block, so ``regress.py --cartography`` can
    # diff search shape (depth/action mix, shard balance) across rounds
    if EXTRAS.get("tpu_paxos3_cartography"):
        doc["tpu_paxos3_cartography"] = EXTRAS["tpu_paxos3_cartography"]
    # ...and the memory block (regress.py --memory): the validated
    # number travels with its HBM footprint + growth forecast
    if EXTRAS.get("tpu_paxos3_memory"):
        doc["tpu_paxos3_memory"] = EXTRAS["tpu_paxos3_memory"]
    # ...and the roofline block (regress.py --roofline): the validated
    # number travels with its per-stage cost ledger + bound verdicts
    if EXTRAS.get("tpu_paxos3_roofline"):
        doc["tpu_paxos3_roofline"] = EXTRAS["tpu_paxos3_roofline"]
    # ...and the full embedded run report (regress.py --diff): future
    # rounds diff their fresh report against this one with the
    # contract-aware engine (telemetry/diff.py) — pre-registry
    # baselines simply lack the key and never trip the gate
    if EXTRAS.get("tpu_paxos3_report"):
        doc["tpu_paxos3_report"] = EXTRAS["tpu_paxos3_report"]
    if EXTRAS.get("tpu_phases"):
        doc["tpu_phases"] = EXTRAS["tpu_phases"]
    pallas = EXTRAS.get("tpu_paxos3_pallas_states_per_sec")
    if pallas and pallas > (doc["tpu_paxos3_states_per_sec"] or 0):
        doc["tpu_paxos3_states_per_sec"] = pallas
        doc["tpu_paxos3_sec"] = EXTRAS.get("tpu_paxos3_pallas_sec")
        doc["provenance"] += " (pallas insert path)"
    cpu_stored = VALIDATED.get("cpu_paxos3_uncontended_states_per_sec")
    _, _, uncontended = _cpu_baseline()
    if uncontended:
        # replace, don't ratchet: an idle measurement that is LOWER than
        # the stored rate (slower box, slower engine) is the new truth
        doc["cpu_paxos3_uncontended_states_per_sec"] = EXTRAS[
            "cpu_paxos3_states_per_sec"
        ]
        doc["cpu_load1"] = EXTRAS.get("cpu_load1")
        # which engine measured the stored rate: the native baseline and
        # the python fallback are NOT comparable across rounds
        doc["cpu_baseline_engine"] = EXTRAS.get("cpu_baseline_engine")
    elif cpu_stored:
        doc["cpu_paxos3_uncontended_states_per_sec"] = cpu_stored
    if doc["tpu_paxos3_states_per_sec"] is None:
        return
    try:
        with open(VALIDATED_PATH, "w") as f:
            json.dump(doc, f, indent=1)
        VALIDATED.clear()
        VALIDATED.update(doc)
    except OSError as e:
        sys.stderr.write(f"bench: could not write BENCH_VALIDATED.json: {e}\n")


def timed(spawn):
    t0 = time.monotonic()
    checker = spawn()
    checker.join()
    dt = max(time.monotonic() - t0, 1e-9)
    return checker, dt


# ---------------------------------------------------------------------------
# CPU phase (parent process; never touches a device backend)
# ---------------------------------------------------------------------------


def cpu_phase(on_primary_done=lambda: None) -> dict:
    from stateright_tpu.models.paxos import paxos_model
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    threads = os.cpu_count() or 1
    try:
        load1 = round(os.getloadavg()[0], 2)
    except OSError:
        load1 = None
    out: dict = {
        # contention evidence for the baseline-source decision (see module
        # docstring): sampled before this phase adds its own load
        "cpu_load1": load1,
        # honesty note (VERDICT r2 weak #3 / r3 next #3): the thread pool
        # is GIL-bound, so the REAL multi-core baseline is the
        # process-parallel BFS (stateright_tpu/checker/mp.py), reported as
        # ``cpu_*_mp_*``.  On this box the distinction is moot when
        # cpu_cores=1 — then single-core IS all the hardware offers and
        # vs_baseline is measured against the best available CPU run.
        "cpu_cores": threads,
        "cpu_baseline_note": (
            f"threads({threads}) under the CPython GIL ~= single-core; "
            "mp numbers (when cores>1) are process-parallel"
        ),
    }

    # primary baseline FIRST: vs_baseline needs it, and every emit after
    # this carries it.  The denominator is the COMPILED single-core
    # baseline when the native module builds (stateright_tpu/native/bfs.cpp
    # — XLA-CPU step kernels + native visited set/queue; ROADMAP "so
    # vs_baseline stops flattering the engine"); the pure-Python thread
    # BFS is the fallback AND is always measured for continuity
    # (``cpu_paxos3_python_states_per_sec``).
    cpu_p3, dt = timed(
        lambda: paxos_model(3)
        .checker()
        .threads(threads)
        .target_states(CPU_TARGET)
        .spawn_bfs()
    )
    out["cpu_paxos3_python_states_per_sec"] = round(
        cpu_p3.state_count() / dt, 1
    )
    out["cpu_paxos3_states_per_sec"] = out["cpu_paxos3_python_states_per_sec"]
    out["cpu_paxos3_states"] = cpu_p3.state_count()
    out["cpu_paxos3_sec"] = round(dt, 3)
    out["cpu_paxos3_note"] = f"prefix run, target_states={CPU_TARGET}"
    out["cpu_baseline_engine"] = "python-thread-bfs"
    try:
        from stateright_tpu.native.baseline import compiled_cpu_bfs

        nat = compiled_cpu_bfs(paxos_model(3), target=CPU_TARGET, batch=2048)
        if nat is not None:
            out["cpu_paxos3_states_per_sec"] = nat["states_per_sec"]
            out["cpu_paxos3_states"] = nat["states"]
            out["cpu_paxos3_sec"] = nat["secs"]
            out["cpu_baseline_engine"] = "native-cpp-bfs"
        else:
            out["cpu_baseline_engine_note"] = (
                "native module unavailable; python fallback"
            )
    except Exception as e:  # noqa: BLE001 - the baseline never voids the run
        out["cpu_native_baseline_error"] = f"{type(e).__name__}: {e}"
    # the baseline measurement is done — only NOW may the probe child
    # start: on a single-core box a concurrently-importing probe steals
    # ~half the primary run's CPU and poisons the uncontended baseline
    on_primary_done()

    # parity gates (pinned counts)
    cpu_p2 = paxos_model(2).checker().threads(threads).spawn_bfs().join()
    cpu_t5 = TwoPhaseSys(5).checker().threads(threads).spawn_bfs().join()
    if cpu_p2.unique_state_count() != PAXOS2_UNIQUE:
        raise AssertionError(
            f"cpu paxos2 unique {cpu_p2.unique_state_count()} != {PAXOS2_UNIQUE}"
        )
    if cpu_t5.unique_state_count() != TPC5_UNIQUE:
        raise AssertionError(
            f"cpu 2pc5 unique {cpu_t5.unique_state_count()} != {TPC5_UNIQUE}"
        )
    out["cpu_paxos2_discoveries"] = sorted(cpu_p2.discoveries())
    out["cpu_2pc5_discoveries"] = sorted(cpu_t5.discoveries())

    cpu_t4, dt4 = timed(
        lambda: TwoPhaseSys(4).checker().threads(threads).spawn_bfs()
    )
    out["cpu_2pc4_states_per_sec"] = round(cpu_t4.state_count() / dt4, 1)
    out["cpu_2pc4_unique"] = cpu_t4.unique_state_count()
    cpu_t6, dt6 = timed(
        lambda: TwoPhaseSys(6).checker().threads(threads).spawn_bfs()
    )
    out["cpu_2pc6_states_per_sec"] = round(cpu_t6.state_count() / dt6, 1)

    # real multi-core baseline: process-parallel BFS on the primary config.
    # Skipped on a single-core box, where it can only equal the thread run
    # minus IPC overhead (correctness is pinned by tests/test_mp.py).
    if threads > 1:
        try:
            from stateright_tpu.checker.mp import spawn_mp_bfs

            mp3, dtm = timed(
                lambda: spawn_mp_bfs(
                    paxos_model(3), target_states=CPU_TARGET
                )
            )
            out["cpu_paxos3_mp_states_per_sec"] = round(
                mp3.state_count() / dtm, 1
            )
            out["cpu_paxos3_mp_workers"] = mp3.worker_count
        except Exception as e:  # noqa: BLE001 - mp never voids the run
            out["cpu_paxos3_mp_error"] = f"{type(e).__name__}: {e}"
    else:
        out["cpu_paxos3_mp_note"] = "single-core box: mp baseline == thread"

    # the reference's full bench protocol (bench.sh:27-34): 2pc 10, paxos 6,
    # single-copy 4, lin-reg 2, lin-reg 3 ordered.  Python CPU BFS cannot
    # finish the big ones in bench budget, so rate-like prefix runs are used
    # (same treatment as paxos 3 above); each config is individually guarded.
    for tag, build, target in _bench_protocol():
        if remaining() < 0.75 * DEADLINE_S:
            out[f"cpu_{tag}_skipped"] = "cpu-phase budget spent"
            continue
        try:
            c, dt = timed(
                lambda: _capped(build().checker().threads(threads), target)
                .spawn_bfs()
            )
            out[f"cpu_{tag}_states_per_sec"] = round(c.state_count() / dt, 1)
            out[f"cpu_{tag}_unique"] = c.unique_state_count()
        except Exception as e:  # noqa: BLE001 - secondary configs never void
            out[f"cpu_{tag}_error"] = f"{type(e).__name__}: {e}"
    return out


def _capped(builder, target):
    return builder.target_states(target) if target else builder


def _bench_protocol():
    """(tag, model builder, unique-state cap or None=full) for the reference
    bench configs not already covered by the primary metrics."""
    from stateright_tpu.models.linearizable_register import abd_model
    from stateright_tpu.models.paxos import paxos_model
    from stateright_tpu.models.single_copy_register import single_copy_model
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys
    from stateright_tpu.actor import Network

    return [
        ("2pc10", lambda: TwoPhaseSys(10), 30_000),
        ("paxos6", lambda: paxos_model(6), 20_000),
        ("singlecopy4", lambda: single_copy_model(4, 1), 30_000),
        ("linreg2", lambda: abd_model(2, 2), None),  # full: 544 unique
        (
            "linreg3_ordered",
            lambda: abd_model(3, 2, Network.new_ordered()),
            10_000,
        ),
    ]


# ---------------------------------------------------------------------------
# TPU phase (child process; may touch / hang on the device backend)
# ---------------------------------------------------------------------------


def _mark(stage: str) -> None:
    """Progress mark on stderr: when the parent kills a hung child, the
    last mark pinpoints the stage that never returned."""
    sys.stderr.write(f"bench-tpu-stage: {stage}\n")
    sys.stderr.flush()


def _persist(out: dict) -> None:
    """Append the cumulative result dict to the stage file.  The parent
    tails this file while the child runs and re-emits the merged JSON line
    after every milestone, so a watchdog kill salvages every number that
    landed instead of only stderr marks."""
    path = os.environ.get("BENCH_STAGE_FILE")
    if not path:
        return
    try:
        with open(path, "a") as f:
            f.write(json.dumps(out) + "\n")
            f.flush()
            os.fsync(f.fileno())
    except OSError:
        pass


def tpu_phase() -> dict:
    import threading

    from stateright_tpu.models.paxos import paxos_model
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    t_start = time.monotonic()
    budget = float(os.environ.get("BENCH_TPU_TIMEOUT", "1200"))
    out: dict = {}
    tpu_phase.partial = out  # surfaced on mid-phase failure (see main)

    def heartbeat():
        # keeps the parent's stall watchdog fed during long silent sections
        # (device runs emit no stderr; only a truly hung child goes quiet)
        while True:
            time.sleep(60)
            _mark(f"alive t+{time.monotonic() - t_start:.0f}s")

    threading.Thread(target=heartbeat, daemon=True).start()

    def _register(checker, leg: str, body=None) -> None:
        """Archive one completed leg into the persistent run registry
        (telemetry/registry.py) when STATERIGHT_TPU_RUN_DIR was set —
        EVERY leg bench runs gets an archived report + index record, so
        the on-chip A/B backlog reads as registry history instead of
        transcript archaeology.  ``body`` reuses a report the leg
        already built (the paxos-3/2pc-7 embeds) instead of
        reconstructing discovery paths a second time.  Never voids a
        measurement."""
        if not RUN_LEDGER_DIR:
            return
        try:
            from stateright_tpu.telemetry.registry import RunRegistry

            rec = RunRegistry(RUN_LEDGER_DIR).record(
                checker, leg=leg, body=body
            )
            out.setdefault("run_registry", {})[leg] = rec["run_id"]
        except Exception as e:  # noqa: BLE001 - the ledger must never
            # void the leg's number
            sys.stderr.write(
                f"bench: run-registry record failed for {leg}: "
                f"{type(e).__name__}: {e}\n"
            )

    phases: dict = {}  # per-phase wall breakdown (docs/perf.md)
    out["tpu_phases"] = phases
    _mark("backend-init (jax.devices)")
    t_init = time.monotonic()
    out["tpu_devices"] = _device_names()
    phases["backend_init_secs"] = round(time.monotonic() - t_init, 3)
    _mark("backend-up")
    _persist(out)

    # parity gate on device (capacity sized so no growth event interrupts).
    # "compile ..." marks delimit cold-compile windows: the parent's
    # watchdog uses the last mark to tell a backend-init hang from an
    # engine-compile hang (the two need different remedies).
    _mark("compile (paxos2 engine)")
    tpu_p2 = paxos_model(2).checker().spawn_tpu(sync=True, capacity=1 << 18)
    _mark("paxos2 parity done")
    if tpu_p2.unique_state_count() != PAXOS2_UNIQUE:
        raise AssertionError(
            f"tpu paxos2 unique {tpu_p2.unique_state_count()} != {PAXOS2_UNIQUE}"
        )
    out["tpu_paxos2_discoveries"] = sorted(tpu_p2.discoveries())
    _register(tpu_p2, "paxos2_parity")
    _persist(out)

    # PRIMARY METRIC NEXT: paxos check 3 — everything else is secondary and
    # must not be able to cost us this number.  Same model instance across
    # warm-up + timed run so the compiled-run cache on the tensor twin is
    # reused.
    target = os.environ.get("BENCH_TPU_TARGET", "")
    m3 = paxos_model(3)
    # tuned on v5e (r4 sweep, full-enumeration runs): 4096x512 and
    # 6144x384 edge out 2048x1024 (~307-321k vs ~303-305k states/s); all
    # configs sit in a ±5% band, larger cand budgets consistently lose
    caps = dict(capacity=1 << 23, queue_capacity=1 << 21, batch=4096,
                steps_per_call=512)

    def spawn3():
        # flight recorder on (stateright_tpu/telemetry/): host-side only,
        # <3% overhead contract (pinned in tests/test_telemetry.py), and
        # the per-step series is the artifact the perf round needs.
        # Cartography counters ride the step (<=5% pin, well inside the
        # regress tolerance): the headline number and the run report that
        # explains it come from the SAME run (docs/telemetry.md).  The
        # memory ledger (host arithmetic only) rides along too, so the
        # measurement arrives with its HBM footprint + growth forecast —
        # what regress.py --memory gates.
        b = m3.checker().telemetry(
            capacity=2048, cartography=True, memory=True, roofline=True
        )
        if target:
            b = b.target_states(int(target))
        return b.spawn_tpu(sync=True, **caps)

    _mark("compile (paxos3 engine)")
    t_warm = time.monotonic()
    spawn3()  # warm-up (compile)
    phases["paxos3_warmup_secs"] = round(time.monotonic() - t_warm, 3)
    _mark("paxos3 warm-up done")
    tpu_p3, dt = timed(spawn3)
    phases["paxos3_run_secs"] = round(dt, 3)
    _mark("paxos3 timed run done")
    if tpu_p3.flight_recorder is not None:
        summ3 = tpu_p3.flight_recorder.summary()
        # the cartography/memory blocks are embedded once as standalone
        # tpu_paxos3_cartography / tpu_paxos3_memory (the regress.py
        # contract keys) and once inside the self-contained report — not
        # a third time here
        summ3.pop("cartography", None)
        summ3.pop("memory", None)
        summ3.pop("roofline", None)  # standalone tpu_paxos3_roofline key
        out["tpu_paxos3_telemetry"] = summ3
        mem3 = tpu_p3.memory()
        if mem3 is not None:
            out["tpu_paxos3_memory"] = mem3
        # the roofline cost ledger (telemetry/roofline.py): the LIVE
        # block — static per-stage FLOPs/bytes + the XLA-reconciliation
        # verdict + achieved-vs-ceiling where a device spec is known —
        # what regress.py --roofline gates and what the MXU round
        # (docs/roofline.md) executes against
        roof3 = tpu_p3.roofline()
        if roof3 is not None:
            out["tpu_paxos3_roofline"] = roof3
        # the per-stage attribution (init-compile / rung-compile /
        # device-step / growth / host) of the TIMED run — the numbers the
        # >=1M states/s chase is driven by (docs/perf.md)
        stages = tpu_p3.flight_recorder.stages()
        if stages:
            out["tpu_paxos3_stages"] = stages
        compiles = tpu_p3.flight_recorder.records("compile")
        if compiles:
            out["tpu_paxos3_compile_events"] = [
                {k: c.get(k) for k in
                 ("rung", "source", "cache_hit", "duration", "cap")}
                for c in compiles
            ]
        # the embedded post-run report (telemetry/report.py): cartography
        # + deterministic health timeline — what regress.py --cartography
        # gates and what the on-chip measurement rounds read to interpret
        # their numbers
        try:
            from stateright_tpu.telemetry.report import build_report

            out["tpu_paxos3_report"] = build_report(tpu_p3)
        except Exception as e:  # noqa: BLE001 - report loss must not
            # void the measured number
            out["tpu_paxos3_report_error"] = f"{type(e).__name__}: {e}"
        cart3 = tpu_p3.cartography()
        if cart3 is not None:
            out["tpu_paxos3_cartography"] = cart3
    out["tpu_paxos3_states_per_sec"] = round(tpu_p3.state_count() / dt, 1)
    out["tpu_paxos3_states"] = tpu_p3.state_count()
    out["tpu_paxos3_unique"] = tpu_p3.unique_state_count()
    out["tpu_paxos3_sec"] = round(dt, 3)
    out["tpu_paxos3_discoveries"] = sorted(tpu_p3.discoveries())
    if target:
        out["tpu_paxos3_note"] = f"prefix run, target_states={target}"
    else:
        out["tpu_paxos3_note"] = (
            "FULL enumeration: the complete paxos-3 space, pinned by "
            "tests/test_paxos_tensor.py (slow tier) at 1,194,428 unique"
        )
    _register(tpu_p3, "paxos3", body=out.get("tpu_paxos3_report"))
    _persist(out)

    # flag-gated POR leg (BENCH_POR=1; docs/analysis.md "State-space
    # reduction"): the same paxos-3 prefix with partial-order reduction
    # requested.  The independence analysis conservatively marks the
    # slot-multiset paxos twin all-dependent (JX302), so this leg measures
    # the FALLBACK contract — identical counts, and the por_status block
    # records why no reduction applied.  On a model that does reduce, the
    # same keys carry the reduced-vs-full split.
    if os.environ.get("BENCH_POR", "") == "1":
        try:
            _mark("compile (paxos3 por engine)")
            b_por = m3.checker().por()
            if target:
                b_por = b_por.target_states(int(target))
            tpu_por, dt_por = timed(
                lambda: b_por.spawn_tpu(sync=True, **caps)
            )
            out["tpu_paxos3_por_states_per_sec"] = round(
                tpu_por.state_count() / dt_por, 1
            )
            out["tpu_paxos3_por_unique"] = tpu_por.unique_state_count()
            out["tpu_paxos3_por_sec"] = round(dt_por, 3)
            out["tpu_paxos3_por"] = tpu_por.por_status()
            if tpu_por.unique_state_count() != tpu_p3.unique_state_count():
                out["tpu_paxos3_por_note"] = (
                    "MISMATCH vs the full-expansion run — investigate"
                )
            _register(tpu_por, "paxos3_por")
            _mark("paxos3 por leg done")
        except Exception as e:  # noqa: BLE001 - the flag-gated leg must
            # never void the primary metric
            out["tpu_paxos3_por_error"] = f"{type(e).__name__}: {e}"
        _persist(out)

        # per-channel leg (same BENCH_POR flag): the encoding where POR
        # actually reduces (docs/analysis.md "Per-channel encoding").
        # paxos-2 is the largest bundled paxos the MECHANICAL compiler
        # covers — the 3-client closure exceeds the per-actor universe
        # cap — so the reduction keys measure the full paxos-2 space:
        # per-channel full expansion vs per-channel + por(), with
        # reduction_ratio = explored/full unique and verdict parity
        # asserted so a broken reduction can't report a win.
        try:
            _mark("compile (paxos2 per-channel engines)")
            pc_caps = dict(sync=True, capacity=1 << 16, batch=512)
            m2f = paxos_model(2, 3)
            m2f.per_channel_()
            tpu_pcf, dt_pcf = timed(
                lambda: m2f.checker().spawn_tpu(**pc_caps)
            )
            m2p = paxos_model(2, 3)
            m2p.per_channel_()
            tpu_pc, dt_pc = timed(
                lambda: m2p.checker().por().spawn_tpu(**pc_caps)
            )
            if sorted(tpu_pc.discoveries()) != sorted(tpu_pcf.discoveries()):
                raise AssertionError(
                    "per-channel por changed property discoveries: "
                    f"{sorted(tpu_pc.discoveries())} != "
                    f"{sorted(tpu_pcf.discoveries())}"
                )
            full_u = tpu_pcf.unique_state_count()
            por_u = tpu_pc.unique_state_count()
            out["tpu_paxos2_por_channel_states_per_sec"] = round(
                tpu_pc.state_count() / dt_pc, 1
            )
            out["tpu_paxos2_por_channel_unique"] = por_u
            out["tpu_paxos2_por_channel_full_unique"] = full_u
            out["tpu_paxos2_por_channel_sec"] = round(dt_pc, 3)
            out["tpu_paxos2_por_channel_full_sec"] = round(dt_pcf, 3)
            out["tpu_paxos2_por_channel_reduction_ratio"] = round(
                por_u / full_u, 4
            ) if full_u else None
            out["tpu_paxos2_por_channel"] = tpu_pc.por_status()
            _register(tpu_pcf, "paxos2_per_channel_full")
            _register(tpu_pc, "paxos2_per_channel_por")
            _mark("paxos2 per-channel por leg done")
        except Exception as e:  # noqa: BLE001 - same never-void rule
            out["tpu_paxos2_por_channel_error"] = f"{type(e).__name__}: {e}"
        _persist(out)

    # remaining parity gate + the driver metric's second config, 2pc check 4
    # AS WRITTEN (it is too small to rate-limit a TPU — ~2k unique states
    # finish in one engine call — so the rate mostly measures fixed per-run
    # overhead; 2pc7/2pc10 below give the throughput-representative number)
    _mark("compile (2pc5 engine)")
    tpu_t5 = TwoPhaseSys(5).checker().spawn_tpu(sync=True, capacity=1 << 17)
    _mark("2pc5 parity done")
    if tpu_t5.unique_state_count() != TPC5_UNIQUE:
        raise AssertionError(
            f"tpu 2pc5 unique {tpu_t5.unique_state_count()} != {TPC5_UNIQUE}"
        )
    out["tpu_2pc5_discoveries"] = sorted(tpu_t5.discoveries())
    _register(tpu_t5, "2pc5_parity")
    try:
        t4 = TwoPhaseSys(4)
        kw4 = dict(sync=True, capacity=1 << 15)
        t4.checker().spawn_tpu(**kw4)  # warm-up
        tpu_t4, dt4 = timed(lambda: t4.checker().spawn_tpu(**kw4))
        if tpu_t4.unique_state_count() != TPC4_UNIQUE:
            raise AssertionError(
                f"tpu 2pc4 unique {tpu_t4.unique_state_count()} != "
                f"{TPC4_UNIQUE}"
            )
        out["tpu_2pc4_states_per_sec"] = round(
            tpu_t4.state_count() / dt4, 1
        )
        out["tpu_2pc4_unique"] = tpu_t4.unique_state_count()
        out["tpu_2pc4_sec"] = round(dt4, 3)
        out["tpu_2pc4_note"] = (
            "full space; dominated by fixed per-run overhead at this size"
        )
        _register(tpu_t4, "2pc4")
        _mark("2pc4 done")
    except Exception as e:  # noqa: BLE001
        out["tpu_2pc4_error"] = f"{type(e).__name__}: {e}"
    _persist(out)

    # A/B the Pallas visited-set insert kernel (ops/pallas_insert.py) on the
    # same primary config; count parity is asserted so a miscompiled kernel
    # can't silently report a win.
    try:
        def spawn3p():
            b = m3.checker()
            if target:
                b = b.target_states(int(target))
            return b.spawn_tpu(sync=True, pallas=True, **caps)

        spawn3p()  # warm-up (compile)
        tpu_p3p, dtp = timed(spawn3p)
        if tpu_p3p.unique_state_count() != tpu_p3.unique_state_count():
            raise AssertionError(
                f"pallas path unique {tpu_p3p.unique_state_count()} != "
                f"{tpu_p3.unique_state_count()}"
            )
        out["tpu_paxos3_pallas_states_per_sec"] = round(
            tpu_p3p.state_count() / dtp, 1
        )
        out["tpu_paxos3_pallas_sec"] = round(dtp, 3)
        _register(tpu_p3p, "paxos3_pallas")
        _mark("paxos3 pallas A/B done")
    except Exception as e:  # noqa: BLE001
        out["tpu_paxos3_pallas_error"] = f"{type(e).__name__}: {e}"
    _persist(out)

    # secondary: 2pc check 7; failure must not void the primary metric, and
    # it is skipped when the phase budget is mostly spent (the parent kills
    # the whole child at the deadline, primary results and all)
    try:
        if time.monotonic() - t_start > 0.6 * budget:
            raise TimeoutError("phase budget mostly spent; skipping 2pc7")
        t7 = TwoPhaseSys(7)
        # cand pre-sized for 2pc's ~9x fanout: growth would work but each
        # doubling recompiles the engine, wasting warm-up budget
        caps7 = dict(capacity=1 << 21, queue_capacity=1 << 19, batch=2048,
                     steps_per_call=256, cand=1 << 15)
        # warm-up must build the SAME engine as the timed run: cartography
        # changes the step program (and the engine cache key), so a plain
        # warm-up would leave the timed run paying the cold compile
        spawn7 = lambda: (  # noqa: E731
            t7.checker()
            .telemetry(capacity=2048, cartography=True, memory=True,
                       roofline=True)
            .spawn_tpu(sync=True, **caps7)
        )
        spawn7()  # warm-up
        tpu_t7, dt7 = timed(spawn7)
        if tpu_t7.flight_recorder is not None:
            # the 2pc7-vs-2pc10 table-size anomaly (VERDICT.md) is
            # diagnosed from exactly this series
            summ7 = tpu_t7.flight_recorder.summary()
            summ7.pop("cartography", None)  # embedded as the standalone
            # tpu_2pc7_cartography key and inside the report already
            summ7.pop("memory", None)  # same rule: standalone key below
            summ7.pop("roofline", None)  # same rule again
            out["tpu_2pc7_telemetry"] = summ7
            mem7 = tpu_t7.memory()
            if mem7 is not None:
                out["tpu_2pc7_memory"] = mem7
            roof7 = tpu_t7.roofline()
            if roof7 is not None:
                out["tpu_2pc7_roofline"] = roof7
            try:
                from stateright_tpu.telemetry.report import build_report

                out["tpu_2pc7_report"] = build_report(tpu_t7)
            except Exception as e:  # noqa: BLE001
                out["tpu_2pc7_report_error"] = f"{type(e).__name__}: {e}"
            cart7 = tpu_t7.cartography()
            if cart7 is not None:
                out["tpu_2pc7_cartography"] = cart7
        out["tpu_2pc7_states_per_sec"] = round(tpu_t7.state_count() / dt7, 1)
        out["tpu_2pc7_states"] = tpu_t7.state_count()
        out["tpu_2pc7_unique"] = tpu_t7.unique_state_count()
        out["tpu_2pc7_sec"] = round(dt7, 3)
        _register(tpu_t7, "2pc7", body=out.get("tpu_2pc7_report"))
        _mark("2pc7 done")
    except Exception as e:  # noqa: BLE001
        out["tpu_2pc7_error"] = f"{type(e).__name__}: {e}"
    _persist(out)

    # flag-gated SPILL leg (BENCH_SPILL=1; docs/spill.md): the same 2pc-7
    # under a SIMULATED device budget provably smaller than the run's
    # steady-state footprint — the ROADMAP's billion-state success
    # metric.  Counts must be bit-identical to the unconstrained leg;
    # the tpu_2pc7_spill block carries the per-tier byte breakdown.
    if os.environ.get("BENCH_SPILL", "") == "1":
        try:
            _mark("2pc7 spill leg")
            from stateright_tpu.parallel.tensor_model import twin_or_none
            from stateright_tpu.telemetry.memory import (
                ENV_DEVICE_BYTES,
                total_bytes,
                wavefront_specs,
            )

            t7s = TwoPhaseSys(7)
            twin = twin_or_none(t7s)
            n_props = len(list(t7s.properties()))
            batch7, qcap7, bloom7 = 2048, 1 << 19, 1 << 23
            sp_cfg = (bloom7, batch7 * twin.max_actions)

            def _tot(cap):
                return total_bytes(wavefront_specs(
                    twin, n_props, cap, qcap7, batch7, cartography=True,
                    spill=sp_cfg,
                ))

            # the unconstrained 2pc-7 run ends at a 1<<21 table; budget
            # the 1<<20 -> 1<<21 migration transient OUT so the hot tier
            # pins at 1<<20 (trigger 262,144 < the ~296k unique space)
            # and at least one eviction must fire for the run to finish
            budget = int(os.environ.get("BENCH_SPILL_BUDGET", 0)) or (
                _tot(1 << 20) + _tot(1 << 21) - 1
            )
            out["tpu_2pc7_spill_budget_bytes"] = budget
            prev = os.environ.get(ENV_DEVICE_BYTES)
            os.environ[ENV_DEVICE_BYTES] = str(budget)
            try:
                spawn7s = lambda: (  # noqa: E731
                    TwoPhaseSys(7).checker().spill()
                    .telemetry(capacity=2048, cartography=True, memory=True)
                    .spawn_tpu(
                        sync=True, capacity=1 << 19, queue_capacity=qcap7,
                        batch=batch7, steps_per_call=256, cand=1 << 15,
                        spill_bloom_bits=bloom7,
                    )
                )
                spawn7s()  # warm-up (same engine as the timed run)
                tpu_sp, dt_sp = timed(spawn7s)
            finally:
                if prev is None:
                    os.environ.pop(ENV_DEVICE_BYTES, None)
                else:
                    os.environ[ENV_DEVICE_BYTES] = prev
            out["tpu_2pc7_spill_states_per_sec"] = round(
                tpu_sp.state_count() / dt_sp, 1
            )
            out["tpu_2pc7_spill_unique"] = tpu_sp.unique_state_count()
            out["tpu_2pc7_spill_states"] = tpu_sp.state_count()
            out["tpu_2pc7_spill_sec"] = round(dt_sp, 3)
            out["tpu_2pc7_spill"] = tpu_sp.spill_status()
            if (
                "tpu_2pc7_unique" in out
                and tpu_sp.unique_state_count() != out["tpu_2pc7_unique"]
            ):
                out["tpu_2pc7_spill_note"] = (
                    "MISMATCH vs the unconstrained run — investigate"
                )
            _register(tpu_sp, "2pc7_spill")
            _mark("2pc7 spill leg done")
        except Exception as e:  # noqa: BLE001 - the flag-gated leg must
            # never void the primary metric
            out["tpu_2pc7_spill_error"] = f"{type(e).__name__}: {e}"
        _persist(out)

    # flag-gated MXU-recast legs (BENCH_MXU=1; docs/roofline.md
    # "Executing the hot-spot list"): the same paxos-3 and 2pc-7 configs
    # with CheckerBuilder.mxu() armed — expand-scatter coalescing, slim
    # queue traffic, and the BLEST one-hot probe.  Count parity against
    # the unflagged legs is ASSERTED (a broken recast cannot report a
    # win), and each leg embeds its FLAGGED roofline block
    # (tpu_*_mxu_roofline) next to the same run's unflagged block —
    # exactly the before/after pair regress.py --mxu gates: paxos-3
    # expand+queue charged bytes must drop >=30%, and 2pc-7's
    # dedup-insert stage must carry a dot-class op.
    if os.environ.get("BENCH_MXU", "") == "1":
        try:
            _mark("compile (paxos3 mxu engine)")

            def spawn3m():
                # the A/B must be FLAG-only: same telemetry set as the
                # unflagged leg (cartography rides the step program at
                # the <=5% pin — dropping it here would inflate the
                # recast's measured delta by the same magnitude)
                b = m3.checker().mxu().telemetry(
                    capacity=2048, cartography=True, memory=True,
                    roofline=True,
                )
                if target:
                    b = b.target_states(int(target))
                return b.spawn_tpu(sync=True, **caps)

            spawn3m()  # warm-up (compile)
            tpu_m3, dt_m3 = timed(spawn3m)
            if tpu_m3.unique_state_count() != tpu_p3.unique_state_count():
                raise AssertionError(
                    f"mxu paxos3 unique {tpu_m3.unique_state_count()} != "
                    f"{tpu_p3.unique_state_count()}"
                )
            out["tpu_paxos3_mxu_states_per_sec"] = round(
                tpu_m3.state_count() / dt_m3, 1
            )
            out["tpu_paxos3_mxu_unique"] = tpu_m3.unique_state_count()
            out["tpu_paxos3_mxu_sec"] = round(dt_m3, 3)
            roof_m3 = tpu_m3.roofline()
            if roof_m3 is not None:
                out["tpu_paxos3_mxu_roofline"] = roof_m3
            _register(tpu_m3, "paxos3_mxu")
            _mark("paxos3 mxu leg done")
        except Exception as e:  # noqa: BLE001 - the flag-gated leg must
            # never void the primary metric
            out["tpu_paxos3_mxu_error"] = f"{type(e).__name__}: {e}"
        _persist(out)
        try:
            _mark("compile (2pc7 mxu engine)")
            caps7m = dict(
                capacity=1 << 21, queue_capacity=1 << 19, batch=2048,
                steps_per_call=256, cand=1 << 15,
            )
            # flag-only A/B: telemetry set mirrors the unflagged leg
            spawn7m = lambda: (  # noqa: E731
                TwoPhaseSys(7).checker().mxu()
                .telemetry(capacity=2048, cartography=True, memory=True,
                           roofline=True)
                .spawn_tpu(sync=True, **caps7m)
            )
            spawn7m()  # warm-up
            tpu_m7, dt_m7 = timed(spawn7m)
            if (
                "tpu_2pc7_unique" in out
                and tpu_m7.unique_state_count() != out["tpu_2pc7_unique"]
            ):
                raise AssertionError(
                    f"mxu 2pc7 unique {tpu_m7.unique_state_count()} != "
                    f"{out['tpu_2pc7_unique']}"
                )
            out["tpu_2pc7_mxu_states_per_sec"] = round(
                tpu_m7.state_count() / dt_m7, 1
            )
            out["tpu_2pc7_mxu_unique"] = tpu_m7.unique_state_count()
            out["tpu_2pc7_mxu_sec"] = round(dt_m7, 3)
            roof_m7 = tpu_m7.roofline()
            if roof_m7 is not None:
                out["tpu_2pc7_mxu_roofline"] = roof_m7
            _register(tpu_m7, "2pc7_mxu")
            _mark("2pc7 mxu leg done")
        except Exception as e:  # noqa: BLE001 - same never-void rule
            out["tpu_2pc7_mxu_error"] = f"{type(e).__name__}: {e}"
        _persist(out)

    # flag-gated SWEEP leg (BENCH_SWEEP=1; docs/sweep.md): the paxos
    # default family (alternating lossy/non-lossy single-client
    # instances) checked as ONE hyper-batched sweep versus the same
    # instances run sequentially.  Per-instance count parity is
    # ASSERTED (a sweep that drifts cannot report a win), the engine
    # compile count must equal the cohort count (the amortization the
    # mode exists for: C compiles for N instances), and the aggregate
    # throughput pair (tpu_sweep_states_per_sec vs
    # tpu_sweep_sequential_states_per_sec) is the A/B the chip decides.
    if os.environ.get("BENCH_SWEEP", "") == "1":
        try:
            from stateright_tpu.models.paxos import sweep_family

            n_sw = int(os.environ.get("BENCH_SWEEP_N", "8") or 8)
            _mark("compile (sweep cohorts)")
            spec = sweep_family(n_sw)
            caps_sw = dict(
                capacity=1 << 15, batch=1024, steps_per_call=64,
            )

            def spawn_sw():
                # the A/B must be FLAG-only (the BENCH_MXU rule): same
                # telemetry set as the sequential legs below, and the
                # per-instance registry archive happens OUTSIDE the
                # timed window — report building walks discovery paths
                # and must not bias the sweep side
                b = spec.instances[0].model.checker().telemetry(
                    capacity=2048
                ).sweep(spec)
                return b.spawn_tpu(sync=True, **caps_sw)

            sw, dt_sw = timed(spawn_sw)
            sw.join()
            # sequential oracle: the SAME family, fresh models (fresh
            # twins — each pays its own engine compile, which is the
            # point), same engine knobs, same telemetry set
            seq_spec = sweep_family(n_sw)
            t_seq = time.monotonic()
            seq_counts = {}
            for inst in seq_spec.instances:
                c1 = inst.model.checker().telemetry(
                    capacity=2048
                ).spawn_tpu(sync=True, **caps_sw)
                seq_counts[inst.key] = (
                    c1.unique_state_count(), c1.state_count(),
                )
            dt_seq = time.monotonic() - t_seq
            if RUN_LEDGER_DIR:
                # archive per-instance records AFTER both timed windows
                sw._run_dir = RUN_LEDGER_DIR
                sw._maybe_record_run()
            mismatches = [
                k for k in seq_counts
                if (sw.results[k].unique, sw.results[k].states)
                != seq_counts[k]
            ]
            if mismatches:
                raise AssertionError(
                    f"sweep-vs-sequential count drift: {mismatches}"
                )
            total_states = sw.state_count()
            out["tpu_sweep_states_per_sec"] = round(
                total_states / dt_sw, 1
            )
            out["tpu_sweep_sequential_states_per_sec"] = round(
                total_states / dt_seq, 1
            )
            out["tpu_sweep"] = {
                "instances": len(spec.instances),
                "cohorts": len(sw.cohorts),
                "engine_compiles": int(sw.engine_compiles),
                "sequential_engine_compiles": len(seq_spec.instances),
                "unique": sw.unique_state_count(),
                "states": total_states,
                "sec": round(dt_sw, 3),
                "sequential_sec": round(dt_seq, 3),
                "parity": "IDENTICAL",
                "per_instance": {
                    k: {"unique": int(sw.results[k].unique),
                        "states": int(sw.results[k].states)}
                    for k in seq_counts
                },
            }
            if RUN_LEDGER_DIR:
                out.setdefault("run_registry", {})["sweep"] = sw.run_id
            _mark("sweep leg done")
        except Exception as e:  # noqa: BLE001 - the flag-gated leg must
            # never void the primary metric
            out["tpu_sweep_error"] = f"{type(e).__name__}: {e}"
        _persist(out)

    # flag-gated FLEET leg (BENCH_FLEET=1; docs/fleet.md): a small
    # multi-tenant job mix (three packable 2pc-3 tenants + a 2pc-4
    # singleton) scheduled over a BENCH_FLEET_SLOTS pool versus the same
    # jobs run one at a time.  Per-job count parity vs the solo runs is
    # ASSERTED (a scheduler that drifts cannot report a win), the packed
    # cohort must compile strictly fewer engines than jobs, and the
    # aggregate-throughput pair (tpu_fleet_states_per_sec vs
    # tpu_fleet_sequential_states_per_sec) is the serving metric.
    if os.environ.get("BENCH_FLEET", "") == "1":
        try:
            from stateright_tpu.checker.base import CheckerBuilder
            from stateright_tpu.fleet import COMPLETED as _FLEET_DONE
            from stateright_tpu.fleet import FleetSpec, Job, run_fleet
            from stateright_tpu.models.two_phase_commit import TwoPhaseSys

            slots_fl = int(os.environ.get("BENCH_FLEET_SLOTS", "2") or 2)

            def job_fl(key, n, packable):
                return Job(
                    key=key, packable=packable, capacity=1 << 13,
                    batch=256,
                    build=lambda n=n: CheckerBuilder(
                        TwoPhaseSys(n)
                    ).telemetry(capacity=2048),
                )

            jobs_fl = [
                job_fl("2pc3-a", 3, True), job_fl("2pc3-b", 3, True),
                job_fl("2pc3-c", 3, True), job_fl("2pc4", 4, False),
            ]
            _mark("fleet leg (pool run)")
            t_fl = time.monotonic()
            fl = run_fleet(
                FleetSpec(jobs=jobs_fl, slots=slots_fl), stream=None
            )
            dt_fl = time.monotonic() - t_fl
            # solo oracle: the SAME jobs one at a time, fresh builders,
            # same engine knobs — each pays its own compile, which is
            # exactly the overhead cohort packing amortizes
            t_fseq = time.monotonic()
            seq_fl = {}
            for j in jobs_fl:
                c1 = j.build().spawn_tpu(sync=True, **j.engine_kw())
                seq_fl[j.key] = (
                    c1.unique_state_count(), c1.state_count(),
                )
            dt_fseq = time.monotonic() - t_fseq
            bad = [
                k for k in seq_fl
                if fl[k].status != _FLEET_DONE
                or (fl[k].unique, fl[k].states) != seq_fl[k]
            ]
            if bad:
                raise AssertionError(f"fleet-vs-solo count drift: {bad}")
            total_fl = sum(r.states or 0 for r in fl.results.values())
            out["tpu_fleet_states_per_sec"] = round(total_fl / dt_fl, 1)
            out["tpu_fleet_sequential_states_per_sec"] = round(
                total_fl / dt_fseq, 1
            )
            out["tpu_fleet"] = {
                "jobs": len(jobs_fl),
                "slots": int(fl.slots),
                "completed": int(fl.completed),
                "preemptions": int(fl.preemptions),
                "engine_compiles": int(fl.engine_compiles),
                "sequential_engine_compiles": len(jobs_fl),
                "packed": sum(len(p["jobs"]) for p in fl.packed),
                "states": int(total_fl),
                "sec": round(dt_fl, 3),
                "sequential_sec": round(dt_fseq, 3),
                "parity": "IDENTICAL",
            }
            _mark("fleet leg done")
        except Exception as e:  # noqa: BLE001 - same never-void rule
            out["tpu_fleet_error"] = f"{type(e).__name__}: {e}"
        _persist(out)

    # flag-gated MESH leg (BENCH_MESH=1; docs/mesh.md): the GSPMD
    # mesh engine vs the single-device wavefront on the same 2pc
    # instance.  Count parity vs the solo run is ASSERTED (a
    # partitioning that drifts cannot report a win), and the block
    # carries the per-shard load vector, the imbalance summary, and the
    # routed-state total NEXT TO the throughput pair — GPUexplore's
    # scalability study names routing imbalance as what breaks at
    # scale, so the A/B ships with its own scalability readout.
    if os.environ.get("BENCH_MESH", "") == "1":
        try:
            from stateright_tpu.checker.base import CheckerBuilder
            from stateright_tpu.models.two_phase_commit import TwoPhaseSys

            n_me = int(os.environ.get("BENCH_MESH_RMS", "5") or 5)

            def build_me():
                return CheckerBuilder(TwoPhaseSys(n_me)).spawn_tpu(
                    sync=True, capacity=1 << 15, batch=256,
                )

            _mark("mesh leg (mesh run)")
            t_me = time.monotonic()
            cm = CheckerBuilder(TwoPhaseSys(n_me)).mesh().spawn_tpu(
                sync=True, capacity=1 << 15, batch=256,
            )
            dt_me = time.monotonic() - t_me
            _mark("mesh leg (solo oracle)")
            t_ms = time.monotonic()
            cs = build_me()
            dt_ms = time.monotonic() - t_ms
            pair_m = (cm.unique_state_count(), cm.state_count())
            pair_s = (cs.unique_state_count(), cs.state_count())
            if pair_m != pair_s:
                raise AssertionError(
                    f"mesh-vs-solo count drift: {pair_m} != {pair_s}"
                )
            stats_me = cm.mesh_stats()
            out["tpu_mesh_states_per_sec"] = round(pair_m[1] / dt_me, 1)
            out["tpu_mesh_solo_states_per_sec"] = round(
                pair_s[1] / dt_ms, 1
            )
            out["tpu_mesh"] = {
                "model": f"2pc-{n_me}",
                "devices": int(stats_me["devices"]),
                "unique": int(pair_m[0]),
                "states": int(pair_m[1]),
                "shard_load": stats_me["shard_load"],
                "imbalance": stats_me["imbalance"],
                "routed_states": int(stats_me["routed_states"]),
                "sec": round(dt_me, 3),
                "solo_sec": round(dt_ms, 3),
                "parity": "IDENTICAL",
            }
            _mark("mesh leg done")
        except Exception as e:  # noqa: BLE001 - same never-void rule
            out["tpu_mesh_error"] = f"{type(e).__name__}: {e}"
        _persist(out)

    # flag-gated LIVE leg (BENCH_LIVE=1; docs/observability.md): paxos-3
    # with plain telemetry (base) vs telemetry + metrics bus + armed
    # progress heartbeat (live).  Count parity vs the base run is
    # ASSERTED (the bus and heartbeat sample host syncs that already
    # happen; instrumentation that changes counts broke the
    # zero-overhead contract outright), and the block carries the
    # measured overhead fraction next to the published family list and
    # the terminal heartbeat — what regress.py --live gates.
    if os.environ.get("BENCH_LIVE", "") == "1":
        import shutil
        import tempfile

        try:
            from stateright_tpu.checkpoint import read_progress
            from stateright_tpu.models.paxos import paxos_model
            from stateright_tpu.telemetry.metrics import (
                default_bus,
                reset_default_bus,
            )

            m_lv = paxos_model(3)
            kw_lv = dict(sync=True, capacity=1 << 18,
                         queue_capacity=1 << 16, batch=1024,
                         steps_per_call=64)

            def run_base():
                return m_lv.checker().telemetry(capacity=2048).spawn_tpu(
                    **kw_lv
                )

            _mark("live leg (warm-up)")
            run_base()  # warm-up (compile; cache shared with both runs)
            _mark("live leg (base run)")
            t_lb = time.monotonic()
            cb = run_base()
            dt_lb = time.monotonic() - t_lb
            hb_dir = tempfile.mkdtemp(prefix="bench-live-")
            try:
                reset_default_bus()
                _mark("live leg (instrumented run)")
                t_lv = time.monotonic()
                # every_secs high enough that no snapshot generation is
                # ever due: the leg measures bus sampling + heartbeat
                # writes, not checkpoint serialization (the autosave arm
                # is what arms the heartbeat)
                cl = (
                    m_lv.checker()
                    .telemetry(capacity=2048, metrics=True)
                    .autosave(hb_dir, every_secs=3600.0)
                    .spawn_tpu(**kw_lv)
                )
                dt_lv = time.monotonic() - t_lv
                pair_b = (cb.unique_state_count(), cb.state_count())
                pair_l = (cl.unique_state_count(), cl.state_count())
                if pair_b != pair_l:
                    raise AssertionError(
                        f"live-vs-base count drift: {pair_l} != {pair_b}"
                    )
                hb = read_progress(hb_dir) or {}
                out["tpu_live"] = {
                    "model": "paxos-3",
                    "unique": int(pair_l[0]),
                    "states": int(pair_l[1]),
                    "parity": "IDENTICAL",
                    "base_sec": round(dt_lb, 3),
                    "live_sec": round(dt_lv, 3),
                    "overhead_frac": round(
                        max(dt_lv - dt_lb, 0.0) / max(dt_lb, 1e-9), 3
                    ),
                    "families": default_bus().families(),
                    "heartbeat": {
                        k: hb.get(k)
                        for k in ("verdict", "status", "states",
                                  "unique", "steps")
                    },
                }
            finally:
                shutil.rmtree(hb_dir, ignore_errors=True)
            _mark("live leg done")
        except Exception as e:  # noqa: BLE001 - same never-void rule
            out["tpu_live_error"] = f"{type(e).__name__}: {e}"
        _persist(out)

    # reference bench protocol on device.  All five configs compile — the
    # actor compiler gained ordered-FIFO network support in round 2
    # (parallel/actor_compiler.py), so lin-reg-3-ordered runs on device too
    # (pinned by tests/test_network_matrix.py); a failure on any config is
    # recorded per-tag without voiding the primary metric.  Device runs use
    # 10x the CPU prefix target: at 100k-1M states/s a CPU-sized prefix
    # finishes in well under a second and the measured "rate" is mostly
    # fixed overhead (tunnel RTT, growth rehashes), not engine throughput —
    # states/sec is rate-like, so a longer prefix measures it more fairly.
    for tag, build, target in _bench_protocol():
        try:
            if time.monotonic() - t_start > 0.75 * budget:
                raise TimeoutError("phase budget mostly spent")
            mm = build()
            target = target * 10 if target else None
            kw = dict(sync=True, capacity=1 << 23, queue_capacity=1 << 21,
                      batch=2048, steps_per_call=256, cand=1 << 15)
            _capped(mm.checker(), target).spawn_tpu(**kw)  # warm-up
            c, dt = timed(
                lambda: _capped(mm.checker(), target).spawn_tpu(**kw)
            )
            from stateright_tpu.parallel._base import SMALL_SPACE_BREAK_EVEN

            out[f"tpu_{tag}_states_per_sec"] = round(c.state_count() / dt, 1)
            out[f"tpu_{tag}_unique"] = c.unique_state_count()
            if c.unique_state_count() < SMALL_SPACE_BREAK_EVEN:
                # the small-space footgun, disclosed per config: below the
                # break-even the measured "rate" is fixed per-run overhead
                # and CPU BFS is faster — spawn_auto() picks CPU here
                out[f"tpu_{tag}_note"] = (
                    "overhead-dominated small space; spawn_auto() selects "
                    "the CPU engine for this config"
                )
            _register(c, tag)
            _mark(f"{tag} done")
        except Exception as e:  # noqa: BLE001
            out[f"tpu_{tag}_error"] = f"{type(e).__name__}: {e}"
        _persist(out)

    return out


def _device_names() -> list:
    import jax

    return [str(d) for d in jax.devices()]


def _tunnel_diagnostics() -> dict:
    """Cheap host-side evidence about the loopback TPU tunnel (see
    docs/axon-init-hang.md): is the relay process alive, and does its first
    listen port accept?  A local accept proves nothing about the far end
    (that is the whole failure mode), but relay-dead vs relay-listening
    cleanly splits 'tunnel torn down' from 'far end unresponsive'."""
    import socket

    diag: dict = {}
    try:
        procs = subprocess.run(
            ["pgrep", "-af", "relay.py"], capture_output=True, text=True,
            timeout=5,
        )
        diag["relay_proc"] = procs.stdout.strip().splitlines()[:2]
    except Exception as e:  # noqa: BLE001
        diag["relay_proc_error"] = str(e)
    try:
        with socket.create_connection(("127.0.0.1", 8082), timeout=3):
            diag["relay_port_8082"] = "accepts"
    except OSError as e:
        diag["relay_port_8082"] = f"refused/timeout: {e}"
    return diag


def _salvage(stage_path: str) -> dict:
    """Last cumulative result dict the child persisted, if any."""
    try:
        with open(stage_path) as f:
            lines = [l for l in f.read().splitlines() if l.strip()]
        for line in reversed(lines):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    except OSError:
        pass
    return {}


def _term_then_kill(proc, grace: float = 5.0):
    """SIGTERM + grace before SIGKILL: wedging-by-kill is disproven
    (docs/axon-init-hang.md), but a clean exit flushes child buffers.
    Returns the final ``communicate()`` output — after a timed-out
    ``communicate()``, CPython buffers the partial pipe data internally and
    hands it to the NEXT call, so this is where a hung child's faulthandler
    stack dump actually surfaces (reading ``proc.stdout`` directly instead
    would raise on the closed file and lose it)."""
    proc.terminate()
    try:
        proc.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        proc.kill()
    try:
        return proc.communicate()
    except ValueError:  # pipes already consumed/closed
        return "", ""


class Probe:
    """Init-only child started right after the primary CPU baseline lands
    (overlapping the rest of the CPU phase): ``import jax; jax.devices()``
    with a faulthandler stack dump armed, so by the time CPU numbers are
    in we know whether the backend is reachable — without having burned
    serial wall-clock on it, and without contending with the single-core
    baseline measurement."""

    def __init__(self):
        self.t0 = time.monotonic()
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--tpu-probe"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )

    def result(self, wait_s: float) -> dict:
        """Wait up to ``wait_s`` more; returns {ok, sec, detail}."""
        try:
            out, err = self.proc.communicate(timeout=wait_s)
            ok = self.proc.returncode == 0 and "probe-ok" in out
            detail = (out.strip().splitlines() + err.strip().splitlines())
            return {
                "ok": ok,
                "sec": round(time.monotonic() - self.t0, 1),
                "detail": detail[-6:],
            }
        except subprocess.TimeoutExpired:
            out, err = _term_then_kill(self.proc)
            return {
                "ok": False,
                "sec": round(time.monotonic() - self.t0, 1),
                "detail": ["probe hung; stack at timeout:"]
                + ((out or "") + "\n" + (err or "")).strip().splitlines()[-12:],
            }


def _kill_reason(
    stuck_init: bool, last_stage: str, init_s: float, timeout_s: float
) -> str:
    """Classify a watchdog kill for the headline ``error`` field: a child
    that never got past backend init (the dead-tunnel signature), one
    that died inside a compile/warm-up window (the ``compile ...`` stage
    marks — each spans the engine compile AND the warm-up run it fuses
    with, so the message says so), and everything else are three
    different problems — the first needs the tunnel fixed, the second
    points at cold compiles (the persistent compile cache, docs/perf.md)
    or a wedged warm-up, the third is a genuine run-budget miss."""
    if stuck_init:
        return f"stuck in backend init for {init_s:.0f}s"
    if last_stage.startswith("compile"):
        return (
            f"stuck in engine compile/warm-up after {timeout_s:.0f}s "
            f"(stage: {last_stage})"
        )
    return f"timed out after {timeout_s:.0f}s (stage: {last_stage or 'unknown'})"


def run_tpu_attempt(timeout_s: float, init_s: float = None) -> dict:
    """Run ``tpu_phase`` in a child; a backend hang cannot take down the
    parent's JSON lines.  Child stderr goes to a temp file (not a pipe) so
    that even after a timeout-kill the staged progress marks survive.  The
    child persists cumulative results to a stage file after every
    milestone; the parent polls that file every watchdog tick and RE-EMITS
    the merged JSON line, so the driver's artifact grows with the run."""
    import tempfile

    if init_s is None:
        init_s = float(os.environ.get("BENCH_TPU_INIT_TIMEOUT", "120"))
    stage_fd, stage_path = tempfile.mkstemp(suffix=".bench-stages")
    os.close(stage_fd)
    # the child's internal skip gates (0.6/0.75 * budget) must see the
    # ACTUAL per-attempt window, not the BENCH_TPU_TIMEOUT default — else
    # under a tight deadline the child never skips secondaries and the
    # watchdog kills it mid-run instead of letting it return cleanly
    env = dict(
        os.environ,
        BENCH_STAGE_FILE=stage_path,
        BENCH_TPU_TIMEOUT=str(int(timeout_s)),
    )
    # re-inject the run-ledger root the parent's main() consumed: the
    # child registers legs explicitly (its own main() consumes it again)
    if RUN_LEDGER_DIR:
        env["STATERIGHT_TPU_RUN_DIR"] = RUN_LEDGER_DIR
    try:
        return _run_tpu_child(timeout_s, init_s, stage_path, env)
    finally:
        try:
            os.unlink(stage_path)
        except OSError:
            pass


def _run_tpu_child(
    timeout_s: float, init_s: float, stage_path: str, env: dict
) -> dict:
    import tempfile

    with tempfile.TemporaryFile(mode="w+", errors="replace") as errf:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--tpu-child"],
            stdout=subprocess.PIPE,
            stderr=errf,
            text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env=env,
        )

        def read_err() -> list:
            # os.pread: the child writes through the same file description,
            # so seeking the shared offset mid-run would corrupt its output
            size = os.fstat(errf.fileno()).st_size
            data = os.pread(errf.fileno(), size, 0).decode(errors="replace")
            return data.strip().splitlines()

        def err_tail(n: int = 8) -> list:
            # heartbeat lines would flood out the stage marks this exists
            # to surface
            return [l for l in read_err() if "stage: alive" not in l][-n:]

        def last_stage() -> str:
            stage = ""
            for line in read_err():
                if line.startswith("bench-tpu-stage:") and "alive" not in line:
                    stage = line.split(":", 1)[1].strip()
            return stage

        # Init watchdog on top of the per-attempt budget: the tunnel's far
        # end has been observed unresponsive at driver-bench time, which
        # presents as an indefinite silent block inside PJRT client
        # creation (docs/axon-init-hang.md).  A healthy init is <10s, so
        # if the child is still in backend-init after ``init_s``, kill it
        # and let the caller retry/diagnose with the remaining budget.
        deadline = time.monotonic() + timeout_s
        t0 = time.monotonic()
        init_passed = False
        while True:
            try:
                stdout, _ = proc.communicate(timeout=5)
                break
            except subprocess.TimeoutExpired:
                # live-emit whatever milestones the child has persisted
                salv = _salvage(stage_path)
                if salv:
                    emit(**salv)
                now = time.monotonic()
                stuck_init = False
                if not init_passed:
                    stage = last_stage()
                    # "" = hung before the first mark (imports/interpreter):
                    # the same early-init hang class, treated identically
                    init_passed = stage not in (
                        "", "backend-init (jax.devices)"
                    )
                    stuck_init = not init_passed and now - t0 > init_s
                if now > deadline or stuck_init:
                    why = _kill_reason(
                        stuck_init, last_stage(), init_s, timeout_s
                    )
                    _term_then_kill(proc)
                    res = _salvage(stage_path)
                    res.update(
                        error=f"TPU phase {why}",
                        tpu_stuck_init=stuck_init,
                        tpu_trace_tail=err_tail(),
                    )
                    return res
        for line in reversed(stdout.strip().splitlines()):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
        res = _salvage(stage_path)
        res.update(
            error=f"TPU phase exited rc={proc.returncode} without JSON",
            tpu_trace_tail=err_tail() or stdout.strip().splitlines()[-8:],
        )
        return res


def run_tpu_with_budget(budget_s: float, probe: Probe) -> dict:
    """Spend the TPU budget landing numbers — never one attempt.  The probe
    (running since the primary CPU baseline landed) gates nothing: full
    attempts start immediately; a probe verdict merely adds evidence.
    Attempts relaunch in fresh children on transient failures until the
    budget is spent.  Results from a killed attempt are salvaged from its
    stage file and merged, so the best partial data survives."""
    t0 = time.monotonic()
    attempts: list = []
    merged: dict = {}

    def remaining_budget() -> float:
        return budget_s - (time.monotonic() - t0)

    # collect the concurrent probe's verdict (wait at most briefly: a
    # healthy backend answers in seconds; a hung probe should not delay
    # the first full attempt, whose own init watchdog covers the hang)
    pr = probe.result(wait_s=max(5.0, min(30.0, remaining_budget() / 10)))
    attempts.append({"kind": "probe", **pr})
    sys.stderr.write(
        f"bench: probe ok={pr['ok']} in {pr['sec']:.0f}s\n"
    )
    if not pr["ok"]:
        merged["tpu_tunnel_diagnostics"] = _tunnel_diagnostics()
        merged["tpu_probe_stack"] = pr["detail"]
        emit(**merged)

    transient = ("init", "UNAVAILABLE", "ALREADY_EXISTS", "hung",
                 "without JSON")
    while remaining_budget() > 60 and len(attempts) < 24:
        res = run_tpu_attempt(remaining_budget())
        stuck = bool(res.pop("tpu_stuck_init", False))
        err = res.get("error")
        attempts.append(
            {"kind": "full", "ok": err is None, "stuck_init": stuck,
             "error": err}
        )
        sys.stderr.write(f"bench: full attempt ok={err is None}: {err}\n")
        cleared = ()
        if err is None:
            merged.pop("error", None)
            merged.pop("tpu_trace_tail", None)
            cleared = ("error", "tpu_trace_tail")
        merged.update(res)
        merged["tpu_attempts"] = attempts
        emit(_clear=cleared, **merged)
        if err is None or "tpu_paxos3_states_per_sec" in merged:
            break  # success, or the primary metric already landed
        if stuck:
            merged["tpu_tunnel_diagnostics"] = _tunnel_diagnostics()
        if not (stuck or any(t in err for t in transient)):
            break  # deterministic failure — a fresh child won't differ
        time.sleep(5)

    merged["tpu_attempts"] = attempts
    if not any(a["kind"] == "full" for a in attempts):
        merged.setdefault(
            "error",
            "TPU phase never attempted: budget exhausted before the first "
            "full child (see tpu_attempts)",
        )
    return merged


def _ab_run_one(rm: int, capacity: int, target) -> dict:
    """One A/B leg: a warm (compile pre-paid) timed 2pc run at the FIXED
    table capacity, with telemetry so the verdict carries occupancy and
    the per-stage breakdown."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    m = TwoPhaseSys(rm)
    caps = dict(sync=True, capacity=capacity, queue_capacity=capacity >> 2,
                batch=2048, steps_per_call=256, cand=1 << 15)

    def spawn():
        b = m.checker().telemetry(capacity=2048, occupancy_every=8)
        if target:
            b = b.target_states(int(target))
        return b.spawn_tpu(**caps)

    spawn()  # warm-up: same model instance, so the engine cache carries
    c, dt = timed(spawn)
    rec = c.flight_recorder
    summ = rec.summary() if rec is not None else {}
    return {
        "states_per_sec": round(c.state_count() / dt, 1),
        "states": c.state_count(),
        "unique": c.unique_state_count(),
        "sec": round(dt, 3),
        "occupancy_last": summ.get("occupancy_last"),
        "stages": rec.stages() if rec is not None else None,
        "growth_events": summ.get("growth_events"),
    }


def ab_table(run_one=None) -> int:
    """``bench.py --ab-table``: the 2pc7-vs-2pc10 same-table-size A/B
    (ROADMAP re-measure item).  Round 4 measured 2pc(7) at 1.45M states/s
    vs same-table-size 2pc(10) at 866k/s; the bucket-mix fix (PR 3)
    removed the prime suspect, and this mode re-measures the spread the
    day the tunnel opens.  Both configs run at the SAME fixed capacity
    (``BENCH_AB_CAPACITY``, default 2^23 slots) and the same insert volume
    (2pc10 targets 2pc7's unique count, or both take ``BENCH_AB_TARGET``),
    so any residual rate spread is table behavior, not volume.  Emits one
    compact JSON line; full legs go to the details side file."""
    cap = int(os.environ.get("BENCH_AB_CAPACITY", str(1 << 23)))
    target = os.environ.get("BENCH_AB_TARGET", "")
    run_one = run_one or (lambda rm, t: _ab_run_one(rm, cap, t))
    out: dict = {"metric": "2pc7 vs 2pc10 same-table-size A/B",
                 "capacity": cap}
    try:
        r7 = run_one(7, int(target) if target else None)
        # same insert volume for the bigger config: 2pc7's unique count
        r10 = run_one(10, int(target) if target else r7["unique"])
    except Exception as e:  # noqa: BLE001 - one JSON line either way
        out["error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(out), flush=True)
        return 1
    out["tpu_2pc7_states_per_sec"] = r7["states_per_sec"]
    out["tpu_2pc7_unique"] = r7["unique"]
    out["tpu_2pc10_states_per_sec"] = r10["states_per_sec"]
    out["tpu_2pc10_unique"] = r10["unique"]
    if r10["states_per_sec"]:
        out["ratio_7_over_10"] = round(
            r7["states_per_sec"] / r10["states_per_sec"], 3
        )
    full = {**out, "tpu_2pc7_ab": r7, "tpu_2pc10_ab": r10}
    base, ext = os.path.splitext(DETAILS_PATH)
    side = f"{base}-ab-table{ext or '.json'}"
    try:
        with open(side, "w") as f:
            json.dump(full, f, indent=1)
    except OSError as e:
        sys.stderr.write(f"bench: ab-table details unwritable: {e}\n")
    print(json.dumps(out), flush=True)
    return 0


def main() -> int:
    # consume the run-ledger knob FIRST (parent, child, probe, ab-table
    # alike): legs register explicitly via _register; an env knob left
    # in place would double-archive every leg through the checkers'
    # join-time auto-record (plus warm-ups/CPU runs as untagged noise)
    global RUN_LEDGER_DIR
    RUN_LEDGER_DIR = os.environ.pop("STATERIGHT_TPU_RUN_DIR", None)
    if "--ab-table" in sys.argv:
        return ab_table()
    if "--tpu-probe" in sys.argv:
        import faulthandler

        # dump the blocking stack EARLY and repeatedly: a healthy init
        # finishes in <10s, so a 45s dump only ever fires on hangs — and it
        # must land before the parent's kill, which can come as soon as
        # ~35s after start (short CPU phase + 30s result() wait)
        faulthandler.dump_traceback_later(45, repeat=True, file=sys.stderr)
        import jax

        print("probe-ok", [str(d) for d in jax.devices()])
        return 0
    if "--tpu-child" in sys.argv:
        try:
            print(json.dumps(tpu_phase()))
            return 0
        except Exception as e:  # noqa: BLE001
            tb = traceback.format_exc().strip().splitlines()
            # whatever sections completed before the failure still count
            partial = getattr(tpu_phase, "partial", {})
            partial.update({"error": f"{type(e).__name__}: {e}",
                            "tpu_trace_tail": tb[-6:]})
            print(json.dumps(partial))
            return 1

    # the probe starts right AFTER the primary CPU baseline lands (its
    # concurrent import would contend with that single-core measurement)
    # and overlaps the rest of the CPU phase; a hung probe never delays
    # the first full attempt, whose own init watchdog covers the hang
    probe_box: list = []
    # Immunize the PARENT against a dead tunnel: the accelerator site hook
    # force-selects jax_platforms="axon,cpu", so any stray backend touch
    # during the CPU phase (a jnp constant, a debug print of an array)
    # would block inside the axon client init exactly when the tunnel is
    # down — the failure mode bench exists to survive.  Forcing "cpu"
    # after import but before any backend init confines the parent to the
    # host; the TPU child/probe are separate processes with default env.
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 - defensive only; bench works without
        pass
    try:
        # line 1: the artifact can never again be empty
        emit(**cpu_phase(lambda: probe_box.append(Probe())))
    except Exception as e:  # noqa: BLE001 - CPU numbers lost, TPU still runs
        tb = traceback.format_exc().strip().splitlines()
        emit(cpu_phase_error=f"{type(e).__name__}: {e}",
             cpu_trace_tail=tb[-6:])
    if not probe_box:  # cpu_phase died before the primary baseline landed
        probe_box.append(Probe())

    tpu_budget = min(
        float(os.environ.get("BENCH_TPU_TIMEOUT", "1200")),
        max(remaining() - 30, 60),
    )
    extras = run_tpu_with_budget(tpu_budget, probe_box[0])

    for w in ("paxos2", "2pc5"):
        cpu_d = EXTRAS.get(f"cpu_{w}_discoveries")
        tpu_d = extras.get(f"tpu_{w}_discoveries")
        # both sides must exist: a cpu_phase crash leaves cpu_d None, which
        # is a CPU failure (already recorded as cpu_phase_error), not a
        # TPU correctness divergence
        if cpu_d is not None and tpu_d is not None and cpu_d != tpu_d:
            extras["error"] = (
                f"discovery parity failed on {w}: cpu={cpu_d} tpu={tpu_d}"
            )
            emit(**extras)
            return 1

    if extras.get("tpu_paxos3_states_per_sec") is not None:
        extras.setdefault(
            "parity",
            "paxos check 2 (16668) + 2pc check 5 (8832) on CPU and TPU",
        )
        emit(**extras)
        # fresh chip-validated number + parity gates passed: persist it so
        # future dead-tunnel invocations degrade to this instead of 0
        record_validated()
        # a partial TPU phase can carry the primary metric AND a phase-level
        # error (e.g. the backend died after the timed run): report the
        # number but exit nonzero so automation sees the broken run
        return 1 if "error" in extras else 0
    emit(**extras)
    return 1


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:  # noqa: BLE001 - a final JSON line must still appear
        tb = traceback.format_exc().strip().splitlines()
        emit(error=f"{type(e).__name__}: {e}", trace_tail=tb[-6:])
        sys.exit(1)
