"""Actor model tests (reference ``src/actor/model.rs`` tests).

Pins the exhaustive 14-state space of ping-pong at max_nat=1 on a lossy
duplicating network (reference ``model.rs:506-600``), the 4,094 / 11 counts
at max_nat=5 (``model.rs:611,642``), network-semantics behavioural
differences, timer semantics, and heterogeneous actor composition
(the reference needs a ``Choice`` combinator, ``model.rs:862-977``).
"""

import pytest

from stateright_tpu import Expectation, StateRecorder
from stateright_tpu.actor import (
    Actor,
    ActorModel,
    ActorModelState,
    Deliver,
    Drop,
    Envelope,
    Id,
    Network,
    ScriptedActor,
    Timeout,
    majority,
    model_peers,
)

from fixtures_actor import PingPongCfg, ping_pong_model


def _states_and_network(states, envelopes, history=(0, 0)):
    return ActorModelState(
        actor_states=tuple(states),
        network=Network.new_unordered_duplicating(envelopes),
        is_timer_set=(False,) * len(states),
        history=history,
    )


def _env(src, dst, msg):
    return Envelope(src=Id(src), dst=Id(dst), msg=msg)


def test_visits_expected_states_exhaustively():
    """Exact full-state-space equality (reference ``model.rs:506-600``)."""
    recorder = StateRecorder()
    model = ping_pong_model(PingPongCfg(maintains_history=False, max_nat=1))
    model.lossy = True
    checker = model.checker().visitor(recorder).spawn_bfs().join()
    assert checker.unique_state_count() == 14
    Ping, Pong = lambda v: ("Ping", v), lambda v: ("Pong", v)
    expected = {
        # lossless evolution
        _states_and_network([0, 0], [_env(0, 1, Ping(0))]),
        _states_and_network([0, 1], [_env(0, 1, Ping(0)), _env(1, 0, Pong(0))]),
        _states_and_network(
            [1, 1],
            [_env(0, 1, Ping(0)), _env(1, 0, Pong(0)), _env(0, 1, Ping(1))],
        ),
        # after losing the only message at (0, 0)
        _states_and_network([0, 0], []),
        # losses from (0, 1)
        _states_and_network([0, 1], [_env(1, 0, Pong(0))]),
        _states_and_network([0, 1], [_env(0, 1, Ping(0))]),
        _states_and_network([0, 1], []),
        # losses from (1, 1)
        _states_and_network([1, 1], [_env(1, 0, Pong(0)), _env(0, 1, Ping(1))]),
        _states_and_network([1, 1], [_env(0, 1, Ping(0)), _env(0, 1, Ping(1))]),
        _states_and_network([1, 1], [_env(0, 1, Ping(0)), _env(1, 0, Pong(0))]),
        _states_and_network([1, 1], [_env(0, 1, Ping(1))]),
        _states_and_network([1, 1], [_env(1, 0, Pong(0))]),
        _states_and_network([1, 1], [_env(0, 1, Ping(0))]),
        _states_and_network([1, 1], []),
    }
    assert set(recorder.states) == expected


def test_maintains_fixed_delta_despite_lossy_duplicating_network():
    model = ping_pong_model(PingPongCfg(maintains_history=False, max_nat=5))
    model.lossy = True
    checker = model.checker().spawn_bfs().join()
    assert checker.unique_state_count() == 4094
    checker.assert_no_discovery("delta within 1")


def test_may_never_reach_max_on_lossy_network():
    model = ping_pong_model(PingPongCfg(maintains_history=False, max_nat=5))
    model.lossy = True
    checker = model.checker().spawn_bfs().join()
    # can lose the first message and get stuck
    checker.assert_discovery(
        "must reach max", [Drop(_env(0, 1, ("Ping", 0)))]
    )


def test_eventually_reaches_max_on_perfect_delivery_network():
    model = ping_pong_model(PingPongCfg(maintains_history=False, max_nat=5))
    model.init_network = Network.new_unordered_nonduplicating()
    checker = model.checker().spawn_bfs().join()
    assert checker.unique_state_count() == 11
    checker.assert_no_discovery("must reach max")


def test_can_reach_max():
    model = ping_pong_model(PingPongCfg(maintains_history=False, max_nat=5))
    checker = model.checker().spawn_bfs().join()
    assert checker.unique_state_count() == 11
    path = checker.assert_any_discovery("can reach max")
    assert path.final_state().actor_states == (4, 5)


def test_history_properties():
    model = ping_pong_model(PingPongCfg(maintains_history=True, max_nat=3))
    checker = model.checker().spawn_bfs().join()
    # #in <= #out always holds; #out <= #in + 1 eventually holds on all paths
    checker.assert_no_discovery("#in <= #out")
    checker.assert_no_discovery("#out <= #in + 1")


# ---------------------------------------------------------------------------
# network semantics (reference ``model.rs:696-836``)
# ---------------------------------------------------------------------------


class _Echo(Actor):
    """Replies 'reply' to every 'msg' received (even when state unchanged)."""

    def on_start(self, id, out):
        return 0

    def on_msg(self, id, state, src, msg, out):
        out.send(src, ("echo", msg))
        return state + 1


def _one_shot_model(network):
    # actor 1 scripted to send two messages to actor 0
    return (
        ActorModel(None, None)
        .actor(_Echo())
        .actor(ScriptedActor([(Id(0), "a"), (Id(0), "b")]))
        .init_network_(network)
    )


def test_ordered_network_delivers_heads_only():
    m = (
        ActorModel(None, None)
        .actor(_Echo())
        .init_network_(
            Network.new_ordered(
                [_env(9, 0, "first"), _env(9, 0, "second"), _env(8, 0, "other")]
            )
        )
    )
    [init] = m.init_states()
    deliverable = {(a.src, a.msg) for a in m.actions(init) if isinstance(a, Deliver)}
    # only flow heads: "first" from 9, "other" from 8 — never "second"
    assert deliverable == {(Id(9), "first"), (Id(8), "other")}


def test_ordered_network_fifo_per_flow():
    m = (
        ActorModel(None, None)
        .actor(_Echo())
        .init_network_(Network.new_ordered([_env(9, 0, "first"), _env(9, 0, "second")]))
    )
    [init] = m.init_states()
    after = m.next_state(init, Deliver(src=Id(9), dst=Id(0), msg="first"))
    heads = [a.msg for a in m.actions(after) if isinstance(a, Deliver)]
    assert "second" in heads


def test_duplicating_network_redelivers():
    m = (
        ActorModel(None, None)
        .actor(_Echo())
        .init_network_(Network.new_unordered_duplicating([_env(9, 0, "dup")]))
    )
    [init] = m.init_states()
    after = m.next_state(init, Deliver(src=Id(9), dst=Id(0), msg="dup"))
    # envelope still deliverable after delivery
    assert any(
        a.msg == "dup" for a in m.actions(after) if isinstance(a, Deliver)
    )


def test_nonduplicating_network_consumes_and_counts_multiplicity():
    # the reference fixed a bug where a set lost multiplicity
    # (regression in ``model.rs:753-836``): two identical sends must allow
    # exactly two deliveries
    class TwoSends(Actor):
        def on_start(self, id, out):
            out.send(Id(1), "x")
            out.send(Id(1), "x")
            return 0

    class Count(Actor):
        def on_start(self, id, out):
            return 0

        def on_msg(self, id, state, src, msg, out):
            return state + 1

    m = (
        ActorModel(None, None)
        .actor(TwoSends())
        .actor(Count())
        .init_network_(Network.new_unordered_nonduplicating())
    )
    [init] = m.init_states()
    assert len(init.network) == 2
    s1 = m.next_state(init, Deliver(src=Id(0), dst=Id(1), msg="x"))
    assert len(s1.network) == 1 and s1.actor_states[1] == 1
    s2 = m.next_state(s1, Deliver(src=Id(0), dst=Id(1), msg="x"))
    assert len(s2.network) == 0 and s2.actor_states[1] == 2


def test_undeliverable_destination_ignored():
    m = (
        ActorModel(None, None)
        .actor(ScriptedActor([(Id(7), "void")]))  # destination doesn't exist
        .init_network_(Network.new_unordered_nonduplicating())
    )
    [init] = m.init_states()
    assert not [a for a in m.actions(init) if isinstance(a, Deliver)]


def test_no_op_deliveries_pruned():
    class Inert(Actor):
        def on_start(self, id, out):
            return 0

    m = (
        ActorModel(None, None)
        .actor(Inert())
        .init_network_(Network.new_unordered_duplicating([_env(5, 0, "ignored")]))
    )
    [init] = m.init_states()
    assert m.next_state(init, Deliver(src=Id(5), dst=Id(0), msg="ignored")) is None


# ---------------------------------------------------------------------------
# timers (reference ``model.rs:838-859``)
# ---------------------------------------------------------------------------


def test_timer_semantics():
    class TimerActor(Actor):
        def on_start(self, id, out):
            out.set_timer()
            return 0

        def on_timeout(self, id, state, out):
            if state < 2:
                out.set_timer()
                return state + 1
            return None  # stop: no re-arm; timer flag still clears

    m = ActorModel(None, None).actor(TimerActor())
    [init] = m.init_states()
    assert init.is_timer_set == (True,)
    s1 = m.next_state(init, Timeout(Id(0)))
    assert s1.actor_states == (1,) and s1.is_timer_set == (True,)
    s2 = m.next_state(s1, Timeout(Id(0)))
    s3 = m.next_state(s2, Timeout(Id(0)))
    # final timeout: no-op handler, but the timer flag must still clear
    assert s3.is_timer_set == (False,)
    assert not m.actions(s3)


# ---------------------------------------------------------------------------
# heterogeneous composition (reference needs Choice, ``model.rs:862-977``)
# ---------------------------------------------------------------------------


def test_heterogeneous_actor_system():
    class A(Actor):
        def on_start(self, id, out):
            out.send(Id(1), ("hello", int(id)))
            return "a"

    class B(Actor):
        def on_start(self, id, out):
            return 0

        def on_msg(self, id, state, src, msg, out):
            out.send(Id(2), ("fwd", msg))
            return state + 1

    class C(Actor):
        def on_start(self, id, out):
            return ()

        def on_msg(self, id, state, src, msg, out):
            return state + (msg,)

    m = (
        ActorModel(None, None)
        .actor(A())
        .actor(B())
        .actor(C())
        .init_network_(Network.new_unordered_nonduplicating())
        .property(
            Expectation.SOMETIMES,
            "c got it",
            lambda model, s: len(s.actor_states[2]) > 0,
        )
    )
    checker = m.checker().spawn_bfs().join()
    path = checker.assert_any_discovery("c got it")
    assert path.final_state().actor_states[2] == (("fwd", ("hello", 0)),)


def test_helpers():
    assert majority(3) == 2 and majority(4) == 3 and majority(5) == 3
    assert model_peers(1, 3) == [Id(0), Id(2)]
    assert Id.from_addr("127.0.0.1", 3000).to_addr() == ("127.0.0.1", 3000)


def test_ping_pong_dfs_agrees_with_bfs():
    model = ping_pong_model(PingPongCfg(maintains_history=False, max_nat=5))
    model.lossy = True
    dfs = model.checker().spawn_dfs().join()
    assert dfs.unique_state_count() == 4094
