"""VectorClock semantics parity (reference ``src/util/vector_clock.rs:110-273``)
plus a model-checked caller (``vector_clock_model`` in quickstart).

The load-bearing property throughout is zero-suffix insensitivity: clocks
over different actor counts must equate/hash/order as if padded with zeros
(reference ``vector_clock.rs:54-106``).
"""

import pytest

from stateright_tpu.fingerprint import fingerprint
from stateright_tpu.utils.vector_clock import VectorClock


def test_can_equate():
    # vector_clock.rs:128-145
    assert VectorClock() == VectorClock()
    assert VectorClock([0]) == VectorClock([])
    assert VectorClock([]) == VectorClock([0])
    assert VectorClock([]) != VectorClock([1])
    assert VectorClock([1]) != VectorClock([])


def test_can_hash():
    # vector_clock.rs:148-187: equal ⇒ equal hash (incl. zero suffixes);
    # fingerprints must agree too — clocks live inside model state.
    assert hash(VectorClock()) == hash(VectorClock())
    assert hash(VectorClock([])) == hash(VectorClock([0, 0]))
    assert hash(VectorClock([1])) == hash(VectorClock([1, 0]))
    assert fingerprint(VectorClock([1])) == fingerprint(VectorClock([1, 0]))
    assert hash(VectorClock([])) != hash(VectorClock([1]))
    assert fingerprint(VectorClock([])) != fingerprint(VectorClock([1]))


def test_can_increment():
    # vector_clock.rs:191-199
    assert VectorClock().incremented(2) == VectorClock([0, 0, 1])
    assert (
        VectorClock().incremented(2).incremented(0).incremented(2)
        == VectorClock([1, 0, 2])
    )


def test_can_merge():
    # vector_clock.rs:201-212
    assert VectorClock([1, 2, 3, 4]).merge_max(
        VectorClock([5, 6, 0])
    ) == VectorClock([5, 6, 3, 4])
    assert VectorClock([1, 0, 2]).merge_max(
        VectorClock([3, 1, 0, 4])
    ) == VectorClock([3, 1, 2, 4])


@pytest.mark.parametrize(
    "a, b, expected",
    [
        # equal (missing elements implicitly zero) — vector_clock.rs:217-230
        ([], [], 0),
        ([], [0, 0], 0),
        ([0, 0], [], 0),
        ([1, 2, 0], [1, 2], 0),
        # less — vector_clock.rs:232-245
        ([], [1], -1),
        ([1, 2, 3], [1, 3, 4], -1),
        ([1, 2, 3], [1, 3, 3], -1),
        ([1, 2, 3], [2, 3, 3], -1),
        # greater — vector_clock.rs:247-260
        ([1], [], 1),
        ([1, 2, 3], [1, 1, 2], 1),
        ([1, 2, 3], [1, 1, 3], 1),
        ([1, 2, 4], [0, 1, 3], 1),
        # incomparable — vector_clock.rs:262-271
        ([1, 2, 3], [1, 3, 2], None),
        ([1, 2, 3], [3, 2, 1], None),
        ([1, 2, 2], [2, 1, 2], None),
    ],
)
def test_can_order_partially(a, b, expected):
    assert VectorClock(a).partial_cmp(VectorClock(b)) == expected


def test_model_checker_detects_concurrency():
    """The quickstart vector-clock system: two causally independent events
    reach the observer; the checker discovers the concurrency witness."""
    from stateright_tpu.models.quickstart import vector_clock_model

    checker = vector_clock_model().checker().spawn_bfs().join()
    checker.assert_any_discovery("concurrency detected")
    final = checker.discovery("concurrency detected").final_state()
    assert final.actor_states[2].saw_concurrent
    # both sender events are merged into the observer's clock
    obs = final.actor_states[2].clock
    assert obs.get(0) == 1 and obs.get(1) == 1
