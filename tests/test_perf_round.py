"""Wavefront throughput round (docs/perf.md): prewarm, persistent compile
cache, candidate pre-dedup, per-stage attribution, and the compiled-CPU
baseline.

The contracts pinned here:

 - pre-dedup ON is bit-identical to OFF (counts, discovery traces, and the
   visited table itself), and OFF leaves the step jaxpr unchanged;
 - a growth boundary consumes a prewarmed executable (compile events say
   ``source="prewarm"``; the engine build ran on the prewarm thread), and a
   READY rung swaps in without blocking (slow-compile stub, component
   level);
 - a second fresh-model run with the persistent cache dir set performs
   zero fresh engine compiles (every compile event is a persistent hit);
 - the flight recorder's per-stage breakdown is present, non-negative, and
   bounded by wall time;
 - the native compiled-CPU BFS reproduces the engines' pinned counts.
"""

import threading
import time

import numpy as np
import pytest

import jax

from helpers import requires_sharded_collectives

from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.parallel.prewarm import (
    PREWARM_THREAD_NAME,
    EnginePrewarmer,
    disable_persistent_compile_cache,
)

TPC3_UNIQUE = 288


def _spawn(model, **kw):
    kw.setdefault("sync", True)
    kw.setdefault("capacity", 1 << 12)
    kw.setdefault("batch", 64)
    return kw


# -- pre-dedup equivalence ----------------------------------------------------


def test_prededup_is_bit_identical_on_2pc3():
    """Fleet-parity contract, strongest form: with capacities pre-sized (no
    growth events to reorder slots), the visited TABLE — every slot's
    fingerprint and parent payload — must be bit-identical with the flag
    on and off, along with every count and discovery."""
    a = TwoPhaseSys(3).checker().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    b = TwoPhaseSys(3).checker().prededup().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    assert a.unique_state_count() == b.unique_state_count() == TPC3_UNIQUE
    assert a.state_count() == b.state_count()
    assert a.max_depth() == b.max_depth()
    ta, tb = a._table_np(), b._table_np()
    assert np.array_equal(ta[0], tb[0])
    assert np.array_equal(ta[1], tb[1])
    da, db = a.discoveries(), b.discoveries()
    assert sorted(da) == sorted(db)
    for name in da:
        assert [str(s) for s in da[name].states()] == [
            str(s) for s in db[name].states()
        ]


@pytest.mark.slow
def test_prededup_parity_under_growth_and_symmetry():
    """Counts/discoveries stay identical when growth events DO interleave
    (slot layouts may differ after rehash — the set contract, not the
    layout contract) and under symmetry reduction (generation-order
    compaction path)."""
    a = TwoPhaseSys(4).checker().spawn_tpu(
        sync=True, capacity=1 << 8, batch=32, cand=128,
        queue_capacity=1 << 12,
    )
    b = TwoPhaseSys(4).checker().prededup().spawn_tpu(
        sync=True, capacity=1 << 8, batch=32, cand=128,
        queue_capacity=1 << 12,
    )
    assert a.unique_state_count() == b.unique_state_count()
    assert a.state_count() == b.state_count()
    assert sorted(a.discoveries()) == sorted(b.discoveries())
    sa = TwoPhaseSys(3).checker().symmetry().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    sb = TwoPhaseSys(3).checker().symmetry().prededup().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    assert sa.unique_state_count() == sb.unique_state_count()
    assert sa.state_count() == sb.state_count()
    ta, tb = sa._table_np(), sb._table_np()
    assert np.array_equal(ta[0], tb[0])  # no growth: bit-identical again
    assert np.array_equal(ta[1], tb[1])


@pytest.mark.slow
@requires_sharded_collectives
def test_prededup_parity_on_sharded_engine():
    a = TwoPhaseSys(3).checker().spawn_tpu(
        sync=True, devices=2, capacity=1 << 12, frontier_capacity=1 << 9
    )
    b = TwoPhaseSys(3).checker().prededup().spawn_tpu(
        sync=True, devices=2, capacity=1 << 12, frontier_capacity=1 << 9
    )
    assert a.unique_state_count() == b.unique_state_count() == TPC3_UNIQUE
    assert a.state_count() == b.state_count()
    assert sorted(a.discoveries()) == sorted(b.discoveries())


def test_prededup_off_leaves_run_jaxpr_bit_identical():
    """Same contract as telemetry/checked: the flag OFF must be the
    pre-flag engine program, and ON must actually add the filter."""

    def run_jaxpr(flag):
        m = TwoPhaseSys(3)
        b = m.checker()
        if flag is not None:
            b = b.prededup(flag)
        c = b.spawn_tpu(sync=True, capacity=1 << 12, batch=64)
        init_fn, run_fn = c._engine(c._cap, c._qcap, c._batch, c._cand)
        carry, _ = init_fn()
        return str(jax.make_jaxpr(lambda cr: run_fn(cr))(tuple(carry)))

    baseline = run_jaxpr(None)
    assert baseline == run_jaxpr(False)
    assert baseline != run_jaxpr(True)  # the filter is really there


# -- prewarm (component level) ------------------------------------------------


def test_prewarmer_ready_rung_swaps_in_without_blocking():
    """The growth-stall elision itself, with an artificially slow compile:
    once the background build finished, consuming it costs ~nothing and
    no compile ever ran on the caller's thread."""
    threads = []

    def build():
        threads.append(threading.current_thread().name)
        time.sleep(0.3)  # artificially slow compile
        return "engine"

    p = EnginePrewarmer()
    try:
        assert p.schedule("k", build)
        assert not p.schedule("k", build)  # idempotent per key
        deadline = time.monotonic() + 20
        while not p.ready("k"):
            assert time.monotonic() < deadline, "background compile hung"
            time.sleep(0.01)
        t0 = time.monotonic()
        result, waited, was_ready, job = p.take("k")
        assert time.monotonic() - t0 < 0.1  # no blocking on a ready rung
        assert result == "engine" and was_ready and waited < 0.1
        assert threads == [PREWARM_THREAD_NAME]
        assert p.take("k") is None  # consumed
    finally:
        p.close()


def test_prewarmer_waits_out_in_flight_and_cancels_queued():
    started = threading.Event()

    def slow():
        started.set()
        time.sleep(0.4)
        return "slow"

    def never():
        return "never"

    p = EnginePrewarmer()
    try:
        p.schedule("a", slow)
        assert started.wait(10)
        p.schedule("b", never)
        # b is queued behind the in-flight a: taking it CANCELS it (the
        # caller cold-builds inline instead of waiting behind a)
        assert p.take("b") is None
        assert not p.scheduled("b")
        # a is in flight: take waits it out (the compile started earlier)
        result, waited, was_ready, _ = p.take("a")
        assert result == "slow" and not was_ready
    finally:
        p.close()


def test_prewarmer_close_drops_queue_and_surfaces_errors():
    def boom():
        raise ValueError("bad build")

    p = EnginePrewarmer()
    p.schedule("e", boom)
    deadline = time.monotonic() + 20
    while p.scheduled("e") and not p.ready("e"):
        assert time.monotonic() < deadline
        time.sleep(0.01)
    with pytest.raises(ValueError, match="bad build"):
        p.take("e")
    blocker = threading.Event()
    p.schedule("x", lambda: blocker.wait(2))
    p.schedule("y", lambda: "y")
    p.close()
    assert not p.schedule("z", lambda: "z")  # closed
    blocker.set()
    assert p.wait_idle(20)


# -- prewarm (growth-boundary integration) ------------------------------------


def test_growth_boundary_consumes_prewarmed_engine(monkeypatch):
    """A growth boundary swaps in the background-compiled rung: the
    boundary's compile event says ``source="prewarm"`` (cache_hit=True),
    and the rung's engine build demonstrably ran on the prewarm thread,
    not the run loop's."""
    import stateright_tpu.parallel.wavefront as wf

    builds = []
    orig = wf._build_engine

    def spy(*args, **kw):
        builds.append((threading.current_thread().name, args[2]))  # cap
        return orig(*args, **kw)

    monkeypatch.setattr(wf, "_build_engine", spy)
    m = TwoPhaseSys(3)
    # batch 8 x arity 17 = 136-lane windows: the candidate budget clamps to
    # full width (no cand rung to predict), so the table doubling is the
    # FIRST scheduled prewarm job; 1024 slots force exactly that doubling
    # at ~256 unique (288 total).  steps_per_call=1 keeps syncs frequent:
    # the 1/16-load prewarm threshold (64 unique) fires at least one full
    # sync before the 1/4-load growth trigger (257) can, so the
    # background compile has demonstrably STARTED when the boundary takes
    # it (in-flight waits still count as prewarm consumption — the
    # compile began earlier than a cold build would have).
    c = (
        m.checker().prewarm().telemetry()
        .spawn_tpu(sync=True, capacity=1 << 10, batch=8,
                   steps_per_call=1, queue_capacity=1 << 12)
    )
    assert c.unique_state_count() == TPC3_UNIQUE
    assert c.growth_events, "capacity must have forced a growth event"
    compiles = c.flight_recorder.records("compile")
    assert compiles[0]["rung"] == "init"
    rungs = [e for e in compiles if e["rung"] != "init"]
    assert rungs, "growth must have acquired at least one new engine"
    assert all(
        e["source"] == "prewarm" and e["cache_hit"] for e in rungs
    ), rungs
    counters = c.flight_recorder.counters()
    assert counters.get("prewarm_consumed", 0) >= len(rungs)
    # the consumed rungs' builds happened on the background thread
    prewarm_built_caps = {
        cap for name, cap in builds if name == PREWARM_THREAD_NAME
    }
    for e in rungs:
        assert e["cap"] in prewarm_built_caps, (e, builds)


# -- persistent compile cache -------------------------------------------------


def test_persistent_cache_round_trip_zero_fresh_compiles(tmp_path):
    """Second run, FRESH model instance (so the in-memory engine caches
    cannot serve), same cache dir: every engine compile must be a
    persistent-cache hit — zero fresh engine compiles — and the counts
    must stay exact.

    The capacities force a growth rung so cache-SERVED executables drive
    real work: this is the regression pin for the donation/deserialization
    bug (docs/perf.md) where cache-retrieved CPU executables read
    donation-deleted buffers and returned garbage counters on every
    second run."""
    d = str(tmp_path / "compile-cache")
    caps = dict(sync=True, capacity=1 << 10, batch=8,
                queue_capacity=1 << 12)
    try:
        c1 = TwoPhaseSys(3).checker().compile_cache(d).telemetry().spawn_tpu(
            **caps
        )
        assert c1.unique_state_count() == TPC3_UNIQUE
        assert c1.growth_events, "capacities must force a growth rung"
        ev1 = c1.flight_recorder.records("compile")
        assert ev1 and all(e["source"] == "fresh" for e in ev1)

        c2 = TwoPhaseSys(3).checker().compile_cache(d).telemetry().spawn_tpu(
            **caps
        )
        assert c2.unique_state_count() == TPC3_UNIQUE
        assert c2.state_count() == c1.state_count()
        ev2 = c2.flight_recorder.records("compile")
        assert len(ev2) >= 2, "init + growth rung must both re-acquire"
        assert all(
            e["cache_hit"] and e["source"] == "persistent" for e in ev2
        ), ev2
    finally:
        disable_persistent_compile_cache()


# -- per-stage attribution ----------------------------------------------------


def test_stage_breakdown_present_and_sane():
    c = TwoPhaseSys(3).checker().telemetry().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    stages = c.flight_recorder.stages()
    assert stages is not None
    for key in ("compile_secs", "device_secs", "wall_secs", "host_secs"):
        assert key in stages and stages[key] >= 0.0, stages
    named = sum(
        v for k, v in stages.items()
        if k.endswith("_secs") and k not in ("wall_secs", "host_secs")
    )
    assert named <= stages["wall_secs"] + 0.05, stages
    summary = c.flight_recorder.summary()
    assert summary["stages"] == stages
    # and the breakdown survives a JSONL round-trip (counters ride the
    # header)
    import tempfile

    from stateright_tpu.telemetry import FlightRecorder

    with tempfile.TemporaryDirectory() as td:
        path = f"{td}/t.jsonl"
        c.flight_recorder.to_jsonl(path)
        back = FlightRecorder.from_jsonl(path)
        rt = back.stages()
        assert rt is not None
        assert rt["compile_secs"] == stages["compile_secs"]
        assert rt["device_secs"] == stages["device_secs"]


def test_stage_counters_absent_without_engine_runs():
    from stateright_tpu.telemetry import FlightRecorder

    rec = FlightRecorder()
    assert rec.stages() is None
    assert "stages" not in rec.summary()


# -- native compiled-CPU baseline ---------------------------------------------


def _native_bfs_available():
    from stateright_tpu.native import load

    mod = load()
    return mod is not None and hasattr(mod, "bfs_run")


@pytest.mark.skipif(
    not _native_bfs_available(),
    reason="native module unavailable (no compiler?)",
)
def test_native_baseline_matches_engine_counts():
    from stateright_tpu.native.baseline import compiled_cpu_bfs

    r = compiled_cpu_bfs(TwoPhaseSys(3), batch=256)
    assert r is not None
    engine = TwoPhaseSys(3).checker().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    assert r["unique"] == engine.unique_state_count() == TPC3_UNIQUE
    assert r["states"] == engine.state_count()
    assert r["states_per_sec"] > 0


@pytest.mark.skipif(
    not _native_bfs_available(),
    reason="native module unavailable (no compiler?)",
)
@pytest.mark.medium
def test_native_baseline_pinned_2pc5_and_target():
    from stateright_tpu.native.baseline import compiled_cpu_bfs

    r = compiled_cpu_bfs(TwoPhaseSys(5))
    assert r["unique"] == 8832  # examples/2pc.rs:133
    t = compiled_cpu_bfs(TwoPhaseSys(5), target=2000)
    assert 2000 <= t["unique"] < 8832  # clean-boundary stop

    class NoTwin:
        pass

    assert compiled_cpu_bfs(NoTwin()) is None
