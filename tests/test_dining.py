"""Dining philosophers — deadlock detection via the general fragment.

Pins: the deadlock is discovered as an ``eventually`` counterexample
whose trace ends in the circular wait (all philosophers holding their
left fork); host and device enumerate the same full space when no
early-exit applies; and the early-exit semantics itself (the reference's
all-properties-discovered stop, ``bfs.rs:121-128``) kicks in on both.
"""

import pytest

from stateright_tpu.actor.device_props import forall_actors
from stateright_tpu.core import Expectation
from stateright_tpu.models.dining import HAS_LEFT, dining_model

DINING3_FULL = 359  # 3 philosophers + 3 forks, full space


def _no_early_exit(m):
    """An always-true ALWAYS property is never discovered, so the
    all-properties-discovered early exit can't fire and both sides must
    enumerate the full space."""
    m.property(
        Expectation.ALWAYS, "no early exit", forall_actors(lambda i, s: True)
    )
    return m


def test_dining3_full_space_parity():
    m = _no_early_exit(dining_model(3))
    h = m.checker().spawn_bfs().join()
    c = m.checker().spawn_tpu(sync=True, capacity=1 << 14)
    assert h.unique_state_count() == c.unique_state_count() == DINING3_FULL
    assert sorted(h.discoveries()) == sorted(c.discoveries()) == [
        "everyone eats",
        "someone eats",
    ]


def test_dining3_deadlock_trace():
    """The eventually-counterexample ends in the classic circular wait:
    every philosopher holds exactly their left fork."""
    m = dining_model(3)
    h = m.checker().spawn_bfs().join()
    trace = h.discoveries()["everyone eats"]
    h.assert_discovery("everyone eats", list(trace.actions()))
    final = h.discoveries()["everyone eats"].final_state()
    phils = final.actor_states[:3]
    forks = final.actor_states[3:]
    assert all(p.phase == HAS_LEFT for p in phils)
    assert all(f.holder != -1 and f.pending for f in forks)
    # terminal: nothing in flight, nothing deliverable
    assert m.next_steps(final) == []


def test_dining3_device_finds_deadlock():
    m = dining_model(3)
    c = m.checker().spawn_tpu(sync=True, capacity=1 << 14)
    assert "everyone eats" in c.discoveries()  # the deadlock counterexample
    assert "someone eats" in c.discoveries()  # and a successful dinner
    final = c.discoveries()["everyone eats"].final_state()
    assert all(p.phase == HAS_LEFT for p in final.actor_states[:3])


# re-tiered fast->slow (PR 2): the fast tier blew the 870s tier-1 budget
@pytest.mark.slow
def test_dining4_scales():
    m = _no_early_exit(dining_model(4))
    h = m.checker().spawn_bfs().join()
    c = m.checker().spawn_tpu(sync=True, capacity=1 << 15)
    assert h.unique_state_count() == c.unique_state_count() > DINING3_FULL
