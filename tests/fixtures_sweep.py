"""Parametric model family for sweep tests (docs/sweep.md).

``BoundedCounterSys(bound, counters)`` is a deliberately simple family
whose *bound* parameter is twin DATA: every instance shares one row
layout and one step-kernel structure, but the bound appears in the
traced jaxpr (as a literal/constant), so a family of instances
exercises the cohort unifier's constant lifting — one compiled program,
genuinely different per-instance state spaces (the space is
``(bound+1)^counters``).
"""

from __future__ import annotations

import numpy as np

from stateright_tpu.core import Expectation, Model
from stateright_tpu.parallel.tensor_model import (
    BitPacker,
    TensorBackedModel,
    TensorModel,
)

_BITS = 6  # fixed field width: bounds up to 63 share one layout


class BoundedCounterTensor(TensorModel):
    def __init__(self, model):
        self.model = model
        self.n = model.n
        self.bound = model.bound
        self.pk = BitPacker([(f"c{i}", _BITS) for i in range(self.n)])
        self.width = self.pk.width
        self.max_actions = self.n

    def init_rows(self) -> np.ndarray:
        return np.asarray(
            [self.encode_state(s) for s in self.model.init_states()],
            np.uint64,
        )

    def encode_state(self, state) -> tuple:
        return self.pk.pack(**{f"c{i}": v for i, v in enumerate(state)})

    def decode_state(self, row):
        d = self.pk.unpack(row)
        return tuple(d[f"c{i}"] for i in range(self.n))

    def step_rows(self, rows):
        import jax.numpy as jnp

        b = rows.shape[0]
        base = jnp.broadcast_to(
            rows[:, None, :], (b, self.n, self.width)
        )
        succ = base
        valid_cols = []
        for i in range(self.n):
            v = self.pk.get(rows, f"c{i}")
            # the BOUND is per-instance twin data: it lands in the
            # traced jaxpr as a literal the cohort unifier lifts
            ok = v < jnp.uint64(self.bound)
            nv = jnp.where(ok, v + jnp.uint64(1), v)
            col = self.pk.set(base[:, i, :], f"c{i}", nv)
            succ = succ.at[:, i, :].set(col)
            valid_cols.append(ok[:, None])
        return succ, jnp.concatenate(valid_cols, axis=1)

    def property_masks(self, rows):
        import jax.numpy as jnp

        vals = jnp.stack(
            [self.pk.get(rows, f"c{i}") for i in range(self.n)], axis=-1
        )
        maxed = jnp.any(vals >= jnp.uint64(self.bound), axis=-1)
        over = jnp.any(vals > jnp.uint64((1 << _BITS) - 1), axis=-1)
        return jnp.stack([~over, maxed], axis=-1)


class BoundedCounterSys(TensorBackedModel, Model):
    """``counters`` independent counters, each incrementable to
    ``bound``; "in range" always holds, "some counter maxed" is a
    sometimes-example found at depth ``bound``."""

    def __init__(self, bound: int, counters: int = 2):
        if not 1 <= bound <= (1 << _BITS) - 1:
            raise ValueError(f"bound must be in 1..{(1 << _BITS) - 1}")
        self.bound = int(bound)
        self.n = int(counters)

    def properties(self):
        from stateright_tpu.core import Property

        return [
            Property(
                Expectation.ALWAYS, "in range",
                lambda m, s: all(v <= m.bound for v in s),
            ),
            Property(
                Expectation.SOMETIMES, "some counter maxed",
                lambda m, s: any(v >= m.bound for v in s),
            ),
        ]

    def init_states(self):
        return [tuple(0 for _ in range(self.n))]

    def actions(self, state):
        return [i for i in range(self.n) if state[i] < self.bound]

    def next_state(self, state, action):
        out = list(state)
        out[action] += 1
        return tuple(out)

    def tensor_model(self):
        return BoundedCounterTensor(self)


def bounded_counter_spec(bounds, counters: int = 2, seeds=None):
    from stateright_tpu.sweep import SweepInstance, SweepSpec

    return SweepSpec([
        SweepInstance(
            f"bc-b{b}",
            BoundedCounterSys(b, counters),
            params={"bound": int(b), "counters": int(counters)},
            seed=(seeds[i] if seeds is not None else 0),
        )
        for i, b in enumerate(bounds)
    ])
