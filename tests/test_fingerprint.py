"""Stable-hash behavior: cross-run stability, order-insensitivity for
sets/maps, type distinction (reference stability contract ``lib.rs:330-344``)."""

from dataclasses import dataclass

from stateright_tpu.fingerprint import (
    FINGERPRINT_SEED,
    hash_words,
    mix64,
    stable_hash,
)


def test_mix64_known_values():
    # pinned so any accidental change to the mixer (which would invalidate
    # every stored fingerprint) fails loudly
    assert mix64(0) == 0
    assert mix64(1) == 0x5692161D100B05E5 == stable_mix_1()


def stable_mix_1():
    h = 1
    h ^= h >> 30
    h = (h * 0xBF58476D1CE4E5B9) % (1 << 64)
    h ^= h >> 27
    h = (h * 0x94D049BB133111EB) % (1 << 64)
    h ^= h >> 31
    return h


def test_hash_words_nonzero_and_length_sensitive():
    assert hash_words([]) != 0
    assert hash_words([0]) != hash_words([0, 0])
    assert hash_words([1, 2]) != hash_words([2, 1])


def test_scalars_distinct():
    vals = [None, True, False, 0, 1, -1, 0.0, 1.0, "", "a", b"a", (), (0,), [0]]
    hashes = [stable_hash(v) for v in vals]
    assert len(set(hashes)) == len(hashes)


def test_int_vs_str_vs_float_distinct():
    assert stable_hash(1) != stable_hash("1")
    assert stable_hash(1) != stable_hash(1.0)
    assert stable_hash((1, 2)) != stable_hash([1, 2])


def test_set_order_insensitive():
    assert stable_hash({1, 2, 3}) == stable_hash({3, 1, 2})
    assert stable_hash(frozenset(["a", "b"])) == stable_hash({"b", "a"})
    assert stable_hash({1: "x", 2: "y"}) == stable_hash({2: "y", 1: "x"})


def test_dict_key_value_pairing():
    assert stable_hash({1: 2, 3: 4}) != stable_hash({1: 4, 3: 2})


def test_dataclass_hash():
    @dataclass
    class P:
        x: int
        y: int

    assert stable_hash(P(1, 2)) == stable_hash(P(1, 2))
    assert stable_hash(P(1, 2)) != stable_hash(P(2, 1))


def test_bigint():
    big = 1 << 200
    assert stable_hash(big) == stable_hash(1 << 200)
    assert stable_hash(big) != stable_hash(-big)


def test_cross_process_stability():
    # values pinned once; if these move, Explorer URLs and stored traces break
    assert FINGERPRINT_SEED == 0x5374617465544655
    assert stable_hash((0, 0)) == stable_hash((0, 0))


def test_negative_int_does_not_collide_with_wrapped_unsigned():
    assert stable_hash(-1) != stable_hash((1 << 64) - 1)
    assert stable_hash(-5) != stable_hash(5)
