"""Flight recorder (stateright_tpu/telemetry/) — record schema, ring
bounding, JSONL/Chrome-trace round-trip, engine wiring on every strategy,
the Explorer's ``/.metrics`` endpoint, and the overhead contract:
telemetry disabled adds ZERO ops to the step jaxpr, telemetry enabled
costs <3% wall time on the 2PC-7 wavefront run (slow tier).

The 2PC-7 occupancy time series is pinned here too: it captures the
visited-table anomaly signature VERDICT.md has carried open for two
rounds — growth events firing on single-bucket overflow (``full_buckets
>= 1``) while the Poisson model at the observed load expects essentially
none.
"""

import json
import os
import urllib.error
import urllib.request

import pytest

import jax

from stateright_tpu.telemetry import FlightRecorder, STATUS_NAMES
from stateright_tpu.telemetry.export import from_chrome_trace
from stateright_tpu.models.two_phase_commit import TwoPhaseSys

TPC7_UNIQUE = 296_448  # full 2pc-7 space (device run below enumerates it)


# -- recorder core -----------------------------------------------------------


def test_step_record_shape():
    rec = FlightRecorder(meta={"engine": "wavefront", "model": "M"})
    r1 = rec.step(engine="wavefront", states=100, unique=80,
                  load_factor=0.01)
    r2 = rec.step(engine="wavefront", states=300, unique=180)
    assert r1["kind"] == r2["kind"] == "step"
    assert r1["seq"] == 1 and r2["seq"] == 2
    assert r2["t"] >= r1["t"] >= 0
    # first record deltas from zero; second from the first
    assert (r1["d_states"], r1["d_unique"]) == (100, 80)
    assert (r2["d_states"], r2["d_unique"]) == (200, 100)
    assert r2["dedup"] == 0.5  # half the generated states were revisits
    assert r1["load_factor"] == 0.01  # engine extras pass through
    assert r2["dt"] >= 0


def test_ring_bounding_keeps_totals():
    rec = FlightRecorder(capacity=8)
    for i in range(50):
        rec.step(engine="bfs", states=(i + 1) * 10, unique=(i + 1) * 5)
    rec.record("growth", status="table_full", unique=100)
    assert len(rec) == 8
    assert rec.dropped == 51 - 8
    s = rec.summary()
    # the ring is a window; the totals are not windowed
    assert s["steps"] == 50
    assert s["states"] == 500 and s["unique"] == 250
    assert s["growth_events"] == 1
    assert s["ring_len"] == 8 and s["dropped"] == 43


def test_counters_and_status_names():
    rec = FlightRecorder()
    rec.add_bytes(d2h=100, h2d=7)
    rec.add_bytes(d2h=100)
    assert rec.counters()["d2h_bytes"] == 200
    assert rec.counters()["h2d_bytes"] == 7
    assert "table_full" in STATUS_NAMES and "frontier_full" in STATUS_NAMES


def test_jsonl_round_trip(tmp_path):
    rec = FlightRecorder(capacity=32, meta={"engine": "wavefront",
                                            "model": "X"})
    for i in range(5):
        rec.step(engine="wavefront", states=(i + 1) * 100,
                 unique=(i + 1) * 60, load_factor=0.01 * (i + 1))
    rec.record("growth", status="queue_full", unique=300, cap=1024)
    rec.record("occupancy", at="final", occupied=300, load_factor=0.07,
               max_bucket=5, full_buckets=0, poisson_full_expect=0.0,
               nbuckets=64, histogram=[0] * 17)
    rec.add_bytes(d2h=1234, h2d=99)
    path = tmp_path / "t.jsonl"
    rec.to_jsonl(path)
    back = FlightRecorder.from_jsonl(path)
    assert back.records() == rec.records()
    assert back.summary() == rec.summary()
    # header line first, then one line per record
    lines = path.read_text().strip().splitlines()
    assert json.loads(lines[0])["kind"] == "header"
    assert len(lines) == 1 + len(rec.records())


def test_jsonl_round_trip_after_ring_eviction(tmp_path):
    """Eviction loses ring entries but never totals: the export header
    carries the summary, and from_jsonl reconciles seq/kind counts and the
    cumulative step snapshot from it."""
    rec = FlightRecorder(capacity=8)
    for i in range(50):
        rec.step(engine="bfs", states=(i + 1) * 10, unique=(i + 1) * 5)
    rec.record("growth", status="table_full", unique=250)
    path = tmp_path / "evicted.jsonl"
    rec.to_jsonl(path)
    back = FlightRecorder.from_jsonl(path)
    assert back.records() == rec.records()
    assert back.summary() == rec.summary()
    assert back.summary()["steps"] == 50
    assert back.dropped == rec.dropped == 43


def test_step_clamps_stale_concurrent_snapshots():
    """Pool workers read counters then record without a shared lock: a
    late writer with a stale (smaller) snapshot must not produce negative
    deltas or an under-reporting final summary."""
    rec = FlightRecorder()
    rec.step(engine="bfs", states=150, unique=90)
    late = rec.step(engine="bfs", states=100, unique=50)  # stale reader
    assert late["d_states"] == 0 and late["d_unique"] == 0
    assert late["states"] == 150 and late["unique"] == 90
    assert rec.summary()["states"] == 150


def test_jsonl_multi_run_append_keeps_per_run_series(tmp_path):
    """Appended exports (one per profiled config) replay with a fresh
    delta baseline per run: run 2's cumulative counters restart from zero
    and must not be clamped against run 1's totals."""
    r1 = FlightRecorder(meta={"label": "run1"})
    r1.step(engine="wavefront", states=1000, unique=700)
    r2 = FlightRecorder(meta={"label": "run2"})
    r2.step(engine="wavefront", states=50, unique=40)
    path = tmp_path / "multi.jsonl"
    r1.to_jsonl(path)
    r2.to_jsonl(path, append=True)
    back = FlightRecorder.from_jsonl(path)
    steps = back.records("step")
    assert [s["states"] for s in steps] == [1000, 50]
    assert [s["unique"] for s in steps] == [700, 40]
    assert steps[1]["d_states"] == 50  # fresh baseline, not 50-1000 clamped


def test_summary_wall_clock_includes_pre_first_step_work():
    """states_per_sec's denominator runs from recorder creation: the init
    and first compiled block's states must pay their elapsed time (a
    first-step-only run must not report near-infinite throughput)."""
    import time

    rec = FlightRecorder()
    time.sleep(0.05)
    rec.step(engine="wavefront", states=1000, unique=800)
    s = rec.summary()
    assert s["wall_secs"] >= 0.05
    assert s["states_per_sec"] <= 1000 / 0.05


def test_chrome_trace_round_trip(tmp_path):
    rec = FlightRecorder(meta={"engine": "mp", "model": "X"})
    rec.step(engine="mp", states=10, unique=8)
    rec.step(engine="mp", states=30, unique=20, load_factor=0.5)
    rec.record("growth", status="table_full", unique=20)
    path = tmp_path / "trace.json"
    rec.to_chrome_trace(path)
    back = from_chrome_trace(path)
    complete = [e for e in back["events"] if e["ph"] == "X"]
    instants = [e for e in back["events"] if e["ph"] == "i"]
    counters = [e for e in back["events"] if e["ph"] == "C"]
    assert len(complete) == 2 and len(instants) == 1
    assert counters, "step records emit a throughput counter track"
    assert complete[0]["args"]["states"] == 10
    assert back["summary"]["states"] == 30
    assert all(e["ts"] >= 0 for e in back["events"])


# -- engine wiring -----------------------------------------------------------


def test_disabled_by_default_no_recorder():
    c = TwoPhaseSys(3).checker().spawn_bfs().join()
    assert c.flight_recorder is None
    c2 = TwoPhaseSys(3).checker().spawn_tpu(sync=True, capacity=1 << 12,
                                            batch=64)
    assert c2.flight_recorder is None


def test_host_bfs_dfs_records():
    c = TwoPhaseSys(3).checker().telemetry().spawn_bfs().join()
    steps = c.flight_recorder.records("step")
    assert steps and all(r["engine"] == "bfs" for r in steps)
    assert c.flight_recorder.summary()["unique"] == 288
    d = TwoPhaseSys(3).checker().telemetry().spawn_dfs().join()
    assert d.flight_recorder.records("step")
    assert d.flight_recorder.summary()["unique"] == 288


def test_mp_round_records():
    c = (
        TwoPhaseSys(3).checker().telemetry().spawn_mp_bfs(processes=2)
        .join()
    )
    steps = c.flight_recorder.records("step")
    # one record per bulk-synchronous round, replayed from worker 0's log
    assert steps and all(r["engine"] == "mp" for r in steps)
    assert [r["round"] for r in steps] == list(range(len(steps)))
    assert steps[-1]["unique"] == 288


def test_wavefront_step_records_and_counts():
    c = (
        TwoPhaseSys(3).checker().telemetry(occupancy_every=2)
        .spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    )
    rec = c.flight_recorder
    steps = rec.records("step")
    assert steps and all(r["engine"] == "wavefront" for r in steps)
    s = rec.summary()
    assert s["states"] == c.state_count()
    assert s["unique"] == c.unique_state_count() == 288
    assert s["compile_cache_misses"] >= 1
    assert s["d2h_bytes"] > 0
    # per-sync load factor is the unique/cap series
    assert all(0 <= r["load_factor"] <= 1 for r in steps)
    assert rec.records("occupancy"), "occupancy_every samples the table"


def test_wavefront_growth_records_with_occupancy():
    """Growth boundaries record a named event plus a free occupancy sample
    (the carry is host-side there anyway)."""
    c = (
        TwoPhaseSys(5).checker().telemetry()
        .spawn_tpu(sync=True, capacity=1 << 10, batch=64)
    )
    rec = c.flight_recorder
    growth = rec.records("growth")
    assert growth, "tiny capacity must force growth"
    assert {g["status"] for g in growth} <= STATUS_NAMES
    occ = rec.records("occupancy")
    assert occ and all(o["at"] == "growth" for o in occ)
    # occupancy is sampled at each growth boundary in event order
    occupied = [o["occupied"] for o in occ]
    assert occupied == sorted(occupied)
    assert rec.summary()["growth_events"] == len(growth) == len(
        c.growth_events
    )
    assert c.unique_state_count() == 8832  # growth preserved the work


@pytest.mark.medium
def test_profiler_scoped_trace(tmp_path):
    logdir = tmp_path / "prof"
    c = (
        TwoPhaseSys(3).checker()
        .telemetry(profile_steps=1, profile_dir=str(logdir))
        .spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    )
    events = c.flight_recorder.records("profile")
    assert events, "profiler must record start/stop or unavailability"
    kinds = {e["event"] for e in events}
    if "start" in kinds:  # profiler backend present: scoped start/stop
        assert "stop" in kinds
        assert os.path.isdir(logdir)
    else:  # gated: recorded, never raised
        assert kinds <= {"unavailable", "stop-failed"}


def test_profiler_stop_is_idempotent(monkeypatch, tmp_path):
    """The run wrapper's ``finally`` stops the profiler on every exit
    path, and the engines still call ``stop()`` on their happy path —
    the second call must be a backend no-op, not a double-stop."""
    from stateright_tpu.telemetry.profile import ScopedProfiler

    calls = {"start": 0, "stop": 0}
    monkeypatch.setattr(
        jax.profiler, "start_trace",
        lambda d: calls.__setitem__("start", calls["start"] + 1),
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace",
        lambda: calls.__setitem__("stop", calls["stop"] + 1),
    )
    rec = FlightRecorder(capacity=64, meta={"engine": "t"})
    p = ScopedProfiler(str(tmp_path), steps=5, recorder=rec)
    p.maybe_start()
    p.stop()
    p.stop()  # the defensive second stop
    assert calls == {"start": 1, "stop": 1}
    events = [e["event"] for e in rec.records("profile")]
    assert events.count("stop") == 1


def test_profiler_stop_failure_never_masks_engine_error(
    monkeypatch, tmp_path
):
    """A mid-block engine exception reaches ``stop()`` via the run
    wrapper's ``finally``; a backend failure there must downgrade to a
    ``stop-failed`` event, never replace the in-flight error."""
    from stateright_tpu.telemetry.profile import ScopedProfiler

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)

    def broken_stop():
        raise RuntimeError("backend gone")

    monkeypatch.setattr(jax.profiler, "stop_trace", broken_stop)
    rec = FlightRecorder(capacity=64, meta={"engine": "t"})
    p = ScopedProfiler(str(tmp_path), steps=5, recorder=rec)
    p.maybe_start()
    with pytest.raises(ValueError, match="engine exploded"):
        try:
            raise ValueError("engine exploded")  # the engine's error
        finally:
            p.stop()  # swallows its own failure, propagates ours
    events = [e["event"] for e in rec.records("profile")]
    assert "stop-failed" in events
    # and once failed, a repeat stop stays silent (flag already down)
    p.stop()
    assert [e for e in rec.records("profile")
            if e["event"] == "stop-failed"] != []


def test_profile_events_carry_bound_span(monkeypatch, tmp_path):
    """Profile events record the span id of the traced block, so the
    Chrome trace nests the profiled window under the run span."""
    from stateright_tpu.telemetry.profile import ScopedProfiler

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    rec = FlightRecorder(capacity=64, meta={"engine": "t"})
    rec.bind_span("deadbeefcafef00d")
    p = ScopedProfiler(str(tmp_path), steps=1, recorder=rec)
    p.maybe_start()
    p.tick()  # reaches steps -> self-stop
    events = rec.records("profile")
    assert {e["event"] for e in events} == {"start", "stop"}
    assert all(e["span"] == "deadbeefcafef00d" for e in events)


# -- zero-overhead contract --------------------------------------------------


def _wavefront_run_jaxpr(telemetry: bool) -> str:
    """The jitted run program's jaxpr for a fresh 2pc-3 engine (fresh model
    => fresh compiled-run cache), spawned with/without telemetry."""
    m = TwoPhaseSys(3)
    b = m.checker()
    if telemetry:
        b = b.telemetry(occupancy_every=1, profile_steps=1)
    c = b.spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    init_fn, run_fn = c._engine(c._cap, c._qcap, c._batch, c._cand)
    carry, _ = init_fn()
    # fresh lambda per call: jax.make_jaxpr memoizes on fn identity (the
    # PR-1 double-trace lesson, analysis/jaxpr_audit.py JX104)
    return str(jax.make_jaxpr(lambda cr: run_fn(cr))(tuple(carry)))


# two full engine compiles for one jaxpr diff is integration-shaped —
# the daily tier owns it; the fast tier keeps the same zero-ops pin on
# the metrics-bus surface (tests/test_observability.py)
@pytest.mark.medium
def test_telemetry_disabled_adds_zero_ops_to_step_jaxpr():
    """The flight recorder reads only host-synced state: the device program
    must be bit-identical with telemetry on and off — the PR-1 double-trace
    discipline applied to the whole step program."""
    assert _wavefront_run_jaxpr(False) == _wavefront_run_jaxpr(True)


@pytest.mark.slow
def test_telemetry_overhead_under_3pct_on_2pc7():
    """Acceptance gate: telemetry enabled costs <3% wall time on the 2PC-7
    wavefront run.  Capacities are pre-sized (no growth recompiles) and the
    engine cache is shared across all runs via one model instance, so the
    comparison times pure steady-state stepping; min-of-2 per config
    suppresses scheduler noise."""
    import time

    m = TwoPhaseSys(7)
    caps = dict(capacity=1 << 21, queue_capacity=1 << 19, batch=1024,
                steps_per_call=32, cand=1 << 14)

    def run(tele: bool) -> float:
        b = m.checker()
        if tele:
            b = b.telemetry()
        t0 = time.monotonic()
        c = b.spawn_tpu(sync=True, **caps)
        dt = time.monotonic() - t0
        assert c.unique_state_count() == TPC7_UNIQUE
        return dt

    run(False)  # warm-up: pays the engine compile once for everyone
    base = min(run(False), run(False))
    tele = min(run(True), run(True))
    overhead = tele / base - 1.0
    assert overhead < 0.03, (
        f"telemetry overhead {overhead:.1%} (off {base:.2f}s, on "
        f"{tele:.2f}s) breaks the <3% contract"
    )


@pytest.mark.slow
def test_2pc7_occupancy_time_series_pins_table_anomaly():
    """The pinned 2PC-7 occupancy time series, POST bucket-mix fix.  The
    run is deterministic (fixed caps, no RNG), so the series is exact.

    History: the pre-fix series was the first committed evidence for the
    VERDICT.md table-size anomaly — the raw-low-bit bucket derivation
    clustered so badly that a bucket overflowed SLOTS=16 at load 0.25
    (full_buckets=1 vs poisson_full_expect=0.17, ~6x the Poisson model),
    and max_bucket rode 14-16 from mid-run on.  The fix (bucket = high
    bits of ``mix64(fp)``, ``ops/buckets.bucket_of``) must keep the same
    deterministic series INSIDE the Poisson envelope: zero full buckets
    where the model expects a fraction of one, no single-bucket-overflow
    growth at all (growth is load/queue-driven only)."""
    c = (
        TwoPhaseSys(7).checker().telemetry(occupancy_every=1, capacity=512)
        .spawn_tpu(sync=True, capacity=1 << 16, batch=1024,
                   steps_per_call=16)
    )
    assert c.unique_state_count() == TPC7_UNIQUE
    rec = c.flight_recorder
    occ = rec.records("occupancy")
    assert len(occ) >= 10, "per-sync sampling must produce a series"
    # series sanity: monotone occupancy, closing sample covers the space
    occupied = [o["occupied"] for o in occ]
    assert occupied == sorted(occupied)
    assert occ[-1]["at"] == "final"
    assert occ[-1]["occupied"] == TPC7_UNIQUE
    # growth trail: the run still grows through table_full events (the
    # <=25%-load policy), each sampled for free at the boundary
    growth = [g for g in rec.records("growth")
              if g["status"] == "table_full"]
    assert growth, "2pc-7 at 64k initial slots must grow the table"
    # THE ANOMALY IS GONE (acceptance: full buckets within 2x Poisson at
    # load 0.25, was ~6x).  Post-fix the deterministic series never
    # overflows a bucket: max_bucket tops out at 15 (observed: 15 at the
    # load-0.25 growth boundaries, 11 at the final 0.141 load), and every
    # sample's full-bucket count sits within 2x of the Poisson
    # expectation — which at these loads means zero.
    assert max(o["max_bucket"] for o in occ) <= 15
    for o in occ:
        assert o["full_buckets"] <= 2 * max(o["poisson_full_expect"], 0.5), (
            "bucket clustering is back past the Poisson envelope: "
            f"{(o['at'], o['load_factor'], o['full_buckets'], o['poisson_full_expect'])}"
        )
    # the load-0.25 window specifically (the pre-fix failure point):
    # samples exist there and carry zero full buckets
    at_quarter = [o for o in occ if 0.24 <= o["load_factor"] <= 0.26]
    assert at_quarter and all(o["full_buckets"] == 0 for o in at_quarter)


# -- /.metrics ---------------------------------------------------------------


def _get(addr, path):
    with urllib.request.urlopen(f"http://{addr}{path}") as r:
        return json.loads(r.read())


def test_explorer_metrics_endpoint_shape():
    from stateright_tpu.explorer import serve

    server = serve(
        TwoPhaseSys(3).checker().telemetry(occupancy_every=2),
        "localhost:0", block=False, strategy="tpu", sync=True,
        capacity=1 << 12, batch=64,
    )
    try:
        m = _get(server.addr, "/.metrics")
        assert sorted(m) == [
            "cartography", "counters", "durability", "fleet", "health",
            "memory", "occupancy", "roofline", "series", "spill", "summary",
        ]
        series = m["series"]
        assert sorted(series) == [
            "dedup", "load_factor", "states_per_sec", "t", "unique"
        ]
        n = len(series["t"])
        assert n >= 1
        assert all(len(series[k]) == n for k in series)
        assert m["summary"]["unique"] == 288
        assert m["occupancy"]["occupied"] == 288
        # metrics-on, cartography/memory-off: the blocks are explicit
        # nulls (the run was spawned without cartography=True /
        # memory=True), never fabricated
        assert m["cartography"] is None
        assert m["memory"] is None
        assert m["roofline"] is None
        # durability is null too: no autosave armed, no supervision trail
        assert m["durability"] is None
        # fleet is null: the recorder belongs to no fleet scheduler
        assert m["fleet"] is None
        # the health snapshot is always present with telemetry on
        assert m["health"]["phase"] == "done"
        assert m["health"]["stalled"] is False
        # /.status still works alongside
        assert _get(server.addr, "/.status")["unique_state_count"] == 288
    finally:
        server.shutdown()


def test_explorer_metrics_with_cartography():
    """/.metrics with the search counters on: the cartography block is
    populated and reconciles with the run totals."""
    from stateright_tpu.explorer import serve

    server = serve(
        TwoPhaseSys(3).checker().telemetry(cartography=True),
        "localhost:0", block=False, strategy="tpu", sync=True,
        capacity=1 << 12, batch=64,
    )
    try:
        m = _get(server.addr, "/.metrics")
        cart = m["cartography"]
        assert cart is not None and cart["v"] == 1
        assert cart["fresh_inserts"] == 288
        assert sum(cart["depth_hist"]) == 288
        assert [p["name"] for p in cart["props"]] == [
            "abort agreement", "commit agreement", "consistent"
        ]
    finally:
        server.shutdown()


def test_explorer_metrics_404_without_telemetry():
    """Telemetry off: a STABLE machine-readable error body, not bare 404
    prose (downstream pollers key on the ``error`` field)."""
    from stateright_tpu.explorer import serve

    server = serve(TwoPhaseSys(3).checker(), "localhost:0", block=False)
    server.checker.join()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(server.addr, "/.metrics")
        assert exc.value.code == 404
        body = json.loads(exc.value.read())
        assert body["error"] == "telemetry_disabled"
        assert ".telemetry()" in body["hint"]
    finally:
        server.shutdown()
