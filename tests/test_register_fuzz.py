"""Register-fragment fuzz: seeded flaky servers under linearizability.

The history codecs (closure verdict for ``put_count=1``, enumerated
multi-op table for ``put_count=2``) are pinned on well-behaved protocols
(ABD, paxos, single-copy).  This fuzzer generates servers with seeded
*arbitrary* behavior — store-and-ack, ack-without-storing (a lying
server), silently ignore — so some seeds genuinely violate
linearizability, exercising the FALSE verdict path host=device.  For
every seed: full-space per-state equivalence (``crawl_and_check``
asserts the device ``linearizable`` mask equals the live tester's
``is_consistent()`` on every reachable state) plus unique-count and
discovery parity across engines.
"""

import random

import pytest

from stateright_tpu.actor import Actor, ActorModel, Id, Network, Out
from stateright_tpu.actor.register import (
    NULL_VALUE,
    GetOk,
    PutOk,
    RegisterClient,
    record_invocations,
    record_returns,
    value_chosen,
)
from stateright_tpu.core import Expectation
from stateright_tpu.parallel.actor_compiler import compile_actor_model
from stateright_tpu.parallel.tensor_model import TensorBackedModel
from stateright_tpu.semantics import LinearizabilityTester, Register

from test_paxos_tensor import crawl_and_check

# put behaviors
STORE_ACK = 0  # store the value, reply put_ok
LIE_ACK = 1  # reply put_ok WITHOUT storing (linearizability hazard)
IGNORE = 2


class FlakyServer(Actor):
    """Unreplicated register whose response behavior is drawn per
    (message kind, whether a value is stored) from the seed."""

    def __init__(self, rng: random.Random):
        self.put_b = {
            stored: rng.choices(
                (STORE_ACK, LIE_ACK, IGNORE), weights=(6, 2, 2)
            )[0]
            for stored in (False, True)
        }
        self.get_b = {
            stored: rng.random() < 0.85 for stored in (False, True)
        }

    def on_start(self, id: Id, out: Out):
        return NULL_VALUE

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        kind = msg[0]
        stored = state != NULL_VALUE
        if kind == "put":
            b = self.put_b[stored]
            if b == IGNORE:
                return None
            out.send(src, PutOk(msg[1]))
            return msg[2] if b == STORE_ACK else state
        if kind == "get":
            if not self.get_b[stored]:
                return None
            out.send(src, GetOk(msg[1], state))
            return state
        return None


class FuzzRegisterModel(TensorBackedModel, ActorModel):
    def tensor_model(self):
        return compile_actor_model(self)


def _model(seed: int, servers: int, clients: int, put_count: int):
    rng = random.Random(seed)
    m = FuzzRegisterModel(
        cfg=None, init_history=LinearizabilityTester(Register(NULL_VALUE))
    )
    for _ in range(servers):
        m.actor(FlakyServer(rng))
    for _ in range(clients):
        m.actor(RegisterClient(put_count=put_count, server_count=servers))
    m.init_network_(Network.new_unordered_nonduplicating())
    m.property(
        Expectation.ALWAYS,
        "linearizable",
        lambda model, s: s.history.is_consistent(),
    )
    m.property(Expectation.SOMETIMES, "value chosen", value_chosen)
    m.record_msg_in(record_returns)
    m.record_msg_out(record_invocations)
    return m


def _assert_parity(m, tag):
    tm = m.tensor_model()
    seen = crawl_and_check(m, tm)  # includes per-state linearizable mask
    h = m.checker().spawn_bfs().join()
    t = m.checker().spawn_tpu(sync=True, capacity=1 << 13)
    # early exit lands at different granularity per engine, so compare
    # discovery SETS (and witness validity), not counts, when a
    # violation stops the run early
    assert sorted(t.discoveries()) == sorted(h.discoveries()), tag
    if "linearizable" not in h.discoveries():
        assert (
            h.unique_state_count()
            == t.unique_state_count()
            == len(seen)
        ), tag
    else:
        final = t.discoveries()["linearizable"].final_state()
        assert not final.history.is_consistent(), tag
    return sorted(h.discoveries())


_FAST_SEEDS = (0, 3)
_SEEDS = [
    s if s in _FAST_SEEDS else pytest.param(s, marks=pytest.mark.medium)
    for s in range(6)
]


# re-tiered fast->slow (PR 2): the fast tier blew the 870s tier-1 budget
@pytest.mark.slow
@pytest.mark.parametrize("seed", _SEEDS)
def test_fuzzed_flaky_register_put1(seed):
    """Closure-strategy verdict under fuzz (put_count=1).  A seed may
    legitimately discover nothing (servers that ignore everything);
    the parity assertions inside are the test."""
    _assert_parity(
        _model(seed, servers=2, clients=2, put_count=1), ("put1", seed)
    )


# re-tiered fast->slow (PR 2): the fast tier blew the 870s tier-1 budget
@pytest.mark.slow
@pytest.mark.parametrize("seed", _SEEDS)
def test_fuzzed_flaky_register_put2(seed):
    """Multi-op table verdict under fuzz (put_count=2)."""
    _assert_parity(
        _model(seed, servers=2, clients=2, put_count=2), ("put2", seed)
    )


def test_fuzz_space_finds_both_verdicts():
    """Sanity on the fuzz distribution itself: across the seeds, at least
    one configuration violates linearizability (the FALSE path is really
    exercised) and at least one does not."""
    verdicts = set()
    for seed in range(6):
        m = _model(seed, servers=2, clients=2, put_count=1)
        h = m.checker().spawn_bfs().join()
        verdicts.add("linearizable" in h.discoveries())
    assert verdicts == {True, False}
