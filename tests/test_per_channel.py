"""Per-channel network encoding (docs/analysis.md "Per-channel encoding").

Three contract families, all pinned:

 - **encoding parity** — the per-channel row layout explores a state
   space ISOMORPHIC to the slot-multiset layout: unique/total counts and
   property verdicts are identical on every network semantics (unordered
   non-duplicating, unordered duplicating, ordered; lossy variants), on
   register-workload history twins (single- and multi-op) and on the
   general fragment (timers).  The actor-form 2pc fixture
   (``fixtures_actor.actor_2pc_model``) is the duplicating-semantics
   exemplar — its persistent envelope set is the TLA+ message set.
 - **real reduction** — under per-channel the independence analysis
   decomposes the consensus twins (no JX302) and ``por()`` explores
   STRICTLY FEWER states on paxos with identical verdicts and preserved
   discoveries; the slot-multiset default keeps firing JX302 plus the
   new JX305 escape-hatch pointer.
 - **default untouched** — per-channel off leaves the compiled twin's
   step jaxpr bit-identical and the hand-tuned paxos twin eligibility
   unchanged (the telemetry/checked/prededup contract pattern).
"""

from __future__ import annotations

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fixtures_actor import actor_2pc_model
from stateright_tpu.actor import Network
from stateright_tpu.analysis.footprint import extract_footprints
from stateright_tpu.analysis.independence import por_plan, run_independence
from stateright_tpu.models.paxos import PaxosModel, PaxosState, paxos_model
from stateright_tpu.models.paxos_tensor import PaxosTensor
from stateright_tpu.models.raft import raft_model
from stateright_tpu.models.single_copy_register import single_copy_model
from stateright_tpu.models.write_once_register import wo_register_model
from stateright_tpu.parallel.actor_compiler import (
    CompiledActorTensor,
    CompileError,
    compile_actor_model,
)

# pinned per-channel paxos-1 space (3 servers, 1 client) and its
# partial-order-reduced subset — also the CI smoke's numbers
P1_FULL = (482, 265)
P1_POR = (437, 250)
# paxos-2: the full pinned 16,668-unique space and its reduced subset
P2_FULL = (32_971, 16_668)
P2_POR = (31_435, 16_258)


def spawn_counts(m, caps=(1 << 15, 256), por=False):
    b = m.checker()
    if por:
        b = b.por()
    c = b.spawn_tpu(sync=True, capacity=caps[0], batch=caps[1])
    return c


def counts(c):
    return (c.state_count(), c.unique_state_count(), sorted(c.discoveries()))


def per_channel(m):
    m.per_channel_()
    return m


# -- encoding parity ----------------------------------------------------------


@pytest.mark.slow
def test_paxos1_stepwise_parity_and_roundtrip():
    """The strongest parity form: per state of the ENTIRE paxos-1 space,
    the per-channel twin's device successors equal the object model's,
    and encode/decode round-trips."""
    from collections import deque

    m = per_channel(paxos_model(1, 3))
    t = m._tensor_cached()
    assert isinstance(t, CompiledActorTensor)
    assert t.network_encoding == "per-channel"
    init = t._init_state
    row = np.asarray(t.encode_state(init), np.uint64)
    assert t.decode_state(row) == init

    def host_succ(st):
        out = set()
        for act in m.actions(st):
            ns = m.next_state(st, act)
            if ns is not None:
                out.add(ns)
        return out

    def dev_succ(st):
        rows = jnp.asarray(np.asarray([t.encode_state(st)], np.uint64))
        succ, valid = t.step_rows(rows)
        succ, valid = np.asarray(succ), np.asarray(valid)
        return {
            t.decode_state(succ[0, a])
            for a in range(valid.shape[1])
            if valid[0, a]
        }

    seen, q = {init}, deque([init])
    while q:
        st = q.popleft()
        h = host_succ(st)
        assert h == dev_succ(st), f"successor mismatch at {st}"
        for s2 in h:
            if s2 not in seen:
                seen.add(s2)
                q.append(s2)
    assert len(seen) == P1_FULL[1]


def test_engine_parity_nondup_and_ordered():
    a = counts(spawn_counts(paxos_model(1, 3)))
    b = counts(spawn_counts(per_channel(paxos_model(1, 3))))
    assert a == b
    assert (b[0], b[1]) == P1_FULL
    a = counts(spawn_counts(
        paxos_model(1, 3, Network.new_ordered()), caps=(1 << 14, 128)
    ))
    b = counts(spawn_counts(
        per_channel(paxos_model(1, 3, Network.new_ordered())),
        caps=(1 << 14, 128),
    ))
    assert a == b == (178, 99, ["value chosen"])


def test_engine_parity_duplicating_actor_2pc():
    """The 2pc acceptance row: actor-form two-phase commit over the
    duplicating network (TLA message-set semantics), host oracle
    included."""
    a = counts(spawn_counts(actor_2pc_model(3), caps=(1 << 13, 64)))
    b = counts(spawn_counts(
        per_channel(actor_2pc_model(3)), caps=(1 << 13, 64)
    ))
    assert a == b == (793, 279, ["abort reached", "commit reached"])
    h = per_channel(actor_2pc_model(3)).checker().spawn_bfs().join()
    assert (h.state_count(), h.unique_state_count()) == (793, 279)


@pytest.mark.slow
def test_engine_parity_register_history_twins():
    """History-carrying register workloads: the multi-op codec
    (put_count=2) and the write-once wfail path."""
    a = counts(spawn_counts(
        single_copy_model(2, 1, put_count=2), caps=(1 << 14, 128)
    ))
    b = counts(spawn_counts(
        per_channel(single_copy_model(2, 1, put_count=2)),
        caps=(1 << 14, 128),
    ))
    assert a == b == (483, 369, ["value chosen"])
    a = counts(spawn_counts(wo_register_model(2, 1), caps=(1 << 14, 128)))
    b = counts(spawn_counts(
        per_channel(wo_register_model(2, 1)), caps=(1 << 14, 128)
    ))
    assert a == b == (97, 71, ["value chosen"])


@pytest.mark.slow
def test_engine_parity_lossy_variants():
    """Lossy networks across two semantics: ordered paxos (drop advances
    the flow) and the duplicating actor-2pc (drop is permanent)."""
    ml = paxos_model(1, 3, Network.new_ordered())
    ml.lossy_network(True)
    a = counts(spawn_counts(ml, caps=(1 << 14, 128)))
    ml2 = per_channel(paxos_model(1, 3, Network.new_ordered()))
    ml2.lossy_network(True)
    b = counts(spawn_counts(ml2, caps=(1 << 14, 128)))
    assert a == b == (3167, 1150, ["value chosen"])
    a = counts(spawn_counts(
        actor_2pc_model(2, lossy=True), caps=(1 << 14, 128)
    ))
    b = counts(spawn_counts(
        per_channel(actor_2pc_model(2, lossy=True)), caps=(1 << 14, 128)
    ))
    assert a == b
    assert (a[0], a[1]) == (58_305, 11_392)


@pytest.mark.slow
def test_engine_parity_raft_timers_and_symmetry_composition():
    """The general fragment with timers, plus the symmetry()+prededup()
    composition (the PR-6 slow-tier pattern)."""
    a = counts(spawn_counts(raft_model(3), caps=(1 << 14, 128)))
    b = counts(spawn_counts(per_channel(raft_model(3)), caps=(1 << 14, 128)))
    assert a == b == (15_607, 5725, ["a leader is elected"])
    sa = raft_model(3).checker().symmetry().prededup().spawn_tpu(
        sync=True, capacity=1 << 14, batch=128
    )
    sb = per_channel(raft_model(3)).checker().symmetry().prededup(
    ).spawn_tpu(sync=True, capacity=1 << 14, batch=128)
    assert (sa.state_count(), sa.unique_state_count()) == (7917, 2926)
    assert (sb.state_count(), sb.unique_state_count()) == (7917, 2926)


# -- independence: decomposition, JX305, visibility ---------------------------


def test_per_channel_paxos_decomposes_and_jx305_names_the_escape_hatch():
    # default slot-multiset compiled twin: JX302 + the new JX305 pointer
    t_ms = paxos_model(1, 3)._compiled_tensor(1)
    assert t_ms.network_encoding == "slot-multiset"
    rep = run_independence(t_ms, list(paxos_model(1, 3).properties()))
    rules = rep.summary()["rules"]
    assert "JX302" in rules and "JX305" in rules
    assert rep.summary()["encoding"] == "slot-multiset"
    assert any(
        "per_channel_" in f.message for f in rep.findings
        if f.rule_id == "JX305"
    )
    # per-channel twin: decomposed, independent pairs, neither rule
    m = per_channel(paxos_model(1, 3))
    t = m._tensor_cached()
    rep = run_independence(t, list(m.properties()))
    s = rep.summary()
    assert s["decomposed"] and s["encoding"] == "per-channel"
    assert s["independent_pairs"] > 0
    assert "JX302" not in s["rules"] and "JX305" not in s["rules"]
    # the conflict matrix is channel-structured: deliveries from server 0
    # to DIFFERENT servers are independent and property-invisible
    assert not rep.visible.all()
    plan = por_plan(t, list(m.properties()))
    assert plan.usable


def test_per_channel_raft_decomposes_but_stays_all_visible():
    """raft's factored properties read every actor's state field, so the
    matrix decomposes (no JX302) yet POR correctly falls back on the C2
    condition — the fleet-gate contract."""
    m = per_channel(raft_model(3))
    t = m.tensor_model()
    rep = run_independence(t, list(m.properties()))
    s = rep.summary()
    assert s["decomposed"] and s["independent_pairs"] > 0
    assert "JX302" not in s["rules"]
    assert bool(rep.visible.all())
    plan = por_plan(t, list(m.properties()))
    assert not plan.usable and "visible" in plan.fallback_reason


def test_accum_poison_write_is_classified_not_conflicting():
    """The saturating poison flag is an OR-accumulate: same-bit poison
    writes alone never make two deliveries conflict (accum∩accum), but
    the bit still counts as a write against plain writers/readers."""
    m = per_channel(paxos_model(1, 3))
    fp = extract_footprints(m._tensor_cached())
    accs = [a.accum.to_json() for a in fp.actions]
    # the non-poisoning, non-sending get_ok channel carries NO poison
    # write at all; the put channels (table poisons) and every sending
    # channel carry exactly the poison bit as accum
    assert {} in accs
    flat = [a for a in accs if a]
    assert flat and all(len(a) == 1 for a in flat)


# -- real reduction -----------------------------------------------------------


def test_por_reduction_pinned_on_paxos1():
    full = spawn_counts(per_channel(paxos_model(1, 3)))
    por = spawn_counts(per_channel(paxos_model(1, 3)), por=True)
    assert (full.state_count(), full.unique_state_count()) == P1_FULL
    assert (por.state_count(), por.unique_state_count()) == P1_POR
    assert por.unique_state_count() < full.unique_state_count()
    assert sorted(por.discoveries()) == sorted(full.discoveries()) == [
        "value chosen"
    ]
    # the discovery trace replays through the model (soundness of the
    # reduced parent chains)
    assert len(por.discoveries()["value chosen"].into_vec()) > 0
    st = por.por_status()
    assert st["enabled"] is True and st["fallback"] is None
    assert st["encoding"] == "per-channel"
    assert st["rows_reduced"] > 0 and st["candidates_masked"] > 0


@pytest.mark.slow
def test_por_reduction_pinned_on_paxos2():
    """The headline: the full pinned 16,668-unique paxos-2 space shrinks
    strictly under per-channel + por() with identical verdicts."""
    full = spawn_counts(per_channel(paxos_model(2, 3)))
    por = spawn_counts(per_channel(paxos_model(2, 3)), por=True)
    assert (full.state_count(), full.unique_state_count()) == P2_FULL
    assert (por.state_count(), por.unique_state_count()) == P2_POR
    assert sorted(por.discoveries()) == sorted(full.discoveries())
    st = por.por_status()
    assert st["rows_reduced"] > 0 and st["encoding"] == "per-channel"


def test_poison_detection_survives_reduction():
    """A too-tight state_bound must fail LOUDLY under per-channel + por()
    exactly like under full expansion: poison writes are monotone
    OR-accumulates on the action's own read footprint, so every
    trace-equivalent reordering the reduced search explores still takes
    the poisoning transition (docs/analysis.md)."""

    class TightPaxos(PaxosModel):
        def tensor_model(self):
            try:
                return compile_actor_model(
                    self,
                    # ballot must reach 1 in any real run: too tight
                    state_bound=lambda i, s: not isinstance(s, PaxosState)
                    or s.ballot[0] <= 0,
                    env_bound=lambda e: e.msg[0] != "internal"
                    or e.msg[1][1][0] <= 1,
                )
            except (CompileError, ValueError):
                return None

    def build():
        m = TightPaxos(
            cfg=None,
            init_history=paxos_model(1, 3).init_history,
        )
        src = paxos_model(1, 3)
        for a in src.actors:
            m.actor(a)
        m.init_network_(src.init_network)
        for p in src.properties():
            m.property(p.expectation, p.name, p.condition)
        m.record_msg_in(src._record_msg_in)
        m.record_msg_out(src._record_msg_out)
        return per_channel(m)

    with pytest.raises(RuntimeError, match="poisoned rows"):
        build().checker().spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    with pytest.raises(RuntimeError, match="poisoned rows"):
        build().checker().por().spawn_tpu(
            sync=True, capacity=1 << 12, batch=64
        )


# -- kill + resume ------------------------------------------------------------


@pytest.mark.slow
def test_killed_and_resumed_per_channel_runs():
    """Mid-run kill + resume under the per-channel layout: the full
    expansion resumes to EXACT totals; under por() the resume boundary
    legitimately re-arms one fully-expanded wavefront (the boost), so
    the contract is verdict parity + a sound subset of the full space
    no smaller than the reduced lattice."""
    m = per_channel(paxos_model(2, 3))
    c = m.checker().spawn_tpu(capacity=1 << 15, batch=256, steps_per_call=2)
    time.sleep(0.4)
    c.stop()
    c.join()
    snap = c.checkpoint()
    r = per_channel(paxos_model(2, 3)).checker().spawn_tpu(
        sync=True, resume=snap
    )
    assert (r.state_count(), r.unique_state_count()) == P2_FULL

    p = per_channel(paxos_model(2, 3)).checker().por().spawn_tpu(
        capacity=1 << 15, batch=256, steps_per_call=2
    )
    time.sleep(0.4)
    p.stop()
    p.join()
    pr = per_channel(paxos_model(2, 3)).checker().por().spawn_tpu(
        sync=True, resume=p.checkpoint()
    )
    assert sorted(pr.discoveries()) == ["value chosen"]
    assert P2_POR[1] <= pr.unique_state_count() <= P2_FULL[1]


# -- default path untouched ---------------------------------------------------


def test_per_channel_off_leaves_twin_and_jaxpr_untouched():
    # flag unset vs explicitly False: byte-identical step jaxprs, same
    # layout, slot-multiset encoding
    t_unset = paxos_model(1, 3)._compiled_tensor(1)
    m_false = paxos_model(1, 3)
    m_false.per_channel_(False)
    t_false = m_false._compiled_tensor(1)
    assert t_unset.network_encoding == t_false.network_encoding \
        == "slot-multiset"
    np.asarray(t_unset.init_rows())
    np.asarray(t_false.init_rows())
    aval = jax.ShapeDtypeStruct((4, t_unset.width), jnp.uint64)
    j_unset = str(jax.make_jaxpr(t_unset.step_rows)(aval))
    j_false = str(jax.make_jaxpr(t_false.step_rows)(aval))
    assert j_unset == j_false
    # the hand-tuned paxos twin stays the default; per-channel routes to
    # the mechanical compiler
    assert isinstance(paxos_model(2, 3).tensor_model(), PaxosTensor)
    assert isinstance(
        per_channel(paxos_model(2, 3)).tensor_model(), CompiledActorTensor
    )


def test_n_slots_is_rejected_with_per_channel():
    m = per_channel(paxos_model(1, 3))
    with pytest.raises(CompileError, match="slot-multiset knob"):
        compile_actor_model(m, n_slots=32)


def test_ordered_duplicate_ranks_poison_loudly_and_depth_knob_fixes_it():
    """An ordered flow carrying the SAME message at two ranks outgrows a
    default per-channel region (capacity = distinct codes): the run must
    fail LOUDLY (overflow → poison), never silently diverge, and
    ``per_channel_depth`` restores parity with the slot-multiset twin."""
    from dataclasses import dataclass

    from stateright_tpu import Expectation
    from stateright_tpu.actor import Actor, ActorModel, Id, Out
    from stateright_tpu.actor.device_props import exists_actor
    from stateright_tpu.parallel.tensor_model import TensorBackedModel

    @dataclass
    class Resender(Actor):
        def on_start(self, id, out):
            if int(id) == 0:
                out.send(Id(1), ("ping",))  # same msg TWICE: ranks 1+2
                out.send(Id(1), ("ping",))
            return 0

        def on_msg(self, id, state, src, msg, out):
            if msg[0] == "ping" and state < 2:
                return state + 1
            return None

    def build(pc, depth=None):
        class M(TensorBackedModel, ActorModel):
            def tensor_model(self):
                return compile_actor_model(
                    self, per_channel=pc, per_channel_depth=depth
                )

        m = M(cfg=None, init_history=None)
        m.actor(Resender())
        m.actor(Resender())
        m.init_network_(Network.new_ordered())
        m.property(
            Expectation.SOMETIMES,
            "both delivered",
            exists_actor(lambda i, s: s == 2),
        )
        return m

    ms = build(False).checker().spawn_tpu(sync=True, capacity=1 << 8,
                                          batch=8)
    # the default per-channel capacity (1 distinct code) cannot hold the
    # 2-deep flow: the INIT state itself refuses to encode — loud
    with pytest.raises(ValueError, match="exceeding its region capacity"):
        build(True).checker().spawn_tpu(sync=True, capacity=1 << 8, batch=8)
    pc = build(True, depth=2).checker().spawn_tpu(
        sync=True, capacity=1 << 8, batch=8
    )
    assert counts(ms) == counts(pc)


# -- surfaces: por_status / run report ----------------------------------------


def test_report_carries_por_block_with_encoding(tmp_path):
    path = str(tmp_path / "report.json")
    m = per_channel(paxos_model(1, 3))
    m.checker().por().report(path).spawn_tpu(
        sync=True, capacity=1 << 15, batch=256
    ).join()
    import json

    body = json.load(open(path))
    assert body["por"]["encoding"] == "per-channel"
    assert body["por"]["enabled"] is True
    assert body["por"]["rows_reduced"] > 0
    md = open(path[:-5] + ".md").read()
    assert "Partial-order reduction" in md
    assert "per-channel" in md


def test_regress_independence_gate_per_channel_leg():
    """The regress.py --independence ratio-sanity gate, with injectable
    artifacts: absent keys never trip; a well-formed leg passes; a bad
    ratio, count inversion, wrong encoding, or crashed leg fails."""
    from regress import independence_verdict

    def clean_fleet(stream=None):
        print("independence fleet: CLEAN", file=stream)
        return 0

    base = {
        "tpu_paxos2_por_channel": {
            "enabled": True, "fallback": None, "encoding": "per-channel",
            "rows_reduced": 269, "rows_full_proviso": 387,
            "candidates_masked": 269,
        },
        "tpu_paxos2_por_channel_unique": P2_POR[1],
        "tpu_paxos2_por_channel_full_unique": P2_FULL[1],
        "tpu_paxos2_por_channel_reduction_ratio": round(
            P2_POR[1] / P2_FULL[1], 4
        ),
    }
    # stale / pre-channel artifact: no keys, no gate
    v = independence_verdict({}, fleet=clean_fleet)
    assert v["clean"] and "por_channel_leg" not in v
    # well-formed leg passes and surfaces the ratio
    v = independence_verdict(dict(base), fleet=clean_fleet)
    assert v["clean"] and v["por_channel_leg"]["ok"]
    assert 0 < v["por_channel_leg"]["reduction_ratio"] <= 1
    # ratio out of range / inconsistent
    bad = dict(base)
    bad["tpu_paxos2_por_channel_reduction_ratio"] = 1.7
    assert not independence_verdict(bad, fleet=clean_fleet)["clean"]
    # reduced > full is impossible
    bad = dict(base)
    bad["tpu_paxos2_por_channel_unique"] = P2_FULL[1] + 1
    assert not independence_verdict(bad, fleet=clean_fleet)["clean"]
    # wrong encoding
    bad = dict(base)
    bad["tpu_paxos2_por_channel"] = dict(
        base["tpu_paxos2_por_channel"], encoding="slot-multiset"
    )
    assert not independence_verdict(bad, fleet=clean_fleet)["clean"]
    # crashed leg
    v = independence_verdict(
        {"tpu_paxos2_por_channel_error": "RuntimeError: boom"},
        fleet=clean_fleet,
    )
    assert not v["clean"] and not v["por_channel_leg"]["ok"]


def test_ret_kind_envelope_to_a_server_skips_history():
    """A put_ok RELAYED to another server must not touch the history
    fields (the multiset kernel's `ci >= 0` guard): the per-channel
    kernel statically skips history on non-client destinations instead
    of tracing `h-1_*` fields."""
    from dataclasses import dataclass

    from stateright_tpu import Expectation
    from stateright_tpu.actor import Actor, ActorModel, Id, Out
    from stateright_tpu.actor.register import (
        NULL_VALUE,
        GetOk,
        PutOk,
        RegisterClient,
        record_invocations,
        record_returns,
        value_chosen,
    )
    from stateright_tpu.parallel.tensor_model import TensorBackedModel
    from stateright_tpu.semantics import LinearizabilityTester, Register

    @dataclass
    class GossipingServer(Actor):
        value: int = NULL_VALUE

        def on_start(self, id, out):
            return NULL_VALUE

        def on_msg(self, id, state, src, msg, out):
            if msg[0] == "put" and state == NULL_VALUE:
                out.send(src, PutOk(msg[1]))
                out.send(Id(1), PutOk(msg[1]))  # relayed to a SERVER
                return msg[2]
            if msg[0] == "get" and state != NULL_VALUE:
                out.send(src, GetOk(msg[1], state))
                return state
            return None

    def build(pc):
        class M(TensorBackedModel, ActorModel):
            def tensor_model(self):
                return compile_actor_model(self, per_channel=pc)

        m = M(
            cfg=None,
            init_history=LinearizabilityTester(Register(NULL_VALUE)),
        )
        m.actor(GossipingServer())
        m.actor(GossipingServer())
        m.actor(RegisterClient(put_count=1, server_count=2))
        m.init_network_(
            Network.new_unordered_nonduplicating()
        )
        m.property(
            Expectation.ALWAYS,
            "linearizable",
            lambda model, s: s.history.is_consistent(),
        )
        m.property(Expectation.SOMETIMES, "value chosen", value_chosen)
        m.record_msg_in(record_returns)
        m.record_msg_out(record_invocations)
        return m

    a = counts(build(False).checker().spawn_tpu(
        sync=True, capacity=1 << 10, batch=16
    ))
    b = counts(build(True).checker().spawn_tpu(
        sync=True, capacity=1 << 10, batch=16
    ))
    assert a == b


def test_network_channel_helpers():
    from stateright_tpu.actor.network import Envelope

    e = Envelope(src=1, dst=2, msg=("x",))
    assert e.channel == (1, 2)
    n = Network.new_unordered_nonduplicating()
    n = n.send(Envelope(0, 1, ("a",))).send(Envelope(1, 0, ("b",)))
    n = n.send(Envelope(0, 1, ("c",)))
    assert n.channels() == [(0, 1), (1, 0)]
    assert Network.new_ordered().channels() == []
