"""regress.py — the perf-regression gate over bench summaries.

Pins the round-6 contract: a stale artifact (the validated-fallback replay)
NEVER validates; per-config throughput below tolerance x baseline fails
loudly with the offending configs named; improvements are reported, not
punished.
"""

import importlib.util
import json
import os

_REGRESS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "regress.py"
)


def _load():
    spec = importlib.util.spec_from_file_location("regress_under_test",
                                                  _REGRESS)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


BASELINE = {
    "tpu_paxos3_states_per_sec": 266699.0,
    "tpu_2pc7_states_per_sec": 1450000.0,
    "tpu_2pc4_states_per_sec": 9000.0,
    "cpu_paxos3_uncontended_states_per_sec": 8188.4,  # not a tpu_ key
    "validated_at": "2026-07-31T03:30:00Z",
}


def test_compare_clean_fresh_run():
    r = _load()
    verdict = r.compare(
        {"fresh": True,
         "tpu_paxos3_states_per_sec": 280000.0,
         "tpu_2pc7_states_per_sec": 1400000.0},
        BASELINE,
    )
    assert verdict["ok"] is True
    assert verdict["checked"] == 2  # only keys present in BOTH, tpu_ only
    assert verdict["regressed"] == []
    assert [e["config"] for e in verdict["improved"]] == [
        "tpu_paxos3_states_per_sec"
    ]


def test_compare_flags_regression_with_detail():
    r = _load()
    verdict = r.compare(
        {"fresh": True,
         "tpu_paxos3_states_per_sec": 100000.0,  # 0.37x: regression
         "tpu_2pc7_states_per_sec": 1300000.0},  # 0.90x: within tolerance
        BASELINE,
    )
    assert verdict["ok"] is False
    (bad,) = verdict["regressed"]
    assert bad["config"] == "tpu_paxos3_states_per_sec"
    assert bad["ratio"] == 0.375
    assert bad["baseline"] == 266699.0


def test_compare_stale_run_is_not_ok():
    r = _load()
    verdict = r.compare(
        {"fresh": False, "tpu_paxos3_states_per_sec": 266699.0}, BASELINE
    )
    assert verdict["ok"] is False and verdict["fresh"] is False


def test_main_exit_codes(tmp_path, capsys):
    r = _load()
    base = tmp_path / "base.json"
    base.write_text(json.dumps(BASELINE))

    def run(doc, *flags):
        p = tmp_path / "run.json"
        p.write_text(json.dumps(doc))
        rc = r.main([str(p), f"--baseline={base}", *flags])
        out = capsys.readouterr().out.strip().splitlines()
        return rc, json.loads(out[-1])

    # fresh + clean -> 0
    rc, v = run({"fresh": True, "tpu_paxos3_states_per_sec": 270000.0})
    assert rc == 0 and v["ok"] is True
    # regression -> 1, offender named on stdout
    rc, v = run({"fresh": True, "tpu_paxos3_states_per_sec": 1000.0})
    assert rc == 1 and v["regressed"][0]["config"] == (
        "tpu_paxos3_states_per_sec"
    )
    # stale -> 2 (the round-5 carry-forward can never validate)
    rc, v = run({"fresh": False, "value": 0.0,
                 "stale": "STALE (fresh=false, carried from r04)"})
    assert rc == 2 and v["fresh"] is False and "STALE" in v["stale"]
    # --allow-stale compares two stored artifacts without the fresh gate
    rc, v = run(
        {"fresh": False, "tpu_paxos3_states_per_sec": 266699.0},
        "--allow-stale",
    )
    assert rc == 0


def test_main_unwraps_driver_artifacts(tmp_path, capsys):
    """Driver BENCH_rNN.json files wrap the headline in ``parsed``."""
    r = _load()
    base = tmp_path / "base.json"
    base.write_text(json.dumps(BASELINE))
    p = tmp_path / "BENCH_r06.json"
    p.write_text(json.dumps({
        "rc": 0,
        "parsed": {"fresh": True, "tpu_paxos3_states_per_sec": 300000.0},
    }))
    rc = r.main([str(p), f"--baseline={base}"])
    v = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and v["checked"] == 1


def test_main_missing_files_exit_2(tmp_path, capsys):
    r = _load()
    rc = r.main([str(tmp_path / "absent.json")])
    assert rc == 2
    assert json.loads(capsys.readouterr().out)["ok"] is False


def test_sanitizer_section_gates_the_verdict(tmp_path, capsys):
    """--sanitize adds a ``sanitizer`` section (the fleet soundness gate,
    docs/analysis.md JX2xx): a clean fleet leaves a fresh run passing, an
    unclean fleet fails it with exit 1 — and the stale-artifact rules are
    unchanged (stale + unclean still exits 2 on staleness first)."""
    r = _load()
    base = tmp_path / "base.json"
    base.write_text(json.dumps(BASELINE))
    run = tmp_path / "run.json"
    run.write_text(json.dumps(
        {"fresh": True, "tpu_paxos3_states_per_sec": 270000.0}
    ))

    def clean_fleet(stream=None):
        print("sanitize fleet: CLEAN", file=stream)
        return 0

    def dirty_fleet(stream=None):
        print("sanitize fleet: FAILED (JX201)", file=stream)
        return 1

    rc = r.main([str(run), f"--baseline={base}", "--sanitize"],
                fleet=clean_fleet)
    v = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and v["ok"] is True
    assert v["sanitizer"] == {"clean": True,
                              "verdict": "sanitize fleet: CLEAN"}

    rc = r.main([str(run), f"--baseline={base}", "--sanitize"],
                fleet=dirty_fleet)
    v = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1 and v["ok"] is False
    assert v["sanitizer"]["clean"] is False
    assert "JX201" in v["sanitizer"]["verdict"]

    # without the flag the verdict is untouched (no import of the fleet)
    rc = r.main([str(run), f"--baseline={base}"])
    v = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and "sanitizer" not in v

    # staleness still wins: a stale artifact exits 2 before sanitizing
    stale = tmp_path / "stale.json"
    stale.write_text(json.dumps({"fresh": False}))
    rc = r.main([str(stale), f"--baseline={base}", "--sanitize"],
                fleet=clean_fleet)
    assert rc == 2


def test_sanitizer_verdict_crash_is_a_failure():
    """An import/trace crash in the fleet runner is a gate FAILURE, never
    a silent skip."""
    r = _load()

    def broken(stream=None):
        raise RuntimeError("boom")

    v = r.sanitizer_verdict(fleet=broken)
    assert v["clean"] is False and "boom" in v["error"]


def test_independence_section_gates_the_verdict(tmp_path, capsys):
    """--independence mirrors --sanitize: the fleet conflict-matrix gate
    (docs/analysis.md JX3xx) plus a well-formedness check on the run's
    flag-gated POR leg — POR must never change paxos counts (its matrix
    is conservatively all-dependent).  Stale artifacts still exit 2 first
    and never pay the fleet import."""
    r = _load()
    base = tmp_path / "base.json"
    base.write_text(json.dumps(BASELINE))
    run = tmp_path / "run.json"
    run.write_text(json.dumps(
        {"fresh": True, "tpu_paxos3_states_per_sec": 270000.0}
    ))

    def clean_fleet(stream=None):
        print("independence fleet: CLEAN", file=stream)
        return 0

    def dirty_fleet(stream=None):
        print("independence fleet: FAILED (JX301)", file=stream)
        return 1

    rc = r.main([str(run), f"--baseline={base}", "--independence"],
                fleet=clean_fleet)
    v = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and v["ok"] is True
    assert v["independence"]["clean"] is True

    rc = r.main([str(run), f"--baseline={base}", "--independence"],
                fleet=dirty_fleet)
    v = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1 and v["ok"] is False
    assert "JX301" in v["independence"]["verdict"]

    # without the flag: untouched, no fleet import
    rc = r.main([str(run), f"--baseline={base}"])
    v = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and "independence" not in v

    # staleness wins before the fleet runs
    stale = tmp_path / "stale.json"
    stale.write_text(json.dumps({"fresh": False}))
    rc = r.main([str(stale), f"--baseline={base}", "--independence"],
                fleet=clean_fleet)
    assert rc == 2


def test_independence_por_leg_well_formedness(tmp_path, capsys):
    """A run artifact carrying the flag-gated POR leg must be well-formed
    and count-stable vs the full-expansion leg."""
    r = _load()

    def clean_fleet(stream=None):
        print("independence fleet: CLEAN", file=stream)
        return 0

    good = {
        "fresh": True,
        "tpu_paxos3_unique": 40000,
        "tpu_paxos3_por_unique": 40000,
        "tpu_paxos3_por": {"enabled": False, "fallback": "all-dependent"},
    }
    v = r.independence_verdict(good, fleet=clean_fleet)
    assert v["clean"] is True and v["por_leg"]["ok"] is True

    drifted = dict(good, tpu_paxos3_por_unique=39999)
    v = r.independence_verdict(drifted, fleet=clean_fleet)
    assert v["clean"] is False
    assert any("por unique" in p for p in v["por_leg"]["problems"])

    malformed = dict(good, tpu_paxos3_por=["not-a-dict"])
    v = r.independence_verdict(malformed, fleet=clean_fleet)
    assert v["clean"] is False

    # a crashed POR leg (bench recorded only the error key) is a gate
    # FAILURE, never a silent skip
    crashed = {"fresh": True, "tpu_paxos3_por_error": "RuntimeError: x"}
    v = r.independence_verdict(crashed, fleet=clean_fleet)
    assert v["clean"] is False
    assert any("crashed" in p for p in v["por_leg"]["problems"])

    # a crash in the fleet runner is a failure, never a skip
    def broken(stream=None):
        raise RuntimeError("boom")

    v = r.independence_verdict({}, fleet=broken)
    assert v["clean"] is False and "boom" in v["error"]


def test_stages_section_gates_fresh_runs_only(tmp_path, capsys):
    """--stages: a FRESH run must carry a well-formed per-stage breakdown;
    stored baselines without stages (pre-attribution hardware numbers)
    never trip the gate, and staleness still wins with exit 2."""
    r = _load()
    base = tmp_path / "base.json"
    base.write_text(json.dumps(BASELINE))  # note: baseline has no stages

    def run(doc, *flags):
        p = tmp_path / "run.json"
        p.write_text(json.dumps(doc))
        rc = r.main([str(p), f"--baseline={base}", *flags])
        out = capsys.readouterr().out.strip().splitlines()
        return rc, json.loads(out[-1])

    stages = {"compile_secs": 1.0, "device_secs": 7.0, "growth_secs": 0.2,
              "wall_secs": 9.0, "host_secs": 0.8}
    # fresh + stages present -> ok, baseline absence is informational
    rc, v = run({"fresh": True, "tpu_paxos3_states_per_sec": 270000.0,
                 "tpu_paxos3_stages": stages}, "--stages")
    assert rc == 0 and v["ok"] is True
    assert v["stages"]["ok"] is True and v["stages"]["baseline"] is None
    assert v["stages"]["run"] == stages
    # fresh but NO stages -> exit 1, named in the verdict
    rc, v = run({"fresh": True, "tpu_paxos3_states_per_sec": 270000.0},
                "--stages")
    assert rc == 1 and v["ok"] is False and v["stages"]["ok"] is False
    # malformed (negative) stage -> exit 1
    rc, v = run({"fresh": True, "tpu_paxos3_states_per_sec": 270000.0,
                 "tpu_paxos3_stages": {"device_secs": -1.0}}, "--stages")
    assert rc == 1 and v["stages"]["malformed"] == ["device_secs"]
    # stale run: staleness exits 2 regardless of stages
    rc, v = run({"fresh": False}, "--stages")
    assert rc == 2
    # --allow-stale: a stored artifact without stages is NOT required to
    # have them (it predates the attribution round)
    rc, v = run({"fresh": False,
                 "tpu_paxos3_states_per_sec": 266699.0},
                "--stages", "--allow-stale")
    assert rc == 0 and v["stages"]["ok"] is False  # reported, not gated
    # baseline WITH stages is attached for comparison
    base.write_text(json.dumps({**BASELINE,
                                "tpu_paxos3_stages": stages}))
    rc, v = run({"fresh": True, "tpu_paxos3_states_per_sec": 270000.0,
                 "tpu_paxos3_stages": stages}, "--stages")
    assert rc == 0 and v["stages"]["baseline"] == stages


def test_cartography_section_gates_fresh_runs_only(tmp_path, capsys):
    """--cartography: a FRESH run must carry a well-formed, reconciling
    cartography block; stored baselines without one (pre-cartography
    rounds) never trip the gate, and staleness still wins with exit 2 —
    the exact --stages rule applied to the search-shape artifact."""
    r = _load()
    base = tmp_path / "base.json"
    base.write_text(json.dumps(BASELINE))  # note: baseline has no block

    def run(doc, *flags):
        p = tmp_path / "run.json"
        p.write_text(json.dumps(doc))
        rc = r.main([str(p), f"--baseline={base}", *flags])
        out = capsys.readouterr().out.strip().splitlines()
        return rc, json.loads(out[-1])

    cart = {
        "v": 1,
        "depth_hist": [1, 10, 29],
        "action_hist": [5, 20, 15],
        "props": [],
        "fresh_inserts": 40,
        "duplicate_hits": 12,
    }
    good = {"fresh": True, "tpu_paxos3_states_per_sec": 270000.0,
            "tpu_paxos3_unique": 40, "tpu_paxos3_cartography": cart}
    # fresh + well-formed block -> ok; absent baseline is informational
    rc, v = run(good, "--cartography")
    assert rc == 0 and v["ok"] is True
    assert v["cartography"]["ok"] is True
    assert v["cartography"]["baseline_present"] is False
    assert v["cartography"]["summary"]["fresh_inserts"] == 40
    # fresh but NO block -> exit 1, named in the verdict
    rc, v = run({"fresh": True, "tpu_paxos3_states_per_sec": 270000.0},
                "--cartography")
    assert rc == 1 and v["cartography"]["ok"] is False
    # malformed: depth histogram does not reconcile with fresh_inserts
    rc, v = run({**good,
                 "tpu_paxos3_cartography": {**cart, "fresh_inserts": 99},
                 "tpu_paxos3_unique": 99}, "--cartography")
    assert rc == 1
    assert any("sum(depth_hist)" in p
               for p in v["cartography"]["problems"])
    # malformed: block disagrees with the run's own headline unique
    rc, v = run({**good, "tpu_paxos3_unique": 41}, "--cartography")
    assert rc == 1
    assert any("tpu_paxos3_unique" in p
               for p in v["cartography"]["problems"])
    # unversioned block -> exit 1
    rc, v = run({**good,
                 "tpu_paxos3_cartography": {
                     k: x for k, x in cart.items() if k != "v"
                 }}, "--cartography")
    assert rc == 1
    assert any("schema version" in p for p in v["cartography"]["problems"])
    # stale run: staleness exits 2 regardless of cartography
    rc, v = run({"fresh": False}, "--cartography")
    assert rc == 2
    # --allow-stale: a stored pre-cartography artifact is reported, not
    # gated
    rc, v = run({"fresh": False,
                 "tpu_paxos3_states_per_sec": 266699.0},
                "--cartography", "--allow-stale")
    assert rc == 0 and v["cartography"]["ok"] is False
    # baseline WITH a block is noted for comparison
    base.write_text(json.dumps({**BASELINE,
                                "tpu_paxos3_cartography": cart}))
    rc, v = run(good, "--cartography")
    assert rc == 0 and v["cartography"]["baseline_present"] is True


def test_memory_section_gates_fresh_runs_only(tmp_path, capsys):
    """--memory: a FRESH run must carry a well-formed HBM-ledger block
    (versioned, buffers summing exactly to total_bytes, a growth
    forecast whose transient covers old+new); stored baselines without
    one (pre-memory rounds) never trip, staleness still exits 2 — the
    --stages/--cartography rule applied to the memory artifact."""
    r = _load()
    base = tmp_path / "base.json"
    base.write_text(json.dumps(BASELINE))  # note: baseline has no block

    def run(doc, *flags):
        p = tmp_path / "run.json"
        p.write_text(json.dumps(doc))
        rc = r.main([str(p), f"--baseline={base}", *flags])
        out = capsys.readouterr().out.strip().splitlines()
        return rc, json.loads(out[-1])

    mem = {
        "v": 1,
        "engine": "wavefront",
        "capacity": 131072,
        "buffers": {"table_fp": 1048576, "table_parent": 1048576,
                    "q_rows": 500000},
        "total_bytes": 2597152,
        "next_rung": {"capacity": 262144, "total_bytes": 4694304,
                      "transient_bytes": 7291456},
    }
    good = {"fresh": True, "tpu_paxos3_states_per_sec": 270000.0,
            "tpu_paxos3_memory": mem}
    # fresh + well-formed -> ok; absent baseline is informational
    rc, v = run(good, "--memory")
    assert rc == 0 and v["ok"] is True
    assert v["memory"]["ok"] is True
    assert v["memory"]["baseline_present"] is False
    assert v["memory"]["summary"]["total_bytes"] == 2597152
    # fresh but NO block -> exit 1, named in the verdict
    rc, v = run({"fresh": True, "tpu_paxos3_states_per_sec": 270000.0},
                "--memory")
    assert rc == 1 and v["memory"]["ok"] is False
    assert any("no tpu_paxos3_memory" in p for p in v["memory"]["problems"])
    # malformed: buffers do not sum to total_bytes
    rc, v = run({**good,
                 "tpu_paxos3_memory": {**mem, "total_bytes": 999}},
                "--memory")
    assert rc == 1
    assert any("sum(buffers)" in p for p in v["memory"]["problems"])
    # malformed: MIXED-TYPE buffers map must yield a verdict, not a
    # TypeError from the mismatch message (review find)
    rc, v = run({**good,
                 "tpu_paxos3_memory": {
                     **mem, "buffers": {"a": 5, "b": "junk"},
                 }}, "--memory")
    assert rc == 1
    assert any("non-int" in p for p in v["memory"]["problems"])
    assert any("sum(buffers)" in p for p in v["memory"]["problems"])
    # malformed: transient below the steady footprint (forecast must
    # hold old + new carry live)
    rc, v = run({**good,
                 "tpu_paxos3_memory": {
                     **mem,
                     "next_rung": {"capacity": 262144,
                                   "total_bytes": 4694304,
                                   "transient_bytes": 100},
                 }}, "--memory")
    assert rc == 1
    assert any("transient" in p for p in v["memory"]["problems"])
    # unversioned -> exit 1
    rc, v = run({**good,
                 "tpu_paxos3_memory": {
                     k: x for k, x in mem.items() if k != "v"
                 }}, "--memory")
    assert rc == 1
    assert any("schema version" in p for p in v["memory"]["problems"])
    # stale run: staleness exits 2 regardless of the memory gate
    rc, v = run({"fresh": False}, "--memory")
    assert rc == 2
    # --allow-stale: a stored pre-memory artifact is reported, not gated
    rc, v = run({"fresh": False,
                 "tpu_paxos3_states_per_sec": 266699.0},
                "--memory", "--allow-stale")
    assert rc == 0 and v["memory"]["ok"] is False
    # baseline WITH a block is noted for comparison
    base.write_text(json.dumps({**BASELINE, "tpu_paxos3_memory": mem}))
    rc, v = run(good, "--memory")
    assert rc == 0 and v["memory"]["baseline_present"] is True


def test_roofline_section_gates_fresh_runs_only(tmp_path, capsys):
    """--roofline: a FRESH run must carry a well-formed roofline block
    (versioned, per-stage non-negative integer FLOPs/bytes summing to
    the totals, a PASSING XLA-reconciliation verdict); stored baselines
    without one (pre-roofline rounds) never trip, staleness still exits
    2 — the --stages/--cartography/--memory rule applied to the cost
    ledger (docs/roofline.md)."""
    r = _load()
    base = tmp_path / "base.json"
    base.write_text(json.dumps(BASELINE))  # note: baseline has no block

    def run(doc, *flags):
        p = tmp_path / "run.json"
        p.write_text(json.dumps(doc))
        rc = r.main([str(p), f"--baseline={base}", *flags])
        out = capsys.readouterr().out.strip().splitlines()
        return rc, json.loads(out[-1])

    roof = {
        "v": 1,
        "engine": "wavefront",
        "batch": 4096,
        "stages": {
            "expand": {"flops": 1000, "bytes_read": 2000,
                       "bytes_written": 500},
            "dedup-insert": {"flops": 4000, "bytes_read": 8000,
                             "bytes_written": 1500},
        },
        "totals": {"flops": 5000, "bytes": 12000},
        "mxu_candidates": [{"rank": 1, "stage": "dedup-insert",
                            "op": "gather", "bytes": 6000}],
        "reconciliation": {"ok": True, "stages": {}},
    }
    good = {"fresh": True, "tpu_paxos3_states_per_sec": 270000.0,
            "tpu_paxos3_roofline": roof}
    # fresh + well-formed + reconciled -> ok; absent baseline is fine
    rc, v = run(good, "--roofline")
    assert rc == 0 and v["ok"] is True
    assert v["roofline"]["ok"] is True
    assert v["roofline"]["baseline_present"] is False
    assert v["roofline"]["summary"]["reconciled"] is True
    assert v["roofline"]["summary"]["mxu_candidates"] == 1
    # fresh but NO block -> exit 1, named in the verdict
    rc, v = run({"fresh": True, "tpu_paxos3_states_per_sec": 270000.0},
                "--roofline")
    assert rc == 1 and v["roofline"]["ok"] is False
    assert any("no tpu_paxos3_roofline" in p
               for p in v["roofline"]["problems"])
    # malformed: stage sums disagree with the totals
    rc, v = run({**good,
                 "tpu_paxos3_roofline": {
                     **roof, "totals": {"flops": 1, "bytes": 12000},
                 }}, "--roofline")
    assert rc == 1
    assert any("totals.flops" in p for p in v["roofline"]["problems"])
    # malformed: negative stage bytes
    rc, v = run({**good,
                 "tpu_paxos3_roofline": {
                     **roof,
                     "stages": {"expand": {"flops": 1, "bytes_read": -5,
                                           "bytes_written": 0}},
                 }}, "--roofline")
    assert rc == 1
    assert any("missing/negative" in p for p in v["roofline"]["problems"])
    # a FAILED XLA reconciliation is a gate failure, not a note
    rc, v = run({**good,
                 "tpu_paxos3_roofline": {
                     **roof, "reconciliation": {"ok": False},
                 }}, "--roofline")
    assert rc == 1
    assert any("reconciliation FAILED" in p
               for p in v["roofline"]["problems"])
    # unversioned -> exit 1
    rc, v = run({**good,
                 "tpu_paxos3_roofline": {
                     k: x for k, x in roof.items() if k != "v"
                 }}, "--roofline")
    assert rc == 1
    assert any("schema version" in p for p in v["roofline"]["problems"])
    # stale run: staleness exits 2 regardless of the roofline gate
    rc, v = run({"fresh": False}, "--roofline")
    assert rc == 2
    # --allow-stale: a stored pre-roofline artifact is reported, not gated
    rc, v = run({"fresh": False,
                 "tpu_paxos3_states_per_sec": 266699.0},
                "--roofline", "--allow-stale")
    assert rc == 0 and v["roofline"]["ok"] is False
    # baseline WITH a block is noted for comparison
    base.write_text(json.dumps({**BASELINE, "tpu_paxos3_roofline": roof}))
    rc, v = run(good, "--roofline")
    assert rc == 0 and v["roofline"]["baseline_present"] is True


def test_sweep_section_gates_fresh_runs_only(tmp_path, capsys):
    """--sweep: the hyper-batched sweep leg (docs/sweep.md).  Flag-gated
    like --spill/--mxu: absence (stale artifacts, pre-sweep baselines)
    never trips; a present-but-crashed, parity-breaking, malformed, or
    unamortized leg trips fresh runs only."""
    r = _load()
    base = tmp_path / "base.json"
    base.write_text(json.dumps(BASELINE))  # pre-sweep: no tpu_sweep

    def run(doc, *flags):
        p = tmp_path / "run.json"
        p.write_text(json.dumps(doc))
        rc = r.main([str(p), f"--baseline={base}", *flags])
        out = capsys.readouterr().out.strip().splitlines()
        return rc, json.loads(out[-1])

    blk = {
        "instances": 8, "cohorts": 2, "engine_compiles": 2,
        "sequential_engine_compiles": 8, "unique": 10572,
        "states": 34716, "sec": 4.2, "sequential_sec": 9.1,
        "parity": "IDENTICAL",
        "per_instance": {
            "paxos1-i0": {"unique": 265, "states": 482},
            "paxos1-lossy-i1": {"unique": 2378, "states": 8197},
        },
    }
    good = {"fresh": True, "tpu_paxos3_states_per_sec": 270000.0,
            "tpu_sweep": blk}
    # absence never trips (pre-sweep artifacts pass untouched)
    rc, v = run({"fresh": True,
                 "tpu_paxos3_states_per_sec": 270000.0}, "--sweep")
    assert rc == 0 and v["sweep"]["ok"] is True
    assert v["sweep"]["present"] is False
    assert v["sweep"]["baseline_present"] is False
    # a well-formed leg passes and reports the amortization
    rc, v = run(good, "--sweep")
    assert rc == 0 and v["sweep"]["ok"] is True
    assert v["sweep"]["amortization"]["engine_compiles"] == 2
    # a crashed leg trips
    rc, v = run({"fresh": True, "tpu_paxos3_states_per_sec": 270000.0,
                 "tpu_sweep_error": "AssertionError: drift"}, "--sweep")
    assert rc == 1 and v["sweep"]["ok"] is False
    # parity drift trips
    bad = json.loads(json.dumps(blk))
    bad["parity"] = "DRIFT"
    rc, v = run({**good, "tpu_sweep": bad}, "--sweep")
    assert rc == 1 and any(
        "parity" in p for p in v["sweep"]["problems"]
    )
    # per-instance compiles (no amortization) trip
    bad = json.loads(json.dumps(blk))
    bad["engine_compiles"] = 8
    rc, v = run({**good, "tpu_sweep": bad}, "--sweep")
    assert rc == 1 and v["sweep"]["ok"] is False
    # malformed/corrupt blocks produce a verdict, not a crash
    for garbage in ("nope", {"instances": "x"}, {"per_instance": []}):
        rc, v = run({**good, "tpu_sweep": garbage}, "--sweep")
        assert rc == 1 and v["sweep"]["ok"] is False
    # stale artifacts still exit 2; --allow-stale reports without gating
    rc, v = run({"fresh": False, "tpu_sweep": blk}, "--sweep")
    assert rc == 2
    rc, v = run({"fresh": False,
                 "tpu_paxos3_states_per_sec": 266699.0,
                 "tpu_sweep": blk},
                "--sweep", "--allow-stale")
    assert rc == 0


def test_diff_section_gates_fresh_runs_only(tmp_path, capsys):
    """--diff: the contract-aware report diff (telemetry/diff.py).
    Engages only when BOTH run and baseline embed a tpu_paxos3_report —
    stale artifacts and pre-registry baselines never trip; a matching
    pair passes; drifted counts under a count-identical contract fail;
    incomparable pairs (prefix run vs stored full enumeration) are
    disclosed and skipped."""
    r = _load()
    base = tmp_path / "base.json"
    base.write_text(json.dumps(BASELINE))  # pre-registry: no report

    def run(doc, *flags):
        p = tmp_path / "run.json"
        p.write_text(json.dumps(doc))
        rc = r.main([str(p), f"--baseline={base}", *flags])
        out = capsys.readouterr().out.strip().splitlines()
        return rc, json.loads(out[-1])

    cfg = {
        "model": "PaxosModel", "instance": {"sig": "abc", "target": None},
        "engine": "wavefront", "encoding": None,
        "flags": {"por": False}, "device": "cpu", "git_rev": "deadbeef",
        "key": "k1",
    }
    rep = {
        "v": 1, "model": "PaxosModel", "engine": "wavefront",
        "config": cfg,
        "totals": {"states": 4_814_218, "unique": 1_194_428,
                   "max_depth": 26, "done": True},
        "properties": [
            {"name": "value chosen", "expectation": "sometimes",
             "discovery": True},
        ],
    }
    good = {"fresh": True, "tpu_paxos3_states_per_sec": 270000.0,
            "tpu_paxos3_report": rep}
    # pre-registry baseline (no embedded report) never trips
    rc, v = run(good, "--diff")
    assert rc == 0 and v["ok"] is True
    assert v["diff"]["ok"] is True and "skipped" in v["diff"]
    assert v["diff"]["baseline_present"] is False
    # matching pair -> IDENTICAL, ok
    base.write_text(json.dumps({**BASELINE, "tpu_paxos3_report": rep}))
    rc, v = run(good, "--diff")
    assert rc == 0 and v["diff"]["verdict"] == "IDENTICAL"
    # drifted counts under a count-identical contract -> exit 1 with the
    # violation named
    drifted = json.loads(json.dumps(rep))
    drifted["totals"]["unique"] -= 7
    rc, v = run({**good, "tpu_paxos3_report": drifted}, "--diff")
    assert rc == 1 and v["diff"]["verdict"] == "DIVERGENT"
    assert any(x["rule"] == "counts_must_match"
               for x in v["diff"]["violations"])
    # incomparable (prefix run: different instance target) -> disclosed,
    # skipped, rc 0
    prefix = json.loads(json.dumps(rep))
    prefix["config"]["instance"]["target"] = 4000
    prefix["totals"]["unique"] = 4000
    prefix["totals"]["states"] = 16000
    rc, v = run({**good, "tpu_paxos3_report": prefix}, "--diff")
    assert rc == 0 and v["diff"]["ok"] is True
    assert "skipped" in v["diff"]
    assert v["diff"]["contract"] == "incomparable"
    # staleness still exits 2 regardless
    rc, v = run({"fresh": False, "tpu_paxos3_report": rep}, "--diff")
    assert rc == 2
    # --allow-stale: reported, never gated
    rc, v = run({"fresh": False, "tpu_paxos3_report": drifted},
                "--diff", "--allow-stale")
    assert rc == 0 and v["diff"]["verdict"] == "DIVERGENT"


def test_fleet_section_gates_fresh_runs_only(tmp_path, capsys):
    """--fleet: the multi-tenant scheduler leg (docs/fleet.md).
    Flag-gated like --spill/--mxu/--sweep: absence (stale artifacts,
    pre-fleet baselines) never trips; a present-but-crashed,
    parity-breaking, incomplete, malformed, or unamortized leg trips
    fresh runs only."""
    r = _load()
    base = tmp_path / "base.json"
    base.write_text(json.dumps(BASELINE))  # pre-fleet: no tpu_fleet

    def run(doc, *flags):
        p = tmp_path / "run.json"
        p.write_text(json.dumps(doc))
        rc = r.main([str(p), f"--baseline={base}", *flags])
        out = capsys.readouterr().out.strip().splitlines()
        return rc, json.loads(out[-1])

    blk = {
        "jobs": 4, "slots": 2, "completed": 4, "preemptions": 0,
        "engine_compiles": 2, "sequential_engine_compiles": 4,
        "packed": 3, "states": 11696, "sec": 6.0,
        "sequential_sec": 14.0, "parity": "IDENTICAL",
    }
    good = {"fresh": True, "tpu_paxos3_states_per_sec": 270000.0,
            "tpu_fleet": blk}
    # absence never trips (pre-fleet artifacts pass untouched)
    rc, v = run({"fresh": True,
                 "tpu_paxos3_states_per_sec": 270000.0}, "--fleet")
    assert rc == 0 and v["fleet"]["ok"] is True
    assert v["fleet"]["present"] is False
    assert v["fleet"]["baseline_present"] is False
    # a well-formed leg passes and reports the amortization
    rc, v = run(good, "--fleet")
    assert rc == 0 and v["fleet"]["ok"] is True
    assert v["fleet"]["amortization"]["engine_compiles"] == 2
    # a crashed leg trips
    rc, v = run({"fresh": True, "tpu_paxos3_states_per_sec": 270000.0,
                 "tpu_fleet_error": "AssertionError: drift"}, "--fleet")
    assert rc == 1 and v["fleet"]["ok"] is False
    # parity drift trips
    bad = json.loads(json.dumps(blk))
    bad["parity"] = "DRIFT"
    rc, v = run({**good, "tpu_fleet": bad}, "--fleet")
    assert rc == 1 and any(
        "parity" in p for p in v["fleet"]["problems"]
    )
    # an unfinished tenant trips (completed != jobs)
    bad = json.loads(json.dumps(blk))
    bad["completed"] = 3
    rc, v = run({**good, "tpu_fleet": bad}, "--fleet")
    assert rc == 1 and any(
        "completed" in p for p in v["fleet"]["problems"]
    )
    # packed cohorts without compile amortization trip
    bad = json.loads(json.dumps(blk))
    bad["engine_compiles"] = 4
    rc, v = run({**good, "tpu_fleet": bad}, "--fleet")
    assert rc == 1 and any(
        "amortization" in p for p in v["fleet"]["problems"]
    )
    # an unpacked fleet owes no amortization
    solo = json.loads(json.dumps(blk))
    solo["packed"] = 0
    solo["engine_compiles"] = 4
    rc, v = run({**good, "tpu_fleet": solo}, "--fleet")
    assert rc == 0 and v["fleet"]["ok"] is True
    # malformed/corrupt blocks produce a verdict, not a crash
    for garbage in ("nope", {"jobs": "x"}, {"preemptions": -1}):
        rc, v = run({**good, "tpu_fleet": garbage}, "--fleet")
        assert rc == 1 and v["fleet"]["ok"] is False
    # stale artifacts still exit 2; --allow-stale reports without gating
    rc, v = run({"fresh": False, "tpu_fleet": blk}, "--fleet")
    assert rc == 2
    rc, v = run({"fresh": False,
                 "tpu_paxos3_states_per_sec": 266699.0,
                 "tpu_fleet": blk},
                "--fleet", "--allow-stale")
    assert rc == 0


def test_live_section_gates_fresh_runs_only(tmp_path, capsys):
    """--live: the live-observability leg (docs/observability.md).
    Flag-gated like --fleet: absence never trips; a present leg must
    carry count parity, a bounded sampling overhead, a published bus,
    and a terminal heartbeat."""
    r = _load()
    base = tmp_path / "base.json"
    base.write_text(json.dumps(BASELINE))  # pre-observability baseline

    def run(doc, *flags):
        p = tmp_path / "run.json"
        p.write_text(json.dumps(doc))
        rc = r.main([str(p), f"--baseline={base}", *flags])
        out = capsys.readouterr().out.strip().splitlines()
        return rc, json.loads(out[-1])

    blk = {
        "model": "paxos-3", "unique": 34914, "states": 156408,
        "parity": "IDENTICAL", "base_sec": 4.1, "live_sec": 4.3,
        "overhead_frac": 0.049,
        "families": ["stateright_states_total",
                     "stateright_unique_states_total"],
        "heartbeat": {"verdict": "done", "status": "done",
                      "states": 156408, "unique": 34914, "steps": 61},
    }
    good = {"fresh": True, "tpu_paxos3_states_per_sec": 270000.0,
            "tpu_live": blk}
    # absence never trips (pre-observability artifacts pass untouched)
    rc, v = run({"fresh": True,
                 "tpu_paxos3_states_per_sec": 270000.0}, "--live")
    assert rc == 0 and v["live"]["ok"] is True
    assert v["live"]["present"] is False
    assert v["live"]["baseline_present"] is False
    # a well-formed leg passes and reports the overhead it measured
    rc, v = run(good, "--live")
    assert rc == 0 and v["live"]["ok"] is True
    assert v["live"]["overhead_frac"] == 0.049
    # a crashed leg trips
    rc, v = run({"fresh": True, "tpu_paxos3_states_per_sec": 270000.0,
                 "tpu_live_error": "RuntimeError: server died"}, "--live")
    assert rc == 1 and v["live"]["ok"] is False
    # parity drift trips — a bus that changes the run it observes
    bad = json.loads(json.dumps(blk))
    bad["parity"] = "DRIFT"
    rc, v = run({**good, "tpu_live": bad}, "--live")
    assert rc == 1 and any("parity" in p for p in v["live"]["problems"])
    # unbounded sampling overhead trips
    bad = json.loads(json.dumps(blk))
    bad["overhead_frac"] = 0.8
    rc, v = run({**good, "tpu_live": bad}, "--live")
    assert rc == 1 and any(
        "overhead_frac" in p for p in v["live"]["problems"]
    )
    # a bus that never published trips
    bad = json.loads(json.dumps(blk))
    bad["families"] = []
    rc, v = run({**good, "tpu_live": bad}, "--live")
    assert rc == 1 and any(
        "stateright_states_total" in p for p in v["live"]["problems"]
    )
    # a missing terminal heartbeat trips
    bad = json.loads(json.dumps(blk))
    bad["heartbeat"] = {"verdict": "dead"}
    rc, v = run({**good, "tpu_live": bad}, "--live")
    assert rc == 1 and any(
        "heartbeat" in p for p in v["live"]["problems"]
    )
    # malformed/corrupt blocks produce a verdict, not a crash
    for garbage in ("nope", {"unique": "x"}, {"states": -5}):
        rc, v = run({**good, "tpu_live": garbage}, "--live")
        assert rc == 1 and v["live"]["ok"] is False
    # stale artifacts still exit 2; --allow-stale reports without gating
    rc, v = run({"fresh": False, "tpu_live": blk}, "--live")
    assert rc == 2
    rc, v = run({"fresh": False,
                 "tpu_paxos3_states_per_sec": 266699.0,
                 "tpu_live": blk},
                "--live", "--allow-stale")
    assert rc == 0
