"""Paxos tensor-twin equivalence + engine parity (the benchmark model).

Same obligations as the 2pc twin (``test_tensor_models.py``) on the much
harder encoding: actor states + multiset network + linearizability-tester
history in fixed-width rows (SURVEY §7.1).  Pinned parity: 16,668 unique
states @ 2 clients / 3 servers (reference ``examples/paxos.rs:291,311``).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from stateright_tpu.fingerprint import hash_words
from stateright_tpu.models.paxos import paxos_model


def crawl_and_check(m, tm, max_levels=None):
    """BFS the object form, asserting per state: encode/decode round-trip,
    device/host fingerprint agreement, and successor-set equality."""
    seen = {}
    frontier = list(m.init_states())
    for s in frontier:
        seen[m.fingerprint_state(s)] = s
    level = 0
    while frontier and (max_levels is None or level < max_levels):
        rows = np.asarray([tm.encode_state(s) for s in frontier], np.uint64)
        succ, valid = tm.step_rows(jnp.asarray(rows))
        succ, valid = np.asarray(succ), np.asarray(valid)
        masks = np.asarray(tm.property_masks(jnp.asarray(rows)))
        nxt = []
        for i, s in enumerate(frontier):
            assert tm.decode_state(rows[i]) == s
            assert m.fingerprint_state(s) == hash_words(
                int(w) for w in rows[i]
            )
            obj_succs = sorted(
                tuple(tm.encode_state(t)) for t in m.next_states(s)
            )
            dev_succs = sorted(
                tuple(int(w) for w in succ[i, a])
                for a in range(tm.max_actions)
                if valid[i, a]
            )
            assert dev_succs == obj_succs, (level, i)
            for p, prop in enumerate(m.properties()):
                assert bool(masks[i, p]) == bool(prop.condition(m, s)), (
                    prop.name,
                    s,
                )
            for t in m.next_states(s):
                fp = m.fingerprint_state(t)
                if fp not in seen:
                    seen[fp] = t
                    nxt.append(t)
        frontier = nxt
        level += 1
    return seen


@pytest.mark.slow
def test_paxos1_full_equivalence():
    m = paxos_model(1, 3)
    tm = m.tensor_model()
    seen = crawl_and_check(m, tm)
    assert len(seen) == 265


@pytest.mark.slow
def test_paxos2_prefix_equivalence():
    # First 6 wavefronts of the 2-client system: covers puts, prepare/prepared
    # quorums, accepts, and the first decisions.
    m = paxos_model(2, 3)
    tm = m.tensor_model()
    crawl_and_check(m, tm, max_levels=6)


# re-tiered fast->slow (PR 2): the fast tier blew the 870s tier-1 budget
@pytest.mark.slow
def test_paxos2_tpu_checker_pinned_count():
    m = paxos_model(2, 3)
    checker = m.checker().spawn_tpu(
        sync=True, capacity=1 << 16, frontier_capacity=1 << 12
    )
    assert checker.unique_state_count() == 16668
    assert set(checker.discoveries()) == {"value chosen"}
    # the "value chosen" example is a real witness
    path = checker.discovery("value chosen")
    assert m.property_by_name("value chosen").condition(m, path.final_state())
    checker.assert_properties()


@pytest.mark.medium
def test_paxos2_sharded_matches():
    m = paxos_model(2, 3)
    checker = m.checker().spawn_tpu(
        devices=8, sync=True, capacity=1 << 16, frontier_capacity=1 << 12
    )
    assert checker.unique_state_count() == 16668
    assert set(checker.discoveries()) == {"value chosen"}


@pytest.mark.slow
def test_paxos2_cpu_bfs_agrees():
    # CPU oracle on the same fingerprint function (row encoding)
    m = paxos_model(2, 3)
    cpu = m.checker().spawn_bfs().join()
    assert cpu.unique_state_count() == 16668
    assert set(cpu.discoveries()) == {"value chosen"}


def test_paxos_tensor_eligibility():
    from stateright_tpu.actor import Network
    from stateright_tpu.models.paxos_tensor import PaxosTensor
    from stateright_tpu.parallel.actor_compiler import CompiledActorTensor

    # benchmark shape -> hand-tuned twin; other shapes -> mechanical compiler
    assert isinstance(paxos_model(2, 3).tensor_model(), PaxosTensor)
    assert isinstance(paxos_model(2, 4).tensor_model(), CompiledActorTensor)
    # ordered networks go through the compiler's rank-in-slot FIFO encoding
    tm = paxos_model(2, 3, Network.new_ordered()).tensor_model()
    assert isinstance(tm, CompiledActorTensor) and tm.ordered
    # duplicating networks make ballots unbounded -> no twin (structural CPU)
    assert (
        paxos_model(2, 3, Network.new_unordered_duplicating()).tensor_model()
        is None
    )


def test_paxos_compiled_4_servers_matches_cpu():
    """The mechanically compiled twin (4 servers is outside the hand twin)
    agrees with the CPU oracle end to end."""
    m = paxos_model(1, 4)
    cpu = m.checker().spawn_bfs().join()
    tpu = m.checker().spawn_tpu(
        sync=True, capacity=1 << 14, frontier_capacity=1 << 10
    )
    assert cpu.unique_state_count() == tpu.unique_state_count() == 1169
    assert set(cpu.discoveries()) == set(tpu.discoveries())


@pytest.mark.slow
def test_paxos3_prefix_equivalence():
    # C=3 exercises the closure linearizability verdict and the full
    # 2C-bit snapshot encoding; crawl_and_check validates property_masks
    # directly against prop.condition on real C=3 rows (the C=2 prefix test
    # cannot reach C=3-specific encoding bugs).
    m = paxos_model(3, 3)
    tm = m.tensor_model()
    crawl_and_check(m, tm, max_levels=5)


@pytest.mark.slow
def test_paxos4_prefix_equivalence():
    # C=4 is past the old (2C)! permutation cap: exercises the closure
    # verdict and the C-parameterized field widths on real rows.
    m = paxos_model(4, 3)
    tm = m.tensor_model()
    crawl_and_check(m, tm, max_levels=4)


@pytest.mark.slow
def test_paxos6_prefix_equivalence():
    # the reference bench config (``paxos check 6``, bench.sh): a shallow
    # crawl proving the widened encoding + closure verdict hold at C=6.
    m = paxos_model(6, 3)
    tm = m.tensor_model()
    crawl_and_check(m, tm, max_levels=2)


# re-tiered fast->slow (PR 2): the fast tier blew the 870s tier-1 budget
@pytest.mark.slow
def test_paxos3_twin_equivalence_bounded():
    """FAST-TIER pin of the flagship config's twin (the driver benchmark is
    ``paxos check 3``): a bounded per-level crawl asserting encode/decode
    round-trips, host=device fingerprints, successor-set equality, and
    property-mask agreement on real C=3 rows — so the per-push tier fails
    if the paxos-3 twin drifts, even when the full 1,194,428-state run
    (slow tier / bench) can't validate it."""
    m = paxos_model(3, 3)
    tm = m.tensor_model()
    seen = crawl_and_check(m, tm, max_levels=5)
    assert len(seen) > 100  # depth-5 reachable set, all states cross-checked


# re-tiered fast->slow (PR 2): the fast tier blew the 870s tier-1 budget
@pytest.mark.slow
def test_paxos3_tpu_vs_cpu_sample():
    """3-client config (the driver benchmark): spot-check engine agreement on
    a bounded prefix via target_state_count."""
    m = paxos_model(3, 3)
    t = m.checker().target_states(3000).spawn_tpu(sync=True)
    assert t.unique_state_count() >= 3000
    # property kernel sanity on visited rows: no linearizability violation
    assert "linearizable" not in t.discoveries()


@pytest.mark.slow
def test_paxos6_device_engine_prefix():
    """The reference bench config (paxos check 6) runs end-to-end on the
    device engine: C=6 twin compiles, expands, dedups and evaluates the
    closure linearizability verdict with no slot-overflow rows and no false
    violations on a bounded prefix."""
    from stateright_tpu.parallel import wavefront as wf

    m = paxos_model(6, 3)
    c = m.checker().target_states(4000).spawn_tpu(
        sync=True, capacity=1 << 16, frontier_capacity=1 << 9
    )
    assert c.unique_state_count() >= 4000
    assert "linearizable" not in c.discoveries()
    # every enqueued row is clean: the network never overflowed its slots
    tm = c.tensor
    rows = np.asarray(c._final_carry[wf._QROWS])
    tail = int(np.asarray(c._final_carry[wf._TAIL]))
    for r in rows[:tail:37]:  # stride-sample the queue
        assert tm.pk.unpack(r[: tm.pw])["overflow"] == 0


@pytest.mark.slow
def test_paxos3_full_space_device_vs_cpu():
    """THE flagship parity result: the COMPLETE paxos-3 space — 1,194,428
    unique states, the driver benchmark's primary config run to exhaustion
    — enumerated by both the CPU oracle and the device engine with equal
    counts and discoveries.  (The bench pins the device side of this number
    every run; this test pins it against the object-form oracle.)"""
    m = paxos_model(3, 3)
    tpu = m.checker().spawn_tpu(
        sync=True, capacity=1 << 23, queue_capacity=1 << 21, batch=2048
    )
    assert tpu.unique_state_count() == 1_194_428
    cpu = m.checker().spawn_bfs().join()
    assert cpu.unique_state_count() == 1_194_428
    assert cpu.state_count() == tpu.state_count()
    assert set(cpu.discoveries()) == set(tpu.discoveries()) == {"value chosen"}
