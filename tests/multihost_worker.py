"""Worker for the multi-controller (multi-host SPMD) sharded-engine test.

Launched as ``python multihost_worker.py <process_id> <num_processes>
<coordinator_port>`` by ``tests/test_multihost.py``.  Each process owns 4
virtual CPU devices; together they form one 8-device global mesh — the
same controller topology as a real multi-host TPU pod slice over ICI/DCN
(one process per host, `jax.distributed` for the control plane, XLA
collectives for data).

Every process runs the identical SPMD program: the sharded wavefront
engine's host loop reads only replicated scalars, so all controllers make
the same decisions in lockstep, and the final table is all-gathered so
each process reconstructs the same discovery paths locally.
"""

import os
import sys


def main() -> int:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        f"localhost:{port}", num_processes=nproc, process_id=pid
    )
    assert jax.process_count() == nproc
    assert len(jax.devices()) == 4 * nproc, jax.devices()

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    m = TwoPhaseSys(3)
    checker = m.checker().spawn_tpu(
        mesh=None,
        n_devices=4 * nproc,  # the full global mesh, spanning both processes
        sync=True,
        capacity=1 << 13,
        frontier_capacity=1 << 9,
    )
    assert checker.unique_state_count() == 288, checker.unique_state_count()
    discs = checker.discoveries()
    assert set(discs) == {"abort agreement", "commit agreement"}, discs
    # each controller reconstructs full paths from its all-gathered table
    for name, path in discs.items():
        checker.assert_discovery(name, list(path.actions()))
    print(f"multihost-worker-ok p{pid}: unique=288 discoveries={sorted(discs)}")

    # LOCKSTEP GROWTH under multi-controller SPMD: capacities sized to
    # overflow mid-run, so every controller must execute the same
    # per-shard growth at the same step boundary and the run must still
    # land the pinned count with monotone unique counters across events.
    m2 = TwoPhaseSys(3)
    grower = m2.checker().spawn_tpu(
        mesh=None,
        n_devices=4 * nproc,
        sync=True,
        capacity=1 << 7,
        frontier_capacity=1 << 5,
    )
    assert grower.unique_state_count() == 288, grower.unique_state_count()
    assert len(grower.growth_events) >= 1, grower.growth_events
    uniq = [u for _, u in grower.growth_events]
    assert uniq == sorted(uniq) and all(u >= 0 for u in uniq), uniq
    assert set(grower.discoveries()) == {"abort agreement", "commit agreement"}
    print(
        f"multihost-growth-ok p{pid}: unique=288 "
        f"growth_events={len(grower.growth_events)} monotone={uniq}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
