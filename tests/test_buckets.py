"""Bucketized visited-set unit tests: the one-shot insert must agree with a
straightforward host-side set on arbitrary candidate streams (duplicates
in-batch, duplicates vs the table, EMPTY lanes, bucket collisions)."""

import numpy as np
import pytest

import jax.numpy as jnp

from stateright_tpu.ops.buckets import (
    SLOTS,
    bucket_insert,
    bucket_of,
    host_bucket_rehash,
)
from stateright_tpu.ops.hashing import EMPTY, mix64_np


def np_u64(x):
    return np.asarray(x, np.uint64)


def fresh(nbuckets):
    return (
        jnp.full((nbuckets * SLOTS,), EMPTY, jnp.uint64),
        jnp.zeros((nbuckets * SLOTS,), jnp.uint64),
    )


def insert(state, fps, payloads=None, window=8, compact=None):
    tfp, tpl = state
    fps = jnp.asarray(np_u64(fps))
    if payloads is None:
        payloads = fps ^ jnp.uint64(7)
    else:
        payloads = jnp.asarray(np_u64(payloads))
    tfp, tpl, sel, n_new, overflow, cand_overflow = bucket_insert(
        tfp, tpl, fps, payloads, window=window, compact=compact
    )
    inserted = np.asarray(fps)[np.asarray(sel)][: int(n_new)]
    return (
        (tfp, tpl),
        inserted,
        int(n_new),
        bool(overflow) or bool(cand_overflow),
    )


def table_contents(state):
    tfp, tpl = state
    tfp, tpl = np.asarray(tfp), np.asarray(tpl)
    occ = tfp != EMPTY
    return dict(zip(tfp[occ].tolist(), tpl[occ].tolist()))


# re-tiered fast->slow (PR 2): the fast tier blew the 870s tier-1 budget
@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_random_stream_matches_host_set(seed):
    rng = np.random.default_rng(seed)
    nbuckets = 64
    state = fresh(nbuckets)
    seen = {}
    for _ in range(20):
        m = int(rng.integers(1, 50))
        fps = rng.integers(1, 1 << 40, m).astype(np.uint64)
        # salt in EMPTY lanes and in-batch duplicates
        fps[rng.random(m) < 0.2] = EMPTY
        if m > 3:
            fps[0] = fps[m // 2]
        pay = rng.integers(1, 1 << 40, m).astype(np.uint64)
        state, inserted, n_new, overflow = insert(state, fps, pay)
        assert not overflow
        expected_new = []
        batch_seen = set()
        for f, p in zip(fps.tolist(), pay.tolist()):
            if f == int(EMPTY) or f in seen or f in batch_seen:
                continue
            batch_seen.add(f)
            expected_new.append(f)
            seen[f] = None  # payload: first writer in *sorted* order wins
        assert n_new == len(expected_new)
        assert sorted(inserted.tolist()) == sorted(expected_new)
    contents = table_contents(state)
    assert sorted(contents) == sorted(int(k) for k in seen)


def test_payloads_stored_for_novel_entries():
    state = fresh(16)
    state, _, n_new, _ = insert(state, [10, 20, 30], [1, 2, 3])
    assert n_new == 3
    assert table_contents(state) == {10: 1, 20: 2, 30: 3}
    # duplicates keep the original payload
    state, _, n_new, _ = insert(state, [20, 40], [99, 4])
    assert n_new == 1
    assert table_contents(state) == {10: 1, 20: 2, 30: 3, 40: 4}


def test_bucket_overflow_is_clean():
    nbuckets = 4
    # SLOTS+1 distinct fps the mix64 derivation places in the SAME bucket
    fps, x = [], 1
    while len(fps) < SLOTS + 1:
        if int(bucket_of(np.uint64(x), nbuckets)) == 0:
            fps.append(x)
        x += 1
    state = fresh(nbuckets)
    state, _, n_new, overflow = insert(state, fps)
    assert overflow
    # nothing was written: the table is untouched
    assert table_contents(state) == {}


def test_window_chunking_covers_large_batches():
    state = fresh(1 << 10)
    fps = np.arange(1, 401, dtype=np.uint64) * 97
    state, inserted, n_new, overflow = insert(state, fps, window=32)
    assert not overflow and n_new == 400
    assert sorted(table_contents(state)) == sorted(fps.tolist())


@pytest.mark.parametrize(
    "seed", [pytest.param(0, marks=pytest.mark.medium), 1]
)
def test_compacted_stream_matches_host_set(seed):
    """``compact=CB`` (the engines' padded-batch fast path) must agree with
    the host set exactly, including EMPTY-heavy lanes, in-batch duplicates,
    and duplicates vs the table."""
    rng = np.random.default_rng(seed)
    state = fresh(64)
    seen = set()
    for _ in range(12):
        m = int(rng.integers(8, 80))
        fps = rng.integers(1, 1 << 40, m).astype(np.uint64)
        fps[rng.random(m) < 0.7] = EMPTY  # mostly padding, like a batch
        if m > 3:
            fps[0] = fps[m // 2]
        state, inserted, n_new, overflow = insert(
            state, fps, window=8, compact=32
        )
        assert not overflow
        expected = [
            f for i, f in enumerate(fps.tolist())
            if f != int(EMPTY) and f not in seen
            and f not in set(fps[:i].tolist())
        ]
        assert n_new == len(expected)
        assert sorted(inserted.tolist()) == sorted(expected)
        seen.update(expected)
    assert sorted(table_contents(state)) == sorted(seen)


def test_cand_overflow_writes_nothing():
    """More valid candidates than the compaction budget: atomically refuse
    (nothing written, n_new 0) so the caller can grow + replay."""
    state = fresh(1 << 6)
    fps = np.arange(1, 41, dtype=np.uint64) * 97  # 40 valid > compact=16
    state, inserted, n_new, overflow = insert(
        state, fps, window=8, compact=16
    )
    assert overflow and n_new == 0 and len(inserted) == 0
    assert table_contents(state) == {}
    # and the same stream succeeds once the budget covers it
    state, _, n_new, overflow = insert(state, fps, window=8, compact=64)
    assert not overflow and n_new == 40


def test_compacted_generation_order_is_preserved():
    """generation_order=True with compaction: sel[:n_new] lists inserted
    candidates by ORIGINAL batch position (symmetry runs key on it)."""
    state = fresh(64)
    fps = np.array(
        [int(EMPTY), 901, int(EMPTY), 17, 445, int(EMPTY), 23], np.uint64
    )
    tfp, tpl = state
    tfp, tpl, sel, n_new, ofl, cofl = bucket_insert(
        tfp,
        tpl,
        jnp.asarray(fps),
        jnp.asarray(fps),
        window=4,
        generation_order=True,
        compact=4,
    )
    assert not bool(ofl) and not bool(cofl) and int(n_new) == 4
    assert np.asarray(sel)[:4].tolist() == [1, 3, 4, 6]


def test_host_rehash_round_trip():
    state = fresh(16)  # max per-bucket load for this stream is 13 < SLOTS
    fps = (np.arange(1, 200, dtype=np.uint64) * 1315423911) & np.uint64(
        (1 << 50) - 1
    )
    fps = np.unique(fps)
    state, _, n_new, overflow = insert(state, fps, window=64)
    assert not overflow
    before = table_contents(state)
    tfp, tpl = host_bucket_rehash(
        np.asarray(state[0]), np.asarray(state[1]), 32
    )
    occ = tfp != EMPTY
    after = dict(zip(tfp[occ].tolist(), tpl[occ].tolist()))
    assert after == before
    # slots fill densely per bucket (occupancy implicit in the table)
    lines = tfp.reshape(32, SLOTS) != EMPTY
    filled = lines.sum(axis=1)
    assert all(lines[b, :filled[b]].all() for b in range(32))
    # and the rehashed table keeps accepting inserts consistently
    state2 = (jnp.asarray(tfp), jnp.asarray(tpl))
    state2, _, n_new2, _ = insert(state2, [123456789, int(fps[0])])
    assert n_new2 == 1


# ---------------------------------------------------------------------------
# the bucket-mix fix (ROADMAP table-size anomaly): avalanche + chi-square
# ---------------------------------------------------------------------------


def test_mix64_avalanche():
    """Flipping any single input bit must flip ~half the output bits of the
    remix the bucket derivation reads (mean avalanche weight near 32, and
    every input bit must propagate into the TOP bits, where the bucket
    lives — the raw low-bit derivation failed exactly this)."""
    rng = np.random.default_rng(7)
    xs = rng.integers(0, 1 << 63, 256, dtype=np.uint64)
    base = mix64_np(xs)
    top16 = np.uint64(0xFFFF_0000_0000_0000)
    for bit in range(64):
        flipped = mix64_np(xs ^ np.uint64(1 << bit))
        diff = base ^ flipped
        # mean bits flipped across samples, whole word and top-16 slice
        weights = np.array([bin(int(d)).count("1") for d in diff])
        assert 24 <= weights.mean() <= 40, (bit, weights.mean())
        top = np.array([bin(int(d & top16)).count("1") for d in diff])
        assert top.mean() >= 4, (bit, top.mean())  # ~8 expected of 16


@pytest.mark.parametrize(
    "stream",
    [
        np.arange(1, (1 << 14) + 1, dtype=np.uint64),  # dense counter
        np.arange(1, (1 << 14) + 1, dtype=np.uint64) * np.uint64(97),
        (np.arange(1, (1 << 14) + 1, dtype=np.uint64) << np.uint64(12)),
    ],
    ids=["counter", "strided", "shifted"],
)
def test_bucket_chi_square_on_structured_streams(stream):
    """The bucket derivation must spread STRUCTURED fingerprint streams
    uniformly: chi-square over 256 buckets at 64 expected per bucket.  The
    pre-fix low-bit derivation fails all three of these catastrophically
    (the dense counter puts everything in 256 consecutive buckets of the
    fingerprint's low bits)."""
    nbuckets = 256
    counts = np.bincount(bucket_of(stream, nbuckets), minlength=nbuckets)
    expect = stream.size / nbuckets
    chi2 = float(((counts - expect) ** 2 / expect).sum())
    # df = 255: mean 255, sd ~22.6; 400 is a > 6-sigma ceiling
    assert chi2 < 400.0, chi2
    # and no bucket anywhere near a SLOTS-deep pile-up at this load
    assert counts.max() < 2 * expect


# -- intra-window pre-dedup (ops/buckets.window_unique) -----------------------


def test_window_unique_keeps_first_occurrence_and_empty_lanes():
    from stateright_tpu.ops.buckets import window_unique

    fps = np_u64([5, EMPTY, 9, 5, 7, 9, 5, EMPTY])
    out = np.asarray(window_unique(jnp.asarray(fps)))
    # first occurrence (lowest lane) survives; later copies become EMPTY
    assert out.tolist() == np_u64(
        [5, EMPTY, 9, EMPTY, 7, EMPTY, EMPTY, EMPTY]
    ).tolist()


@pytest.mark.parametrize("seed", [0, 1])
def test_window_unique_then_insert_is_bit_identical(seed):
    """The equivalence contract behind the engines' prededup flag: running
    ``bucket_insert`` on a pre-deduped window must produce the identical
    table, payloads, n_new, and selected prefix — in BOTH compaction
    orders — because the filter keeps exactly the lane the insert's
    stable sort would have kept."""
    from stateright_tpu.ops.buckets import window_unique

    rng = np.random.default_rng(seed)
    fps = rng.integers(1, 50, size=256, dtype=np.uint64)  # heavy duplication
    fps[rng.random(256) < 0.3] = np.uint64(EMPTY)
    payloads = np_u64(np.arange(1, 257))
    for generation_order in (False, True):
        for compact in (None, 224):  # budget sized so neither side overflows
            tfp0, tpl0 = fresh(16)
            plain = bucket_insert(
                tfp0, tpl0, jnp.asarray(fps), jnp.asarray(payloads),
                window=32, generation_order=generation_order,
                compact=compact,
            )
            tfp1, tpl1 = fresh(16)
            dedup = bucket_insert(
                tfp1, tpl1, window_unique(jnp.asarray(fps)),
                jnp.asarray(payloads), window=32,
                generation_order=generation_order, compact=compact,
            )
            assert not bool(plain[5]) and not bool(dedup[5])  # no cand ovfl
            assert int(plain[3]) == int(dedup[3])  # n_new
            n = int(plain[3])
            assert np.array_equal(np.asarray(plain[0]), np.asarray(dedup[0]))
            assert np.array_equal(np.asarray(plain[1]), np.asarray(dedup[1]))
            assert np.array_equal(
                np.asarray(plain[2])[:n], np.asarray(dedup[2])[:n]
            )  # the consumed sel prefix
            assert not bool(plain[4]) and not bool(dedup[4])


def test_window_unique_shrinks_candidate_pressure():
    """The point of the filter: a duplicate-heavy window that cand-
    overflows a tight compaction budget FITS once pre-deduped (fewer
    growth/replay events on the engines, never more)."""
    from stateright_tpu.ops.buckets import window_unique

    rng = np.random.default_rng(3)
    fps = rng.integers(1, 33, size=256, dtype=np.uint64)  # ~32 unique
    payloads = np_u64(np.arange(1, 257))
    tfp, tpl = fresh(16)
    plain = bucket_insert(
        tfp, tpl, jnp.asarray(fps), jnp.asarray(payloads),
        window=32, compact=64,
    )
    assert bool(plain[5]) and int(plain[3]) == 0  # overflowed, wrote nothing
    tfp, tpl = fresh(16)
    dedup = bucket_insert(
        tfp, tpl, window_unique(jnp.asarray(fps)), jnp.asarray(payloads),
        window=32, compact=64,
    )
    assert not bool(dedup[5]) and int(dedup[3]) > 0


# -- BLEST one-hot membership probe (ops/mxu.py; docs/roofline.md) ------------


def _insert_all(state, fps, payloads, probe_dot, window=8):
    tfp, tpl = state
    return bucket_insert(
        tfp, tpl, jnp.asarray(np_u64(fps)), jnp.asarray(np_u64(payloads)),
        window=window, probe_dot=probe_dot,
    )


# all seeds ride the daily tiers: a 20-window random-stream sweep is
# integration-shaped fuzzing, not a fast-tier unit pin (870s budget)
@pytest.mark.parametrize(
    "seed",
    [pytest.param(0, marks=pytest.mark.medium),
     pytest.param(1, marks=pytest.mark.slow),
     pytest.param(2, marks=pytest.mark.slow)],
)
def test_blest_probe_matches_bucket_insert_on_random_streams(seed):
    """``probe_dot=True`` must be a pure op-class recast: every output of
    ``bucket_insert`` — table fingerprints, payloads, sel, n_new, both
    overflow flags — bit-identical across 20 random windows salted with
    EMPTY lanes and in-batch duplicates, tables evolved independently."""
    rng = np.random.default_rng(seed)
    nbuckets = 32
    plain, dotted = fresh(nbuckets), fresh(nbuckets)
    for _ in range(20):
        m = int(rng.integers(1, 48))
        fps = rng.integers(1, 1 << 40, m).astype(np.uint64)
        fps[rng.random(m) < 0.25] = EMPTY
        if m > 3:
            fps[0] = fps[m // 2]  # in-batch duplicate
        pay = rng.integers(1, 1 << 40, m).astype(np.uint64)
        a = _insert_all(plain, fps, pay, probe_dot=False)
        b = _insert_all(dotted, fps, pay, probe_dot=True)
        plain, dotted = (a[0], a[1]), (b[0], b[1])
        assert int(a[3]) == int(b[3])  # n_new
        n = int(a[3])
        assert np.array_equal(
            np.asarray(a[2])[:n], np.asarray(b[2])[:n]
        )  # consumed sel prefix
        assert bool(a[4]) == bool(b[4]) and bool(a[5]) == bool(b[5])
        assert np.array_equal(np.asarray(a[0]), np.asarray(b[0]))
        assert np.array_equal(np.asarray(a[1]), np.asarray(b[1]))


def test_blest_probe_full_bucket_overflow_parity():
    """A bucket driven past SLOTS must overflow identically (flag on and
    off) and leave both tables untouched."""
    nbuckets = 4
    fps, x = [], 1
    while len(fps) < SLOTS + 1:
        if int(bucket_of(np.uint64(x), nbuckets)) == 0:
            fps.append(x)
        x += 1
    pay = list(range(1, len(fps) + 1))
    a = _insert_all(fresh(nbuckets), fps, pay, probe_dot=False)
    b = _insert_all(fresh(nbuckets), fps, pay, probe_dot=True)
    assert bool(a[4]) and bool(b[4])  # both overflow
    assert int(a[3]) == int(b[3]) == 0
    assert table_contents((a[0], a[1])) == table_contents((b[0], b[1])) == {}
    # and a FULL-but-not-overfull bucket still probes exactly
    a = _insert_all(fresh(nbuckets), fps[:SLOTS], pay[:SLOTS], probe_dot=False)
    b = _insert_all(fresh(nbuckets), fps[:SLOTS], pay[:SLOTS], probe_dot=True)
    assert not bool(a[4]) and not bool(b[4])
    assert int(a[3]) == int(b[3]) == SLOTS
    assert np.array_equal(np.asarray(a[0]), np.asarray(b[0]))
    # re-probing the full bucket classifies every candidate a duplicate
    a2 = _insert_all((a[0], a[1]), fps[:SLOTS], pay[:SLOTS], probe_dot=False)
    b2 = _insert_all((b[0], b[1]), fps[:SLOTS], pay[:SLOTS], probe_dot=True)
    assert int(a2[3]) == int(b2[3]) == 0 and not bool(b2[4])


def test_blest_probe_unit_matches_reduction_pair():
    """:func:`ops.mxu.blest_probe` against the reduce_or/reduce_sum pair
    it replaces, on a hand-built line window: EMPTY lanes, full lines,
    absent and present fingerprints."""
    from stateright_tpu.ops.mxu import blest_probe

    E = np.uint64(EMPTY)
    lines = np_u64([
        [E] * SLOTS,                              # empty line
        [7] + [E] * (SLOTS - 1),                  # singleton, hit
        [7] + [E] * (SLOTS - 1),                  # singleton, miss
        list(range(100, 100 + SLOTS)),            # full line, hit at end
        list(range(200, 200 + SLOTS)),            # full line, miss
    ])
    wfp = np_u64([3, 7, 9, 100 + SLOTS - 1, 5])
    p, b = blest_probe(jnp.asarray(lines), jnp.asarray(wfp), EMPTY)
    p, b = np.asarray(p), np.asarray(b)
    exp_p = np.any(lines == wfp[:, None], axis=-1)
    exp_b = np.sum(lines != E, axis=-1).astype(np.int32)
    assert np.array_equal(p, exp_p) and p.tolist() == [
        False, True, False, True, False
    ]
    assert np.array_equal(b, exp_b) and b.tolist() == [
        0, 1, 1, SLOTS, SLOTS
    ]
