"""Compiling ORL-wrapped systems (the round-4 gap: reference
``src/actor/ordered_reliable_link.rs:30-57`` wraps actors with unbounded
sequencers, which the closure cannot enumerate unless the run is bounded).

Two halves: a naturally-bounded ORL system (fixed message script) compiles
through the GENERAL fragment and pins host=device — lossy duplicating
network, resend timers, at-most-once watermarks and all; an unbounded one
(echo loop) fails with a CompileError that names the ORL wrapper's
unbounded fields and points at the recipe doc, and compiles once
``state_bound`` caps them.
"""

import pytest

from stateright_tpu.actor import Actor, ActorModel, Id, Network
from stateright_tpu.actor.device_props import exists_actor, forall_actors
from stateright_tpu.actor.ordered_reliable_link import (
    LinkState,
    OrderedReliableLink,
)
from stateright_tpu.core import Expectation
from stateright_tpu.parallel.actor_compiler import (
    CompileError,
    compile_actor_model,
)
from stateright_tpu.parallel.tensor_model import TensorBackedModel


class _Sender(Actor):
    """Fixed two-message script (reference ``ordered_reliable_link.rs``
    test shape): the whole system is finite without any boundary."""

    def __init__(self, rid):
        self.rid = rid

    def on_start(self, id, out):
        out.send(self.rid, 42)
        out.send(self.rid, 43)
        return ()

    def on_msg(self, id, state, src, msg, out):
        return state + ((src, msg),)


class _Receiver(Actor):
    def on_start(self, id, out):
        return ()

    def on_msg(self, id, state, src, msg, out):
        return state + ((src, msg),)


class _OrlModel(TensorBackedModel, ActorModel):
    def __init__(self, state_bound=None):
        super().__init__(None, None)
        self._sb = state_bound

    def tensor_model(self):
        try:
            return compile_actor_model(self, state_bound=self._sb)
        except (CompileError, ValueError):
            return None


def _received(s):
    return [m for _, m in s.wrapped_state]


def _orl_model(state_bound=None):
    """ORL sender/receiver over a LOSSY DUPLICATING network with factored
    properties — the compiled twin must reproduce at-most-once delivery,
    ordering, resend timers, and the delivered witness."""
    return (
        _OrlModel(state_bound)
        .actor(OrderedReliableLink(_Sender(Id(1))))
        .actor(OrderedReliableLink(_Receiver()))
        .init_network_(Network.new_unordered_duplicating())
        .lossy_network(True)
        .property(
            Expectation.ALWAYS,
            "no redelivery",
            forall_actors(
                lambda i, s: i != 1
                or (_received(s).count(42) < 2 and _received(s).count(43) < 2)
            ),
        )
        .property(
            Expectation.ALWAYS,
            "ordered",
            forall_actors(
                lambda i, s: i != 1 or _received(s) == sorted(_received(s))
            ),
        )
        .property(
            Expectation.SOMETIMES,
            "delivered",
            exists_actor(
                lambda i, s: i == 1
                and s.wrapped_state == ((Id(0), 42), (Id(0), 43))
            ),
        )
    )


def test_orl_compiles_and_pins_host_device():
    m = _orl_model()
    h = m.checker().spawn_bfs().join()
    assert h.unique_state_count() == 148
    assert sorted(h.discoveries()) == ["delivered"]
    c = m.checker().spawn_tpu(sync=True, capacity=1 << 12)
    assert c.unique_state_count() == 148
    assert sorted(c.discoveries()) == ["delivered"]
    # the ORL guarantees hold on device: no redelivery / ordering never
    # discovered as counterexamples, delivery witness re-executes
    h.assert_discovery(
        "delivered", list(c.discoveries()["delivered"].actions())
    )


class _Echo(Actor):
    """Replies to every delivery with a fresh send: the ORL sequencer
    grows without bound and the closure can never finish."""

    def __init__(self, peer):
        self.peer = peer

    def on_start(self, id, out):
        out.send(self.peer, 0)
        return ()

    def on_msg(self, id, state, src, msg, out):
        out.send(src, msg + 1)
        return state


def test_unbounded_orl_raises_targeted_compile_error():
    m = (
        _OrlModel()
        .actor(OrderedReliableLink(_Echo(Id(1))))
        .actor(OrderedReliableLink(_Echo(Id(0))))
        .init_network_(Network.new_unordered_nonduplicating())
        .property(
            Expectation.ALWAYS, "ok", forall_actors(lambda i, s: True)
        )
    )
    with pytest.raises(CompileError) as e:
        compile_actor_model(m, max_states_per_actor=500)
    msg = str(e.value)
    assert "OrderedReliableLink" in msg
    assert "next_send_seq" in msg
    assert "state_bound" in msg
    assert "compiling-actor-systems.md" in msg


# re-tiered fast->slow (PR 2): the fast tier blew the 870s tier-1 budget
@pytest.mark.slow
def test_unbounded_orl_compiles_with_state_bound_recipe():
    """The recipe from docs/compiling-actor-systems.md: cap the ORL
    sequencer and the wrapped payloads; device equals a host run bounded
    the same way."""
    CAP = 3

    # Closure bounds must admit the IMAGE of every boundary-interior
    # transition (one step past the boundary on every capped field, in
    # that field's own arithmetic): seq advances by 1 per send, so
    # seq <= CAP+2; echo payloads advance ~2 per round trip (each actor
    # sends every other payload), so interior payloads reach 2*CAP-1 and
    # crossing sends reach 2*CAP.  A cap equal to the boundary poisons
    # exactly the reachable crossing transitions.
    def bound(i, s):
        return (
            not isinstance(s, LinkState)
            or (
                s.next_send_seq <= CAP + 2
                and all(m <= 2 * CAP for _, _, m in s.msgs_pending_ack)
            )
        )

    def env_bound(env):
        return env.msg[0] != "deliver" or env.msg[2] <= 2 * CAP

    def make():
        # DUPLICATING network on purpose: ORL resend-on-timeout re-sends
        # pending envelopes forever, which grows a counting
        # (nonduplicating) network without bound — under the set-based
        # duplicating semantics resends are absorbed and the capped space
        # is finite (the reference's ORL test bounds `len(network)` for
        # the same reason)
        return (
            _OrlModel()
            .actor(OrderedReliableLink(_Echo(Id(1))))
            .actor(OrderedReliableLink(_Echo(Id(0))))
            .init_network_(Network.new_unordered_duplicating())
            .property(
                Expectation.SOMETIMES,
                "echoed thrice",
                exists_actor(
                    lambda i, s: isinstance(s, LinkState)
                    and s.next_send_seq > CAP
                ),
            )
            # never-violated ALWAYS: keeps the run from early-exiting on
            # all-properties-discovered, so counts compare at FULL space
            .property(
                Expectation.ALWAYS,
                "seq in bound",
                forall_actors(
                    lambda i, s: not isinstance(s, LinkState)
                    or s.next_send_seq <= CAP + 1
                ),
            )
            .within_boundary_(
                forall_actors(
                    lambda i, s: not isinstance(s, LinkState)
                    or s.next_send_seq <= CAP + 1
                )
            )
        )

    m = make()
    tm = compile_actor_model(m, state_bound=bound, env_bound=env_bound)
    m._tensor_cached = lambda: tm
    h = make().checker().spawn_bfs().join()
    c = m.checker().spawn_tpu(sync=True, capacity=1 << 13)
    assert h.unique_state_count() == c.unique_state_count() > 0
    assert sorted(h.discoveries()) == sorted(c.discoveries())
