"""Wavefront checkpoint/resume (SURVEY §5: "A TPU build at 20× throughput
should add real wavefront checkpointing").

The engine's whole run state is a host-visible carry (table, queue, counters,
discovery fps); ``TpuChecker.checkpoint()`` snapshots it mid-run at a clean
batch boundary and ``spawn_tpu(resume=snap)`` continues it — in the same
process or after a serialize/deserialize round-trip.
"""

import io

import numpy as np
import pytest

from stateright_tpu.models.two_phase_commit import TwoPhaseSys


def run_full(n, **kw):
    return TwoPhaseSys(n).checker().spawn_tpu(sync=True, **kw)


# re-tiered fast->slow (PR 2): the fast tier blew the 870s tier-1 budget
@pytest.mark.slow
def test_killed_and_resumed_2pc7_matches_uninterrupted():
    full = run_full(7)
    expected_unique = full.unique_state_count()
    expected_states = full.state_count()
    expected_disc = {
        name: len(path) for name, path in full.discoveries().items()
    }
    assert expected_unique > 100_000  # the run is big enough to interrupt

    # interrupted run: small batches + frequent host syncs, checkpoint taken
    # mid-flight, then the checker is stopped ("killed")
    sys = TwoPhaseSys(7)
    running = sys.checker().spawn_tpu(batch=256, steps_per_call=4)
    snap = running.checkpoint(timeout=120.0)
    running.stop()
    running.join()
    assert int(snap["head"]) < int(snap["tail"]), "checkpoint was not mid-run"
    assert 0 < int(snap["unique"]) < expected_unique

    resumed = TwoPhaseSys(7).checker().spawn_tpu(sync=True, resume=snap)
    assert resumed.unique_state_count() == expected_unique
    assert resumed.state_count() == expected_states
    got_disc = {
        name: len(path) for name, path in resumed.discoveries().items()
    }
    assert got_disc == expected_disc
    resumed.assert_properties()


def test_checkpoint_survives_npz_round_trip():
    sys = TwoPhaseSys(5)
    running = sys.checker().spawn_tpu(batch=64, steps_per_call=2)
    snap = running.checkpoint(timeout=120.0)
    running.stop()
    running.join()

    buf = io.BytesIO()
    np.savez(buf, **snap)
    buf.seek(0)
    loaded = dict(np.load(buf))

    resumed = TwoPhaseSys(5).checker().spawn_tpu(sync=True, resume=loaded)
    assert resumed.unique_state_count() == 8832  # examples/2pc.rs:133
    resumed.assert_properties()


def test_resume_rejects_snapshot_from_different_model():
    snap = run_full(3).checkpoint()
    with pytest.raises(ValueError, match="different model"):
        TwoPhaseSys(4).checker().spawn_tpu(sync=True, resume=snap)


def test_checkpoint_after_completion_is_final_state():
    checker = run_full(3)
    snap = checker.checkpoint()
    assert int(snap["unique"]) == 288  # examples/2pc.rs:128
    assert int(snap["head"]) == int(snap["tail"])
    # resuming a finished run is a no-op with identical results
    resumed = TwoPhaseSys(3).checker().spawn_tpu(sync=True, resume=snap)
    assert resumed.unique_state_count() == 288
    resumed.assert_properties()


def test_growth_boundary_checkpoint_resume():
    """A snapshot taken at a growth boundary carries ``status != OK``; the
    resume path must apply the growth (rehash/compact) BEFORE stepping and
    finish with pinned counts (``wavefront.py`` resume-growth branch).  The
    engine serves checkpoint requests before growing, so boundary snapshots
    occur naturally; the boundary statuses are forced here so the test is
    deterministic."""
    running = TwoPhaseSys(5).checker().spawn_tpu(batch=64, steps_per_call=2)
    snap = running.checkpoint(timeout=120.0)
    running.stop().join()
    assert 0 < int(snap["unique"]) < 8832, "checkpoint was not mid-run"
    # _STATUS_TABLE_FULL (rehash), _STATUS_QUEUE_FULL (compact),
    # _STATUS_CAND_FULL (budget doubles, no carry transform)
    for status in (2, 1, 3):
        s = dict(snap)
        s["status"] = np.int32(status)
        resumed = TwoPhaseSys(5).checker().spawn_tpu(sync=True, resume=s)
        assert resumed.unique_state_count() == 8832  # examples/2pc.rs:133
        resumed.assert_properties()


@pytest.mark.medium
def test_queue_growth_preserves_work():
    # a queue high-water mark far below the state count forces repeated
    # compaction/growth events mid-run; counts must still be exact
    checker = run_full(5, queue_capacity=64, batch=32)
    assert checker.unique_state_count() == 8832
    assert checker._qcap > 64  # a growth event actually happened
    checker.assert_properties()


@pytest.mark.medium
def test_table_growth_preserves_work():
    checker = run_full(5, capacity=1 << 8, batch=32)
    assert checker.unique_state_count() == 8832
    assert checker._cap > (1 << 8)
    checker.assert_properties()


def test_cand_budget_growth_preserves_work():
    """A candidate budget far below the batch's real fanout forces
    _STATUS_CAND_FULL growth events mid-run; the budget doubles (engine
    parameter only — the replayed carry is untouched) and the run still
    finishes with pinned counts.  Regression: the growth branch previously
    never cleared the carry's status word and looped forever."""
    checker = run_full(3, batch=32, cand=16, capacity=1 << 12)
    assert checker.unique_state_count() == 288  # examples/2pc.rs:128
    assert any(status == 3 for status, _ in checker.growth_events)
    assert checker._cand > 16
    checker.assert_properties()


def test_many_init_states_fit_tiny_queue():
    """A model whose init set alone exceeds the queue high-water mark must
    grow cleanly instead of clamp-corrupting the init write (regression:
    init_fn only checked table occupancy)."""
    import numpy as np
    import jax.numpy as jnp

    from stateright_tpu import Expectation, Model, Property
    from stateright_tpu.parallel.tensor_model import (
        TensorBackedModel,
        TensorModel,
    )

    N = 100  # init states; queue_capacity below is far smaller

    class ManyTensor(TensorModel):
        width = 1
        max_actions = 1

        def __init__(self, model):
            self.model = model

        def init_rows(self):
            return np.arange(1, N + 1, dtype=np.uint64).reshape(N, 1)

        def encode_state(self, s):
            return (s,)

        def decode_state(self, row):
            return int(row[0])

        def step_rows(self, rows):
            # each state n steps to n+N once, then n+N is terminal
            w = rows[..., 0]
            succ = (w + jnp.uint64(N))[..., None, None]
            valid = (w <= jnp.uint64(N))[..., None]
            return succ, valid

        def property_masks(self, rows):
            return jnp.ones(rows.shape[:-1] + (1,), bool)

    class Many(TensorBackedModel, Model):
        def tensor_model(self):
            return ManyTensor(self)

        def init_states(self):
            return list(range(1, N + 1))

        def actions(self, s):
            return [0] if s <= N else []

        def next_state(self, s, a):
            return s + N

        def properties(self):
            return [Property(Expectation.ALWAYS, "ok", lambda m, s: True)]

    checker = Many().checker().spawn_tpu(
        sync=True, queue_capacity=16, batch=8, capacity=1 << 10
    )
    assert checker.unique_state_count() == 2 * N
    assert checker.state_count() == 2 * N  # N inits + N successors
    checker.assert_properties()
