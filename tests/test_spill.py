"""Billion-state spill tier (stateright_tpu/spill/, docs/spill.md).

Pins the round's contracts:

 - EXACTNESS: a run under a simulated device budget provably smaller
   than its steady-state footprint COMPLETES with bit-identical
   unique/total counts and property verdicts vs an unconstrained run,
   and its cartography block reconciles exactly (the acceptance
   criterion; 2pc-5 in the fast tier, 2pc-7 in the slow tier);
 - ZERO JAXPR IMPACT off: spill off leaves the step jaxpr bit-identical
   and the engine cache unkeyed (the telemetry/checked/prededup/por
   discipline);
 - NO FALSE NEGATIVES: every spilled fingerprint tests Bloom-positive
   on device (host mirror and device test agree bit-for-bit), so
   exactness reduces to the host index's verdict;
 - kill+resume MID-SPILL: the snapshot manifest carries the host/disk
   tier contents (and in-flight pending/offloaded rows); resumed totals
   are exact; ``snapshot_fits_guard`` accounts the HOT tier only;
 - the tiers themselves: HostIndex/SpillStore units incl. the mmap'd
   disk tier, the spill-aware ``capacity_plan`` column, the health
   model's growth_oom_risk -> spill_forecast downgrade, and the
   sharded/POR rejection guards.
"""

import io

import numpy as np
import pytest

import jax

from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.ops.hashing import EMPTY
from stateright_tpu.parallel.tensor_model import twin_or_none
from stateright_tpu.spill import (
    SPILL_V,
    HostIndex,
    SpillStore,
    bloom_est_false_pos,
    bloom_set_np,
    bloom_test,
    bloom_test_np,
)
from stateright_tpu.telemetry.memory import (
    ENV_DEVICE_BYTES,
    capacity_plan,
    snapshot_fits_guard,
    total_bytes,
    wavefront_specs,
)

BATCH = 128
BLOOM = 1 << 14
QCAP = 4096


def _budget_for(n: int, cap_fit: int, *, batch: int = BATCH,
                qcap: int = QCAP) -> int:
    """A simulated device budget that admits the ``cap_fit`` table rung
    but NOT the next migration transient — forcing eviction."""
    m = TwoPhaseSys(n)
    twin = twin_or_none(m)
    n_props = len(list(m.properties()))
    sp = (BLOOM, batch * twin.max_actions)

    def tot(cap):
        return total_bytes(
            wavefront_specs(twin, n_props, cap, qcap, batch, spill=sp)
        )

    return tot(cap_fit) + tot(cap_fit * 2) - 1


def _spawn_spill(n: int, budget: int, monkeypatch, *, sync=True,
                 batch: int = BATCH, qcap: int = QCAP, **kw):
    monkeypatch.setenv(ENV_DEVICE_BYTES, str(budget))
    monkeypatch.setenv("STATERIGHT_TPU_CAPACITY_GUARD", "off")
    b = TwoPhaseSys(n).checker().spill()
    tel = kw.pop("telemetry", None)
    if tel:
        b = b.telemetry(**tel)
    kw.setdefault("steps_per_call", 8)
    return b.spawn_tpu(
        sync=sync, capacity=1 << 12, batch=batch, queue_capacity=qcap,
        spill_bloom_bits=BLOOM, **kw,
    )


# -- the tiers: HostIndex / SpillStore / Bloom -------------------------------


def test_host_index_insert_lookup_growth():
    rng = np.random.default_rng(7)
    fps = np.unique(rng.integers(1, 2**63, 20000, dtype=np.uint64))
    vals = fps ^ np.uint64(0xABCD)
    ix = HostIndex(capacity=16)  # tiny: forces repeated growth
    ix.insert(fps[:5000], vals[:5000])
    got, found = ix.lookup(fps)
    assert found[:5000].all() and not found[5000:].any()
    assert (got[:5000] == vals[:5000]).all()
    # duplicate re-insert: first writer wins
    ix.insert(fps, vals + np.uint64(1))
    got2, found2 = ix.lookup(fps)
    assert found2.all()
    assert (got2[:5000] == vals[:5000]).all()
    assert (got2[5000:] == vals[5000:] + np.uint64(1)).all()
    assert len(ix) == fps.size
    # load stays <= 50%
    assert len(ix) * 2 <= ix.capacity


def test_host_index_intra_batch_duplicates_keep_first():
    fps = np.asarray([5, 9, 5, 9, 5], np.uint64)
    vals = np.asarray([1, 2, 3, 4, 5], np.uint64)
    ix = HostIndex()
    ix.insert(fps, vals)
    got, found = ix.lookup(np.asarray([5, 9], np.uint64))
    assert found.all()
    assert got.tolist() == [1, 2]
    assert len(ix) == 2


def test_spill_store_ram_tier_and_contains():
    store = SpillStore()  # no budget: never flushes
    fps = np.arange(1, 1001, dtype=np.uint64)
    store.append(fps, fps + np.uint64(10))
    assert len(store) == 1000
    assert store.host_bytes == 1000 * 16
    assert store.disk_bytes == 0
    assert store.contains(fps).all()
    assert not store.contains(np.asarray([5000], np.uint64)).any()
    # re-appending already-spilled fps is a no-op
    assert store.append(fps[:10], fps[:10]) == 0
    assert len(store) == 1000


def test_spill_store_disk_tier_flush_and_roundtrip(tmp_path):
    store = SpillStore(directory=str(tmp_path), host_budget=4096)
    fps = np.arange(1, 2001, dtype=np.uint64)
    store.append(fps[:1000], fps[:1000])
    assert store.disk_bytes > 0, "tiny host budget must flush to disk"
    assert store.host_bytes == 0
    store.append(fps[1000:], fps[1000:])
    assert store.contains(fps).all()
    assert len(list(tmp_path.glob("spill-*.bin"))) >= 1
    # the portable snapshot form round-trips every tier
    f, p = store.to_arrays()
    assert sorted(f.tolist()) == fps.tolist()
    assert (p == f).all()
    back = SpillStore.from_arrays(f, p)
    assert len(back) == 2000 and back.contains(fps).all()
    # lifecycle: close() releases the mmap handles and (on request)
    # removes the segment files — a campaign must not leak disk
    store.close(delete=True)
    assert not list(tmp_path.glob("spill-*.bin"))
    store.close()  # idempotent


def test_bloom_no_false_negatives_and_device_host_agreement():
    rng = np.random.default_rng(3)
    fps = np.unique(rng.integers(1, 2**63, 8000, dtype=np.uint64))
    members, probes = fps[:4000], fps[4000:]
    words = np.zeros(BLOOM // 32, np.uint32)
    bloom_set_np(words, members)
    # NO false negatives, ever — the exactness contract's foundation
    assert bloom_test_np(words, members).all()
    dev = np.asarray(
        bloom_test(jax.numpy.asarray(words), jax.numpy.asarray(fps), BLOOM)
    )
    assert (dev == bloom_test_np(words, fps)).all()
    # probes are not members: positives here are the (bounded) FP rate
    fp_rate = float(bloom_test_np(words, probes).mean())
    assert fp_rate < 1.0
    assert 0.0 < bloom_est_false_pos(4000, BLOOM) < 1.0
    assert bloom_est_false_pos(0, BLOOM) == 0.0


# -- analytic model exactness with the tier armed ----------------------------


def test_spill_analytic_bytes_reconcile_exactly(monkeypatch):
    """The ledger's per-buffer model must cover the spill carry tail
    (bloom, pending, scalars) exactly — the budget decisions hang off
    these bytes."""
    budget = _budget_for(5, 1 << 13)
    c = _spawn_spill(
        5, budget, monkeypatch, telemetry={"memory": True}
    )
    specs = c._memory_spec_fn()(
        {"cap": c._cap, "qcap": c._qcap, "batch": c._batch}
    )
    carry = c._final_carry
    assert len(specs) == len(carry)
    for s, arr in zip(specs, carry):
        a = np.asarray(arr)
        assert a.nbytes == s.nbytes, (s.name, a.nbytes, s.nbytes)
        assert a.shape == s.shape, (s.name, a.shape, s.shape)
    names = [s.name for s in specs]
    for expect in ("spill_bloom", "pend_fp", "pend_rows", "spill_stats"):
        assert expect in names


# -- zero jaxpr impact off + unkeyed cache -----------------------------------


def _build_jaxpr(checker) -> str:
    init_fn, run_fn = checker._build(
        checker._cap, checker._qcap, checker._batch, checker._cand
    )
    carry, _ = init_fn()
    return str(jax.make_jaxpr(lambda cr: run_fn(cr))(tuple(carry)))


def test_spill_off_leaves_step_jaxpr_bit_identical():
    """Spill OFF is exactly the pre-spill engine: same step jaxpr, same
    engine-cache key shape — even after a spill-on engine was built on
    the same tensor twin (no leakage through the cached twin)."""
    kw = dict(sync=True, capacity=1 << 12, batch=64)
    plain = TwoPhaseSys(3).checker().spawn_tpu(**kw)
    base_jaxpr = _build_jaxpr(plain)
    base_key = plain._engine_key(
        plain._cap, plain._qcap, plain._batch, plain._cand
    )
    assert not any(
        isinstance(e, str) and e == "spill" for e in base_key
    )
    on = TwoPhaseSys(3).checker().spill().spawn_tpu(
        spill_bloom_bits=BLOOM, **kw
    )
    assert "spill" in on._engine_key(on._cap, on._qcap, on._batch, on._cand)
    off_again = TwoPhaseSys(3).checker().spawn_tpu(**kw)
    assert _build_jaxpr(off_again) == base_jaxpr
    assert (
        off_again._engine_key(
            off_again._cap, off_again._qcap, off_again._batch,
            off_again._cand,
        )
        == base_key
    )


# -- the acceptance criterion: complete + reconcile under a small budget -----


def _parity_run(n, budget, monkeypatch, **kw):
    base = TwoPhaseSys(n).checker().spawn_tpu(
        sync=True, capacity=1 << 12, batch=kw.get("batch", BATCH)
    )
    c = _spawn_spill(
        n, budget, monkeypatch,
        telemetry={"cartography": True, "memory": True}, **kw,
    )
    assert c.state_count() == base.state_count()
    assert c.unique_state_count() == base.unique_state_count()
    assert sorted(c.discoveries()) == sorted(base.discoveries())
    return base, c


def test_2pc5_under_budget_completes_bit_identical(monkeypatch):
    """A 2pc-5 run under a budget smaller than its steady-state
    footprint completes, forces eviction, and reconciles: counts and
    property verdicts bit-identical to the unconstrained run, the
    cartography block exact, the spill tallies consistent."""
    budget = _budget_for(5, 1 << 13)
    # the budget is provably smaller than the unconstrained steady state
    m = TwoPhaseSys(5)
    twin = twin_or_none(m)
    steady = total_bytes(wavefront_specs(
        twin, len(list(m.properties())), 1 << 16, QCAP, BATCH,
        spill=(BLOOM, BATCH * twin.max_actions),
    ))
    assert budget < steady
    base, c = _parity_run(5, budget, monkeypatch)
    sp = c.spill_status()
    assert sp["v"] == SPILL_V and sp["enabled"]
    assert sp["evictions"] >= 1, "budget did not force a single eviction"
    assert sp["spilled_fps"] > 0
    assert sp["host_bytes"] == sp["spilled_fps"] * 16
    assert sp["resolved_novel"] + sp["resolved_dups"] > 0
    # spilled + hot == unique (the tiers partition the visited set)
    hot = int(
        (np.asarray(c._final_carry[0]) != np.uint64(EMPTY)).sum()
    )
    assert hot + sp["spilled_fps"] == c.unique_state_count()
    # cartography reconciles EXACTLY across evictions/injections
    cart = c.cartography()
    assert sum(cart["depth_hist"]) == c.unique_state_count()
    assert cart["fresh_inserts"] == c.unique_state_count()
    assert sum(cart["action_hist"]) == c.state_count() - len(
        TwoPhaseSys(5).init_states()
    )
    assert cart["duplicate_hits"] == c.state_count() - c.unique_state_count()


# a budget-starved end-to-end run through the queue-offload path is
# integration-shaped — the daily tier owns it (870s fast-tier budget)
@pytest.mark.medium
def test_queue_offload_under_queue_blocking_budget(monkeypatch):
    """A budget that blocks the QUEUE doubling too: the frontier's tail
    excess rides the host FIFO and refills at drain — counts still
    bit-identical, every offloaded row refilled."""
    m = TwoPhaseSys(5)
    twin = twin_or_none(m)
    n_props = len(list(m.properties()))
    batch, qcap = 64, 512
    sp = (BLOOM, batch * twin.max_actions)
    steady = total_bytes(
        wavefront_specs(twin, n_props, 8192, qcap, batch, spill=sp)
    )
    budget = 2 * steady - 1
    base, c = _parity_run(
        5, budget, monkeypatch, batch=batch, qcap=qcap, steps_per_call=4
    )
    sp_st = c.spill_status()
    assert sp_st["queue_offloaded"] > 0
    assert sp_st["queue_offloaded"] == sp_st["queue_refilled"]
    assert sp_st["queue_host_rows"] == 0  # every tier drained at the end


def test_offloaded_rows_keep_depth_histogram_reconciling(monkeypatch):
    """A run that ENDS with frontier rows still in the host FIFO (target
    early-exit) must still reconcile its depth histogram: offloaded
    rows' depth lanes are banked at offload and un-banked at refill, so
    sum(depth_hist) == unique at every sync — not only after a full
    drain."""
    m = TwoPhaseSys(5)
    twin = twin_or_none(m)
    n_props = len(list(m.properties()))
    batch, qcap = 64, 512
    sp = (BLOOM, batch * twin.max_actions)
    steady = total_bytes(
        wavefront_specs(twin, n_props, 8192, qcap, batch, spill=sp)
    )
    monkeypatch.setenv(ENV_DEVICE_BYTES, str(2 * steady - 1))
    monkeypatch.setenv("STATERIGHT_TPU_CAPACITY_GUARD", "off")
    c = (
        TwoPhaseSys(5).checker().spill()
        .telemetry(cartography=True)
        .target_states(6000)
        .spawn_tpu(
            sync=True, capacity=1 << 12, batch=batch, queue_capacity=qcap,
            spill_bloom_bits=BLOOM, steps_per_call=4,
        )
    )
    sp_st = c.spill_status()
    assert sp_st["queue_offloaded"] > 0, "budget did not force an offload"
    assert sp_st["queue_host_rows"] > 0, (
        "target run was expected to END with rows still offloaded"
    )
    cart = c.cartography()
    assert sum(cart["depth_hist"]) == c.unique_state_count()
    assert cart["fresh_inserts"] == c.unique_state_count()


def test_spill_trace_reconstruction_spans_tiers(monkeypatch):
    """Discovery traces walk parent chains that cross the hot/host tier
    boundary: reconstruction must merge the spilled parents back."""
    budget = _budget_for(5, 1 << 13)
    c = _spawn_spill(5, budget, monkeypatch)
    assert c.spill_status()["evictions"] >= 1
    disc = c.discoveries()
    assert disc  # 2pc-5 has sometimes-properties with examples
    base = TwoPhaseSys(5).checker().spawn_tpu(
        sync=True, capacity=1 << 12, batch=BATCH
    )
    base_disc = base.discoveries()
    for name, path in disc.items():
        assert len(path) >= 1
        assert name in base_disc
    c.assert_properties()


# -- kill + resume mid-spill -------------------------------------------------


def test_kill_and_resume_mid_spill_totals_exact(monkeypatch):
    """Checkpoint after the first eviction, kill, resume: the manifest
    carries the host-tier contents (and survives an npz round trip), the
    resumed totals are exact, and resuming WITHOUT the tier armed is
    refused with guidance."""
    import time

    base = TwoPhaseSys(5).checker().spawn_tpu(
        sync=True, capacity=1 << 12, batch=BATCH
    )
    budget = _budget_for(5, 1 << 13)
    running = _spawn_spill(
        5, budget, monkeypatch, sync=False, steps_per_call=2
    )
    snap = None
    for _ in range(500):
        if running.is_done():
            break
        s = running.checkpoint(timeout=120.0)
        if int(s.get("spill_base", 0)) > 0 and int(s["tail"]) > int(s["head"]):
            snap = s
            break
        time.sleep(0.01)
    assert snap is not None, "never caught a mid-spill checkpoint"
    running.stop()
    running.join()
    assert "spill_fp" in snap and "spill_parent" in snap
    assert int(snap["spill_base"]) == len(np.asarray(snap["spill_fp"]))
    # npz round trip (process-restart shape)
    buf = io.BytesIO()
    np.savez(buf, **dict(snap))
    buf.seek(0)
    snap2 = dict(np.load(buf, allow_pickle=False))
    resumed = (
        TwoPhaseSys(5).checker().spill()
        .spawn_tpu(sync=True, resume=snap2, spill_bloom_bits=BLOOM)
    )
    assert resumed.unique_state_count() == base.unique_state_count()
    assert resumed.state_count() == base.state_count()
    assert sorted(resumed.discoveries()) == sorted(base.discoveries())
    resumed.assert_properties()
    # the resumed hot tier stayed budget-pinned: the restored store's
    # length must feed the growth trigger (a resume that forgot the
    # spill base would balloon the table past the budget)
    assert resumed._cap <= 1 << 15
    assert resumed.spill_status()["spilled_fps"] > 0
    with pytest.raises(ValueError, match="spill-tier contents"):
        TwoPhaseSys(5).checker().spawn_tpu(sync=True, resume=snap2)


def test_snapshot_fits_guard_accounts_hot_tier_only(monkeypatch, capsys):
    """The resume capacity guard must not count the host-resident
    spill_* manifest arrays against the DEVICE budget: a snapshot whose
    hot tier fits passes even when its spilled contents dwarf it."""
    snap = {
        "table_fp": np.zeros(1024, np.uint64),
        "spill_fp": np.zeros(1 << 20, np.uint64),  # 8MB of HOST data
        "spill_parent": np.zeros(1 << 20, np.uint64),
    }
    monkeypatch.setenv(ENV_DEVICE_BYTES, str(64 * 1024))
    monkeypatch.delenv("STATERIGHT_TPU_CAPACITY_GUARD", raising=False)
    snapshot_fits_guard(snap, "test")  # must not warn
    assert "capacity guard" not in capsys.readouterr().err
    # ...and the hot tier still gates: inflate it past the budget
    snap["table_fp"] = np.zeros(1 << 20, np.uint64)
    snapshot_fits_guard(snap, "test")
    assert "capacity guard" in capsys.readouterr().err


# -- capacity plan + health downgrade + telemetry surfaces -------------------


def test_capacity_plan_spill_column_extends_max_unique(monkeypatch):
    m = TwoPhaseSys(3)
    twin = twin_or_none(m)
    n_props = len(list(m.properties()))

    def spec_fn(c):
        return wavefront_specs(
            twin, n_props, int(c["cap"]), int(c["qcap"]), int(c["batch"])
        )

    caps = {"cap": 1 << 12, "qcap": 1 << 11, "batch": 64}
    budget = total_bytes(spec_fn(caps)) * 8
    plain = capacity_plan(spec_fn, caps, budget=budget)
    sp = capacity_plan(
        spec_fn, caps, budget=budget, spill=True,
        spill_host_bytes=1 << 30,
    )
    assert "spill" not in plain
    assert sp["spill"]["hot_max_unique"] == plain["max_unique"]
    assert sp["spill"]["host_max_unique"] == (1 << 30) // 16
    assert sp["max_unique"] == plain["max_unique"] + (1 << 30) // 16
    # no budget -> no spill block (nothing to extend past)
    assert "spill" not in capacity_plan(spec_fn, caps, spill=True)


def test_health_downgrades_oom_risk_to_spill_forecast():
    from stateright_tpu.telemetry.health import HealthTracker

    def drive(tracker):
        tracker.set_memory_forecast(10_000, 5_000)  # transient > budget
        events = []
        for _ in range(3):
            events += tracker.update({
                "d_states": 100, "d_unique": 50, "dt": 0.1,
                "queue": 10, "load_factor": 0.2,
            })
        return events

    plain = HealthTracker()
    evs = drive(plain)
    assert any(e["event"] == "growth_oom_risk" for e in evs)
    assert plain.snapshot()["oom_risk"] is True

    armed = HealthTracker()
    armed.spill_armed = True
    evs = drive(armed)
    assert any(e["event"] == "spill_forecast" for e in evs)
    assert not any(e["event"] == "growth_oom_risk" for e in evs)
    snap = armed.snapshot()
    assert snap["oom_risk"] is False and snap["spill_forecast"] is True
    done = armed.mark_done()
    assert any(e["event"] == "spill_forecast_cleared" for e in done)


def test_chrome_trace_carries_spill_counter_tracks(monkeypatch, tmp_path):
    """Satellite: spill events plot as ``spill_bytes`` and
    ``bloom_filter`` counter tracks in the Chrome-trace export."""
    from stateright_tpu.telemetry.export import from_chrome_trace

    budget = _budget_for(5, 1 << 13)
    c = _spawn_spill(5, budget, monkeypatch, telemetry={"memory": True})
    path = tmp_path / "trace.json"
    c.flight_recorder.to_chrome_trace(path)
    back = from_chrome_trace(path)
    counters = {}
    for e in back["events"]:
        if e["ph"] == "C":
            counters.setdefault(e["name"], []).append(e)
    assert "spill_bytes" in counters
    assert all(
        "host_bytes" in e["args"] for e in counters["spill_bytes"]
    )
    assert "bloom_filter" in counters


def test_report_and_summary_carry_the_spill_block(monkeypatch, tmp_path):
    from stateright_tpu.telemetry.report import build_report, write_report

    budget = _budget_for(5, 1 << 13)
    c = _spawn_spill(
        5, budget, monkeypatch,
        telemetry={"cartography": True, "memory": True},
    )
    rep = build_report(c)
    assert rep["spill"]["evictions"] >= 1
    assert rep["spill"]["spilled_fps"] > 0
    assert c.flight_recorder.summary()["spill"]["spilled_fps"] > 0
    write_report(c, str(tmp_path / "r.json"))
    md = (tmp_path / "r.md").read_text()
    assert "Spill tier" in md and "Bloom filter" in md


def test_spill_resolution_skips_when_nothing_spilled():
    """No budget, no eviction: the Bloom stays all-zero, nothing ever
    defers, and the spill status reads idle."""
    c = TwoPhaseSys(3).checker().spill().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64, spill_bloom_bits=BLOOM
    )
    sp = c.spill_status()
    assert sp["evictions"] == 0 and sp["spilled_fps"] == 0
    assert sp["deferred"] == 0 and sp["resolved_novel"] == 0
    base = TwoPhaseSys(3).checker().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    assert c.unique_state_count() == base.unique_state_count()
    assert base.spill_status() is None  # plain runs expose None


# -- rejection guards --------------------------------------------------------


def test_sharded_engine_rejects_spill_with_guidance():
    with pytest.raises(NotImplementedError, match="single-device"):
        TwoPhaseSys(3).checker().spill().spawn_tpu(devices=2)


def test_spill_and_por_are_mutually_exclusive():
    with pytest.raises(NotImplementedError, match="partial-order"):
        TwoPhaseSys(3).checker().spill().por().spawn_tpu(sync=True)


# -- regress gate (injectable artifacts; satellite) --------------------------


def _spill_leg(**over):
    leg = {
        "v": 1, "enabled": True, "evictions": 2, "spilled_fps": 1000,
        "host_bytes": 16000, "disk_bytes": 0, "resolved_dups": 10,
        "resolved_novel": 5,
    }
    leg.update(over)
    return leg


def test_regress_spill_gate_absence_never_trips():
    from regress import spill_verdict

    # stale / pre-spill artifacts carry no block: pass
    assert spill_verdict({}, {})["ok"]
    assert spill_verdict({}, {"tpu_2pc7_spill": _spill_leg()})["ok"]


def test_regress_spill_gate_validates_present_legs():
    from regress import spill_verdict

    good = {
        "tpu_2pc7_spill": _spill_leg(),
        "tpu_2pc7_spill_unique": 296448,
        "tpu_2pc7_unique": 296448,
    }
    assert spill_verdict(good, {})["ok"]
    # count drift is the cardinal sin
    bad = dict(good, tpu_2pc7_spill_unique=296447)
    v = spill_verdict(bad, {})
    assert not v["ok"] and any("unique" in p for p in v["problems"])
    # a leg that never evicted did not exercise the tier
    v = spill_verdict(
        {"tpu_2pc7_spill": _spill_leg(evictions=0)}, {}
    )
    assert not v["ok"]
    # malformed block
    v = spill_verdict({"tpu_2pc7_spill": {"enabled": True}}, {})
    assert not v["ok"]
    # crashed leg fails, never skips
    v = spill_verdict({"tpu_2pc7_spill_error": "RuntimeError: x"}, {})
    assert not v["ok"]


def test_regress_main_spill_flag(tmp_path, capsys):
    import json

    from regress import main as regress_main

    run = {
        "fresh": True,
        "tpu_2pc7_spill": _spill_leg(),
        "tpu_2pc7_spill_unique": 296448,
        "tpu_2pc7_unique": 296448,
    }
    rp = tmp_path / "run.json"
    bp = tmp_path / "base.json"
    rp.write_text(json.dumps(run))
    bp.write_text(json.dumps({}))
    rc = regress_main([str(rp), f"--baseline={bp}", "--spill"])
    assert rc == 0
    run["tpu_2pc7_spill_unique"] = 1
    rp.write_text(json.dumps(run))
    rc = regress_main([str(rp), f"--baseline={bp}", "--spill"])
    assert rc == 1
    capsys.readouterr()


# -- the ROADMAP acceptance run (slow tier) ----------------------------------


@pytest.mark.slow
def test_2pc7_under_budget_completes_bit_identical(monkeypatch):
    """THE acceptance criterion: 2pc-7 under a ``STATERIGHT_TPU_DEVICE_
    BYTES`` budget provably smaller than its steady-state footprint
    completes with bit-identical unique/total/property counts vs the
    unconstrained run, and its cartography block reconciles exactly."""
    m = TwoPhaseSys(7)
    twin = twin_or_none(m)
    n_props = len(list(m.properties()))
    batch, qcap = 1024, 1 << 17
    sp = (BLOOM, batch * twin.max_actions)

    def tot(cap):
        return total_bytes(wavefront_specs(
            twin, n_props, cap, qcap, batch, cartography=True, spill=sp
        ))

    # the unconstrained run ends at a 1<<21 table (>= 4 * 296,448);
    # budget out the 1<<20 -> 1<<21 migration so the hot tier pins
    budget = tot(1 << 20) + tot(1 << 21) - 1
    assert budget < tot(1 << 21) + tot(1 << 22)  # < the steady-state peak
    base = TwoPhaseSys(7).checker().spawn_tpu(
        sync=True, capacity=1 << 17, batch=batch
    )
    assert base.unique_state_count() > (1 << 20) // 4  # must NOT fit hot
    monkeypatch.setenv(ENV_DEVICE_BYTES, str(budget))
    monkeypatch.setenv("STATERIGHT_TPU_CAPACITY_GUARD", "off")
    c = (
        TwoPhaseSys(7).checker().spill()
        .telemetry(cartography=True, memory=True)
        .spawn_tpu(
            sync=True, capacity=1 << 17, queue_capacity=qcap, batch=batch,
            steps_per_call=64, spill_bloom_bits=BLOOM,
        )
    )
    assert c.unique_state_count() == base.unique_state_count()
    assert c.state_count() == base.state_count()
    assert sorted(c.discoveries()) == sorted(base.discoveries())
    sp_st = c.spill_status()
    assert sp_st["evictions"] >= 1
    cart = c.cartography()
    assert sum(cart["depth_hist"]) == c.unique_state_count()
    assert cart["fresh_inserts"] == c.unique_state_count()
    assert sum(cart["action_hist"]) == c.state_count() - len(
        TwoPhaseSys(7).init_states()
    )
    c.assert_properties()
