"""Compiler fuzz: random bounded actor systems, host=device across engines.

The fixed examples (2pc, paxos, raft, registers) pin exact counts but
share a handful of structural shapes.  This fuzzer generates seeded
random actor systems inside the general compiled fragment — random
per-actor monotone FSMs exchanging messages from a small alphabet, with
factored properties — and requires, for every seed:

 - the mechanical compiler accepts the system (its closure terminates:
   actor states only advance, so total sends are bounded);
 - per-state equivalence over the FULL space (encode/decode round-trip,
   fingerprint agreement, successor-set equality, property-mask
   agreement) via the same crawl used for the examples;
 - unique-count and discovery parity across spawn_bfs / spawn_dfs /
   spawn_mp_bfs / spawn_tpu / the 8-device sharded engine.

Seeds are fixed, so failures reproduce exactly.
"""

import random

import pytest

from stateright_tpu.actor import Actor, ActorModel, Id, Network, Out
from stateright_tpu.actor.device_props import exists_actor, forall_actors
from stateright_tpu.core import Expectation
from stateright_tpu.parallel.actor_compiler import compile_actor_model
from stateright_tpu.parallel.tensor_model import TensorBackedModel

from test_paxos_tensor import crawl_and_check

N_STATES = 4  # per-actor FSM size; states only advance -> bounded space
ALPHABET = 3  # message kinds


class FuzzActor(Actor):
    """Monotone random FSM: on a delivery, either ignore it or advance
    one state and (maybe) send one random message to a random peer.  The
    tables are drawn once from the seed, so the actor is deterministic."""

    def __init__(self, rng: random.Random, me: int, n_actors: int):
        self.me = me
        # start[k]: message kind sent at boot to a random peer (or None)
        self.boot = None
        if rng.random() < 0.8:
            self.boot = (rng.randrange(n_actors), rng.randrange(ALPHABET))
        # advance[state][kind] -> None (ignore) | (dst, kind) | (None,)
        self.table = {}
        for s in range(N_STATES - 1):
            for k in range(ALPHABET):
                roll = rng.random()
                if roll < 0.35:
                    self.table[s, k] = None  # ignore: no-op transition
                elif roll < 0.75:
                    self.table[s, k] = (
                        rng.randrange(n_actors), rng.randrange(ALPHABET)
                    )
                else:
                    self.table[s, k] = (None,)  # advance silently

    def on_start(self, id: Id, out: Out):
        if self.boot is not None:
            dst, kind = self.boot
            if dst != self.me:
                out.send(Id(dst), ("m", kind))
        return 0

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        if state >= N_STATES - 1:
            return None
        eff = self.table[state, msg[1]]
        if eff is None:
            return None
        if len(eff) == 2:
            dst, kind = eff
            if dst != self.me:
                out.send(Id(dst), ("m", kind))
        return state + 1


class FuzzModel(TensorBackedModel, ActorModel):
    def tensor_model(self):
        return compile_actor_model(self)


def _fuzz_model(
    seed: int, n_actors: int, network, actor_cls=FuzzActor
) -> FuzzModel:
    rng = random.Random(seed)
    m = FuzzModel(None, None)
    for i in range(n_actors):
        m.actor(actor_cls(rng, i, n_actors))
    m.init_network_(network)
    m.property(
        Expectation.SOMETIMES,
        "someone finishes",
        exists_actor(lambda i, s: s == N_STATES - 1),
    )
    # never-violated ALWAYS: forces full exploration so engine counts
    # compare at the complete space, not at early-exit granularity
    m.property(
        Expectation.ALWAYS,
        "states in range",
        forall_actors(lambda i, s: 0 <= s < N_STATES),
    )
    return m


NETWORKS = {
    "nondup": Network.new_unordered_nonduplicating,
    "dup": Network.new_unordered_duplicating,
    "ordered": Network.new_ordered,
}


class FuzzTimerActor(FuzzActor):
    """FuzzActor plus a timer axis: boot may arm the timer; a timeout at
    a non-final state may advance (and maybe send) and maybe re-arm —
    still monotone, so still bounded."""

    def __init__(self, rng: random.Random, me: int, n_actors: int):
        super().__init__(rng, me, n_actors)
        self.boot_timer = rng.random() < 0.7
        # ttable[state] -> None (clear only) | (advance?, send | None, rearm?)
        self.ttable = {}
        for s in range(N_STATES - 1):
            if rng.random() < 0.3:
                self.ttable[s] = None
            else:
                send = None
                if rng.random() < 0.5:
                    send = (rng.randrange(n_actors), rng.randrange(ALPHABET))
                advance = rng.random() < 0.8
                # re-arming must imply advancing: a timeout that re-arms
                # without changing state fires forever, adding one more
                # envelope copy per firing — an infinite space
                self.ttable[s] = (
                    advance, send, advance and rng.random() < 0.6
                )

    def on_start(self, id: Id, out: Out):
        state = super().on_start(id, out)
        if self.boot_timer:
            out.set_timer((1.0, 2.0))
        return state

    def on_timeout(self, id: Id, state, out: Out):
        eff = self.ttable.get(state)
        if eff is None:
            return None  # the timeout still clears the timer bit
        advance, send, rearm = eff
        if send is not None and send[0] != self.me:
            out.send(Id(send[0]), ("m", send[1]))
        if rearm and state < N_STATES - 2:
            out.set_timer((1.0, 2.0))
        return state + 1 if advance and state < N_STATES - 1 else None


def _assert_engine_parity(m, seed, net):
    tm = m.tensor_model()
    seen = crawl_and_check(m, tm)  # full-space per-state equivalence
    h = m.checker().spawn_bfs().join()
    assert h.unique_state_count() == len(seen)
    for build in (
        lambda: m.checker().spawn_dfs().join(),
        lambda: m.checker().spawn_mp_bfs(processes=2).join(),
        lambda: m.checker().spawn_tpu(sync=True, capacity=1 << 12),
        lambda: m.checker().spawn_tpu(
            sync=True, devices=8, capacity=1 << 12,
            frontier_capacity=1 << 7,
        ),
    ):
        c = build()
        assert c.unique_state_count() == h.unique_state_count(), (seed, net)
        assert sorted(c.discoveries()) == sorted(h.discoveries()), (seed, net)


# fast tier runs two seeds (0 = a typical chatty system; 4 = the empty
# envelope universe that crashed device gathers); the rest join the daily
# medium tier per the repo's tiering convention
_FAST_SEEDS = (0, 4)
_SEEDS = [
    s if s in _FAST_SEEDS else pytest.param(s, marks=pytest.mark.medium)
    for s in range(6)
]


@pytest.mark.parametrize("seed", _SEEDS)
@pytest.mark.parametrize("net", sorted(NETWORKS))
# re-tiered fast->slow (PR 2): the fast tier blew the 870s tier-1 budget
@pytest.mark.slow
def test_fuzzed_system_host_equals_device(seed, net):
    m = _fuzz_model(seed, n_actors=2 + seed % 2, network=NETWORKS[net]())
    _assert_engine_parity(m, seed, net)


# re-tiered fast->slow (PR 2): the fast tier blew the 870s tier-1 budget
@pytest.mark.slow
@pytest.mark.parametrize("seed", _SEEDS)
def test_fuzzed_timer_system_host_equals_device(seed):
    """The timer axis of the general fragment under fuzz: boot-armed
    timers, timeout-driven advances/sends, re-arming — every engine
    agrees with the host on the full space."""
    m = _fuzz_model(
        1000 + seed,
        n_actors=2 + seed % 2,
        network=Network.new_unordered_nonduplicating(),
        actor_cls=FuzzTimerActor,
    )
    _assert_engine_parity(m, seed, "timer")


@pytest.mark.parametrize("seed", _SEEDS)
# re-tiered fast->slow (PR 2): the fast tier blew the 870s tier-1 budget
@pytest.mark.slow
def test_fuzzed_lossy_system_host_equals_device(seed):
    """Drop actions under fuzz: a lossy duplicating network adds a Drop
    per deliverable envelope; engines must agree on the enlarged space."""
    m = _fuzz_model(
        seed, n_actors=2, network=Network.new_unordered_duplicating()
    )
    m.lossy_network(True)
    _assert_engine_parity(m, seed, "lossy-dup")
