"""Test harness config.

Tests never assume real TPU hardware: JAX is forced onto CPU with 8 virtual
devices so multi-chip sharding (mesh + all-to-all fingerprint routing) is
exercised exactly as the driver's ``dryrun_multichip`` does.  Must run before
jax is used anywhere.

Note the env override must be unconditional: the environment may arrive with
``JAX_PLATFORMS`` already pointing at a real accelerator plugin, and a
``setdefault`` would silently leave the whole suite running on one real chip.
``jax.config.update`` additionally beats any plugin that force-selected its
platform at interpreter startup (site hooks run before this file).
"""

import os
import re
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
flags += " --xla_force_host_platform_device_count=8"
# Tests are compile-time-bound (dozens of engine variants), not
# run-time-bound, and their correctness oracle is host Python — so XLA's
# CPU backend optimizations only cost wall clock here (~23% of the fast
# tier).  Long-running deep-parity jobs (the daily slow+medium CI tier,
# where RUN time dominates) opt back in via STATERIGHT_TPU_TEST_OPT=1.
if not os.environ.get("STATERIGHT_TPU_TEST_OPT"):
    flags += " --xla_backend_optimization_level=0"
os.environ["XLA_FLAGS"] = flags.strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()}"
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """``medium`` implies ``slow`` for selection: pytest.ini documents
    medium as "run with the daily slow tier", so the fast tier's
    ``-m 'not slow'`` must deselect it without every harness having to
    spell ``not slow and not medium``.  The daily tier's ``slow or
    medium`` selection is unaffected, and every medium test keeps a
    cheaper fast-tier sibling (the re-tiering discipline)."""
    for item in items:
        if "medium" in item.keywords:
            item.add_marker(pytest.mark.slow)
