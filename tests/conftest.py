"""Test harness config.

Tests never assume real TPU hardware: JAX is forced onto CPU with 8 virtual
devices so multi-chip sharding (mesh + all-to-all fingerprint routing) is
exercised exactly as the driver's ``dryrun_multichip`` does.  Must run before
jax is imported anywhere.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
