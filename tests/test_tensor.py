"""Tensor-form core tests: device/host hash parity, bit packing, hash table.

These pin the contract that makes the TPU engine sound: the device row hash
equals the host ``hash_words`` bit-for-bit, and the scatter-min hash-table
insert dedupes exactly.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from stateright_tpu.fingerprint import MASK64, hash_words
from stateright_tpu.ops import EMPTY, row_hash
from stateright_tpu.parallel import BitPacker


def test_row_hash_matches_host_hash_words():
    rng = np.random.default_rng(7)
    for width in (1, 2, 4, 7):
        rows = rng.integers(0, MASK64, size=(64, width), dtype=np.uint64)
        dev = np.asarray(row_hash(jnp.asarray(rows)))
        for i in range(rows.shape[0]):
            assert int(dev[i]) == hash_words(int(w) for w in rows[i])


def test_row_hash_avoids_sentinels():
    # exhaustively confirmed impossible to hit by construction; just pin the
    # remap behavior of the scalar function
    assert hash_words([0]) not in (0, MASK64)


def test_bitpacker_roundtrip_and_device_access():
    pk = BitPacker([("a", 3), ("b", 60), ("c", 5), ("d", 64)])
    assert pk.width == 3  # a+b share word 0, c word 1, d word 2
    row = pk.pack(a=5, b=(1 << 59) | 123, c=17, d=MASK64)
    assert pk.unpack(row) == {"a": 5, "b": (1 << 59) | 123, "c": 17, "d": MASK64}

    rows = jnp.asarray(np.asarray([row, pk.pack(a=1, b=2, c=3, d=4)], np.uint64))
    assert int(pk.get(rows, "b")[0]) == (1 << 59) | 123
    assert int(pk.get(rows, "c")[1]) == 3
    updated = pk.set(rows, "a", jnp.asarray([7, 0], jnp.uint64))
    assert int(pk.get(updated, "a")[0]) == 7
    assert int(pk.get(updated, "b")[0]) == (1 << 59) | 123  # untouched


def test_bitpacker_rejects_out_of_range():
    pk = BitPacker([("x", 4)])
    with pytest.raises(ValueError):
        pk.pack(x=16)
    with pytest.raises(ValueError):
        pk.pack(y=1)


