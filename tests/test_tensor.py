"""Tensor-form core tests: device/host hash parity, bit packing, hash table.

These pin the contract that makes the TPU engine sound: the device row hash
equals the host ``hash_words`` bit-for-bit, and the scatter-min hash-table
insert dedupes exactly.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from stateright_tpu.fingerprint import MASK64, hash_words
from stateright_tpu.ops import EMPTY, hash_insert, row_hash
from stateright_tpu.ops.hashtable import dedupe_sorted
from stateright_tpu.parallel import BitPacker


def test_row_hash_matches_host_hash_words():
    rng = np.random.default_rng(7)
    for width in (1, 2, 4, 7):
        rows = rng.integers(0, MASK64, size=(64, width), dtype=np.uint64)
        dev = np.asarray(row_hash(jnp.asarray(rows)))
        for i in range(rows.shape[0]):
            assert int(dev[i]) == hash_words(int(w) for w in rows[i])


def test_row_hash_avoids_sentinels():
    # exhaustively confirmed impossible to hit by construction; just pin the
    # remap behavior of the scalar function
    assert hash_words([0]) not in (0, MASK64)


def test_bitpacker_roundtrip_and_device_access():
    pk = BitPacker([("a", 3), ("b", 60), ("c", 5), ("d", 64)])
    assert pk.width == 3  # a+b share word 0, c word 1, d word 2
    row = pk.pack(a=5, b=(1 << 59) | 123, c=17, d=MASK64)
    assert pk.unpack(row) == {"a": 5, "b": (1 << 59) | 123, "c": 17, "d": MASK64}

    rows = jnp.asarray(np.asarray([row, pk.pack(a=1, b=2, c=3, d=4)], np.uint64))
    assert int(pk.get(rows, "b")[0]) == (1 << 59) | 123
    assert int(pk.get(rows, "c")[1]) == 3
    updated = pk.set(rows, "a", jnp.asarray([7, 0], jnp.uint64))
    assert int(pk.get(updated, "a")[0]) == 7
    assert int(pk.get(updated, "b")[0]) == (1 << 59) | 123  # untouched


def test_bitpacker_rejects_out_of_range():
    pk = BitPacker([("x", 4)])
    with pytest.raises(ValueError):
        pk.pack(x=16)
    with pytest.raises(ValueError):
        pk.pack(y=1)


def test_dedupe_sorted_marks_first_occurrences():
    fps = jnp.asarray(
        np.asarray([9, 3, 9, int(MASK64), 3, 7], np.uint64)
    )
    order, first = dedupe_sorted(fps)
    sorted_fps = np.asarray(fps)[np.asarray(order)]
    firsts = np.asarray(first)
    kept = sorted_fps[firsts].tolist()
    assert sorted(kept) == [3, 7, 9]  # EMPTY masked out, dups masked out


def test_hash_insert_dedupes_and_reports_novelty():
    cap = 16
    tfp = jnp.full((cap,), EMPTY, jnp.uint64)
    tpl = jnp.zeros((cap,), jnp.uint64)
    fps = jnp.asarray(np.asarray([10, 20, 30], np.uint64))
    pay = jnp.asarray(np.asarray([1, 2, 3], np.uint64))
    valid = jnp.ones((3,), bool)
    tfp, tpl, novel, overflow = hash_insert(tfp, tpl, fps, pay, valid)
    assert np.asarray(novel).all() and not bool(overflow)
    # re-insert: all duplicates now
    tfp, tpl, novel, overflow = hash_insert(tfp, tpl, fps, pay, valid)
    assert not np.asarray(novel).any()
    # payloads of the original insert survived
    table = np.asarray(tfp)
    payload = np.asarray(tpl)
    stored = {int(f): int(p) for f, p in zip(table, payload) if f != MASK64}
    assert stored == {10: 1, 20: 2, 30: 3}


def test_hash_insert_handles_slot_collisions():
    # Force many fps into the same home slot (same low bits): linear probing
    # must place them all.
    cap = 32
    tfp = jnp.full((cap,), EMPTY, jnp.uint64)
    tpl = jnp.zeros((cap,), jnp.uint64)
    n = 8
    fps_np = np.asarray([(i << 32) | 5 for i in range(1, n + 1)], np.uint64)
    fps = jnp.asarray(fps_np)  # all home to slot 5
    pay = jnp.asarray(np.arange(1, n + 1, dtype=np.uint64))
    tfp, tpl, novel, overflow = hash_insert(
        tfp, tpl, fps, pay, jnp.ones((n,), bool)
    )
    assert np.asarray(novel).all() and not bool(overflow)
    stored = {
        int(f): int(p)
        for f, p in zip(np.asarray(tfp), np.asarray(tpl))
        if f != MASK64
    }
    assert stored == {int(f): int(p) for f, p in zip(fps_np, pay)}


def test_hash_insert_overflow_on_full_table():
    cap = 4
    tfp = jnp.full((cap,), EMPTY, jnp.uint64)
    tpl = jnp.zeros((cap,), jnp.uint64)
    fps = jnp.asarray(np.asarray([1, 2, 3, 4, 5, 6], np.uint64))
    pay = jnp.zeros((6,), jnp.uint64)
    _, _, novel, overflow = hash_insert(
        tfp, tpl, fps, pay, jnp.ones((6,), bool)
    )
    assert bool(overflow)
