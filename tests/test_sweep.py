"""Hyper-batched instance sweeps (stateright_tpu/sweep/, docs/sweep.md).

The acceptance pins, per ISSUE 15:

 - an N>=8-instance sweep reconciles EVERY instance's unique/total
   counts, property verdicts, and discovery traces bit-identically
   against its own sequential oracle run, with exactly ONE cohort
   engine compile (pinned via compile-event count) versus N
   sequentially;
 - sweep off leaves the step jaxpr bit-identical and the engine cache
   unkeyed (the wavefront engine carries zero sweep coupling);
 - kill+resume mid-sweep (the snapshot carries instance tags);
 - fingerprint namespacing: host ``ns_fingerprint`` == device
   ``ns_hash`` bit-for-bit, order-preserving within an instance.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from stateright_tpu.fingerprint import (
    mix64,
    ns_fingerprint,
    sweep_ns_bits,
    unmix64,
)
from stateright_tpu.models.paxos import paxos_model
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.sweep import SweepInstance, SweepSpec
from stateright_tpu.sweep.cohort import build_cohorts

from fixtures_sweep import BoundedCounterSys, bounded_counter_spec

TPC3 = (288, 1146, 10)  # unique, states, depth (pinned 2pc.rs:138)
PAXOS1 = (265, 482, 13)


def _sweep(spec, *, cartography=False, runs=None, **kw):
    b = spec.instances[0].model.checker()
    telemetry = kw.pop("telemetry", False)
    if cartography or telemetry:
        b = b.telemetry(cartography=cartography)
    if runs:
        b = b.runs(runs)
    kw.setdefault("capacity", 1 << 12)
    kw.setdefault("batch", 64)
    return b.sweep(spec).spawn_tpu(sync=True, **kw)


def _oracle(model, *, cartography=False, **kw):
    b = model.checker()
    if cartography:
        b = b.telemetry(cartography=True)
    kw.setdefault("capacity", 1 << 12)
    kw.setdefault("batch", 64)
    return b.spawn_tpu(sync=True, **kw)


def _assert_instance_parity(sweep, key, oracle, cartography=False):
    r = sweep.results[key]
    assert (r.unique, r.states, r.max_depth) == (
        oracle.unique_state_count(),
        oracle.state_count(),
        oracle.max_depth(),
    )
    sd = sweep.instance_discoveries(key)
    od = oracle.discoveries()
    assert sorted(sd) == sorted(od)
    for name in od:
        # discovery traces bit-identical: same states, same actions
        assert sd[name].states() == od[name].states()
        assert sd[name].actions() == od[name].actions()
    if cartography:
        oc, rc = oracle.cartography(), r.cartography
        # exact parity for the generated-state counters; the depth
        # histograms are different ESTIMATORS (sweep = exact bincount,
        # wavefront = sorted-prefix searchsorted) and only reconcile by
        # sum — docs/sweep.md
        assert rc["action_hist"] == oc["action_hist"]
        assert rc["props"] == oc["props"]
        assert rc["fresh_inserts"] == oc["fresh_inserts"]
        assert rc["duplicate_hits"] == oc["duplicate_hits"]
        assert sum(rc["depth_hist"]) == r.unique


# -- fingerprint namespacing ------------------------------------------------


def test_unmix64_inverts_mix64():
    rng = np.random.default_rng(7)
    for x in [0, 1, (1 << 64) - 1] + [
        int(v) for v in rng.integers(0, 1 << 63, 32, dtype=np.uint64)
    ]:
        assert unmix64(mix64(x)) == x
        assert mix64(unmix64(x)) == x


def test_ns_fingerprint_matches_device_ns_hash():
    from stateright_tpu.ops.hashing import ns_hash

    rng = np.random.default_rng(3)
    fps = rng.integers(1, (1 << 63), 64, dtype=np.uint64)
    for bits, tag, seed in ((1, 0, 0), (3, 5, 0), (4, 9, 12345)):
        host = np.asarray(
            [ns_fingerprint(int(f), tag, seed, bits) for f in fps],
            np.uint64,
        )
        from stateright_tpu.fingerprint import (
            SWEEP_NS_SEED,
            fold64,
        )

        xor = (
            np.uint64(0) if not seed
            else np.uint64(mix64(fold64(SWEEP_NS_SEED, seed)))
        )
        dev = np.asarray(ns_hash(
            jnp.asarray(fps),
            jnp.full((64,), np.uint64(tag)),
            jnp.full((64,), xor),
            bits,
        ))
        assert np.array_equal(host, dev)


def test_ns_is_order_preserving_and_disjoint():
    """Within an instance the sort key keeps the raw order (trace
    parity's mechanism); across instances the namespaced fps are
    disjoint even for IDENTICAL raw fps."""
    rng = np.random.default_rng(11)
    fps = sorted(
        int(v) for v in rng.integers(1, 1 << 62, 128, dtype=np.uint64)
    )
    bits = 3
    keyed = [mix64(ns_fingerprint(f, 2, 0, bits)) for f in fps]
    raw_order = sorted(range(128), key=lambda i: mix64(fps[i]))
    ns_order = sorted(range(128), key=lambda i: keyed[i])
    assert raw_order == ns_order
    a = {ns_fingerprint(f, 0, 0, bits) for f in fps}
    b = {ns_fingerprint(f, 1, 0, bits) for f in fps}
    assert not (a & b)


def test_sweep_ns_bits():
    assert sweep_ns_bits(1) == 1
    assert sweep_ns_bits(2) == 1
    assert sweep_ns_bits(3) == 2
    assert sweep_ns_bits(8) == 3
    assert sweep_ns_bits(9) == 4
    assert sweep_ns_bits(1000) == 10


# -- spec + cohorts ----------------------------------------------------------


def test_spec_rejects_duplicates_and_empty():
    with pytest.raises(ValueError):
        SweepSpec([])
    with pytest.raises(ValueError):
        SweepSpec([
            SweepInstance("a", TwoPhaseSys(3)),
            SweepInstance("a", TwoPhaseSys(3)),
        ])


def test_cohort_grouping_and_const_lifting():
    """Bounded counters with differing bounds unify into ONE cohort
    (the bound is lifted twin data); a 2pc member lands in its own."""
    spec = SweepSpec(
        list(bounded_counter_spec([2, 3, 5]).instances)
        + [SweepInstance("2pc", TwoPhaseSys(3))]
    )
    cohorts = build_cohorts(spec)
    assert [c.K for c in cohorts] == [3, 1]
    assert cohorts[0].unified
    # namespace tags are GLOBAL spec positions, not cohort-local
    assert cohorts[0].global_index == [0, 1, 2]
    assert cohorts[1].global_index == [3]


# -- the acceptance sweep ----------------------------------------------------


@pytest.mark.medium
def test_eight_instance_sweep_one_compile_full_parity():
    """ISSUE 15 acceptance: 8 bound-swept instances, ONE cohort engine
    compile (compile-event count) versus 8 sequentially, and every
    instance's counts/verdicts/traces bit-identical to its own
    sequential oracle."""
    bounds = [1, 2, 3, 4, 5, 6, 7, 8]
    spec = bounded_counter_spec(bounds, counters=2)
    c = _sweep(spec, telemetry=True, cartography=True, batch=32)
    assert len(c.cohorts) == 1 and c.cohorts[0].K == 8
    assert c.engine_compiles == 1
    assert len(c.flight_recorder.records("compile")) == 1
    seq_compiles = 0
    for bound in bounds:
        o = (
            BoundedCounterSys(bound).checker()
            .telemetry(cartography=True)
            .spawn_tpu(sync=True, capacity=1 << 12, batch=32)
        )
        seq_compiles += len(o.flight_recorder.records("compile"))
        r = c.results[f"bc-b{bound}"]
        assert r.unique == (bound + 1) ** 2
        assert r.max_depth == 2 * bound
        _assert_instance_parity(
            c, f"bc-b{bound}", o, cartography=True
        )
    assert seq_compiles >= 8  # one per instance sequentially
    # the sweep ring records tell the same story
    recs = c.flight_recorder.records("sweep")
    events = [r["event"] for r in recs]
    assert events.count("cohort_compile") == 1
    assert events.count("instance_done") == 8
    assert events[-1] == "summary"
    assert recs[-1]["engine_compiles"] == 1


def test_seed_sweep_shares_one_program_and_reconciles():
    """Table-seed fuzzing: same dynamics under distinct namespaces —
    one cohort, one compile, every member at the pinned 2pc-3 counts."""
    spec = TwoPhaseSys(3).sweep_family(4)
    c = _sweep(spec, telemetry=True)
    assert len(c.cohorts) == 1 and c.engine_compiles == 1
    for inst in spec.instances:
        r = c.results[inst.key]
        assert (r.unique, r.states, r.max_depth) == TPC3
        assert sorted(r.chains) == [
            "abort agreement", "commit agreement",
        ]


@pytest.mark.slow
def test_paxos1_hand_twin_member_parity():
    spec = SweepSpec([
        SweepInstance("2pc", TwoPhaseSys(3)),
        SweepInstance("paxos1", paxos_model(1, 3)),
    ])
    c = _sweep(spec, cartography=True, capacity=1 << 13, batch=256)
    assert len(c.cohorts) == 2
    _assert_instance_parity(
        c, "2pc", _oracle(TwoPhaseSys(3), cartography=True,
                          capacity=1 << 13, batch=256),
        cartography=True,
    )
    _assert_instance_parity(
        c, "paxos1", _oracle(paxos_model(1, 3), cartography=True,
                             capacity=1 << 13, batch=256),
        cartography=True,
    )
    r = c.results["paxos1"]
    assert (r.unique, r.states, r.max_depth) == PAXOS1


def test_per_instance_target_early_termination():
    """A targeted instance stops early without stalling (or corrupting)
    the full-enumeration member sharing its cohort."""
    spec = SweepSpec([
        SweepInstance("full", TwoPhaseSys(3)),
        SweepInstance("prefix", TwoPhaseSys(3), target=5),
    ])
    c = _sweep(spec, batch=16)
    assert c.results["full"].unique == TPC3[0]
    pre = c.results["prefix"].unique
    assert 5 <= pre < TPC3[0]


def test_growth_preserves_per_instance_counts():
    spec = SweepSpec([
        SweepInstance("a", TwoPhaseSys(4)),
        SweepInstance("b", TwoPhaseSys(4), seed=9),
    ])
    c = _sweep(spec, capacity=1 << 10, batch=32, steps_per_call=4)
    assert c.growth_events, "tiny capacity must force growth"
    for k in ("a", "b"):
        assert c.results[k].unique == 1568


# -- off-contract ------------------------------------------------------------


def test_sweep_off_is_the_plain_engine_and_cache_unkeyed(monkeypatch):
    """No sweep requested => spawn_tpu returns the plain wavefront
    checker with the pre-sweep cache key and step program; the env knob
    on a model without a family prints the loud one-liner and changes
    NOTHING (key + jaxpr pinned equal)."""
    from stateright_tpu.parallel.wavefront import TpuChecker

    def spawn():
        c = TwoPhaseSys(3).checker().spawn_tpu(
            sync=True, capacity=1 << 12, batch=64
        )
        assert type(c) is TpuChecker
        key = c._engine_key(c._cap, c._qcap, c._batch, c._cand)
        init_fn, run_fn = c._engine(c._cap, c._qcap, c._batch, c._cand)
        carry, _ = init_fn()
        return key, str(jax.make_jaxpr(lambda cr: run_fn(cr))(
            tuple(carry)
        ))

    k_off, j_off = spawn()
    assert not any("sweep" in str(e) for e in k_off)
    monkeypatch.setenv("STATERIGHT_TPU_SWEEP", "1")

    class NoFamily(TwoPhaseSys):
        pass

    m = NoFamily(3)
    m.sweep_family = None  # the knob finds no family hook
    c2 = m.checker().spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    assert type(c2) is TpuChecker
    monkeypatch.delenv("STATERIGHT_TPU_SWEEP")
    k_on, j_on = spawn()
    assert k_on == k_off and j_on == j_off


def test_env_knob_routes_models_with_a_family(monkeypatch):
    from stateright_tpu.sweep.engine import SweepChecker

    monkeypatch.setenv("STATERIGHT_TPU_SWEEP", "2")
    c = TwoPhaseSys(3).checker().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    assert isinstance(c, SweepChecker)
    assert len(c.spec.instances) == 2
    for r in c.results.values():
        assert (r.unique, r.states) == TPC3[:2]


def test_sweep_rejects_unsupported_modes():
    spec = SweepSpec([SweepInstance("a", TwoPhaseSys(3))])
    for cfg in (
        lambda b: b.por(),
        lambda b: b.spill(),
        lambda b: b.checked(),
        lambda b: b.mxu(),
        lambda b: b.prededup(),
        lambda b: b.symmetry(),
        lambda b: b.autosave("/tmp/nope"),
    ):
        with pytest.raises(NotImplementedError):
            cfg(TwoPhaseSys(3).checker().sweep(spec)).spawn_tpu(
                sync=True
            )
    with pytest.raises(NotImplementedError):
        TwoPhaseSys(3).checker().sweep(spec).spawn_tpu(devices=2)


# -- kill + resume mid-sweep -------------------------------------------------


@pytest.mark.medium
def test_kill_resume_mid_sweep(tmp_path):
    """The snapshot carries instance tags + completed-instance results;
    the resumed sweep finishes every member at oracle counts with the
    lineage header set."""
    import time

    spec = SweepSpec([
        SweepInstance("2pc-3", TwoPhaseSys(3)),
        SweepInstance("2pc-5", TwoPhaseSys(5)),
    ])
    c = (
        TwoPhaseSys(3).checker().telemetry(cartography=True)
        .sweep(spec).spawn_tpu(
            capacity=1 << 12, batch=64, steps_per_call=2
        )
    )
    deadline = time.monotonic() + 60
    snap = None
    while time.monotonic() < deadline:
        try:
            snap = c.checkpoint(timeout=10)
            break
        except (TimeoutError, RuntimeError):
            if c.is_done():
                snap = c.checkpoint()
                break
    assert snap is not None and "q_tag" in snap
    c.stop().join()
    p = tmp_path / "sweep.npz"
    np.savez(p, **{k: np.asarray(v) for k, v in snap.items()})
    loaded = dict(np.load(p, allow_pickle=False))
    spec2 = SweepSpec([
        SweepInstance("2pc-3", TwoPhaseSys(3)),
        SweepInstance("2pc-5", TwoPhaseSys(5)),
    ])
    c2 = (
        TwoPhaseSys(3).checker().telemetry(cartography=True)
        .sweep(spec2).spawn_tpu(
            sync=True, capacity=1 << 12, batch=64, resume=loaded
        )
    )
    assert c2.parent_run_id == c.run_id
    assert c2.results["2pc-3"].unique == 288
    assert c2.results["2pc-5"].unique == 8832
    assert sorted(c2.instance_discoveries("2pc-5")) == [
        "abort agreement", "commit agreement",
    ]
    # the snapshot's banked depth lanes keep the resumed per-instance
    # depth histograms COMPLETE: sum(depth_hist) == unique per instance
    # even across the kill's pre-snapshot growth compactions
    for key, unique in (("2pc-3", 288), ("2pc-5", 8832)):
        dh = c2.results[key].cartography["depth_hist"]
        assert sum(dh) == unique, (key, sum(dh))


def test_resume_refuses_a_foreign_sweep(tmp_path):
    spec = SweepSpec([SweepInstance("a", TwoPhaseSys(3))])
    c = TwoPhaseSys(3).checker().sweep(spec).spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    snap = c.checkpoint()
    other = SweepSpec([SweepInstance("b", TwoPhaseSys(4))])
    with pytest.raises(ValueError, match="different sweep"):
        TwoPhaseSys(3).checker().sweep(other).spawn_tpu(
            sync=True, resume=snap
        )
    from stateright_tpu.parallel.wavefront import TpuChecker  # noqa: F401

    with pytest.raises(ValueError, match="sweep"):
        TwoPhaseSys(3).checker().spawn_tpu(sync=True, resume=snap)


# -- registry + diff ---------------------------------------------------------


def test_registry_per_instance_records_and_identical_diff(tmp_path):
    """One index record per instance tagged sweep_id/instance_key, and
    the sweep-instance-vs-sequential-oracle pair classifies IDENTICAL
    under the contract-aware diff (the one-command parity check)."""
    from stateright_tpu.telemetry.diff import diff_reports
    from stateright_tpu.telemetry.registry import RunRegistry

    runs = str(tmp_path / "runs")
    spec = bounded_counter_spec([2, 3])
    c = _sweep(spec, cartography=True, runs=runs, batch=32)
    c.join()
    reg = RunRegistry(runs)
    idx = reg.index()
    assert len(idx) == 2
    assert {r["instance_key"] for r in idx} == {"bc-b2", "bc-b3"}
    assert all(r["sweep_id"] == c.run_id for r in idx)
    o = (
        BoundedCounterSys(3).checker().telemetry(cartography=True)
        .runs(runs).spawn_tpu(sync=True, capacity=1 << 12, batch=32)
    )
    o.join()
    idx = reg.index()
    swp = next(r for r in idx if r.get("instance_key") == "bc-b3")
    seq = next(r for r in idx if not r.get("sweep_id"))
    d = diff_reports(reg.load(swp["run_id"]), reg.load(seq["run_id"]))
    assert d["verdict"] == "IDENTICAL", d["violations"]
    assert d["config_delta"]["flags.sweep"]["class"] == "identical"
    assert d["config_delta"]["engine"]["a"] == "sweep"
    # tampering an instance record still trips the counts gate
    doc = reg.load(swp["run_id"])
    doc["totals"]["unique"] += 1
    d2 = diff_reports(doc, reg.load(seq["run_id"]))
    assert d2["verdict"] == "DIVERGENT"


def test_runs_verb_groups_sweep_members(tmp_path):
    import io

    from stateright_tpu.models._cli import fleet_runs

    runs = str(tmp_path / "runs")
    spec = bounded_counter_spec([2, 3])
    _sweep(spec, runs=runs, batch=32).join()
    buf = io.StringIO()
    assert fleet_runs([runs], stream=buf) == 0
    out = buf.getvalue()
    assert "2 instance(s)" in out
    assert "verdicts [**]" in out
    assert "[bc-b2]" in out and "[bc-b3]" in out


# -- the mixed-family crawl (lossy/non-lossy paxos + 2pc) --------------------


@pytest.mark.slow
def test_mixed_lossiness_sweep_full_parity():
    """The ISSUE's sweep: 2pc + lossy/non-lossy paxos-1 (hand twin +
    compiled twins, three shape cohorts), every instance reconciling
    counts/verdicts/traces/cartography against its sequential oracle."""
    lossy = paxos_model(1, 3)
    lossy.lossy_network(True)
    spec = SweepSpec([
        SweepInstance("2pc-3", TwoPhaseSys(3)),
        SweepInstance("paxos1", paxos_model(1, 3)),
        SweepInstance("paxos1-lossy", lossy),
    ])
    c = _sweep(spec, cartography=True, capacity=1 << 13, batch=256)
    assert len(c.cohorts) == 3
    oracle_models = {
        "2pc-3": TwoPhaseSys(3),
        "paxos1": paxos_model(1, 3),
        "paxos1-lossy": (lambda m: (m.lossy_network(True), m)[1])(
            paxos_model(1, 3)
        ),
    }
    for key, m in oracle_models.items():
        _assert_instance_parity(
            c, key,
            _oracle(m, cartography=True, capacity=1 << 13, batch=256),
            cartography=True,
        )
    assert c.results["paxos1-lossy"].unique == 2378


@pytest.mark.medium
def test_lossy_cohort_members_unify_across_twin_instances():
    """Two lossy paxos-1 instances compile to ONE cohort program even
    though each carries its own compiled twin object."""
    def lossy():
        m = paxos_model(1, 3)
        m.lossy_network(True)
        return m

    spec = SweepSpec([
        SweepInstance("l0", lossy()),
        SweepInstance("l1", lossy(), seed=3),
    ])
    c = _sweep(spec, telemetry=True, capacity=1 << 15, batch=256)
    assert len(c.cohorts) == 1 and c.engine_compiles == 1
    assert c.results["l0"].unique == c.results["l1"].unique == 2378


# -- CLI verb ----------------------------------------------------------------


def test_sweep_cli_verb(capsys):
    from stateright_tpu.models import two_phase_commit

    two_phase_commit.main([
        "sweep", "2", "--batch=64", "--capacity=4096",
    ])
    out = capsys.readouterr().out
    assert "2 instances over 1 cohort(s), 1 engine compile(s)" in out
    assert "2pc3-seed0: unique=288 states=1146" in out


# -- closure fail-fast estimate (actor_compiler satellite) -------------------


def test_closure_estimator_trips_fast_on_paxos3_per_channel():
    import time

    from stateright_tpu.models.paxos import PaxosState
    from stateright_tpu.parallel.actor_compiler import (
        CompileError,
        compile_actor_model,
    )

    m = paxos_model(3, 3)
    m.per_channel_(True)
    t0 = time.monotonic()
    with pytest.raises(CompileError, match="pre-closure estimate"):
        compile_actor_model(
            m,
            state_bound=lambda i, s: not isinstance(s, PaxosState)
            or s.ballot[0] <= 3,
            env_bound=lambda e: e.msg[0] != "internal"
            or e.msg[1][1][0] <= 3,
        )
    assert time.monotonic() - t0 < 20


def test_closure_estimator_escape_hatch(monkeypatch):
    """STATERIGHT_TPU_CLOSURE_ESTIMATE=off keeps the old exact-wall
    behavior (and legit closures never consult the estimator at all —
    the fleet compiles are pinned elsewhere)."""
    monkeypatch.setenv("STATERIGHT_TPU_CLOSURE_ESTIMATE", "off")
    m = paxos_model(2, 3)
    m.per_channel_(True)
    assert m.tensor_model() is not None


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import sys

    sys.exit(pytest.main([__file__, "-v"]))
