"""Example-model parity tests: pinned unique-state counts and witness traces
(reference ``examples/*.rs`` tests; values mirrored in BASELINE.md)."""

import pytest

from stateright_tpu import Property
from stateright_tpu.actor import Deliver, Id
from stateright_tpu.actor.register import Get, GetOk, Internal, Put, PutOk
from stateright_tpu.models.increment import Increment
from stateright_tpu.models.increment_lock import IncrementLock
from stateright_tpu.models.linearizable_register import (
    AckQuery,
    AckRecord,
    Query,
    Record,
    abd_model,
)
from stateright_tpu.models.paxos import paxos_model
from stateright_tpu.models.single_copy_register import single_copy_model
from stateright_tpu.models.two_phase_commit import TwoPhaseSys


# ---------------------------------------------------------------------------
# 2PC (reference ``2pc.rs:125-140``)
# ---------------------------------------------------------------------------

def test_2pc_bfs_3_rms():
    checker = TwoPhaseSys(3).checker().spawn_bfs().join()
    assert checker.unique_state_count() == 288
    checker.assert_properties()


def test_2pc_dfs_5_rms():
    checker = TwoPhaseSys(5).checker().spawn_dfs().join()
    assert checker.unique_state_count() == 8832
    checker.assert_properties()


def test_2pc_dfs_5_rms_symmetry():
    checker = TwoPhaseSys(5).checker().symmetry().spawn_dfs().join()
    assert checker.unique_state_count() == 665
    checker.assert_properties()


# ---------------------------------------------------------------------------
# single-copy register (reference ``single-copy-register.rs:84-122``)
# ---------------------------------------------------------------------------

def test_single_copy_one_server_linearizable():
    checker = single_copy_model(2, 1).checker().spawn_dfs().join()
    assert checker.unique_state_count() == 93
    checker.assert_properties()
    checker.assert_discovery(
        "value chosen",
        [
            Deliver(src=Id(2), dst=Id(0), msg=Put(2, "B")),
            Deliver(src=Id(0), dst=Id(2), msg=PutOk(2)),
            Deliver(src=Id(2), dst=Id(0), msg=Get(4)),
        ],
    )


def test_single_copy_two_servers_violation():
    checker = single_copy_model(2, 2).checker().spawn_bfs().join()
    # stale read: client 3 puts 'B' to server 1, then reads '\0' from server 0
    checker.assert_discovery(
        "linearizable",
        [
            Deliver(src=Id(3), dst=Id(1), msg=Put(3, "B")),
            Deliver(src=Id(1), dst=Id(3), msg=PutOk(3)),
            Deliver(src=Id(3), dst=Id(0), msg=Get(6)),
            Deliver(src=Id(0), dst=Id(3), msg=GetOk(6, "\0")),
        ],
    )
    # NOTE: the reference pins 20 here; the exact early-exit count depends on
    # within-level exploration order (its HashSet iteration order), which is
    # implementation-specific. Ours is deterministic too, just different.
    assert checker.unique_state_count() == 26


# ---------------------------------------------------------------------------
# ABD linearizable register (reference ``linearizable-register.rs:234-282``)
# ---------------------------------------------------------------------------

def test_abd_2_clients_2_servers():
    checker = abd_model(2, 2).checker().spawn_bfs().join()
    assert checker.unique_state_count() == 544
    checker.assert_properties()
    checker.assert_discovery(
        "value chosen",
        [
            Deliver(src=Id(3), dst=Id(1), msg=Put(3, "B")),
            Deliver(src=Id(1), dst=Id(0), msg=Internal(Query(3))),
            Deliver(
                src=Id(0),
                dst=Id(1),
                msg=Internal(AckQuery(3, (0, Id(0)), "\0")),
            ),
            Deliver(
                src=Id(1),
                dst=Id(0),
                msg=Internal(Record(3, (1, Id(1)), "B")),
            ),
            Deliver(src=Id(0), dst=Id(1), msg=Internal(AckRecord(3))),
            Deliver(src=Id(1), dst=Id(3), msg=PutOk(3)),
            Deliver(src=Id(3), dst=Id(0), msg=Get(6)),
            Deliver(src=Id(0), dst=Id(1), msg=Internal(Query(6))),
            Deliver(
                src=Id(1),
                dst=Id(0),
                msg=Internal(AckQuery(6, (1, Id(1)), "B")),
            ),
            Deliver(
                src=Id(0),
                dst=Id(1),
                msg=Internal(Record(6, (1, Id(1)), "B")),
            ),
            Deliver(src=Id(1), dst=Id(0), msg=Internal(AckRecord(6))),
        ],
    )


def test_abd_dfs_matches():
    checker = abd_model(2, 2).checker().spawn_dfs().join()
    assert checker.unique_state_count() == 544
    checker.assert_properties()


# ---------------------------------------------------------------------------
# Paxos (reference ``paxos.rs:270-312``) — the benchmark workload
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_paxos_2_clients_3_servers():
    checker = paxos_model(2, 3).checker().spawn_bfs().join()
    assert checker.unique_state_count() == 16668
    checker.assert_properties()
    checker.assert_discovery(
        "value chosen",
        [
            Deliver(src=Id(4), dst=Id(1), msg=Put(4, "B")),
            Deliver(
                src=Id(1),
                dst=Id(0),
                msg=Internal(("prepare", (1, Id(1)))),
            ),
            Deliver(
                src=Id(0),
                dst=Id(1),
                msg=Internal(("prepared", (1, Id(1)), None)),
            ),
            Deliver(
                src=Id(1),
                dst=Id(2),
                msg=Internal(("accept", (1, Id(1)), (4, Id(4), "B"))),
            ),
            Deliver(
                src=Id(2),
                dst=Id(1),
                msg=Internal(("accepted", (1, Id(1)))),
            ),
            Deliver(src=Id(1), dst=Id(4), msg=PutOk(4)),
            Deliver(
                src=Id(1),
                dst=Id(2),
                msg=Internal(("decided", (1, Id(1)), (4, Id(4), "B"))),
            ),
            Deliver(src=Id(4), dst=Id(2), msg=Get(8)),
        ],
    )


# ---------------------------------------------------------------------------
# increment / increment_lock (reference ``increment.rs:36-105``)
# ---------------------------------------------------------------------------

class _IncrementFull(Increment):
    """Disable early exit to enumerate the documented full space."""

    def properties(self):
        return list(super().properties()) + [
            Property.sometimes("never", lambda m, s: False)
        ]


def test_increment_full_space_documented_counts():
    checker = _IncrementFull(2).checker().spawn_dfs().join()
    assert checker.unique_state_count() == 13
    checker = _IncrementFull(2).checker().symmetry().spawn_dfs().join()
    assert checker.unique_state_count() == 8


def test_increment_race_found():
    checker = Increment(2).checker().spawn_bfs().join()
    path = checker.assert_any_discovery("fin")  # the data race
    # interleaved read-read-write-write: counter 1, finished 2
    final = path.final_state()
    assert sum(1 for _t, pc in final.s if pc == 3) != final.i


def test_increment_lock_holds():
    checker = IncrementLock(2).checker().spawn_bfs().join()
    checker.assert_no_discovery("fin")
    checker.assert_no_discovery("mutex")


def test_increment_lock_symmetry():
    full = IncrementLock(3).checker().spawn_dfs().join()
    sym = IncrementLock(3).checker().symmetry().spawn_dfs().join()
    # same verdicts under reduction
    assert not sym.discoveries() and not full.discoveries()
