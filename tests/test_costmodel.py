"""Roofline cost ledger (analysis/costmodel.py + telemetry/roofline.py).

Pins the round's contracts (docs/roofline.md):

 - ZERO ENGINE IMPACT (the family's strongest form): roofline on or off
   leaves the engine's step jaxpr bit-identical and the engine cache
   unkeyed — the ledger re-traces kernels on the side, it never touches
   the run program;
 - RECONCILIATION: the analytic per-stage FLOPs/bytes totals land
   inside the pinned tolerance bands of XLA's own
   ``compiled.cost_analysis()`` on the 2pc and paxos twins, and the
   purely elementwise ``hash`` stage charges FLOPs EXACTLY equal to
   XLA's count ("exact where XLA reports exact");
 - the run report's ``roofline`` block is DETERMINISTIC (static costs
   only — XLA numbers, device specs, and wall clock never enter the
   JSON body);
 - op classification, per-action attribution via the action-axis
   decomposition, the JX4xx MXU-candidate ranking, the device-spec
   table + ``STATERIGHT_TPU_DEVICE_SPEC`` override, and the CPU
   degradation (no spec ⇒ arithmetic-intensity-only, never a crash).
"""

import json

import pytest

import jax

from stateright_tpu.analysis.costmodel import (
    BYTES_HI,
    BYTES_LO,
    COSTMODEL_V,
    FLOPS_BAND,
    classify_primitive,
    wavefront_costs,
    xla_cost,
)
from stateright_tpu.models.paxos import paxos_model
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.parallel.tensor_model import twin_or_none
from stateright_tpu.telemetry.roofline import (
    ENV_DEVICE_SPEC,
    ROOFLINE_V,
    achieved_block,
    classify_stages,
    device_spec,
)
from tests.helpers import requires_sharded_collectives

_KW = dict(capacity=1 << 12, batch=64)
_STAGES = ("property", "expand", "hash", "dedup-insert", "queue")


def _twin(model):
    cached = getattr(model, "_tensor_cached", None)
    return cached() if cached is not None else model.tensor_model()


# -- zero engine impact ------------------------------------------------------


def _wavefront_build_jaxpr(roofline: bool) -> str:
    m = TwoPhaseSys(3)
    b = m.checker()
    if roofline:
        b = b.telemetry(roofline=True)
    c = b.spawn_tpu(sync=True, **_KW)
    init_fn, run_fn = c._build(c._cap, c._qcap, c._batch, c._cand)
    carry, _ = init_fn()
    # fresh lambda per call: make_jaxpr memoizes on fn identity
    return str(jax.make_jaxpr(lambda cr: run_fn(cr))(tuple(carry)))


def test_roofline_leaves_run_jaxpr_bit_identical():
    """The ledger never touches the device program — ON is bit-identical
    to OFF (re-traced side kernels only)."""
    assert _wavefront_build_jaxpr(False) == _wavefront_build_jaxpr(True)


def test_roofline_does_not_key_the_engine_cache():
    """Roofline on/off must share one compiled engine: a roofline-off
    spawn after a roofline-on spawn on the same model is a cache HIT."""
    m = TwoPhaseSys(3)
    c1 = m.checker().telemetry(roofline=True).spawn_tpu(sync=True, **_KW)
    n_keys = len(c1.tensor._run_cache)
    c2 = m.checker().telemetry().spawn_tpu(sync=True, **_KW)
    assert len(c2.tensor._run_cache) == n_keys
    assert c2.unique_state_count() == c1.unique_state_count()


@requires_sharded_collectives
def test_sharded_roofline_block_and_cache_identity():
    """The sharded engine carries the model-kernel ledger (its insert /
    all-to-all are the pod-scale round's work) under the same
    cache-identity contract."""
    m = TwoPhaseSys(3)
    c1 = (
        m.checker().telemetry(roofline=True)
        .spawn_tpu(sync=True, devices=2, capacity=1 << 12)
    )
    roof = c1.roofline()
    assert roof is not None and roof["engine"] == "sharded"
    assert set(roof["stages"]) == {"property", "expand", "hash"}


# -- reconciliation (the acceptance-criteria pin) ----------------------------


@pytest.mark.parametrize("model_fn", [
    lambda: TwoPhaseSys(3),
    lambda: paxos_model(1),
], ids=["2pc", "paxos"])
def test_analytic_totals_reconcile_against_xla(model_fn):
    """The pinned contract: every stage's analytic FLOPs/bytes land
    inside the tolerance bands of XLA's own cost_analysis() on the 2pc
    AND paxos twins."""
    m = model_fn()
    twin = _twin(m)
    rep = wavefront_costs(twin, 1 << 12, 1 << 11, 64)
    assert rep is not None
    recon = rep.recon_block()
    assert recon["ok"], recon
    for name in _STAGES:
        assert name in rep.stages, sorted(rep.stages)
        v = recon["stages"][name]
        if v.get("xla_flops"):
            r = v["flops_ratio"]
            assert 1.0 / FLOPS_BAND <= r <= FLOPS_BAND, (name, v)
        if v.get("xla_bytes"):
            r = v["bytes_ratio"]
            assert BYTES_LO <= r <= BYTES_HI, (name, v)


def test_hash_stage_flops_exact_where_xla_is_exact():
    """"Exact where XLA reports exact": the hash stage is purely
    elementwise — both models count one scalar op per output element,
    so the analytic FLOPs equal XLA's bit-for-bit on both twins."""
    import jax.numpy as jnp
    import numpy as np

    from stateright_tpu.ops.hashing import row_hash

    for m in (TwoPhaseSys(3), paxos_model(1)):
        twin = _twin(m)
        np.asarray(twin.init_rows())
        rep = wavefront_costs(twin, 1 << 12, 1 << 11, 64)
        aval = jax.ShapeDtypeStruct(
            (64, twin.max_actions, twin.width), jnp.uint64
        )
        xla = xla_cost(row_hash, (aval,))
        if not xla or not xla.get("flops"):
            pytest.skip("backend exposes no cost_analysis flops")
        assert rep.stages["hash"].flops == xla["flops"]


# -- classification + attribution units --------------------------------------


def test_classify_primitive_covers_the_catalogue():
    assert classify_primitive("gather") == "gather"
    assert classify_primitive("dynamic_slice") == "gather"
    assert classify_primitive("scatter") == "scatter"
    assert classify_primitive("dynamic_update_slice") == "scatter"
    assert classify_primitive("sort") == "sort"
    assert classify_primitive("dot_general") == "dot"
    assert classify_primitive("reduce_sum") == "reduce"
    assert classify_primitive("argmax") == "reduce"
    assert classify_primitive("while") == "control"
    assert classify_primitive("pjit") == "control"
    assert classify_primitive("add") == "elementwise"
    assert classify_primitive("reshape") == "elementwise"


def test_per_action_attribution_follows_the_decomposition():
    """2pc's hand twin decomposes per action: the attribution carries
    one entry per action slot plus the trailing shared bucket, with
    non-negative costs; the slot-multiset paxos twin does NOT decompose
    (JX302) and honestly reports None."""
    m = TwoPhaseSys(3)
    twin = _twin(m)
    rep = wavefront_costs(twin, 1 << 12, 1 << 11, 64)
    acts = rep.actions
    assert acts is not None
    assert len(acts) == twin.max_actions + 1
    assert acts[-1]["action"] == "shared"
    assert all(a["flops"] >= 0 and a["bytes"] >= 0 for a in acts)
    assert any(a["bytes"] > 0 for a in acts[:-1])

    p = paxos_model(1)
    prep = wavefront_costs(_twin(p), 1 << 12, 1 << 11, 64)
    assert prep.actions is None


def test_mxu_candidates_rank_by_bytes_and_emit_jx4xx():
    """The ranking is byte-descending, every candidate is a
    gather/scatter/sort site, and the findings carry the JX400/JX401
    per-candidate rules plus the JX402 summary."""
    m = TwoPhaseSys(3)
    rep = wavefront_costs(_twin(m), 1 << 12, 1 << 11, 64)
    cands = rep.candidates
    assert cands, "2pc's insert pipeline must surface MXU candidates"
    byte_list = [c["bytes"] for c in cands]
    assert byte_list == sorted(byte_list, reverse=True)
    assert all(c["op_class"] in ("gather", "scatter", "sort")
               for c in cands)
    assert [c["rank"] for c in cands] == list(range(1, len(cands) + 1))
    rules = {f.rule_id for f in rep.findings}
    assert "JX400" in rules and "JX402" in rules
    # the dedup-insert membership gather is the known top hot spot
    assert cands[0]["stage"] == "dedup-insert"


# -- device spec + roofline classification ----------------------------------


def test_device_spec_env_override_and_cpu_degradation(monkeypatch, capsys):
    monkeypatch.delenv(ENV_DEVICE_SPEC, raising=False)
    spec = device_spec()
    if jax.devices()[0].platform == "cpu":
        assert spec is None  # arithmetic-intensity-only degradation
    monkeypatch.setenv(ENV_DEVICE_SPEC, "1.97e14:8.19e11:tpu-v5e")
    spec = device_spec()
    # the pre-split fields hold exactly (back-compat contract) ...
    assert {
        k: spec[k]
        for k in ("name", "peak_flops", "hbm_bytes_per_sec", "ridge",
                  "src")
    } == {
        "name": "tpu-v5e", "peak_flops": 1.97e14,
        "hbm_bytes_per_sec": 8.19e11,
        "ridge": 1.97e14 / 8.19e11, "src": "env",
    }
    # ... and the two-peak split rides along (MXU aliases the old pair;
    # VPU defaults to PEAK/64 for the 3-field form — docs/roofline.md)
    assert spec["mxu_peak"] == spec["peak_flops"]
    assert spec["mxu_ridge"] == spec["ridge"]
    assert spec["vpu_peak"] == 1.97e14 / 64.0
    assert spec["vpu_ridge"] == spec["vpu_peak"] / 8.19e11
    monkeypatch.setenv(ENV_DEVICE_SPEC, "garbage")
    assert device_spec() is None or device_spec()["src"] != "env"
    assert "malformed" in capsys.readouterr().err


def test_classify_stages_verdicts():
    static = {"stages": {
        "a": {"intensity": 0.05},
        "b": {"intensity": 500.0},
        "c": {},
    }}
    spec = {"peak_flops": 1e14, "hbm_bytes_per_sec": 1e12, "ridge": 100.0}
    v = classify_stages(static, spec)
    assert v["a"]["verdict"] == "memory-bound"
    assert v["b"]["verdict"] == "compute-bound"
    assert v["c"]["verdict"] == "unknown"
    # no spec: every verdict degrades to unknown, intensities survive
    v = classify_stages(static, None)
    assert {e["verdict"] for e in v.values()} == {"unknown"}
    assert v["a"]["intensity"] == 0.05


def test_achieved_block_math():
    static = {"totals": {"bytes": 1000, "flops": 100}, "batch": 10}
    spec = {"peak_flops": 1e6, "hbm_bytes_per_sec": 1e6, "ridge": 1.0}
    ach = achieved_block(
        static, spec, {"device_secs": 2.0}, unique=25, batch=10,
    )
    assert ach["est_device_steps"] == 3  # ceil(25 / 10)
    assert ach["bytes_per_sec"] == 1500.0
    assert ach["frac_of_hbm_ceiling"] == pytest.approx(0.0015)
    # sharded: the static costs price ONE chip's kernels, and a mesh
    # pops batch x devices rows per lockstep step — the per-chip view
    # must divide the step estimate by the mesh, not inflate the
    # achieved fraction ndev-fold
    ach = achieved_block(
        {**static, "devices": 4}, spec, {"device_secs": 2.0},
        unique=100, batch=10,
    )
    assert ach["est_device_steps"] == 3  # ceil(100 / (10 * 4))
    assert ach["bytes_per_sec"] == 1500.0
    # no attribution yet / no bytes: no achieved block, never a crash
    assert achieved_block(static, spec, None, 25, 10) is None
    assert achieved_block({"totals": {}}, spec,
                          {"device_secs": 2.0}, 25, 10) is None


def test_fold_into_report_merges_jx4xx_and_metrics():
    """The for-callers AuditReport hook (the independence.fold_into_report
    pattern): findings land deduped in the report, the metrics block
    carries the ledger summary."""
    from stateright_tpu.analysis import AuditReport
    from stateright_tpu.analysis.costmodel import fold_into_report

    m = TwoPhaseSys(3)
    rep = wavefront_costs(_twin(m), 1 << 12, 1 << 11, 64)
    report = AuditReport()
    fold_into_report(rep, report)
    rules = {f.rule_id for f in report.findings}
    assert "JX400" in rules and "JX402" in rules
    mc = report.metrics["costmodel"]
    assert mc["reconciled"] is True
    assert mc["flops"] == rep.total_flops
    assert mc["mxu_candidates"] == len(rep.candidates)


# -- checker surfaces --------------------------------------------------------


def _spawn(roofline=True, **kw):
    b = TwoPhaseSys(3).checker()
    b = b.telemetry(cartography=True, memory=True, roofline=roofline) \
        if roofline else b.telemetry()
    kw = {**_KW, **kw}
    return b.spawn_tpu(sync=True, **kw)


def test_roofline_accessor_off_and_on():
    assert _spawn(roofline=False).roofline() is None
    c = _spawn()
    live = c.roofline()
    assert live["v"] == COSTMODEL_V
    assert set(live["stages"]) == set(_STAGES)
    assert live["reconciliation"]["ok"]
    assert "verdicts" in live
    # achieved exists once stage attribution does (sync run is done)
    assert live.get("achieved") is None or (
        live["achieved"]["est_device_steps"] >= 1
    )


def test_report_roofline_block_is_deterministic_and_static_only(tmp_path):
    """The run report's roofline block is byte-stable across runs and
    carries NO XLA / device-spec / wall-clock fields (those live in the
    markdown rendering only)."""
    from stateright_tpu.telemetry.report import build_report

    bodies = []
    for i in range(2):
        c = (
            TwoPhaseSys(3).checker()
            .telemetry(roofline=True)
            .report(str(tmp_path / f"r{i}.json"))
            .spawn_tpu(sync=True, **_KW)
        )
        c.join()
        bodies.append(build_report(c)["roofline"])
    assert json.dumps(bodies[0], sort_keys=True) == json.dumps(
        bodies[1], sort_keys=True
    )
    blk = bodies[0]
    assert blk["v"] == COSTMODEL_V
    for forbidden in ("reconciliation", "device_spec", "verdicts",
                      "achieved"):
        assert forbidden not in blk, forbidden
    # totals reconcile against the per-stage sums (the regress gate)
    assert blk["totals"]["flops"] == sum(
        s["flops"] for s in blk["stages"].values()
    )
    assert blk["totals"]["bytes"] == sum(
        s["bytes_read"] + s["bytes_written"]
        for s in blk["stages"].values()
    )
    md = (tmp_path / "r1.md").read_text()
    assert "## Roofline (static cost model)" in md


def test_roofline_ring_record_and_metrics_block():
    c = _spawn()
    # the ledger's findings accessor mirrors CostReport's (JX4xx)
    assert {f.rule_id for f in c._roofline_ledger.findings()} >= {
        "JX400", "JX402",
    }
    recs = c.flight_recorder.records("roofline")
    assert len(recs) == 1 and recs[0]["at"] == "init"
    assert recs[0]["v"] == ROOFLINE_V
    assert recs[0]["reconciled"] is True
    assert "roofline" in c.flight_recorder.summary()
    from stateright_tpu.explorer import _metrics_view

    view = _metrics_view(c)
    assert view["roofline"]["totals"]["bytes"] > 0


def test_costmodel_verb_fleet_entry(capsys):
    """The per-example verb runs end-to-end and exits clean on 2pc."""
    from stateright_tpu.models import two_phase_commit

    two_phase_commit.main(["costmodel"])
    out = capsys.readouterr().out
    assert "XLA reconciliation: ok" in out
    assert "JX402" in out
