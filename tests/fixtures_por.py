"""Fixture models for the partial-order-reduction tests.

The bundled examples deliberately get NO reduction from sound POR — 2pc's
verdict-relevant actions are all property-visible (the C2 invisibility
condition), and the slot-multiset actor twins do not decompose per action
(JX302).  These fixtures are the models where reduction IS sound, so the
ample-set machinery's effect (and the cycle proviso's necessity) can be
pinned exactly:

 - :class:`WorkersSys` — ``n`` independent workers each advancing a
   private 2-bit counter 0→1→2; the properties read worker 0 only, so
   workers 1..n-1 are invisible and pairwise independent.  Full space =
   ``3^n`` states; the reduced search is linear in ``n``.
 - :class:`ToggleSys` — a cycle (worker A toggles a private bit) plus a
   visible one-shot action B.  Without the duplicate-based cycle proviso
   the reduced search would starve B forever on the A-cycle and lose the
   ``y set`` discovery; with it, all 4 states are found with strictly
   fewer generated candidates (5 < 6).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from stateright_tpu import Model, Property
from stateright_tpu.parallel.tensor_model import (
    BitPacker,
    TensorBackedModel,
    TensorModel,
)


class WorkersTensor(TensorModel):
    def __init__(self, sys: "WorkersSys"):
        self.model = sys
        self.n = sys.n
        self.packer = BitPacker([(f"f{i}", 2) for i in range(sys.n)])
        self.width = self.packer.width
        self.max_actions = sys.n

    def init_rows(self):
        return np.zeros((1, self.width), np.uint64)

    def encode_state(self, s):
        return self.packer.pack(**{f"f{i}": v for i, v in enumerate(s)})

    def decode_state(self, row):
        f = self.packer.unpack(row)
        return tuple(f[f"f{i}"] for i in range(self.n))

    def step_rows(self, rows):
        import jax.numpy as jnp

        pk = self.packer
        succs, valids = [], []
        for i in range(self.n):
            f = pk.get(rows, f"f{i}")
            valids.append(f < jnp.uint64(2))
            succs.append(pk.set(rows, f"f{i}", f + jnp.uint64(1)))
        return jnp.stack(succs, -2), jnp.stack(valids, -1)

    def property_masks(self, rows):
        import jax.numpy as jnp

        f0 = self.packer.get(rows, "f0")
        return jnp.stack(
            [f0 == jnp.uint64(2), f0 <= jnp.uint64(2)], -1
        )


@dataclass(frozen=True)
class WorkersSys(TensorBackedModel, Model):
    """``n`` independent private counters; properties read worker 0 only.
    The always-property never discovers, so full runs crawl the whole
    ``3^n`` space instead of early-exiting."""

    n: int

    def tensor_model(self):
        return WorkersTensor(self)

    def init_states(self):
        return [(0,) * self.n]

    def actions(self, s):
        return [i for i in range(self.n) if s[i] < 2]

    def next_state(self, s, a):
        out = list(s)
        out[a] += 1
        return tuple(out)

    def properties(self):
        return [
            Property.sometimes("w0 done", lambda m, s: s[0] == 2),
            Property.always("w0 bounded", lambda m, s: s[0] <= 2),
        ]


class ToggleTensor(TensorModel):
    def __init__(self, sys: "ToggleSys"):
        self.model = sys
        self.packer = BitPacker([("x", 1), ("y", 1)])
        self.width = 1
        self.max_actions = 2

    def init_rows(self):
        return np.zeros((1, 1), np.uint64)

    def encode_state(self, s):
        return self.packer.pack(x=s[0], y=s[1])

    def decode_state(self, row):
        f = self.packer.unpack(row)
        return (f["x"], f["y"])

    def step_rows(self, rows):
        import jax.numpy as jnp

        pk = self.packer
        x = pk.get(rows, "x")
        y = pk.get(rows, "y")
        s_a = pk.set(rows, "x", x ^ jnp.uint64(1))
        v_a = jnp.ones(rows.shape[:-1], bool)
        s_b = pk.set(rows, "y", jnp.uint64(1))
        v_b = y == jnp.uint64(0)
        return jnp.stack([s_a, s_b], -2), jnp.stack([v_a, v_b], -1)

    def property_masks(self, rows):
        import jax.numpy as jnp

        y = self.packer.get(rows, "y")
        # the always-property also reads ONLY y: the toggle action stays
        # invisible, and the never-discovered always keeps the crawl from
        # early-exiting once "y set" is found
        return jnp.stack(
            [y == jnp.uint64(1), y <= jnp.uint64(1)], -1
        )


@dataclass(frozen=True)
class ToggleSys(TensorBackedModel, Model):
    """A toggle cycle (invisible) racing a visible one-shot set."""

    def tensor_model(self):
        return ToggleTensor(self)

    def init_states(self):
        return [(0, 0)]

    def actions(self, s):
        return (["toggle"] + (["set"] if s[1] == 0 else []))

    def next_state(self, s, a):
        if a == "toggle":
            return (1 - s[0], s[1])
        return (s[0], 1)

    def properties(self):
        return [
            Property.sometimes("y set", lambda m, s: s[1] == 1),
            Property.always("y bounded", lambda m, s: s[1] <= 1),
        ]
