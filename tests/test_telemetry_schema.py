"""Golden schema for the flight-recorder JSONL export.

Downstream tooling (regress.py, the report renderer, the driver's
artifact parsers, external dashboards) reads these records by field name.
This test pins the export schema — field names AND types, per record
kind — so exporter drift breaks HERE instead of in a consumer three
rounds later.  The schema is versioned: the JSONL header carries
``v`` (:data:`stateright_tpu.telemetry.export.SCHEMA_V`); bump it (and
this golden) together when the shape legitimately changes.

The rule per kind: required fields must all be present with the pinned
types; any OTHER field must be in the kind's allowed-optional set —
an unknown field is drift, not decoration.  ``note`` records are the
explicit free-form escape hatch and are exempt.
"""

import json
import numbers

from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.telemetry.export import SCHEMA_V

# (required, optional) field -> type per record kind.  ``numbers.Real``
# covers int-or-float counters; bool is pinned apart from int where the
# distinction carries meaning (cache_hit, stalled).
_REAL = numbers.Real
SCHEMA = {
    "step": (
        {
            "engine": str, "dt": _REAL, "states": int, "unique": int,
            "d_states": int, "d_unique": int, "dedup": _REAL,
        },
        {
            # engine-specific annotations: wavefront/sharded add device
            # capacities + table load, mp adds round/frontier, pool adds
            # its work-queue length
            "depth": int, "status": _REAL, "queue": int, "cap": int,
            "cand": int, "load_factor": _REAL, "frontier": int,
            "round": int,
            # sharded: explicit liveness for the health model's stall
            # guard (no frontier count crosses to the host there)
            "busy": bool,
            # engine-run span binding (telemetry/spans.py): steps of a
            # traced run carry their engine_run span id
            "span": str,
        },
    ),
    "growth": (
        {"status": str},
        {"unique": int, "cap": int, "qcap": int, "cand": int,
         "from_init": bool},
    ),
    "occupancy": (
        {
            "at": str, "nbuckets": int, "slots_per_bucket": int,
            "occupied": int, "load_factor": _REAL, "mean_bucket": _REAL,
            "max_bucket": int, "full_buckets": int,
            "poisson_full_expect": _REAL, "histogram": list,
        },
        {},
    ),
    "compile": (
        {"rung": str, "source": str, "cache_hit": bool,
         "duration": _REAL},
        {"cap": int, "qcap": int, "batch": int, "cand": int, "fcap": int,
         "bucket_cap": int, "prewarm_ready": bool, "build_secs": _REAL,
         # memory ledger on: the executable's compile-time memory
         # analysis (temp/argument/output bytes), backfilled via amend()
         "memory": dict},
    ),
    "profile": (
        {"event": str},
        {"logdir": str, "steps": int, "error": str, "detail": str,
         "span": str},
    ),
    "span": (
        # span-structured tracing (telemetry/spans.py,
        # docs/observability.md): one record per closed span, written at
        # close time (``t - dur`` is the start).  The optional set is
        # the union of per-span attrs: engine/error (engine_run,
        # attempt), attempt ordinal, gen (autosave), pending
        # (spill_drain), cap/unique (resharding), key/slot (fleet job),
        # jobs/slots (fleet root)
        {"v": int, "name": str, "trace_id": str, "span_id": str,
         "dur": _REAL},
        {"parent_id": str, "engine": str, "error": str, "attempt": int,
         "gen": int, "pending": int, "cap": int, "unique": int,
         "key": str, "slot": int, "jobs": int, "slots": int},
    ),
    "health": (
        {"v": int, "event": str},
        {"phase": str, "reason": str},
    ),
    "cartography": (
        {
            "v": int, "at": str, "depth_hist": list, "action_hist": list,
            "props": list, "fresh_inserts": int, "duplicate_hits": int,
        },
        {"shard_load": list, "shard_imbalance": dict,
         "route_matrix": list, "routed_candidates": int},
    ),
    "spill": (
        # spill-tier events (stateright_tpu/spill/, docs/spill.md):
        # arm (run start), evict (hot table -> host tier), resolve
        # (pending vs the host index), queue_offload/queue_refill
        # (budget-blocked queue doubling), final
        {"v": int, "event": str},
        {"bloom_bits": int, "pend_cap": int, "budget_bytes": int,
         "evicted": int, "spilled_fps": int, "host_bytes": int,
         "disk_bytes": int, "bloom_est_false_pos": _REAL,
         "pending": int, "dups": int, "novel": int,
         "rows": int, "host_rows": int},
    ),
    "roofline": (
        # the roofline cost ledger's spawn-time record
        # (telemetry/roofline.py): per-stage analytic FLOPs/bytes +
        # totals + the XLA-reconciliation verdict.  Emitted once at
        # init — the static model cannot change mid-run.
        {
            "v": int, "at": str, "engine": str, "stages": dict,
            "totals": dict, "reconciled": bool,
        },
        {},
    ),
    "checkpoint": (
        # autosave generation writes (stateright_tpu/checkpoint.py,
        # docs/robustness.md): ok=False records a degraded (failed)
        # write — the run continues, the record discloses it
        {"v": int, "gen": int, "ok": bool},
        {"unique": int, "states": int, "secs": _REAL, "error": str},
    ),
    "fault": (
        # a FaultPlan delivery (testing/faults.py): site + action + the
        # occurrence ordinal it fired at — the chaos run's ring trail
        {"v": int, "site": str, "action": str, "at": int},
        {},
    ),
    "restart": (
        # a supervised resume (supervisor.py): attempt ordinal + the
        # failure class that caused it; parent_run_id links the lineage
        {"v": int, "attempt": int, "reason": str},
        {"parent_run_id": str, "degradation": str},
    ),
    "sweep": (
        # hyper-batched instance sweeps (stateright_tpu/sweep/,
        # docs/sweep.md): cohort_compile (one per compiled shape
        # cohort), instance_done (per-instance totals at extraction),
        # summary (instances/cohorts/compile amortization at run end)
        {"v": int, "event": str},
        {"cohort": int, "instances": int, "width": int, "arity": int,
         "unified": bool, "key": str, "unique": int, "states": int,
         "depth": int, "cohorts": int, "engine_compiles": int},
    ),
    "fleet": (
        # fleet-scheduler pool bookkeeping (stateright_tpu/fleet/,
        # docs/fleet.md): start (pool opens) and done (pool drained,
        # with the terminal tallies + compile accounting)
        {"v": int, "event": str, "slots": int, "jobs": int},
        {"completed": int, "failed": int, "refused": int,
         "preemptions": int, "engine_compiles": int, "packed": int},
    ),
    "job": (
        # per-tenant lifecycle (stateright_tpu/fleet/, docs/fleet.md):
        # submit -> place (admission decision) -> [pack] -> [preempt ->
        # resume]* -> done; gen is the autosave generation a preempted
        # job yields at / resumes from, run_id/parent_run_id the
        # registry lineage the exactly-once gate walks
        {"v": int, "event": str, "key": str},
        {"priority": int, "decision": str, "reason": str, "slot": int,
         "cohort": str, "jobs": int, "gen": int, "status": str,
         "unique": int, "states": int, "run_id": str,
         "parent_run_id": str},
    ),
    "memory": (
        # the HBM ledger's per-rung snapshot (telemetry/memory.py):
        # per-buffer analytic bytes + the growth-transient forecast;
        # live device stats / budget / exec analysis appear only where
        # the backend provides them
        {
            "v": int, "at": str, "engine": str, "capacity": int,
            "buffers": dict, "total_bytes": int, "next_rung": dict,
        },
        {"queue_capacity": int, "frontier_capacity": int, "devices": int,
         "per_device_bytes": int, "budget_bytes": int, "budget_src": str,
         "exec": dict, "device": dict},
    ),
}
_ENVELOPE = {"seq": int, "t": _REAL, "kind": str}


def _check_record(rec: dict) -> list:
    problems = []
    for k, t in _ENVELOPE.items():
        if not isinstance(rec.get(k), t):
            problems.append(f"envelope field {k} missing/mistyped: {rec}")
    kind = rec.get("kind")
    if kind == "note":
        return problems  # free-form by design
    if kind not in SCHEMA:
        return problems + [f"unknown record kind {kind!r}: {rec}"]
    required, optional = SCHEMA[kind]
    body = {k: v for k, v in rec.items() if k not in _ENVELOPE}
    for k, t in required.items():
        if k not in body:
            problems.append(f"{kind}: missing required field {k}")
        elif isinstance(body[k], bool) and t is not bool:
            problems.append(f"{kind}.{k}: bool where {t} pinned")
        elif not isinstance(body[k], t):
            problems.append(
                f"{kind}.{k}: {type(body[k]).__name__} != pinned "
                f"{getattr(t, '__name__', t)}"
            )
    for k, v in body.items():
        if k in required:
            continue
        if k not in optional:
            problems.append(
                f"{kind}: UNKNOWN field {k!r} (drift — add it to the "
                "golden schema deliberately, with its consumer)"
            )
        elif v is not None and not isinstance(v, optional[k]):
            problems.append(
                f"{kind}.{k}: {type(v).__name__} != pinned "
                f"{getattr(optional[k], '__name__', optional[k])}"
            )
    return problems


def _export_lines(tmp_path, builder, **spawn_kw):
    c = builder.spawn_tpu(sync=True, **spawn_kw)
    path = tmp_path / "export.jsonl"
    c.flight_recorder.to_jsonl(path)
    return [json.loads(ln) for ln in path.read_text().splitlines() if ln]


def test_jsonl_header_is_versioned(tmp_path):
    lines = _export_lines(
        tmp_path,
        TwoPhaseSys(3).checker().telemetry(),
        capacity=1 << 12, batch=64,
    )
    header = lines[0]
    assert header["kind"] == "header"
    assert header["v"] == SCHEMA_V == 1
    assert isinstance(header["meta"], dict)
    assert isinstance(header["capacity"], int)
    assert isinstance(header["summary"], dict)


def test_every_exported_record_matches_the_golden_schema(tmp_path):
    """One run exercising every record kind the wavefront engine can emit
    (steps, growth, occupancy, compile, health, cartography, memory),
    validated field-by-field against the pinned schema."""
    lines = _export_lines(
        tmp_path,
        TwoPhaseSys(5).checker().telemetry(
            occupancy_every=2, cartography=True, memory=True,
            roofline=True,
        ),
        capacity=1 << 10, batch=256,  # tiny: forces growth events
    )
    records = [ln for ln in lines if ln.get("kind") != "header"]
    kinds = {r["kind"] for r in records}
    for expect in ("step", "growth", "occupancy", "compile", "health",
                   "cartography", "memory", "roofline"):
        assert expect in kinds, f"run did not exercise {expect!r} records"
    problems = []
    for r in records:
        problems += _check_record(r)
    assert not problems, "\n".join(problems)


def test_spill_records_match_the_golden_schema(tmp_path, monkeypatch):
    """A run under a simulated budget that forces eviction emits the
    versioned ``spill`` record kind (arm/evict/resolve/final), every
    record validated field-by-field like the rest of the export."""
    from stateright_tpu.parallel.tensor_model import twin_or_none
    from stateright_tpu.telemetry.memory import (
        ENV_DEVICE_BYTES,
        total_bytes,
        wavefront_specs,
    )

    m = TwoPhaseSys(5)
    twin = twin_or_none(m)
    n_props = len(list(m.properties()))
    batch, bloom, qcap = 128, 1 << 14, 4096
    sp = (bloom, batch * twin.max_actions)

    def tot(cap):
        return total_bytes(
            wavefront_specs(twin, n_props, cap, qcap, batch, spill=sp)
        )

    monkeypatch.setenv(ENV_DEVICE_BYTES, str(tot(1 << 13) + tot(1 << 14) - 1))
    monkeypatch.setenv("STATERIGHT_TPU_CAPACITY_GUARD", "off")
    lines = _export_lines(
        tmp_path,
        TwoPhaseSys(5).checker().spill().telemetry(),
        capacity=1 << 12, batch=batch, queue_capacity=qcap,
        spill_bloom_bits=bloom, steps_per_call=8,
    )
    records = [ln for ln in lines if ln.get("kind") != "header"]
    spills = [r for r in records if r["kind"] == "spill"]
    events = {r["event"] for r in spills}
    for expect in ("arm", "evict", "resolve", "final"):
        assert expect in events, f"run did not emit a spill {expect!r} event"
    problems = []
    for r in records:
        problems += _check_record(r)
    assert not problems, "\n".join(problems)
    # the summary carries the live spill block alongside memory/cartography
    assert lines[0]["summary"]["spill"]["spilled_fps"] > 0


def test_checkpoint_fault_restart_records_match_the_golden_schema(tmp_path):
    """A supervised chaos run (kill injected mid-flight, autosave every
    sync) exercises the versioned ``checkpoint`` + ``restart`` record
    kinds; the killed attempt's recorder carries the ``fault`` record.
    Every record validates field-by-field like the rest of the export."""
    from stateright_tpu.supervisor import supervise
    from stateright_tpu.testing.faults import Fault, FaultPlan

    killed_recs = []

    def spawn(b, resume=None, **kw):
        c = b.spawn_tpu(resume=resume, **kw)
        killed_recs.append(c.flight_recorder)
        return c

    plan = FaultPlan([Fault(site="host_sync", action="kill", at=3)])
    with plan:
        res = supervise(
            TwoPhaseSys(3).checker().telemetry(),
            autosave_dir=str(tmp_path / "auto"), every_secs=0.0,
            max_restarts=2, sleep=lambda s: None, spawn=spawn,
            capacity=1 << 12, batch=64, steps_per_call=2,
        )
    assert res.restarts == 1
    path = tmp_path / "export.jsonl"
    res.checker.flight_recorder.to_jsonl(path)
    # the fault record landed in the KILLED attempt's ring
    killed_recs[0].to_jsonl(path, append=True)
    lines = [json.loads(ln) for ln in path.read_text().splitlines() if ln]
    records = [ln for ln in lines if ln.get("kind") != "header"]
    kinds = {r["kind"] for r in records}
    for expect in ("checkpoint", "restart", "fault"):
        assert expect in kinds, f"run did not exercise {expect!r} records"
    problems = []
    for r in records:
        problems += _check_record(r)
    assert not problems, "\n".join(problems)
    # the summary carries the durability block alongside the others
    assert lines[0]["summary"]["durability"]["restarts"] == 1


def test_sweep_records_match_the_golden_schema(tmp_path):
    """A two-instance sweep emits the versioned ``sweep`` record kind
    (cohort_compile / instance_done / summary), every record validated
    field-by-field, and the export round-trips through from_jsonl."""
    from stateright_tpu.models.two_phase_commit import sweep_family
    from stateright_tpu.telemetry import FlightRecorder

    spec = sweep_family(2)
    c = (
        spec.instances[0].model.checker().telemetry()
        .sweep(spec)
        .spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    )
    path = tmp_path / "export.jsonl"
    c.flight_recorder.to_jsonl(path)
    lines = [json.loads(ln) for ln in path.read_text().splitlines() if ln]
    records = [ln for ln in lines if ln.get("kind") != "header"]
    sweeps = [r for r in records if r["kind"] == "sweep"]
    events = [r["event"] for r in sweeps]
    assert events.count("cohort_compile") == 1
    assert events.count("instance_done") == 2
    assert events[-1] == "summary"
    problems = []
    for r in records:
        problems += _check_record(r)
    assert not problems, "\n".join(problems)
    # round-trip: the restored ring carries the same sweep records
    rec2 = FlightRecorder.from_jsonl(path)
    assert [
        (r["event"], r.get("key")) for r in rec2.records("sweep")
    ] == [(r["event"], r.get("key")) for r in sweeps]


def test_fleet_records_match_the_golden_schema(tmp_path):
    """A scheduled fleet emits the versioned ``fleet``/``job`` record
    kinds (submit/place/preempt/resume/done + start/done), every record
    validated field-by-field, and the export round-trips through
    from_jsonl — without spawning a single engine (fake builders: the
    schema is the scheduler's, not the engines')."""
    from stateright_tpu.fleet import FleetSpec, Job, run_fleet
    from stateright_tpu.telemetry import FlightRecorder
    from tests.fleet_fakes import FakeBuilder

    spec = FleetSpec(
        jobs=[
            Job(key="a", build=lambda: FakeBuilder(unique=7, states=9)),
            Job(key="b", build=lambda: FakeBuilder(unique=3, states=4),
                priority=1),
        ],
        slots=1,
    )
    res = run_fleet(spec, root=str(tmp_path / "fleet"))
    path = tmp_path / "export.jsonl"
    res.recorder.to_jsonl(path)
    lines = [json.loads(ln) for ln in path.read_text().splitlines() if ln]
    records = [ln for ln in lines if ln.get("kind") != "header"]
    fleet = [r for r in records if r["kind"] == "fleet"]
    jobs = [r for r in records if r["kind"] == "job"]
    assert [r["event"] for r in fleet] == ["start", "done"]
    events = [(r["event"], r["key"]) for r in jobs]
    for key in ("a", "b"):
        for ev in ("submit", "place", "done"):
            assert (ev, key) in events, f"missing {ev}/{key}"
    problems = []
    for r in records:
        problems += _check_record(r)
    assert not problems, "\n".join(problems)
    # the summary carries the final pool snapshot alongside the others
    assert lines[0]["summary"]["fleet"]["slots"] == 1
    # round-trip: the restored ring carries the same job records AND
    # the reconciled pool snapshot
    rec2 = FlightRecorder.from_jsonl(path)
    assert [
        (r["event"], r["key"]) for r in rec2.records("job")
    ] == events
    assert rec2.fleet() == lines[0]["summary"]["fleet"]


def test_summary_cartography_block_matches_snapshot_schema(tmp_path):
    """The summary's embedded cartography block is the same shape as the
    ring records minus the envelope/at: consumers share one parser."""
    lines = _export_lines(
        tmp_path,
        TwoPhaseSys(3).checker().telemetry(cartography=True),
        capacity=1 << 12, batch=64,
    )
    cart = lines[0]["summary"]["cartography"]
    required, optional = SCHEMA["cartography"]
    for k in required:
        if k == "at":
            continue  # summary holds the latest snapshot, not a series
        assert k in cart, f"summary cartography missing {k}"
    for k in cart:
        assert k in required or k in optional
    props = cart["props"]
    assert all(
        sorted(p) == ["condition_hits", "evaluated", "name"]
        for p in props
    )


def test_summary_roofline_block_matches_report_block_shape(tmp_path):
    """The summary's embedded roofline block is the live-snapshot shape
    (static block + reconciliation/verdicts): the per-stage map and the
    totals parse with the same reader as the run report's block."""
    lines = _export_lines(
        tmp_path,
        TwoPhaseSys(3).checker().telemetry(roofline=True),
        capacity=1 << 12, batch=64,
    )
    roof = lines[0]["summary"]["roofline"]
    assert isinstance(roof["v"], int)
    assert isinstance(roof["stages"], dict) and roof["stages"]
    for s in roof["stages"].values():
        for k in ("flops", "bytes_read", "bytes_written"):
            assert isinstance(s[k], int) and s[k] >= 0
    assert roof["totals"]["flops"] == sum(
        s["flops"] for s in roof["stages"].values()
    )
    assert roof["reconciliation"]["ok"] is True


def test_costmodel_verb_out_round_trips(tmp_path):
    """The ``costmodel`` verb's ``--out=`` fixture: the written JSON
    parses back into versioned per-config blocks whose stage maps and
    totals satisfy the regress gate's well-formedness rules."""
    from stateright_tpu.models import two_phase_commit

    out = tmp_path / "costmodel.json"
    two_phase_commit.main(["costmodel", f"--out={out}"])
    doc = json.loads(out.read_text())
    assert isinstance(doc["v"], int)
    assert doc["configs"], "no config blocks written"
    for blk in doc["configs"]:
        assert isinstance(blk["label"], str)
        assert isinstance(blk["stages"], dict) and blk["stages"]
        assert blk["totals"]["flops"] == sum(
            s["flops"] for s in blk["stages"].values()
        )
        assert blk["totals"]["bytes"] == sum(
            s["bytes_read"] + s["bytes_written"]
            for s in blk["stages"].values()
        )
        assert blk["reconciliation"]["ok"] is True
        assert isinstance(blk["mxu_candidates"], list)


def test_summary_memory_block_matches_snapshot_schema(tmp_path):
    """The summary's embedded memory block is the ring records' shape
    minus the envelope/at (the ``v`` field rides inside the snapshot):
    consumers share one parser."""
    lines = _export_lines(
        tmp_path,
        TwoPhaseSys(3).checker().telemetry(memory=True),
        capacity=1 << 12, batch=64,
    )
    mem = lines[0]["summary"]["memory"]
    required, optional = SCHEMA["memory"]
    for k in required:
        if k == "at":
            continue  # summary holds the latest snapshot, not a series
        assert k in mem, f"summary memory missing {k}"
    for k in mem:
        assert k in required or k in optional
    assert mem["total_bytes"] == sum(mem["buffers"].values())
