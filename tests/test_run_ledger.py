"""Run ledger & differential observability (telemetry/registry.py +
telemetry/diff.py; docs/telemetry.md "Comparing runs").

Pins the round's contracts:

 - IDENTITY: the run report carries a deterministic ``config`` block with
   a canonical ``config_key``, and a volatile ``run_id`` header — with
   :data:`report.VOLATILE_KEYS` as the SCHEMA the diff engine scrubs by
   (never hand-listed downstream);
 - REGISTRY: ``CheckerBuilder.runs(DIR)`` / ``STATERIGHT_TPU_RUN_DIR``
   archive each completed run (report document + versioned index
   record, golden-schema-pinned + round-trip);
 - ZERO JAXPR IMPACT (the family's strongest contract): registry on or
   off leaves the step jaxpr bit-identical and the engine cache unkeyed,
   both engines (sharded leg behind ``requires_sharded_collectives``);
 - the CONTRACT MATRIX: observability flag deltas classify IDENTICAL,
   ``--por`` ISOMORPHIC (with the explored-count delta reported and
   reduction-direction enforced), pure perf knobs PERF-ONLY, corrupted
   counts DIVERGENT with named violations, different instances
   incomparable;
 - LINEAGE: snapshot manifests carry ``run_id``, resumed runs record
   ``parent_run_id``, the registry links kill+resume chains, and the
   resumed-vs-full compare is the PR-8/PR-10 exact-totals pin as one
   command;
 - the ``compare``/``runs`` CLI verbs (per-example + fleet) and the
   Explorer's ``/.runs`` endpoints with the UNIFIED stable error shape
   (``{"error", "hint"}`` — exactly the ``/.metrics`` telemetry-off
   body's shape).
"""

import copy
import json
import numbers
import urllib.error
import urllib.request

import pytest

import jax

from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.telemetry.diff import (
    DIFF_V,
    DIVERGENT,
    IDENTICAL,
    ISOMORPHIC,
    PERF_ONLY,
    diff_reports,
    render_diff,
)
from stateright_tpu.telemetry.registry import (
    ENV_RUN_DIR,
    REGISTRY_V,
    RunRegistry,
)
from stateright_tpu.telemetry.report import VOLATILE_KEYS, config_key
from tests.helpers import requires_sharded_collectives

TPC3_UNIQUE, TPC3_STATES = 288, 1146


def _spawn(runs_dir=None, telemetry=True, **kw):
    b = TwoPhaseSys(3).checker()
    if runs_dir is not None:
        b = b.runs(str(runs_dir))
    if telemetry:
        b = b.telemetry(cartography=True, memory=True)
    kw.setdefault("capacity", 1 << 12)
    kw.setdefault("batch", 64)
    return b.spawn_tpu(sync=True, **kw).join()


@pytest.fixture(scope="module")
def ledger(tmp_path_factory):
    """One populated registry shared by the read-side tests: two
    archived same-config runs + their index records."""
    root = tmp_path_factory.mktemp("ledger")
    c1 = _spawn(runs_dir=root)
    c2 = _spawn(runs_dir=root)
    reg = RunRegistry(str(root))
    return reg, c1, c2


# -- identity: config block + run_id header ----------------------------------


def test_report_carries_config_and_run_identity(tmp_path):
    path = tmp_path / "r.json"
    c = TwoPhaseSys(3).checker().report(str(path)).spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    doc = json.loads(path.read_text())
    # volatile header: generated_at + run_id, leading the document, all
    # named by the VOLATILE_KEYS schema
    assert doc["run_id"] == c.run_id and len(c.run_id) == 16
    head = [k for k in doc if k in VOLATILE_KEYS]
    assert list(doc)[: len(head)] == head and "run_id" in head
    cfg = doc["config"]
    assert cfg["model"] == "TwoPhaseSys" and cfg["engine"] == "wavefront"
    assert isinstance(cfg["instance"]["sig"], str)
    assert cfg["key"] == config_key(cfg)
    for flag in ("telemetry", "cartography", "memory", "checked",
                 "prededup", "spill", "por", "symmetry", "prewarm",
                 "pallas", "compile_cache", "roofline", "sweep"):
        assert flag in cfg["flags"], flag
    # different instance arguments -> different config_key
    from stateright_tpu.telemetry.report import build_config

    other = build_config(
        TwoPhaseSys(4).checker().spawn_tpu(
            sync=True, capacity=1 << 13, batch=64
        )
    )
    assert other["key"] != cfg["key"]
    assert other["instance"]["sig"] != cfg["instance"]["sig"]


# -- registry: archive + golden index schema + round-trip --------------------

_REAL = numbers.Real
_INDEX_REQUIRED = {
    "v": int, "run_id": str, "config_key": str, "model": str,
    "engine": str, "generated_at": str, "path": str, "headline": dict,
}
_INDEX_OPTIONAL = {"parent_run_id": str, "leg": str}
_HEADLINE_REQUIRED = {
    "states": int, "unique": int, "max_depth": int, "done": bool,
    "discoveries": list,
}
_HEADLINE_OPTIONAL = {"states_per_sec": _REAL, "wall_secs": _REAL,
                      "stages": dict}


def _check_index_record(rec: dict) -> list:
    problems = []
    for k, t in _INDEX_REQUIRED.items():
        if not isinstance(rec.get(k), t):
            problems.append(f"index.{k} missing/mistyped: {rec.get(k)!r}")
    for k, v in rec.items():
        if k in _INDEX_REQUIRED:
            continue
        if k not in _INDEX_OPTIONAL:
            problems.append(f"index: UNKNOWN field {k!r} (drift — extend "
                            "the golden deliberately, with its consumer)")
        elif not isinstance(v, _INDEX_OPTIONAL[k]):
            problems.append(f"index.{k} mistyped: {v!r}")
    h = rec.get("headline") or {}
    for k, t in _HEADLINE_REQUIRED.items():
        if not isinstance(h.get(k), t):
            problems.append(f"headline.{k} missing/mistyped: {h.get(k)!r}")
    for k, v in h.items():
        if k in _HEADLINE_REQUIRED:
            continue
        if k not in _HEADLINE_OPTIONAL:
            problems.append(f"headline: UNKNOWN field {k!r}")
        elif not isinstance(v, _HEADLINE_OPTIONAL[k]):
            problems.append(f"headline.{k} mistyped: {v!r}")
    return problems


def test_registry_index_record_matches_golden_schema(ledger):
    reg, c1, c2 = ledger
    recs = reg.index()
    assert len(recs) == 2
    problems = []
    for rec in recs:
        assert rec["v"] == REGISTRY_V == 1
        problems += _check_index_record(rec)
    assert not problems, "\n".join(problems)
    # same configuration -> same config_key; append order preserved
    assert recs[0]["config_key"] == recs[1]["config_key"]
    assert [r["run_id"] for r in recs] == [c1.run_id, c2.run_id]
    h = recs[0]["headline"]
    assert h["unique"] == TPC3_UNIQUE and h["states"] == TPC3_STATES
    assert h["done"] is True


def test_registry_archive_round_trips(ledger):
    reg, c1, _ = ledger
    doc = reg.load(c1.run_id)
    assert doc["run_id"] == c1.run_id
    assert doc["totals"]["unique"] == TPC3_UNIQUE
    assert doc["config"]["key"] == reg.index()[0]["config_key"]
    # the headline accessor reads the index, not the archive
    assert reg.headline(c1.run_id)["unique"] == TPC3_UNIQUE
    # trends group by config_key
    trends = reg.trends()
    (series,) = trends.values()
    assert [s["unique"] for s in series] == [TPC3_UNIQUE, TPC3_UNIQUE]


def test_registry_env_knob_archives_plain_runs(tmp_path, monkeypatch):
    monkeypatch.setenv(ENV_RUN_DIR, str(tmp_path))
    _spawn(telemetry=False)
    recs = RunRegistry(str(tmp_path)).index()
    assert len(recs) == 1 and recs[0]["headline"]["unique"] == TPC3_UNIQUE


def test_registry_skips_malformed_index_lines(ledger, tmp_path):
    reg, *_ = ledger
    tainted = tmp_path / "index.jsonl"
    tainted.write_text(
        open(reg.index_path).read() + "{torn line\n"
    )
    reg2 = RunRegistry(str(tmp_path))
    reg2.index_path = str(tainted)
    assert len(reg2.index()) == 2  # the torn tail hides nothing


# -- zero jaxpr impact + engine cache unkeyed (both engines) -----------------


def _wavefront_build_jaxpr(runs_dir) -> str:
    m = TwoPhaseSys(3)
    b = m.checker()
    if runs_dir:
        b = b.runs(str(runs_dir))
    c = b.spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    init_fn, run_fn = c._build(c._cap, c._qcap, c._batch, c._cand)
    carry, _ = init_fn()
    # fresh lambda per call: make_jaxpr memoizes on fn identity
    return str(jax.make_jaxpr(lambda cr: run_fn(cr))(tuple(carry)))


def test_registry_leaves_run_jaxpr_bit_identical(tmp_path):
    """Strongest form of the contract: the registry is post-run host
    I/O — the device program is bit-identical with it on or off."""
    assert _wavefront_build_jaxpr(None) == _wavefront_build_jaxpr(tmp_path)


def test_registry_does_not_key_the_engine_cache(tmp_path):
    """Registry on/off must share one compiled engine: a plain spawn
    after a registry-armed spawn on the same model is a cache HIT."""
    m = TwoPhaseSys(3)
    kw = dict(sync=True, capacity=1 << 12, batch=64)
    c1 = m.checker().runs(str(tmp_path)).spawn_tpu(**kw)
    n_keys = len(c1.tensor._run_cache)
    c2 = m.checker().spawn_tpu(**kw)
    assert len(c2.tensor._run_cache) == n_keys
    assert c2.unique_state_count() == c1.unique_state_count()
    assert RunRegistry(str(tmp_path)).index(), "armed spawn must archive"


@requires_sharded_collectives
def test_registry_sharded_archives_and_cache_unkeyed(tmp_path):
    m = TwoPhaseSys(3)
    kw = dict(sync=True, n_devices=2, capacity=1 << 12, batch=64)
    c1 = m.checker().runs(str(tmp_path)).spawn_tpu(**kw)
    n_keys = len(c1.tensor._sharded_run_cache)
    c2 = m.checker().spawn_tpu(**kw)
    assert len(c2.tensor._sharded_run_cache) == n_keys
    recs = RunRegistry(str(tmp_path)).index()
    assert recs and recs[0]["engine"] == "sharded"
    assert recs[0]["headline"]["unique"] == TPC3_UNIQUE


# -- the diff engine: contract matrix ----------------------------------------


def test_diff_same_config_pair_is_identical(ledger):
    reg, c1, c2 = ledger
    d = diff_reports(
        reg.load(c1.run_id), reg.load(c2.run_id),
        a_headline=reg.headline(c1.run_id),
        b_headline=reg.headline(c2.run_id),
    )
    assert d["v"] == DIFF_V == 1
    assert d["verdict"] == IDENTICAL and d["contract"] == "same"
    assert d["violations"] == [] and d["config_delta"] == {}
    assert d["blocks"]["totals"]["unique"]["match"] is True
    assert d["blocks"]["cartography"]["match"] is True
    # the wall-clock headline rides as a non-gating perf block
    assert "states_per_sec" in d["blocks"]["perf"]
    assert "IDENTICAL" in render_diff(d)
    # the diff document is JSON-safe and round-trips
    assert json.loads(json.dumps(d)) == d


def test_diff_volatile_fields_ignored_by_schema(ledger, monkeypatch):
    """The scrub consults report.VOLATILE_KEYS at diff time: a NEW
    volatile field registered there is ignored with no diff change."""
    from stateright_tpu.telemetry import report as report_mod

    reg, c1, _ = ledger
    a = reg.load(c1.run_id)
    b = copy.deepcopy(a)
    b["generated_at"] = "2099-01-01T00:00:00+00:00"
    b["run_id"] = "ffffffffffffffff"
    assert diff_reports(a, b)["verdict"] == IDENTICAL
    b["freshly_volatile"] = "zzz"
    monkeypatch.setattr(
        report_mod, "VOLATILE_KEYS",
        report_mod.VOLATILE_KEYS + ("freshly_volatile",),
    )
    d = diff_reports(a, b)
    assert d["verdict"] == IDENTICAL and d["violations"] == []


def test_diff_contract_matrix(ledger):
    reg, c1, _ = ledger
    a = reg.load(c1.run_id)

    # observability delta -> IDENTICAL (blocks may appear/disappear)
    b = copy.deepcopy(a)
    for f in ("telemetry", "cartography", "memory"):
        b["config"]["flags"][f] = False
    b.pop("cartography")
    b.pop("memory")
    d = diff_reports(a, b)
    assert (d["verdict"], d["contract"]) == (IDENTICAL, "observability")

    # pure perf knob -> PERF-ONLY (counts still gated)
    b = copy.deepcopy(a)
    b["config"]["flags"]["prewarm"] = True
    d = diff_reports(a, b)
    assert (d["verdict"], d["contract"]) == (PERF_ONLY, "perf")

    # --por with shrunken counts -> ISOMORPHIC, delta reported
    b = copy.deepcopy(a)
    b["config"]["flags"]["por"] = True
    b["totals"]["states"] -= 45
    b["totals"]["unique"] -= 15
    d = diff_reports(a, b)
    assert (d["verdict"], d["contract"]) == (ISOMORPHIC, "isomorphic")
    assert d["blocks"]["totals"]["unique"]["delta"] == -15
    assert all(p["match"] for p in d["blocks"]["properties"])

    # --por that GREW the space -> DIVERGENT reduction_grew
    b = copy.deepcopy(a)
    b["config"]["flags"]["por"] = True
    b["totals"]["unique"] += 10
    b["totals"]["states"] += 10
    d = diff_reports(a, b)
    assert d["verdict"] == DIVERGENT
    assert any(v["rule"] == "reduction_grew" for v in d["violations"])

    # corrupted counts under a count-identical contract -> DIVERGENT
    # with the violation naming the field
    b = copy.deepcopy(a)
    b["totals"]["unique"] += 1
    d = diff_reports(a, b)
    assert d["verdict"] == DIVERGENT
    (v,) = [x for x in d["violations"] if x["field"] == "totals.unique"]
    assert v["rule"] == "counts_must_match"
    assert (v["a"], v["b"]) == (TPC3_UNIQUE, TPC3_UNIQUE + 1)

    # flipped property verdict -> DIVERGENT verdict_parity (every
    # comparable contract gates on it)
    b = copy.deepcopy(a)
    b["config"]["flags"]["por"] = True
    for p in b["properties"]:
        if p["name"] == "commit agreement":
            p["discovery"] = False
    d = diff_reports(a, b)
    assert d["verdict"] == DIVERGENT
    assert any(v["rule"] == "verdict_parity" for v in d["violations"])

    # different model -> incomparable, DIVERGENT with ONE named violation
    b = copy.deepcopy(a)
    b["model"] = "Other"
    b["config"]["model"] = "Other"
    d = diff_reports(a, b)
    assert (d["verdict"], d["contract"]) == (DIVERGENT, "incomparable")
    assert [v["rule"] for v in d["violations"]] == ["incomparable"]

    # pre-registry pair (no config blocks): unknown contract — equal
    # counts classify IDENTICAL, differing counts ISOMORPHIC (nothing
    # stronger can be promised), verdict parity still gates
    a0, b0 = copy.deepcopy(a), copy.deepcopy(a)
    a0.pop("config")
    b0.pop("config")
    assert diff_reports(a0, b0)["verdict"] == IDENTICAL
    b0["totals"]["unique"] -= 1
    d = diff_reports(a0, b0)
    assert (d["verdict"], d["contract"]) == (ISOMORPHIC, "unknown")


def test_diff_cartography_gates_count_contracts(ledger):
    """A tampered depth histogram with untouched totals still diverges
    under a count-identical contract — the search shape is count-derived
    too."""
    reg, c1, _ = ledger
    a = reg.load(c1.run_id)
    b = copy.deepcopy(a)
    h = list(b["cartography"]["depth_hist"])
    h[0] += 1
    h[1] -= 1
    b["cartography"]["depth_hist"] = h
    d = diff_reports(a, b)
    assert d["verdict"] == DIVERGENT
    assert any(v["field"] == "cartography" for v in d["violations"])


def test_host_prefix_target_enters_the_instance_identity(tmp_path):
    """A host run's target_states is instance identity too (device
    engines store it as _target; the thread-pool checkers only keep the
    builder options): a prefix host run vs a full host run must be
    INCOMPARABLE, not falsely same-config DIVERGENT."""
    from stateright_tpu.telemetry.report import build_config

    full = TwoPhaseSys(3).checker().spawn_bfs().join()
    prefix = TwoPhaseSys(3).checker().target_states(64).spawn_bfs().join()
    cfg_full, cfg_prefix = build_config(full), build_config(prefix)
    assert cfg_full["instance"]["target"] is None
    assert cfg_prefix["instance"]["target"] == 64
    a = {"v": 1, "model": "TwoPhaseSys", "engine": "BfsChecker",
         "config": cfg_full,
         "totals": {"states": 1146, "unique": 288, "max_depth": 0,
                    "done": True},
         "properties": []}
    b = copy.deepcopy(a)
    b["config"] = cfg_prefix
    b["totals"].update(states=158, unique=67)
    d = diff_reports(a, b)
    assert (d["verdict"], d["contract"]) == (DIVERGENT, "incomparable")
    assert [v["rule"] for v in d["violations"]] == ["incomparable"]


def test_diff_cross_engine_pair_gates_unique_only(ledger, tmp_path):
    """Host BFS vs device wavefront on the same instance: the engine
    delta is identical-class, gated on unique counts + verdicts — the
    host engine's different generated-states accounting and missing
    max_depth must not false-positive, while the instance signature
    (twin-resolved on both sides) keeps the pair comparable."""
    reg, c1, _ = ledger
    host = TwoPhaseSys(3).checker().runs(str(tmp_path)).spawn_bfs()
    host.join()
    hreg = RunRegistry(str(tmp_path))
    a, b = reg.load(c1.run_id), hreg.load(host.run_id)
    assert (
        a["config"]["instance"]["sig"] == b["config"]["instance"]["sig"]
    )
    d = diff_reports(a, b)
    assert d["contract"] == "identical"
    assert d["verdict"] == IDENTICAL, d["violations"]
    # ...but a cross-engine UNIQUE drift still diverges
    b2 = copy.deepcopy(b)
    b2["totals"]["unique"] += 1
    d2 = diff_reports(a, b2)
    assert d2["verdict"] == DIVERGENT
    assert any(v["field"] == "totals.unique" for v in d2["violations"])


# -- lineage: snapshot run_id -> parent_run_id -> registry chain -------------


def test_kill_resume_lineage_links_and_compares(tmp_path):
    root = tmp_path / "reg"
    parent = (
        TwoPhaseSys(3).checker().runs(str(root)).target_states(64)
        .spawn_tpu(sync=True, capacity=1 << 12, batch=32)
    )
    parent.join()
    snap = parent.checkpoint()
    assert str(snap["run_id"]) == parent.run_id  # manifest carries it
    resumed = TwoPhaseSys(3).checker().runs(str(root)).spawn_tpu(
        sync=True, resume=snap, capacity=1 << 12, batch=32
    )
    resumed.join()
    assert resumed.parent_run_id == parent.run_id
    reg = RunRegistry(str(root))
    chain = reg.chain(resumed.run_id)
    assert [r["run_id"] for r in chain] == [parent.run_id, resumed.run_id]
    # the resumed run completed the space exactly (PR-8/PR-10 pin)
    assert resumed.unique_state_count() == TPC3_UNIQUE
    assert resumed.state_count() == TPC3_STATES
    # parent -> resumed: lineage contract, monotone, IDENTICAL
    d = diff_reports(reg.load(parent.run_id), reg.load(resumed.run_id))
    assert d["verdict"] == IDENTICAL and d["contract"] == "lineage"
    assert d["lineage"]["parent"] == parent.run_id
    # resumed vs a fresh FULL run: the exact-totals one-command check
    full = _spawn(runs_dir=root, telemetry=False)
    d2 = diff_reports(reg.load(full.run_id), reg.load(resumed.run_id))
    assert d2["verdict"] == IDENTICAL and d2["violations"] == []
    # a resumed run that LOST work diverges loudly
    tampered = copy.deepcopy(reg.load(resumed.run_id))
    tampered["totals"]["unique"] = 10
    d3 = diff_reports(reg.load(parent.run_id), tampered)
    assert d3["verdict"] == DIVERGENT
    assert any(v["rule"] == "resume_lost_work" for v in d3["violations"])


def test_npz_round_tripped_snapshot_keeps_lineage(tmp_path):
    """run_id survives np.savez/np.load like the rest of the manifest
    (kill+resume across processes is the point of the chain)."""
    import numpy as np

    parent = (
        TwoPhaseSys(3).checker().target_states(64)
        .spawn_tpu(sync=True, capacity=1 << 12, batch=32)
    )
    parent.join()
    snap = parent.checkpoint()
    path = tmp_path / "snap.npz"
    np.savez(path, **snap)
    loaded = dict(np.load(path, allow_pickle=False))
    resumed = TwoPhaseSys(3).checker().spawn_tpu(
        sync=True, resume=loaded, capacity=1 << 12, batch=32
    )
    resumed.join()
    assert resumed.parent_run_id == parent.run_id
    assert resumed.unique_state_count() == TPC3_UNIQUE


# -- CLI verbs: compare (per-example + fleet) and runs -----------------------


def test_compare_cli_verb_identical_and_tampered(ledger, tmp_path, capsys):
    from stateright_tpu.models.two_phase_commit import main

    reg, c1, c2 = ledger
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(reg.load(c1.run_id)))
    b.write_text(json.dumps(reg.load(c2.run_id)))
    main(["compare", str(a), str(b), "--expect=IDENTICAL"])
    out = capsys.readouterr().out
    assert "verdict: IDENTICAL" in out
    # machine-readable JSON line rides along
    last = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
    assert json.loads(last)["verdict"] == IDENTICAL
    # tampered report -> DIVERGENT, non-empty violations, nonzero exit
    doc = json.loads(b.read_text())
    doc["totals"]["unique"] += 3
    b.write_text(json.dumps(doc))
    with pytest.raises(SystemExit) as e:
        main(["compare", str(a), str(b)])
    assert e.value.code == 1
    out = capsys.readouterr().out
    assert "DIVERGENT" in out and "counts_must_match" in out


def test_compare_cli_resolves_registry_run_ids(ledger, capsys):
    from stateright_tpu.models._cli import compare_reports_cmd

    reg, c1, c2 = ledger
    rc = compare_reports_cmd([
        c1.run_id, c2.run_id, f"--registry={reg.root}",
        "--expect=IDENTICAL",
    ])
    assert rc == 0
    assert "throughput" in capsys.readouterr().out  # headline attached


def test_compare_cli_expect_mismatch_fails(ledger, capsys, tmp_path):
    from stateright_tpu.models._cli import compare_reports_cmd

    reg, c1, c2 = ledger
    rc = compare_reports_cmd([
        c1.run_id, c2.run_id, f"--registry={reg.root}",
        "--expect=ISOMORPHIC",
    ])
    assert rc == 1
    assert "!= expected" in capsys.readouterr().out
    # an explicit --expect=DIVERGENT asserting a known-bad pair exits 0
    # (the expectation is the whole judgement)
    bad = copy.deepcopy(reg.load(c2.run_id))
    bad["totals"]["unique"] += 1
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(bad))
    rc = compare_reports_cmd([
        c1.run_id, str(p), f"--registry={reg.root}",
        "--expect=DIVERGENT",
    ])
    assert rc == 0


def test_runs_fleet_verb_lists_registry(ledger, capsys):
    from stateright_tpu.models._cli import fleet_runs

    reg, c1, c2 = ledger
    assert fleet_runs([reg.root]) == 0
    out = capsys.readouterr().out
    assert c1.run_id in out and c2.run_id in out
    assert "2 archived over 1 config(s)" in out
    assert "trend" in out
    # no registry anywhere -> loud rc 2, not a crash
    assert fleet_runs([]) == 2


# -- Explorer: /.runs endpoints + unified error bodies -----------------------


def _get(addr, path):
    try:
        with urllib.request.urlopen(f"http://{addr}{path}") as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(scope="module")
def runs_server(ledger):
    from stateright_tpu.explorer import serve

    reg, *_ = ledger
    server = serve(
        TwoPhaseSys(3).checker(), "localhost:0", block=False,
        runs_dir=reg.root,
    )
    server.checker.join()
    yield server, reg
    server.shutdown()


def test_explorer_runs_index_and_archive(runs_server):
    server, reg = runs_server
    code, view = _get(server.addr, "/.runs")
    assert code == 200 and view["v"] == REGISTRY_V
    assert len(view["runs"]) == len(reg.index())
    assert view["trends"]
    rid = view["runs"][0]["run_id"]
    code, doc = _get(server.addr, f"/.runs/{rid}")
    assert code == 200 and doc["run_id"] == rid
    assert doc["totals"]["unique"] == TPC3_UNIQUE


def test_explorer_runs_diff_endpoint(runs_server):
    server, reg = runs_server
    ids = [r["run_id"] for r in reg.index()]
    code, d = _get(server.addr, f"/.runs/diff/{ids[0]}/{ids[1]}")
    assert code == 200 and d["verdict"] == IDENTICAL
    assert "perf" in d["blocks"]  # index headlines attached


def test_explorer_error_bodies_are_unified(runs_server):
    """Satellite contract: every /.runs error body has EXACTLY the
    /.metrics telemetry-off shape — {"error": token, "hint": prose} —
    no ad-hoc strings."""
    server, _ = runs_server
    code, body = _get(server.addr, "/.runs/nope")
    assert code == 404 and set(body) == {"error", "hint"}
    assert body["error"] == "unknown_run"
    code, body = _get(server.addr, "/.runs/diff/onlyone")
    assert code == 404 and set(body) == {"error", "hint"}
    assert body["error"] == "bad_diff_request"
    code, body = _get(server.addr, "/.metrics")
    assert code == 404 and set(body) == {"error", "hint"}
    assert body["error"] == "telemetry_disabled"


def test_explorer_without_registry_answers_registry_disabled():
    from stateright_tpu.explorer import serve

    server = serve(
        TwoPhaseSys(3).checker(), "localhost:0", block=False
    )
    try:
        server.checker.join()
        code, body = _get(server.addr, "/.runs")
        assert code == 404 and set(body) == {"error", "hint"}
        assert body["error"] == "registry_disabled"
    finally:
        server.shutdown()
