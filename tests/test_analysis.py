"""The preflight static auditor (``stateright_tpu/analysis/``): every rule
class firing on a deliberately broken model, clean (or exactly-pinned)
reports for the shipped fleet, the ``spawn_tpu`` preflight abort +
``skip_audit()`` escape hatch, the ``audit`` CLI verbs, and the
bucket-occupancy counters in the audit/status report."""

from __future__ import annotations

import http.client
import json
import random  # noqa: F401 - referenced by a linted handler below
import time

import numpy as np
import pytest

import jax.numpy as jnp

from stateright_tpu import Model, Property
from stateright_tpu.analysis import (
    AuditError,
    AuditReport,
    Severity,
    audit_model,
)
from stateright_tpu.actor import Actor, ActorModel, Id, Network, Out
from stateright_tpu.actor.device_props import forall_actors
from stateright_tpu.core import Expectation
from stateright_tpu.parallel.tensor_model import (
    TensorBackedModel,
    TensorModel,
)

# ---------------------------------------------------------------------------
# synthetic twins: one per jaxpr rule class
# ---------------------------------------------------------------------------


class _TwinBase(TensorModel):
    """Minimal conformant twin: 2-state chain 0 -> 1."""

    width = 1
    max_actions = 1

    def __init__(self, model):
        self.model = model

    def init_rows(self):
        return np.zeros((1, 1), np.uint64)

    def encode_state(self, s):
        return (int(s),)

    def decode_state(self, row):
        return int(row[0])

    def step_rows(self, rows):
        succ = (rows + jnp.uint64(1))[:, None, :]
        valid = (rows[..., 0] < jnp.uint64(1))[:, None]
        return succ, valid

    def property_masks(self, rows):
        return jnp.ones((rows.shape[0], 1), bool)


class _HostModel(TensorBackedModel, Model):
    twin_cls = _TwinBase

    def tensor_model(self):
        return self.twin_cls(self)

    def init_states(self):
        return [0]

    def actions(self, s):
        return [0] if s < 1 else []

    def next_state(self, s, a):
        return s + 1

    def properties(self):
        return [Property.always("ok", lambda m, s: True)]


def _host_model(twin_cls):
    class M(_HostModel):
        pass

    M.__name__ = M.__qualname__ = f"Host_{twin_cls.__name__}"
    M.twin_cls = twin_cls
    return M()


def test_clean_twin_audits_clean():
    report = audit_model(_host_model(_TwinBase), deep=True)
    assert report.ok and not report.warnings
    # the perf preflight always reports
    assert "JX106" in report.rule_ids()
    assert report.metrics["step_rows"]["eqns"] > 0


def test_impure_kernel_retrace_literal():
    """Satellite: a deliberately impure step_rows (closure over a mutated
    host list) must be caught by the double-trace diff (JX104)."""

    class ImpureTwin(_TwinBase):
        def __init__(self, model):
            super().__init__(model)
            self.trace_log = []  # mutated host list the kernel closes over

        def step_rows(self, rows):
            self.trace_log.append(len(self.trace_log))
            k = jnp.uint64(len(self.trace_log))  # differs per trace
            succ = (rows + k)[:, None, :]
            valid = (rows[..., 0] < jnp.uint64(1))[:, None]
            return succ, valid

    report = audit_model(_host_model(ImpureTwin))
    assert any(
        f.rule_id == "JX104" and f.severity == Severity.ERROR
        for f in report.findings
    ), report.format()


def test_impure_kernel_retrace_consts():
    """Same rule, other branch: identical jaxpr structure but a mutated
    closed-over array (constants differ between traces)."""

    class ConstMutTwin(_TwinBase):
        def __init__(self, model):
            super().__init__(model)
            self.offsets = np.zeros(4, np.uint64)

        def step_rows(self, rows):
            self.offsets = self.offsets + np.uint64(1)  # drifts per trace
            k = jnp.asarray(self.offsets)[0]
            succ = (rows + k)[:, None, :]
            valid = (rows[..., 0] < jnp.uint64(1))[:, None]
            return succ, valid

    report = audit_model(_host_model(ConstMutTwin))
    assert any(f.rule_id == "JX104" for f in report.findings), report.format()


def test_dtype_escape_float():
    class FloatTwin(_TwinBase):
        def step_rows(self, rows):
            f = rows.astype(jnp.float32) + 1.0  # u64 -> f32 round trip
            succ = f.astype(jnp.uint64)[:, None, :]
            valid = (rows[..., 0] < jnp.uint64(1))[:, None]
            return succ, valid

    report = audit_model(_host_model(FloatTwin))
    assert report.ok  # warning, not error: values < 2^53 survive
    assert any(
        f.rule_id == "JX102" and f.severity == Severity.WARNING
        for f in report.findings
    ), report.format()


def test_dtype_contract_violation():
    class I32Twin(_TwinBase):
        def step_rows(self, rows):
            succ = (rows + jnp.uint64(1)).astype(jnp.int32)[:, None, :]
            valid = (rows[..., 0] < jnp.uint64(1))[:, None]
            return succ, valid  # int32 successors: fingerprint corruption

    report = audit_model(_host_model(I32Twin))
    assert any(
        f.rule_id == "JX103" and f.severity == Severity.ERROR
        for f in report.findings
    ), report.format()


def test_shape_contract_violation():
    class WrongArityTwin(_TwinBase):
        max_actions = 2  # declares 2, produces 1

        def step_rows(self, rows):
            succ = (rows + jnp.uint64(1))[:, None, :]
            valid = (rows[..., 0] < jnp.uint64(1))[:, None]
            return succ, valid

    report = audit_model(_host_model(WrongArityTwin))
    assert any(f.rule_id == "JX103" for f in report.findings), report.format()


def test_dtype_escape_integer_narrowing():
    """The other fingerprint-corrupting dtype class: casting raw u64 row
    words to 32-bit integers (JX107).  Masked field extraction
    (BitPacker.get) must stay quiet — it's the idiom every twin uses."""

    class NarrowTwin(_TwinBase):
        def step_rows(self, rows):
            w = rows.astype(jnp.uint32)  # raw words: top 32 bits zeroed
            succ = (w + jnp.uint32(1)).astype(jnp.uint64)[:, None, :]
            valid = (rows[..., 0] < jnp.uint64(1))[:, None]
            return succ, valid

    report = audit_model(_host_model(NarrowTwin))
    assert any(
        f.rule_id == "JX107" and f.severity == Severity.WARNING
        for f in report.findings
    ), report.format()

    class MaskedTwin(_TwinBase):
        def step_rows(self, rows):
            field = (rows & jnp.uint64(0xFF)).astype(jnp.int32)  # provably small
            succ = (field + 1).astype(jnp.uint64)[:, None, :]
            valid = (rows[..., 0] < jnp.uint64(1))[:, None]
            return succ, valid

    report = audit_model(_host_model(MaskedTwin))
    assert "JX107" not in report.rule_ids(), report.format()


def test_side_effecting_kernel():
    class CallbackTwin(_TwinBase):
        def step_rows(self, rows):
            import jax

            jax.debug.print("row {}", rows[0, 0])
            succ = (rows + jnp.uint64(1))[:, None, :]
            valid = (rows[..., 0] < jnp.uint64(1))[:, None]
            return succ, valid

    report = audit_model(_host_model(CallbackTwin))
    assert any(
        f.rule_id == "JX101" and f.severity == Severity.ERROR
        for f in report.findings
    ), report.format()


def test_untraceable_kernel():
    class BrokenTwin(_TwinBase):
        def step_rows(self, rows):
            if rows[0, 0] > 0:  # traced-bool branch: TracerBoolConversionError
                return rows[:, None, :], jnp.ones((rows.shape[0], 1), bool)
            return rows[:, None, :], jnp.zeros((rows.shape[0], 1), bool)

    report = audit_model(_host_model(BrokenTwin))
    assert any(
        f.rule_id == "JX000" and f.severity == Severity.ERROR
        for f in report.findings
    ), report.format()


# ---------------------------------------------------------------------------
# preflight integration: spawn_tpu aborts on errors, skip_audit overrides
# ---------------------------------------------------------------------------


def test_spawn_tpu_preflight_aborts_before_launch():
    class I32Twin(_TwinBase):
        def step_rows(self, rows):
            succ = (rows + jnp.uint64(1)).astype(jnp.int32)[:, None, :]
            valid = (rows[..., 0] < jnp.uint64(1))[:, None]
            return succ, valid

    m = _host_model(I32Twin)
    with pytest.raises(AuditError, match="JX103"):
        m.checker().spawn_tpu(sync=True, batch=8, capacity=1 << 10)
    # escape hatch: the preflight itself is silenced (no AuditError)
    b = m.checker().skip_audit()
    assert b._preflight_audit() is None


def test_preflight_warning_prints_once(capsys):
    class FloatTwin(_TwinBase):
        def step_rows(self, rows):
            f = rows.astype(jnp.float32) + 1.0
            succ = f.astype(jnp.uint64)[:, None, :]
            valid = (rows[..., 0] < jnp.uint64(1))[:, None]
            return succ, valid

    m = _host_model(FloatTwin)
    c = m.checker().spawn_tpu(sync=True, batch=8, capacity=1 << 10)
    assert c.unique_state_count() == 2  # warnings do NOT abort the launch
    first = capsys.readouterr().err
    assert "JX102" in first
    m.checker().spawn_tpu(sync=True, batch=8, capacity=1 << 10)
    assert "JX102" not in capsys.readouterr().err  # printed once per model


def test_builder_audit_returns_report():
    report = _host_model(_TwinBase).checker().audit()
    assert isinstance(report, AuditReport)
    assert report.ok
    assert report.to_json()["ok"] is True


# ---------------------------------------------------------------------------
# handler lint rules
# ---------------------------------------------------------------------------


def _actor_model(*actors):
    m = ActorModel(cfg=None)
    for a in actors:
        m.actor(a)
    m.init_network_(Network.new_unordered_nonduplicating())
    return m


def test_handler_nondeterminism():
    class DiceActor(Actor):
        def on_start(self, id: Id, out: Out):
            return 0

        def on_msg(self, id: Id, state, src: Id, msg, out: Out):
            return int(random.random() * 10)  # AH201

    report = audit_model(_actor_model(DiceActor()))
    hits = [f for f in report.findings if f.rule_id == "AH201"]
    assert hits and hits[0].severity == Severity.ERROR, report.format()
    assert "DiceActor" in hits[0].location


def test_handler_inplace_mutation():
    class MutActor(Actor):
        def on_start(self, id: Id, out: Out):
            return 0

        def on_msg(self, id: Id, state, src: Id, msg, out: Out):
            state.items.append(msg)  # AH203: mutating method call
            state.count = 1  # AH203: assignment into the state
            return state

    report = audit_model(_actor_model(MutActor()))
    hits = [f for f in report.findings if f.rule_id == "AH203"]
    assert len(hits) == 2, report.format()
    assert all(f.severity == Severity.ERROR for f in hits)


def test_handler_rebound_state_not_flagged():
    """Rebinding the state name to a local copy and mutating THAT is
    sound; AH203 must not abort it."""

    class CopyActor(Actor):
        def on_start(self, id: Id, out: Out):
            return (0,)

        def on_msg(self, id: Id, state, src: Id, msg, out: Out):
            state = list(state)  # fresh local copy under the same name
            state.append(msg)
            return tuple(state)

    report = audit_model(_actor_model(CopyActor()))
    assert "AH203" not in report.rule_ids(), report.format()


def test_handler_set_iteration_order():
    class SetActor(Actor):
        def on_start(self, id: Id, out: Out):
            return 0

        def on_msg(self, id: Id, state, src: Id, msg, out: Out):
            for peer in {Id(1), Id(2)}:  # AH202: hash-ordered sends
                out.send(peer, msg)
            return None

    report = audit_model(_actor_model(SetActor()))
    assert any(
        f.rule_id == "AH202" and f.severity == Severity.WARNING
        for f in report.findings
    ), report.format()


def test_unhashable_state():
    class ListActor(Actor):
        def on_start(self, id: Id, out: Out):
            return []  # unhashable state

        def on_msg(self, id: Id, state, src: Id, msg, out: Out):
            return None

    report = audit_model(_actor_model(ListActor()))
    assert any(
        f.rule_id == "AH204" and f.severity == Severity.ERROR
        for f in report.findings
    ), report.format()


# -- AH205: the Paxos-ballot trap --------------------------------------------

from dataclasses import dataclass


@dataclass(frozen=True)
class TickState:
    n: int


class Ticker(Actor):
    """Counter that grows forever via a self-addressed message loop — the
    minimal ballot-style unbounded domain."""

    def on_start(self, id: Id, out: Out):
        out.send(id, ("tick",))
        return TickState(0)

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        out.send(id, ("tick",))
        return TickState(state.n + 1)


def test_unbounded_domain_warns():
    report = audit_model(_actor_model(Ticker()), deep=True)
    hits = [f for f in report.findings if f.rule_id == "AH205"]
    assert hits and hits[0].severity == Severity.WARNING, report.format()
    assert "state_bound" in hits[0].message


def test_unbounded_domain_downgraded_with_state_bound():
    class BoundedTicker(TensorBackedModel, ActorModel):
        def tensor_model(self):
            from stateright_tpu.parallel.actor_compiler import (
                compile_actor_model,
            )

            return compile_actor_model(
                self, state_bound=lambda i, s: s.n <= 3
            )

    m = BoundedTicker(cfg=None, init_history=None)
    m.actor(Ticker())
    m.init_network_(Network.new_unordered_nonduplicating())
    m.property(
        Expectation.ALWAYS, "trivial", forall_actors(lambda i, s: True)
    )
    report = audit_model(m, deep=True)
    hits = [f for f in report.findings if f.rule_id == "AH205"]
    assert hits and hits[0].severity == Severity.INFO, report.format()
    assert report.ok and not report.warnings


# ---------------------------------------------------------------------------
# CF301: config mutation after twin resolution is a preflight failure
# ---------------------------------------------------------------------------


def test_config_mutation_after_resolution_flagged():
    """Satellite: TensorBackedModel._config_mutated raises only after the
    first fingerprint; the audit flags the silent window before that."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    m = TwoPhaseSys(3)
    assert m._tensor_cached() is not None  # resolve + snapshot the config
    m.rm_count = 2  # direct write: bypasses _config_mutated entirely
    report = audit_model(m)
    hits = [f for f in report.findings if f.rule_id == "CF301"]
    assert hits and hits[0].severity == Severity.ERROR, report.format()
    with pytest.raises(AuditError, match="CF301"):
        m.checker().spawn_tpu()


def test_config_mutation_invisible_to_signature_caught_deep():
    """The deep tier re-resolves the twin and diffs it against the cache,
    catching drift the cheap signature cannot see (config behind a dict)."""

    class WidthTwin(_TwinBase):
        def __init__(self, model):
            super().__init__(model)
            self.width = model.cfg["w"]

        def init_rows(self):
            return np.zeros((1, self.width), np.uint64)

        def step_rows(self, rows):
            succ = (rows + jnp.uint64(1))[:, None, :]
            valid = (rows[..., 0] < jnp.uint64(1))[:, None]
            return succ, valid

        def encode_state(self, s):
            return (int(s),) * self.width

    class DictCfg(_HostModel):
        twin_cls = WidthTwin

        def __init__(self):
            self.cfg = {"w": 1}  # mutable config the signature cannot see

    m = DictCfg()
    assert m._tensor_cached() is not None
    m.cfg["w"] = 2
    report = audit_model(m, deep=True)
    assert any(f.rule_id == "CF301" for f in report.findings), report.format()


# ---------------------------------------------------------------------------
# satellite: every shipped model audits clean (or exactly-pinned)
# ---------------------------------------------------------------------------


def _shipped_models():
    from stateright_tpu.models.dining import dining_model
    from stateright_tpu.models.increment import Increment
    from stateright_tpu.models.increment_lock import IncrementLock
    from stateright_tpu.models.linearizable_register import abd_model
    from stateright_tpu.models.paxos import paxos_model
    from stateright_tpu.models.quickstart import (
        SlidingPuzzle,
        vector_clock_model,
    )
    from stateright_tpu.models.single_copy_register import single_copy_model
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys
    from stateright_tpu.models.write_once_register import wo_register_model

    return [
        ("two_phase_commit", TwoPhaseSys(3)),
        ("paxos", paxos_model(1)),
        ("linearizable_register", abd_model(2, 2)),
        ("single_copy_register", single_copy_model(1)),
        ("write_once_register", wo_register_model(1, 2)),
        ("dining", dining_model(3)),
        ("increment", Increment(2)),
        ("increment_lock", IncrementLock(2)),
        ("sliding_puzzle", SlidingPuzzle()),
        ("vector_clocks", vector_clock_model()),
    ]


def test_shipped_models_audit_clean():
    """New rules cannot silently break the fleet: every shipped model must
    stay free of errors AND warnings (infos are advisory)."""
    bad = []
    for name, model in _shipped_models():
        report = audit_model(model, deep=True)
        if report.errors or report.warnings:
            bad.append((name, report.format()))
    assert not bad, "\n\n".join(f"{n}:\n{r}" for n, r in bad)


def test_quickstart_clock_pinned_finding():
    """The Lamport clock model is the one shipped example with a pinned
    non-clean report: logical clocks grow without bound (AH205), which is
    exactly what the rule exists to catch."""
    from stateright_tpu.models.quickstart import clock_model

    report = audit_model(clock_model(), deep=True)
    assert report.ok  # warning-severity only
    assert {f.rule_id for f in report.warnings} == {"AH205"}


@pytest.mark.slow
def test_fleet_audit_all_examples():
    from stateright_tpu.models._cli import fleet_audit

    assert fleet_audit() == 0


# ---------------------------------------------------------------------------
# CLI verbs
# ---------------------------------------------------------------------------


def test_cli_audit_verb(capsys):
    from stateright_tpu.models import increment

    increment.main(["audit"])
    out = capsys.readouterr().out
    assert "audit Increment" in out
    assert "0 error(s)" in out


def test_cli_fleet_audit_subset(capsys):
    from stateright_tpu.models._cli import fleet_audit

    rc = fleet_audit(
        ["increment", "increment_lock", "two_phase_commit", "quickstart"]
    )
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "audit fleet: CLEAN" in out
    # the lamport example's pinned AH205 warning rides along without
    # failing the fleet (errors fail, warnings do not)
    assert "AH205" in out


# ---------------------------------------------------------------------------
# satellite: bucket-occupancy counters in the audit/status report
# ---------------------------------------------------------------------------


def test_occupancy_stats_and_audit_metrics():
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    m = TwoPhaseSys(3)
    c = m.checker().spawn_tpu(sync=True, batch=64, capacity=1 << 12)
    stats = c.occupancy_stats()
    assert stats is not None
    assert stats["occupied"] == c.unique_state_count() == 288
    assert 0 < stats["load_factor"] <= 1
    assert (
        sum(k * v for k, v in enumerate(stats["histogram"]))
        == stats["occupied"]
    )
    assert stats["max_bucket"] <= stats["slots_per_bucket"]
    # the counters fold into the model's last audit report
    assert m._audit_report.metrics["table"]["occupied"] == 288


def test_explorer_status_exposes_audit_and_table():
    from stateright_tpu.explorer import ExplorerServer
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    server = ExplorerServer(
        TwoPhaseSys(3).checker(), "localhost:0", strategy="tpu", batch=64
    ).start_background()
    try:
        host, port = server.addr.rsplit(":", 1)
        deadline = time.monotonic() + 60
        status = None
        while time.monotonic() < deadline:
            conn = http.client.HTTPConnection(host, int(port), timeout=10)
            conn.request("GET", "/.status")
            status = json.loads(conn.getresponse().read())
            conn.close()
            if status["done"]:
                break
            time.sleep(0.2)
        assert status is not None and status["done"]
        # the preflight audit report rides /.status
        assert status["audit"] is not None
        assert status["audit"]["ok"] is True
        assert status["audit"]["model"] == "TwoPhaseSys"
        # ... and so do the visited-table occupancy counters
        assert status["table"]["occupied"] == status["unique_state_count"]
    finally:
        server.shutdown()
