"""Crash-safe checking (docs/robustness.md): periodic atomic autosave
checkpoints, supervised runs with retry/backoff + graceful OOM
degradation, and the deterministic fault-injection layer.

Fast tier: unit-level fault-plan / atomic-write / classification /
checkpoint-store tests plus the jaxpr+cache contract pins.  The chaos
integration acceptance runs (supervised 2pc-5 killed mid-flight,
injected growth-OOM degrading to a spill eviction, lineage-gated
kill+resume chains) are pinned ``medium`` per the tiering rule —
integration work that needs double-digit seconds stays out of the fast
tier.

Pinned chaos contracts (the ISSUE 13 acceptance criteria):

 (a) a supervised 2pc-5 killed mid-flight by an injected fault
     auto-resumes from an autosave generation and finishes bit-identical
     to an uninterrupted run, with the PR 12 lineage diff classifying
     the chain IDENTICAL;
 (b) an injected RESOURCE_EXHAUSTED at a growth boundary degrades to a
     spill eviction (counts bit-identical to unconstrained) instead of
     crashing;
 (c) autosave/fault hooks OFF leave the step jaxpr bit-identical and
     the engine cache unkeyed, both with and without a plan installed.
"""

import errno
import json

import numpy as np
import pytest

import jax

from stateright_tpu import checkpoint as ckpt
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.supervisor import (
    FATAL,
    IO,
    OOM,
    PREEMPTION,
    classify_failure,
    supervise,
)
from stateright_tpu.testing.faults import (
    Fault,
    FaultPlan,
    InjectedKill,
    InjectedOOM,
    fire,
)

# 2pc pinned counts (examples/2pc.rs:125-140).  ``states`` (generated,
# incl. duplicates) is config-invariant: every unique state is expanded
# exactly once regardless of batch/growth schedule, so the total is
# sum-over-uniques of enabled actions + inits.
UNIQUE_2PC3, STATES_2PC3 = 288, 1146
UNIQUE_2PC5, STATES_2PC5 = 8832, 58146


# -- fault-plan units (fast tier) --------------------------------------------


def test_fault_plan_fires_once_at_the_scheduled_occurrence():
    plan = FaultPlan([Fault(site="host_sync", action="kill", at=2)])
    with plan:
        fire("host_sync")  # 0
        fire("host_sync")  # 1
        with pytest.raises(InjectedKill):
            fire("host_sync")  # 2 — fires
        fire("host_sync")  # 3 — one-shot: never again
    assert plan.fired == [{"site": "host_sync", "action": "kill", "at": 2}]
    assert plan.faults[0].fired


def test_fault_plan_uninstalled_is_inert():
    plan = FaultPlan([Fault(site="host_sync", action="kill", at=0)])
    fire("host_sync")  # no plan installed: nothing can fire
    assert plan.fired == []


def test_fault_plan_sites_are_independent_counters():
    plan = FaultPlan([
        Fault(site="growth", action="oom", at=1),
        Fault(site="spill_flush", action="enospc", at=0),
    ])
    with plan:
        fire("growth")  # growth[0]: not yet
        with pytest.raises(OSError) as ei:
            fire("spill_flush")  # spill_flush[0]: ENOSPC
        assert ei.value.errno == errno.ENOSPC
        with pytest.raises(InjectedOOM) as oi:
            fire("growth")  # growth[1]: fires
        assert "RESOURCE_EXHAUSTED" in str(oi.value)


def test_fault_plan_seeded_schedule_is_deterministic():
    a = FaultPlan.scheduled(7, "host_sync", lo=1, hi=32)
    b = FaultPlan.scheduled(7, "host_sync", lo=1, hi=32)
    assert a.faults[0].at == b.faults[0].at
    assert 1 <= a.faults[0].at < 32
    # JSON round trip preserves the schedule
    back = FaultPlan.from_json(a.to_json())
    assert back.faults[0].at == a.faults[0].at
    assert back.seed == a.seed


def test_fault_plan_rejects_unknown_site_and_action():
    with pytest.raises(ValueError):
        FaultPlan([Fault(site="nope")])
    with pytest.raises(ValueError):
        FaultPlan([Fault(site="growth", action="nope")])


def test_fault_plan_jsonl_trail(tmp_path):
    plan = FaultPlan([Fault(site="growth", action="io", at=0)], seed=3)
    with plan:
        with pytest.raises(OSError):
            fire("growth", unique=17)
    out = tmp_path / "faults.jsonl"
    plan.to_jsonl(str(out))
    lines = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert lines[0]["kind"] == "plan" and lines[0]["seed"] == 3
    assert lines[1] == {
        "kind": "fired", "site": "growth", "action": "io", "at": 0,
        "unique": 17,
    }


def test_fault_fire_records_into_the_ring():
    from stateright_tpu.telemetry import FlightRecorder

    rec = FlightRecorder()
    plan = FaultPlan([Fault(site="host_sync", action="kill", at=0)])
    with plan:
        with pytest.raises(InjectedKill):
            fire("host_sync", recorder=rec)
    (r,) = rec.records("fault")
    assert (r["site"], r["action"], r["at"], r["v"]) == (
        "host_sync", "kill", 0, 1
    )


# -- failure classification (fast tier) --------------------------------------


def test_classify_failure_taxonomy():
    assert classify_failure(InjectedKill("x")) == PREEMPTION
    assert classify_failure(KeyboardInterrupt()) == PREEMPTION
    assert classify_failure(SystemExit(1)) == PREEMPTION
    assert classify_failure(InjectedOOM("RESOURCE_EXHAUSTED: x")) == OOM
    # a real jaxlib device OOM matches structurally (the
    # RESOURCE_EXHAUSTED status in the message), never by import
    # identity — and an XlaRuntimeError WITHOUT it (INVALID_ARGUMENT,
    # INTERNAL: codegen/model bugs) is FATAL, not retried
    XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
    assert classify_failure(
        XlaRuntimeError("RESOURCE_EXHAUSTED: out of memory allocating")
    ) == OOM
    assert classify_failure(XlaRuntimeError("INTERNAL: boom")) == FATAL
    assert classify_failure(
        RuntimeError("RESOURCE_EXHAUSTED: out of memory")
    ) == OOM
    assert classify_failure(OSError(errno.EIO, "disk")) == IO
    assert classify_failure(ValueError("model bug")) == FATAL
    assert classify_failure(RuntimeError("poisoned rows")) == FATAL


def test_supervise_reraises_fatal_without_retry(tmp_path):
    calls = []

    def spawn(b, resume=None, **kw):
        calls.append(1)
        raise ValueError("model bug")

    with pytest.raises(ValueError):
        supervise(
            TwoPhaseSys(3).checker(),
            autosave_dir=str(tmp_path), spawn=spawn,
            sleep=lambda s: None,
        )
    assert len(calls) == 1  # no retry on a fatal class


def test_supervise_respects_the_restart_budget(tmp_path):
    def spawn(b, resume=None, **kw):
        raise InjectedKill("always")

    with pytest.raises(InjectedKill):
        supervise(
            TwoPhaseSys(3).checker(),
            autosave_dir=str(tmp_path), spawn=spawn,
            max_restarts=3, sleep=lambda s: None,
        )


def test_supervise_backoff_is_bounded_and_grows(tmp_path):
    delays = []
    boom = [0]

    def spawn(b, resume=None, **kw):
        if boom[0] < 4:
            boom[0] += 1
            raise InjectedKill("x")
        return TwoPhaseSys(3).checker().spawn_tpu(
            sync=True, capacity=1 << 12, batch=64
        )

    res = supervise(
        TwoPhaseSys(3).checker(),
        autosave_dir=str(tmp_path), spawn=spawn,
        max_restarts=5, backoff_base=0.5, backoff_max=2.0,
        sleep=delays.append, seed=1,
    )
    assert res.restarts == 4
    assert len(delays) == 4
    # exponential up to the cap, jitter <= 25%
    assert delays[0] <= 0.5 * 1.25
    assert all(d <= 2.0 * 1.25 for d in delays)
    assert delays[1] >= delays[0] / 1.25


# -- atomic writes + torn-tail resilience (fast tier) ------------------------


def test_atomic_write_failure_leaves_old_contents(tmp_path):
    from stateright_tpu.telemetry._atomic import atomic_write_json

    path = tmp_path / "doc.json"
    atomic_write_json(str(path), {"gen": 1})
    plan = FaultPlan([Fault(site="atomic_write", action="io", at=0)])
    with plan:
        with pytest.raises(OSError):
            atomic_write_json(str(path), {"gen": 2})
    assert json.loads(path.read_text()) == {"gen": 1}
    # no temp litter
    assert [p.name for p in tmp_path.iterdir()] == ["doc.json"]


def test_registry_index_survives_a_torn_tail(tmp_path):
    """A killed writer tears at most the ledger's LAST line; prior
    records stay readable and later appends work (the crash contract of
    durable_append_line + index())."""
    from stateright_tpu.telemetry.registry import RunRegistry

    reg = RunRegistry(str(tmp_path))
    doc1 = {"run_id": "aaa", "v": 1, "model": "M", "engine": "wavefront",
            "totals": {"unique": 1}, "config": {"key": "k1"}}
    reg.record_doc(doc1)
    # simulate the torn tail a SIGKILL mid-append leaves
    with open(reg.index_path, "a") as f:
        f.write('{"run_id": "bbb", "trunc')
    assert [r["run_id"] for r in reg.index()] == ["aaa"]
    doc2 = dict(doc1, run_id="ccc")
    reg.record_doc(doc2)
    assert [r["run_id"] for r in reg.index()] == ["aaa", "ccc"]
    # the archives themselves are complete JSON (atomic replace writes)
    assert reg.load("aaa")["run_id"] == "aaa"
    assert reg.load("ccc")["run_id"] == "ccc"


# -- checkpoint generation store (fast tier) ---------------------------------


def _snap(unique: int) -> dict:
    return {
        "unique": np.int64(unique), "scount": np.int64(unique * 3),
        "maxdepth": np.int32(4), "disc": np.zeros(3, np.uint64),
    }


def test_generations_rotate_and_latest_wins(tmp_path):
    root = str(tmp_path)
    for i in range(5):
        ckpt.save_generation(
            root, i, _snap(i), {"run_id": "r", "totals": {"unique": i}},
            keep=2,
        )
    gens = ckpt.list_generations(root)
    assert [g["gen"] for g in gens] == [3, 4]
    assert all(g["complete"] for g in gens)
    snap, man = ckpt.latest_generation(root)
    assert int(snap["unique"]) == 4
    assert man["gen"] == 4 and man["v"] == ckpt.CKPT_V
    # numbering continues across restarts — a resumed run never
    # overwrites its parent's generations
    assert ckpt.next_generation(root) == 5


def test_torn_generation_is_skipped_loudly(tmp_path, capsys):
    """A generation without a committed manifest (or with a garbage npz)
    is TORN: resume warns and falls back to the previous complete one —
    a half-written snapshot never poisons resume."""
    root = str(tmp_path)
    ckpt.save_generation(
        root, 0, _snap(7), {"run_id": "r", "totals": {"unique": 7}},
    )
    # torn case 1: npz present, manifest missing (killed between writes)
    torn = tmp_path / "gen-000001"
    torn.mkdir()
    (torn / "snapshot.npz").write_bytes(b"\x00garbage")
    # torn case 2: manifest committed but npz unreadable (bit rot)
    torn2 = tmp_path / "gen-000002"
    torn2.mkdir()
    (torn2 / "snapshot.npz").write_bytes(b"not-an-npz")
    (torn2 / "MANIFEST.json").write_text('{"v": 1, "gen": 2}\n')
    snap, man = ckpt.latest_generation(root)
    assert int(snap["unique"]) == 7 and man["gen"] == 0
    err = capsys.readouterr().err
    assert "torn generation" in err and "unreadable" in err


def test_failed_snapshot_write_preserves_previous_generation(tmp_path):
    root = str(tmp_path)
    ckpt.save_generation(
        root, 0, _snap(3), {"run_id": "r", "totals": {"unique": 3}},
    )
    plan = FaultPlan([Fault(site="snapshot_write", action="enospc", at=0)])
    with plan:
        with pytest.raises(OSError):
            ckpt.save_generation(
                root, 1, _snap(9), {"run_id": "r", "totals": {"unique": 9}},
            )
    snap, man = ckpt.latest_generation(root)
    assert int(snap["unique"]) == 3  # the old generation is intact


def test_snapshot_write_kill_fault_reaches_the_supervisor(tmp_path):
    """A scheduled kill at the ``snapshot_write`` seam is manufactured
    process death, not a write failure: it must propagate through the
    engines' autosave guard to the supervisor's classifier (preemption)
    instead of being swallowed as a degraded write."""
    plan = FaultPlan([Fault(site="snapshot_write", action="kill", at=0)])
    with plan:
        res = supervise(
            TwoPhaseSys(3).checker().telemetry(),
            autosave_dir=str(tmp_path / "auto"), every_secs=0.0,
            max_restarts=2, sleep=lambda s: None,
            capacity=1 << 12, batch=32, steps_per_call=2,
        )
    assert res.restarts == 1
    assert res.attempts[0].outcome == PREEMPTION
    assert plan.fired and plan.fired[0]["site"] == "snapshot_write"
    assert res.unique_state_count() == UNIQUE_2PC3
    assert res.state_count() == STATES_2PC3


def test_non_oserror_autosave_failure_is_accounted(tmp_path, monkeypatch):
    """A non-OSError generation-write failure (e.g. a snapshot
    materialization bug) must not kill the run — but it must be
    DISCLOSED: the durability block's failure counter bumps and an
    ``ok=false`` checkpoint record lands in the ring, same as an
    OSError from the atomic write."""
    def boom(*a, **k):
        raise ValueError("manufactured non-OSError write failure")

    monkeypatch.setattr(ckpt, "save_generation", boom)
    c = (
        TwoPhaseSys(3).checker().telemetry()
        .autosave(str(tmp_path / "auto"), every_secs=0.0)
        .spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    )
    assert c.is_done()
    assert c.unique_state_count() == UNIQUE_2PC3
    dur = c.durability_status()
    assert dur["autosave"]["failures"] >= 1
    recs = c.flight_recorder.records("checkpoint")
    assert recs and all(r["ok"] is False for r in recs)
    assert "ValueError" in recs[0]["error"]


def test_resolve_autosave_env_knobs(monkeypatch, tmp_path, capsys):
    monkeypatch.delenv(ckpt.ENV_AUTOSAVE, raising=False)
    assert ckpt.resolve_autosave(None) is None
    monkeypatch.setenv(ckpt.ENV_AUTOSAVE, str(tmp_path))
    monkeypatch.setenv(ckpt.ENV_AUTOSAVE_SECS, "5")
    monkeypatch.setenv(ckpt.ENV_AUTOSAVE_KEEP, "junk")
    got = ckpt.resolve_autosave(None)
    assert got == {
        "dir": str(tmp_path), "every_secs": 5.0, "keep": ckpt.DEFAULT_KEEP,
    }
    assert "malformed" in capsys.readouterr().err
    # builder opts win over env
    assert ckpt.resolve_autosave({"dir": "x", "every_secs": 1, "keep": 2})[
        "dir"
    ] == "x"


# -- spill disk-tier degradation (fast tier, unit level) ---------------------


def test_spill_store_degrades_on_enospc_instead_of_crashing(capsys):
    from stateright_tpu.spill import SpillStore

    store = SpillStore(host_budget=1)  # any append overflows the budget
    fps = np.arange(1, 300, dtype=np.uint64)
    plan = FaultPlan([Fault(site="spill_flush", action="enospc", at=0)])
    with plan:
        store.append(fps, fps)
    assert store.degraded
    assert "enospc" in (store.degraded_reason or "").lower()
    assert "degraded" in capsys.readouterr().err
    # exactness survives: the index + RAM segments are intact, no disk
    assert store.disk_bytes == 0 and store.host_bytes > 0
    assert bool(store.contains(np.asarray([5], np.uint64))[0])
    # warn-once: a second overflow does not retry or re-warn
    store.append(fps + 1000, fps)
    assert store.disk_bytes == 0
    assert "degraded" not in capsys.readouterr().err
    got = np.concatenate([f for f, _ in store.iter_segments()])
    assert got.size == len(store)
    store.close()


# -- contract (c): jaxpr bit-identical + cache unkeyed (fast tier) -----------


def _build_jaxpr(checker) -> str:
    init_fn, run_fn = checker._build(
        checker._cap, checker._qcap, checker._batch, checker._cand
    )
    carry, _ = init_fn()
    return str(jax.make_jaxpr(lambda cr: run_fn(cr))(tuple(carry)))


def test_autosave_and_faults_leave_step_jaxpr_bit_identical(tmp_path):
    """Acceptance (c): autosave armed or a FaultPlan installed, the
    engines compile the SAME program — injection and checkpointing are
    host-side only — and the engine cache key is unchanged."""
    kw = dict(sync=True, capacity=1 << 12, batch=64)
    plain = TwoPhaseSys(3).checker().spawn_tpu(**kw)
    base_jaxpr = _build_jaxpr(plain)
    base_key = plain._engine_key(
        plain._cap, plain._qcap, plain._batch, plain._cand
    )
    plan = FaultPlan(
        [Fault(site="host_sync", action="kill", at=10**9)]  # never fires
    )
    with plan:
        armed = TwoPhaseSys(3).checker().autosave(
            str(tmp_path), every_secs=3600
        ).spawn_tpu(**kw)
    assert armed.unique_state_count() == UNIQUE_2PC3
    assert _build_jaxpr(armed) == base_jaxpr
    assert armed._engine_key(
        armed._cap, armed._qcap, armed._batch, armed._cand
    ) == base_key


# -- autosave end-to-end on a small space (fast tier) ------------------------


def test_autosave_generations_resume_bit_identical(tmp_path):
    root = str(tmp_path / "auto")
    running = TwoPhaseSys(3).checker().telemetry().autosave(
        root, every_secs=0.0, keep=2
    ).spawn_tpu(capacity=1 << 12, batch=32, steps_per_call=2)
    # let at least one generation land mid-run, then "preempt"
    while not ckpt.list_generations(root):
        if running.is_done():
            break
        import time

        time.sleep(0.01)
    running.stop().join()
    gens = ckpt.list_generations(root)
    assert gens and len(gens) <= 2  # rotation held
    found = ckpt.latest_generation(root)
    assert found is not None
    snap, man = found
    # the manifest is self-describing: identity + config + progress
    assert man["run_id"] == running.run_id
    assert man["model"] == "TwoPhaseSys"
    assert man["engine"] == "wavefront"
    assert man["config"]["key"]
    assert {p["name"] for p in man["properties"]} == {
        "abort agreement", "commit agreement", "consistent",
    }
    # checkpoint ring records + stage attribution + durability block
    rec = running.flight_recorder
    assert rec.kind_count("checkpoint") >= 1
    assert any(r["ok"] for r in rec.records("checkpoint"))
    assert rec.counters().get("stage_checkpoint_secs", 0) >= 0
    dur = running.durability_status()
    assert dur["autosave"]["generations"] >= 1
    assert dur["restarts"] == 0
    # resume from the latest generation: bit-identical completion
    resumed = TwoPhaseSys(3).checker().spawn_tpu(sync=True, resume=snap)
    assert resumed.unique_state_count() == UNIQUE_2PC3
    assert resumed.state_count() == STATES_2PC3
    assert resumed.parent_run_id == running.run_id
    resumed.assert_properties()


def test_report_durability_block_is_deterministic_config_only(tmp_path):
    """The report's durability block carries the CONFIG subset only —
    cadence + restart count — never wall-clock generation counts
    (report-determinism contract)."""
    from stateright_tpu.telemetry.report import build_report

    c = TwoPhaseSys(3).checker().autosave(
        str(tmp_path), every_secs=30.0, keep=4
    ).spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    body = build_report(c)
    assert body["durability"] == {
        "v": ckpt.CKPT_V,
        "restarts": 0,
        "autosave": {"every_secs": 30.0, "keep": 4},
    }
    # without autosave or a supervision trail there is NO block at all
    c2 = TwoPhaseSys(3).checker().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    assert "durability" not in build_report(c2)


# -- chaos integration acceptance (medium tier: >15s integration) ------------


@pytest.mark.medium
def test_supervised_2pc5_killed_mid_flight_resumes_bit_identical(tmp_path):
    """Acceptance (a): a supervised 2pc-5 killed mid-flight by an
    injected fault auto-resumes from an autosave generation and finishes
    bit-identical (unique, generated, discoveries), restart count 1."""
    d = str(tmp_path / "auto")
    plan = FaultPlan([Fault(site="host_sync", action="kill", at=6)])
    with plan:
        res = supervise(
            TwoPhaseSys(5).checker().telemetry(),
            autosave_dir=d, every_secs=0.0, max_restarts=3,
            sleep=lambda s: None,
            batch=64, steps_per_call=2,
        )
    assert plan.fired and plan.fired[0]["site"] == "host_sync"
    assert res.restarts == 1
    assert res.unique_state_count() == UNIQUE_2PC5
    assert res.state_count() == STATES_2PC5
    assert res.checker.parent_run_id  # the resume linked its parent
    res.checker.assert_properties()
    rec = res.checker.flight_recorder
    (restart,) = rec.records("restart")
    assert restart["reason"] == "preemption" and restart["attempt"] == 1
    assert restart["parent_run_id"] == res.checker.parent_run_id


@pytest.mark.medium
def test_injected_growth_oom_degrades_to_spill_eviction(
    tmp_path, monkeypatch,
):
    """Acceptance (b): RESOURCE_EXHAUSTED injected at a growth boundary
    degrades to a spill eviction — the supervisor arms the PR 8 tier,
    the resumed run evicts instead of growing, and the counts stay
    bit-identical to an unconstrained run."""
    from stateright_tpu.parallel.tensor_model import twin_or_none
    from stateright_tpu.telemetry.memory import (
        ENV_DEVICE_BYTES,
        total_bytes,
        wavefront_specs,
    )

    m = TwoPhaseSys(5)
    twin = twin_or_none(m)
    n_props = len(list(m.properties()))
    batch, bloom, qcap = 128, 1 << 14, 4096
    sp = (bloom, 4 * batch * twin.max_actions)

    def tot(cap):
        return total_bytes(
            wavefront_specs(twin, n_props, cap, qcap, batch, spill=sp)
        )

    monkeypatch.setenv(
        ENV_DEVICE_BYTES, str(tot(1 << 13) + tot(1 << 14) - 1)
    )
    monkeypatch.setenv("STATERIGHT_TPU_CAPACITY_GUARD", "off")
    plan = FaultPlan([Fault(site="growth", action="oom", at=0)])
    with plan:
        res = supervise(
            TwoPhaseSys(5).checker().telemetry(),
            autosave_dir=str(tmp_path / "auto"), every_secs=0.0,
            max_restarts=3, sleep=lambda s: None,
            batch=batch, steps_per_call=8, capacity=1 << 12,
            queue_capacity=qcap, spill_bloom_bits=bloom,
        )
    assert res.restarts == 1
    assert res.degradations == ["spill_armed"]
    assert res.unique_state_count() == UNIQUE_2PC5
    assert res.state_count() == STATES_2PC5
    sp_status = res.checker.spill_status()
    assert sp_status["evictions"] >= 1  # evicted, did not grow past the wall
    res.checker.assert_properties()


def test_oom_without_spill_shrinks_the_resumed_batch(tmp_path):
    """The non-spill degradation path (here: POR requested, which spill
    refuses to compose with): an injected growth-OOM halves the
    expansion batch, and the halving actually LANDS on the resumed
    run's buffer layout — the supervise loop re-applies it to every
    freshly loaded generation (a one-shot snap mutation would be
    silently discarded)."""
    plan = FaultPlan([Fault(site="growth", action="oom", at=0)])
    with plan:
        res = supervise(
            TwoPhaseSys(3).checker().por().telemetry(),
            autosave_dir=str(tmp_path / "auto"), every_secs=0.0,
            max_restarts=2, sleep=lambda s: None,
            # cand=64 keeps the pre-sizing rule (cand*4 <= cap) from
            # inflating the table past every growth boundary — the run
            # must actually HIT a boundary for the fault to fire
            capacity=1 << 10, batch=64, steps_per_call=2, cand=64,
        )
    assert res.restarts == 1
    assert res.degradations == ["batch_shrunk(64->32)"]
    assert res.checker._batch == 32  # the shrink governed the resume
    assert res.unique_state_count() == UNIQUE_2PC3
    assert res.state_count() == STATES_2PC3
    res.checker.assert_properties()


def test_supervise_leaves_no_trail_on_the_builder(tmp_path):
    """Supervision state must not outlive the call: a later plain spawn
    from the same builder reports no restarts, no degradations, no
    autosave cadence into the supervisor's dir, and no armed spill
    tier — never a stale trail from the supervised run."""
    b = TwoPhaseSys(3).checker().telemetry()
    plan = FaultPlan([Fault(site="host_sync", action="kill", at=2)])
    with plan:
        res = supervise(
            b, autosave_dir=str(tmp_path / "auto"), every_secs=0.0,
            max_restarts=2, sleep=lambda s: None,
            capacity=1 << 12, batch=32, steps_per_call=2,
        )
    assert res.restarts == 1
    assert not hasattr(b, "_supervise_restarts")
    # config mutated for supervision (autosave arming, spill arming on
    # an OOM degradation) is restored too, not just the private attrs
    assert b.autosave_opts is None and b.spill_mode is None
    later = b.spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    assert later.durability_status() is None


@pytest.mark.medium
def test_killed_parent_gets_stub_archived_and_lineage_gate_passes(
    tmp_path, capsys,
):
    """Cross-process recovery story end to end: a run killed before it
    could archive itself leaves only autosave generations; the next
    supervise over the same dir archives a checkpoint-derived STUB for
    the dead parent, resumes, completes — and ``compare parent child
    --expect=IDENTICAL`` passes the PR 12 lineage gate (resumed >=
    parent totals, discoveries preserved)."""
    from stateright_tpu.models._cli import compare_reports_cmd

    auto = str(tmp_path / "auto")
    runs = str(tmp_path / "runs")
    # "process 1": supervised run dies to an injected kill with the
    # restart budget exhausted (the in-process stand-in for SIGKILL —
    # nothing after the kill runs, no report, no archive)
    plan = FaultPlan([Fault(site="host_sync", action="kill", at=4)])
    with plan:
        with pytest.raises(InjectedKill):
            supervise(
                TwoPhaseSys(3).checker().telemetry().runs(runs),
                autosave_dir=auto, every_secs=0.0, max_restarts=0,
                sleep=lambda s: None,
                capacity=1 << 12, batch=32, steps_per_call=2,
            )
    _, man = ckpt.latest_generation(auto)
    parent_id = man["run_id"]
    from stateright_tpu.telemetry.registry import RunRegistry

    assert RunRegistry(runs).index() == []  # the parent never archived
    # "process 2": same command, same dirs — resumes and completes
    res = supervise(
        TwoPhaseSys(3).checker().telemetry().runs(runs),
        autosave_dir=auto, every_secs=0.0, max_restarts=0,
        sleep=lambda s: None,
        capacity=1 << 12, batch=32, steps_per_call=2,
    )
    res.checker.join()
    assert res.unique_state_count() == UNIQUE_2PC3
    child_id = res.checker.run_id
    reg = RunRegistry(runs)
    ids = [r["run_id"] for r in reg.index()]
    assert parent_id in ids and child_id in ids
    stub = reg.load(parent_id)
    assert stub["totals"]["interrupted"] is True
    assert stub["totals"]["done"] is False
    # the registry links the chain parent -> child
    chain = [r["run_id"] for r in reg.chain(child_id)]
    assert chain == [parent_id, child_id]
    # the one-command lineage gate (docs/telemetry.md "Comparing runs")
    capsys.readouterr()
    rc = compare_reports_cmd([
        parent_id, child_id, f"--registry={runs}", "--expect=IDENTICAL",
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "lineage" in out


# -- cooperative yield (the fleet scheduler's preemption hook) ---------------


def test_supervise_yield_event_stops_and_marks_partial(tmp_path):
    """A pre-set ``yield_event`` asks the engine to stop at its next
    host sync: ``supervise`` returns ``yielded=True`` WITHOUT burning a
    restart, and a fresh ``supervise`` on the same dir continues the
    work (with fakes: the scripted second attempt completes)."""
    import threading

    from tests.fleet_fakes import FakeBuilder

    b = FakeBuilder(unique=5, states=8, depth=1,
                    spawn_plan={0: {"block": True}})
    ev = threading.Event()
    ev.set()
    run = supervise(b, autosave_dir=str(tmp_path), every_secs=60,
                    yield_event=ev)
    assert run.yielded is True
    assert run.restarts == 0  # a yield is not a failure
    resumed = supervise(b, autosave_dir=str(tmp_path), every_secs=60)
    assert resumed.yielded is False
    assert resumed.unique_state_count() == 5
    assert len(b.spawn_log) == 2


@pytest.mark.medium
def test_supervise_yielded_2pc4_resumes_bit_identical(tmp_path):
    """The yield/resume contract on a REAL engine (docs/fleet.md
    "Preemption"): a yielded run leaves a resumable final autosave
    generation, and re-supervising the same dir finishes with counts
    bit-identical to an uninterrupted run, linked by lineage."""
    import threading

    d = str(tmp_path / "auto")
    ev = threading.Event()
    ev.set()  # yield at the very first opportunity
    part = supervise(
        TwoPhaseSys(4).checker().telemetry(),
        autosave_dir=d, every_secs=0.0, yield_event=ev,
        batch=64, steps_per_call=2,
    )
    assert part.yielded is True
    assert ckpt.latest_gen_number(d) is not None  # resume point exists
    assert part.unique_state_count() < 1568  # genuinely partial
    done = supervise(
        TwoPhaseSys(4).checker().telemetry(),
        autosave_dir=d, every_secs=0.0,
        batch=64, steps_per_call=2,
    )
    assert done.yielded is False
    assert done.unique_state_count() == 1568
    assert done.state_count() == 8258
    assert done.checker.parent_run_id == part.checker.run_id
