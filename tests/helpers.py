"""Shared pytest helpers for the device-engine tests.

``requires_sharded_collectives`` is THE skip marker for tests that drive
the mesh-sharded engine: it needs the vma-cast collectives
(``jax.lax.pcast`` / ``jax.lax.pvary``) that the pinned local jax lacks —
the same pre-existing failure class ROADMAP tracks as the 23 standing
sharded failures.  One definition here instead of a copied ``skipif``
expression per test file, so a jax upgrade flips every sharded test on in
one place.
"""

import jax
import pytest

requires_sharded_collectives = pytest.mark.skipif(
    not (hasattr(jax.lax, "pcast") or hasattr(jax.lax, "pvary")),
    reason="sharded engine needs vma casts this jax lacks",
)
