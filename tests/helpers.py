"""Shared pytest helpers for the device-engine tests.

``requires_sharded_collectives`` is THE skip marker for tests that drive
the OLD hand-rolled ``shard_map`` engine (``parallel/sharded.py``): its
body marks per-device values with the vma-cast collectives
(``jax.lax.pcast`` / ``jax.lax.pvary``) that the pinned local jax lacks —
the same pre-existing failure class ROADMAP tracks as the standing
sharded failures.  The requirement is PER-ENGINE
(``parallel/partition.engine_requires_collectives``): the mesh engine
(``parallel/mesh.py``) partitions plain jitted global programs with
``NamedSharding`` rules and needs neither collective, so its tests RUN
(never skip) on jax 0.4.37.  One definition here instead of a copied
``skipif`` expression per test file, so a jax upgrade flips every
old-engine test on in one place.
"""

import pytest

from stateright_tpu.parallel.partition import (
    engine_requires_collectives,
    has_vma_collectives,
)

requires_sharded_collectives = pytest.mark.skipif(
    engine_requires_collectives("sharded") and not has_vma_collectives(),
    reason="the shard_map engine needs vma casts this jax lacks "
    "(the mesh engine does not — its tests never take this skip)",
)
