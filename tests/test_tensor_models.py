"""Object-form ⇄ tensor-form equivalence, and TPU-engine parity vs CPU oracle.

The equivalence obligation (SURVEY §7.1): for every reachable state, the
tensor twin's encode/decode round-trips, its jitted ``step_rows`` produces
exactly the object model's successor set, and host/device fingerprints agree.
Then the wavefront engine must reproduce the reference's pinned unique-state
counts (288 @ 3 RMs, 8,832 @ 5 RMs — reference ``examples/2pc.rs:125-140``)
and the CPU checkers' discovery behavior.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from stateright_tpu.fingerprint import hash_words
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.ops import row_hash


def reachable_states(model, limit=100_000):
    seen = {}
    frontier = list(model.init_states())
    for s in frontier:
        seen[model.fingerprint_state(s)] = s
    while frontier:
        nxt = []
        for s in frontier:
            for t in model.next_states(s):
                fp = model.fingerprint_state(t)
                if fp not in seen:
                    seen[fp] = t
                    nxt.append(t)
        frontier = nxt
        assert len(seen) < limit
    return list(seen.values())


@pytest.mark.parametrize("n", [2, 3])
def test_tensor_2pc_equivalence(n):
    sys = TwoPhaseSys(n)
    tensor = sys.tensor_model()
    states = reachable_states(sys)

    rows = np.asarray([tensor.encode_state(s) for s in states], np.uint64)
    succ, valid = tensor.step_rows(jnp.asarray(rows))
    succ, valid = np.asarray(succ), np.asarray(valid)
    dev_fps = np.asarray(row_hash(jnp.asarray(rows)))

    for i, s in enumerate(states):
        # encode/decode round-trip
        assert tensor.decode_state(rows[i]) == s
        # host fingerprint = device fingerprint = hash of encoded words
        assert sys.fingerprint_state(s) == int(dev_fps[i])
        assert sys.fingerprint_state(s) == hash_words(int(w) for w in rows[i])
        # successor sets agree (as multisets of encoded rows)
        obj_succs = sorted(
            tuple(tensor.encode_state(t)) for t in sys.next_states(s)
        )
        dev_succs = sorted(
            tuple(int(w) for w in succ[i, a])
            for a in range(tensor.max_actions)
            if valid[i, a]
        )
        assert dev_succs == obj_succs


def test_tensor_2pc_property_masks_match_object_conditions():
    sys = TwoPhaseSys(3)
    tensor = sys.tensor_model()
    states = reachable_states(sys)
    rows = jnp.asarray(
        np.asarray([tensor.encode_state(s) for s in states], np.uint64)
    )
    masks = np.asarray(tensor.property_masks(rows))
    for i, s in enumerate(states):
        for p, prop in enumerate(sys.properties()):
            assert bool(masks[i, p]) == bool(prop.condition(sys, s)), (
                prop.name,
                s,
            )


@pytest.mark.parametrize("n,expected", [(3, 288), (5, 8832)])
def test_tpu_checker_2pc_pinned_counts(n, expected):
    sys = TwoPhaseSys(n)
    checker = sys.checker().spawn_tpu(sync=True)
    assert checker.unique_state_count() == expected
    # full parity with the CPU oracle, including duplicate-counting semantics
    cpu = sys.checker().spawn_bfs().join()
    assert cpu.unique_state_count() == expected
    assert checker.state_count() == cpu.state_count()
    # same discoveries; "consistent" never violated, both agreements found
    assert set(checker.discoveries()) == set(cpu.discoveries()) == {
        "abort agreement",
        "commit agreement",
    }
    checker.assert_properties()


def test_tpu_checker_discovery_paths_are_valid_and_shortest():
    sys = TwoPhaseSys(3)
    checker = sys.checker().spawn_tpu(sync=True)
    cpu = sys.checker().spawn_bfs().join()  # single-thread BFS: shortest paths
    for name in ("abort agreement", "commit agreement"):
        path = checker.discovery(name)
        cond = sys.property_by_name(name).condition
        assert cond(sys, path.final_state())
        # wavefront discovery is level-synchronous => shortest, like 1-thread BFS
        assert len(path) == len(cpu.discovery(name))


def test_tpu_checker_capacity_overflow_restarts():
    sys = TwoPhaseSys(3)
    checker = sys.checker().spawn_tpu(
        sync=True, capacity=1 << 6, frontier_capacity=1 << 3
    )
    assert checker.unique_state_count() == 288
    assert checker._cap >= 512  # grew past 288/load-factor
    checker.assert_properties()


def test_tpu_checker_target_state_count():
    sys = TwoPhaseSys(5)
    checker = sys.checker().target_states(1000).spawn_tpu(sync=True)
    assert 1000 <= checker.unique_state_count() < 8832


def test_tpu_checker_honors_builder_timeout():
    """``timeout()`` parity with the pool checkers: the device run stops
    cooperatively at a host sync with partial counts, and its final
    snapshot resumes to the full space (a timed-out run loses no work)."""
    sys = TwoPhaseSys(5)
    c = sys.checker().timeout(0.0).spawn_tpu(
        sync=True, steps_per_call=1, frontier_capacity=1 << 6
    )
    assert c.is_done()
    assert c.unique_state_count() < 8832
    snap = c.checkpoint()
    resumed = sys.checker().spawn_tpu(
        sync=True, steps_per_call=1, frontier_capacity=1 << 6, resume=snap
    )
    assert resumed.unique_state_count() == 8832


def test_tpu_checker_requires_tensor_form():
    from stateright_tpu import Model

    class Plain(Model):
        def init_states(self):
            return [0]

        def actions(self, s):
            return []

    with pytest.raises(TypeError, match="tensor form"):
        Plain().checker().spawn_tpu(sync=True)


# -- device-side symmetry reduction -----------------------------------------


def host_fifo_sym_oracle(model):
    """FIFO BFS over ORIGINAL states deduped on the representative's
    structural hash — the engine-independent semantics the device engine
    implements.  (Symmetry-reduced *counts* are visit-order-dependent when
    the representative is not class-invariant — the reference's own DFS
    count, 665 @ 5 RMs with 2pc's sort-by-rm-state representative, differs
    from any BFS engine's for the same reason — so the device pins BFS-order
    counts against this oracle instead.)"""
    from collections import deque

    from stateright_tpu.fingerprint import stable_hash

    key = lambda s: stable_hash(s.representative())  # noqa: E731
    seen, q = set(), deque()
    for s in model.init_states():
        k = key(s)
        if k not in seen:
            seen.add(k)
            q.append(s)
    while q:
        s = q.popleft()
        for t in model.next_states(s):
            k = key(t)
            if k not in seen:
                seen.add(k)
                q.append(t)
    return len(seen)


def test_2pc_tpu_symmetry_matches_host_oracle():
    """Device symmetry reduction (representative_rows): counts match the
    host FIFO+representative-dedup oracle exactly (508 @ 5 RMs, vs 9 832
    unreduced and 665 on the reference's DFS ordering), and discoveries
    survive the reduction with genuine traces."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    checker = TwoPhaseSys(5).checker().symmetry().spawn_tpu(
        sync=True, capacity=1 << 14, frontier_capacity=1 << 9
    )
    assert checker.unique_state_count() == 508
    assert checker.unique_state_count() == host_fifo_sym_oracle(TwoPhaseSys(5))
    assert set(checker.discoveries()) == {"abort agreement", "commit agreement"}
    # discovery traces are genuine model paths (canonical-class matching)
    for name, path in checker.discoveries().items():
        m = TwoPhaseSys(5)
        assert m.property_by_name(name).condition(m, path.final_state())


@pytest.mark.medium
def test_2pc_sharded_symmetry_reduces_and_discovers():
    """The mesh engine's symmetry reduction: all-to-all routing scrambles
    enqueue order across shards, so only reduction + discovery validity are
    asserted (counts are deterministic per mesh but order-sensitive)."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    checker = TwoPhaseSys(4).checker().symmetry().spawn_tpu(
        devices=8, sync=True, capacity=1 << 13, frontier_capacity=1 << 8
    )
    full = TwoPhaseSys(4).checker().spawn_tpu(sync=True, capacity=1 << 13)
    assert checker.unique_state_count() < full.unique_state_count()
    assert set(checker.discoveries()) == {"abort agreement", "commit agreement"}


def test_representative_rows_matches_object():
    """Device canonicalizer == encode(representative(decode(row))) on every
    reachable state of the 3-RM system."""
    import jax.numpy as jnp
    import numpy as np

    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    m = TwoPhaseSys(3)
    tm = m.tensor_model()
    seen, frontier = set(), list(m.init_states())
    states = []
    while frontier:
        s = frontier.pop()
        fp = m.fingerprint_state(s)
        if fp in seen:
            continue
        seen.add(fp)
        states.append(s)
        frontier.extend(m.next_states(s))
    rows = jnp.asarray(
        np.asarray([tm.encode_state(s) for s in states], np.uint64)
    )
    got = np.asarray(tm.representative_rows(rows))
    want = np.asarray(
        [tm.encode_state(s.representative()) for s in states], np.uint64
    )
    np.testing.assert_array_equal(got, want)


def test_custom_symmetry_fn_rejected_on_device():
    import pytest

    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    b = TwoPhaseSys(3).checker().symmetry_with(lambda s: s)
    with pytest.raises(NotImplementedError, match="symmetry_with"):
        b.spawn_tpu(sync=True)
