"""Exhaustive cross-validation of the closure-strategy linearizability
verdict (``LinHistoryCodec.device_verdict``) against the object tester's
exhaustive interleaving search (reference ``linearizability.rs:178-240``).

The closure strategy replaces the enumerated verdict table with an O(C^3)
precedence-graph acyclicity check, which is what lets device checking scale
to the reference's ``paxos check 6`` bench config (6 client threads — far
past the 63-bit key and enumeration limits of the table strategy).  These
tests force-build the enumeration table anyway and demand bit-identical
verdicts on EVERY reachable joint tester state, so the reduction is proven
against the oracle rather than argued.
"""

import numpy as np
import pytest

from stateright_tpu.parallel.history_tensor import LinHistoryCodec


def closure_codec(C: int) -> LinHistoryCodec:
    return LinHistoryCodec(list(range(C)), [f"v{i}" for i in range(C)], None)


def unpack_fields(codec: LinHistoryCodec, keys: np.ndarray):
    """Invert ``key_of_fields`` for a vector of table keys."""
    C = codec.C
    tb = codec.thread_bits
    phases = np.zeros((len(keys), C), np.int32)
    snaps = np.zeros((len(keys), C), np.int32)
    rvals = np.zeros((len(keys), C), np.int32)
    for i in range(C):
        word = (keys >> (i * tb)) & ((1 << tb) - 1)
        phases[:, i] = word & 3
        snaps[:, i] = (word >> codec.phase_bits) & ((1 << codec.snap_bits) - 1)
        rvals[:, i] = (word >> (codec.phase_bits + codec.snap_bits)) & 7
    return phases, snaps, rvals


@pytest.mark.parametrize(
    "C", [1, 2, pytest.param(3, marks=pytest.mark.medium)]
)
def test_closure_matches_exhaustive_search(C):
    import jax.numpy as jnp

    codec = closure_codec(C)
    assert codec.strategy == "closure"
    codec.ensure_table()  # oracle: every reachable joint state + its verdict
    phases, snaps, rvals = unpack_fields(codec, codec.table_keys)
    got = np.asarray(
        codec.device_verdict(
            jnp.asarray(phases), jnp.asarray(snaps), jnp.asarray(rvals)
        )
    )
    mismatch = np.nonzero(got != codec.table_ok)[0]
    assert mismatch.size == 0, (
        f"C={C}: {mismatch.size}/{len(got)} verdicts disagree; first at "
        f"fields={list(zip(phases[mismatch[0]], snaps[mismatch[0]], rvals[mismatch[0]]))} "
        f"closure={got[mismatch[0]]} oracle={codec.table_ok[mismatch[0]]}"
    )


@pytest.mark.parametrize("C", [4, 5, 6, 7])
def test_closure_matches_oracle_sampled(C):
    """Full enumeration is infeasible past C=3, so sample the reachable
    joint-state space with random event walks (every intermediate state of
    every walk) and compare against the object tester's exhaustive search —
    the direct oracle for exactly the ``paxos check 6`` regime."""
    import jax.numpy as jnp

    from stateright_tpu.semantics.register import READ, write

    codec = closure_codec(C)
    rng = np.random.default_rng(12345 + C)
    read_rets = [("read_ok", codec.null_value)] + [
        ("read_ok", v) for v in codec.values
    ]
    states: dict = {}
    for _ in range(120):
        tester = codec._tester_factory()
        for i, t in enumerate(codec.threads):
            tester = tester.on_invoke(t, write(codec.values[i]))
        states.setdefault(codec.key_of_fields(codec.fields_of_tester(tester)), tester)
        while True:
            # enabled events: return an in-flight op, or invoke the read
            choices = []
            for t in codec.threads:
                if t in tester.in_flight_by_thread:
                    op = tester.in_flight_by_thread[t][1]
                    rets = read_rets if op == READ else [("write_ok",)]
                    choices += [("ret", t, r) for r in rets]
                elif len(tester.history_by_thread.get(t, ())) == 1:
                    choices.append(("inv", t, READ))
            if not choices:
                break
            kind, t, x = choices[rng.integers(len(choices))]
            tester = (
                tester.on_return(t, x) if kind == "ret" else tester.on_invoke(t, x)
            )
            states.setdefault(
                codec.key_of_fields(codec.fields_of_tester(tester)), tester
            )
    testers = list(states.values())
    assert len(testers) > 200
    fields = [codec.fields_of_tester(t) for t in testers]
    phases = jnp.asarray([[f[0] for f in fs] for fs in fields], jnp.int32)
    snaps = jnp.asarray([[f[1] for f in fs] for fs in fields], jnp.int32)
    rvals = jnp.asarray([[f[2] for f in fs] for fs in fields], jnp.int32)
    got = np.asarray(codec.device_verdict(phases, snaps, rvals))
    want = np.asarray([t.is_consistent() for t in testers])
    mismatch = np.nonzero(got != want)[0]
    assert mismatch.size == 0, (
        f"C={C}: {mismatch.size}/{len(got)} verdicts disagree; first: "
        f"{testers[mismatch[0]]!r} closure={got[mismatch[0]]}"
    )


def test_closure_rejects_write_fail_workloads():
    codec = LinHistoryCodec(
        [0, 1],
        ["v0", "v1"],
        None,
        write_rets=(("write_ok",), ("write_fail",)),
    )
    assert codec.strategy == "table"
    import jax.numpy as jnp

    z = jnp.zeros((1, 2), jnp.int32)
    with pytest.raises(ValueError):
        codec.device_verdict(z, z, z)


def test_closure_scales_past_table_cap():
    """6 clients — impossible for the table strategy (key > 63 bits) — must
    construct and evaluate without enumeration."""
    import jax.numpy as jnp

    codec = closure_codec(6)
    C = 6
    # all writes in flight: trivially linearizable
    phases = jnp.zeros((1, C), jnp.int32)
    ok = codec.device_verdict(
        phases, jnp.zeros((1, C), jnp.int32), jnp.zeros((1, C), jnp.int32)
    )
    assert bool(ok[0])
    assert not codec._table_built  # closure never paid for enumeration
