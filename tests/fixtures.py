"""Fake models for checker tests (reference ``src/test_util.rs``).

These define correctness for the checkers: exact visit orders, exact state
counts, and liveness semantics are pinned against them.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

from stateright_tpu import Expectation, Model, Property


class BinaryClock(Model):
    """2-state toggle model (reference ``test_util.rs:4-46``).
    States are 0/1; init both; action flips."""

    def init_states(self):
        return [0, 1]

    def actions(self, state):
        return ["toggle"]

    def next_state(self, state, action):
        return 1 - state

    def properties(self):
        return [Property.always("in bounds", lambda m, s: s in (0, 1))]


@dataclass
class DGraph(Model):
    """Directed graph with explicit edges + configurable properties — the
    harness for eventually/liveness semantics tests
    (reference ``test_util.rs:49-117``)."""

    inits: Sequence[int]
    edges: dict[int, Sequence[int]]
    props: Sequence[Property] = field(default_factory=list)

    def init_states(self):
        return list(self.inits)

    def actions(self, state):
        return list(self.edges.get(state, []))

    def next_state(self, state, action):
        return action  # action IS the destination node

    def properties(self):
        return list(self.props)


@dataclass
class FnModel(Model):
    """Model from a successor function, for path-reconstruction failure tests
    (reference ``test_util.rs:120-138``)."""

    inits: Sequence
    successors: Callable[[object], Sequence]

    def init_states(self):
        return list(self.inits)

    def actions(self, state):
        return list(range(len(self.successors(state))))

    def next_state(self, state, action):
        succ = self.successors(state)
        return succ[action] if action < len(succ) else None


@dataclass
class LinearEquation(Model):
    """Solve ``a*x + b*y = c (mod 256)`` by nondeterministic increments — the
    canonical checker test with known BFS/DFS visit orders and state counts
    (reference ``test_util.rs:141-188``).  State is ``(x, y)`` with u8 wrap."""

    a: int
    b: int
    c: int

    def init_states(self):
        return [(0, 0)]

    def actions(self, state):
        return ["IncreaseX", "IncreaseY"]

    def next_state(self, state, action):
        x, y = state
        if action == "IncreaseX":
            return ((x + 1) % 256, y)
        return (x, (y + 1) % 256)

    def properties(self):
        return [
            Property.sometimes(
                "solvable",
                lambda m, s: (m.a * s[0] + m.b * s[1]) % 256 == m.c % 256,
            )
        ]
