"""Engine-free fakes for fleet-scheduler unit tests.

The fast tier must exercise the scheduler's policy surface — admission,
queueing, preemption, records, ledgers — in milliseconds, which means
no JAX engine may ever spawn.  ``FakeBuilder``/``FakeChecker`` present
exactly the builder/checker surface the scheduler and ``supervise()``
touch: a twin-less model (admission admits host-side checks without a
capacity plan), the autosave/spill/telemetry mutation points supervise
saves and restores, and a checker that either completes instantly or
blocks until ``stop()`` (the cooperative-yield path).
"""

from __future__ import annotations

import threading
import uuid


class FakeModel:
    """Twin-less model stub: ``twin_or_none`` returns None, so
    admission admits it as a host-side check without pricing."""

    def properties(self):
        return []


class FakeChecker:
    """The checker surface the scheduler + supervise read.

    ``block=True`` makes ``join()`` wait for ``stop()`` (bounded, so a
    broken test fails loudly instead of hanging) — the shape of a run
    long enough to preempt.  ``fail`` raises from ``join()`` — the
    supervised-failure shape."""

    def __init__(
        self,
        model,
        *,
        unique=1,
        states=1,
        depth=1,
        discoveries=None,
        block=False,
        fail=None,
        recorder=None,
        resume=None,
    ):
        self.model = model
        self._unique = int(unique)
        self._states = int(states)
        self._depth = int(depth)
        self._discoveries = dict(discoveries or {})
        self._block = bool(block)
        self._fail = fail
        self.flight_recorder = recorder
        self.parent_run_id = (
            str(resume["run_id"])
            if resume and resume.get("run_id") else None
        )
        self._run_id = uuid.uuid4().hex[:16]
        self._stop = threading.Event()
        self._done = threading.Event()
        if not block and fail is None:
            self._done.set()

    @property
    def run_id(self) -> str:
        return self._run_id

    def is_done(self) -> bool:
        return self._done.is_set()

    def stop(self):
        self._stop.set()
        self._done.set()
        return self

    def join(self):
        if self._fail is not None:
            self._done.set()
            raise self._fail
        if self._block:
            assert self._stop.wait(10.0), "FakeChecker never stopped"
        self._done.set()
        return self

    def state_count(self) -> int:
        return self._states

    def unique_state_count(self) -> int:
        return self._unique

    def max_depth(self) -> int:
        return self._depth

    def discoveries(self) -> dict:
        return dict(self._discoveries)


class FakeBuilder:
    """The builder surface the scheduler + supervise mutate.  One
    ``FakeBuilder`` per ``Job.build()`` call, like a real builder
    factory; ``spawn_plan`` maps the spawn ordinal (0-based, across
    ALL builders sharing the plan list) to FakeChecker kwargs — how a
    test scripts "first attempt blocks until preempted, the resumed
    attempt completes"."""

    def __init__(
        self,
        *,
        unique=1,
        states=1,
        depth=1,
        discoveries=None,
        recorder_factory=None,
        spawn_plan=None,
        spawn_log=None,
    ):
        self.model = FakeModel()
        self.telemetry_opts = None
        self.autosave_opts = None
        self.spill_mode = None
        self.target_state_count = None
        self.run_dir = None
        self._kw = {
            "unique": unique, "states": states, "depth": depth,
            "discoveries": discoveries,
        }
        self._recorder_factory = recorder_factory
        self._spawn_plan = spawn_plan
        self.spawn_log = spawn_log if spawn_log is not None else []

    def telemetry(self, enabled=True, **kw):
        self.telemetry_opts = {"capacity": 256} if enabled else None
        return self

    def spill(self, enabled=True):
        self.spill_mode = bool(enabled)
        return self

    def autosave(self, path, every_secs=60.0, keep=3):
        self.autosave_opts = {
            "dir": str(path), "every_secs": float(every_secs),
            "keep": int(keep),
        }
        return self

    def spawn_tpu(self, resume=None, **kw):
        ordinal = len(self.spawn_log)
        extra = {}
        if self._spawn_plan is not None:
            extra = dict(self._spawn_plan.get(ordinal, {}))
        self.spawn_log.append({"resume": resume, "kw": dict(kw)})
        rec = self._recorder_factory() if self._recorder_factory else None
        fkw = dict(self._kw)
        fkw.update(extra)
        return FakeChecker(
            self.model, recorder=rec, resume=resume, **fkw,
        )
