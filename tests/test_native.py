"""Native consistency-search parity: the C++ search must agree with the
Python search on every history, linearizability and sequential consistency
alike."""

import random

import pytest

from stateright_tpu.native import load
from stateright_tpu.semantics import (
    LinearizabilityTester,
    Register,
    SequentialConsistencyTester,
)
from stateright_tpu.semantics.register import READ, write

pytestmark = pytest.mark.skipif(
    load() is None, reason="native module unavailable (no compiler?)"
)


def python_verdict(tester) -> bool:
    return tester.valid and tester.serialized_history() is not None


def native_verdict(tester) -> bool:
    v = tester._native_verdict()
    assert v is not None, "native path unexpectedly unavailable"
    return v


def random_histories(seed: int, n: int):
    """Generate testers by simulating random register traffic."""
    rng = random.Random(seed)
    for _ in range(n):
        for cls in (LinearizabilityTester, SequentialConsistencyTester):
            t = cls(Register("\0"))
            threads = list(range(rng.randint(1, 3)))
            pending = {}
            register = "\0"  # a "real" execution trace to bias toward valid
            for _ in range(rng.randint(0, 8)):
                th = rng.choice(threads)
                if th in pending:
                    op = pending.pop(th)
                    if op[0] == "write":
                        register = (
                            op[1] if rng.random() < 0.8 else register
                        )
                        t = t.on_return(th, ("write_ok",))
                    else:
                        value = (
                            register
                            if rng.random() < 0.6
                            else rng.choice("ABC\0")
                        )
                        t = t.on_return(th, ("read_ok", value))
                else:
                    if rng.random() < 0.5:
                        op = write(rng.choice("ABC"))
                    else:
                        op = READ
                    pending[th] = op
                    t = t.on_invoke(th, op)
            yield t


def test_native_matches_python_on_random_histories():
    mismatches = []
    for i, tester in enumerate(random_histories(seed=42, n=400)):
        py = python_verdict(tester)
        nat = native_verdict(tester)
        if py != nat:
            mismatches.append((i, tester, py, nat))
    assert not mismatches, mismatches[:3]


def test_native_handles_known_cases():
    # linearizable: W(A) completes, then read returns A
    t = LinearizabilityTester(Register("\0"))
    t = t.on_invoke(0, write("A")).on_return(0, ("write_ok",))
    t = t.on_invoke(1, READ).on_return(1, ("read_ok", "A"))
    assert native_verdict(t) and python_verdict(t)

    # NOT linearizable: read of a value that was never written
    t2 = LinearizabilityTester(Register("\0"))
    t2 = t2.on_invoke(1, READ).on_return(1, ("read_ok", "Z"))
    assert not native_verdict(t2) and not python_verdict(t2)

    # stale read: linearizability rejects, sequential consistency accepts
    def run(cls):
        t = cls(Register("\0"))
        t = t.on_invoke(0, write("A")).on_return(0, ("write_ok",))
        t = t.on_invoke(1, READ).on_return(1, ("read_ok", "\0"))
        return t

    assert not native_verdict(run(LinearizabilityTester))
    assert native_verdict(run(SequentialConsistencyTester))

    # in-flight write may explain a read (never returned)
    t3 = LinearizabilityTester(Register("\0"))
    t3 = t3.on_invoke(0, write("A"))  # in flight forever
    t3 = t3.on_invoke(1, READ).on_return(1, ("read_ok", "A"))
    assert native_verdict(t3) and python_verdict(t3)

    # protocol misuse invalidates permanently
    t4 = LinearizabilityTester(Register("\0"))
    t4 = t4.on_return(0, ("write_ok",))
    assert not t4.valid and t4._native_verdict() is False


def test_is_consistent_uses_native_and_caches():
    t = LinearizabilityTester(Register("\0"))
    t = t.on_invoke(0, write("A")).on_return(0, ("write_ok",))
    assert t.is_consistent() is True
    assert t.is_consistent() is True  # cached path
