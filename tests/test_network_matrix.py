"""Engine-parity sweep over the full network-semantics matrix.

Every compilable combination of {unordered non-duplicating, unordered
duplicating, ordered} × {lossless, lossy} runs the single-copy register
through the host BFS oracle and the device wavefront engine; discovery sets
must match, and counts must match exactly whenever no property forced an
early exit.  This is the consolidated regression net for the compiler's
three network encodings (multiset counts / set / rank-in-slot FIFO) and
both drop semantics."""

import pytest

from stateright_tpu.actor import Network
from stateright_tpu.models.single_copy_register import single_copy_model

NETWORKS = {
    "unordered_nonduplicating": Network.new_unordered_nonduplicating,
    "unordered_duplicating": Network.new_unordered_duplicating,
    "ordered": Network.new_ordered,
}


@pytest.mark.parametrize("lossy", [False, True], ids=["lossless", "lossy"])
@pytest.mark.parametrize("net", sorted(NETWORKS))
def test_single_copy_engine_parity(net, lossy):
    def build():
        m = single_copy_model(2, 1, NETWORKS[net]())
        m.lossy_network(lossy)
        return m

    tm = build().tensor_model()
    assert tm is not None, f"{net} must compile"

    cpu = build().checker().spawn_bfs().join()
    tpu = build().checker().spawn_tpu(sync=True)
    assert set(cpu.discoveries()) == set(tpu.discoveries()), (net, lossy)
    cpu_props = {p.name for p in build().properties()}
    if set(cpu.discoveries()) != cpu_props:
        # no early exit on either engine: exact enumeration parity
        assert cpu.unique_state_count() == tpu.unique_state_count(), (
            net,
            lossy,
            cpu.unique_state_count(),
            tpu.unique_state_count(),
        )
    # discovered violations must be genuine traces; the duplicating network
    # is the one where even a single server violates linearizability (a
    # stale redelivered get_ok returns an old value)
    if net == "unordered_duplicating":
        assert set(tpu.discoveries()) == {"linearizable", "value chosen"}
    if "linearizable" in tpu.discoveries():
        m = build()
        final = tpu.discovery("linearizable").final_state()
        assert not m.property_by_name("linearizable").condition(m, final)
