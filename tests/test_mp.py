"""Process-parallel BFS (``checker/mp.py``): parity with the thread oracle.

The mp checker is the honest multi-core CPU baseline (VERDICT r3 next #3);
its per-state semantics must be indistinguishable from ``spawn_bfs`` —
pinned unique counts, same discoveries, valid reconstructed paths — while
its plumbing (fp-ownership sharding, all-to-all rounds, double-barrier
termination) is the CPU analogue of ``parallel/sharded.py``.
"""

import pytest

from stateright_tpu.checker.mp import spawn_mp_bfs
from stateright_tpu.core import Model, Property
from stateright_tpu.fingerprint import stable_hash

from fixtures import LinearEquation


class TwoPhase3:
    def __new__(cls):
        from stateright_tpu.models.two_phase_commit import TwoPhaseSys

        return TwoPhaseSys(3)


def test_mp_pinned_counts_and_discovery_parity():
    # 2pc @ 3 RMs: 288 unique (reference examples/2pc.rs:128)
    c = spawn_mp_bfs(TwoPhase3(), workers=3)
    assert c.unique_state_count() == 288
    ref = TwoPhase3().checker().spawn_bfs().join()
    assert sorted(c.discoveries()) == sorted(ref.discoveries())
    assert c.state_count() == ref.state_count()


def test_mp_paths_are_valid_and_reach_discovery():
    m = LinearEquation(2, 10, 14)
    c = spawn_mp_bfs(m, workers=2)
    ref = m.checker().spawn_bfs().join()
    # early exit (all properties discovered) lands at ROUND granularity in
    # BSP, so the mp run may overshoot the thread checker's mid-block stop
    # by up to one wavefront — same relaxation the device engines get
    assert c.unique_state_count() >= ref.unique_state_count()
    for name, path in c.discoveries().items():
        prop = m.property_by_name(name)
        # the path re-executes the model by construction (Path
        # reconstruction raises on an invalid trace); its final state must
        # actually witness the property
        assert prop.condition(m, path.final_state())


def test_mp_target_states_stops_early():
    # 0x + 0y = 1 is unsolvable, so only the target can stop the run short
    # of the full 65,536-state space
    c = spawn_mp_bfs(LinearEquation(0, 0, 1), workers=2,
                     target_states=500)
    # BSP rounds overshoot by at most one wavefront, never undershoot
    assert 500 <= c.unique_state_count() < 65_536


class _Exploding(Model):
    def init_states(self):
        return [0]

    def actions(self, state):
        return [1]

    def next_state(self, state, action):
        if state >= 3:
            raise RuntimeError("model bug at depth 3")
        return state + action

    def properties(self):
        return [Property.always("fine", lambda m, s: True)]


def test_mp_worker_error_propagates():
    with pytest.raises(RuntimeError, match="model bug at depth 3"):
        spawn_mp_bfs(_Exploding(), workers=2)


def test_mp_visitor_observes_every_state_thread_bfs_visits():
    """Multi-core CPU + visitor (the reference forces a choice: its
    visitor hook exists only on the thread checkers): workers record
    per-round visit order and the parent replays it, so a StateRecorder
    sees exactly the full explored space."""
    from stateright_tpu.checker.visitor import StateRecorder

    m = TwoPhase3()
    rec_mp = StateRecorder()
    c = m.checker().visitor(rec_mp).spawn_mp_bfs(processes=3).join()
    assert c.unique_state_count() == 288
    rec_ref = StateRecorder()
    TwoPhase3().checker().visitor(rec_ref).spawn_bfs().join()
    assert len(rec_mp.states) == len(rec_ref.states) == 288
    assert set(map(stable_hash, rec_mp.states)) == set(
        map(stable_hash, rec_ref.states)
    )


def test_mp_visitor_paths_are_valid_and_deterministic():
    """Replayed visit paths re-execute the model (Path reconstruction
    raises otherwise) and the visit SEQUENCE — order included — is
    identical run to run for a fixed worker count (StateRecorder keeps
    insertion order, unlike PathRecorder's set)."""
    from stateright_tpu.checker.visitor import StateRecorder

    seqs = []
    for _ in range(2):
        rec = StateRecorder()
        m = TwoPhase3()
        m.checker().visitor(rec).spawn_mp_bfs(processes=2).join()
        seqs.append([stable_hash(s) for s in rec.states])
    assert seqs[0] == seqs[1]  # exact order, not just the same multiset
    assert len(seqs[0]) == 288


def test_mp_visitor_composes_with_symmetry():
    """Visitor + symmetry + multi-core together (impossible in the
    reference, where symmetry is DFS-only and visitors thread-only):
    the recorder sees one ORIGINAL state per symmetry class."""
    from stateright_tpu.checker.visitor import StateRecorder
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    rec = StateRecorder()
    c = (
        TwoPhaseSys(5)
        .checker()
        .symmetry()
        .visitor(rec)
        .spawn_mp_bfs(processes=2)
        .join()
    )
    assert c.unique_state_count() == TPC5_SYM_BY_WORKERS[2]
    assert len(rec.states) == TPC5_SYM_BY_WORKERS[2]


# Reduced counts are visit-order-dependent (representatives are not
# class-invariant), but the BSP schedule is deterministic for a fixed
# worker count, so counts pin EXACTLY per n.  n=1 is FIFO BFS order and
# equals the host FIFO oracle — the engine-independent parity signal the
# device engines are pinned against too.
TPC5_SYM_BY_WORKERS = {1: 508, 2: 723, 4: 665}


def test_mp_symmetry_reduces_and_matches_fifo_oracle():
    """Multi-core CPU + symmetry (reference: DFS-only, ``dfs.rs:260-269``;
    the round-4 fence ``mp.py:34-36`` is gone): dedup on the class key
    ``stable_hash(representative(state))`` routed to class owners."""
    import sys as _sys
    from pathlib import Path as _P

    _sys.path.insert(0, str(_P(__file__).parent))
    from test_tensor_models import host_fifo_sym_oracle

    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    assert host_fifo_sym_oracle(TwoPhaseSys(5)) == TPC5_SYM_BY_WORKERS[1]
    for n, expected in TPC5_SYM_BY_WORKERS.items():
        c = TwoPhaseSys(5).checker().symmetry().spawn_mp_bfs(processes=n)
        assert c.unique_state_count() == expected, (n, c.unique_state_count())
        assert sorted(c.discoveries()) == [
            "abort agreement", "commit agreement",
        ]


def test_mp_symmetry_paths_are_original_state_traces():
    """The search continues with ORIGINAL states (the ``dfs.rs:394-483``
    regression subtlety): parent pointers chain real fingerprints, so
    discovery paths re-execute without a class-matching walk and their
    final states witness the property."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    m = TwoPhaseSys(5)
    c = m.checker().symmetry().spawn_mp_bfs(processes=2)
    for name, path in c.discoveries().items():
        prop = m.property_by_name(name)
        assert prop.condition(m, path.final_state())
        assert len(path.actions()) >= 1
