"""Ping-pong actor fixture (reference ``src/actor/actor_test_util.rs``).

Two actors bounce a counter; history optionally tracks (#in, #out) message
counts; six properties span all three expectations.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from stateright_tpu import Expectation
from stateright_tpu.actor import Actor, ActorModel, Id, Out


@dataclass
class PingPongActor(Actor):
    serve_to: Optional[Id] = None

    def on_start(self, id, out):
        if self.serve_to is not None:
            out.send(self.serve_to, ("Ping", 0))
        return 0

    def on_msg(self, id, state, src, msg, out):
        kind, value = msg
        if kind == "Pong" and state == value:
            out.send(src, ("Ping", value + 1))
            return state + 1
        if kind == "Ping" and state == value:
            out.send(src, ("Pong", value))
            return state + 1
        return None


@dataclass
class PingPongCfg:
    maintains_history: bool = False
    max_nat: int = 5


def ping_pong_model(cfg: PingPongCfg) -> ActorModel:
    def record_in(c, history, env):
        if c.maintains_history:
            i, o = history
            return (i + 1, o)
        return None

    def record_out(c, history, env):
        if c.maintains_history:
            i, o = history
            return (i, o + 1)
        return None

    return (
        ActorModel(cfg, (0, 0))
        .actor(PingPongActor(serve_to=Id(1)))
        .actor(PingPongActor())
        .record_msg_in(record_in)
        .record_msg_out(record_out)
        .within_boundary_(
            lambda c, state: all(s <= c.max_nat for s in state.actor_states)
        )
        .property(
            Expectation.ALWAYS,
            "delta within 1",
            lambda m, s: max(s.actor_states) - min(s.actor_states) <= 1,
        )
        .property(
            Expectation.SOMETIMES,
            "can reach max",
            lambda m, s: any(c == m.cfg.max_nat for c in s.actor_states),
        )
        .property(
            Expectation.EVENTUALLY,
            "must reach max",
            lambda m, s: any(c == m.cfg.max_nat for c in s.actor_states),
        )
        .property(
            Expectation.EVENTUALLY,
            "must exceed max",  # falsifiable due to the boundary
            lambda m, s: any(c == m.cfg.max_nat + 1 for c in s.actor_states),
        )
        .property(
            Expectation.ALWAYS,
            "#in <= #out",
            lambda m, s: s.history[0] <= s.history[1],
        )
        .property(
            Expectation.EVENTUALLY,
            "#out <= #in + 1",
            lambda m, s: s.history[1] <= s.history[0] + 1,
        )
    )
