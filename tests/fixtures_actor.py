"""Actor fixtures for the compiler/engine tests.

 - ping-pong (reference ``src/actor/actor_test_util.rs``): two actors
   bounce a counter; history optionally tracks (#in, #out) message
   counts; six properties span all three expectations.
 - actor-form two-phase commit (:func:`actor_2pc_model`): the
   Gray/Lamport 2pc recast as real actors over an unordered DUPLICATING
   network — the persistent envelope set mirrors the TLA+ model's
   monotonic message set, which makes it the duplicating-semantics
   exemplar for the per-channel network-encoding parity tests
   (``tests/test_per_channel.py``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from stateright_tpu import Expectation
from stateright_tpu.actor import Actor, ActorModel, Id, Network, Out
from stateright_tpu.parallel.tensor_model import TensorBackedModel


@dataclass
class PingPongActor(Actor):
    serve_to: Optional[Id] = None

    def on_start(self, id, out):
        if self.serve_to is not None:
            out.send(self.serve_to, ("Ping", 0))
        return 0

    def on_msg(self, id, state, src, msg, out):
        kind, value = msg
        if kind == "Pong" and state == value:
            out.send(src, ("Ping", value + 1))
            return state + 1
        if kind == "Ping" and state == value:
            out.send(src, ("Pong", value))
            return state + 1
        return None


@dataclass
class PingPongCfg:
    maintains_history: bool = False
    max_nat: int = 5


def ping_pong_model(cfg: PingPongCfg) -> ActorModel:
    def record_in(c, history, env):
        if c.maintains_history:
            i, o = history
            return (i + 1, o)
        return None

    def record_out(c, history, env):
        if c.maintains_history:
            i, o = history
            return (i, o + 1)
        return None

    return (
        ActorModel(cfg, (0, 0))
        .actor(PingPongActor(serve_to=Id(1)))
        .actor(PingPongActor())
        .record_msg_in(record_in)
        .record_msg_out(record_out)
        .within_boundary_(
            lambda c, state: all(s <= c.max_nat for s in state.actor_states)
        )
        .property(
            Expectation.ALWAYS,
            "delta within 1",
            lambda m, s: max(s.actor_states) - min(s.actor_states) <= 1,
        )
        .property(
            Expectation.SOMETIMES,
            "can reach max",
            lambda m, s: any(c == m.cfg.max_nat for c in s.actor_states),
        )
        .property(
            Expectation.EVENTUALLY,
            "must reach max",
            lambda m, s: any(c == m.cfg.max_nat for c in s.actor_states),
        )
        .property(
            Expectation.EVENTUALLY,
            "must exceed max",  # falsifiable due to the boundary
            lambda m, s: any(c == m.cfg.max_nat + 1 for c in s.actor_states),
        )
        .property(
            Expectation.ALWAYS,
            "#in <= #out",
            lambda m, s: s.history[0] <= s.history[1],
        )
        .property(
            Expectation.EVENTUALLY,
            "#out <= #in + 1",
            lambda m, s: s.history[1] <= s.history[0] + 1,
        )
    )


# -- actor-form two-phase commit ---------------------------------------------

RM_WORKING, RM_PREPARED, RM_COMMITTED, RM_ABORTED = (
    "working", "prepared", "committed", "aborted"
)


@dataclass
class TwoPhaseRmActor(Actor):
    """One resource manager.  Its spontaneous choices (prepare / choose
    abort) arrive as self-addressed seed envelopes that the duplicating
    network keeps deliverable forever, TLA-style."""

    tm: Id

    def on_start(self, id, out):
        return RM_WORKING

    def on_msg(self, id, state, src, msg, out):
        kind = msg[0]
        if kind == "do_prepare" and state == RM_WORKING:
            out.send(self.tm, ("prepared", int(id)))
            return RM_PREPARED
        if kind == "do_abort" and state == RM_WORKING:
            return RM_ABORTED
        if kind == "commit" and state == RM_PREPARED:
            return RM_COMMITTED
        if kind == "abort" and state in (RM_WORKING, RM_PREPARED):
            return RM_ABORTED
        return None


@dataclass
class TwoPhaseTmActor(Actor):
    """The transaction manager: collects ``prepared`` votes, broadcasts
    commit on a full quorum; a persistent self-addressed ``do_abort``
    seed lets it abort at any point while undecided."""

    rm_ids: list

    def on_start(self, id, out):
        return ("init", frozenset())

    def on_msg(self, id, state, src, msg, out):
        phase, prepared = state
        kind = msg[0]
        if kind == "prepared" and phase == "init":
            prepared = prepared | {int(msg[1])}
            if len(prepared) == len(self.rm_ids):
                for r in self.rm_ids:
                    out.send(r, ("commit",))
                return ("committed", prepared)
            return (phase, prepared)
        if kind == "do_abort" and phase == "init":
            for r in self.rm_ids:
                out.send(r, ("abort",))
            return ("aborted", prepared)
        return None


class Actor2pcModel(TensorBackedModel, ActorModel):
    """Tensor-backed actor 2pc (mechanically compiled twin)."""

    def tensor_model(self):
        from stateright_tpu.parallel.actor_compiler import (
            CompileError,
            compile_actor_model,
        )

        try:
            return compile_actor_model(self)
        except (CompileError, ValueError):
            return None


def actor_2pc_model(rm_count: int = 3, lossy: bool = False,
                    network: Optional[Network] = None) -> ActorModel:
    """TM at index 0, RMs at 1..rm_count; duplicating network by default
    (the message-set reading of the TLA+ model)."""
    from stateright_tpu.actor.device_props import (
        exists_actor,
        forall_actor_pairs,
    )

    if network is None:
        network = Network.new_unordered_duplicating()
    rm_ids = [Id(i + 1) for i in range(rm_count)]
    m = Actor2pcModel(cfg=None, init_history=None)
    m.actor(TwoPhaseTmActor(rm_ids=rm_ids))
    for _ in rm_ids:
        m.actor(TwoPhaseRmActor(tm=Id(0)))
    # self-addressed choice seeds: spontaneous TLA actions as deliveries
    for r in rm_ids:
        network = network.send(__envelope(r, r, ("do_prepare",)))
        network = network.send(__envelope(r, r, ("do_abort",)))
    network = network.send(__envelope(Id(0), Id(0), ("do_abort",)))
    m.init_network_(network)
    m.lossy_network(lossy)

    def _is_rm(s):
        return isinstance(s, str)

    m.property(
        Expectation.ALWAYS,
        "consistent",
        forall_actor_pairs(
            lambda i, si, j, sj: not (
                _is_rm(si) and _is_rm(sj)
                and {si, sj} == {RM_COMMITTED, RM_ABORTED}
            )
        ),
    )
    m.property(
        Expectation.SOMETIMES,
        "commit reached",
        exists_actor(lambda i, s: s == RM_COMMITTED),
    )
    m.property(
        Expectation.SOMETIMES,
        "abort reached",
        exists_actor(lambda i, s: s == RM_ABORTED),
    )
    return m


def __envelope(src, dst, msg):
    from stateright_tpu.actor.network import Envelope

    return Envelope(src=src, dst=dst, msg=msg)
