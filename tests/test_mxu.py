"""MXU recast round (ops/mxu.py; docs/roofline.md "Executing the
hot-spot list"): expand-scatter coalescing, slim queue traffic, and the
BLEST one-hot membership probe.

The contracts pinned here, in the family's strongest form:

 - every knob OFF leaves the step jaxpr bit-identical to a pre-MXU
   engine and the engine cache unkeyed (both engines);
 - every knob ON keeps counts, the visited table, and discovery traces
   bit-identical (2pc-3 strongest form; compositions with symmetry /
   POR / prededup / spill / kill+resume in the tiered crawls);
 - the coalesced step kernels compute bit-identical successors over the
   WHOLE per-channel paxos-1 space (and the hand twin's paxos-1 space);
 - the flagged cost ledger proves the bytes actually dropped: paxos
   expand+queue charged bytes fall >=30% and dedup-insert carries a
   genuine dot-class op with raised arithmetic intensity;
 - the roofline device table judges dot-dominated stages against the
   MXU ridge and everything else against the VPU ridge;
 - JX400 findings name the landed ``--mxu`` escape hatch pre-flag and
   go silent post-flag (the JX305 pattern);
 - ``regress.py --mxu`` validates present legs and never trips on
   absent/stale ones (injectable artifacts).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from helpers import requires_sharded_collectives

from stateright_tpu.models.paxos import paxos_model
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.ops.mxu import MxuConfig, coalesced_step_fn, resolve_mxu

TPC3_UNIQUE = 288


def _spawn(m, mxu=None, **kw):
    b = m.checker()
    if mxu is not None:
        b = b.mxu(**mxu) if isinstance(mxu, dict) else b.mxu(mxu)
    kw.setdefault("sync", True)
    kw.setdefault("capacity", 1 << 12)
    kw.setdefault("batch", 64)
    return b.spawn_tpu(**kw)


def _counts(c):
    return (c.state_count(), c.unique_state_count(),
            sorted(c.discoveries()))


# -- config resolution --------------------------------------------------------


def test_resolve_mxu_builder_and_env(monkeypatch):
    monkeypatch.delenv("STATERIGHT_TPU_MXU", raising=False)
    assert resolve_mxu(None) is None
    monkeypatch.setenv("STATERIGHT_TPU_MXU", "1")
    assert resolve_mxu(None) == MxuConfig(True, True, True)
    # explicit builder off beats the env knob (resolve_flag's rule)
    assert resolve_mxu(
        {"coalesce": False, "slim_queue": False, "probe": False}
    ) is None
    # component subset survives resolution
    cfg = resolve_mxu({"coalesce": False, "slim_queue": True, "probe": True})
    assert cfg == MxuConfig(False, True, True)
    assert cfg.key()[0] == "mxu"


def test_builder_mxu_off_overrides_env(monkeypatch):
    monkeypatch.setenv("STATERIGHT_TPU_MXU", "1")
    b = TwoPhaseSys(3).checker().mxu(False)
    assert resolve_mxu(b.mxu_opts) is None


# -- jaxpr + engine-cache-key pins (wavefront) --------------------------------


def test_mxu_off_leaves_run_jaxpr_bit_identical():
    """The prededup contract: OFF must be the pre-flag engine program;
    each component ON must actually change it, and the probe must put a
    real dot_general in the step."""

    def run_jaxpr(opts):
        m = TwoPhaseSys(3)
        b = m.checker()
        if opts is not None:
            b = b.mxu(**opts) if opts else b.mxu(False)
        c = b.spawn_tpu(sync=True, capacity=1 << 12, batch=64)
        init_fn, run_fn = c._engine(c._cap, c._qcap, c._batch, c._cand)
        carry, _ = init_fn()
        return str(jax.make_jaxpr(lambda cr: run_fn(cr))(tuple(carry)))

    baseline = run_jaxpr(None)
    assert baseline == run_jaxpr({})  # .mxu(False): explicit off
    probe = run_jaxpr(
        {"coalesce": False, "slim_queue": False, "probe": True}
    )
    assert probe != baseline and "dot_general" in probe
    slim = run_jaxpr(
        {"coalesce": False, "slim_queue": True, "probe": False}
    )
    assert slim != baseline and slim != probe


def test_mxu_engine_cache_key_pin():
    """OFF leaves the cache key exactly the pre-MXU tuple (unkeyed by
    the feature's absence); ON appends the EFFECTIVE component tuple —
    a component that falls back to an identical program (a twin without
    a coalesced kernel) is keyed off, so equivalent configs share one
    engine compile."""
    off = _spawn(TwoPhaseSys(3))
    on = _spawn(TwoPhaseSys(3), mxu=True)
    k_off = off._engine_key(off._cap, off._qcap, off._batch, off._cand)
    k_on = on._engine_key(on._cap, on._qcap, on._batch, on._cand)
    assert not any(
        isinstance(e, tuple) and e and e[0] == "mxu" for e in k_off
    )
    assert k_on[:-1] == k_off
    # the 2pc hand twin gained a real coalesced kernel (the FieldWriter
    # round): the component keys ON and the config is its own entry
    assert k_on[-1] == ("mxu", True, True, True)
    no_co = _spawn(TwoPhaseSys(3), mxu={"coalesce": False})
    k_no_co = no_co._engine_key(
        no_co._cap, no_co._qcap, no_co._batch, no_co._cand
    )
    assert k_no_co[-1] == ("mxu", False, True, True)
    assert k_on != k_no_co
    # effective_mxu still downgrades for twins WITHOUT a coalesced
    # kernel (ops/mxu.py fallback pin lives in
    # test_coalesced_step_fn_fallback_without_method)
    pax = paxos_model(1, 3).checker().mxu().spawn_tpu(
        sync=True, capacity=1 << 15, batch=256
    )
    k_pax = pax._engine_key(pax._cap, pax._qcap, pax._batch, pax._cand)
    assert k_pax[-1] == ("mxu", True, True, True)


# -- bit-identical engine runs (strongest form) -------------------------------


def test_mxu_is_bit_identical_on_2pc3():
    """With capacities pre-sized (no growth), the visited TABLE itself —
    every slot's fingerprint and parent payload — must be bit-identical
    with the flag on and off, along with every count and discovery."""
    a = _spawn(TwoPhaseSys(3))
    b = _spawn(TwoPhaseSys(3), mxu=True)
    assert a.unique_state_count() == b.unique_state_count() == TPC3_UNIQUE
    assert a.state_count() == b.state_count()
    assert a.max_depth() == b.max_depth()
    ta, tb = a._table_np(), b._table_np()
    assert np.array_equal(ta[0], tb[0])
    assert np.array_equal(ta[1], tb[1])
    da, db = a.discoveries(), b.discoveries()
    assert sorted(da) == sorted(db)
    for name in da:
        assert [str(s) for s in da[name].states()] == [
            str(s) for s in db[name].states()
        ]


def test_mxu_parity_per_channel_paxos1_with_por_and_prededup():
    """The composition the round exists for: per-channel paxos-1 under
    --mxu must reproduce the pinned full space AND the pinned reduced
    space under por(), with prededup stacked on top."""
    def pc():
        m = paxos_model(1, 3)
        m.per_channel_()
        return m

    full = _counts(_spawn(pc(), capacity=1 << 15, batch=256))
    full_m = _counts(_spawn(pc(), mxu=True, capacity=1 << 15, batch=256))
    assert full == full_m
    assert (full[0], full[1]) == (482, 265)
    por = _counts(
        pc().checker().por().mxu().prededup().spawn_tpu(
            sync=True, capacity=1 << 15, batch=256
        )
    )
    assert (por[0], por[1]) == (437, 250)
    assert por[2] == full[2]


def test_slim_queue_exotic_cand_budgets():
    """The chunk width must DIVIDE the candidate stack or the final
    slice start would clamp and misalign the queue writes.  A
    non-multiple ``cand`` statically falls back to the plain window; a
    ``cand`` SMALLER than batch chunks at the cand width — counts exact
    either way."""
    ref = _counts(_spawn(TwoPhaseSys(3)))
    # cand=96 < batch=128: qchunk=96 divides, slim stays armed
    small = _counts(
        _spawn(TwoPhaseSys(3), mxu=True, capacity=1 << 12, batch=128,
               cand=96, queue_capacity=1 << 12)
    )
    assert small == ref
    # cand=100 not a multiple of qchunk=64: static plain-window fallback
    odd = _counts(
        _spawn(TwoPhaseSys(3), mxu=True, capacity=1 << 12, batch=64,
               cand=100, queue_capacity=1 << 12)
    )
    assert odd == ref


def test_fieldwriter_get_after_or_matches_eager():
    """get() after or_field must see the pending OR in BOTH modes (the
    eager mode reads the running block; the coalesced mode must not
    return the stale base) — and the assembled blocks stay equal."""
    from stateright_tpu.parallel.tensor_model import FieldWriter

    t = paxos_model(1, 3).tensor_model()
    pk = t.pk
    name = next(n for n, (_w, _o, bits) in pk.layout.items() if bits == 1)
    base = jnp.zeros((2, 1, pk.width), jnp.uint64)
    flag = jnp.asarray([[True], [False]])
    eager = FieldWriter(pk, base, coalesce=False).or_field(name, flag)
    co = FieldWriter(pk, base, coalesce=True).or_field(name, flag)
    assert np.array_equal(np.asarray(eager.get(name)),
                          np.asarray(co.get(name)))
    assert np.array_equal(np.asarray(eager.done()), np.asarray(co.done()))
    # a later set SUPERSEDES the OR (done applies ops in call order;
    # get must agree in both modes) — and an OR after a set stacks
    for ops in (("or", "set"), ("set", "or"), ("or", "set", "or")):
        fe = FieldWriter(pk, base, coalesce=False)
        fc = FieldWriter(pk, base, coalesce=True)
        for op in ops:
            for fw in (fe, fc):
                if op == "or":
                    fw.or_field(name, flag)
                else:
                    fw.set(name, jnp.zeros((2, 1), jnp.uint64))
        assert np.array_equal(np.asarray(fe.get(name)),
                              np.asarray(fc.get(name))), ops
        assert np.array_equal(np.asarray(fe.done()),
                              np.asarray(fc.done())), ops


def test_slim_queue_fallback_keeps_queue_findings():
    """When the chunk width does not divide the candidate stack the
    slim path statically falls back — the queue JX400 findings must
    then keep firing (a fallen-back recast never silences its advice,
    the effective_mxu discipline)."""
    from stateright_tpu.analysis.costmodel import wavefront_costs

    t = TwoPhaseSys(3).tensor_model()
    on = wavefront_costs(
        t, 1 << 12, 1 << 11, 64, 100, reconcile=False, mxu=MxuConfig()
    )
    assert not any(
        c.get("recast_landed")
        for c in on.candidates if c["stage"] == "queue"
    )
    assert [
        f for f in on.findings
        if f.rule_id == "JX400" and "stage:queue" in f.location
    ], "fallen-back slim queue must keep its JX400 advice"
    # while a dividing budget on the same twin slims the windows below
    # the candidate threshold entirely — no queue advice left to give
    on2 = wavefront_costs(
        t, 1 << 12, 1 << 11, 64, 128, reconcile=False, mxu=MxuConfig()
    )
    assert not [
        f for f in on2.findings
        if f.rule_id == "JX400" and "stage:queue" in f.location
    ]


# -- coalesced-step whole-space successor parity ------------------------------


def _crawl_step_parity(tensor, batch=64, max_unique=4000):
    """Drive the whole reachable space with the PLAIN kernel as oracle,
    asserting per batch that the coalesced kernel produces bit-identical
    (valid, successor) pairs.  Returns the unique-row count."""
    step_a = jax.jit(tensor.step_rows)
    step_b = jax.jit(tensor.step_rows_coalesced)
    init = np.asarray(tensor.init_rows(), np.uint64)
    seen = {tuple(int(w) for w in r) for r in init}
    frontier = list(init)
    while frontier:
        chunk, frontier = frontier[:batch], frontier[batch:]
        pad = batch - len(chunk)
        rows = np.stack(chunk + [chunk[0]] * pad).astype(np.uint64)
        s_a, v_a = step_a(jnp.asarray(rows))
        s_b, v_b = step_b(jnp.asarray(rows))
        s_a, v_a = np.asarray(s_a), np.asarray(v_a)
        s_b, v_b = np.asarray(s_b), np.asarray(v_b)
        assert np.array_equal(v_a, v_b)
        # invalid lanes may hold garbage in BOTH kernels; compare masked
        assert np.array_equal(
            np.where(v_a[..., None], s_a, 0),
            np.where(v_b[..., None], s_b, 0),
        )
        n_real = batch - pad
        for b_i in range(n_real):
            for a_i in range(v_a.shape[1]):
                if not v_a[b_i, a_i]:
                    continue
                key = tuple(int(w) for w in s_a[b_i, a_i])
                if key not in seen:
                    seen.add(key)
                    frontier.append(s_a[b_i, a_i])
        assert len(seen) <= max_unique, "space exceeded the test bound"
    return len(seen)


def test_coalesced_whole_space_parity_per_channel_paxos1():
    m = paxos_model(1, 3)
    m.per_channel_()
    t = m._tensor_cached()
    assert _crawl_step_parity(t) == 265


def test_coalesced_whole_space_parity_hand_twin_paxos1():
    t = paxos_model(1, 3).tensor_model()
    assert _crawl_step_parity(t) == 265


def test_coalesced_step_fn_fallback_without_method():
    """Twins without a coalesced kernel silently keep the plain step —
    the flag then still buys the queue/probe recasts."""
    class Bare:
        def step_rows(self, rows):
            return rows

    t = Bare()
    assert coalesced_step_fn(t, MxuConfig()) == t.step_rows
    assert coalesced_step_fn(t, None) == t.step_rows
    t2 = paxos_model(1, 3).tensor_model()
    assert coalesced_step_fn(t2, MxuConfig()) == t2.step_rows_coalesced
    assert coalesced_step_fn(
        t2, MxuConfig(coalesce=False)
    ) == t2.step_rows


def test_multiset_compiled_twin_coalesce_is_real():
    """The slot-multiset compiled twin's coalesce is REAL since its
    history/timer/poison write-backs were threaded through the
    FieldWriter seam — has_coalesced_step advertises it, the engines
    trace the coalesced kernel, and its successors stay bit-identical
    over the whole actor-2pc space."""
    from fixtures_actor import actor_2pc_model

    from stateright_tpu.ops.mxu import has_coalesced_step

    ms = actor_2pc_model(2)._tensor_cached()
    assert has_coalesced_step(ms)
    assert coalesced_step_fn(ms, MxuConfig()) == ms.step_rows_coalesced
    pc = actor_2pc_model(2)
    pc.per_channel_()
    tpc = pc._tensor_cached()
    assert has_coalesced_step(tpc)
    assert coalesced_step_fn(tpc, MxuConfig()) == tpc.step_rows_coalesced
    assert _crawl_step_parity(ms, max_unique=6000) == _crawl_step_parity(
        tpc, max_unique=6000
    )


def test_coalesced_whole_space_parity_hand_twin_2pc3():
    """The 2pc hand twin's new coalesced kernel: bit-identical
    successors over the whole 2pc-3 space (the per-action FieldWriter
    assembly must preserve every mask and write)."""
    t = TwoPhaseSys(3).tensor_model()
    assert _crawl_step_parity(t) == TPC3_UNIQUE


# -- cost-model payoff (the regress --mxu bars, statically) -------------------


def test_costmodel_mxu_reduction_and_dot_class():
    """The flagged ledger must prove the bytes dropped: paxos-2 (hand
    twin, same kernel family as the bench paxos-3) expand+queue charged
    bytes fall >=30%, and dedup-insert carries a dot-class op with
    raised arithmetic intensity.  Also pins that the twin-level cost
    cache keys flagged and unflagged ledgers separately."""
    from stateright_tpu.analysis.costmodel import wavefront_costs

    t = paxos_model(2, 3).tensor_model()
    off = wavefront_costs(t, 1 << 16, 1 << 15, 512, reconcile=False)
    on = wavefront_costs(
        t, 1 << 16, 1 << 15, 512, reconcile=False, mxu=MxuConfig()
    )
    assert off is not None and on is not None and off is not on
    eq_off = (off.stages["expand"].bytes_total
              + off.stages["queue"].bytes_total)
    eq_on = (on.stages["expand"].bytes_total
             + on.stages["queue"].bytes_total)
    assert 1 - eq_on / eq_off >= 0.30, (eq_off, eq_on)
    # the probe landed a genuine dot op on the insert stage
    assert "dot" not in off.stages["dedup-insert"].classes
    dot = on.stages["dedup-insert"].classes.get("dot")
    assert dot and dot["flops"] > 0
    assert (on.stages["dedup-insert"].intensity
            > off.stages["dedup-insert"].intensity)
    # expand scatters collapse under coalescing
    assert "scatter" in off.stages["expand"].classes
    assert "scatter" not in on.stages["expand"].classes


def test_jx400_escape_hatch_pre_flag_and_silent_post():
    """The JX305 pattern: pre-flag, the dedup-gather JX400 finding
    names the --mxu hatch; with the probe armed, the finding goes
    silent (the recast is live)."""
    from stateright_tpu.analysis.costmodel import wavefront_costs

    t = TwoPhaseSys(5).tensor_model()
    off = wavefront_costs(t, 1 << 16, 1 << 15, 512, reconcile=False)
    dedup_off = [
        f for f in off.findings
        if f.rule_id == "JX400" and "dedup-insert" in f.location
    ]
    assert dedup_off, "pre-flag JX400 dedup finding must fire"
    assert any("--mxu" in f.message for f in dedup_off)
    on = wavefront_costs(
        t, 1 << 16, 1 << 15, 512, reconcile=False, mxu=MxuConfig()
    )
    assert not [
        f for f in on.findings
        if f.rule_id == "JX400" and "dedup-insert" in f.location
        and "gather" in f.message
    ], "post-flag the dedup gather JX400 finding must go silent"
    # the insert-stage SCATTER (the table write-back) is NOT retired by
    # the probe — its finding must keep firing (honest ranking)
    assert [
        f for f in on.findings
        if f.rule_id == "JX400" and "dedup-insert" in f.location
        and "scatter" in f.message
    ], "the un-recast dedup scatter finding must stay live"
    # the candidate row itself survives, marked landed (the ranking is
    # still the hot-spot table; only the advice retires)
    assert any(
        c.get("recast_landed")
        for c in on.candidates
        if c["stage"] == "dedup-insert" and c["op_class"] == "gather"
    )
    # 2pc's hand twin gained a real coalesced kernel (the FieldWriter
    # round): its expand scatters vanish from the flagged trace, exactly
    # like the paxos hand twin's
    assert "scatter" in off.stages["expand"].classes
    assert "scatter" not in on.stages["expand"].classes


# -- roofline two-peak verdicts -----------------------------------------------


def test_roofline_judges_dot_stages_against_mxu_ridge(monkeypatch):
    """The satellite pin: one shared peak hands a recast stage the
    wrong verdict.  A synthetic dot-heavy stage whose intensity sits
    between the VPU and MXU ridges must judge memory-bound (MXU ridge),
    while an elementwise stage at the same intensity judges
    compute-bound (VPU ridge)."""
    from stateright_tpu.telemetry.roofline import (
        classify_stages,
        device_spec,
    )

    # peak 1e14 MXU, 1e12 VPU, 1e11 B/s: mxu ridge 1000, vpu ridge 10
    monkeypatch.setenv(
        "STATERIGHT_TPU_DEVICE_SPEC", "1e14:1e11:synth:1e12"
    )
    spec = device_spec()
    assert spec["mxu_peak"] == 1e14 and spec["vpu_peak"] == 1e12
    assert spec["mxu_ridge"] == 1000.0 and spec["vpu_ridge"] == 10.0
    static = {"stages": {
        "recast": {
            "flops": 100_000, "bytes_read": 500, "bytes_written": 500,
            "intensity": 100.0,
            "classes": {"dot": {"flops": 90_000, "bytes": 600,
                                "count": 1}},
        },
        "plain": {
            "flops": 100_000, "bytes_read": 500, "bytes_written": 500,
            "intensity": 100.0,
            "classes": {"elementwise": {"flops": 100_000, "bytes": 1000,
                                        "count": 4}},
        },
    }}
    v = classify_stages(static, spec)
    assert v["recast"]["ridge_kind"] == "mxu"
    assert v["recast"]["verdict"] == "memory-bound"
    assert v["plain"]["ridge_kind"] == "vpu"
    assert v["plain"]["verdict"] == "compute-bound"


def test_roofline_env_spec_back_compat(monkeypatch):
    """The pre-split 3-field env format still parses; VPU defaults to
    PEAK/64 and the pre-split ``peak_flops``/``ridge`` aliases hold."""
    from stateright_tpu.telemetry.roofline import device_spec

    monkeypatch.setenv("STATERIGHT_TPU_DEVICE_SPEC", "6.4e13:1e11:old")
    spec = device_spec()
    assert spec["peak_flops"] == spec["mxu_peak"] == 6.4e13
    assert spec["vpu_peak"] == 1e12
    assert spec["ridge"] == spec["mxu_ridge"]


def test_roofline_device_table_carries_both_peaks():
    from stateright_tpu.telemetry.roofline import DEVICE_SPECS

    for _needle, _name, mxu_peak, vpu_peak, bw in DEVICE_SPECS:
        assert mxu_peak > vpu_peak > 0 and bw > 0


# -- regress --mxu gate (injectable artifacts) --------------------------------


def _roof(expand_b, queue_b, dedup=None):
    stages = {
        "expand": {"flops": 1, "bytes_read": expand_b, "bytes_written": 0},
        "queue": {"flops": 1, "bytes_read": queue_b, "bytes_written": 0},
    }
    if dedup is not None:
        stages["dedup-insert"] = dedup
    return {"v": 1, "stages": stages}


def _good_mxu_run():
    return {
        "tpu_paxos3_unique": 100, "tpu_paxos3_mxu_unique": 100,
        "tpu_2pc7_unique": 50, "tpu_2pc7_mxu_unique": 50,
        "tpu_paxos3_roofline": _roof(1000, 200),
        "tpu_paxos3_mxu_roofline": _roof(600, 20),
        "tpu_2pc7_roofline": _roof(10, 10, {
            "flops": 10, "bytes_read": 100, "bytes_written": 0,
            "intensity": 0.1, "classes": {},
        }),
        "tpu_2pc7_mxu_roofline": _roof(10, 10, {
            "flops": 50, "bytes_read": 100, "bytes_written": 0,
            "intensity": 0.5,
            "classes": {"dot": {"flops": 40, "bytes": 10, "count": 1}},
        }),
    }


def test_regress_mxu_gate_absence_never_trips():
    import regress

    v = regress.mxu_verdict({}, {})
    assert v["ok"] and not v["present"]
    # a stale/pre-mxu BASELINE never trips a run either way
    v = regress.mxu_verdict(_good_mxu_run(), {})
    assert v["ok"] and v["present"] and not v["baseline_present"]


def test_regress_mxu_gate_validates_present_legs():
    import regress

    good = _good_mxu_run()
    v = regress.mxu_verdict(good, {})
    assert v["ok"], v
    assert v["paxos3_expand_queue_bytes"]["drop"] >= 0.30

    crashed = dict(good, tpu_paxos3_mxu_error="RuntimeError: boom")
    assert not regress.mxu_verdict(crashed, {})["ok"]

    drifted = dict(good, tpu_paxos3_mxu_unique=99)
    v = regress.mxu_verdict(drifted, {})
    assert not v["ok"] and any(
        "must not change counts" in p for p in v["problems"]
    )

    shallow = dict(good, tpu_paxos3_mxu_roofline=_roof(1100, 180))
    v = regress.mxu_verdict(shallow, {})
    assert not v["ok"] and any("30%" in p for p in v["problems"])

    no_dot = dict(good)
    no_dot["tpu_2pc7_mxu_roofline"] = good["tpu_2pc7_roofline"]
    v = regress.mxu_verdict(no_dot, {})
    assert not v["ok"] and any("dot-class" in p for p in v["problems"])

    no_base = dict(good)
    del no_base["tpu_paxos3_roofline"]
    v = regress.mxu_verdict(no_base, {})
    assert not v["ok"] and any("unflagged" in p for p in v["problems"])

    # injected artifacts are arbitrary JSON: a non-dict roofline block
    # (e.g. a stringified crash) must produce a verdict, not a traceback
    for key in ("tpu_2pc7_mxu_roofline", "tpu_paxos3_mxu_roofline"):
        trash = dict(good, **{key: "XlaRuntimeError: boom"})
        v = regress.mxu_verdict(trash, {})
        assert not v["ok"], key
    nested = dict(good)
    nested["tpu_2pc7_mxu_roofline"] = {"v": 1, "stages": "corrupt"}
    assert not regress.mxu_verdict(nested, {})["ok"]


def test_regress_main_mxu_flag(tmp_path, capsys):
    """End-to-end through regress.main: a fresh run with good legs
    passes; one with a crashed leg exits 1; a run WITHOUT legs passes
    (flag-gated)."""
    import json

    import regress

    base = {}

    def run_file(extra):
        doc = {"fresh": True, **extra}
        p = tmp_path / f"run{len(list(tmp_path.iterdir()))}.json"
        p.write_text(json.dumps(doc))
        return str(p)

    bp = tmp_path / "base.json"
    bp.write_text(json.dumps(base))
    args = ["--baseline=" + str(bp), "--mxu"]
    assert regress.main([run_file(_good_mxu_run())] + args) == 0
    assert regress.main([run_file({})] + args) == 0
    rc = regress.main(
        [run_file({"tpu_2pc7_mxu_error": "boom"})] + args
    )
    assert rc == 1
    capsys.readouterr()


# -- heavier compositions (tiered) --------------------------------------------


@pytest.mark.medium
def test_mxu_parity_under_growth_symmetry_and_spill(monkeypatch):
    """Counts/discoveries identical when growth interleaves, under
    symmetry's generation-order compaction, and with the spill tier
    evicting under a simulated budget."""
    a = TwoPhaseSys(4).checker().spawn_tpu(
        sync=True, capacity=1 << 8, batch=32, cand=128,
        queue_capacity=1 << 12,
    )
    b = TwoPhaseSys(4).checker().mxu().spawn_tpu(
        sync=True, capacity=1 << 8, batch=32, cand=128,
        queue_capacity=1 << 12,
    )
    assert _counts(a) == _counts(b)
    sa = TwoPhaseSys(3).checker().symmetry().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    sb = TwoPhaseSys(3).checker().symmetry().mxu().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    assert _counts(sa) == _counts(sb)
    ta, tb = sa._table_np(), sb._table_np()
    assert np.array_equal(ta[0], tb[0])  # no growth: bit-identical
    assert np.array_equal(ta[1], tb[1])
    # spill composition: a budget that forces eviction, counts pinned
    from stateright_tpu.parallel.tensor_model import twin_or_none
    from stateright_tpu.telemetry.memory import (
        ENV_DEVICE_BYTES,
        total_bytes,
        wavefront_specs,
    )

    m5 = TwoPhaseSys(5)
    twin = twin_or_none(m5)
    n_props = len(list(m5.properties()))
    sp = (1 << 14, 128 * twin.max_actions)

    def tot(cap):
        return total_bytes(
            wavefront_specs(twin, n_props, cap, 4096, 128, spill=sp)
        )

    monkeypatch.setenv(ENV_DEVICE_BYTES, str(tot(1 << 12) + tot(1 << 13) - 1))
    monkeypatch.setenv("STATERIGHT_TPU_CAPACITY_GUARD", "off")
    c = TwoPhaseSys(5).checker().spill().mxu().spawn_tpu(
        sync=True, capacity=1 << 12, batch=128, queue_capacity=4096,
        spill_bloom_bits=1 << 14, steps_per_call=8,
    )
    assert c.unique_state_count() == 8832
    assert c.spill_status()["evictions"] >= 1


@pytest.mark.medium
def test_mxu_kill_and_resume_parity():
    """Checkpoint an mxu run mid-flight and resume it (still flagged):
    totals must equal the uninterrupted flagged run's."""
    m = TwoPhaseSys(5)
    ref = m.checker().mxu().spawn_tpu(
        sync=True, capacity=1 << 14, batch=128
    )
    c = TwoPhaseSys(5).checker().mxu().spawn_tpu(
        sync=False, capacity=1 << 14, batch=128, steps_per_call=2
    )
    snap = c.checkpoint()
    c.stop()
    c.join()
    r = TwoPhaseSys(5).checker().mxu().spawn_tpu(
        sync=True, capacity=1 << 14, batch=128, resume=snap
    )
    assert r.unique_state_count() == ref.unique_state_count()
    assert sorted(r.discoveries()) == sorted(ref.discoveries())


@pytest.mark.medium
@requires_sharded_collectives
def test_mxu_parity_on_sharded_engine():
    a = TwoPhaseSys(3).checker().spawn_tpu(
        sync=True, devices=2, capacity=1 << 12, frontier_capacity=1 << 9
    )
    b = TwoPhaseSys(3).checker().mxu().spawn_tpu(
        sync=True, devices=2, capacity=1 << 12, frontier_capacity=1 << 9
    )
    assert a.unique_state_count() == b.unique_state_count() == TPC3_UNIQUE
    assert a.state_count() == b.state_count()
    assert sorted(a.discoveries()) == sorted(b.discoveries())
    # cache-key pin: the unflagged sharded key carries no mxu element;
    # the flagged one ends with the components the sharded program
    # actually reads (coalesce, probe — slim_queue has no sharded
    # analogue, so keying on it would recompile an identical shard_map)
    assert not any(
        isinstance(e, tuple) and e and e[0] == "mxu"
        for e in a._last_engine_key
    )
    # (the 2pc hand twin gained a real coalesced kernel: keyed on)
    assert b._last_engine_key[-1] == ("mxu", True, True)
    c = TwoPhaseSys(3).checker().mxu(
        coalesce=False, slim_queue=True, probe=False
    ).spawn_tpu(
        sync=True, devices=2, capacity=1 << 12, frontier_capacity=1 << 9
    )
    assert c.unique_state_count() == TPC3_UNIQUE
    assert not any(
        isinstance(e, tuple) and e and e[0] == "mxu"
        for e in c._last_engine_key
    ), "slim-only mxu must leave the sharded key pre-MXU (same program)"


@pytest.mark.slow
def test_mxu_fleet_parity_across_semantics():
    """The fleet crawl: every network semantics (unordered
    non-duplicating, ordered, duplicating actor-2pc, lossy ordered) on
    the per-channel compiled twins, mxu-on vs mxu-off, counts and
    discoveries identical."""
    from fixtures_actor import actor_2pc_model
    from stateright_tpu.actor import Network

    def pc(m):
        m.per_channel_()
        return m

    builds = [
        lambda: pc(paxos_model(1, 3)),
        lambda: pc(paxos_model(1, 3, Network.new_ordered())),
        lambda: pc(actor_2pc_model(2)),
        lambda: pc(actor_2pc_model(2, lossy=True)),
    ]
    ml = paxos_model(1, 3, Network.new_ordered())
    ml.lossy_network(True)
    ml.per_channel_()

    def lossy_ordered():
        m = paxos_model(1, 3, Network.new_ordered())
        m.lossy_network(True)
        m.per_channel_()
        return m

    builds.append(lossy_ordered)
    for build in builds:
        a = _counts(_spawn(build(), capacity=1 << 14, batch=128))
        b = _counts(_spawn(build(), mxu=True, capacity=1 << 14, batch=128))
        assert a == b, build
