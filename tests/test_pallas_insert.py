"""The Pallas visited-set insert kernel (``ops/pallas_insert.py``) must be
bit-identical to the XLA windowed-scatter path — same tables and novelty
verdicts — on random batches and inside the full engine.

On CPU the kernel runs in Pallas interpret mode; on TPU hardware it
compiles to the real DMA kernel (bench A/Bs both paths on chip).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from stateright_tpu.ops.buckets import SLOTS, bucket_insert
from stateright_tpu.ops.hashing import EMPTY


def random_batch(rng, m, nbuckets, dup_rate=0.3):
    fps = rng.integers(1, 1 << 60, size=m, dtype=np.uint64)
    # force duplicates and empties
    dup = rng.random(m) < dup_rate
    fps[dup] = fps[rng.integers(0, m, size=dup.sum())]
    fps[rng.random(m) < 0.1] = np.uint64(EMPTY)
    payloads = rng.integers(0, 1 << 60, size=m, dtype=np.uint64)
    return jnp.asarray(fps), jnp.asarray(payloads)


@pytest.mark.parametrize(
    "m,nbuckets",
    [
        # interpret-mode rounds are slow; the engine-realistic size stays in
        # the fast tier, the tiny-table padding paths run in the medium tier
        pytest.param(64, 16, marks=pytest.mark.medium),
        pytest.param(256, 64, marks=pytest.mark.medium),
        (1024, 256),
    ],
)
def test_pallas_matches_xla_insert(m, nbuckets):
    rng = np.random.default_rng(m * 31 + nbuckets)
    shapes = (nbuckets * SLOTS,)
    tfp_x = jnp.full(shapes, EMPTY, jnp.uint64)
    tpl_x = jnp.zeros(shapes, jnp.uint64)
    tfp_p, tpl_p = tfp_x, tpl_x

    for round_ in range(4):
        fps, payloads = random_batch(rng, m, nbuckets)
        rx = bucket_insert(
            tfp_x, tpl_x, fps, payloads, window=64, use_pallas=False
        )
        rp = bucket_insert(
            tfp_p, tpl_p, fps, payloads, window=64, use_pallas=True
        )
        # (tfp, tpl, sel, n_new, overflow, cand_overflow)
        tfp_x, tpl_x = rx[0], rx[1]
        tfp_p, tpl_p = rp[0], rp[1]
        assert bool(rx[4]) == bool(rp[4]), round_  # overflow agreement
        if bool(rx[4]):
            break
        assert int(rx[3]) == int(rp[3])  # n_new agreement
        # inserted-candidate selection agreement (novelty verdicts)
        np.testing.assert_array_equal(
            np.asarray(rx[2])[: int(rx[3])], np.asarray(rp[2])[: int(rp[3])]
        )
        np.testing.assert_array_equal(np.asarray(tfp_x), np.asarray(tfp_p))
        np.testing.assert_array_equal(np.asarray(tpl_x), np.asarray(tpl_p))


def test_pallas_overflow_writes_nothing():
    from stateright_tpu.ops.buckets import bucket_of

    nbuckets = 4
    tfp = jnp.full((nbuckets * SLOTS,), EMPTY, jnp.uint64)
    tpl = jnp.zeros((nbuckets * SLOTS,), jnp.uint64)
    # >SLOTS distinct fps in one bucket (constructed through the mix64
    # bucket derivation): guaranteed overflow
    colliding, x = [], 1
    while len(colliding) < SLOTS + 1:
        if int(bucket_of(np.uint64(x), nbuckets)) == 0:
            colliding.append(x)
        x += 1
    fps = jnp.asarray(np.asarray(colliding, np.uint64))
    payloads = jnp.arange(SLOTS + 1, dtype=jnp.uint64)
    out = bucket_insert(tfp, tpl, fps, payloads, window=8, use_pallas=True)
    assert bool(out[4]) and int(out[3]) == 0  # overflow, nothing counted
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(tfp))
    np.testing.assert_array_equal(np.asarray(out[1]), np.asarray(tpl))


def test_engine_pinned_count_with_pallas():
    """Full device engine with the Pallas insert: pinned 2pc count parity
    (reference ``examples/2pc.rs:133``: 288 @ 3 RMs)."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    checker = TwoPhaseSys(3).checker().spawn_tpu(
        sync=True, capacity=1 << 12, frontier_capacity=1 << 8, pallas=True
    )
    assert checker.unique_state_count() == 288
    assert set(checker.discoveries()) == {"abort agreement", "commit agreement"}
