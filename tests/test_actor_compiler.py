"""Mechanical actor→tensor compiler: equivalence + engine parity.

The compiler (``parallel/actor_compiler.py``) must reproduce the object
model's transition semantics (reference ``src/actor/model.rs:187-306``)
table-for-table: pinned counts 544 (ABD, reference
``linearizable-register.rs:258``) and 93 (single-copy, reference
``single-copy-register.rs:100``), plus crawl-level successor-set equality.
"""

import pytest

from stateright_tpu.core import Expectation
from stateright_tpu.models.linearizable_register import abd_model
from stateright_tpu.models.paxos import paxos_model
from stateright_tpu.models.single_copy_register import single_copy_model
from stateright_tpu.parallel.actor_compiler import CompiledActorTensor
from stateright_tpu.parallel.history_tensor import LinHistoryCodec
from stateright_tpu.semantics import LinearizabilityTester
from stateright_tpu.semantics.register import READ, Register, write

from test_paxos_tensor import crawl_and_check


# ---------------------------------------------------------------------------
# history codec
# ---------------------------------------------------------------------------


def test_history_codec_roundtrip_and_verdicts():
    hc = LinHistoryCodec([3, 4], ["A", "B"], "\0")
    hc.ensure_table()  # the closure strategy no longer enumerates eagerly
    # every enumerated joint state round-trips and the baked verdict equals
    # the live tester's
    seen = 0
    t = LinearizabilityTester(Register("\0"))
    t = t.on_invoke(3, write("A")).on_invoke(4, write("B"))
    frontier = [t]
    visited = {t}
    while frontier:
        cur = frontier.pop()
        seen += 1
        fields = hc.fields_of_tester(cur)
        assert hc.tester_of_fields(fields) == cur
        key = hc.key_of_fields(fields)
        import numpy as np

        i = int(np.searchsorted(hc.table_keys, key))
        assert hc.table_keys[i] == key
        assert bool(hc.table_ok[i]) == cur.is_consistent()
        for thread in (3, 4):
            infl = cur.in_flight_by_thread.get(thread)
            comp = cur.history_by_thread.get(thread, ())
            if infl is not None and infl[1] == READ:
                nxts = [
                    cur.on_return(thread, ("read_ok", v))
                    for v in ("\0", "A", "B")
                ]
            elif infl is not None:
                nxts = [cur.on_return(thread, ("write_ok",))]
            elif len(comp) == 1:
                nxts = [cur.on_invoke(thread, READ)]
            else:
                nxts = []
            for n in nxts:
                if n not in visited:
                    visited.add(n)
                    frontier.append(n)
    assert seen == len(hc.table_keys) == 124


def test_multiop_codec_roundtrip_and_verdicts():
    """put_count=2 codec (reference ``register.rs:96,178-186``): every
    enumerated joint tester state round-trips fields→tester→fields and the
    baked verdict equals the live tester's — including write-invocation
    snapshots, which the K=1 layout cannot express."""
    import numpy as np

    from stateright_tpu.parallel.history_tensor import MultiOpLinHistoryCodec

    hc = MultiOpLinHistoryCodec([2, 3], [["A", "Z"], ["B", "Y"]], "\0")
    assert hc.K == 2 and len(hc.table_keys) == 2016
    step = max(1, len(hc.table_keys) // 200)
    for idx in range(0, len(hc.table_keys), step):
        key = int(hc.table_keys[idx])
        fields = []
        for i in range(hc.C):
            word = (key >> (i * hc.thread_bits)) & (
                (1 << hc.thread_bits) - 1
            )
            phase = word & ((1 << hc.phase_bits) - 1)
            off = hc.phase_bits
            snaps = []
            for _ in range(hc.K):
                snaps.append((word >> off) & ((1 << hc.snap_bits) - 1))
                off += hc.snap_bits
            rval = (word >> off) & ((1 << hc.rval_bits) - 1)
            fields.append((phase, tuple(snaps), rval))
        tester = hc.tester_of_fields(fields)
        assert hc.fields_of_tester(tester) == fields
        assert hc.key_of_fields(fields) == key
        assert bool(hc.table_ok[idx]) == tester.is_consistent()


# ---------------------------------------------------------------------------
# single-copy register (compiled)
# ---------------------------------------------------------------------------


@pytest.mark.medium
# re-tiered fast->slow (PR 2): the fast tier blew the 870s tier-1 budget
@pytest.mark.slow
def test_single_copy_compiled_equivalence():
    m = single_copy_model(2, 1)
    tm = m.tensor_model()
    assert isinstance(tm, CompiledActorTensor)
    seen = crawl_and_check(m, tm)
    assert len(seen) == 93


def test_single_copy_tpu_pinned_counts():
    m = single_copy_model(2, 1)
    t = m.checker().spawn_tpu(sync=True, capacity=1 << 10, frontier_capacity=1 << 7)
    assert t.unique_state_count() == 93
    assert set(t.discoveries()) == {"value chosen"}
    t.assert_properties()


def test_single_copy_two_servers_tpu_finds_violation():
    m = single_copy_model(2, 2)
    t = m.checker().spawn_tpu(sync=True, capacity=1 << 10, frontier_capacity=1 << 7)
    disc = t.discoveries()
    assert set(disc) == {"linearizable", "value chosen"}
    # the counterexample is a real trace: re-execution reaches a state whose
    # history is NOT linearizable (reference ``single-copy-register.rs:103-120``)
    final = disc["linearizable"].final_state()
    assert not final.history.is_consistent()


def test_single_copy_sharded_matches():
    m = single_copy_model(2, 1)
    t = m.checker().spawn_tpu(
        devices=8, sync=True, capacity=1 << 10, frontier_capacity=1 << 7
    )
    assert t.unique_state_count() == 93
    assert set(t.discoveries()) == {"value chosen"}


# ---------------------------------------------------------------------------
# ABD register (compiled)
# ---------------------------------------------------------------------------


@pytest.mark.medium
def test_abd_compiled_prefix_equivalence():
    m = abd_model(2, 2)
    tm = m.tensor_model()
    assert isinstance(tm, CompiledActorTensor)
    crawl_and_check(m, tm, max_levels=5)


def test_abd_tpu_pinned_counts():
    m = abd_model(2, 2)
    t = m.checker().spawn_tpu(sync=True, capacity=1 << 12, frontier_capacity=1 << 9)
    assert t.unique_state_count() == 544
    assert set(t.discoveries()) == {"value chosen"}
    t.assert_properties()


def test_abd_put2_host_device_pinned():
    """put_count=2 ABD (the round-4 device-story gap: reference
    ``register.rs:96,178-186`` supports arbitrary put_count, the compiler
    stopped at 1): full enumeration pinned host=device with discovery
    parity.  ABD stays linearizable, so no 'linearizable' discovery."""
    m = abd_model(2, 2, put_count=2)
    h = m.checker().spawn_bfs().join()
    assert h.unique_state_count() == 2980
    t = m.checker().spawn_tpu(sync=True, capacity=1 << 14)
    assert t.unique_state_count() == 2980
    assert sorted(t.discoveries()) == sorted(h.discoveries()) == [
        "value chosen"
    ]
    t.assert_properties()


def test_singlecopy_put2_violation_discovery_parity():
    """The put_count=2 linearizability verdict's FALSE path: two
    unreplicated servers violate; host and device both discover it, and
    the device witness re-executes to a genuinely inconsistent history."""
    m = single_copy_model(2, 2, put_count=2)
    h = m.checker().spawn_bfs().join()
    t = m.checker().spawn_tpu(sync=True, capacity=1 << 12)
    assert sorted(t.discoveries()) == sorted(h.discoveries()) == [
        "linearizable",
        "value chosen",
    ]
    final = t.discoveries()["linearizable"].final_state()
    assert not final.history.is_consistent()
    h.assert_discovery(
        "linearizable", list(t.discoveries()["linearizable"].actions())
    )


# re-tiered fast->slow (PR 2): the fast tier blew the 870s tier-1 budget
@pytest.mark.slow
def test_singlecopy_put2_full_crawl_equivalence():
    """Per-state equivalence over the FULL put_count=2 single-copy space
    (no early exit): encode/decode round-trip, fingerprint agreement,
    successor-set equality, and property-mask agreement — including
    states where the device linearizability verdict is False."""
    m = single_copy_model(2, 2, put_count=2)
    tm = m.tensor_model()
    assert isinstance(tm, CompiledActorTensor)
    seen = crawl_and_check(m, tm)
    assert len(seen) == 384


def test_singlecopy_put2_single_server_pinned():
    m = single_copy_model(2, 1, put_count=2)
    h = m.checker().spawn_bfs().join()
    assert h.unique_state_count() == 369
    t = m.checker().spawn_tpu(sync=True, capacity=1 << 12)
    assert t.unique_state_count() == 369
    assert set(t.discoveries()) == {"value chosen"}
    t.assert_properties()


def test_wo_rejects_put2():
    """Write-once workloads stay put_count=1 (a failed write changes
    which op takes effect; the multi-op codec models write_ok only)."""
    from stateright_tpu.actor.write_once_register import WORegisterClient
    from stateright_tpu.models.write_once_register import wo_register_model
    from stateright_tpu.parallel.actor_compiler import (
        CompileError,
        compile_actor_model,
    )

    m = wo_register_model(2, 1)
    for a in m.actors:
        if isinstance(a, WORegisterClient):
            a.put_count = 2
    with pytest.raises(CompileError, match="put_count"):
        compile_actor_model(m)


def test_abd_sharded_matches():
    m = abd_model(2, 2)
    t = m.checker().spawn_tpu(
        devices=8, sync=True, capacity=1 << 12, frontier_capacity=1 << 9
    )
    assert t.unique_state_count() == 544
    assert set(t.discoveries()) == {"value chosen"}


# ---------------------------------------------------------------------------
# compiled paxos agrees with the hand-built twin
# ---------------------------------------------------------------------------


def test_compiled_paxos_agrees_with_hand_twin():
    # same config through both twins: unique counts and discoveries agree
    hand = paxos_model(1, 3)
    assert not isinstance(hand.tensor_model(), CompiledActorTensor)
    h = hand.checker().spawn_tpu(
        sync=True, capacity=1 << 12, frontier_capacity=1 << 9
    )

    compiled = paxos_model(1, 3)
    tm = compiled._compiled_tensor(1)
    assert isinstance(tm, CompiledActorTensor)
    # force the compiled twin in place of the hand twin
    object.__setattr__(compiled, "_tensor_model_cache", tm)
    c = compiled.checker().spawn_tpu(
        sync=True, capacity=1 << 12, frontier_capacity=1 << 9
    )
    assert h.unique_state_count() == c.unique_state_count() == 265
    assert set(h.discoveries()) == set(c.discoveries())


# -- duplicating-network compilation -----------------------------------------


# re-tiered fast->slow (PR 2): the fast tier blew the 870s tier-1 budget
@pytest.mark.slow
def test_single_copy_duplicating_compiled_equivalence():
    """Duplicating network (redelivery allowed; reference network.rs:203-205)
    through the mechanical compiler: full device/host parity."""
    from stateright_tpu.actor import Network

    m = single_copy_model(2, 1, Network.new_unordered_duplicating())
    tm = m.tensor_model()
    assert tm is not None and tm.dup
    crawl_and_check(m, tm)


def test_single_copy_duplicating_full_enumeration_parity():
    """1 client / 1 server: no concurrency, so linearizability holds and
    both engines enumerate the whole (finite) duplicating-network space —
    counts must agree exactly."""
    from stateright_tpu.actor import Network

    def build():
        return single_copy_model(1, 1, Network.new_unordered_duplicating())

    cpu = build().checker().spawn_bfs().join()
    tpu = build().checker().spawn_tpu(sync=True)
    assert "linearizable" not in cpu.discoveries()
    assert cpu.unique_state_count() == tpu.unique_state_count()
    assert set(cpu.discoveries()) == set(tpu.discoveries())


def test_single_copy_lossy_duplicating_parity():
    """Lossy + duplicating (the reference's harshest unordered config): a
    drop removes the envelope forever (network.rs:242-244) while deliveries
    never consume it; full-enumeration count parity on the 1-client system."""
    from stateright_tpu.actor import Network

    def build():
        m = single_copy_model(1, 1, Network.new_unordered_duplicating())
        m.lossy_network(True)
        return m

    cpu = build().checker().spawn_bfs().join()
    tpu = build().checker().spawn_tpu(sync=True)
    assert "linearizable" not in cpu.discoveries()
    assert cpu.unique_state_count() == tpu.unique_state_count()
    assert set(cpu.discoveries()) == set(tpu.discoveries())


def test_bounded_models_reject_duplicating_twins():
    """ABD/paxos closure bounds assume at-most-once delivery (a redelivered
    put restarts a round, growing clocks/ballots unboundedly), so their
    compiled twins must refuse duplicating networks and fall back to
    structural fingerprints rather than poison real reachable states."""
    from stateright_tpu.actor import Network

    m = abd_model(1, 2, Network.new_unordered_duplicating())
    assert m.tensor_model() is None
    # structural fingerprints survive genuinely redelivery-reachable states
    s = m.init_states()[0]
    for _ in range(8):
        nxt = m.next_states(s)
        if not nxt:
            break
        s = nxt[0]
        m.fingerprint_state(s)

    p = paxos_model(1, 3, Network.new_unordered_duplicating())
    assert p.tensor_model() is None


# -- ordered-network compilation ---------------------------------------------


@pytest.mark.medium
def test_single_copy_ordered_compiled_equivalence():
    """Ordered (per-pair FIFO) network through the compiler: rank-in-slot
    encoding must reproduce the object flows state-for-state."""
    from stateright_tpu.actor import Network

    m = single_copy_model(2, 1, Network.new_ordered())
    tm = m.tensor_model()
    assert tm is not None and tm.ordered
    crawl_and_check(m, tm)


@pytest.mark.medium
def test_abd_ordered_compiled_equivalence():
    from stateright_tpu.actor import Network

    m = abd_model(2, 2, Network.new_ordered())
    tm = m.tensor_model()
    assert tm is not None and tm.ordered
    crawl_and_check(m, tm, max_levels=6)


def test_abd3_ordered_compiles_to_a_device_twin():
    """The reference bench's ``lin-reg 3 ordered`` config (bench.sh:31-34)
    compiles — pinning the fact the round-2 bench comment got wrong (it
    claimed ordered networks were outside the compiled fragment).  Full
    engine parity for ordered ABD is pinned at (2,2) below; the (3,2)
    config's device rate is recorded by bench.py's protocol sweep."""
    from stateright_tpu.actor import Network

    m = abd_model(3, 2, Network.new_ordered())
    tm = m.tensor_model()
    assert tm is not None and tm.ordered


def test_abd_ordered_engine_parity():
    """The reference bench protocol's ``lin-reg N ordered`` config
    (bench.sh:31-34) on the device engine."""
    from stateright_tpu.actor import Network

    def build():
        return abd_model(2, 2, Network.new_ordered())

    cpu = build().checker().spawn_bfs().join()
    tpu = build().checker().spawn_tpu(sync=True)
    assert "linearizable" not in cpu.discoveries()
    assert cpu.unique_state_count() == tpu.unique_state_count()
    assert set(cpu.discoveries()) == set(tpu.discoveries())


def test_single_copy_ordered_lossy_parity():
    """Lossy ordered network: drops remove flow heads only (the object model
    enumerates Drop over iter_deliverable)."""
    from stateright_tpu.actor import Network

    def build():
        m = single_copy_model(1, 1, Network.new_ordered())
        m.lossy_network(True)
        return m

    cpu = build().checker().spawn_bfs().join()
    tpu = build().checker().spawn_tpu(sync=True)
    assert cpu.unique_state_count() == tpu.unique_state_count()
    assert set(cpu.discoveries()) == set(tpu.discoveries())


# re-tiered fast->slow (PR 2): the fast tier blew the 870s tier-1 budget
@pytest.mark.slow
def test_paxos_ordered_lossy_deep_flow_equivalence():
    """Lossy ordered paxos reaches ≥2-deep flows (e.g. prepare then accept
    queued on one pair), exercising head-only drop semantics and mid-flow
    rank bookkeeping that shallow configs cannot distinguish."""
    from stateright_tpu.actor import Network

    m = paxos_model(1, 3, Network.new_ordered())
    m.lossy_network(True)
    tm = m.tensor_model()
    assert tm is not None and tm.ordered
    crawl_and_check(m, tm)


def test_paxos_ordered_engine_parity():
    from stateright_tpu.actor import Network

    def build():
        return paxos_model(1, 3, Network.new_ordered())

    cpu = build().checker().spawn_bfs().join()
    tpu = build().checker().spawn_tpu(sync=True)
    assert cpu.unique_state_count() == tpu.unique_state_count() == 99
    assert set(cpu.discoveries()) == set(tpu.discoveries())


def test_register_workload_accepts_extra_factored_properties():
    """Register workloads compile the two standard history-driven
    properties PLUS any factored extras — evaluated as tabulated lookups
    on device, the same predicate directly on host."""
    from stateright_tpu.actor.device_props import exists_actor, forall_actors
    from stateright_tpu.actor.register import NULL_VALUE
    from stateright_tpu.models.single_copy_register import single_copy_model

    m = single_copy_model(2, 1)
    m.property(
        Expectation.ALWAYS,
        "server value known",  # holds: never discovered
        forall_actors(lambda i, s: i != 0 or s in (NULL_VALUE, "A", "B")),
    )
    m.property(
        Expectation.SOMETIMES,
        "server took a write",  # discovered once a put lands
        exists_actor(lambda i, s: i == 0 and s in ("A", "B")),
    )
    h = m.checker().spawn_bfs().join()
    c = m.checker().spawn_tpu(sync=True, capacity=1 << 13)
    assert h.unique_state_count() == c.unique_state_count() == 93
    assert (
        sorted(h.discoveries())
        == sorted(c.discoveries())
        == ["server took a write", "value chosen"]
    )


def test_register_workload_rejects_non_factored_extras():
    from stateright_tpu.models.single_copy_register import single_copy_model
    from stateright_tpu.parallel.actor_compiler import (
        CompileError,
        compile_actor_model,
    )

    m = single_copy_model(2, 1)
    m.property(Expectation.ALWAYS, "opaque", lambda mm, s: True)
    with pytest.raises(CompileError, match="factored"):
        compile_actor_model(m)
