"""Explicit ``Choice`` composition of three differently-typed actors.

Mirrors the reference's 3-way choice test (``src/actor/model.rs:862-977``):
actor A holds a wrapping byte counter, B a character, C a string — three
different state types behind one message vocabulary — in a ring
A -> B -> C -> A started by C, checked under DFS with a
:class:`StateRecorder`, and the exact visit sequence is pinned.
"""

from stateright_tpu.actor import Actor, ActorModel, Id, Network
from stateright_tpu.actor.choice import Choice, ChoiceState
from stateright_tpu.checker.visitor import StateRecorder
from stateright_tpu.core import Expectation


class A(Actor):  # u8-style wrapping counter (model.rs:869-881)
    def __init__(self, b: Id):
        self.b = b

    def on_start(self, id, out):
        return 1

    def on_msg(self, id, state, src, msg, out):
        out.send(self.b, msg)
        return (state + 1) % 256


class B(Actor):  # char state (model.rs:884-897)
    def __init__(self, c: Id):
        self.c = c

    def on_start(self, id, out):
        return "a"

    def on_msg(self, id, state, src, msg, out):
        out.send(self.c, msg)
        return chr((ord(state) + 1) % 256)


class C(Actor):  # string state; kicks off the ring (model.rs:899-913)
    def __init__(self, a: Id):
        self.a = a

    def on_start(self, id, out):
        out.send(self.a, ())
        return "I"

    def on_msg(self, id, state, src, msg, out):
        out.send(self.a, msg)
        return state + "I"


def _sys():
    return (
        ActorModel(cfg=None, init_history=0)
        .actor(Choice.new(A(Id(1))))
        .actor(Choice.new(B(Id(2))).or_())
        .actor(Choice.new(C(Id(0))).or_().or_())
        .init_network_(Network.new_unordered_nonduplicating())
        .record_msg_out(lambda cfg, out_count, env: out_count + 1)
        .property(Expectation.ALWAYS, "true", lambda m, s: True)
        .within_boundary_(lambda cfg, state: state.history < 8)
    )


def test_choice_correctly_implements_actor():
    """Exact DFS visit sequence parity with ``model.rs:914-977``."""
    recorder = StateRecorder()
    _sys().checker().visitor(recorder).spawn_dfs().join()
    states = [tuple(s.actor_states) for s in recorder.states]
    expected = [
        # Init.
        (ChoiceState(0, 1), ChoiceState(1, "a"), ChoiceState(2, "I")),
        # Then deliver to A.
        (ChoiceState(0, 2), ChoiceState(1, "a"), ChoiceState(2, "I")),
        # Then deliver to B.
        (ChoiceState(0, 2), ChoiceState(1, "b"), ChoiceState(2, "I")),
        # Then deliver to C.
        (ChoiceState(0, 2), ChoiceState(1, "b"), ChoiceState(2, "II")),
        # Then deliver to A again.
        (ChoiceState(0, 3), ChoiceState(1, "b"), ChoiceState(2, "II")),
        # Then deliver to B again.
        (ChoiceState(0, 3), ChoiceState(1, "c"), ChoiceState(2, "II")),
        # Then deliver to C again.
        (ChoiceState(0, 3), ChoiceState(1, "c"), ChoiceState(2, "III")),
    ]
    assert states == expected


def test_choice_tags_disambiguate_equal_inner_states():
    """Two variants over identical inner states are distinct values — the
    combinator's entire reason to exist (reference nested L/R tags)."""
    s0 = ChoiceState(0, 1)
    s1 = ChoiceState(1, 1)
    assert s0 != s1 and hash(s0) != hash(s1)
    from stateright_tpu.fingerprint import fingerprint

    assert fingerprint(s0) != fingerprint(s1)


def test_choice_noop_is_preserved():
    """A wrapped no-op handler result stays a no-op so the model still
    prunes it (reference model.rs:253-260 pruning semantics)."""

    class Quiet(Actor):
        def on_start(self, id, out):
            return 0

    from stateright_tpu.actor import Out

    c = Choice.new(Quiet()).or_()
    out = Out()
    assert c.on_msg(Id(0), ChoiceState(1, 0), Id(1), (), out) is None
    assert len(out) == 0
