"""UDP actor runtime integration tests — real sockets on 127.0.0.1.

The deployment path the reference documents (``examples/paxos.rs:376-383``:
``spawn`` runs servers over UDP + JSON; users drive them with raw packets)
executed end-to-end: a register server answers Put/Get through real sockets,
and the ordered-reliable-link wrapper recovers from an injected drop by
resending until acked (reference ``src/actor/spawn.rs:63-183``,
``src/actor/ordered_reliable_link.rs:90-127``).

The "drop" injection uses UDP's own semantics: a datagram sent to a port
nobody has bound yet vanishes, exactly like a lossy network losing the
packet — no mock transport needed.
"""

import json
import socket
import time
from dataclasses import dataclass

import pytest

from stateright_tpu.actor import Actor, Id, Out
from stateright_tpu.actor.ordered_reliable_link import OrderedReliableLink
from stateright_tpu.actor.spawn import spawn
from stateright_tpu.models.single_copy_register import SingleCopyServer


def free_port() -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def wait_until(pred, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


@pytest.fixture
def client_sock():
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    s.settimeout(5.0)
    yield s
    s.close()


def test_register_server_put_get_over_udp(client_sock):
    """Spawn a single-copy register server on a real socket and drive a
    Put/Get round trip with raw JSON datagrams (the reference's documented
    deployment interaction, ``single-copy-register.rs`` spawn +
    ``spawn.rs:105-133`` serde loop)."""
    port = free_port()
    server_id = Id.from_addr("127.0.0.1", port)
    handles = spawn([(server_id, SingleCopyServer())])
    try:
        addr = ("127.0.0.1", port)
        client_sock.sendto(json.dumps(["put", 1, "X"]).encode(), addr)
        reply, _ = client_sock.recvfrom(65536)
        assert json.loads(reply) == ["put_ok", 1]

        client_sock.sendto(json.dumps(["get", 2]).encode(), addr)
        reply, _ = client_sock.recvfrom(65536)
        assert json.loads(reply) == ["get_ok", 2, "X"]

        # server state converged to the written value (observable handle)
        assert wait_until(lambda: handles[0].state == "X")
    finally:
        for h in handles:
            h.stop()
            h.join(2.0)


def test_malformed_datagram_is_ignored(client_sock):
    """Garbage input must be logged-and-dropped, not kill the actor thread
    (reference ``spawn.rs:105-133``)."""
    port = free_port()
    server_id = Id.from_addr("127.0.0.1", port)
    handles = spawn([(server_id, SingleCopyServer())])
    try:
        addr = ("127.0.0.1", port)
        client_sock.sendto(b"\xff\xfenot json", addr)
        # the server must still answer a well-formed request afterwards
        client_sock.sendto(json.dumps(["put", 7, "Y"]).encode(), addr)
        reply, _ = client_sock.recvfrom(65536)
        assert json.loads(reply) == ["put_ok", 7]
    finally:
        for h in handles:
            h.stop()
            h.join(2.0)


def test_spawn_partial_bind_failure_releases_sockets():
    """If a later actor's bind fails, the sockets already bound must be
    released before the error propagates — otherwise their ports stay stuck
    until GC and a retry fails EADDRINUSE."""
    ok_port = free_port()
    blocker = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    blocker.bind(("127.0.0.1", 0))
    taken_port = blocker.getsockname()[1]
    try:
        with pytest.raises(OSError):
            spawn([
                (Id.from_addr("127.0.0.1", ok_port), SingleCopyServer()),
                (Id.from_addr("127.0.0.1", taken_port), SingleCopyServer()),
            ])
        # the first actor's socket must have been closed: rebinding works
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.bind(("127.0.0.1", ok_port))
        s.close()
    finally:
        blocker.close()


@dataclass
class BurstSender(Actor):
    """Sends a burst of messages at start; the ORL wrapper sequences them."""

    dst: int
    msgs: tuple

    def on_start(self, id: Id, out: Out):
        for m in self.msgs:
            out.send(Id(self.dst), m)
        return ()


class Recorder(Actor):
    """Accumulates every delivered message, in order."""

    def on_start(self, id: Id, out: Out):
        return ()

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        return state + (msg,)


def test_orl_resends_until_ack_after_injected_drop():
    """Both data messages are sent while the receiver's port is unbound (the
    datagrams vanish — an injected drop).  The receiver then comes up; the
    sender's ORL resend timer must redeliver IN ORDER, exactly once, and the
    acks must drain the pending set (reference
    ``ordered_reliable_link.rs:90-127`` resend + at-most-once)."""
    sport, rport = free_port(), free_port()
    sender_id = Id.from_addr("127.0.0.1", sport)
    receiver_id = Id.from_addr("127.0.0.1", rport)

    sender = OrderedReliableLink(
        BurstSender(dst=int(receiver_id), msgs=(("hello", 1), ("world", 2))),
        resend_interval=(0.05, 0.1),
    )
    s_handles = spawn([(sender_id, sender)])
    try:
        # the initial sends happened into the void; let at least one resend
        # cycle fire against the still-unbound port too
        assert wait_until(
            lambda: s_handles[0].state is not None
            and len(s_handles[0].state.msgs_pending_ack) == 2
        )
        time.sleep(0.15)

        receiver = OrderedReliableLink(Recorder(), resend_interval=(0.05, 0.1))
        r_handles = spawn([(receiver_id, receiver)])
        try:
            # resends deliver both messages, in seq order, exactly once
            assert wait_until(
                lambda: r_handles[0].state is not None
                and len(r_handles[0].state.wrapped_state) >= 2
            ), "ORL never redelivered after the drop"
            assert r_handles[0].state.wrapped_state == (
                ("hello", 1),
                ("world", 2),
            )
            # acks flowed back: nothing left pending, no further redelivery
            assert wait_until(
                lambda: len(s_handles[0].state.msgs_pending_ack) == 0
            ), "acks never drained the pending set"
            time.sleep(0.3)  # a few more resend timer cycles
            assert r_handles[0].state.wrapped_state == (
                ("hello", 1),
                ("world", 2),
            ), "at-most-once delivery violated by a late resend"
        finally:
            for h in r_handles:
                h.stop()
                h.join(2.0)
    finally:
        for h in s_handles:
            h.stop()
            h.join(2.0)


def test_raft_leader_election_over_udp():
    """The SAME RaftServer actor that was model checked (tests/test_raft.py)
    deployed on real loopback sockets with Raft's randomized election
    timeouts: three servers elect a leader through genuine UDP exchange and
    real timer fires, and election safety holds over the observed states
    (one leader per term) — the reference's model-then-deploy story
    (``spawn.rs:63-140``) exercised with timers."""
    from stateright_tpu.models.raft import LEADER, RaftServer

    ports = [free_port() for _ in range(3)]
    ids = [Id.from_addr("127.0.0.1", p) for p in ports]
    handles = spawn(
        [
            (
                ids[i],
                RaftServer(
                    peers=[x for x in ids if x != ids[i]],
                    cluster=3,
                    max_term=50,
                    timer_range=(0.02, 0.12),
                ),
            )
            for i in range(3)
        ]
    )
    try:
        assert wait_until(
            lambda: any(
                h.state is not None and h.state.role == LEADER
                for h in handles
            ),
            timeout=15.0,
        ), [h.state for h in handles]
        states = [h.state for h in handles if h.state is not None]
        leaders_by_term = [s.term for s in states if s.role == LEADER]
        assert len(leaders_by_term) == len(set(leaders_by_term)), states
    finally:
        for h in handles:
            h.stop()
        for h in handles:
            h.join(timeout=2.0)
