"""Consistency-semantics tests (reference ``linearizability.rs:268-453``,
``sequential_consistency.rs:240-344``, spec tests, ORL checked by the model
checker itself ``ordered_reliable_link.rs:217-244``)."""

import pytest

from stateright_tpu import Expectation
from stateright_tpu.actor import (
    Actor,
    ActorModel,
    Deliver,
    Id,
    Network,
    Out,
)
from stateright_tpu.actor.ordered_reliable_link import OrderedReliableLink
from stateright_tpu.semantics import (
    LinearizabilityTester,
    Register,
    SequentialConsistencyTester,
    VecSpec,
    WORegister,
)
from stateright_tpu.semantics.register import READ, write


# ---------------------------------------------------------------------------
# sequential specs
# ---------------------------------------------------------------------------

def test_register_spec():
    r = Register("A")
    r2, ret = r.invoke(READ)
    assert ret == ("read_ok", "A") and r2 == r
    r3, ret = r.invoke(write("B"))
    assert ret == ("write_ok",)
    _, ret = r3.invoke(READ)
    assert ret == ("read_ok", "B")
    assert r.is_valid_history(
        [(write("B"), ("write_ok",)), (READ, ("read_ok", "B"))]
    )
    assert not r.is_valid_history(
        [(write("B"), ("write_ok",)), (READ, ("read_ok", "A"))]
    )


def test_wo_register_spec():
    r = WORegister()
    r2, ret = r.invoke(write("A"))
    assert ret == ("write_ok",)
    _, ret = r2.invoke(write("A"))
    assert ret == ("write_ok",)  # idempotent equal write
    _, ret = r2.invoke(write("B"))
    assert ret == ("write_fail",)
    _, ret = r2.invoke(READ)
    assert ret == ("read_ok", "A")


def test_vec_spec():
    v = VecSpec(("A",))
    v, ret = v.invoke(("len",))
    assert ret == ("len_ok", 1)
    v, ret = v.invoke(("push", "B"))
    assert ret == ("push_ok",)
    v, ret = v.invoke(("pop",))
    assert ret == ("pop_ok", "B")
    v, ret = v.invoke(("pop",))
    assert ret == ("pop_ok", "A")
    v, ret = v.invoke(("pop",))
    assert ret == ("pop_ok", None)


# ---------------------------------------------------------------------------
# linearizability (reference ``linearizability.rs:268-453``)
# ---------------------------------------------------------------------------

def test_linearizable_sequential_history():
    h = (
        LinearizabilityTester(Register("A"))
        .on_invret(0, write("B"), ("write_ok",))
        .on_invret(0, READ, ("read_ok", "B"))
    )
    assert h.is_consistent()
    assert h.serialized_history() == [
        (write("B"), ("write_ok",)),
        (READ, ("read_ok", "B")),
    ]


def test_stale_read_not_linearizable():
    # T0 writes B and returns; T1 then reads A (the initial value): the
    # real-time constraint forbids serializing the read before the write
    h = (
        LinearizabilityTester(Register("A"))
        .on_invret(0, write("B"), ("write_ok",))
        .on_invret(1, READ, ("read_ok", "A"))
    )
    assert not h.is_consistent()


def test_stale_read_is_sequentially_consistent():
    # same history IS sequentially consistent (read serialized first)
    h = (
        SequentialConsistencyTester(Register("A"))
        .on_invret(0, write("B"), ("write_ok",))
        .on_invret(1, READ, ("read_ok", "A"))
    )
    assert h.is_consistent()
    assert h.serialized_history() == [
        (READ, ("read_ok", "A")),
        (write("B"), ("write_ok",)),
    ]


def test_concurrent_read_may_see_either_value():
    # write in flight: concurrent read may see old or new value
    for seen in ("A", "B"):
        h = (
            LinearizabilityTester(Register("A"))
            .on_invoke(0, write("B"))
            .on_invret(1, READ, ("read_ok", seen))
        )
        assert h.is_consistent(), seen


def test_in_flight_op_may_remain_unserialized():
    h = LinearizabilityTester(Register("A")).on_invoke(0, write("B"))
    assert h.is_consistent()
    assert h.serialized_history() == []


def test_invalid_history_double_invoke():
    h = LinearizabilityTester(Register("A")).on_invoke(0, READ)
    h2 = h.on_invoke(0, READ)  # same thread, op already in flight
    assert not h2.valid
    assert not h2.is_consistent()
    h3 = LinearizabilityTester(Register("A")).on_return(0, ("write_ok",))
    assert not h3.valid


def test_tester_equality_and_hash():
    a = LinearizabilityTester(Register("A")).on_invret(0, READ, ("read_ok", "A"))
    b = LinearizabilityTester(Register("A")).on_invret(0, READ, ("read_ok", "A"))
    assert a == b and hash(a) == hash(b)
    c = a.on_invoke(1, write("B"))
    assert a != c


def test_real_time_chain_across_three_threads():
    # T0 writes B; then T1 writes C; then T2 reads — must see C, not B
    h = (
        LinearizabilityTester(Register("A"))
        .on_invret(0, write("B"), ("write_ok",))
        .on_invret(1, write("C"), ("write_ok",))
    )
    assert h.on_invret(2, READ, ("read_ok", "C")).is_consistent()
    assert not h.on_invret(2, READ, ("read_ok", "B")).is_consistent()
    assert not h.on_invret(2, READ, ("read_ok", "A")).is_consistent()


def test_vec_histories():
    # pop before push is not linearizable unless concurrent
    h = (
        LinearizabilityTester(VecSpec())
        .on_invret(0, ("pop",), ("pop_ok", "X"))
        .on_invret(1, ("push", "X"), ("push_ok",))
    )
    assert not h.is_consistent()
    h2 = (
        LinearizabilityTester(VecSpec())
        .on_invoke(1, ("push", "X"))
        .on_invret(0, ("pop",), ("pop_ok", "X"))
    )
    assert h2.is_consistent()


# ---------------------------------------------------------------------------
# ordered reliable link, checked by the model checker itself
# (reference ``ordered_reliable_link.rs:150-244``)
# ---------------------------------------------------------------------------

class _TestSender(Actor):
    def __init__(self, receiver_id):
        self.receiver_id = receiver_id

    def on_start(self, id, out):
        out.send(self.receiver_id, 42)
        out.send(self.receiver_id, 43)
        return ()

    def on_msg(self, id, state, src, msg, out):
        return state + ((src, msg),)


class _TestReceiver(Actor):
    def on_start(self, id, out):
        return ()

    def on_msg(self, id, state, src, msg, out):
        return state + ((src, msg),)


def _orl_model():
    def received(state):
        return [m for _, m in state.actor_states[1].wrapped_state]

    return (
        ActorModel(None, None)
        .actor(OrderedReliableLink(_TestSender(Id(1))))
        .actor(OrderedReliableLink(_TestReceiver()))
        .init_network_(Network.new_unordered_duplicating())
        .lossy_network(True)
        .property(
            Expectation.ALWAYS,
            "no redelivery",
            lambda m, s: received(s).count(42) < 2 and received(s).count(43) < 2,
        )
        .property(
            Expectation.ALWAYS,
            "ordered",
            lambda m, s: received(s) == sorted(received(s)),
        )
        .property(
            Expectation.SOMETIMES,
            "delivered",
            lambda m, s: s.actor_states[1].wrapped_state
            == ((Id(0), 42), (Id(0), 43)),
        )
        .within_boundary_(lambda c, s: len(s.network) < 4)
    )


def test_orl_messages_not_delivered_twice():
    _orl_model().checker().spawn_bfs().join().assert_no_discovery("no redelivery")


def test_orl_messages_delivered_in_order():
    _orl_model().checker().spawn_bfs().join().assert_no_discovery("ordered")


def test_orl_messages_eventually_delivered():
    checker = _orl_model().checker().spawn_bfs().join()
    checker.assert_discovery(
        "delivered",
        [
            Deliver(src=Id(0), dst=Id(1), msg=("deliver", 1, 42)),
            Deliver(src=Id(0), dst=Id(1), msg=("deliver", 2, 43)),
        ],
    )
