"""The bench emission pipeline (bench.py) — the driver artifact's contract.

BENCH r01–r03 all failed to land a TPU number because of emission
mechanics; r04 failed because the final line outgrew the driver's ~2KB
stdout-tail capture window.  These pin the round-5 contract: every printed
line is a complete, parseable result for everything known so far; every
line stays under ``MAX_LINE_BYTES``; later lines supersede earlier ones;
a dead tunnel degrades to the last chip-validated number (``fresh:
false``) instead of 0; salvage recovers the last milestone a killed child
persisted; ``BENCH_VALIDATED.json`` is rewritten only by full validated
runs (never by prefix runs or partial/errored phases).
"""

import importlib.util
import json
import os

import pytest

_BENCH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py"
)


@pytest.fixture
def bench_env(tmp_path, monkeypatch):
    """Isolate bench.py from the repo's real BENCH_VALIDATED.json and
    docs/bench-last-details.json (a bare import must never clobber the
    shipping artifacts with test fixture data)."""
    monkeypatch.setenv(
        "BENCH_VALIDATED_FILE", str(tmp_path / "VALIDATED.json")
    )
    monkeypatch.setenv("BENCH_DETAILS_FILE", str(tmp_path / "details.json"))
    monkeypatch.delenv("BENCH_TPU_TARGET", raising=False)
    return tmp_path


def _load_bench():
    """Fresh module instance per test (emit keeps cumulative state)."""
    spec = importlib.util.spec_from_file_location("bench_under_test", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _lines(capsys):
    return [
        json.loads(l)
        for l in capsys.readouterr().out.strip().splitlines()
        if l.strip()
    ]


def test_every_emit_is_a_complete_parseable_line(bench_env, capsys):
    b = _load_bench()
    b.emit(cpu_paxos3_states_per_sec=8000.0)
    b.emit(tpu_paxos3_states_per_sec=240_000.0)
    out = _lines(capsys)
    assert len(out) == 2
    # line 1 is already a valid final answer (value 0: nothing validated
    # is stored in this isolated env and no TPU number has landed)
    assert out[0]["value"] == 0.0 and out[0]["unit"] == "states/sec"
    # line 2 supersedes: value + vs_baseline recomputed from all extras
    assert out[1]["value"] == 240_000.0
    assert out[1]["vs_baseline"] == 30.0
    assert out[1]["fresh"] is True
    assert out[1]["cpu_baseline_states_per_sec"] == 8000.0


def test_emit_clear_removes_stale_error(bench_env, capsys):
    b = _load_bench()
    b.emit(error="TPU phase stuck", cpu_paxos3_states_per_sec=8000.0)
    b.emit(_clear=("error",), tpu_paxos3_states_per_sec=160_000.0)
    out = _lines(capsys)
    assert "error" in out[0]
    assert "error" not in out[1]  # a successful retry must drop the error
    assert out[1]["vs_baseline"] == 20.0


def test_perf_regression_guard_flags_fresh_slowdowns(bench_env, capsys):
    """ADVICE item 8: a FRESH run whose per-config states/s fall below
    REGRESS_TOLERANCE x the stored validated history emits a
    ``regressed: [...]`` entry naming the config, both rates, and the
    ratio; configs at/above tolerance (and configs the baseline never
    validated) stay out."""
    b = _load_bench()
    b.VALIDATED.update({
        "tpu_paxos3_states_per_sec": 266_699.0,
        "tpu_2pc7_states_per_sec": 100_000.0,
        "validated_at": "2025-01-01T00:00:00Z",
    })
    b.emit(
        tpu_paxos3_states_per_sec=100_000.0,  # 0.375x: regression
        tpu_2pc7_states_per_sec=99_000.0,  # 0.99x: within tolerance
        tpu_2pc4_states_per_sec=50.0,  # never validated: cannot regress
    )
    line = _lines(capsys)[-1]
    assert line["fresh"] is True
    (entry,) = line["regressed"]
    assert entry["config"] == "tpu_paxos3_states_per_sec"
    assert entry["run"] == 100_000.0
    assert entry["baseline"] == 266_699.0
    assert entry["ratio"] == round(100_000.0 / 266_699.0, 3)
    details = json.load(open(os.environ["BENCH_DETAILS_FILE"]))
    assert details["regressed"] == [entry]


def test_perf_regression_guard_never_trips_on_stale_runs(bench_env, capsys):
    """The guard compares MEASUREMENTS: a dead-tunnel run that only
    replays the validated number (fresh: false, value 0.0) emits no
    ``regressed`` field at all — a carried number cannot regress
    against itself."""
    b = _load_bench()
    b.VALIDATED.update({
        "tpu_paxos3_states_per_sec": 266_699.0,
        "validated_at": "2025-01-01T00:00:00Z",
    })
    b.emit(cpu_paxos3_states_per_sec=8000.0)  # no fresh TPU number
    line = _lines(capsys)[-1]
    assert line["fresh"] is False and line["value"] == 0.0
    assert "regressed" not in line
    details = json.load(open(os.environ["BENCH_DETAILS_FILE"]))
    assert "regressed" not in details


def test_perf_regression_guard_clean_run_emits_empty_list(bench_env, capsys):
    """A fresh run at/above tolerance still carries the field — an empty
    list says the guard RAN and found nothing, distinct from a stale
    run where it never ran."""
    b = _load_bench()
    b.VALIDATED.update({
        "tpu_paxos3_states_per_sec": 100_000.0,
        "validated_at": "2025-01-01T00:00:00Z",
    })
    b.emit(tpu_paxos3_states_per_sec=99_000.0)
    line = _lines(capsys)[-1]
    assert line["fresh"] is True
    assert line["regressed"] == []


def test_emit_prefers_winning_insert_path(bench_env, capsys):
    b = _load_bench()
    b.emit(
        cpu_paxos3_states_per_sec=1000.0,
        tpu_paxos3_states_per_sec=2000.0,
        tpu_paxos3_sec=100.0,
        tpu_paxos3_pallas_states_per_sec=3000.0,
        tpu_paxos3_pallas_sec=66.7,
    )
    (line,) = _lines(capsys)
    assert line["value"] == 3000.0  # best path wins
    assert line["insert_path"] == "pallas"
    # the fields describing the run stay mutually consistent: when the
    # pallas path wins, rate AND wall-time come from the pallas run
    assert line["tpu_paxos3_states_per_sec"] == 3000.0
    assert line["tpu_paxos3_sec"] == 66.7
    b.emit(tpu_paxos3_pallas_states_per_sec=1500.0)
    (line2,) = _lines(capsys)
    assert line2["value"] == 2000.0
    assert line2["insert_path"] == "xla-scatter"
    assert line2["tpu_paxos3_sec"] == 100.0


def test_emit_suppresses_duplicate_lines(bench_env, capsys):
    b = _load_bench()
    b.emit(cpu_paxos3_states_per_sec=8000.0)
    b.emit(cpu_paxos3_states_per_sec=8000.0)  # no change -> no line
    assert len(_lines(capsys)) == 1


def test_every_line_is_small(bench_env, capsys):
    """The driver stores only a ~2KB tail of stdout (the BENCH_r04
    failure): every line must stay under MAX_LINE_BYTES with the four
    contract keys intact, no matter how much detail accumulates."""
    b = _load_bench()
    big = {f"tpu_cfg{i}_states_per_sec": float(i) * 7 for i in range(200)}
    b.emit(
        cpu_paxos3_states_per_sec=8000.0,
        tpu_paxos3_states_per_sec=240_000.0,
        tpu_attempts=[{"kind": "full", "error": "x" * 100}] * 20,
        **big,
    )
    raw = capsys.readouterr().out.strip().splitlines()
    assert raw
    for line in raw:
        assert len(line.encode()) <= b.MAX_LINE_BYTES
        d = json.loads(line)
        for key in ("metric", "value", "unit", "vs_baseline"):
            assert key in d
    # the bulk went to the details side file instead
    details = json.load(open(os.environ["BENCH_DETAILS_FILE"]))
    assert details["tpu_cfg199_states_per_sec"] == 199.0 * 7


def test_dead_tunnel_stale_never_headlines(bench_env, capsys):
    """No fresh TPU number + a stored chip-validated result: the stored
    number rides ONLY the explicit STALE annotation — value stays 0.0 with
    fresh=false, so a dead-tunnel round can never masquerade as a
    measurement (the round-5 silent carry-forward: BENCH_r05.json headlined
    round 4's 266.7k while the chip never ran)."""
    validated = {
        "tpu_paxos3_states_per_sec": 266699.0,
        "tpu_paxos3_unique": 1_194_428,
        "tpu_paxos3_sec": 9.076,
        "validated_at": "2026-07-31T03:30:00Z",
        "cpu_paxos3_uncontended_states_per_sec": 8188.4,
    }
    with open(os.environ["BENCH_VALIDATED_FILE"], "w") as f:
        json.dump(validated, f)
    b = _load_bench()
    b.emit(cpu_paxos3_states_per_sec=4000.0, cpu_load1=2.5,
           error="TPU phase stuck in backend init for 120s")
    (line,) = _lines(capsys)
    assert line["value"] == 0.0
    assert line["fresh"] is False
    assert line["vs_baseline"] == 0.0
    # the stale number appears only inside the explicit annotation
    assert line["stale"].startswith(
        "STALE (fresh=false, carried from 2026-07-31T03:30:00Z)"
    )
    assert "266699.0 states/s" in line["stale"]
    assert line["validated_at"] == "2026-07-31T03:30:00Z"
    assert line.get("tpu_paxos3_states_per_sec") is None
    assert "error" in line
    # contended same-run CPU (4000 < 80% of stored 8188, load 2.5): the
    # stored uncontended baseline is used and the choice is disclosed
    assert line["cpu_baseline_states_per_sec"] == 8188.4
    assert line["cpu_baseline_src"].startswith("stored-uncontended")


def test_fresh_number_clears_stale_annotation(bench_env, capsys):
    """Once a fresh chip number lands, the headline is real again and the
    STALE annotation disappears."""
    with open(os.environ["BENCH_VALIDATED_FILE"], "w") as f:
        json.dump({"tpu_paxos3_states_per_sec": 266699.0,
                   "validated_at": "2026-07-31T03:30:00Z"}, f)
    b = _load_bench()
    b.emit(cpu_paxos3_states_per_sec=8000.0)
    b.emit(tpu_paxos3_states_per_sec=320_000.0)
    first, second = _lines(capsys)
    assert first["value"] == 0.0 and "STALE" in first["stale"]
    assert second["value"] == 320_000.0 and second["fresh"] is True
    assert "stale" not in second


def test_idle_same_run_baseline_replaces_stored(bench_env, capsys):
    """An idle-box (load1 < 0.7) same-run CPU rate is the new truth even
    when LOWER than the stored rate — no one-way ratchet."""
    with open(os.environ["BENCH_VALIDATED_FILE"], "w") as f:
        json.dump({"cpu_paxos3_uncontended_states_per_sec": 9999.0}, f)
    b = _load_bench()
    b.emit(cpu_paxos3_states_per_sec=7000.0, cpu_load1=0.1,
           tpu_paxos3_states_per_sec=210_000.0,
           tpu_paxos3_unique=1_194_428,
           tpu_devices=["d0"],
           tpu_paxos2_discoveries=["value chosen"],
           tpu_2pc5_discoveries=["abort agreement", "commit agreement"])
    (line,) = _lines(capsys)
    assert line["cpu_baseline_states_per_sec"] == 7000.0
    assert line["cpu_baseline_src"] == "same-run"
    assert line["vs_baseline"] == 30.0
    b.record_validated()
    doc = json.load(open(os.environ["BENCH_VALIDATED_FILE"]))
    assert doc["cpu_paxos3_uncontended_states_per_sec"] == 7000.0
    assert doc["tpu_paxos3_states_per_sec"] == 210_000.0
    assert doc["validated_at"]


def test_record_validated_skips_prefix_runs(bench_env, monkeypatch):
    """BENCH_TPU_TARGET prefix rates are overhead-dominated and must not
    overwrite the stored full-enumeration number."""
    monkeypatch.setenv("BENCH_TPU_TARGET", "50000")
    b = _load_bench()
    b.emit(tpu_paxos3_states_per_sec=50_000.0,
           tpu_paxos2_discoveries=["value chosen"],
           tpu_2pc5_discoveries=["abort agreement"])
    b.record_validated()
    assert not os.path.exists(os.environ["BENCH_VALIDATED_FILE"])


def test_record_validated_requires_device_parity_evidence(bench_env):
    """A salvaged partial (killed before the 2pc5 device gate) or an
    errored phase carries a real number but must not persist as
    'parity gates passed'."""
    b = _load_bench()
    b.emit(tpu_paxos3_states_per_sec=300_000.0,
           tpu_paxos2_discoveries=["value chosen"])  # no 2pc5 gate ran
    b.record_validated()
    assert not os.path.exists(os.environ["BENCH_VALIDATED_FILE"])
    b2 = _load_bench()
    b2.emit(tpu_paxos3_states_per_sec=300_000.0,
            tpu_paxos2_discoveries=["value chosen"],
            tpu_2pc5_discoveries=["abort agreement"],
            error="backend died after the timed run")
    b2.record_validated()
    assert not os.path.exists(os.environ["BENCH_VALIDATED_FILE"])


def test_salvage_returns_last_parseable_milestone(bench_env, tmp_path):
    b = _load_bench()
    stage = tmp_path / "stages"
    stage.write_text(
        json.dumps({"tpu_devices": ["d0"]})
        + "\n"
        + json.dumps({"tpu_devices": ["d0"], "tpu_paxos3_states_per_sec": 9.0})
        + "\n"
        + '{"truncated by kill...'  # partial final write survives
    )
    assert b._salvage(str(stage))["tpu_paxos3_states_per_sec"] == 9.0


def test_salvage_missing_or_empty_file(bench_env, tmp_path):
    b = _load_bench()
    assert b._salvage(str(tmp_path / "absent")) == {}
    empty = tmp_path / "empty"
    empty.write_text("")
    assert b._salvage(str(empty)) == {}


def test_driver_parse_of_last_line(bench_env, capsys):
    """The driver's contract: parse the LAST stdout line as the result."""
    b = _load_bench()
    b.emit(cpu_paxos3_states_per_sec=8000.0)
    b.emit(error="first attempt hung")
    b.emit(_clear=("error",), tpu_paxos3_states_per_sec=320_000.0,
           tpu_paxos3_unique=1_194_428)
    last = _lines(capsys)[-1]
    assert last["value"] == 320_000.0
    assert last["vs_baseline"] == 40.0
    assert "error" not in last
    assert last["tpu_paxos3_unique"] == 1_194_428


def test_kill_reason_distinguishes_init_compile_and_run(bench_env):
    """The watchdog's headline ``error`` classification: backend-init hang
    vs engine-compile hang vs a genuine run-budget miss are three
    different problems (tunnel / persistent compile cache / budget)."""
    b = _load_bench()
    assert b._kill_reason(True, "", 120, 900) == (
        "stuck in backend init for 120s"
    )
    why = b._kill_reason(False, "compile (paxos3 engine)", 120, 900)
    assert why.startswith("stuck in engine compile/warm-up after 900s")
    assert "paxos3" in why
    why = b._kill_reason(False, "paxos3 timed run done", 120, 900)
    assert why.startswith("timed out after 900s")
    assert "paxos3 timed run done" in why


def test_phase_breakdown_reaches_details_file(bench_env, capsys):
    """The per-phase/per-stage breakdown is a details-file artifact (the
    headline line stays small): emitting it must land it in
    docs/bench-last-details.json verbatim."""
    b = _load_bench()
    stages = {"compile_secs": 1.25, "device_secs": 7.5, "growth_secs": 0.1,
              "wall_secs": 9.0, "host_secs": 0.15}
    phases = {"backend_init_secs": 2.0, "paxos3_warmup_secs": 11.0,
              "paxos3_run_secs": 9.0}
    b.emit(cpu_paxos3_states_per_sec=8000.0,
           tpu_paxos3_states_per_sec=300000.0,
           tpu_paxos3_stages=stages, tpu_phases=phases)
    details = json.load(open(os.environ["BENCH_DETAILS_FILE"]))
    assert details["tpu_paxos3_stages"] == stages
    assert details["tpu_phases"] == phases
    for line in capsys.readouterr().out.strip().splitlines():
        assert len(line.encode()) <= b.MAX_LINE_BYTES


def test_record_validated_persists_stage_breakdown(bench_env):
    b = _load_bench()
    stages = {"compile_secs": 1.0, "device_secs": 7.0, "wall_secs": 9.0,
              "host_secs": 1.0}
    b.emit(cpu_paxos3_states_per_sec=7000.0, cpu_load1=0.1,
           tpu_paxos3_states_per_sec=210000.0,
           tpu_paxos3_stages=stages,
           cpu_baseline_engine="native-cpp-bfs",
           tpu_paxos2_discoveries=["value chosen"],
           tpu_2pc5_discoveries=["abort agreement"])
    b.record_validated()
    doc = json.load(open(os.environ["BENCH_VALIDATED_FILE"]))
    assert doc["tpu_paxos3_stages"] == stages
    assert doc["cpu_baseline_engine"] == "native-cpp-bfs"


def test_ab_table_mode_with_injected_runner(bench_env, capsys):
    """--ab-table: both legs at the same capacity, 2pc10 targeted at
    2pc7's unique volume, ratio on the line, full legs in the side
    file."""
    b = _load_bench()
    calls = []

    def fake_run(rm, target):
        calls.append((rm, target))
        return {"states_per_sec": 1450000.0 if rm == 7 else 866000.0,
                "states": 10, "unique": 296448 if rm == 7 else 296000,
                "sec": 1.0, "occupancy_last": {"load_factor": 0.1},
                "stages": {"device_secs": 1.0}, "growth_events": 0}

    rc = b.ab_table(run_one=fake_run)
    assert rc == 0
    assert calls == [(7, None), (10, 296448)]  # same insert volume
    (line,) = [json.loads(l) for l in
               capsys.readouterr().out.strip().splitlines()]
    assert line["tpu_2pc7_states_per_sec"] == 1450000.0
    assert line["ratio_7_over_10"] == round(1450000.0 / 866000.0, 3)
    assert len(json.dumps(line).encode()) <= b.MAX_LINE_BYTES
    side = os.environ["BENCH_DETAILS_FILE"].replace(
        ".json", "-ab-table.json"
    )
    full = json.load(open(side))
    assert full["tpu_2pc7_ab"]["occupancy_last"] == {"load_factor": 0.1}


def test_ab_table_failure_emits_one_line_rc1(bench_env, capsys):
    b = _load_bench()

    def broken(rm, target):
        raise RuntimeError("tunnel down")

    rc = b.ab_table(run_one=broken)
    assert rc == 1
    (line,) = [json.loads(l) for l in
               capsys.readouterr().out.strip().splitlines()]
    assert "tunnel down" in line["error"]


def test_trend_deltas_cover_every_validated_config(bench_env, capsys):
    """A fresh run's details carry ``trend``: EVERY measured
    tpu_*_states_per_sec with a stored history value and its ratio —
    improvements and regressions alike (``regressed`` stays the
    below-tolerance subset); never-validated configs have no trend
    entry, and stale runs carry no trend at all."""
    b = _load_bench()
    b.VALIDATED.update({
        "tpu_paxos3_states_per_sec": 200_000.0,
        "tpu_2pc7_states_per_sec": 100_000.0,
        "validated_at": "2025-01-01T00:00:00Z",
    })
    b.emit(
        tpu_paxos3_states_per_sec=100_000.0,  # 0.5x: regression + trend
        tpu_2pc7_states_per_sec=150_000.0,  # 1.5x: improvement, trend only
        tpu_2pc4_states_per_sec=50.0,  # never validated: no trend
    )
    details = json.load(open(os.environ["BENCH_DETAILS_FILE"]))
    trend = {e["config"]: e for e in details["trend"]}
    assert set(trend) == {
        "tpu_paxos3_states_per_sec", "tpu_2pc7_states_per_sec"
    }
    assert trend["tpu_2pc7_states_per_sec"]["ratio"] == 1.5
    assert [e["config"] for e in details["regressed"]] == [
        "tpu_paxos3_states_per_sec"
    ]
    # trend is a details-artifact field, never a headline-line key
    assert "trend" not in _lines(capsys)[-1]
    # stale runs: no trend (nothing was measured)
    b2 = _load_bench()
    b2.VALIDATED.update({
        "tpu_paxos3_states_per_sec": 200_000.0,
        "validated_at": "2025-01-01T00:00:00Z",
    })
    b2.emit(cpu_paxos3_states_per_sec=8000.0)
    details = json.load(open(os.environ["BENCH_DETAILS_FILE"]))
    assert "trend" not in details


def test_record_validated_embeds_the_run_report(bench_env):
    """A validated full run persists its embedded tpu_paxos3_report into
    BENCH_VALIDATED.json — the baseline half of ``regress.py --diff``
    (pre-registry baselines simply lack the key)."""
    b = _load_bench()
    rep = {"v": 1, "model": "PaxosModel",
           "config": {"key": "k"}, "totals": {"unique": 42}}
    b.EXTRAS.update({
        "tpu_paxos3_states_per_sec": 250_000.0,
        "tpu_paxos2_discoveries": ["value chosen"],
        "tpu_2pc5_discoveries": ["abort agreement"],
        "tpu_paxos3_report": rep,
    })
    b.record_validated()
    doc = json.load(open(os.environ["BENCH_VALIDATED_FILE"]))
    assert doc["tpu_paxos3_report"] == rep


def test_main_consumes_run_ledger_env_no_double_record(
    bench_env, monkeypatch
):
    """main() CONSUMES STATERIGHT_TPU_RUN_DIR into RUN_LEDGER_DIR (every
    process: parent/child/probe/ab-table): legs register explicitly and
    leg-tagged via _register, and with the env knob gone the checkers'
    join-time auto-record cannot double-archive the same run_id (which
    would also pollute the index with untagged warm-up/CPU records)."""
    import sys

    from stateright_tpu.models.two_phase_commit import TwoPhaseSys
    from stateright_tpu.telemetry.registry import RunRegistry

    ledger = str(bench_env / "ledger")
    monkeypatch.setenv("STATERIGHT_TPU_RUN_DIR", ledger)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--tpu-probe"])
    b = _load_bench()
    assert b.main() == 0  # the probe path runs main()'s consumption
    assert b.RUN_LEDGER_DIR == ledger
    assert "STATERIGHT_TPU_RUN_DIR" not in os.environ
    # a post-consumption checker run does NOT auto-record...
    c = TwoPhaseSys(2).checker().spawn_tpu(
        sync=True, capacity=1 << 11, batch=64
    )
    c.join()
    assert RunRegistry(ledger).index() == []
    # ...and the explicit leg registration is the single, tagged record
    RunRegistry(b.RUN_LEDGER_DIR).record(c, leg="2pc2")
    idx = RunRegistry(ledger).index()
    assert [(r["run_id"], r.get("leg")) for r in idx] == [(c.run_id, "2pc2")]
