"""The bench emission pipeline (bench.py) — the driver artifact's contract.

BENCH r01–r03 all failed to land a TPU number, twice because of emission
mechanics rather than the device (see docs/axon-init-hang.md).  These pin
the round-4 contract: every printed line is a complete, parseable result
for everything known so far; later lines supersede earlier ones; salvage
recovers the last milestone a killed child persisted.
"""

import importlib.util
import json
import os

_BENCH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py"
)


def _load_bench():
    """Fresh module instance per test (emit keeps cumulative state)."""
    spec = importlib.util.spec_from_file_location("bench_under_test", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _lines(capsys):
    return [
        json.loads(l)
        for l in capsys.readouterr().out.strip().splitlines()
        if l.strip()
    ]


def test_every_emit_is_a_complete_parseable_line(capsys):
    b = _load_bench()
    b.emit(cpu_paxos3_states_per_sec=8000.0)
    b.emit(tpu_paxos3_states_per_sec=240_000.0)
    out = _lines(capsys)
    assert len(out) == 2
    # line 1 is already a valid final answer (value 0 until TPU lands)
    assert out[0]["value"] == 0.0 and out[0]["unit"] == "states/sec"
    # line 2 supersedes: value + vs_baseline recomputed from all extras
    assert out[1]["value"] == 240_000.0
    assert out[1]["vs_baseline"] == 30.0
    assert out[1]["cpu_paxos3_states_per_sec"] == 8000.0


def test_emit_clear_removes_stale_error(capsys):
    b = _load_bench()
    b.emit(error="TPU phase stuck", cpu_paxos3_states_per_sec=8000.0)
    b.emit(_clear=("error",), tpu_paxos3_states_per_sec=160_000.0)
    out = _lines(capsys)
    assert "error" in out[0]
    assert "error" not in out[1]  # a successful retry must drop the error
    assert out[1]["vs_baseline"] == 20.0


def test_emit_prefers_winning_insert_path(capsys):
    b = _load_bench()
    b.emit(
        cpu_paxos3_states_per_sec=1000.0,
        tpu_paxos3_states_per_sec=2000.0,
        tpu_paxos3_pallas_states_per_sec=3000.0,
    )
    (line,) = _lines(capsys)
    assert line["value"] == 3000.0  # best path wins
    assert line["insert_path"] == "pallas"
    b.emit(tpu_paxos3_pallas_states_per_sec=1500.0)
    (line2,) = _lines(capsys)
    assert line2["value"] == 2000.0
    assert line2["insert_path"] == "xla-scatter"


def test_emit_suppresses_duplicate_lines(capsys):
    b = _load_bench()
    b.emit(cpu_paxos3_states_per_sec=8000.0)
    b.emit(cpu_paxos3_states_per_sec=8000.0)  # no change -> no line
    assert len(_lines(capsys)) == 1


def test_salvage_returns_last_parseable_milestone(tmp_path):
    b = _load_bench()
    stage = tmp_path / "stages"
    stage.write_text(
        json.dumps({"tpu_devices": ["d0"]})
        + "\n"
        + json.dumps({"tpu_devices": ["d0"], "tpu_paxos3_states_per_sec": 9.0})
        + "\n"
        + '{"truncated by kill...'  # partial final write survives
    )
    assert b._salvage(str(stage))["tpu_paxos3_states_per_sec"] == 9.0


def test_salvage_missing_or_empty_file(tmp_path):
    b = _load_bench()
    assert b._salvage(str(tmp_path / "absent")) == {}
    empty = tmp_path / "empty"
    empty.write_text("")
    assert b._salvage(str(empty)) == {}


def test_driver_parse_of_last_line(capsys):
    """The driver's contract: parse the LAST stdout line as the result."""
    b = _load_bench()
    b.emit(cpu_paxos3_states_per_sec=8000.0)
    b.emit(error="first attempt hung")
    b.emit(_clear=("error",), tpu_paxos3_states_per_sec=320_000.0,
           tpu_paxos3_unique=1_194_428)
    last = _lines(capsys)[-1]
    assert last["value"] == 320_000.0
    assert last["vs_baseline"] == 40.0
    assert "error" not in last
    assert last["tpu_paxos3_unique"] == 1_194_428
