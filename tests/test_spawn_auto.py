"""``spawn_auto()`` — engine selection by measured space size.

The small-space footgun (bench r4): the device engine's fixed per-run
cost dominates below ~1e5 states, where CPU BFS is 8-100x faster
(lin-reg-2's 544-state space: 927 states/s on a v5e vs 7.4k/s on one CPU
core).  ``spawn_auto`` runs a time-bounded CPU probe first; a space that
exhausts within the budget returns the finished CPU checker, a bigger
one escalates to the device engine.  No reference counterpart (the
reference has one strategy family); the CLI shape being served is
``examples/paxos.rs:314-395``'s check commands.
"""

import pytest

from stateright_tpu.checker.bfs import BfsChecker
from stateright_tpu.checker.dfs import DfsChecker
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.parallel.wavefront import TpuChecker


def test_small_space_finishes_on_cpu():
    """A space the CPU probe exhausts is answered by the probe itself —
    the device is never touched (no compile cost, no tunnel)."""
    c = TwoPhaseSys(3).checker().spawn_auto()
    assert isinstance(c, BfsChecker)
    assert c.is_done() and not c.timed_out
    assert c.unique_state_count() == 288  # examples/2pc.rs:128
    assert set(c.discoveries()) == {"abort agreement", "commit agreement"}


def test_large_space_escalates_to_device_engine():
    """A probe that times out means the space outgrew its CPU budget:
    the check restarts on the device engine and completes there."""
    c = (
        TwoPhaseSys(5)
        .checker()
        .spawn_auto(probe_secs=0.01, sync=True, capacity=1 << 17)
    )
    assert isinstance(c, TpuChecker)
    assert c.unique_state_count() == 8832  # examples/2pc.rs:133
    assert set(c.discoveries()) == {"abort agreement", "commit agreement"}


def test_no_tensor_twin_checks_on_cpu():
    """Object-form-only models (no tensor twin) go straight to CPU."""
    from stateright_tpu.core import Model, Property

    class Toggle(Model):
        def init_states(self):
            return [0]

        def actions(self, state):
            return ["flip"]

        def next_state(self, state, action):
            return 1 - state

        def properties(self):
            return [Property.sometimes("one", lambda m, s: s == 1)]

    c = Toggle().checker().spawn_auto()
    assert isinstance(c, BfsChecker)
    assert c.unique_state_count() == 2
    assert set(c.discoveries()) == {"one"}


def test_visitor_small_space_finishes_on_thread_probe():
    """Visitors: the device engines are out, but the probe still runs —
    a small space is answered by the finished thread checker without
    paying mp fork/queue setup."""
    seen = []
    c = (
        TwoPhaseSys(3)
        .checker()
        .visitor(lambda model, path: seen.append(path.final_state()))
        .spawn_auto()
    )
    assert isinstance(c, BfsChecker)
    c.join()
    assert len(seen) == 288


@pytest.mark.medium
def test_visitor_large_space_escalates_to_mp(monkeypatch):
    """A visitor run whose space outgrows the probe escalates to the
    process-parallel BFS (multi-core + visitor via replay), never to a
    device engine."""
    import os

    from stateright_tpu.checker.mp import MpBfsChecker

    monkeypatch.setattr(os, "cpu_count", lambda: 4)
    seen = []
    c = (
        TwoPhaseSys(5)
        .checker()
        .visitor(lambda model, path: seen.append(1))
        .spawn_auto(probe_secs=0.01)
    )
    assert isinstance(c, MpBfsChecker)
    assert c.unique_state_count() == 8832
    assert len(seen) == 8832


@pytest.mark.medium
def test_visitor_escalation_defers_visits_to_run_end(monkeypatch):
    """ADVICE item 6 — the visitor-timing hole, pinned: when a visitor
    run escalates to mp-BFS, the callbacks are DEFERRED TO RUN END.
    Worker processes record per-round visit orders (fingerprints only —
    callbacks cannot cross the fork boundary) and the PARENT replays
    them round-major through the visitor only after every worker joined
    and the parent map merged, so each callback sees a complete,
    reconstructable path and the replay is a valid BFS level order.
    Callers needing LIVE per-state visits (progress bars, streaming
    consumers) should stay on the thread engine — spawn_bfs() — where
    visits interleave with exploration; this is the documented
    behavior, not a bug (docs/telemetry.md "Visitors and engines")."""
    import os

    from stateright_tpu.checker import mp as mp_mod

    monkeypatch.setattr(os, "cpu_count", lambda: 4)
    at_replay = {}
    orig = mp_mod.MpBfsChecker._replay_visits

    def spy(self, visitor, results):
        # the moment callbacks start: the merged space must already be
        # COMPLETE (deferred-to-run-end, not live)
        at_replay["unique"] = len(self._generated)
        at_replay["count"] = self._count
        return orig(self, visitor, results)

    monkeypatch.setattr(mp_mod.MpBfsChecker, "_replay_visits", spy)
    depths = []
    c = (
        TwoPhaseSys(5)
        .checker()
        .visitor(lambda model, path: depths.append(len(path.into_vec())))
        .spawn_auto(probe_secs=0.01)
    )
    assert isinstance(c, mp_mod.MpBfsChecker)
    # visits began only after the full space was merged...
    assert at_replay["unique"] == 8832
    # ...fired exactly once per unique state...
    assert len(depths) == 8832
    # ...in round-major replay order = a valid BFS level order
    assert depths == sorted(depths)


def test_symmetry_probe_uses_dfs():
    """With ``symmetry()`` the CPU probe is DFS (the host engine that
    supports representative dedup, as in the reference where symmetry is
    DFS-only) and pins the reduced count."""
    c = TwoPhaseSys(5).checker().symmetry().spawn_auto(probe_secs=30.0)
    assert isinstance(c, DfsChecker)
    assert c.unique_state_count() == 665  # examples/2pc.rs:138


def test_tiny_user_timeout_stays_on_cpu():
    """A user timeout within the probe budget means the whole run fits in
    the probe: no point paying device setup for a run this short."""
    c = TwoPhaseSys(3).checker().timeout(0.5).spawn_auto(probe_secs=2.0)
    assert isinstance(c, BfsChecker)
    c.join()
    assert c.unique_state_count() == 288


def test_check_auto_cli_verb(capsys):
    """The ``check-auto`` CLI verb runs end-to-end on every model that
    wires it, including argument passing (the single-copy NETWORK
    argument regression class)."""
    from stateright_tpu.models import (
        single_copy_register,
        two_phase_commit,
        write_once_register,
    )

    two_phase_commit.main(["check-auto", "3"])
    out = capsys.readouterr().out
    assert "auto engine selection" in out
    assert "unique=288" in out

    single_copy_register.main(["check-auto", "2", "ordered"])
    out = capsys.readouterr().out
    assert "Done." in out  # the ordered network parsed and ran

    write_once_register.main(["check-auto", "2", "1"])
    out = capsys.readouterr().out
    assert "unique=71" in out


def test_timed_out_flag_distinguishes_deadline_from_completion():
    """``timed_out`` is the probe's decision signal: set only by the
    deadline, not by finishing or reaching target_states."""
    done = TwoPhaseSys(3).checker().spawn_bfs().join()
    assert not done.timed_out
    capped = (
        TwoPhaseSys(5).checker().target_states(100).spawn_bfs().join()
    )
    assert not capped.timed_out
    cut = TwoPhaseSys(6).checker().timeout(0.01).spawn_bfs().join()
    assert cut.timed_out
    assert cut.unique_state_count() < 30_000  # stopped well short
