"""The quick-start examples are executable specs (reference doctest parity:
``src/lib.rs:40-116`` sliding puzzle, ``src/actor.rs:11-78`` logical
clocks)."""

from stateright_tpu.models.quickstart import (
    GOAL,
    SlidingPuzzle,
    clock_counterexample,
    clock_model,
    solve_puzzle,
)


def test_puzzle_solved_shortest():
    path = solve_puzzle()
    # BFS discovery is a shortest solve; the reference's pinned solution is
    # 4 moves (lib.rs:96-116)
    assert path.actions() == ["down", "right", "down", "right"]
    assert path.final_state() == GOAL


def test_puzzle_assert_discovery():
    checker = SlidingPuzzle().checker().spawn_bfs().join()
    checker.assert_discovery("solved", ["down", "right", "down", "right"])


def test_clock_counterexample():
    trace = clock_counterexample()
    # reference pins the 2-delivery counterexample with clocks (2, 3)
    assert len(trace.actions()) == 2
    assert tuple(trace.final_state().actor_states) == (2, 3)


def test_clock_dfs_agrees():
    bfs = clock_model().checker().spawn_bfs().join()
    dfs = clock_model().checker().spawn_dfs().join()
    assert (
        "less than max" in bfs.discoveries()
        and "less than max" in dfs.discoveries()
    )


def test_fizzbuzz_served_model():
    """The reference's serve doctest (``checker.rs:60-97``) as a live
    server: a browsable bounded sequence with its reach-the-bound witness."""
    from stateright_tpu.models.quickstart import FizzBuzz, serve_fizzbuzz

    server = serve_fizzbuzz("localhost:0", block=False)
    try:
        server.checker.join()
        import json
        import urllib.request

        with urllib.request.urlopen(
            f"http://{server.addr}/.status"
        ) as r:
            s = json.loads(r.read())
        assert s["done"] is True
        assert s["unique_state_count"] == 31  # prefixes of length 0..30
        assert dict(
            (name, disc) for _, name, disc in s["properties"]
        )["reaches the bound"] is not None
    finally:
        server.shutdown()
    # the checker surface works standalone too
    c = FizzBuzz(30).checker().spawn_bfs().join()
    assert c.unique_state_count() == 31
    c.assert_properties()
