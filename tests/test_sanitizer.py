"""The soundness sanitizer (``stateright_tpu/analysis/interval.py`` +
``sanitizer.py``) and checked execution mode: fault-injection models caught
BOTH statically (pinned JX2xx rule ids) and dynamically (checkify error
naming the row), the interval pass proving shipped twins' sites in range,
the checked-off bit-identity contract, and the CLI/Explorer/report
surfaces."""

from __future__ import annotations

import http.client
import json
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from stateright_tpu import Model, Property
from stateright_tpu.analysis import (
    AuditError,
    CheckedExecutionError,
    Severity,
    audit_model,
)
from stateright_tpu.analysis.interval import IVal
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.parallel.tensor_model import (
    BitPacker,
    RowDomain,
    TensorBackedModel,
    TensorModel,
)

EMPTY = (1 << 64) - 1


# ---------------------------------------------------------------------------
# fault-injection twins (the seeded corrupted models of the satellite task)
# ---------------------------------------------------------------------------


class _FaultBase(TensorModel):
    width = 1
    max_actions = 1

    def __init__(self, model):
        self.model = model

    def init_rows(self):
        return np.zeros((1, 1), np.uint64)

    def encode_state(self, s):
        return (int(s),)

    def decode_state(self, row):
        return int(row[0])

    def property_masks(self, rows):
        return jnp.ones((rows.shape[0], 1), bool)


class OOBGatherTwin(_FaultBase):
    """A 3-bit counter field indexes a 4-entry table: values 4..7 silently
    clamp on TPU — dropped successors, under-explored space (JX201)."""

    packer = BitPacker([("count", 3)])

    def __init__(self, model):
        super().__init__(model)
        self.pk = OOBGatherTwin.packer

    def step_rows(self, rows):
        c = self.pk.get(rows, "count").astype(jnp.int32)
        tbl = jnp.asarray([1, 2, 3, 4], jnp.uint64)
        nxt = tbl[c]  # OOB for c in 4..7
        succ = rows.at[..., 0].set(nxt)[:, None, :]
        valid = (c < 7)[:, None]
        return succ, valid


class OOBScatterTwin(_FaultBase):
    """A 3-bit field used as a dynamic-update start into a 4-wide vector:
    the write silently clamps/misplaces (JX202, the buckets.insert class)."""

    def __init__(self, model):
        super().__init__(model)
        self.pk = BitPacker([("slot", 3)])

    def step_rows(self, rows):
        s = self.pk.get(rows, "slot").astype(jnp.int32)
        vec = jnp.zeros((rows.shape[0], 4), jnp.uint64)
        upd = jnp.ones((rows.shape[0], 1), jnp.uint64)
        marked = jax.lax.dynamic_update_slice(vec, upd, (jnp.int32(0), s[0]))
        succ = rows.at[..., 0].set(marked[:, 0] + rows[..., 0])[:, None, :]
        valid = (s < 7)[:, None]
        return succ, valid


class OverflowCounterTwin(_FaultBase):
    """count + 5 into a 2-bit field: EVERY input overflows the declared
    width before the mask — the packed counter wraps (JX203 warning)."""

    def __init__(self, model):
        super().__init__(model)
        self.pk = BitPacker([("count", 2)])

    def step_rows(self, rows):
        c = self.pk.get(rows, "count")
        succ = self.pk.set(rows, "count", c + jnp.uint64(5))[:, None, :]
        valid = (c < jnp.uint64(3))[:, None]
        return succ, valid


class EmptyReadTwin(_FaultBase):
    """Gathers from a table whose tail is EMPTY padding, then does
    arithmetic on the result with no EMPTY comparison (JX204)."""

    def step_rows(self, rows):
        tbl = jnp.asarray([1, 2, EMPTY, EMPTY], jnp.uint64)
        v = tbl[(rows[..., 0] & jnp.uint64(3)).astype(jnp.int32)]
        succ = rows.at[..., 0].set(v + jnp.uint64(1))[:, None, :]
        valid = (rows[..., 0] < jnp.uint64(3))[:, None]
        return succ, valid


class DeadBranchTwin(_FaultBase):
    """A 3-bit field compared against 8: the predicate is constantly true,
    the other branch is dead (JX205, model smell)."""

    def __init__(self, model):
        super().__init__(model)
        self.pk = BitPacker([("v", 3)])

    def step_rows(self, rows):
        v = self.pk.get(rows, "v")
        nxt = jnp.where(v < jnp.uint64(8), v + jnp.uint64(1),
                        jnp.uint64(99))  # dead branch
        succ = self.pk.set(rows, "v", nxt & jnp.uint64(7))[:, None, :]
        valid = (v < jnp.uint64(1))[:, None]
        return succ, valid


class _HostModel(TensorBackedModel, Model):
    twin_cls = _FaultBase

    def tensor_model(self):
        return self.twin_cls(self)

    def init_states(self):
        return [0]

    def actions(self, s):
        return [0] if s < 7 else []

    def next_state(self, s, a):
        return s + 1

    def properties(self):
        return [Property.always("ok", lambda m, s: True)]


def _host_model(twin_cls):
    class M(_HostModel):
        pass

    M.__name__ = M.__qualname__ = f"Host_{twin_cls.__name__}"
    M.twin_cls = twin_cls
    return M()


# ---------------------------------------------------------------------------
# static: pinned rule ids per fault class
# ---------------------------------------------------------------------------


def _pinned(twin_cls, rule_id, severity):
    report = audit_model(_host_model(twin_cls))
    hits = [f for f in report.findings if f.rule_id == rule_id]
    assert hits, report.format()
    assert all(f.severity == severity for f in hits), report.format()
    return report, hits


def test_oob_gather_pins_jx201_error():
    report, hits = _pinned(OOBGatherTwin, "JX201", Severity.ERROR)
    # the message names the learned interval and the escaped axis
    assert "[0, 7]" in hits[0].message and "axis 4" in hits[0].message
    assert not report.ok


def test_oob_update_pins_jx202_error():
    report, _ = _pinned(OOBScatterTwin, "JX202", Severity.ERROR)
    assert not report.ok


def test_overflowing_counter_pins_jx203_warning():
    report, hits = _pinned(OverflowCounterTwin, "JX203", Severity.WARNING)
    assert "[5, 8]" in hits[0].message  # every input escapes mask 0x3
    assert report.ok  # warning severity: does not abort spawns


def test_empty_sentinel_read_pins_jx204_warning():
    _pinned(EmptyReadTwin, "JX204", Severity.WARNING)


def test_dead_branch_pins_jx205_info():
    _pinned(DeadBranchTwin, "JX205", Severity.INFO)


def test_spawn_preflight_aborts_on_jx201_with_machine_readable_rules():
    """The sanitizer is part of the spawn preflight: a JX201 aborts before
    any device work, and AuditError carries the rule ids machine-readably
    (the CLI exit-path contract)."""
    m = _host_model(OOBGatherTwin)
    with pytest.raises(AuditError, match="JX201") as exc:
        m.checker().spawn_tpu(sync=True, batch=8, capacity=1 << 10)
    assert "JX201" in exc.value.rule_ids


# ---------------------------------------------------------------------------
# static: precision on clean kernels
# ---------------------------------------------------------------------------


def test_2pc_twin_proves_every_site():
    report = audit_model(TwoPhaseSys(3), deep=True)
    s = report.metrics["sanitizer"]
    assert s["clean"] and s["sites"] > 0
    assert s["proved"] == s["sites"] and s["undecided"] == 0
    assert not report.by_rule("JX201") and not report.by_rule("JX202")


def test_compiled_actor_twin_proves_every_site():
    """The compiled actor twin's table gathers (``trans[sc * ne + ecode]``)
    are provable only through the declared RowDomain: state-code field
    bounds + EMPTY-sentinel slot words.  This is the tentpole's precision
    acceptance — compiled models must be PROVED, not undecided."""
    from stateright_tpu.models.dining import dining_model

    report = audit_model(dining_model(3), deep=True)
    s = report.metrics["sanitizer"]
    assert s["seeded"], "compiled twin must declare a row domain"
    assert s["sites"] > 0 and s["proved"] == s["sites"], s
    assert s["clean"]


def test_row_domain_field_bound_tightens_below_field_width():
    """A 3-bit field declared to hold only codes 0..4 proves a gather from
    a 5-entry table — the field-width fallback alone could not."""

    class FiveStateTwin(_FaultBase):
        def __init__(self, model):
            super().__init__(model)
            self.pk = BitPacker([("code", 3)])

        def row_domain(self):
            return RowDomain.from_packer(self.pk,
                                         field_bounds={"code": 4})

        def step_rows(self, rows):
            c = self.pk.get(rows, "code").astype(jnp.int32)
            tbl = jnp.asarray([1, 2, 3, 4, 0], jnp.uint64)
            succ = rows.at[..., 0].set(tbl[c])[:, None, :]
            valid = (c < 4)[:, None]
            return succ, valid

    report = audit_model(_host_model(FiveStateTwin))
    s = report.metrics["sanitizer"]
    assert not report.by_rule("JX201"), report.format()
    assert s["proved"] == s["sites"]

    class FiveStateUnseeded(FiveStateTwin):
        def row_domain(self):
            return None  # falls back to field WIDTH (0..7): escapes

    report = audit_model(_host_model(FiveStateUnseeded))
    assert report.by_rule("JX201"), report.format()


def test_scan_widening_never_narrows_ys():
    """Soundness of loop widening: a scan whose carry outgrows the
    widening budget must NOT report its ys at the narrow pre-widening
    bounds — the gather it feeds is *undecided* (info), never 'proved'
    against a small table."""

    class ScanTwin(_FaultBase):
        def step_rows(self, rows):
            def body(c, _):
                return c + jnp.int32(1), c

            _, ys = jax.lax.scan(body, jnp.int32(0), None, length=10)
            tbl = jnp.asarray([1, 2, 3, 4], jnp.uint64)
            v = tbl[jnp.broadcast_to(ys[-1], (rows.shape[0],))]
            succ = rows.at[..., 0].set(v)[:, None, :]
            valid = (rows[..., 0] < jnp.uint64(3))[:, None]
            return succ, valid

    report = audit_model(_host_model(ScanTwin))
    s = report.metrics["sanitizer"]
    # the index escaped the widened carry's knowledge: the site must not
    # count as proved, and must not be a false-positive ERROR either
    assert s["proved"] < s["sites"], s
    assert not [f for f in report.by_rule("JX201")
                if f.severity == Severity.ERROR], report.format()


def test_abs_index_does_not_false_positive():
    """|i - j| over masked fields is a classic in-range index; the abs
    rule must fold the negative half instead of keeping it (which would
    verdict a learned-bound escape -> spurious JX201 ERROR)."""

    class AbsTwin(_FaultBase):
        def __init__(self, model):
            super().__init__(model)
            self.pk = BitPacker([("i", 2), ("j", 2)])

        def step_rows(self, rows):
            i = self.pk.get(rows, "i").astype(jnp.int32)
            j = self.pk.get(rows, "j").astype(jnp.int32)
            tbl = jnp.asarray([1, 2, 3, 4], jnp.uint64)  # |i-j| in [0,3]
            succ = rows.at[..., 0].set(tbl[jnp.abs(i - j)])[:, None, :]
            valid = (i < 3)[:, None]
            return succ, valid

    report = audit_model(_host_model(AbsTwin))
    s = report.metrics["sanitizer"]
    assert not report.by_rule("JX201"), report.format()
    assert s["proved"] == s["sites"], s


def test_interval_domain_unit_ops():
    """Spot-checks of the IVal algebra the pass rests on."""
    a = IVal(0, 7)
    assert a.join(IVal(3, 12)).hull() == (0, 12)
    assert a.clip(2, 5).hull() == (2, 5)
    assert a.clip(9, 12) is None  # empty
    s = IVal(0, 100, frozenset({EMPTY}))
    assert s.may_contain(EMPTY)
    assert s.drop_point(EMPTY).hull() == (0, 100)
    assert s.map_exact(lambda v: v >> 6).hull() == (0, EMPTY >> 6)


# ---------------------------------------------------------------------------
# dynamic: checked execution mode
# ---------------------------------------------------------------------------


def test_checked_mode_clean_model_same_counts():
    c = (TwoPhaseSys(3).checker().checked()
         .spawn_tpu(sync=True, batch=64, capacity=1 << 12))
    assert c.unique_state_count() == 288
    assert len(c.discoveries()) == 2  # both sometimes-examples found


def test_checked_mode_names_the_offending_row():
    """The dynamic half of the fault-injection satellite: the OOB gather
    model (statically JX201) also fails loudly under ``.checked()``, with
    the error naming the batch row and decoded state.  skip_audit() is the
    documented route to reproducing a flagged defect on device."""
    m = _host_model(OOBGatherTwin)
    with pytest.raises(CheckedExecutionError) as exc:
        m.checker().skip_audit().checked().spawn_tpu(
            sync=True, batch=8, capacity=1 << 10
        )
    e = exc.value
    assert e.row_index is not None
    assert e.state == 4  # first state whose count field escapes the table
    assert "out-of-bounds" in str(e)
    # and WITHOUT checked mode the same model runs to a silently wrong
    # verdict — the exact failure class the sanitizer exists for
    c = m.checker().skip_audit().spawn_tpu(
        sync=True, batch=8, capacity=1 << 10
    )
    assert c.unique_state_count() == 5  # clamp truncated the 8-state chain


def test_checked_false_leaves_run_jaxpr_bit_identical():
    """The telemetry contract applied to checked mode: checked=False must
    build the exact device program an engine without the feature builds."""

    def run_jaxpr(flag):
        m = TwoPhaseSys(3)  # fresh model => fresh compiled-run cache
        b = m.checker()
        if flag is not None:
            b = b.checked(flag)
        c = b.spawn_tpu(sync=True, capacity=1 << 12, batch=64)
        init_fn, run_fn = c._engine(c._cap, c._qcap, c._batch, c._cand)
        carry, _ = init_fn()
        return str(jax.make_jaxpr(lambda cr: run_fn(cr))(tuple(carry)))

    baseline = run_jaxpr(None)
    assert baseline == run_jaxpr(False)
    assert baseline != run_jaxpr(True)  # instrumentation is really there


def test_sharded_engine_rejects_checked():
    with pytest.raises(NotImplementedError, match="single-device"):
        TwoPhaseSys(3).checker().checked().spawn_tpu(devices=2)


# ---------------------------------------------------------------------------
# surfaces: CLI verbs, Explorer, report plumbing
# ---------------------------------------------------------------------------


def test_cli_sanitize_verb(capsys):
    from stateright_tpu.models import two_phase_commit

    two_phase_commit.main(["sanitize"])
    out = capsys.readouterr().out
    assert "proved in range" in out


def test_cli_fleet_sanitize_subset(capsys):
    from stateright_tpu.models._cli import fleet_sanitize

    rc = fleet_sanitize(["two_phase_commit", "increment"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "sanitize fleet: CLEAN" in out


@pytest.mark.slow
def test_fleet_sanitize_all_examples():
    from stateright_tpu.models._cli import fleet_sanitize

    assert fleet_sanitize() == 0


def test_cli_checked_flag_parses():
    from stateright_tpu.models._cli import pop_checked

    assert pop_checked(["3", "--checked"]) == (True, ["3"])
    assert pop_checked(["--checked"]) == (True, [])
    assert pop_checked(["3"]) == (False, ["3"])


def test_explorer_status_exposes_sanitizer_block():
    from stateright_tpu.explorer import ExplorerServer

    server = ExplorerServer(
        TwoPhaseSys(3).checker(), "localhost:0", strategy="tpu", batch=64
    ).start_background()
    try:
        host, port = server.addr.rsplit(":", 1)
        deadline = time.monotonic() + 60
        status = None
        while time.monotonic() < deadline:
            conn = http.client.HTTPConnection(host, int(port), timeout=10)
            conn.request("GET", "/.status")
            status = json.loads(conn.getresponse().read())
            conn.close()
            if status["done"]:
                break
            time.sleep(0.2)
        assert status is not None and status["done"]
        s = status["sanitizer"]
        assert s is not None and s["clean"] is True
        assert s["proved"] == s["sites"] > 0
        assert s["checked_run"] is False
    finally:
        server.shutdown()


def test_report_merge_dedupes_across_passes():
    from stateright_tpu.analysis import AuditReport

    a = AuditReport(model="M")
    a.add("JX201", Severity.ERROR, "step_rows:gather#1", "escape")
    b = AuditReport(model="M")
    b.add("JX201", Severity.ERROR, "step_rows:gather#1", "escape")  # dup
    b.add("JX203", Severity.WARNING, "step_rows:and#1", "overflow")
    b.metrics["sanitizer"] = {"clean": False}
    a.merge(b)
    assert len(a.findings) == 2  # the duplicate folded away
    assert a.metrics["sanitizer"] == {"clean": False}
    # extend() itself is dedup-safe (cache re-extends must not double up)
    a.extend(list(b.findings))
    assert len(a.findings) == 2
