"""Search cartography (ops/cartography.py + both engines), the progress/
health model (telemetry/health.py), the live watch view, and the post-run
report (telemetry/report.py).

The load-bearing contracts pinned here:

 - cartography OFF leaves the engines' run jaxpr BIT-IDENTICAL (the
   telemetry/checked/prededup discipline applied to the search counters);
 - cartography ON reconciles EXACTLY with the checker's own totals:
   ``sum(depth_hist) == unique``, ``sum(action_hist) == states - inits``,
   every property evaluated exactly ``unique`` times, and the
   duplicate/fresh split is ``states - unique`` — including across growth
   replays (an overflowed batch must count nothing);
 - the report JSON is byte-stable for a fixed model/config, with the
   single volatile field being the ``generated_at`` header;
 - ``--watch`` degrades to plain periodic lines on a non-TTY stream.

The 2pc-7 ≤5% overhead pin and the growth-heavy full-crawl parity live in
the slow/medium tier (ROADMAP tiering rule).
"""

import io
import json
import re

import pytest

import jax
import numpy as np

from helpers import requires_sharded_collectives

from stateright_tpu.models.dining import dining_model
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.telemetry.health import HealthTracker, phase_timeline

TPC3_UNIQUE = 288
TPC5_UNIQUE = 8_832
TPC7_UNIQUE = 296_448


def _reconcile(checker, n_init: int = 1, early_exit: bool = False) -> dict:
    """Assert the cartography block reconciles exactly with the checker's
    reported totals; returns the block.  ``early_exit=True`` relaxes the
    per-property evaluation count to <= unique: a run that discovered
    every property stops with queued rows never popped (the one caveat
    the ops/cartography.py invariants carve out)."""
    cart = checker.cartography()
    assert cart is not None and cart["v"] == 1
    states = checker.state_count()
    unique = checker.unique_state_count()
    assert sum(cart["depth_hist"]) == unique
    assert cart["fresh_inserts"] == unique
    assert cart["duplicate_hits"] == states - unique
    assert sum(cart["action_hist"]) == states - n_init
    for p in cart["props"]:
        if early_exit:
            assert 0 < p["evaluated"] <= unique
        else:
            assert p["evaluated"] == unique
        assert 0 <= p["condition_hits"] <= p["evaluated"]
    return cart


# -- wavefront engine --------------------------------------------------------


def test_cartography_off_leaves_run_jaxpr_bit_identical():
    """The telemetry/checked/prededup contract: the flag OFF is the
    pre-feature step program, ON actually adds the reductions."""

    def run_jaxpr(telemetry, cartography):
        m = TwoPhaseSys(3)
        b = m.checker()
        if telemetry:
            b = b.telemetry(cartography=cartography)
        c = b.spawn_tpu(sync=True, capacity=1 << 12, batch=64)
        init_fn, run_fn = c._engine(c._cap, c._qcap, c._batch, c._cand)
        carry, _ = init_fn()
        # fresh lambda per call: make_jaxpr memoizes on fn identity
        return str(jax.make_jaxpr(lambda cr: run_fn(cr))(tuple(carry)))

    plain = run_jaxpr(False, False)
    assert plain == run_jaxpr(True, False)
    assert plain != run_jaxpr(True, True)


def test_wavefront_counts_reconcile_exactly():
    on = (
        TwoPhaseSys(3).checker().telemetry(cartography=True)
        .spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    )
    off = TwoPhaseSys(3).checker().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    cart = _reconcile(on)
    # counters are observers: counts/discoveries identical with the flag
    assert on.unique_state_count() == off.unique_state_count() == TPC3_UNIQUE
    assert on.state_count() == off.state_count()
    assert sorted(on.discoveries()) == sorted(off.discoveries())
    # 2pc-3's space: 1 init at depth 0, diameter 10, 3 properties
    assert cart["depth_hist"][0] == 1
    assert len(cart["depth_hist"]) == 11
    assert [p["name"] for p in cart["props"]] == [
        "abort agreement", "commit agreement", "consistent"
    ]
    # the always-property "consistent" holds everywhere: hits == evaluated
    assert cart["props"][2]["condition_hits"] == TPC3_UNIQUE


def test_growth_replay_never_double_counts():
    """Grow the table mid-run (tiny initial capacity): overflowed batches
    replay after the growth transform, and the counters must come out
    exact — an overflow that counted anything would show up here."""
    c = (
        TwoPhaseSys(5).checker().telemetry(cartography=True)
        .spawn_tpu(sync=True, capacity=1 << 10, batch=256)
    )
    assert c.unique_state_count() == TPC5_UNIQUE
    growth = c.flight_recorder.records("growth")
    assert growth, "2pc-5 from 1k slots must grow"
    _reconcile(c)
    # the growth-boundary cartography series is in the ring: one record
    # per growth + the closing "final", all reconciling cumulatively
    series = c.flight_recorder.records("cartography")
    assert series and series[-1]["at"] == "final"
    assert sum(series[-1]["depth_hist"]) == TPC5_UNIQUE
    for snap in series:
        assert sum(snap["depth_hist"]) == snap["fresh_inserts"]


def test_resume_preserves_banked_depth_histogram():
    """Growth compactions bank consumed queue prefixes' depth lanes in
    ``_cart_depth_base``; a snapshot must carry the bank or a resumed
    histogram forgets every state popped before a pre-snapshot growth
    (regression: the bank was not in the snapshot and silently dropped,
    breaking ``sum(depth_hist) == unique`` across resume)."""
    c = TwoPhaseSys(3).checker().telemetry(cartography=True).spawn_tpu(
        sync=True, batch=32, queue_capacity=64, capacity=1 << 12
    )
    assert c.unique_state_count() == TPC3_UNIQUE
    assert c.flight_recorder.records("growth"), "qcap=64 must grow"
    snap = c.checkpoint()
    assert "cart_depth_base" in snap, "growth banked no depth lanes"
    assert int(np.asarray(snap["cart_depth_base"]).sum()) > 0
    r = TwoPhaseSys(3).checker().telemetry(cartography=True).spawn_tpu(
        sync=True, resume=snap
    )
    assert r.unique_state_count() == TPC3_UNIQUE
    assert sum(r.cartography()["depth_hist"]) == TPC3_UNIQUE


def test_checked_mode_composes_with_cartography():
    """The checked error flag and the counter tail share the carry tail;
    both features on must still reconcile exactly."""
    c = (
        TwoPhaseSys(3).checker().checked().telemetry(cartography=True)
        .spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    )
    assert c.unique_state_count() == TPC3_UNIQUE
    _reconcile(c)


def test_dining_reconciles_and_fills_action_histogram():
    m = dining_model(3)
    c = m.checker().telemetry(cartography=True).spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    # dining discovers every property and early-exits with rows queued:
    # histograms stay exact, per-property tallies count what actually ran
    cart = _reconcile(c, early_exit=True)
    # the histogram spans the twin's full action arity; several distinct
    # slots fire (a single hot slot would mean the column sum is
    # miswired), and — the cartography point — the padded slots the
    # compiled twin never enables are now VISIBLE as zeros
    assert len(cart["action_hist"]) == c.tensor.max_actions
    fired = sum(1 for v in cart["action_hist"] if v > 0)
    assert fired >= 3
    assert fired < len(cart["action_hist"])


# -- sharded engine ----------------------------------------------------------


@requires_sharded_collectives
def test_sharded_cartography_counts_and_shard_extras():
    c = TwoPhaseSys(3).checker().telemetry(cartography=True).spawn_tpu(
        sync=True, devices=2, capacity=1 << 12, frontier_capacity=1 << 9
    )
    cart = _reconcile(c)
    # shard-local extras: per-shard fresh inserts sum to unique, the
    # routed-candidate matrix is 2x2 and covers at least the non-init
    # unique states (every fresh insert arrived through the all-to-all)
    assert sum(cart["shard_load"]) == TPC3_UNIQUE
    assert len(cart["route_matrix"]) == 2
    assert all(len(row) == 2 for row in cart["route_matrix"])
    assert cart["routed_candidates"] >= TPC3_UNIQUE - 1
    imb = cart["shard_imbalance"]
    assert imb["ratio"] >= 1.0
    assert imb["max"] >= imb["mean"]


@requires_sharded_collectives
def test_sharded_resume_preserves_cartography_counters():
    """The sharded counter tail is cumulative IN-CARRY, so snapshots must
    persist it: a resumed run re-seeded with zeros pairs restarted
    histograms with total-derived fresh_inserts and breaks
    ``sum(depth_hist) == unique`` (regression)."""
    c = TwoPhaseSys(3).checker().telemetry(cartography=True).spawn_tpu(
        sync=True, devices=2, capacity=1 << 12, frontier_capacity=1 << 9
    )
    assert c.unique_state_count() == TPC3_UNIQUE
    snap = c.checkpoint()
    assert any(k.startswith("cart") for k in snap), (
        "snapshot must carry the cartography counter tail"
    )
    r = TwoPhaseSys(3).checker().telemetry(cartography=True).spawn_tpu(
        sync=True, devices=2, resume=snap
    )
    assert r.unique_state_count() == TPC3_UNIQUE
    _reconcile(r)


@requires_sharded_collectives
def test_sharded_cartography_off_program_unchanged():
    """Flag-off pin for the sharded engine: the whole-run program traced
    with ``cartography=False`` is bit-identical to a build that never
    mentions the flag (the default path every pre-cartography caller
    takes), and the flag ON actually changes the program."""
    import jax.numpy as jnp

    from stateright_tpu.parallel.sharded import (
        _build_sharded_run,
        default_mesh,
    )

    m = TwoPhaseSys(3)
    tensor = m._tensor_cached()
    props = list(m.properties())
    mesh = default_mesh(2)

    def step_jaxpr(cartography):
        kw = {} if cartography is None else {"cartography": cartography}
        init_fn, step_fn = _build_sharded_run(
            tensor, props, mesh, 1 << 11, 1 << 9, 1 << 10, None, **kw
        )
        out = init_fn()
        carry = tuple(jnp.asarray(x) for x in out[:-1])
        return str(jax.make_jaxpr(lambda *cr: step_fn(*cr))(*carry))

    assert step_jaxpr(None) == step_jaxpr(False)
    assert step_jaxpr(None) != step_jaxpr(True)


# -- health model ------------------------------------------------------------


def _step(d_states, d_unique, queue=1, load=0.01, dt=0.1):
    return {
        "d_states": d_states, "d_unique": d_unique, "queue": queue,
        "load_factor": load, "dt": dt,
    }


def test_health_phases_expand_peak_drain_done():
    t = HealthTracker()
    events = []
    # ramp: fresh inserts growing -> expanding
    for n in (10, 50, 100):
        events += t.update(_step(n * 2, n))
    assert t.phase == "expanding"
    # novelty collapses to a trickle -> draining
    events += t.update(_step(200, 4))
    assert t.phase == "draining"
    # midband novelty -> peaking
    events += t.update(_step(120, 50))
    assert t.phase == "peaking"
    events += t.mark_done()
    assert t.phase == "done"
    phases = [e["phase"] for e in events if e["event"] == "phase"]
    assert phases == ["draining", "peaking", "done"]
    assert all(e["v"] == 1 for e in events)
    assert t.mark_done() == []  # idempotent


def test_health_stall_detection_and_clear():
    t = HealthTracker(stall_after=3)
    t.update(_step(100, 100))
    evs = []
    for _ in range(3):
        evs += t.update(_step(100, 0, queue=50))
    assert t.stalled and t.stall_reason == "no_fresh_inserts"
    assert [e["event"] for e in evs if "stall" in e["event"]] == ["stall"]
    evs = t.update(_step(100, 5, queue=50))
    assert not t.stalled
    assert [e["event"] for e in evs if "stall" in e["event"]] == [
        "stall_cleared"
    ]
    # an empty queue is completion-shaped, not a stall
    t2 = HealthTracker(stall_after=2)
    t2.update(_step(100, 100))
    for _ in range(5):
        t2.update(_step(100, 0, queue=0))
    assert not t2.stalled


def test_health_stall_on_pinned_table_load():
    t = HealthTracker(stall_after=3)
    for _ in range(3):
        t.update(_step(100, 60, load=0.249))
    assert t.stalled
    assert t.stall_reason == "load_pinned_at_growth_threshold"


def test_health_mark_done_closes_open_stall():
    """A run that completes while flagged stalled must emit the pairing
    ``stall_cleared`` transition — consumers pair stall/stall_cleared, so
    a finished run must never leave one open (regression: mark_done
    cleared the flag silently)."""
    t = HealthTracker(stall_after=2)
    t.update(_step(100, 100))
    for _ in range(2):
        t.update(_step(100, 0, queue=50))
    assert t.stalled
    events = t.mark_done()
    assert [e["event"] for e in events] == ["stall_cleared", "phase"]
    assert not t.stalled and t.phase == "done"
    assert t.mark_done() == []  # still idempotent


def test_health_busy_flag_overrides_missing_queue():
    """The sharded engine has no cheap frontier count (only the replicated
    keep-going flag crosses to the host) and sends ``busy`` explicitly;
    ``busy=False`` is completion-shaped even with no queue field, and
    ``busy=True`` arms the zero-novelty stall guard."""
    t = HealthTracker(stall_after=2)
    t.update({"d_states": 100, "d_unique": 100, "dt": 0.1, "busy": True})
    for _ in range(5):
        t.update({"d_states": 100, "d_unique": 0, "dt": 0.1, "busy": False})
    assert not t.stalled  # drained frontier, not a stall
    t2 = HealthTracker(stall_after=2)
    t2.update({"d_states": 100, "d_unique": 100, "dt": 0.1, "busy": True})
    for _ in range(2):
        t2.update({"d_states": 100, "d_unique": 0, "dt": 0.1, "busy": True})
    assert t2.stalled and t2.stall_reason == "no_fresh_inserts"


def test_health_eta_only_while_draining():
    t = HealthTracker()
    t.update(_step(1000, 800, queue=500, dt=1.0))
    assert t.snapshot()["eta_secs"] is None  # expanding: no honest ETA
    for _ in range(3):
        t.update(_step(1000, 10, queue=400, dt=1.0))
    snap = t.snapshot()
    assert t.phase == "draining" and snap["eta_secs"] is not None
    assert snap["frontier"] == 400


def test_health_eta_uses_queue_drain_rate_not_fresh_rate():
    """The queue empties at the pop rate minus the insert rate; during
    draining the fresh-insert rate tends to zero by definition, so an
    ETA divided by it would overestimate without bound (regression)."""
    t = HealthTracker()
    t.update(_step(100_000, 80_000, queue=100_000, dt=1.0))
    # drains 50k rows/sec while the fresh rate has collapsed to 1k/sec
    t.update(_step(100_000, 1_000, queue=50_000, dt=1.0))
    t.update(_step(100_000, 1_000, queue=10_000, dt=1.0))
    snap = t.snapshot()
    assert t.phase == "draining"
    # true drain: ~10k rows at a smoothed ~40k rows/s => well under 1s;
    # the old fresh-rate divisor would have claimed ~10 seconds
    assert snap["eta_secs"] is not None and snap["eta_secs"] < 2.0


def test_recorder_emits_health_transitions_and_close():
    from stateright_tpu.telemetry import FlightRecorder

    rec = FlightRecorder()
    rec.step(engine="x", states=100, unique=90, queue=10)
    for i in range(8):
        rec.step(engine="x", states=200 + i, unique=90, queue=10)
    kinds = [
        (r["event"], r.get("reason")) for r in rec.records("health")
    ]
    assert ("stall", "no_fresh_inserts") in kinds
    rec.close_run(done=True)
    rec.close_run(done=True)  # idempotent: exactly one done record
    phases = [r["phase"] for r in rec.records("health")
              if r["event"] == "phase"]
    assert phases.count("done") == 1
    assert rec.health()["phase"] == "done"


def test_jsonl_replay_keeps_health_events_verbatim(tmp_path):
    """Exported health records replay verbatim; replayed steps must not
    regenerate them (each event would otherwise appear twice)."""
    from stateright_tpu.telemetry import FlightRecorder

    rec = FlightRecorder()
    rec.step(engine="x", states=100, unique=90, queue=10)
    for i in range(8):
        rec.step(engine="x", states=200 + i, unique=90, queue=10)
    rec.close_run()
    n_health = len(rec.records("health"))
    assert n_health >= 2  # stall + done at minimum
    path = tmp_path / "t.jsonl"
    rec.to_jsonl(path)
    back = FlightRecorder.from_jsonl(path)
    assert len(back.records("health")) == n_health
    assert [r["event"] for r in back.records("health")] == [
        r["event"] for r in rec.records("health")
    ]


def test_phase_timeline_is_deterministic_and_count_derived():
    recs = [
        _step(20, 10), _step(200, 100), _step(220, 100), _step(300, 5),
    ]
    a, b = phase_timeline(recs), phase_timeline(recs)
    assert a == b
    assert [e["phase"] for e in a] == [
        "expanding", "expanding", "expanding", "draining"
    ]
    # wall-clock signals never leak into the deterministic series
    assert all(set(e) == {"step", "unique", "d_unique", "novelty", "phase"}
               for e in a)


def test_checker_health_surface_end_to_end():
    c = (
        TwoPhaseSys(3).checker().telemetry(cartography=True)
        .spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    )
    h = c.flight_recorder.health()
    assert h["phase"] == "done" and h["stalled"] is False
    assert h["v"] == 1


# -- post-run report ---------------------------------------------------------


def _strip_stamp(text: str) -> str:
    # the volatile header is stripped BY SCHEMA (report.VOLATILE_KEYS):
    # a new volatile identity field added there is covered here for free
    from stateright_tpu.telemetry.report import VOLATILE_KEYS

    for k in VOLATILE_KEYS:
        text = re.sub(rf'"{k}": "[^"]*"', f'"{k}": "X"', text)
    return text


def test_report_json_is_byte_stable_across_runs(tmp_path):
    def run(path):
        TwoPhaseSys(3).checker().report(str(path)).spawn_tpu(
            sync=True, capacity=1 << 12, batch=64
        )
        return path.read_text()

    a = run(tmp_path / "a.json")
    b = run(tmp_path / "b.json")
    assert _strip_stamp(a) == _strip_stamp(b)
    # the volatile fields are EXACTLY the identity header, leading the
    # document (report.VOLATILE_KEYS is the schema the diff engine
    # scrubs by)
    from stateright_tpu.telemetry.report import VOLATILE_KEYS

    doc = json.loads(a)
    head = [k for k in doc if k in VOLATILE_KEYS]
    assert list(doc)[: len(head)] == head
    assert list(doc)[0] == "generated_at"
    assert "run_id" in head


def test_report_contents_and_markdown(tmp_path):
    path = tmp_path / "run.json"
    c = TwoPhaseSys(3).checker().report(str(path)).spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    doc = json.loads(path.read_text())
    assert doc["v"] == 1
    assert doc["model"] == "TwoPhaseSys" and doc["engine"] == "wavefront"
    assert doc["totals"]["unique"] == TPC3_UNIQUE
    assert doc["totals"]["done"] is True
    assert doc["cartography"]["fresh_inserts"] == TPC3_UNIQUE
    assert doc["final_phase"] == "done"
    assert doc["growth_events"] == []  # pre-sized: no growth
    assert doc["health_timeline"], "step stream must be replayed"
    names = {p["name"]: p for p in doc["properties"]}
    assert names["abort agreement"]["discovery"] is True
    assert names["consistent"]["discovery"] is False
    # audit ran at spawn preflight: status travels with the report
    assert doc["audit"]["ok"] is True
    # the sibling markdown rendering exists and carries the sections
    md = (tmp_path / "run.md").read_text()
    for section in ("# Run report", "## Properties",
                    "## Search cartography", "## Health timeline",
                    "## Wall clock (non-deterministic)"):
        assert section in md
    # builder contract: .report() implied cartography telemetry
    assert c.cartography() is not None


def test_implied_cartography_survives_telemetry_reconfig(tmp_path):
    """``.report()``/``.cartography()`` imply the counters; a later
    ``.telemetry(...)`` reconfiguring the recorder (e.g. enlarging the
    ring for a long run) must not silently drop them."""
    path = tmp_path / "sticky.json"
    b = TwoPhaseSys(3).checker().report(str(path)).telemetry(capacity=1 << 14)
    assert b.telemetry_opts["cartography"] is True
    assert b.telemetry_opts["capacity"] == 1 << 14
    c = b.spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    assert c.cartography() is not None
    assert json.loads(path.read_text())["cartography"]["fresh_inserts"] == \
        TPC3_UNIQUE


def test_report_rejects_md_target_path(tmp_path):
    """A ``.md`` report target would collapse the JSON body and the
    markdown sibling onto one file — refused up front, at build time."""
    import pytest

    with pytest.raises(ValueError, match="ends in .md"):
        TwoPhaseSys(3).checker().report(str(tmp_path / "run.md"))
    # same guard at the write layer (direct write_report callers)
    from stateright_tpu.telemetry.report import write_report

    with pytest.raises(ValueError, match="ends in .md"):
        write_report(object(), str(tmp_path / "direct.md"))


def test_report_written_once_at_join_for_async_runs(tmp_path):
    path = tmp_path / "async.json"
    c = TwoPhaseSys(3).checker().report(str(path)).spawn_tpu(
        capacity=1 << 12, batch=64
    )
    c.join()
    stamp = path.read_text()
    c.join()  # second join must not rewrite (generated_at would move)
    assert path.read_text() == stamp


def test_report_cli_verb(tmp_path, capsys):
    from stateright_tpu.models.two_phase_commit import main

    out = tmp_path / "cli.json"
    main(["report", f"--out={out}", "3"])
    assert "report written to" in capsys.readouterr().out
    doc = json.loads(out.read_text())
    assert doc["totals"]["unique"] == TPC3_UNIQUE
    assert (tmp_path / "cli.md").exists()


def test_report_marks_deadline_cut_runs_incomplete(tmp_path):
    """is_done() means STOPPED, not finished: a deadline-cut run's report
    must say done=false / timed_out=true, and its health phase must stay
    where the run actually was (regression: the report claimed
    completion — the exact artifact-misreads-the-run failure it exists
    to prevent)."""
    path = tmp_path / "cut.json"
    # the deadline fires during engine compile, so the run is cut at its
    # first host sync — deterministic on any machine
    c = (
        TwoPhaseSys(5).checker().timeout(0.05).report(str(path))
        .spawn_tpu(sync=True, capacity=1 << 15, batch=256)
    )
    c.join()
    assert c.timed_out
    body = json.loads(path.read_text())
    assert body["totals"]["done"] is False
    assert body["totals"]["timed_out"] is True
    assert body["final_phase"] != "done"
    assert "cut short" in (tmp_path / "cut.md").read_text()


def test_stall_reason_switch_emits_transition():
    """While already stalled, the cause can change (a fresh insert clears
    the novelty counter on a step where the load counter is already over
    threshold); the live reason and the timeline must name the actual
    cause (regression: the first reason stuck for the stall's life)."""
    t = HealthTracker(stall_after=2)
    evs = []
    evs += t.update(_step(100, 100, load=0.249))
    evs += t.update(_step(100, 0, load=0.249))
    assert t.stalled and t.stall_reason == "load_pinned_at_growth_threshold"
    evs += t.update(_step(100, 0, load=0.249))
    assert t.stalled and t.stall_reason == "no_fresh_inserts"
    stall_evs = [e for e in evs if e["event"] == "stall"]
    assert [e["reason"] for e in stall_evs] == [
        "load_pinned_at_growth_threshold", "no_fresh_inserts"
    ]


def test_pool_runs_never_flag_zero_novelty_stalls():
    """Thread-pool job blocks carry un-deduped successors, so a
    duplicate-heavy tail legitimately produces zero fresh inserts —
    the pool opts out of the stall heuristic with ``busy=False``
    (regression: ``queue`` was the just-processed block size, always
    positive, arming spurious stall records on converging runs)."""
    c = TwoPhaseSys(3).checker().telemetry().spawn_bfs().join()
    assert c.unique_state_count() == TPC3_UNIQUE
    rec = c.flight_recorder
    assert rec.records("step"), "pool runs must record steps"
    assert all(r.get("busy") is False for r in rec.records("step"))
    assert not [r for r in rec.records("health") if r["event"] == "stall"]


def test_report_flags_ring_truncated_timeline(tmp_path):
    """A run with more host syncs than the telemetry ring holds must say
    so — a silently mid-run timeline misclassifies phases (the true
    peak is evicted)."""
    path = tmp_path / "trunc.json"
    c = (
        TwoPhaseSys(3).checker().telemetry(capacity=4).report(str(path))
        .spawn_tpu(sync=True, capacity=1 << 12, batch=16, steps_per_call=1)
    )
    c.join()
    assert c.flight_recorder.kind_count("step") > 4
    body = json.loads(path.read_text())
    assert body.get("health_timeline_truncated") is True
    assert "truncated" in (tmp_path / "trunc.md").read_text()


def test_report_written_by_host_strategies(tmp_path):
    """``.report(PATH)`` is honored at the first join() on EVERY strategy,
    not just the device engines (regression: the report verb's host-BFS
    fallback printed success without writing anything)."""
    from stateright_tpu.models._cli import report_models
    from stateright_tpu.models.quickstart import FizzBuzz

    path = tmp_path / "bfs.json"
    FizzBuzz(8).checker().report(str(path)).spawn_bfs().join()
    body = json.loads(path.read_text())
    assert body["v"] == 1 and body["totals"]["done"]
    assert "cartography" not in body  # host run: no device counters
    assert (tmp_path / "bfs.md").exists()

    # the twinless report_models fallback path writes what it advertises
    out = tmp_path / "fallback.json"
    stream = io.StringIO()
    paths = report_models([("fizzbuzz", FizzBuzz(8))], str(out), stream)
    assert paths == [str(out)]
    assert "no device twin" in stream.getvalue()
    assert json.loads(out.read_text())["totals"]["done"]


# -- live watch view ---------------------------------------------------------


class _FakeTty(io.StringIO):
    def isatty(self):
        return True


def test_watch_line_reads_live_surfaces():
    from stateright_tpu.models._cli import watch_line

    c = (
        TwoPhaseSys(3).checker().telemetry(cartography=True)
        .spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    )
    line = watch_line(c)
    assert "states=1146" in line and "unique=288" in line
    assert "phase=done" in line
    assert "depth=10" in line


def test_watch_non_tty_degrades_to_plain_lines():
    """CI/pipe smoke: no carriage returns, no ANSI escapes — one plain
    line per refresh window plus the final line."""
    from stateright_tpu.models._cli import watch_checker

    c = (
        TwoPhaseSys(3).checker().telemetry(cartography=True)
        .spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    )
    buf = io.StringIO()  # isatty() -> False
    watch_checker(c, stream=buf)
    out = buf.getvalue()
    assert out.endswith("\n")
    assert "\r" not in out and "\x1b" not in out
    assert "unique=288" in out


def test_watch_tty_rewrites_in_place():
    from stateright_tpu.models._cli import watch_checker

    c = (
        TwoPhaseSys(3).checker().telemetry(cartography=True)
        .spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    )
    buf = _FakeTty()
    watch_checker(c, stream=buf)
    out = buf.getvalue()
    assert "\r" in out and out.endswith("\n")
    assert "\x1b" not in out  # plain rewrite, no ANSI
    assert "unique=288" in out


def test_watch_flag_pops_and_arms_telemetry():
    from stateright_tpu.models._cli import apply_watch, pop_watch

    watch, rest = pop_watch(["3", "--watch"])
    assert watch is True and rest == ["3"]
    watch2, rest2 = pop_watch(["3"])
    assert watch2 is False and rest2 == ["3"]
    b = TwoPhaseSys(3).checker()
    b = apply_watch(b, True)
    assert b.telemetry_opts["cartography"] is True
    # watch over an existing telemetry config only ADDS cartography
    b2 = TwoPhaseSys(3).checker().telemetry(occupancy_every=4)
    b2 = apply_watch(b2, True)
    assert b2.telemetry_opts["occupancy_every"] == 4
    assert b2.telemetry_opts["cartography"] is True


# -- overhead + heavy parity (slow/medium tier) ------------------------------


@pytest.mark.slow
def test_cartography_overhead_under_5pct_on_2pc7():
    """Acceptance gate: the on-device counters cost <=5% wall time on the
    2PC-7 wavefront run (same protocol as the telemetry <3% pin:
    pre-sized capacities, shared engine cache, min-of-2)."""
    import time

    m = TwoPhaseSys(7)
    caps = dict(capacity=1 << 21, queue_capacity=1 << 19, batch=1024,
                steps_per_call=32, cand=1 << 14)

    def run(cart: bool) -> float:
        b = m.checker()
        if cart:
            b = b.telemetry(cartography=True)
        t0 = time.monotonic()
        c = b.spawn_tpu(sync=True, **caps)
        dt = time.monotonic() - t0
        assert c.unique_state_count() == TPC7_UNIQUE
        return dt

    run(False)  # warm-up
    run(True)   # warm-up the cartography engine variant too
    base = min(run(False), run(False))
    cart = min(run(True), run(True))
    overhead = cart / base - 1.0
    assert overhead < 0.05, (
        f"cartography overhead {overhead:.1%} (off {base:.2f}s, on "
        f"{cart:.2f}s) breaks the <=5% contract"
    )


@pytest.mark.slow
def test_cartography_full_crawl_reconciles_on_2pc7():
    """Full-crawl reconciliation at scale, through the real growth ladder
    (daily tier): the counters stay exact across hundreds of syncs and
    multiple growth replays."""
    c = (
        TwoPhaseSys(7).checker().telemetry(cartography=True)
        .spawn_tpu(sync=True, capacity=1 << 16, batch=1024,
                   steps_per_call=16)
    )
    assert c.unique_state_count() == TPC7_UNIQUE
    cart = _reconcile(c)
    assert c.flight_recorder.records("growth")
    # depth histogram covers the full 2pc-7 diameter
    depth = np.asarray(cart["depth_hist"])
    assert depth[0] == 1 and depth.sum() == TPC7_UNIQUE
