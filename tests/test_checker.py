"""Checker engine tests (reference ``bfs.rs``/``dfs.rs``/``checker.rs`` tests).

Pins: BFS/DFS visit order, report shapes (states=15/unique=12 BFS,
55/55 DFS on LinearEquation{2,10,14} — reference ``checker.rs:459-479``),
full enumeration (65,536), early exit, discovery validity by re-execution,
liveness semantics including the reference's documented false negative.
"""

import io

import pytest

from stateright_tpu import Model, Property, StateRecorder
from stateright_tpu.checker import PathRecorder

from fixtures import BinaryClock, DGraph, FnModel, LinearEquation


# ---------------------------------------------------------------------------
# visit order
# ---------------------------------------------------------------------------

def test_bfs_visits_by_distance():
    recorder = StateRecorder()
    LinearEquation(2, 10, 14).checker().visitor(recorder).spawn_bfs().join()
    # breadth-first: states appear in nondecreasing distance order
    expected = [
        (0, 0),
        (1, 0), (0, 1),
        (2, 0), (1, 1), (0, 2),
        (3, 0), (2, 1),
    ]
    assert recorder.states == expected


def test_dfs_visits_depth_first():
    recorder = StateRecorder()
    LinearEquation(2, 10, 14).checker().visitor(recorder).spawn_dfs().join()
    states = recorder.states
    # depth-first: walks the y-chain from (0,0) up to the (0,27) solution
    assert states[0] == (0, 0)
    assert states[1:] == [(0, y) for y in range(1, 28)]


# ---------------------------------------------------------------------------
# counts / report shapes (reference ``checker.rs:459-479``)
# ---------------------------------------------------------------------------

def test_bfs_report_shape():
    checker = LinearEquation(2, 10, 14).checker().spawn_bfs().join()
    assert checker.state_count() == 15
    assert checker.unique_state_count() == 12
    out = io.StringIO()
    checker.report(out)
    text = out.getvalue()
    assert "Done. states=15, unique=12, sec=" in text
    assert 'Discovered "solvable" example' in text


def test_dfs_report_shape():
    checker = LinearEquation(2, 10, 14).checker().spawn_dfs().join()
    assert checker.state_count() == 55
    assert checker.unique_state_count() == 55


def test_bfs_full_enumeration_when_unsolvable():
    # 2x + 4y is always even: never equals 7 (mod 256). Explores all 256*256.
    checker = LinearEquation(2, 4, 7).checker().spawn_bfs().join()
    assert checker.unique_state_count() == 65536
    assert checker.discovery("solvable") is None


def test_bfs_multithreaded_matches_single():
    single = LinearEquation(2, 4, 7).checker().spawn_bfs().join()
    multi = LinearEquation(2, 4, 7).checker().threads(4).spawn_bfs().join()
    assert multi.unique_state_count() == single.unique_state_count() == 65536


def test_dfs_full_enumeration_when_unsolvable():
    checker = LinearEquation(2, 4, 7).checker().spawn_dfs().join()
    assert checker.unique_state_count() == 65536


def test_target_state_count_bounds_run():
    checker = (
        LinearEquation(2, 4, 7).checker().target_states(100).spawn_bfs().join()
    )
    assert 100 <= checker.unique_state_count() < 3000


# ---------------------------------------------------------------------------
# discovery validity (reference ``checker.rs:293-338``)
# ---------------------------------------------------------------------------

def test_bfs_finds_shortest_example_and_assert_discovery():
    checker = LinearEquation(2, 10, 14).checker().spawn_bfs().join()
    path = checker.assert_any_discovery("solvable")
    assert path.final_state() == (2, 1)
    assert len(path.actions()) == 3  # shortest: 2 IncreaseX + 1 IncreaseY
    checker.assert_discovery(
        "solvable", ["IncreaseX", "IncreaseX", "IncreaseY"]
    )


def test_dfs_discovery_valid_but_not_shortest():
    checker = LinearEquation(2, 10, 14).checker().spawn_dfs().join()
    path = checker.assert_any_discovery("solvable")
    x, y = path.final_state()
    assert (2 * x + 10 * y) % 256 == 14


def test_assert_properties_raises_on_missing_example():
    checker = LinearEquation(2, 4, 7).checker().spawn_bfs().join()
    with pytest.raises(AssertionError):
        checker.assert_properties()


def test_always_counterexample():
    m = DGraph(
        inits=[0],
        edges={0: [1], 1: [2]},
        props=[Property.always("small", lambda m, s: s < 2)],
    )
    checker = m.checker().spawn_bfs().join()
    path = checker.assert_any_discovery("small")
    assert path.final_state() == 2
    assert checker.discovery_classification("small") == "counterexample"
    checker.assert_discovery("small", [1, 2])


# ---------------------------------------------------------------------------
# liveness (eventually) semantics (reference ``checker.rs:350-414``)
# ---------------------------------------------------------------------------

def _eventually(name, target):
    return Property.eventually(name, lambda m, s: s == target)


def test_eventually_satisfied_on_all_paths_no_discovery():
    # diamond: 0 -> {1,2} -> 3; eventually reaches 3 on every maximal path
    m = DGraph(
        inits=[0],
        edges={0: [1, 2], 1: [3], 2: [3]},
        props=[_eventually("reaches 3", 3)],
    )
    for spawn in ("spawn_bfs", "spawn_dfs"):
        checker = getattr(m.checker(), spawn)().join()
        assert checker.discovery("reaches 3") is None, spawn


def test_eventually_counterexample_at_terminal_state():
    # 0 -> 1 (terminal), target 9 never reached
    m = DGraph(
        inits=[0],
        edges={0: [1]},
        props=[_eventually("reaches 9", 9)],
    )
    for spawn in ("spawn_bfs", "spawn_dfs"):
        checker = getattr(m.checker(), spawn)().join()
        path = checker.assert_any_discovery("reaches 9")
        assert path.final_state() == 1, spawn


def test_eventually_mid_path_satisfaction_counts():
    # 0 -> 1(target) -> 2 terminal: satisfied before terminal, no discovery
    m = DGraph(
        inits=[0],
        edges={0: [1], 1: [2]},
        props=[_eventually("reaches 1", 1)],
    )
    checker = m.checker().spawn_bfs().join()
    assert checker.discovery("reaches 1") is None


def test_fixme_can_miss_counterexample_when_revisiting_a_state():
    """Replicates the reference's documented false negative
    (``checker.rs:402-414``): ebits aren't part of the fingerprint, so a
    path that joins an already-visited state inherits nothing; a cycle is
    not treated as terminal.  0 -> 1 -> 0 cycles forever without reaching
    the target, but no counterexample is reported."""
    m = DGraph(
        inits=[0],
        edges={0: [1], 1: [0]},
        props=[_eventually("reaches 9", 9)],
    )
    for spawn in ("spawn_bfs", "spawn_dfs"):
        checker = getattr(m.checker(), spawn)().join()
        # known false negative, pinned for parity with the reference
        assert checker.discovery("reaches 9") is None, spawn


# ---------------------------------------------------------------------------
# misc surface
# ---------------------------------------------------------------------------

def test_binary_clock_enumerates_both_states():
    checker = BinaryClock().checker().spawn_bfs().join()
    assert checker.unique_state_count() == 2
    checker.assert_properties()


def test_path_recorder_collects_paths():
    recorder = PathRecorder()
    m = DGraph(inits=[0], edges={0: [1], 1: [2]}, props=[
        Property.always("true", lambda m, s: True)])
    m.checker().visitor(recorder).spawn_bfs().join()
    assert len(recorder.paths) == 3  # paths to 0, 0->1, 0->1->2


def test_path_reconstruction_detects_nondeterminism():
    import itertools

    counter = itertools.count(100)

    def successors(s):
        # deliberately nondeterministic: different successors on re-execution
        return [next(counter)]

    m = FnModel(inits=[0], successors=successors)
    m.properties = lambda: [Property.sometimes("hit", lambda mm, s: s == 105)]
    checker = m.checker().spawn_bfs().join()
    with pytest.raises(RuntimeError, match="not deterministic"):
        checker.discoveries()


def test_boundary_prunes_expansion():
    class Bounded(LinearEquation):
        def within_boundary(self, state):
            return state[0] + state[1] <= 2

    checker = Bounded(2, 4, 7).checker().spawn_bfs().join()
    # triangle x+y<=2: 6 states
    assert checker.unique_state_count() == 6


def test_no_properties_explores_everything():
    # a model with zero properties must fully enumerate, not early-exit
    m = DGraph(inits=[0], edges={0: [1], 1: [2]})
    for spawn in ("spawn_bfs", "spawn_dfs"):
        checker = getattr(m.checker(), spawn)().join()
        assert checker.unique_state_count() == 3, spawn


def test_model_exception_propagates_to_join():
    class Boom(LinearEquation):
        def actions(self, state):
            if state == (2, 0):
                raise ValueError("user bug")
            return super().actions(state)

    for spawn in ("spawn_bfs", "spawn_dfs"):
        checker = getattr(Boom(2, 4, 7).checker(), spawn)()
        with pytest.raises(ValueError, match="user bug"):
            checker.join()


def test_timeout_stops_unbounded_run():
    import time

    class Unbounded(Model):
        def init_states(self):
            return [0]

        def actions(self, s):
            return [1, 2]

        def next_state(self, s, a):
            time.sleep(0.0001)
            return s * 2 + a

        def properties(self):
            return [Property.always("t", lambda m, s: True)]

    start = time.monotonic()
    checker = Unbounded().checker().timeout(0.5).spawn_bfs().join()
    assert time.monotonic() - start < 10
    assert checker.unique_state_count() > 0


def test_bfs_no_duplicate_visits_when_actions_converge():
    # two actions from the same state reaching the same successor must not
    # double-enqueue (regression: parent-fp dedup ambiguity)
    m = DGraph(
        inits=[0],
        edges={0: [1, 1], 1: [2], 2: [3], 3: [4]},
        props=[Property.always("t", lambda m, s: True)],
    )
    rec = StateRecorder()
    checker = m.checker().visitor(rec).spawn_bfs().join()
    assert rec.states == [0, 1, 2, 3, 4]
    assert checker.unique_state_count() == 5
    assert checker.state_count() == 6  # dup generation still counted
