"""Write-once register example: end-to-end checks, pinned counts, symmetry,
and compiled-device-twin parity (closing the reference's unexercised
write-once harness, ``src/actor/write_once_register.rs:119-299``)."""

import pytest

from stateright_tpu.actor import Envelope, Id
from stateright_tpu.actor.network import Network
from stateright_tpu.actor.register import NULL_VALUE
from stateright_tpu.models.write_once_register import (
    WOServer,
    main,
    server_representative,
    wo_register_model,
)
from stateright_tpu.semantics import LinearizabilityTester, WORegister


def test_one_server_is_linearizable_pinned_counts():
    checker = wo_register_model(2, 1).checker().spawn_bfs().join()
    assert checker.unique_state_count() == 71
    assert checker.state_count() == 97
    checker.assert_properties()  # no linearizability violation
    assert sorted(checker.discoveries()) == ["value chosen"]


def test_one_server_dfs_agrees():
    checker = wo_register_model(2, 1).checker().spawn_dfs().join()
    assert checker.unique_state_count() == 71
    checker.assert_properties()


def test_two_independent_servers_violate_linearizability():
    checker = wo_register_model(2, 2).checker().spawn_dfs().join()
    path = checker.assert_any_discovery("linearizable")
    # the witness ends in a genuinely inconsistent history
    assert not path.final_state().history.is_consistent()


def test_second_write_fails_and_history_records_write_fail():
    model = wo_register_model(2, 1)
    state = model.init_states()[0]

    def deliver(pred):
        action = next(
            a
            for a in model.actions(state)
            if type(a).__name__ == "Deliver" and pred(a)
        )
        return model.next_state(state, action)

    # both puts reach the server (first wins), then both replies deliver
    state = deliver(lambda a: a.msg[0] == "put" and a.src == Id(1))
    state = deliver(lambda a: a.msg[0] == "put" and a.src == Id(2))
    assert {e.msg[0] for e in state.network.iter_all()} == {
        "put_ok",
        "put_fail",
    }
    state = deliver(lambda a: a.msg[0] == "put_ok")
    state = deliver(lambda a: a.msg[0] == "put_fail")
    rets = sorted(
        ret
        for t in state.history.history_by_thread.values()
        for (_, _, ret) in t
    )
    assert rets == [("write_fail",), ("write_ok",)]
    # the server kept the first value
    assert state.actor_states[0] == "A"


def test_symmetry_preserves_verdicts():
    plain = wo_register_model(2, 2).checker().spawn_dfs().join()
    sym = (
        wo_register_model(2, 2)
        .checker()
        .symmetry_with(lambda s: server_representative(s, 2))
        .spawn_dfs()
        .join()
    )
    assert sorted(plain.discoveries()) == sorted(sym.discoveries()) == [
        "linearizable",
        "value chosen",
    ]


def test_server_representative_canonicalizes_permuted_servers():
    """Two hand-built states differing only by a server permutation (with
    ids rewritten through the network) share a representative; clients are
    never permuted."""
    model = wo_register_model(1, 2)
    base = model.init_states()[0]
    S = type(base)

    def with_servers(v0, v1, dst):
        return S(
            actor_states=(v0, v1) + base.actor_states[2:],
            network=Network.new_unordered_nonduplicating().send(
                Envelope(src=Id(2), dst=Id(dst), msg=("get", 9))
            ),
            is_timer_set=base.is_timer_set,
            history=base.history,
        )

    a = with_servers("A", NULL_VALUE, 0)
    b = with_servers(NULL_VALUE, "A", 1)  # servers swapped, ids rewritten
    ra = server_representative(a, 2)
    rb = server_representative(b, 2)
    assert ra == rb
    # fixed point + client block untouched
    assert server_representative(ra, 2) == ra
    assert ra.actor_states[2:] == base.actor_states[2:]


def test_wo_spec_semantics():
    t = LinearizabilityTester(WORegister(None))
    t = t.on_invoke(1, ("write", "A")).on_return(1, ("write_ok",))
    t = t.on_invoke(2, ("write", "B")).on_return(2, ("write_fail",))
    t = t.on_invoke(1, ("read",)).on_return(1, ("read_ok", "A"))
    assert t.is_consistent()
    # a read of B is impossible: B's write failed
    t2 = LinearizabilityTester(WORegister(None))
    t2 = t2.on_invoke(1, ("write", "A")).on_return(1, ("write_ok",))
    t2 = t2.on_invoke(2, ("write", "B")).on_return(2, ("write_fail",))
    t2 = t2.on_invoke(1, ("read",)).on_return(1, ("read_ok", "B"))
    assert not t2.is_consistent()


def test_compiled_twin_parity_single_device():
    cpu = wo_register_model(2, 1).checker().spawn_bfs().join()
    tpu = wo_register_model(2, 1).checker().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    assert tpu.unique_state_count() == cpu.unique_state_count() == 71
    assert tpu.state_count() == cpu.state_count() == 97
    assert sorted(tpu.discoveries()) == sorted(cpu.discoveries())
    tpu.assert_properties()


def test_compiled_twin_parity_sharded():
    tpu = wo_register_model(2, 1).checker().spawn_tpu(
        devices=8, sync=True, capacity=1 << 12, frontier_capacity=1 << 7
    )
    assert tpu.unique_state_count() == 71
    tpu.assert_properties()


def test_compiled_twin_finds_violation_on_two_servers():
    tpu = wo_register_model(2, 2).checker().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    path = tpu.assert_any_discovery("linearizable")
    assert not path.final_state().history.is_consistent()


def test_cli_check_smoke(capsys):
    main(["check", "2", "1"])
    out = capsys.readouterr().out
    assert "write-once register" in out and "sec=" in out


def test_cli_check_sym_smoke(capsys):
    main(["check-sym", "2", "2"])
    out = capsys.readouterr().out
    assert "symmetry" in out and "sec=" in out
