"""Liveness (``eventually``) on the device engines: the per-row ebits are
set at path start, cleared when the condition holds, and flushed as
counterexamples at terminal rows (reference ``bfs.rs:212-222,265-272``; the
documented DAG-join/cycle false-negative caveats carry over since ebits are
not fingerprinted)."""

import numpy as np
import pytest

import jax.numpy as jnp

from stateright_tpu import Property
from stateright_tpu.core import Model
from stateright_tpu.parallel.tensor_model import (
    BitPacker,
    TensorBackedModel,
    TensorModel,
)


class ClimbTensor(TensorModel):
    """Row = (height, stopped); climb to N step by step, or give up early."""

    def __init__(self, model):
        self.model = model
        self.pk = BitPacker([("h", 8), ("stopped", 1)])
        self.width = self.pk.width
        self.max_actions = 2

    def encode_state(self, s):
        return self.pk.pack(h=s[0], stopped=int(s[1]))

    def decode_state(self, row):
        d = self.pk.unpack(row)
        return (d["h"], bool(d["stopped"]))

    def init_rows(self):
        return np.asarray(
            [self.encode_state(s) for s in self.model.init_states()],
            np.uint64,
        )

    def step_rows(self, rows):
        n = self.model.n
        h = self.pk.get(rows, "h").astype(jnp.int32)
        stopped = self.pk.get(rows, "stopped").astype(jnp.int32)
        live = stopped == 0
        # action 0: climb
        climb = self.pk.set(rows[:, None, :], "h", (h + 1)[:, None])
        climb_ok = (live & (h < n))[:, None]
        # action 1: give up (terminal sink)
        stop = self.pk.set(rows[:, None, :], "stopped", jnp.uint64(1))
        stop_ok = (live & (h < n))[:, None]
        if not self.model.can_stop:
            stop_ok = jnp.zeros_like(stop_ok)
        return (
            jnp.concatenate([climb, stop], axis=1),
            jnp.concatenate([climb_ok, stop_ok], axis=1),
        )

    def property_masks(self, rows):
        h = self.pk.get(rows, "h").astype(jnp.int32)
        return (h >= self.model.n)[:, None]


class Climb(TensorBackedModel, Model):
    """``eventually "summited"``: holds on every full climb; a path that
    gives up terminates below the summit — a liveness counterexample iff
    giving up is enabled."""

    def __init__(self, n=5, can_stop=True):
        super().__init__()
        self.n = n
        self.can_stop = can_stop

    def tensor_model(self):
        return ClimbTensor(self)

    def init_states(self):
        return [(0, False)]

    def actions(self, s):
        acts = []
        if not s[1] and s[0] < self.n:
            acts.append("climb")
            if self.can_stop:
                acts.append("stop")
        return acts

    def next_state(self, s, a):
        if a == "climb":
            return (s[0] + 1, s[1])
        return (s[0], True)

    def properties(self):
        return [Property.eventually("summited", lambda m, s: s[0] >= m.n)]


@pytest.mark.parametrize("devices", [None, 8])
def test_eventually_counterexample_on_device(devices):
    kw = dict(devices=devices) if devices else {}
    checker = Climb(5, can_stop=True).checker().spawn_tpu(sync=True, **kw)
    cpu = Climb(5, can_stop=True).checker().spawn_bfs().join()
    assert set(checker.discoveries()) == set(cpu.discoveries()) == {"summited"}
    path = checker.discovery("summited")
    final = path.final_state()
    assert final[1] and final[0] < 5  # gave up below the summit


@pytest.mark.parametrize("devices", [None, 8])
def test_eventually_satisfied_no_discovery(devices):
    kw = dict(devices=devices) if devices else {}
    checker = Climb(5, can_stop=False).checker().spawn_tpu(sync=True, **kw)
    assert checker.discoveries() == {}
    checker.assert_properties()
