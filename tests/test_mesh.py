"""Mesh-native sharded engine (``parallel/mesh.py`` +
``parallel/partition.py``; docs/mesh.md) — ISSUE 19 acceptance.

The contracts pinned here, in the family's strongest form:

 - mesh-vs-wavefront BIT-IDENTICAL parity — counts, verdicts, discovery
   traces — on 2pc-3 and paxos-1 under the suite's forced 8-device CPU
   mesh, including the per-channel static-routing layout;
 - kill+resume exact totals on the mesh engine, snapshot engine tag,
   and the cross-engine resume rejection;
 - growth preserves both the work AND the sharded placement;
 - the per-shard load / routing-matrix readout is well-formed and rides
   the results;
 - engine selection: ``.mesh()`` / ``--mesh`` / ``STATERIGHT_TPU_MESH``
   arm THIS engine, the old spelling (``devices=``/``n_devices=``/
   ``mesh=`` kwargs) stays the old engine, sweep x mesh is fenced;
 - the partition-rule matcher's guards (scalar, divisibility, no-match,
   flag/layout drift);
 - ZERO vma-cast collectives in the mesh path: these tests RUN — never
   take ``requires_sharded_collectives`` — on the pinned jax 0.4.37.
"""

from __future__ import annotations

import ast

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from stateright_tpu.checker.base import CheckerBuilder
from stateright_tpu.models.paxos import paxos_model
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.parallel.mesh import MeshTpuChecker
from stateright_tpu.parallel.partition import (
    ENV_MESH,
    MESH_AXES,
    WAVEFRONT_CARRY_RULES,
    build_mesh,
    engine_requires_collectives,
    match_partition_rules,
    resolve_mesh_flag,
    wavefront_carry_names,
)
from stateright_tpu.parallel.wavefront import TpuChecker

TPC3_UNIQUE, TPC3_TOTAL = 288, 1146
PAXOS1_TOTAL, PAXOS1_UNIQUE = 482, 265


def _mesh_spawn(m, **kw):
    kw.setdefault("sync", True)
    return m.checker().mesh().spawn_tpu(**kw)


def _solo_spawn(m, **kw):
    kw.setdefault("sync", True)
    return m.checker().spawn_tpu(**kw)


def _assert_trace_parity(a, b):
    da, db = a.discoveries(), b.discoveries()
    assert set(da) == set(db)
    for name in da:
        assert [str(s) for s in da[name].states()] == [
            str(s) for s in db[name].states()
        ], name


# -- bit-identical parity (the acceptance pins) -------------------------------


def test_mesh_parity_2pc3_counts_verdicts_traces():
    """2pc-3 on the suite's 8-device mesh: every count, the visited
    table contents, every verdict, and every discovery trace must match
    the single-device wavefront bit-for-bit (same programs, partitioned
    placement — parity is by construction, pinned here)."""
    solo = _solo_spawn(TwoPhaseSys(3), capacity=1 << 12, batch=256)
    mesh = _mesh_spawn(TwoPhaseSys(3), capacity=1 << 12, batch=256)
    assert isinstance(mesh, MeshTpuChecker)
    assert mesh.n_devices == 8
    assert (
        mesh.unique_state_count() == solo.unique_state_count() == TPC3_UNIQUE
    )
    assert mesh.state_count() == solo.state_count() == TPC3_TOTAL
    assert mesh.max_depth() == solo.max_depth()
    ts, tm = solo._table_np(), mesh._table_np()
    assert np.array_equal(ts[0], tm[0])
    assert np.array_equal(ts[1], tm[1])
    mesh.assert_properties()
    _assert_trace_parity(solo, mesh)


# cross-engine full-space parity on a consensus model is an
# integration sweep — the daily tier owns it (870s fast-tier budget)
@pytest.mark.medium
def test_mesh_parity_paxos1():
    solo = _solo_spawn(paxos_model(1, 3), capacity=1 << 15, batch=256)
    mesh = _mesh_spawn(paxos_model(1, 3), capacity=1 << 15, batch=256)
    assert (
        mesh.unique_state_count()
        == solo.unique_state_count()
        == PAXOS1_UNIQUE
    )
    assert mesh.state_count() == solo.state_count() == PAXOS1_TOTAL
    mesh.assert_properties()
    _assert_trace_parity(solo, mesh)


def test_mesh_parity_per_channel_static_routing():
    """The first queued unlock: with the per-channel layout armed the
    (src,dst) channel map makes candidate destinations static on the
    mesh — counts and traces must still match the wavefront on the same
    encoding."""
    def pc():
        m = paxos_model(1, 3)
        m.per_channel_()
        return m

    solo = _solo_spawn(pc(), capacity=1 << 15, batch=256)
    mesh = _mesh_spawn(pc(), capacity=1 << 15, batch=256)
    assert (
        mesh.unique_state_count()
        == solo.unique_state_count()
        == PAXOS1_UNIQUE
    )
    assert mesh.state_count() == solo.state_count() == PAXOS1_TOTAL
    _assert_trace_parity(solo, mesh)


# -- kill + resume ------------------------------------------------------------


def test_mesh_kill_resume_exact_totals_and_engine_tag():
    m = TwoPhaseSys(4)
    ref = _solo_spawn(m, capacity=1 << 12, batch=64)
    c = m.checker().mesh().spawn_tpu(
        sync=False, capacity=1 << 12, batch=64, steps_per_call=2
    )
    snap = c.checkpoint()
    c.stop()
    c.join()
    assert snap["engine"] == "mesh"
    r = m.checker().mesh().spawn_tpu(sync=True, resume=snap)
    assert r.unique_state_count() == ref.unique_state_count()
    assert r.state_count() == ref.state_count()
    _assert_trace_parity(ref, r)
    # a mesh snapshot must not silently resume on the plain engine
    with pytest.raises(ValueError, match="engine"):
        m.checker().spawn_tpu(sync=True, resume=snap)


def test_mesh_growth_preserves_work_and_sharding():
    """Capacity growth round-trips the carry through host numpy; the
    re-jitted engine must land the grown table SHARDED again (the
    in_shardings re-shard), with totals matching a pre-sized solo run."""
    m = TwoPhaseSys(4)
    mesh = _mesh_spawn(m, capacity=1 << 9, batch=128)
    assert len(mesh.growth_events) >= 1
    ref = _solo_spawn(m, capacity=1 << 12, batch=128)
    assert mesh.unique_state_count() == ref.unique_state_count()
    assert mesh.state_count() == ref.state_count()
    table = mesh._final_carry[0]
    assert table.sharding.spec == P(MESH_AXES)
    assert not table.sharding.is_fully_replicated
    assert len(table.addressable_shards) == 8


# -- the A/B readout ----------------------------------------------------------


def test_mesh_stats_well_formed_and_in_results():
    mesh = _mesh_spawn(TwoPhaseSys(3), capacity=1 << 12, batch=256)
    stats = mesh.mesh_stats()
    assert stats is not None
    assert stats["devices"] == 8
    assert stats["axes"] == {"host": 1, "chip": 8}
    assert len(stats["shard_load"]) == 8
    assert sum(stats["shard_load"]) == TPC3_UNIQUE
    imb = stats["imbalance"]
    assert imb["max"] >= imb["mean"] > 0 and imb["ratio"] >= 1.0
    route = np.asarray(stats["route_matrix"])
    assert route.shape == (8, 8)
    # every non-init unique state routes parent-owner -> child-owner
    # (2pc has ONE init state, the only row with parent fingerprint 0)
    assert route.sum() == stats["routed_states"] == TPC3_UNIQUE - 1
    assert mesh._results["mesh"] == stats


def test_mesh_stats_ride_cartography_block():
    mesh = (
        TwoPhaseSys(3).checker().mesh().cartography().spawn_tpu(
            sync=True, capacity=1 << 12, batch=256
        )
    )
    cart = mesh._results["cartography"]
    assert cart["shard_load"] == mesh.mesh_stats()["shard_load"]
    assert cart["route_matrix"] == mesh.mesh_stats()["route_matrix"]
    assert "ratio" in cart["shard_imbalance"]


# -- engine selection ---------------------------------------------------------


def test_builder_mesh_selects_mesh_engine(monkeypatch):
    monkeypatch.delenv(ENV_MESH, raising=False)
    c = _mesh_spawn(TwoPhaseSys(3), capacity=1 << 12, batch=64)
    assert isinstance(c, MeshTpuChecker)
    # bounded mesh: .mesh(devices=2)
    c2 = TwoPhaseSys(3).checker().mesh(devices=2).spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    assert c2.n_devices == 2
    assert c2.unique_state_count() == TPC3_UNIQUE


def test_env_knob_and_malformed_warning(monkeypatch, capsys):
    monkeypatch.setenv(ENV_MESH, "1")
    assert resolve_mesh_flag(None, None) == (True, None)
    monkeypatch.setenv(ENV_MESH, "4")
    assert resolve_mesh_flag(None, None) == (True, 4)
    monkeypatch.setenv(ENV_MESH, "0")
    assert resolve_mesh_flag(None, None) == (False, None)
    # explicit builder setting beats the env knob in BOTH directions
    monkeypatch.setenv(ENV_MESH, "1")
    assert resolve_mesh_flag(False, None) == (False, None)
    monkeypatch.setenv(ENV_MESH, "0")
    assert resolve_mesh_flag(True, 2) == (True, 2)
    # a typo'd knob warns loudly and never silently disarms as "off"
    monkeypatch.setenv(ENV_MESH, "yes")
    assert resolve_mesh_flag(None, None) == (False, None)
    assert "malformed" in capsys.readouterr().err


def test_env_knob_spawns_mesh_engine(monkeypatch):
    monkeypatch.setenv(ENV_MESH, "1")
    c = TwoPhaseSys(3).checker().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    assert isinstance(c, MeshTpuChecker)
    assert c.unique_state_count() == TPC3_UNIQUE


def test_old_spelling_stays_old_engine(monkeypatch):
    """``devices=``/``n_devices=`` keep routing to the OLD shard_map
    engine even with the mesh flag armed — the A/B harness depends on
    the two spellings staying distinct."""
    import stateright_tpu.parallel.sharded as sharded_mod

    calls = []

    class Sentinel:
        def __init__(self, options, **kw):
            calls.append(kw)
            raise RuntimeError("sentinel-constructed")

    monkeypatch.setattr(sharded_mod, "ShardedTpuChecker", Sentinel)
    monkeypatch.setenv(ENV_MESH, "1")
    with pytest.raises(RuntimeError, match="sentinel"):
        TwoPhaseSys(3).checker().spawn_tpu(sync=True, devices=2)
    assert calls and calls[0].get("n_devices") == 2


def test_sweep_x_mesh_is_fenced():
    from stateright_tpu.sweep.spec import SweepSpec

    from stateright_tpu.models.two_phase_commit import sweep_family

    spec = sweep_family(2)
    assert isinstance(spec, SweepSpec)
    with pytest.raises(NotImplementedError, match="sweep x mesh"):
        TwoPhaseSys(3).checker().sweep(spec).mesh().spawn_tpu(sync=True)


def test_mesh_rejects_pallas_and_oversized_mesh():
    with pytest.raises(NotImplementedError, match="[Pp]allas"):
        TwoPhaseSys(3).checker().mesh().spawn_tpu(
            sync=True, pallas=True, capacity=1 << 12, batch=64
        )
    with pytest.raises(ValueError, match="visible"):
        build_mesh(n_devices=99)


def test_mesh_engine_cache_key_never_collides():
    """The compiled-run cache lives on the SHARED tensor twin: the mesh
    key must carry the engine tag + device ids so a mesh entry never
    answers a single-device lookup (or a different sub-mesh's)."""
    solo = _solo_spawn(TwoPhaseSys(3), capacity=1 << 12, batch=64)
    mesh = _mesh_spawn(TwoPhaseSys(3), capacity=1 << 12, batch=64)
    k_solo = solo._engine_key(
        solo._cap, solo._qcap, solo._batch, solo._cand
    )
    k_mesh = mesh._engine_key(
        mesh._cap, mesh._qcap, mesh._batch, mesh._cand
    )
    assert k_mesh[:-1] == k_solo
    assert k_mesh[-1] == ("mesh",) + tuple(range(8))
    sub = TwoPhaseSys(3).checker().mesh(devices=2).spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    k_sub = sub._engine_key(sub._cap, sub._qcap, sub._batch, sub._cand)
    assert k_sub[-1] == ("mesh", 0, 1)
    assert len({k_solo, k_mesh, k_sub}) == 3


# -- partition rules ----------------------------------------------------------


def test_match_partition_rules_guards():
    mesh = build_mesh()  # 1 x 8 over the suite's virtual devices
    names = ("table_fp", "q_rows", "head", "odd_dim")
    avals = (
        jax.ShapeDtypeStruct((1 << 12,), np.uint64),  # divisible: sharded
        jax.ShapeDtypeStruct((640, 3), np.uint64),    # divisible: sharded
        jax.ShapeDtypeStruct((), np.int32),           # scalar: replicated
        jax.ShapeDtypeStruct((13,), np.int32),        # 13 % 8: replicated
    )
    rules = WAVEFRONT_CARRY_RULES + ((r"odd_dim", P(MESH_AXES)),)
    s = match_partition_rules(rules, names, avals, mesh)
    assert s[0].spec == P(MESH_AXES)
    assert s[1].spec == P(MESH_AXES)
    assert s[2].spec == P()
    # divisibility guard replicated the dim (P(None) normalizes to P())
    assert all(ax is None for ax in s[3].spec)
    with pytest.raises(ValueError, match="no partition rule"):
        match_partition_rules(
            ((r"^table_", P(MESH_AXES)),), ("stray",),
            (jax.ShapeDtypeStruct((8,), np.int32),), mesh,
        )


def test_wavefront_carry_names_flag_guards():
    base = wavefront_carry_names(13)
    assert base[0] == "table_fp" and base[12] == "status"
    with_err = wavefront_carry_names(16, checked=True)
    assert with_err[13] == "err" and with_err[14] == "cart_0"
    with pytest.raises(ValueError, match="carry has"):
        wavefront_carry_names(13, checked=True, por=True)


# -- no vma collectives in the mesh path --------------------------------------


def test_mesh_engine_needs_no_vma_collectives():
    """The acceptance pin that keeps these tests RUNNING on jax 0.4.37:
    the mesh module's code contains no ``pvary``/``pcast`` attribute
    access and no ``shard_map`` use (AST-checked, so docstrings don't
    count), and the per-engine skip helper knows it."""
    import stateright_tpu.parallel.mesh as mesh_mod

    assert engine_requires_collectives("sharded")
    assert not engine_requires_collectives("mesh")
    assert not engine_requires_collectives("single")

    tree = ast.parse(open(mesh_mod.__file__).read())
    banned = {"pvary", "pcast", "shard_map"}
    hits = [
        node.attr
        for node in ast.walk(tree)
        if isinstance(node, ast.Attribute) and node.attr in banned
    ] + [
        node.id
        for node in ast.walk(tree)
        if isinstance(node, ast.Name) and node.id in banned
    ] + [
        alias.name
        for node in ast.walk(tree)
        if isinstance(node, (ast.Import, ast.ImportFrom))
        for alias in node.names
        if alias.name in banned
    ]
    assert not hits, hits


# -- regress --mesh gate (injectable artifacts) -------------------------------


def _good_mesh_leg():
    return {
        "tpu_mesh_states_per_sec": 1000.0,
        "tpu_mesh_solo_states_per_sec": 900.0,
        "tpu_mesh": {
            "model": "2pc-5", "devices": 4,
            "unique": 100, "states": 180,
            "shard_load": [25, 25, 30, 20],
            "imbalance": {"max": 30, "mean": 25.0, "ratio": 1.2},
            "routed_states": 99,
            "sec": 1.0, "solo_sec": 1.1,
            "parity": "IDENTICAL",
        },
    }


def _leg(**over):
    run = _good_mesh_leg()
    run["tpu_mesh"] = dict(run["tpu_mesh"], **over)
    return run


def test_regress_mesh_gate_absence_never_trips():
    import regress

    v = regress.mesh_verdict({}, {})
    assert v["ok"] and not v["present"]
    # a stale/pre-mesh BASELINE never trips a run either way
    v = regress.mesh_verdict(_good_mesh_leg(), {})
    assert v["ok"] and v["present"] and not v["baseline_present"]


def test_regress_mesh_gate_validates_present_legs():
    import regress

    good = _good_mesh_leg()
    v = regress.mesh_verdict(good, {})
    assert v["ok"], v
    assert v["shard_load"] == [25, 25, 30, 20]
    assert v["imbalance_ratio"] == 1.2

    crashed = dict(good, tpu_mesh_error="RuntimeError: boom")
    assert not regress.mesh_verdict(crashed, {})["ok"]

    v = regress.mesh_verdict(_leg(parity="DIVERGENT"), {})
    assert not v["ok"] and any("IDENTICAL" in p for p in v["problems"])

    # a load vector that cannot account for every visited row
    v = regress.mesh_verdict(_leg(shard_load=[25, 25, 30, 19]), {})
    assert not v["ok"] and any(
        "one shard owner" in p for p in v["problems"]
    )
    # ... or whose width disagrees with the mesh
    assert not regress.mesh_verdict(_leg(shard_load=[50, 50]), {})["ok"]

    # routed_states must exclude the init states
    v = regress.mesh_verdict(_leg(routed_states=100), {})
    assert not v["ok"] and any(
        "route nowhere" in p for p in v["problems"]
    )

    v = regress.mesh_verdict(_leg(states=50), {})
    assert not v["ok"] and any("bound uniques" in p for p in v["problems"])

    # injected artifacts are arbitrary JSON: a stringified crash in the
    # block slot must produce a verdict, not a traceback
    trash = dict(good, tpu_mesh="XlaRuntimeError: boom")
    assert not regress.mesh_verdict(trash, {})["ok"]
    assert not regress.mesh_verdict(_leg(devices="8"), {})["ok"]


def test_regress_main_mesh_flag(tmp_path, capsys):
    """End-to-end through regress.main: a fresh run with a good leg
    passes; one with a crashed leg exits 1; a run WITHOUT the leg passes
    (flag-gated, the spill/mxu/sweep/fleet rule)."""
    import json

    import regress

    bp = tmp_path / "base.json"
    bp.write_text(json.dumps({}))
    args = ["--baseline=" + str(bp), "--mesh"]

    def run_file(extra):
        doc = {"fresh": True, **extra}
        p = tmp_path / f"run{len(list(tmp_path.iterdir()))}.json"
        p.write_text(json.dumps(doc))
        return str(p)

    assert regress.main([run_file(_good_mesh_leg())] + args) == 0
    assert regress.main([run_file({})] + args) == 0
    assert regress.main([run_file({"tpu_mesh_error": "boom"})] + args) == 1
    # stale artifacts never trip the mesh gate (exit 2 is staleness,
    # not a gate failure; --allow-stale with a broken leg still passes)
    stale = tmp_path / "stale.json"
    stale.write_text(json.dumps({"fresh": False, "tpu_mesh_error": "boom"}))
    assert regress.main([str(stale)] + args) == 2
    assert regress.main([str(stale), "--allow-stale"] + args) == 0
    capsys.readouterr()
