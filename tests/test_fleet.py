"""stateright_tpu/fleet/ — the multi-tenant fleet scheduler
(docs/fleet.md).

Unit tier (engine-free, tests/fleet_fakes.py): spec validation,
admission decisions under simulated device budgets, cohort-pack
grouping, the preempt→yield→re-queue→resume cycle with its record
trail, campaign grids + ledgers, the CLI surfaces, and the
zero-coupling contract (no engine module may import the fleet).

Medium tier (real engines, CPU backend): the N-job acceptance — fleet
counts bit-identical to solo runs, packed cohorts compiling strictly
fewer engines than jobs, and a preempted job resuming exactly-once
(lineage pair classifying IDENTICAL).
"""

import io
import json
import os
import threading
import time

import pytest

from stateright_tpu.fleet import (
    ADMITTED,
    ADMITTED_SPILL,
    COMPLETED,
    FAILED,
    FLEET_V,
    LEDGER_NAME,
    REFUSED,
    FleetScheduler,
    FleetSpec,
    Job,
    PreemptionPlan,
    build_ledger,
    campaign_spec,
    expand_grid,
    run_campaign,
    run_fleet,
)
from stateright_tpu.telemetry import FlightRecorder

from tests.fleet_fakes import FakeBuilder, FakeModel


def _job(key, builder=None, **kw):
    b = builder if builder is not None else FakeBuilder()
    return Job(key=key, build=lambda: b, **kw)


def _sched(jobs, **spec_kw):
    return FleetScheduler(FleetSpec(jobs=jobs, **spec_kw), stream=None)


def _build_2pc(n, **builder_calls):
    def build():
        from stateright_tpu.checker.base import CheckerBuilder
        from stateright_tpu.models.two_phase_commit import TwoPhaseSys

        b = CheckerBuilder(TwoPhaseSys(n))
        for name, arg in builder_calls.items():
            b = getattr(b, name)(arg)
        return b

    return build


# -- spec validation ---------------------------------------------------------


def test_fleet_spec_validation():
    with pytest.raises(ValueError):
        FleetSpec(jobs=[])
    with pytest.raises(ValueError):
        FleetSpec(jobs=[_job("a"), _job("a")])  # duplicate keys
    with pytest.raises(ValueError):
        FleetSpec(jobs=[_job("a")], slots=0)
    with pytest.raises(TypeError):
        FleetSpec(jobs=[Job(key="a", build="not-callable")])
    spec = FleetSpec(jobs=[_job("a"), _job("b")], slots=3)
    assert spec.slots == 3 and len(spec.jobs) == 2


def test_job_engine_kw_hints_then_overrides():
    j = Job(key="a", build=lambda: FakeBuilder(), capacity=1 << 10,
            batch=64, queue_capacity=2048, steps_per_call=8,
            spawn_kw={"batch": 128})
    kw = j.engine_kw()
    assert kw["capacity"] == 1024
    assert kw["batch"] == 128  # explicit spawn_kw wins over the hint
    assert kw["queue_capacity"] == 2048 and kw["steps_per_call"] == 8


def test_preemption_plan_is_one_shot_per_key():
    p = PreemptionPlan({"a": 3})
    assert not p.due("a", 2)
    assert p.due("a", 3)
    assert not p.due("a", 4)  # fired once, never again
    assert not p.due("b", 99)  # unplanned keys never fire


# -- admission (capacity_plan pricing under simulated budgets) ---------------


def test_admission_host_side_and_unbudgeted_jobs_admit(monkeypatch):
    monkeypatch.delenv("STATERIGHT_TPU_DEVICE_BYTES", raising=False)
    # twin-less model: host-side check, no HBM ladder to price
    j = _job("a")
    d, reason, _b = _sched([j])._admit(j)
    assert d == ADMITTED and "host-side" in reason
    # a priced model with no budget known degrades to admission (the
    # capacity verb's rule), loudly
    jp = Job(key="2pc", build=_build_2pc(3), capacity=1 << 12, batch=256)
    d, reason, _b = _sched([jp])._admit(jp)
    assert d == ADMITTED and "budget" in reason
    # ...and a roomy budget admits with nothing to report
    monkeypatch.setenv("STATERIGHT_TPU_DEVICE_BYTES", str(100 * 10**9))
    d, reason, _b = _sched([jp])._admit(jp)
    assert d == ADMITTED and reason is None


def test_admission_refuses_and_spills_under_budgets(monkeypatch):
    # a budget the requested capacity cannot even start under: REFUSED
    tiny = Job(key="2pc", build=_build_2pc(3), capacity=1 << 20,
               batch=1024)
    monkeypatch.setenv("STATERIGHT_TPU_DEVICE_BYTES", "1000000")
    d, reason, _b = _sched([tiny])._admit(tiny)
    assert d == REFUSED and "budget" in reason
    # demand beyond the ladder's reach: REFUSED without spill...
    big = Job(key="2pc-big",
              build=_build_2pc(3, target_states=10_000_000),
              capacity=1 << 12, batch=256)
    monkeypatch.setenv("STATERIGHT_TPU_DEVICE_BYTES", "30000000")
    d, reason, _b = _sched([big])._admit(big)
    assert d == REFUSED and "demand" in reason
    # ...and routed to the host tier with spill enabled
    d, reason, _b = _sched([big], spill=True)._admit(big)
    assert d == ADMITTED_SPILL and "spill" in reason.lower()


def test_twin_less_job_runs_the_host_engine():
    """A REAL model with no tensor twin is served by the host BFS
    engine in its slot (unsupervised, the packed-cohort rule) — never
    spawned on the device engine it cannot run on."""
    from stateright_tpu.core import Model, Property

    class Ring(Model):
        n = 4

        def init_states(self):
            return [0]

        def actions(self, state):
            return [("tick",)]

        def next_state(self, state, action):
            return (state + 1) % self.n

        def properties(self):
            return [
                Property.sometimes("wrapped", lambda m, s: s == self.n - 1)
            ]

    sched = _sched([Job(key="ring", build=lambda: Ring().checker())])
    res = sched.run()
    r = res["ring"]
    assert r.status == COMPLETED and r.decision == ADMITTED
    assert "host-side" in r.reason
    assert r.unique == 4 and r.discoveries == ["wrapped"]
    assert res.engine_compiles == 0  # nothing compiled for the device


# -- cohort packing ----------------------------------------------------------


def test_pack_groups_same_shape_admitted_jobs():
    jobs = [
        Job(key="a", build=_build_2pc(3), packable=True),
        Job(key="b", build=_build_2pc(3), packable=True),
        Job(key="c", build=_build_2pc(4), packable=True),  # other shape
        Job(key="d", build=_build_2pc(3), packable=False),  # opted out
    ]
    packed, leftover = _sched(jobs, slots=2)._pack(
        [(j, ADMITTED, None) for j in jobs]
    )
    assert len(packed) == 1
    members, cohort_id = packed[0]
    assert sorted(j.key for j in members) == ["a", "b"]
    assert cohort_id.startswith("pack-")
    # the different-shape and opted-out jobs fall back to singletons
    assert sorted(j.key for j, _d, _r in leftover) == ["c", "d"]


def test_pack_disabled_spilled_or_unsignable_yields_singletons():
    jobs = [_job("a", packable=True), _job("b", packable=True)]
    admitted = [(j, ADMITTED, None) for j in jobs]
    # pack=False: nobody packs
    packed, leftover = _sched(jobs, slots=1, pack=False)._pack(admitted)
    assert packed == [] and len(leftover) == 2
    # pack=True but twin-less fakes cannot shape-sign: loud singleton
    # fallback (reason pack_fallback), never a crash
    packed, leftover = _sched(jobs, slots=1)._pack(admitted)
    assert packed == []
    assert [r for _j, _d, r in leftover] == ["pack_fallback"] * 2
    # spill-admitted jobs never pack (the sweep engine rejects spill)
    real = [
        Job(key="a", build=_build_2pc(3), packable=True),
        Job(key="b", build=_build_2pc(3), packable=True),
    ]
    packed, leftover = _sched(real, slots=1)._pack(
        [(j, ADMITTED_SPILL, "spilled") for j in real]
    )
    assert packed == [] and len(leftover) == 2


# -- scheduling, priorities, records -----------------------------------------


def test_fleet_runs_jobs_and_respects_priority(tmp_path):
    order = []

    def tracked(key):
        b = FakeBuilder(unique=3, states=5, depth=1)
        real = b.spawn_tpu

        def spy(resume=None, **kw):
            order.append(key)
            return real(resume=resume, **kw)

        b.spawn_tpu = spy
        return lambda: b

    jobs = [
        Job(key="low", build=tracked("low"), priority=0),
        Job(key="high", build=tracked("high"), priority=9),
        Job(key="mid", build=tracked("mid"), priority=5),
    ]
    res = run_fleet(
        FleetSpec(jobs=jobs, slots=1), root=str(tmp_path), stream=None
    )
    assert order == ["high", "mid", "low"]
    assert res.completed == 3 and res.failed == 0 and res.refused == 0
    assert all(r.status == COMPLETED for r in res.results.values())
    # results read back in SPEC order regardless of run order
    assert [r.key for r in res.results.values()] == ["low", "high", "mid"]
    assert res["mid"].unique == 3 and res["mid"].states == 5


def test_fleet_job_failure_is_a_ledger_row_not_a_crash(tmp_path):
    boom = FakeBuilder(
        spawn_plan={0: {"fail": RuntimeError("device on fire")}}
    )
    jobs = [_job("bad", builder=boom), _job("good")]
    res = run_fleet(
        FleetSpec(jobs=jobs, slots=1, max_restarts=0),
        root=str(tmp_path), stream=None,
    )
    assert res.failed == 1 and res.completed == 1
    assert res["bad"].status == FAILED
    assert "device on fire" in (res["bad"].reason or "")
    assert res["good"].status == COMPLETED


def test_fleet_refused_job_never_spawns(tmp_path, monkeypatch):
    monkeypatch.setenv("STATERIGHT_TPU_DEVICE_BYTES", "1000000")
    huge = Job(key="huge", build=_build_2pc(3), capacity=1 << 20,
               batch=1024)
    res = run_fleet(
        FleetSpec(jobs=[huge, _job("ok")], slots=1),
        root=str(tmp_path), stream=None,
    )
    assert res.refused == 1 and res.completed == 1
    assert res["huge"].status == REFUSED and res["huge"].run_id is None
    assert res["huge"].reason and "budget" in res["huge"].reason


def test_injected_stall_preempts_requeues_and_resumes(tmp_path):
    """The chaos cycle with fakes: the victim blocks on the only slot,
    the in-band injection (armed at spawn) forces a stall record on its
    third step, the monitor yields it, the waiting job drains FIRST
    (the re-queue landed the victim behind equal-priority work — that
    is what the yield bought), then the victim resumes and completes —
    with the submit/place/preempt/resume/done trail on the fleet
    recorder."""
    recs = []

    def rf():
        r = FlightRecorder(capacity=256)
        recs.append(r)
        return r

    victim = FakeBuilder(unique=7, states=9, depth=2,
                         recorder_factory=rf,
                         spawn_plan={0: {"block": True}})
    other = FakeBuilder(unique=1, states=2, depth=1)
    jobs = [
        Job(key="victim", build=lambda: victim),
        Job(key="other", build=lambda: other),
    ]
    rec = FlightRecorder(capacity=1024)
    sched = FleetScheduler(
        FleetSpec(jobs=jobs, slots=1), root=str(tmp_path),
        recorder=rec, preemption=PreemptionPlan({"victim": 3}),
        stream=None,
    )
    stop_driving = threading.Event()

    def drive():
        # emit step records on the victim's recorder until the injected
        # stall lands (the in-band seam fires on the crossing step)
        deadline = time.monotonic() + 10.0
        n = 0
        while not stop_driving.is_set() and time.monotonic() < deadline:
            if recs:
                n += 1
                recs[0].step(engine="fake", states=n, unique=n)
                if any(
                    h.get("reason") == "injected"
                    for h in recs[0].records("health")
                ):
                    return
            time.sleep(0.005)

    t = threading.Thread(target=drive, daemon=True)
    t.start()
    try:
        res = sched.run()
    finally:
        stop_driving.set()
        t.join(timeout=5)
    assert res.preemptions == 1
    v = res["victim"]
    assert v.status == COMPLETED and v.preemptions == 1
    assert v.unique == 7 and v.states == 9
    assert res["other"].status == COMPLETED
    # the trail: victim preempted, then the waiting job drained, then
    # the victim resumed — the yield actually bought the slot
    trail = [(r["key"], r["event"]) for r in rec.records("job")]
    assert trail.index(("victim", "preempt")) \
        < trail.index(("other", "done")) \
        < trail.index(("victim", "resume")) \
        < trail.index(("victim", "done"))
    # two spawns: the preempted attempt and the resume
    assert len(victim.spawn_log) == 2
    # pool snapshot reconciles on the shared recorder
    snap = rec.fleet()
    assert snap["v"] == FLEET_V and snap["completed"] == 2
    assert snap["preemptions"] == 1 and snap["running"] == []


def test_fleet_result_json_and_metrics_view(tmp_path):
    res = run_fleet(
        FleetSpec(jobs=[_job("a"), _job("b")], slots=2),
        root=str(tmp_path), stream=None,
    )
    doc = res.to_json()
    assert doc["v"] == FLEET_V and doc["completed"] == 2
    assert len(doc["jobs"]) == 2
    json.dumps(doc)  # JSON-serializable end to end
    # the Explorer pool panel reads the fleet block off /.metrics
    from stateright_tpu.explorer import _metrics_view

    class Host:
        flight_recorder = res.recorder

    view = _metrics_view(Host())
    assert view["fleet"]["slots"] == 2
    assert view["fleet"]["completed"] == 2


# -- campaigns ---------------------------------------------------------------


def test_expand_grid_cross_product_and_validation():
    pts = expand_grid({"b": [1, 2], "a": ["x"]})
    assert pts == [{"a": "x", "b": 1}, {"a": "x", "b": 2}]
    assert expand_grid({}) == [{}]
    assert expand_grid({"a": 3}) == [{"a": 3}]  # scalars auto-wrap
    with pytest.raises(ValueError):
        expand_grid({"a": []})


def test_campaign_spec_maps_grid_points_to_jobs():
    spec = campaign_spec(
        lambda n=3: FakeModel(), {"n": [3, 4]},
        campaign_id="c-test", priority_fn=lambda p: p["n"],
    )
    assert spec.campaign_id == "c-test"
    assert [j.key for j in spec.jobs] == ["n=3", "n=4"]
    assert [j.priority for j in spec.jobs] == [3, 4]
    assert all(j.packable for j in spec.jobs)
    assert spec.jobs[0].params == {"n": 3}
    # an omitted campaign_id still mints one (the grouping tag)
    anon = campaign_spec(lambda n=3: FakeModel(), {"n": [3]})
    assert anon.campaign_id


class _CampaignModel(FakeModel):
    """A fake model whose ``.checker()`` yields a FakeBuilder — the
    campaign build path prefers a model-provided checker factory."""

    def __init__(self, n):
        self.n = int(n)

    def checker(self):
        return FakeBuilder(unique=self.n, states=2 * self.n, depth=1)


def test_run_campaign_writes_the_ledger(tmp_path):
    spec = campaign_spec(_CampaignModel, {"n": [3, 5]},
                         campaign_id="c-led")
    res, ledger = run_campaign(spec, root=str(tmp_path), stream=None)
    assert res.completed == 2
    assert ledger["v"] == FLEET_V and ledger["campaign_id"] == "c-led"
    assert ledger["completed"] == 2 and ledger["failed"] == 0
    assert ledger["total_states"] == 6 + 10
    assert {r["key"] for r in ledger["results"]} == {"n=3", "n=5"}
    on_disk = json.loads((tmp_path / LEDGER_NAME).read_text())
    assert on_disk == ledger
    assert build_ledger(spec, res)["total_states"] == 16


def test_run_campaign_ledger_write_failure_degrades_loudly(tmp_path):
    target = tmp_path / "blocked"
    target.write_text("a file where the campaign root must go")
    spec = campaign_spec(_CampaignModel, {"n": [1]})
    err = io.StringIO()
    res, ledger = run_campaign(spec, root=str(target), stream=err)
    assert res.completed == 1  # the answer survives the artifact
    assert ledger["completed"] == 1
    assert "ledger write failed" in err.getvalue()


# -- CLI surfaces ------------------------------------------------------------


def test_pop_fleet_opts_parses_shared_flags():
    from stateright_tpu.models._cli import _pop_fleet_opts

    opts, rest = _pop_fleet_opts(
        ["--slots=4", "--budget=1000", "--spill", "--no-pack",
         "--root=/r", "--runs=/q", "--every=0.5", "--stall=k@7",
         "--max-restarts=1", "--id=cid", "--grid={\"a\":[1]}",
         "positional"],
        {"slots": 2, "budget": None, "spill": False, "pack": True,
         "root": None, "runs": None, "every": 0.0, "stall": None,
         "max_restarts": 2, "id": None, "grid": None},
    )
    assert opts["slots"] == 4 and opts["budget"] == 1000
    assert opts["spill"] is True and opts["pack"] is False
    assert opts["root"] == "/r" and opts["runs"] == "/q"
    assert opts["every"] == 0.5 and opts["stall"] == "k@7"
    assert opts["max_restarts"] == 1 and opts["id"] == "cid"
    assert json.loads(opts["grid"]) == {"a": [1]}
    assert rest == ["positional"]


def test_campaign_verb_rejects_unknown_factory():
    from stateright_tpu.models._cli import fleet_campaign

    out = io.StringIO()
    assert fleet_campaign(["nope"], stream=out) == 2
    assert "usage: campaign" in out.getvalue()


def test_runs_verb_groups_campaign_jobs(tmp_path):
    from stateright_tpu.models._cli import fleet_runs

    reg = tmp_path / "runs"
    reg.mkdir()
    recs = [
        {"v": 1, "run_id": f"r{i}", "config_key": "cfg",
         "model": "M", "engine": "wavefront",
         "campaign_id": "camp-1", "job_key": f"job-{i}",
         "headline": {"unique": 10 + i, "done": True,
                      "discoveries": ["p"] if i else []},
         "generated_at": "2026-08-07T00:00:00+00:00"}
        for i in range(2)
    ]
    with open(reg / "index.jsonl", "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    out = io.StringIO()
    assert fleet_runs([str(reg)], stream=out) == 0
    text = out.getvalue()
    assert "campaign camp-1  2 job(s)  verdicts [.*]" in text
    assert "[job-0]" in text and "[job-1]" in text


# -- zero-coupling contract --------------------------------------------------


def test_engine_modules_never_import_the_fleet():
    """Fleet off ⇒ zero coupling: no engine/checker/sweep/telemetry
    module may import stateright_tpu.fleet (the scheduler calls INTO
    the engines, never the reverse), so a fleet-less run's jaxprs and
    cache keys cannot change by construction."""
    import stateright_tpu

    root = os.path.dirname(stateright_tpu.__file__)
    offenders = []
    for sub in ("parallel", "checker", "sweep", "telemetry", "spill",
                "ops"):
        for dirpath, _dirs, files in os.walk(os.path.join(root, sub)):
            for name in files:
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                with open(path) as f:
                    src = f.read()
                for ln in src.splitlines():
                    stmt = ln.strip().split("#")[0]
                    if not (stmt.startswith("import ")
                            or stmt.startswith("from ")):
                        continue
                    if "fleet" in stmt:
                        offenders.append(
                            f"{os.path.relpath(path, root)}: {stmt}"
                        )
    assert not offenders, (
        f"engine modules import the fleet subsystem: {offenders}"
    )


def test_supervisor_yield_event_is_optional(tmp_path):
    """The cooperative-yield hook must be pay-for-use: supervise()
    without yield_event completes exactly as before PR 17 (the PR 13
    surface is unchanged for existing callers)."""
    from stateright_tpu.supervisor import supervise

    b = FakeBuilder(unique=4, states=6, depth=1)
    run = supervise(b, autosave_dir=str(tmp_path), every_secs=60)
    assert run.yielded is False
    assert run.unique_state_count() == 4


# -- medium tier: real-engine acceptance -------------------------------------


@pytest.mark.medium
def test_fleet_acceptance_packs_and_matches_solo_counts(tmp_path):
    """The N-job acceptance (docs/fleet.md): three packable 2pc-3
    tenants + a 2pc-4 singleton over a 2-slot pool.  Every count must
    be bit-identical to the solo pins, the three same-shape jobs must
    share ONE cohort engine compile (compile accounting strictly below
    the job count), and the registry must group every member under the
    campaign tag."""
    runs = str(tmp_path / "runs")

    def job(key, n, packable, cap):
        return Job(key=key, build=_build_2pc(n, runs=runs),
                   packable=packable, capacity=cap, batch=256)

    jobs = [
        job("2pc3-a", 3, True, 1 << 12),
        job("2pc3-b", 3, True, 1 << 12),
        job("2pc3-c", 3, True, 1 << 12),
        job("2pc4", 4, False, 1 << 13),
    ]
    res = run_fleet(
        FleetSpec(jobs=jobs, slots=2, campaign_id="camp-accept"),
        root=str(tmp_path / "fleet"), stream=None,
    )
    assert res.completed == 4 and res.failed == 0 and res.refused == 0
    for k in ("2pc3-a", "2pc3-b", "2pc3-c"):
        assert (res[k].unique, res[k].states) == (288, 1146), k
        assert res[k].cohort  # rode a packed cohort
    assert (res["2pc4"].unique, res["2pc4"].states) == (1568, 8258)
    assert res["2pc4"].cohort is None
    # compile amortization: 1 cohort compile + 1 singleton compile
    assert res.engine_compiles < len(jobs)
    assert res.engine_compiles == 2
    assert sum(len(p["jobs"]) for p in res.packed) == 3
    # every job archived under the campaign tag (packed members too)
    from stateright_tpu.telemetry.registry import RunRegistry

    idx = RunRegistry(runs).index()
    tagged = [r for r in idx if r.get("campaign_id") == "camp-accept"]
    assert {r.get("job_key") for r in tagged} == {
        "2pc3-a", "2pc3-b", "2pc3-c", "2pc4",
    }


@pytest.mark.medium
def test_fleet_acceptance_preempt_resume_exactly_once(tmp_path):
    """The exactly-once acceptance (docs/fleet.md): an injected stall
    preempts the victim mid-run (snapshot + yield), the victim resumes
    from its final autosave generation, and the parent/child report
    pair classifies IDENTICAL under the lineage contract — same final
    counts as an uninterrupted run."""
    from stateright_tpu.models._cli import compare_reports_cmd

    runs = str(tmp_path / "runs")
    jobs = [
        Job(key="victim", build=_build_2pc(4, runs=runs),
            capacity=1 << 13, batch=256),
        Job(key="other", build=_build_2pc(3, runs=runs),
            capacity=1 << 12, batch=256),
    ]
    res = run_fleet(
        FleetSpec(jobs=jobs, slots=1),
        root=str(tmp_path / "fleet"),
        preemption=PreemptionPlan({"victim": 2}),
        every_secs=0.2, stream=None,
    )
    assert res.completed == 2 and res.preemptions == 1
    v = res["victim"]
    assert v.status == COMPLETED and v.preemptions == 1
    # exactly-once: the solo pin, not a partial and not a double-count
    assert (v.unique, v.states) == (1568, 8258)
    assert v.parent_run_id and v.run_id
    assert (res["other"].unique, res["other"].states) == (288, 1146)
    out = io.StringIO()
    rc = compare_reports_cmd(
        [v.parent_run_id, v.run_id, f"--registry={runs}",
         "--expect=IDENTICAL"],
        out,
    )
    assert rc == 0, out.getvalue()
    assert "IDENTICAL (contract: lineage)" in out.getvalue()
