"""Multi-controller SPMD: the sharded engine across separate processes.

The reference scales across threads of one process (``bfs.rs:70-151``);
the brief's distributed requirement is a communication backend that
scales to multi-host.  This test runs the sharded wavefront engine as
TRUE multi-controller SPMD — two OS processes, each owning half the
device mesh, coordinated by ``jax.distributed`` (the same control plane
a multi-host TPU pod uses) — and requires both controllers to agree on
the pinned 2pc-3 space (288 unique) and reconstruct valid discovery
paths from the all-gathered table.

CPU analogue of: one process per TPU host, collectives over ICI/DCN.
"""

import os
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_sharded_engine_multi_controller_2pc3():
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    port = _free_port()
    # children must NOT inherit this process's 8-virtual-device XLA_FLAGS
    # (each worker sets its own 4-device split) nor a preset platform
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"multihost-worker-ok p{pid}" in out, out[-2000:]


def test_async_run_thread_error_surfaces_at_join(monkeypatch):
    """A single-controller-only path hit inside an ASYNC run (e.g. mid-run
    growth under multi-controller SPMD) must raise at join(), not leave a
    forever-undone checker with counters silently reading 0."""
    import pytest

    from stateright_tpu.models.two_phase_commit import TwoPhaseSys
    from stateright_tpu.parallel import sharded

    m = TwoPhaseSys(4)
    # simulate a second controller process so the growth guard trips; the
    # tiny capacity forces a mid-run growth event
    monkeypatch.setattr(sharded.jax, "process_count", lambda: 2)
    c = m.checker().spawn_tpu(
        sync=False, devices=8, capacity=1 << 8, frontier_capacity=1 << 5
    )
    with pytest.raises(NotImplementedError, match="single-controller"):
        c.join()
