"""Multi-controller SPMD: the sharded engine across separate processes.

The reference scales across threads of one process (``bfs.rs:70-151``);
the brief's distributed requirement is a communication backend that
scales to multi-host.  This test runs the sharded wavefront engine as
TRUE multi-controller SPMD — two OS processes, each owning half the
device mesh, coordinated by ``jax.distributed`` (the same control plane
a multi-host TPU pod uses) — and requires both controllers to agree on
the pinned 2pc-3 space (288 unique) and reconstruct valid discovery
paths from the all-gathered table.

CPU analogue of: one process per TPU host, collectives over ICI/DCN.
"""

import os
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_sharded_engine_multi_controller_2pc3():
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    port = _free_port()
    # children must NOT inherit this process's 8-virtual-device XLA_FLAGS
    # (each worker sets its own 4-device split) nor a preset platform
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"multihost-worker-ok p{pid}" in out, out[-2000:]
