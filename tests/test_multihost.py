"""Multi-controller SPMD: the sharded engine across separate processes.

The reference scales across threads of one process (``bfs.rs:70-151``);
the brief's distributed requirement is a communication backend that
scales to multi-host.  This test runs the sharded wavefront engine as
TRUE multi-controller SPMD — two OS processes, each owning half the
device mesh, coordinated by ``jax.distributed`` (the same control plane
a multi-host TPU pod uses) — and requires both controllers to agree on
the pinned 2pc-3 space (288 unique) and reconstruct valid discovery
paths from the all-gathered table.

CPU analogue of: one process per TPU host, collectives over ICI/DCN.
"""

import os
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_sharded_engine_multi_controller_2pc3():
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    port = _free_port()
    # children must NOT inherit this process's 8-virtual-device XLA_FLAGS
    # (each worker sets its own 4-device split) nor a preset platform
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"multihost-worker-ok p{pid}" in out, out[-2000:]
        # lockstep growth: both controllers grew at the same boundaries and
        # still landed the pinned count with monotone counters
        assert f"multihost-growth-ok p{pid}" in out, out[-2000:]


def test_lockstep_growth_not_fenced_under_multi_controller(monkeypatch):
    """Mid-run growth no longer raises under multi-controller SPMD (the
    round-4 fence): with a simulated second controller, a run forced to
    grow completes via the per-shard lockstep transform."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys
    from stateright_tpu.parallel import sharded

    m = TwoPhaseSys(4)
    monkeypatch.setattr(sharded.jax, "process_count", lambda: 2)
    c = m.checker().spawn_tpu(
        sync=True, devices=8, capacity=1 << 8, frontier_capacity=1 << 5
    )
    assert c.unique_state_count() == 1568  # pinned 2pc@4
    assert len(c.growth_events) >= 1
    uniq = [u for _, u in c.growth_events]
    assert uniq == sorted(uniq)


def test_async_run_thread_error_surfaces_at_join(monkeypatch):
    """An error raised inside an ASYNC run thread must raise at join(),
    not leave a forever-undone checker with counters silently reading 0.
    (The engine build happens inside the run thread on cache miss, so a
    build failure is a faithful run-thread error.)"""
    import pytest

    from stateright_tpu.models.two_phase_commit import TwoPhaseSys
    from stateright_tpu.parallel import sharded

    def boom(*a, **k):
        raise RuntimeError("boom in run thread")

    monkeypatch.setattr(sharded, "_build_sharded_run", boom)
    c = TwoPhaseSys(3).checker().spawn_tpu(
        sync=False, devices=8, capacity=1 << 13, frontier_capacity=1 << 9
    )
    with pytest.raises(RuntimeError, match="boom in run thread"):
        c.join()
