"""Multi-device sharded wavefront engine parity (8 virtual CPU devices).

The sharded engine (mesh + all-to-all fingerprint routing,
``stateright_tpu/parallel/sharded.py``) must reproduce exactly the counts and
discoveries of the single-device engine and the CPU oracle — the same parity
bar the reference pins for its multithreaded checkers (reference
``examples/2pc.rs:125-140``).
"""

import numpy as np
import pytest

import jax

from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.parallel.sharded import ShardedTpuChecker, default_mesh


def test_default_mesh_uses_all_devices():
    mesh = default_mesh()
    assert mesh.shape["d"] == len(jax.devices()) == 8


@pytest.mark.parametrize("n,expected", [(3, 288), (5, 8832)])
def test_sharded_2pc_pinned_counts(n, expected):
    sys = TwoPhaseSys(n)
    checker = sys.checker().spawn_tpu(devices=8, sync=True)
    assert isinstance(checker, ShardedTpuChecker)
    assert checker.unique_state_count() == expected
    cpu = sys.checker().spawn_bfs().join()
    assert cpu.unique_state_count() == expected
    assert checker.state_count() == cpu.state_count()
    assert set(checker.discoveries()) == set(cpu.discoveries()) == {
        "abort agreement",
        "commit agreement",
    }
    checker.assert_properties()


def test_sharded_discovery_paths_are_valid_and_shortest():
    sys = TwoPhaseSys(3)
    checker = sys.checker().spawn_tpu(devices=8, sync=True)
    cpu = sys.checker().spawn_bfs().join()  # single-thread BFS: shortest paths
    for name in ("abort agreement", "commit agreement"):
        path = checker.discovery(name)
        cond = sys.property_by_name(name).condition
        assert cond(sys, path.final_state())
        # level-synchronous wavefront => shortest witness, like 1-thread BFS
        assert len(path) == len(cpu.discovery(name))


def test_sharded_capacity_overflow_grows():
    sys = TwoPhaseSys(3)
    checker = sys.checker().spawn_tpu(
        devices=8, sync=True, capacity=1 << 8, frontier_capacity=1 << 5
    )
    assert checker.unique_state_count() == 288
    checker.assert_properties()


@pytest.mark.medium
def test_sharded_growth_preserves_work_mid_flight():
    """Capacities far below the state space force mid-run growth events;
    the atomic-step + host-grow protocol must preserve all work: pinned
    counts, discovery parity with the CPU oracle, and a monotone unique
    counter across every growth boundary (the old engine restarted from
    scratch and reset counters — VERDICT r2 missing #4)."""
    sys = TwoPhaseSys(5)
    checker = sys.checker().spawn_tpu(
        devices=8, sync=True, capacity=1 << 10, frontier_capacity=1 << 7,
        steps_per_call=1,
    )
    assert checker.unique_state_count() == 8832  # examples/2pc.rs:133
    cpu = sys.checker().spawn_bfs().join()
    assert checker.state_count() == cpu.state_count()
    assert set(checker.discoveries()) == set(cpu.discoveries())
    # growth really happened mid-flight, and never lost progress
    assert checker.growth_events, "capacities were too generous to test growth"
    uniq = [u for _, u in checker.growth_events]
    assert uniq == sorted(uniq)
    assert all(0 < u <= 8832 for u in uniq)


@pytest.mark.medium
def test_sharded_growth_boundary_checkpoint_resume():
    """A snapshot carrying a growth-boundary flag (status != OK) must grow
    on resume and still finish with pinned counts.  A checkpoint request
    served at a growth boundary produces exactly this snapshot shape; the
    boundary statuses are forced here so the test is deterministic."""
    kw = dict(devices=8, capacity=1 << 13, frontier_capacity=1 << 9,
              steps_per_call=1)
    running = TwoPhaseSys(5).checker().spawn_tpu(**kw)
    snap = running.checkpoint(timeout=120.0)
    running.stop().join()
    assert 0 < int(snap["unique"]) < 8832, "checkpoint was not mid-run"
    for status in (2, 1):  # _TABLE_OVERFLOW (shard rehash), _FRONTIER (pad)
        s = dict(snap)
        s["status"] = np.int32(status)
        resumed = TwoPhaseSys(5).checker().spawn_tpu(
            sync=True, resume=s, **kw
        )
        assert resumed.unique_state_count() == 8832
        resumed.assert_properties()


def test_sharded_target_state_count():
    sys = TwoPhaseSys(5)
    checker = sys.checker().target_states(1000).spawn_tpu(devices=8, sync=True)
    assert 1000 <= checker.unique_state_count() < 8832


def test_sharded_matches_single_device_table_contents():
    """Every fingerprint the single-device engine visits must appear in the
    union of the sharded engine's table shards, and vice versa."""
    sys = TwoPhaseSys(3)
    single = sys.checker().spawn_tpu(sync=True)
    sharded = sys.checker().spawn_tpu(devices=8, sync=True)
    assert set(single._parents()) == set(sharded._parents())
    # parent pointers may differ (different wave tie-breaks) but each parent
    # must itself be a visited state or 0 (init marker)
    visited = set(sharded._parents())
    for fp, parent in sharded._parents().items():
        assert parent == 0 or parent in visited


def test_sharded_on_two_devices():
    sys = TwoPhaseSys(3)
    checker = sys.checker().spawn_tpu(devices=2, sync=True)
    assert checker.unique_state_count() == 288


def test_sharded_live_progress_counters():
    """The chunked host loop surfaces live counters mid-run (the old
    whole-run jit call hid everything until completion)."""
    import time

    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    checker = TwoPhaseSys(5).checker().spawn_tpu(
        devices=8, capacity=1 << 17, frontier_capacity=1 << 12,
        steps_per_call=1,
    )
    samples = []
    while not checker.is_done():
        samples.append(checker.unique_state_count())
        time.sleep(0.05)
    checker.join()
    assert checker.unique_state_count() == 8832
    # monotone live counters (no overflow restart at these capacities)
    assert samples == sorted(samples)


@pytest.mark.medium
def test_sharded_checkpoint_resume_matches_uninterrupted():
    """Stop a sharded run mid-flight, snapshot, resume on a fresh checker:
    final counts and discoveries must match the uninterrupted run."""
    import numpy as np

    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    kw = dict(devices=8, capacity=1 << 15, frontier_capacity=1 << 10,
              steps_per_call=1)
    full = TwoPhaseSys(5).checker().spawn_tpu(sync=True, **kw)

    # start async, snapshot early, stop, resume from the snapshot
    running = TwoPhaseSys(5).checker().spawn_tpu(**kw)
    snap = running.checkpoint()
    running.stop().join()
    resumed = TwoPhaseSys(5).checker().spawn_tpu(sync=True, resume=snap, **kw)
    assert resumed.unique_state_count() == full.unique_state_count() == 8832
    assert set(resumed.discoveries()) == set(full.discoveries())
    # snapshots survive a real savez/load round trip AND resume from the
    # loaded NpzFile (0-d scalars, ndev coercion, key set)
    import io

    buf = io.BytesIO()
    np.savez(buf, **snap)
    buf.seek(0)
    loaded = dict(np.load(buf, allow_pickle=False))
    resumed2 = TwoPhaseSys(5).checker().spawn_tpu(
        sync=True, resume=loaded, **kw
    )
    assert resumed2.unique_state_count() == 8832


def test_sharded_resume_rejects_other_model_or_mesh():
    import pytest

    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    kw = dict(devices=8, capacity=1 << 13, frontier_capacity=1 << 9)
    c = TwoPhaseSys(3).checker().spawn_tpu(sync=True, **kw)
    snap = c.checkpoint()
    with pytest.raises(ValueError, match="different model"):
        TwoPhaseSys(4).checker().spawn_tpu(sync=True, resume=snap, **kw)
    with pytest.raises(ValueError, match="mesh"):
        TwoPhaseSys(3).checker().spawn_tpu(
            sync=True, devices=4, capacity=1 << 13, frontier_capacity=1 << 9,
            resume=snap,
        )
    # cross-engine confusion is caught, both directions
    with pytest.raises(ValueError, match="engine"):
        TwoPhaseSys(3).checker().spawn_tpu(sync=True, resume=snap)
    single_snap = TwoPhaseSys(3).checker().spawn_tpu(sync=True).checkpoint()
    with pytest.raises(ValueError, match="engine"):
        TwoPhaseSys(3).checker().spawn_tpu(sync=True, resume=single_snap, **kw)
