"""Static independence analysis → partial-order reduction
(``analysis/footprint.py``, ``analysis/independence.py``, ``ops/por.py``,
and both device engines' ample-set successor generation).

The load-bearing contracts pinned here:

 - footprints are BIT-exact on the flagship hand-written twin (2pc): the
   per-action write/guard masks equal the hand-derived BitPacker fields;
 - the conflict matrix is symmetric, dependent on the diagonal, and every
   UNDECIDABLE site defaults to dependent (paxos/dining: the
   slot-multiset twins do not decompose — JX302 — and their matrices are
   all-dependent);
 - ``por()`` OFF leaves the run jaxpr BIT-IDENTICAL (the
   telemetry/checked/prededup discipline); ON, property verdicts are
   identical everywhere — with a strict generated-candidate reduction on
   the locality-structured fixtures (``fixtures_por.py``) and EXACT
   count/table parity on 2pc, whose verdict-relevant actions are all
   property-visible (the C2 invisibility condition — the honest result
   of a sound analysis, documented in docs/analysis.md);
 - the cycle proviso (all-ample-duplicates ⇒ full expansion) is what
   keeps the toggle fixture's visible action reachable;
 - POR composes with symmetry and prededup, and survives kill+resume.
"""

import numpy as np
import pytest

import jax

from fixtures_por import ToggleSys, WorkersSys
from helpers import requires_sharded_collectives

from stateright_tpu.analysis.footprint import (
    FieldSet,
    conjunct_eval_fn,
    extract_footprints,
)
from stateright_tpu.analysis.independence import por_plan, run_independence
from stateright_tpu.models.two_phase_commit import TwoPhaseSys

TPC3_UNIQUE, TPC3_STATES = 288, 1146
WORKERS7_FULL = (2187, 10207)  # 3^7 unique; generated + 1 init
WORKERS7_POR = (15, 15)  # linear in n: the reduction the analysis buys
TOGGLE_FULL = (4, 7)
TOGGLE_POR = (4, 6)  # strictly fewer generated candidates


# -- footprints (2pc is the bit-exactness oracle) ----------------------------


def _tpc3_footprints():
    return extract_footprints(TwoPhaseSys(3)._tensor_cached())


def test_2pc_footprints_are_bit_exact():
    fp = _tpc3_footprints()
    assert fp.decomposed and fp.n_actions == 17
    assert all(a.decided for a in fp.actions)
    # layout: rm 2b*3 @0, tm 2b @6, tm_prepared 3b @8, msg_prepared 3b
    # @11, msg_commit @14, msg_abort @15
    def masks(a):
        return (
            fp.actions[a].writes.to_json(),
            fp.actions[a].guard.to_json(),
        )

    assert masks(0) == ({"0": "0x40c0"}, {"0": "0x7c0"})  # tm_commit
    assert masks(1) == ({"0": "0x80c0"}, {"0": "0xc0"})  # tm_abort
    # per-RM block for RM 0: slots 2..6
    assert masks(2) == ({"0": "0x100"}, {"0": "0x8c0"})  # tm_rcv_prepared
    assert masks(3) == ({"0": "0x803"}, {"0": "0x3"})  # rm_prepare
    assert masks(4) == ({"0": "0x3"}, {"0": "0x3"})  # rm_choose_abort
    assert masks(5) == ({"0": "0x3"}, {"0": "0x4000"})  # rm_rcv_commit
    assert masks(6) == ({"0": "0x3"}, {"0": "0x8000"})  # rm_rcv_abort
    # every property reads exactly the rm field
    assert [p.to_json() for p in fp.prop_reads] == [{"0": "0x3f"}] * 3


def test_2pc_guard_conjuncts_and_kernel_agree_with_guard():
    import jax.numpy as jnp

    m = TwoPhaseSys(3)
    t = m._tensor_cached()
    fp = extract_footprints(t)
    cj = fp.conjuncts
    assert cj is not None and cj.n_leaves == 17 and cj.max_conjuncts == 2
    # tm_commit = (tm == init) AND (all prepared): two conjuncts with the
    # tm / tm_prepared read sets
    assert [s.to_json() for s in cj.sets[0]] == [
        {"0": "0xc0"}, {"0": "0x700"}
    ]
    fn = conjunct_eval_fn(t)
    rows = jnp.asarray(np.asarray(t.init_rows(), np.uint64))
    leaves = [np.asarray(x) for x in fn(rows)]
    _, valid = t.step_rows(rows)
    v = np.asarray(valid)[0]
    for a in range(fp.n_actions):
        idx = cj.leaf_idx[a]
        assert idx is not None
        assert v[a] == all(
            bool(leaves[j][0] if lane is None else leaves[j][0, lane])
            for (j, lane) in idx
        )


def test_fieldset_top_is_conservative():
    top = FieldSet.top_set()
    assert top.intersects(FieldSet.of(0, 1))
    assert top.intersects(top)
    assert not top.intersects(FieldSet.empty())
    assert FieldSet.of(0, 0b1100).intersects(FieldSet.of(0, 0b0100))
    assert not FieldSet.of(0, 0b1100).intersects(FieldSet.of(0, 0b0011))
    assert not FieldSet.of(0, 1).intersects(FieldSet.of(1, 1))


# -- the conflict matrix ------------------------------------------------------


def test_2pc_conflict_matrix_pins():
    m = TwoPhaseSys(3)
    rep = run_independence(m._tensor_cached(), list(m.properties()))
    c = rep.conflict
    assert c.shape == (17, 17)
    assert np.array_equal(c, c.T) and c.diagonal().all()
    assert rep.independent_pairs == 102
    # per-RM blocks: RM0's rm_prepare is independent of every RM1 action
    for j in range(7, 12):
        assert not c[3, j]
    # tm_commit writes msg_commit, which guards every rm_rcv_commit
    for i in range(3):
        assert c[0, 5 + 5 * i]
    # visibility: every rm-writing action is visible to the properties
    # (they read the whole rm field) — the C2 reason 2pc cannot reduce
    assert rep.visible.sum() == 12
    assert not rep.visible[0] and not rep.visible[1]  # tm actions


def test_undecidable_defaults_to_dependent_on_slot_multiset_twins():
    """paxos's per-slot delivery writes are data-dependent (dst comes from
    the message): the kernel does not decompose, JX302 fires, and the
    matrix is conservatively ALL-dependent — the acceptance contract that
    undecidable pairs can never claim independence."""
    from stateright_tpu.models.paxos import paxos_model

    m = paxos_model(2)
    rep = run_independence(m._tensor_cached(), list(m.properties()))
    assert not rep.footprints.decomposed
    assert rep.independent_pairs == 0
    assert rep.conflict.all()
    assert "JX302" in {f.rule_id for f in rep.findings}
    plan = por_plan(m._tensor_cached(), list(m.properties()))
    assert not plan.usable


def test_por_plan_fallback_reasons():
    from stateright_tpu.models.dining import dining_model

    dm = dining_model(3)
    plan = por_plan(dm._tensor_cached(), list(dm.properties()))
    assert not plan.usable
    assert "eventually" in plan.fallback_reason
    rep = run_independence(dm._tensor_cached(), list(dm.properties()))
    assert "JX304" in {f.rule_id for f in rep.findings}

    wm = WorkersSys(4)
    wplan = por_plan(wm._tensor_cached(), list(wm.properties()))
    assert wplan.usable and wplan.fallback_reason is None
    # workers 1..3 are invisible; worker 0 is visible to both properties
    assert list(wplan.visible.astype(int)) == [1, 0, 0, 0]


def test_jx301_undecidable_action_is_dependent_on_everything():
    """A kernel that decomposes but contains one data-dependent write
    (scatter with a traced index) gets JX301 on that action, whose
    conflict row is all-True."""
    from stateright_tpu.core import Property
    from stateright_tpu.parallel.tensor_model import BitPacker, TensorModel

    class OneBad(TensorModel):
        def __init__(self):
            self.packer = BitPacker([("a", 4), ("b", 4)])
            self.width = 2  # word 1 is an extra scratch word
            self.max_actions = 2
            self.model = None

        def init_rows(self):
            return np.zeros((1, 2), np.uint64)

        def step_rows(self, rows):
            import jax.numpy as jnp

            pk = self.packer
            a = pk.get(rows, "a")
            s0 = pk.set(rows, "a", jnp.minimum(a + jnp.uint64(1),
                                               jnp.uint64(15)))
            # data-dependent write: the target word comes from a field
            idx = (a & jnp.uint64(1)).astype(jnp.int32)
            s1 = jnp.stack([rows[..., 0], rows[..., 1]], -1)
            s1 = jnp.take_along_axis(
                jnp.broadcast_to(s1[..., None], s1.shape + (2,)),
                idx[..., None, None], axis=-1,
            )[..., 0]
            return (
                jnp.stack([s0, s1], -2),
                jnp.stack([a < jnp.uint64(15),
                           jnp.ones_like(a, bool)], -1),
            )

        def property_masks(self, rows):
            import jax.numpy as jnp

            return jnp.stack(
                [self.packer.get(rows, "a") <= jnp.uint64(15)], -1
            )

    t = OneBad()
    rep = run_independence(t, [Property.always("p", lambda m, s: True)])
    assert rep.footprints.decomposed
    und = rep.footprints.undecided_actions
    assert und == [1]
    assert rep.conflict[1].all() and rep.conflict[:, 1].all()
    assert "JX301" in {f.rule_id for f in rep.findings}


# -- JX303: the vacuous-property lint (satellite) ----------------------------


def test_jx303_fires_on_property_reading_never_written_field():
    from stateright_tpu.core import Property
    from stateright_tpu.parallel.tensor_model import BitPacker, TensorModel

    class DeadProp(TensorModel):
        def __init__(self):
            self.packer = BitPacker([("live", 2), ("frozen", 2)])
            self.width = 1
            self.max_actions = 1
            self.model = None

        def init_rows(self):
            return np.zeros((1, 1), np.uint64)

        def step_rows(self, rows):
            import jax.numpy as jnp

            pk = self.packer
            v = pk.get(rows, "live")
            return (
                jnp.stack(
                    [pk.set(rows, "live", v + jnp.uint64(1))], -2
                ),
                jnp.stack([v < jnp.uint64(2)], -1),
            )

        def property_masks(self, rows):
            import jax.numpy as jnp

            # reads ONLY the never-written field
            return jnp.stack(
                [self.packer.get(rows, "frozen") == jnp.uint64(0)], -1
            )

    from stateright_tpu.core import Property

    rep = run_independence(
        DeadProp(), [Property.always("frozen is 0", lambda m, s: True)]
    )
    jx303 = [f for f in rep.findings if f.rule_id == "JX303"]
    assert len(jx303) == 1
    assert jx303[0].severity == "warning"
    assert "frozen is 0" in jx303[0].location

    # and the flagship example is CLEAN: its properties read written fields
    m = TwoPhaseSys(3)
    rep2 = run_independence(m._tensor_cached(), list(m.properties()))
    assert not [f for f in rep2.findings if f.rule_id == "JX303"]


@pytest.mark.medium
def test_fleet_independence_gate_is_clean():
    """The CI gate's contract: every bundled example produces a
    well-formed conflict matrix with no ERROR-level JX3xx finding."""
    import io

    from stateright_tpu.models._cli import fleet_independence

    buf = io.StringIO()
    assert fleet_independence(stream=buf) == 0
    out = buf.getvalue()
    assert "independence fleet: CLEAN" in out
    # the flagship twin's pair count is visible in the fleet output
    assert "102 independent pair(s)" in out


# -- device-side ample selection ---------------------------------------------


def test_ample_mask_selects_singleton_invisible_worker():
    import jax.numpy as jnp

    from stateright_tpu.ops.por import ample_mask

    m = WorkersSys(4)
    t = m._tensor_cached()
    plan = por_plan(t, list(m.properties()))
    kernel = conjunct_eval_fn(t)
    rows = jnp.asarray(np.asarray(t.init_rows(), np.uint64))
    _, valid = t.step_rows(rows)
    amp = np.asarray(ample_mask(valid, rows, plan, kernel))
    # all 4 workers enabled; the ample set is one INVISIBLE worker
    assert np.asarray(valid).sum() == 4
    assert amp.sum() == 1
    assert not amp[0, 0]  # worker 0 is visible: never a reduced ample


# -- engine wiring: the por-off jaxpr pin ------------------------------------


def test_por_off_leaves_run_jaxpr_bit_identical():
    """The telemetry/checked/prededup contract applied to por()."""

    def run_jaxpr(flag):
        m = TwoPhaseSys(3)
        b = m.checker()
        if flag is not None:
            b = b.por(flag)
        c = b.spawn_tpu(sync=True, capacity=1 << 12, batch=64)
        init_fn, run_fn = c._engine(c._cap, c._qcap, c._batch, c._cand)
        carry, _ = init_fn()
        return str(jax.make_jaxpr(lambda cr: run_fn(cr))(tuple(carry)))

    baseline = run_jaxpr(None)
    assert baseline == run_jaxpr(False)
    assert baseline != run_jaxpr(True)  # the selection is really there


# -- verdict parity + pinned reductions --------------------------------------


def test_por_parity_is_bit_identical_on_2pc3():
    """2pc's verdict-relevant actions are all property-visible, so a SOUND
    reduction must select ample == enabled everywhere: counts, traces and
    the visited TABLE itself are bit-identical, and the reduced-vs-full
    tallies honestly report zero reduction."""
    a = TwoPhaseSys(3).checker().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    b = TwoPhaseSys(3).checker().por().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    assert a.unique_state_count() == b.unique_state_count() == TPC3_UNIQUE
    assert a.state_count() == b.state_count() == TPC3_STATES
    ta, tb = a._table_np(), b._table_np()
    assert np.array_equal(ta[0], tb[0]) and np.array_equal(ta[1], tb[1])
    assert sorted(a.discoveries()) == sorted(b.discoveries())
    st = b.por_status()
    assert st["enabled"] is True
    assert st["rows_reduced"] == 0 and st["candidates_masked"] == 0


def test_por_strict_reduction_pinned_on_workers7():
    """The reduction the analysis buys where it IS sound: 3^7 = 2187
    unique states collapse to 15 (one interleaving of the independent
    invisible workers), with identical property verdicts."""
    full = WorkersSys(7).checker().spawn_tpu(
        sync=True, capacity=1 << 13, batch=64
    )
    por = WorkersSys(7).checker().por().spawn_tpu(
        sync=True, capacity=1 << 13, batch=64
    )
    assert (full.unique_state_count(), full.state_count()) == WORKERS7_FULL
    assert (por.unique_state_count(), por.state_count()) == WORKERS7_POR
    assert sorted(full.discoveries()) == sorted(por.discoveries()) == [
        "w0 done"
    ]
    st = por.por_status()
    assert st["rows_reduced"] > 0
    assert st["candidates_masked"] > 0


def test_cycle_proviso_keeps_visible_action_reachable_on_toggle():
    """The toggle cycle starves the visible one-shot action without the
    all-ample-duplicates proviso; with it, every state and the discovery
    survive — at strictly fewer generated candidates."""
    full = ToggleSys().checker().spawn_tpu(
        sync=True, capacity=1 << 8, batch=8
    )
    por = ToggleSys().checker().por().spawn_tpu(
        sync=True, capacity=1 << 8, batch=8
    )
    assert (full.unique_state_count(), full.state_count()) == TOGGLE_FULL
    assert (por.unique_state_count(), por.state_count()) == TOGGLE_POR
    assert sorted(por.discoveries()) == ["y set"]
    st = por.por_status()
    assert st["rows_full_proviso"] >= 1  # the proviso demonstrably fired


def test_por_fallback_on_liveness_model_runs_full_expansion():
    """dining declares eventually properties: por() must fall back (the
    JX304 contract) and produce exactly the plain run."""
    from stateright_tpu.models.dining import dining_model

    a = dining_model(3).checker().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    b = dining_model(3).checker().por().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    assert b._por is False
    st = b.por_status()
    assert st["enabled"] is False and "eventually" in st["fallback"]
    assert a.unique_state_count() == b.unique_state_count()
    assert a.state_count() == b.state_count()
    assert sorted(a.discoveries()) == sorted(b.discoveries())


# -- cartography / status surfaces -------------------------------------------


def test_por_block_rides_cartography_and_reconciles():
    c = (
        WorkersSys(7).checker().por().telemetry(cartography=True)
        .spawn_tpu(sync=True, capacity=1 << 13, batch=64)
    )
    assert (c.unique_state_count(), c.state_count()) == WORKERS7_POR
    cart = c.cartography()
    assert cart is not None
    # reconciliation holds with the REDUCED totals: the histogram counts
    # what was actually generated
    assert sum(cart["depth_hist"]) == c.unique_state_count()
    assert sum(cart["action_hist"]) == c.state_count() - 1
    por = cart["por"]
    assert set(por) == {
        "rows_reduced", "rows_full_proviso", "candidates_masked"
    }
    assert por["rows_reduced"] > 0
    status = c.por_status()
    assert all(status[k] == v for k, v in por.items())


def test_por_status_surfaces_in_explorer_status_view():
    from stateright_tpu.explorer import _Snapshot, _status_view

    m = WorkersSys(4)
    c = m.checker().por().spawn_tpu(sync=True, capacity=1 << 10, batch=16)
    view = _status_view(m, c, _Snapshot())
    assert view["por"]["enabled"] is True
    assert view["por"]["rows_reduced"] > 0
    # a por-less run reports null, never a fabricated block
    c2 = WorkersSys(4).checker().spawn_tpu(
        sync=True, capacity=1 << 10, batch=16
    )
    assert _status_view(m, c2, _Snapshot())["por"] is None


# -- composition + resume (satellites; heavier: daily tier) ------------------


@pytest.mark.slow
def test_por_composes_with_symmetry_and_prededup_on_2pc_and_dining():
    """Same verdicts, counts pinned: POR × symmetry × prededup on 2pc
    (sym-reduced space 94) and POR × prededup on dining (liveness
    fallback path)."""
    a = TwoPhaseSys(3).checker().symmetry().prededup().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    b = TwoPhaseSys(3).checker().symmetry().prededup().por().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    assert a.unique_state_count() == b.unique_state_count() == 94
    assert a.state_count() == b.state_count()
    assert sorted(a.discoveries()) == sorted(b.discoveries())

    from stateright_tpu.models.dining import dining_model

    da = dining_model(3).checker().prededup().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    db = dining_model(3).checker().prededup().por().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    assert da.unique_state_count() == db.unique_state_count()
    assert sorted(da.discoveries()) == sorted(db.discoveries())


@pytest.mark.slow
def test_killed_and_resumed_por_run_matches_uninterrupted():
    """Kill a por() 2pc-5 run mid-flight, resume from the snapshot: the
    final totals match an uninterrupted run exactly (2pc reduces nothing,
    so the resume-boundary full-expansion boost is also count-neutral).
    On a REDUCING model the boost legitimately widens the explored
    lattice, so the contract there is verdict parity + soundness (a
    subset of the full space that still finds the discovery)."""
    import time

    m = TwoPhaseSys(5)
    c = m.checker().por().spawn_tpu(capacity=1 << 14, batch=256)
    time.sleep(0.3)
    c.stop()
    c.join()
    snap = c.checkpoint()
    r = TwoPhaseSys(5).checker().por().spawn_tpu(sync=True, resume=snap)
    u = TwoPhaseSys(5).checker().por().spawn_tpu(
        sync=True, capacity=1 << 14, batch=256
    )
    assert r.unique_state_count() == u.unique_state_count() == 8832
    assert sorted(r.discoveries()) == sorted(u.discoveries())

    w = WorkersSys(7).checker().por().spawn_tpu(
        capacity=1 << 13, batch=8, steps_per_call=1
    )
    time.sleep(0.1)
    w.stop()
    w.join()
    wr = WorkersSys(7).checker().por().spawn_tpu(
        sync=True, resume=w.checkpoint()
    )
    assert sorted(wr.discoveries()) == ["w0 done"]
    assert wr.unique_state_count() <= 2187  # sound subset of the space


@pytest.mark.slow
def test_2pc7_por_counts_pinned_full_parity():
    """The 2pc-7 pin the acceptance asks for, with the honest number: a
    SOUND reduction selects ample == enabled on 2pc (every rm action is
    property-visible), so the reduced successor count EQUALS full
    expansion — pinned so any future analysis change that starts
    reducing 2pc (or inflating it) trips loudly and gets re-verified."""
    caps = dict(capacity=1 << 21, queue_capacity=1 << 19, batch=1024,
                steps_per_call=32, cand=1 << 14)
    full = TwoPhaseSys(7).checker().spawn_tpu(sync=True, **caps)
    por = TwoPhaseSys(7).checker().por().spawn_tpu(sync=True, **caps)
    assert full.unique_state_count() == por.unique_state_count() == 296_448
    assert full.state_count() == por.state_count()
    st = por.por_status()
    assert st["rows_reduced"] == 0 and st["candidates_masked"] == 0


# -- sharded engine (runs on CI's newer jax; the pinned local jax lacks
# the vma collectives — tests/helpers.py) ------------------------------------


@requires_sharded_collectives
def test_sharded_por_parity_and_reduction():
    a = TwoPhaseSys(3).checker().spawn_tpu(
        sync=True, devices=2, capacity=1 << 12, frontier_capacity=1 << 9
    )
    b = TwoPhaseSys(3).checker().por().spawn_tpu(
        sync=True, devices=2, capacity=1 << 12, frontier_capacity=1 << 9
    )
    assert a.unique_state_count() == b.unique_state_count() == TPC3_UNIQUE
    assert a.state_count() == b.state_count()
    assert sorted(a.discoveries()) == sorted(b.discoveries())
    # and the reducing fixture reduces on the mesh too, same verdicts
    wf = WorkersSys(7).checker().spawn_tpu(
        sync=True, devices=2, capacity=1 << 13, frontier_capacity=1 << 9
    )
    wp = WorkersSys(7).checker().por().spawn_tpu(
        sync=True, devices=2, capacity=1 << 13, frontier_capacity=1 << 9
    )
    assert wf.unique_state_count() == 2187
    assert wp.unique_state_count() < wf.unique_state_count()
    assert wp.state_count() < wf.state_count()
    assert sorted(wp.discoveries()) == ["w0 done"]


@requires_sharded_collectives
def test_sharded_por_off_program_unchanged():
    import jax.numpy as jnp

    from stateright_tpu.parallel.sharded import (
        _build_sharded_run,
        default_mesh,
    )

    m = TwoPhaseSys(3)
    tensor = m._tensor_cached()
    props = list(m.properties())
    mesh = default_mesh(2)

    def step_jaxpr(por_plan_arg):
        kw = {} if por_plan_arg == "absent" else {"por": por_plan_arg}
        init_fn, step_fn = _build_sharded_run(
            tensor, props, mesh, 1 << 11, 1 << 9, 1 << 10, None, **kw
        )
        out = init_fn()
        carry = tuple(jnp.asarray(x) for x in out[:-1])
        return str(jax.make_jaxpr(lambda *cr: step_fn(*cr))(*carry))

    assert step_jaxpr("absent") == step_jaxpr(None)
    plan = por_plan(tensor, props)
    assert step_jaxpr("absent") != step_jaxpr(plan)
