"""HBM ledger & capacity planning (stateright_tpu/telemetry/memory.py).

Pins the round's contracts (docs/telemetry.md "Memory ledger"):

 - EXACTNESS: the analytic per-buffer bytes reconcile exactly against the
   live engine buffers' ``nbytes`` — per buffer, both engines (the
   sharded leg behind ``requires_sharded_collectives``);
 - ZERO JAXPR IMPACT: the ledger is host arithmetic only — the run
   program is bit-identical with the ledger on or off (the
   telemetry/checked/prededup/cartography discipline, in its strongest
   form: not even the ON path may touch the program);
 - the run report's ``memory`` block is DETERMINISTIC (byte-stable
   across runs; live-device fields never enter the JSON body);
 - the growth forecast, the ``growth_oom_risk`` health condition, the
   preflight/resume capacity guards (exercised on CPU via the
   ``STATERIGHT_TPU_DEVICE_BYTES`` budget override), and the
   ``capacity`` CLI verb's graceful degradation where no budget exists.
"""

import io
import json

import numpy as np
import pytest

import jax

from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.telemetry.health import HealthTracker
from stateright_tpu.telemetry.memory import (
    MEMORY_V,
    BufferSpec,
    CapacityError,
    capacity_plan,
    device_budget,
    fmt_bytes,
    next_rung_block,
    total_bytes,
    wavefront_specs,
)
from tests.helpers import requires_sharded_collectives


# -- exactness: analytic bytes == live buffer nbytes -------------------------


def _spawn_wavefront(memory=True, **kw):
    b = TwoPhaseSys(3).checker()
    if memory:
        b = b.telemetry(memory=True, cartography=True)
    kw.setdefault("capacity", 1 << 12)
    kw.setdefault("batch", 64)
    return b.spawn_tpu(sync=True, **kw)


def test_wavefront_analytic_bytes_reconcile_exactly():
    """Per-buffer: the ledger's analytic model (derived from the engine's
    own carry avals) must equal the final carry's live nbytes EXACTLY —
    table, queue, scalars, cartography counters, everything."""
    c = _spawn_wavefront()
    specs = c._memory_spec_fn()(
        {"cap": c._cap, "qcap": c._qcap, "batch": c._batch}
    )
    carry = c._final_carry
    assert len(specs) == len(carry)
    for s, arr in zip(specs, carry):
        a = np.asarray(arr)
        assert a.nbytes == s.nbytes, (s.name, a.nbytes, s.nbytes)
        assert a.shape == s.shape, (s.name, a.shape, s.shape)
    snap = c.memory()
    assert snap["v"] == MEMORY_V
    assert snap["total_bytes"] == sum(s.nbytes for s in specs)
    assert snap["buffers"] == {s.name: s.nbytes for s in specs}


@requires_sharded_collectives
def test_sharded_analytic_bytes_reconcile_exactly():
    """Same exactness on the mesh engine: the GLOBAL carry arrays'
    nbytes equal the sharded analytic model per buffer."""
    c = (
        TwoPhaseSys(3)
        .checker()
        .telemetry(memory=True, cartography=True)
        .spawn_tpu(sync=True, devices=2, capacity=1 << 12)
    )
    specs = c._memory_spec_fn()(c._memory_caps())
    carry = c._final_state[0]
    assert len(specs) == len(carry)
    for s, arr in zip(specs, carry):
        a = np.asarray(arr)
        assert a.nbytes == s.nbytes, (s.name, a.nbytes, s.nbytes)
    snap = c.memory()
    assert snap["devices"] == 2
    assert snap["per_device_bytes"] <= snap["total_bytes"]


def test_exec_memory_analysis_agrees_with_the_analytic_carry():
    """Cross-check against XLA's own accounting: the AOT-compiled run
    executable's argument bytes ARE the carry — the two independent
    models must agree on a no-growth run."""
    c = _spawn_wavefront(capacity=1 << 14)
    snap = c.memory()
    exe = snap.get("exec")
    if exe is None or "argument_bytes" not in exe:
        pytest.skip("backend exposes no compiled memory_analysis")
    assert exe["argument_bytes"] == snap["total_bytes"]
    compiles = c.flight_recorder.records("compile")
    assert any(
        isinstance(r.get("memory"), dict)
        and r["memory"].get("argument_bytes") == snap["total_bytes"]
        for r in compiles
    ), compiles


# -- zero jaxpr impact -------------------------------------------------------


def _wavefront_build_jaxpr(memory: bool) -> str:
    m = TwoPhaseSys(3)
    b = m.checker()
    if memory:
        b = b.telemetry(memory=True)
    c = b.spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    # trace the BUILD product, not the engine cache: the ledger's AOT
    # path swaps a Compiled into the cache, which is the same program
    # compiled earlier (the prewarm contract) but cannot be re-traced
    init_fn, run_fn = c._build(c._cap, c._qcap, c._batch, c._cand)
    carry, _ = init_fn()
    # fresh lambda per call: make_jaxpr memoizes on fn identity
    return str(jax.make_jaxpr(lambda cr: run_fn(cr))(tuple(carry)))


def test_ledger_leaves_run_jaxpr_bit_identical():
    """Strongest form of the overhead contract: the ledger never touches
    the device program — ON is bit-identical to OFF (host arithmetic
    over shapes the engine already knows)."""
    assert _wavefront_build_jaxpr(False) == _wavefront_build_jaxpr(True)


def test_ledger_does_not_key_the_engine_cache():
    """Ledger on/off must share one compiled engine: a memory-off spawn
    after a memory-on spawn on the same model is a cache HIT (the flag
    is not part of the engine key — same program, compiled once)."""
    m = TwoPhaseSys(3)
    kw = dict(sync=True, capacity=1 << 12, batch=64)
    c1 = m.checker().telemetry(memory=True).spawn_tpu(**kw)
    n_keys = len(c1.tensor._run_cache)
    c2 = m.checker().telemetry().spawn_tpu(**kw)
    assert len(c2.tensor._run_cache) == n_keys
    assert c2.unique_state_count() == c1.unique_state_count()


# -- memory ring records + growth series -------------------------------------


def test_growth_emits_memory_records_and_manifest():
    """A run that grows emits a ``memory`` record per rung change (the
    per-growth series) plus init/final, each carrying the versioned
    analytic block; the final snapshot manifest records the footprint."""
    c = (
        TwoPhaseSys(5)
        .checker()
        .telemetry(memory=True, cartography=True)
        .spawn_tpu(sync=True, capacity=1 << 10, batch=256)
    )  # tiny table vs 8832 unique states: forces growth
    recs = c.flight_recorder.records("memory")
    tags = [r["at"] for r in recs]
    assert tags[0] == "init" and tags[-1] == "final"
    assert "growth" in tags, tags
    for r in recs:
        assert r["v"] == MEMORY_V
        assert r["engine"] == "wavefront"
        assert r["total_bytes"] == sum(r["buffers"].values())
        nxt = r["next_rung"]
        assert nxt["transient_bytes"] == r["total_bytes"] + nxt["total_bytes"]
    # capacities are monotone along the growth series
    caps = [r["capacity"] for r in recs]
    assert caps == sorted(caps)
    snap = c.checkpoint()
    assert int(snap["footprint_bytes"]) == c.memory()["total_bytes"]


def test_chrome_trace_carries_pressure_and_hbm_counters(tmp_path):
    """Satellite: the Chrome-trace export plots resource pressure as
    counter tracks — queue depth + table load per step, HBM bytes per
    memory record — round-tripped through the existing parser."""
    from stateright_tpu.telemetry.export import from_chrome_trace

    c = (
        TwoPhaseSys(5)
        .checker()
        .telemetry(memory=True, cartography=True)
        .spawn_tpu(sync=True, capacity=1 << 10, batch=256)
    )
    path = tmp_path / "trace.json"
    c.flight_recorder.to_chrome_trace(path)
    back = from_chrome_trace(path)
    counters = [e for e in back["events"] if e["ph"] == "C"]
    by_name = {}
    for e in counters:
        by_name.setdefault(e["name"], []).append(e)
    assert "pressure" in by_name
    assert all(
        "queue" in e["args"] and "table_load" in e["args"]
        for e in by_name["pressure"]
    )
    assert "hbm_bytes" in by_name
    assert all(
        isinstance(e["args"].get("analytic_bytes"), int)
        for e in by_name["hbm_bytes"]
    )


# -- deterministic report block ----------------------------------------------


def test_report_memory_block_is_deterministic_and_live_free(tmp_path):
    """The run report's memory block is byte-stable across runs and
    carries NO live-device / machine-local fields (device stats and the
    budget live in the markdown rendering only)."""
    from stateright_tpu.telemetry.report import build_report

    bodies = []
    for i in range(2):
        c = (
            TwoPhaseSys(3)
            .checker()
            .report(str(tmp_path / f"r{i}.json"))
            .spawn_tpu(sync=True, capacity=1 << 12, batch=64)
        )
        c.join()
        bodies.append(build_report(c))
    assert json.dumps(bodies[0]) == json.dumps(bodies[1])
    mem = bodies[0]["memory"]
    assert mem["v"] == MEMORY_V
    assert set(mem) <= {
        "v", "engine", "capacity", "queue_capacity", "frontier_capacity",
        "devices", "buffers", "total_bytes", "per_device_bytes",
        "next_rung",
    }
    assert sum(mem["buffers"].values()) == mem["total_bytes"]
    # the written artifact renders the block in markdown too
    md = (tmp_path / "r0.md").read_text()
    assert "## Memory (analytic)" in md


def test_metrics_view_and_watch_line_surface_memory():
    from stateright_tpu.explorer import _metrics_view
    from stateright_tpu.models._cli import watch_line

    c = _spawn_wavefront()
    view = _metrics_view(c)
    assert view["memory"] is not None
    assert view["memory"]["total_bytes"] > 0
    line = watch_line(c)
    assert "hbm=" in line and "hbm=-" not in line


# -- forecast + plan ---------------------------------------------------------


def test_next_rung_forecast_holds_old_plus_new():
    spec_fn = lambda caps: [  # noqa: E731
        BufferSpec("table", (caps["cap"],), np.uint64),
        BufferSpec("fixed", (100,), np.uint8),
    ]
    nxt = next_rung_block(spec_fn, {"cap": 1024})
    assert nxt["capacity"] == 2048
    assert nxt["total_bytes"] == 2048 * 8 + 100
    assert nxt["transient_bytes"] == (1024 * 8 + 100) + (2048 * 8 + 100)


def test_capacity_plan_max_unique_is_transient_bounded():
    """The plan's headline is bounded by the TRANSIENT, not the steady
    state: a rung whose steady bytes fit but whose migration does not is
    unreachable."""
    spec_fn = lambda caps: [  # noqa: E731
        BufferSpec("table", (caps["cap"],), np.uint64)
    ]
    # budget fits cap=2048 steady (16KB) and the 1024->2048 transient
    # (24KB), but not the 2048->4096 transient (48KB)
    plan = capacity_plan(spec_fn, {"cap": 1024}, budget=30_000)
    assert plan["max_unique"] == 2048 // 4
    fits = [r["fits"] for r in plan["rungs"]]
    assert fits == [True, True, False]
    # no budget: analytic ladder only, no verdict
    plan2 = capacity_plan(spec_fn, {"cap": 1024}, budget=None, rungs=3)
    assert "max_unique" not in plan2
    assert all("fits" not in r for r in plan2["rungs"])


def test_fmt_bytes():
    assert fmt_bytes(None) == "-"
    assert fmt_bytes(512) == "512B"
    assert fmt_bytes(2048) == "2.0KB"
    assert fmt_bytes(3 << 30) == "3.0GB"


def test_device_budget_env_override(monkeypatch):
    monkeypatch.setenv("STATERIGHT_TPU_DEVICE_BYTES", "123456")
    assert device_budget() == (123456, "env")


# -- preflight + resume capacity guards --------------------------------------


def test_preflight_guard_warns_then_errors(monkeypatch, capsys):
    monkeypatch.setenv("STATERIGHT_TPU_DEVICE_BYTES", "10000")  # ~10KB
    # default mode: warn once, run proceeds (and completes correctly)
    c = TwoPhaseSys(3).checker().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    assert c.unique_state_count() == 288
    err = capsys.readouterr().err
    assert "capacity guard" in err and "exceeds the device budget" in err
    # flag-gated error: raises BEFORE any device work
    monkeypatch.setenv("STATERIGHT_TPU_CAPACITY_GUARD", "error")
    with pytest.raises(CapacityError):
        TwoPhaseSys(4).checker().spawn_tpu(sync=True, capacity=1 << 12)
    # off: silent
    monkeypatch.setenv("STATERIGHT_TPU_CAPACITY_GUARD", "off")
    capsys.readouterr()
    TwoPhaseSys(3).checker().spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    assert "capacity guard" not in capsys.readouterr().err


def test_resume_guard_checks_the_snapshot_manifest(monkeypatch, capsys):
    """Satellite: snapshot manifests carry the analytic footprint, and a
    resume onto a device that analytically cannot hold it warns (flag-
    gated error) BEFORE compiling — riding _check_snapshot_sig."""
    m = TwoPhaseSys(3)
    snap = m.checker().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    ).checkpoint()
    assert int(snap["footprint_bytes"]) > 0
    monkeypatch.setenv("STATERIGHT_TPU_DEVICE_BYTES", "10000")
    monkeypatch.setenv("STATERIGHT_TPU_CAPACITY_GUARD", "error")
    with pytest.raises(CapacityError):
        m.checker().skip_audit().spawn_tpu(sync=True, resume=snap)
    # warn mode: proceeds, resumed run completes
    monkeypatch.delenv("STATERIGHT_TPU_CAPACITY_GUARD")
    capsys.readouterr()
    c = m.checker().skip_audit().spawn_tpu(sync=True, resume=snap)
    assert c.unique_state_count() == 288
    assert "cannot hold the snapshot" in capsys.readouterr().err


# -- growth_oom_risk health condition ----------------------------------------


def _step(load, d_states=100, d_unique=50, queue=10):
    return {
        "d_states": d_states, "d_unique": d_unique, "queue": queue,
        "load_factor": load, "dt": 0.1,
    }


def test_health_growth_oom_risk_transitions():
    t = HealthTracker()
    t.set_memory_forecast(next_transient_bytes=2_000_000,
                          budget_bytes=1_000_000)
    # below the risk load: no event even though the forecast misses
    assert not [
        e for e in t.update(_step(0.05))
        if e["event"].startswith("growth_oom")
    ]
    assert t.oom_risk is False
    # crossing the risk load with a missing forecast -> risk event
    events = t.update(_step(0.2))
    assert any(e["event"] == "growth_oom_risk" for e in events)
    assert t.oom_risk and t.snapshot()["oom_risk"] is True
    # transitions only: staying at risk emits nothing new
    assert not t.update(_step(0.2))
    # fitting forecast clears
    t.set_memory_forecast(500_000, 1_000_000)
    events = t.update(_step(0.2))
    assert any(e["event"] == "growth_oom_risk_cleared" for e in events)
    assert not t.oom_risk


def test_health_mark_done_closes_an_open_risk_span():
    t = HealthTracker()
    t.set_memory_forecast(2_000_000, 1_000_000)
    t.update(_step(0.2))
    assert t.oom_risk
    events = t.mark_done()
    assert any(e["event"] == "growth_oom_risk_cleared" for e in events)
    assert t.snapshot()["oom_risk"] is False


def test_health_no_forecast_means_no_risk():
    t = HealthTracker()  # ledger off: forecast never armed
    assert not [
        e for e in t.update(_step(0.24))
        if e["event"].startswith("growth_oom")
    ]


# -- capacity CLI verb -------------------------------------------------------


def test_capacity_verb_degrades_gracefully_without_budget(monkeypatch):
    """Satellite/CI contract: on CPU (no live memory stats) the verb
    prints the analytic ladder and never crashes."""
    monkeypatch.delenv("STATERIGHT_TPU_DEVICE_BYTES", raising=False)
    from stateright_tpu.models._cli import fleet_capacity

    buf = io.StringIO()
    rc = fleet_capacity(["two_phase_commit"], stream=buf)
    out = buf.getvalue()
    assert rc == 0
    assert "no device memory limit known" in out
    assert "capacity plan" in out and "NO" not in out


def test_capacity_verb_prints_a_plan_with_budget(monkeypatch):
    monkeypatch.setenv("STATERIGHT_TPU_DEVICE_BYTES", str(300 << 20))
    from stateright_tpu.models._cli import fleet_capacity

    buf = io.StringIO()
    rc = fleet_capacity(["two_phase_commit"], stream=buf)
    out = buf.getvalue()
    assert rc == 0
    assert "unique states before spilling" in out
    assert "NO" in out  # the first unfitting rung is shown


def test_capacity_verb_reports_twinless_models(monkeypatch):
    from stateright_tpu.models._cli import capacity_and_report

    class NoTwin:
        def properties(self):
            return []

    buf = io.StringIO()
    ok = capacity_and_report([("no-twin", NoTwin())], stream=buf)
    assert ok is True  # disclosed, not a failure
    assert "no device twin" in buf.getvalue()
