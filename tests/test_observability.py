"""Live-run observability (docs/observability.md): the typed metrics bus,
the Prometheus/``/.progress`` service plane, the progress heartbeat +
``status`` verb, and span-structured tracing end to end.

The covering contract, same as the flight recorder's: everything here is
host-side sampling at seams that already exist.  The parity pin in this
file is the acceptance gate — metrics on vs off must leave the step
record stream (minus wall-clock and the random span id) and the step
jaxpr bit-identical.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from stateright_tpu.checkpoint import (
    PROGRESS_FILE,
    ProgressHeartbeat,
    read_progress,
)
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.telemetry import FlightRecorder
from stateright_tpu.telemetry.export import to_chrome_trace
from stateright_tpu.telemetry.metrics import (
    ENGINE_LABELS,
    MetricsBus,
    default_bus,
    engine_families,
    fleet_families,
    reset_default_bus,
)


@pytest.fixture(autouse=True)
def _fresh_default_bus():
    """Family values on the process bus are cumulative by design; tests
    must not see each other's samples."""
    reset_default_bus()
    yield
    reset_default_bus()


# -- the typed family registry ----------------------------------------------


def test_family_registration_is_idempotent_and_type_checked():
    bus = MetricsBus()
    c1 = bus.counter("x_total", "Things.", labelnames=("engine",))
    c2 = bus.counter("x_total", "Things.", labelnames=("engine",))
    assert c1 is c2  # same-name same-type re-registration returns it
    with pytest.raises(ValueError, match="already registered"):
        bus.gauge("x_total")
    with pytest.raises(ValueError):
        bus.counter("not a metric name!")
    with pytest.raises(ValueError):
        bus.counter("x_total").inc(-1)  # counters are monotone


def test_label_cardinality_guard():
    bus = MetricsBus(max_series=3)
    c = bus.counter("y_total", "Things.", labelnames=("key",))
    for i in range(3):
        c.inc(1, key=f"k{i}")
    with pytest.raises(ValueError, match="label-cardinality cap"):
        c.inc(1, key="k3")
    # the guard is per family, not global: a second family starts fresh
    bus.gauge("z", labelnames=("key",)).set(1.0, key="other")


def test_exposition_format_golden():
    """The exact Prometheus text format a scraper parses: HELP/TYPE
    headers, sorted families, cumulative histogram buckets with +Inf,
    bare integers.  Byte-for-byte golden — exposition drift breaks HERE,
    not in a dashboard three rounds later."""
    bus = MetricsBus()
    bus.counter("demo_total", "Things counted.",
                labelnames=("engine",)).inc(3, engine="wavefront")
    bus.gauge("demo_load", "Load.").set(0.5)
    h = bus.histogram("demo_seconds", "Durations.", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(2.0)
    assert bus.expose() == (
        "# HELP demo_load Load.\n"
        "# TYPE demo_load gauge\n"
        "demo_load 0.5\n"
        "# HELP demo_seconds Durations.\n"
        "# TYPE demo_seconds histogram\n"
        'demo_seconds_bucket{le="0.1"} 1\n'
        'demo_seconds_bucket{le="1"} 2\n'
        'demo_seconds_bucket{le="+Inf"} 2\n'
        "demo_seconds_sum 2.05\n"
        "demo_seconds_count 2\n"
        "# HELP demo_total Things counted.\n"
        "# TYPE demo_total counter\n"
        'demo_total{engine="wavefront"} 3\n'
    )


def test_family_catalogue_is_pinned():
    """The standard engine + fleet family names (what the CI /metrics
    smoke asserts and dashboards key on)."""
    bus = MetricsBus()
    eng = engine_families(bus)
    flt = fleet_families(bus)
    assert eng["states"].name == "stateright_states_total"
    assert eng["unique"].name == "stateright_unique_states_total"
    assert eng["step"].kind == "histogram"
    assert ENGINE_LABELS == ("engine", "model")
    assert flt["queue"].name == "stateright_fleet_queue_depth"
    assert flt["admissions"].kind == "counter"
    # both catalogues resolve idempotently on one bus
    assert engine_families(bus)["states"] is eng["states"]


# -- engine publication + the zero-overhead parity pin -----------------------


def _spawn_2pc3(metrics: bool):
    b = TwoPhaseSys(3).checker().telemetry(metrics=metrics)
    return b.spawn_tpu(sync=True, capacity=1 << 12, batch=64)


def test_engine_publishes_per_sync_samples():
    c = _spawn_2pc3(metrics=True)
    bus = default_bus()
    assert "stateright_states_total" in bus.families()
    exp = bus.expose()
    # the counter ends at the run's terminal total, labeled by engine+model
    assert 'stateright_states_total{engine="wavefront",' in exp
    assert "} %d\n" % c.state_count() in exp
    assert "stateright_step_seconds_bucket" in exp
    # per-sync gauges sampled from already-synced host values
    assert "stateright_table_load{" in exp
    assert "stateright_frontier_size{" in exp


def test_metrics_on_off_step_records_are_identical():
    """The parity pin: attaching the bus must not change what the
    recorder records — same step stream minus wall-clock (dt/t) and the
    randomly-minted span id."""

    def strip(rec):
        return [
            {k: v for k, v in r.items() if k not in ("t", "dt", "span")}
            for r in rec.records("step")
        ]

    c_off = _spawn_2pc3(metrics=False)
    c_on = _spawn_2pc3(metrics=True)
    assert strip(c_off.flight_recorder) == strip(c_on.flight_recorder)
    assert c_off.unique_state_count() == c_on.unique_state_count() == 288


def test_metrics_attach_adds_zero_ops_to_step_jaxpr():
    """The device half of the parity pin: the compiled step program is
    bit-identical with the bus attached — publication is host-side
    sampling of values the sync already materialized."""
    import jax

    def run_jaxpr(metrics: bool) -> str:
        c = _spawn_2pc3(metrics)
        init_fn, run_fn = c._engine(c._cap, c._qcap, c._batch, c._cand)
        carry, _ = init_fn()
        return str(jax.make_jaxpr(lambda cr: run_fn(cr))(tuple(carry)))

    assert run_jaxpr(False) == run_jaxpr(True)


def test_publisher_crash_detaches_bus_not_run(monkeypatch):
    """A broken publisher must cost the bus, never the check: the
    recorder detaches it and discloses via a note record."""
    from stateright_tpu.telemetry import recorder as recmod

    def boom(*a, **kw):
        raise RuntimeError("bus exploded")

    monkeypatch.setattr(recmod.FlightRecorder, "_engine_fams", boom)
    c = _spawn_2pc3(metrics=True)
    assert c.unique_state_count() == 288  # the run finished regardless
    notes = [r for r in c.flight_recorder.records("note")
             if r.get("what") == "metrics bus detached"]
    assert notes, "the drop must be disclosed in the ring"


# -- heartbeat + status verb -------------------------------------------------


def test_heartbeat_beats_throttle_and_verdicts(tmp_path):
    rec = FlightRecorder(capacity=64, meta={"engine": "t"})
    rec.step(engine="single", dt=0.1, states=10, unique=5)
    hb = ProgressHeartbeat(str(tmp_path), every_secs=30.0)
    assert hb.beat(rec) is True  # first beat always lands
    assert hb.beat(rec) is False  # throttled
    assert hb.beat(rec, force=True) is True
    doc = read_progress(str(tmp_path))
    assert doc["status"] == "running" and doc["verdict"] == "running"
    assert doc["states"] == 10 and doc["unique"] == 5
    assert doc["fresh"] is True
    hb.beat(rec, status="done", force=True)
    assert read_progress(str(tmp_path))["verdict"] == "done"


def test_stale_running_heartbeat_reads_dead(tmp_path):
    """The post-mortem path: a SIGKILLed run leaves a ``running``
    heartbeat behind; once it goes stale the verdict is ``dead`` —
    'where did it stall' instead of a lying 'running'."""
    p = tmp_path / PROGRESS_FILE
    doc = {"v": 1, "status": "running", "ts": time.time() - 120.0,
           "every_secs": 1.0, "states": 42, "unique": 17}
    p.write_text(json.dumps(doc))
    back = read_progress(str(tmp_path))
    assert back["verdict"] == "dead" and back["fresh"] is False
    assert back["states"] == 42
    # a DONE heartbeat never goes dead, no matter how old
    doc["status"] = "done"
    p.write_text(json.dumps(doc))
    assert read_progress(str(tmp_path))["verdict"] == "done"


def test_autosave_armed_run_writes_terminal_heartbeat(tmp_path):
    c = (
        TwoPhaseSys(3).checker().telemetry()
        .autosave(str(tmp_path), every_secs=3600.0)
        .spawn_tpu(sync=True, capacity=1 << 12, batch=64)
    )
    doc = read_progress(str(tmp_path))
    assert doc is not None and doc["verdict"] == "done"
    assert doc["states"] == c.state_count()
    assert doc["unique"] == c.unique_state_count()


def test_status_verb_reports_live_and_dead_runs(tmp_path, capsys):
    """``_cli status RUN_DIR`` over a pool root: the top-level heartbeat
    plus per-job heartbeats under ``jobs/``, including a SIGKILLed job
    (stale running heartbeat -> DEAD)."""
    from stateright_tpu.models._cli import fleet_status

    (tmp_path / PROGRESS_FILE).write_text(json.dumps(
        {"v": 1, "status": "done", "ts": time.time(), "every_secs": 1.0,
         "jobs": 2, "completed": 2}
    ))
    dead = tmp_path / "jobs" / "killed"
    dead.mkdir(parents=True)
    (dead / PROGRESS_FILE).write_text(json.dumps(
        {"v": 1, "status": "running", "ts": time.time() - 300.0,
         "every_secs": 1.0, "states": 7, "phase": "explore"}
    ))
    live = tmp_path / "jobs" / "ok"
    live.mkdir()
    (live / PROGRESS_FILE).write_text(json.dumps(
        {"v": 1, "status": "running", "ts": time.time(),
         "every_secs": 1.0, "states": 3}
    ))
    assert fleet_status([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "DONE" in out
    assert "jobs/killed: DEAD" in out
    assert "jobs/ok: RUNNING" in out
    # an empty dir is a loud exit-1, not a silent success
    empty = tmp_path / "nothing"
    empty.mkdir()
    assert fleet_status([str(empty)]) == 1


# -- the service plane -------------------------------------------------------


def _get(addr, path):
    with urllib.request.urlopen(f"http://{addr}{path}") as r:
        return r.status, dict(r.headers), r.read()


def test_metrics_and_progress_endpoints(tmp_path):
    from stateright_tpu.explorer import serve

    b = (
        TwoPhaseSys(3).checker().telemetry(metrics=True)
        .autosave(str(tmp_path), every_secs=3600.0)
    )
    server = serve(b, "localhost:0", block=False, strategy="tpu",
                   sync=True, capacity=1 << 12, batch=64)
    try:
        server.checker.join()
        status, headers, body = _get(server.addr, "/metrics")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain; version=0.0.4")
        text = body.decode()
        assert "# TYPE stateright_states_total counter" in text
        assert 'engine="wavefront"' in text
        status, _, body = _get(server.addr, "/.progress")
        assert status == 200
        doc = json.loads(body)
        assert doc["verdict"] == "done"
        assert doc["states"] == server.checker.state_count()
        # traversal-shaped job keys are refused with the stable error shape
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(server.addr, "/.progress/../evil")
        assert e.value.code == 404
        assert json.loads(e.value.read())["error"] == "bad_job_key"
    finally:
        server.shutdown()


def test_progress_endpoint_disabled_without_root():
    from stateright_tpu.explorer import serve

    server = serve(TwoPhaseSys(3).checker(), "localhost:0", block=False)
    try:
        server.checker.join()
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(server.addr, "/.progress")
        assert e.value.code == 404
        assert json.loads(e.value.read())["error"] == "progress_disabled"
        # /metrics still answers (the process default bus; possibly empty)
        status, headers, _ = _get(server.addr, "/metrics")
        assert status == 200
    finally:
        server.shutdown()


# -- span tracing end to end -------------------------------------------------


def test_supervised_run_span_chain(tmp_path):
    """attempt -> engine_run -> (steps, autosave) under an injected
    parent: the propagation path the fleet scheduler drives, pinned at
    the supervisor boundary."""
    from stateright_tpu.supervisor import supervise
    from stateright_tpu.telemetry.spans import SpanContext

    b = TwoPhaseSys(3).checker().telemetry()
    parent = SpanContext()
    b._span_ctx = parent
    res = supervise(
        b, autosave_dir=str(tmp_path / "auto"), every_secs=0.0,
        max_restarts=0, sleep=lambda s: None,
        capacity=1 << 12, batch=64,
    )
    rec = res.checker.flight_recorder
    spans = rec.records("span")
    att = [s for s in spans if s["name"] == "attempt"]
    run = [s for s in spans if s["name"] == "engine_run"]
    saves = [s for s in spans if s["name"] == "autosave"]
    assert len(att) == 1 and len(run) == 1 and saves
    assert att[0]["parent_id"] == parent.span_id
    assert run[0]["parent_id"] == att[0]["span_id"]
    assert all(s["parent_id"] == run[0]["span_id"] for s in saves)
    assert {s["trace_id"] for s in spans} == {parent.trace_id}
    # the supervisor restores the builder's ctx after the episode
    assert b._span_ctx is parent
    steps = rec.records("step")
    assert steps and all(
        s["span"] == run[0]["span_id"] for s in steps
    )
    # a standalone (unparented) run roots a fresh trace instead
    c2 = TwoPhaseSys(3).checker().telemetry().spawn_tpu(
        sync=True, capacity=1 << 12, batch=64
    )
    roots = c2.flight_recorder.records("span")
    assert [s["name"] for s in roots] == ["engine_run"]
    assert "parent_id" not in roots[0]


def test_two_job_fleet_chrome_trace_nests(tmp_path):
    """The acceptance trace: a 2-job fleet campaign exported as ONE
    Chrome trace — fleet -> job -> attempt -> engine_run spans with
    correct parenting, all on one trace id, rendered as nested duration
    events on per-job lanes."""
    from stateright_tpu.fleet import FleetSpec, Job, run_fleet

    checkers = []

    class SpyBuilder:
        """Forwarding proxy: captures the spawned checkers (whose
        recorders hold the attempt/engine_run spans) without touching
        the builder surface the scheduler/supervisor mutate."""

        def __init__(self, inner):
            object.__setattr__(self, "_inner", inner)

        def __getattr__(self, k):
            return getattr(self._inner, k)

        def __setattr__(self, k, v):
            setattr(self._inner, k, v)

        def spawn_tpu(self, **kw):
            c = self._inner.spawn_tpu(**kw)
            checkers.append(c)
            return c

    def build():
        return SpyBuilder(TwoPhaseSys(3).checker().telemetry())

    spec = FleetSpec(
        jobs=[
            Job(key="a", build=build, capacity=1 << 12, batch=64),
            Job(key="b", build=build, capacity=1 << 12, batch=64),
        ],
        slots=2,
    )
    res = run_fleet(spec, root=str(tmp_path / "fleet"))
    assert res.completed == 2 and len(checkers) == 2

    # one combined export: the fleet ring plus both job rings (the
    # JSONL header's monotonic origin aligns the appended runs)
    path = tmp_path / "trace.jsonl"
    res.recorder.to_jsonl(path)
    for c in checkers:
        c.flight_recorder.to_jsonl(path, append=True)
    rec = FlightRecorder.from_jsonl(path)
    spans = rec.records("span")
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    fleet = by_name["fleet"]
    jobs = by_name["job"]
    attempts = by_name["attempt"]
    runs = by_name["engine_run"]
    assert len(fleet) == 1 and len(jobs) == 2
    assert len(attempts) == 2 and len(runs) == 2
    assert {j["key"] for j in jobs} == {"a", "b"}
    assert all(j["parent_id"] == fleet[0]["span_id"] for j in jobs)
    assert {a["parent_id"] for a in attempts} == {
        j["span_id"] for j in jobs
    }
    assert {r["parent_id"] for r in runs} == {
        a["span_id"] for a in attempts
    }
    assert {s["trace_id"] for s in spans} == {fleet[0]["trace_id"]}

    out = tmp_path / "trace.json"
    to_chrome_trace(rec, out)
    events = json.loads(out.read_text())["traceEvents"]
    xs = {e["args"]["span_id"]: e for e in events
          if e["cat"] == "span" and e["ph"] == "X"}
    assert len(xs) == len(spans)

    def contains(outer, inner):
        return (outer["ts"] <= inner["ts"] + 1e-6
                and inner["ts"] + inner["dur"]
                <= outer["ts"] + outer["dur"] + 1e-6)

    for s in spans:
        pid = s.get("parent_id")
        if pid is None:
            continue
        # every child renders inside its parent AND on its lineage's
        # lane — what makes the viewer nest them
        assert contains(xs[pid], xs[s["span_id"]]), (
            f"{s['name']} not nested in its parent"
        )
        assert xs[pid]["tid"] == xs[s["span_id"]]["tid"]
    # sibling jobs render on distinct lanes... no: one fleet root =>
    # one lineage lane; concurrency is visible by overlap, parenting by
    # containment.  What must hold: span lanes are the dedicated >=100
    # band, never the plain step lane
    assert all(e["tid"] >= 100 for e in xs.values())
