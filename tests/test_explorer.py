"""Explorer endpoint tests (reference ``src/checker/explorer.rs:242-447``):
exact JSON views against a live (background) server over small models."""

import json
import urllib.request

import pytest

from stateright_tpu.explorer import serve
from stateright_tpu.models.two_phase_commit import TwoPhaseSys

from fixtures import LinearEquation


@pytest.fixture(scope="module")
def lineq_server():
    server = serve(
        LinearEquation(a=2, b=10, c=14).checker(),
        "localhost:0",  # ephemeral port
        block=False,
    )
    server.checker.join()
    yield server
    server.shutdown()


def get(server, path):
    with urllib.request.urlopen(f"http://{server.addr}{path}") as r:
        return json.loads(r.read())


def get_status(server, path):
    try:
        with urllib.request.urlopen(f"http://{server.addr}{path}") as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_status_endpoint(lineq_server):
    s = get(lineq_server, "/.status")
    assert s["done"] is True
    assert s["model"] == "LinearEquation"
    assert s["unique_state_count"] == 12  # reference checker.rs:459-461
    assert s["state_count"] == 15
    kinds = {name: kind for kind, name, _ in s["properties"]}
    assert kinds == {"solvable": "sometimes"}
    # the sometimes-property discovery is an encoded fingerprint path
    discovery = dict(
        (name, disc) for _, name, disc in s["properties"]
    )["solvable"]
    assert discovery is not None and "/" in discovery


def test_init_states_view(lineq_server):
    views = get(lineq_server, "/.states/")
    assert len(views) == 1
    assert views[0]["state"] == "(0, 0)"
    assert "action" not in views[0]
    assert int(views[0]["fingerprint"]) > 0


def test_steps_view_follows_fingerprints(lineq_server):
    init = get(lineq_server, "/.states/")[0]
    steps = get(lineq_server, f"/.states/{init['fingerprint']}")
    # format_action is repr(), like the reference's Debug formatting
    assert {v["action"] for v in steps} == {"'IncreaseX'", "'IncreaseY'"}
    for v in steps:
        assert "state" in v and "fingerprint" in v
    # walk one more level
    nxt = steps[0]
    steps2 = get(
        lineq_server, f"/.states/{init['fingerprint']}/{nxt['fingerprint']}"
    )
    assert len(steps2) == 2


def test_unknown_fingerprint_404(lineq_server):
    code, body = get_status(lineq_server, "/.states/12345")
    assert code == 404 and "Unable to find state" in body["error"]


def test_unparseable_fingerprint_404(lineq_server):
    code, body = get_status(lineq_server, "/.states/zzz")
    assert code == 404 and "Unable to parse" in body["error"]


def test_ui_is_served(lineq_server):
    with urllib.request.urlopen(f"http://{lineq_server.addr}/") as r:
        html = r.read().decode()
    assert "State Space Explorer" in html
    with urllib.request.urlopen(f"http://{lineq_server.addr}/app.js") as r:
        assert "pollStatus" in r.read().decode()


def test_discovery_path_resolves_through_states_endpoint():
    server = serve(TwoPhaseSys(3).checker(), "localhost:0", block=False)
    try:
        server.checker.join()
        s = get(server, "/.status")
        disc = dict((n, d) for _, n, d in s["properties"])
        fps = disc["commit agreement"].split("/")
        # every prefix of the discovery path resolves
        for i in range(len(fps)):
            views = get(server, "/.states/" + "/".join(fps[: i + 1]))
            assert isinstance(views, list)
        # the recent-path snapshot was populated by the visitor
        assert s["recent_path"] is None or s["recent_path"].startswith("[")
    finally:
        server.shutdown()


def test_actor_svg_sequence_diagram():
    """An actor-model trace renders as a sequence-diagram SVG, surfaced in
    the ``/.states`` views (reference ``src/actor/model.rs:384-475`` +
    ``explorer.rs:231``)."""
    from stateright_tpu.models.paxos import paxos_model

    model = paxos_model(1)
    # direct: a delivery arrow appears for a short concrete trace
    init = model.init_states()[0]
    action = next(a for a in model.actions(init) if type(a).__name__ == "Deliver")
    nxt = model.next_state(init, action)
    from stateright_tpu.checker.path import Path

    svg = model.as_svg(Path([(init, action), (nxt, None)]))
    assert svg is not None and svg.startswith("<svg")
    assert "svg-actor-timeline" in svg and "svg-event-line" in svg
    assert "marker-end='url(#arrow)'" in svg

    # endpoint: the init view itself has no deliveries yet, but step views do
    server = serve(model.checker().target_states(50), "localhost:0", block=False)
    try:
        server.checker.join()
        inits = get(server, "/.states/")
        steps = get(server, f"/.states/{inits[0]['fingerprint']}")
        svgs = [v["svg"] for v in steps if "svg" in v]
        assert svgs and all(s.startswith("<svg") for s in svgs)
        assert any("svg-event-line" in s for s in svgs)
    finally:
        server.shutdown()


def test_timeout_renders_circle():
    from fixtures_actor import PingPongCfg, ping_pong_model
    from stateright_tpu.actor import Actor, ActorModel, Id
    from stateright_tpu.checker.path import Path
    from stateright_tpu.core import Expectation

    class TimerActor(Actor):
        def on_start(self, id, out):
            out.set_timer()
            return 0

        def on_timeout(self, id, state, out):
            out.send(id, "tick")
            return state + 1

    model = ActorModel().actor(TimerActor()).property(
        Expectation.ALWAYS, "small", lambda m, s: s.actor_states[0] < 3
    )
    init = model.init_states()[0]
    timeout = next(
        a for a in model.actions(init) if type(a).__name__ == "Timeout"
    )
    nxt = model.next_state(init, timeout)
    svg = model.as_svg(Path([(init, timeout), (nxt, None)]))
    assert "<circle" in svg and "Timeout" in svg


def test_status_reports_discoveries_mid_run():
    """Discoveries are visible in ``/.status`` while the check is still
    running (reference ``explorer.rs:133-157`` reads the live map)."""
    import threading
    import time as _time

    from fixtures_actor import PingPongCfg, ping_pong_model

    from stateright_tpu import Expectation

    model = ping_pong_model(PingPongCfg(maintains_history=True, max_nat=150_000))
    # violated a few steps in, while the bounded space is far from exhausted,
    # so the discovery must surface mid-run
    model.property(
        Expectation.ALWAYS,
        "never above 3",
        lambda m, s: max(s.actor_states) <= 3,
    )
    gate = threading.Event()

    # A visitor that blocks after a while keeps the check "running" while we
    # poll the status endpoint.
    seen = [0]

    def slow_visit(m, path):
        seen[0] += 1
        if seen[0] > 200:
            gate.wait(10.0)

    server = serve(
        model.checker().visitor(slow_visit), "localhost:0", block=False
    )
    try:
        deadline = _time.monotonic() + 30.0
        status = get(server, "/.status")
        while _time.monotonic() < deadline:
            status = get(server, "/.status")
            disc = {n: d for _, n, d in status["properties"] if d is not None}
            if disc and not status["done"]:
                break
            _time.sleep(0.1)
        assert not status["done"]
        disc = {n: d for _, n, d in status["properties"] if d is not None}
        # the falsifiable liveness property is discovered long before the
        # huge bounded space is exhausted
        assert disc, "no discovery surfaced while the check was running"
    finally:
        gate.set()
        server.checker._stop.set()
        server.shutdown()


def test_serve_tpu_strategy_endpoints():
    """The Explorer can browse a device wavefront run (beyond the reference,
    whose Explorer wraps only BfsChecker): ``/.status`` serves the engine's
    counters and parent-walk-reconstructed discovery paths, and ``/.states``
    browsing works identically (it re-executes the object form)."""
    server = serve(
        TwoPhaseSys(3).checker(), "localhost:0", block=False, strategy="tpu"
    )
    try:
        server.checker.join()
        s = get(server, "/.status")
        assert s["done"] is True
        assert s["unique_state_count"] == 288  # examples/2pc.rs:128
        disc = {n: d for _, n, d in s["properties"] if d is not None}
        assert set(disc) == {"abort agreement", "commit agreement"}
        # every discovery path resolves through /.states (object-form
        # re-execution matches device fingerprints bit-for-bit)
        for encoded in disc.values():
            code, views = get_status(server, f"/.states/{encoded}")
            assert code == 200
        # init view works too
        views = get(server, "/.states/")
        assert len(views) == 1
    finally:
        server.shutdown()


def test_serve_tpu_live_status_mid_run():
    """``/.status`` surfaces live counters and discovery paths while the
    device run is still in flight (VERDICT r2 missing #5): tiny batches plus
    per-step host syncs keep the run pollable."""
    import time as _time

    server = serve(
        TwoPhaseSys(5).checker(),
        "localhost:0",
        block=False,
        strategy="tpu",
        batch=32,
        steps_per_call=1,
    )
    try:
        saw_live = False
        saw_live_disc = False
        deadline = _time.monotonic() + 120.0
        while _time.monotonic() < deadline:
            status = get(server, "/.status")
            if status["done"]:
                break
            if status["unique_state_count"] > 0:
                saw_live = True
            disc = {n for _, n, d in status["properties"] if d is not None}
            if disc:
                saw_live_disc = True
                break
            _time.sleep(0.02)
        assert saw_live, "no live counter surfaced before completion"
        assert saw_live_disc, "no discovery path surfaced mid-run"
        server.checker.join()
        status = get(server, "/.status")
        assert status["done"] is True
        assert status["unique_state_count"] == 8832  # examples/2pc.rs:133
        disc = {n: d for _, n, d in status["properties"] if d is not None}
        assert set(disc) == {"abort agreement", "commit agreement"}
    finally:
        server.checker._stop.set()
        server.shutdown()


def test_explorer_serves_general_fragment_tpu_run():
    """The Explorer browses a device run of the compiled general fragment
    (raft): live status, discovery path links, and state pages with the
    per-step outcomes."""
    from stateright_tpu.models.raft import raft_model

    server = serve(
        raft_model(3).checker(),
        "localhost:0",
        strategy="tpu",
        block=False,
        sync=True,
        capacity=1 << 14,
    )
    try:
        server.checker.join()
        s = get(server, "/.status")
        assert s["done"] is True
        assert s["unique_state_count"] == 5_725
        props = {name: disc for _, name, disc in s["properties"]}
        assert props["a leader is elected"] is not None
        # follow the witness path to its final state page
        code, view = get_status(
            server, "/.states/" + props["a leader is elected"]
        )
        assert code == 200
        assert isinstance(view, list)
    finally:
        server.shutdown()
