"""Explorer endpoint tests (reference ``src/checker/explorer.rs:242-447``):
exact JSON views against a live (background) server over small models."""

import json
import urllib.request

import pytest

from stateright_tpu.explorer import serve
from stateright_tpu.models.two_phase_commit import TwoPhaseSys

from fixtures import LinearEquation


@pytest.fixture(scope="module")
def lineq_server():
    server = serve(
        LinearEquation(a=2, b=10, c=14).checker(),
        "localhost:0",  # ephemeral port
        block=False,
    )
    server.checker.join()
    yield server
    server.shutdown()


def get(server, path):
    with urllib.request.urlopen(f"http://{server.addr}{path}") as r:
        return json.loads(r.read())


def get_status(server, path):
    try:
        with urllib.request.urlopen(f"http://{server.addr}{path}") as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_status_endpoint(lineq_server):
    s = get(lineq_server, "/.status")
    assert s["done"] is True
    assert s["model"] == "LinearEquation"
    assert s["unique_state_count"] == 12  # reference checker.rs:459-461
    assert s["state_count"] == 15
    kinds = {name: kind for kind, name, _ in s["properties"]}
    assert kinds == {"solvable": "sometimes"}
    # the sometimes-property discovery is an encoded fingerprint path
    discovery = dict(
        (name, disc) for _, name, disc in s["properties"]
    )["solvable"]
    assert discovery is not None and "/" in discovery


def test_init_states_view(lineq_server):
    views = get(lineq_server, "/.states/")
    assert len(views) == 1
    assert views[0]["state"] == "(0, 0)"
    assert "action" not in views[0]
    assert int(views[0]["fingerprint"]) > 0


def test_steps_view_follows_fingerprints(lineq_server):
    init = get(lineq_server, "/.states/")[0]
    steps = get(lineq_server, f"/.states/{init['fingerprint']}")
    # format_action is repr(), like the reference's Debug formatting
    assert {v["action"] for v in steps} == {"'IncreaseX'", "'IncreaseY'"}
    for v in steps:
        assert "state" in v and "fingerprint" in v
    # walk one more level
    nxt = steps[0]
    steps2 = get(
        lineq_server, f"/.states/{init['fingerprint']}/{nxt['fingerprint']}"
    )
    assert len(steps2) == 2


def test_unknown_fingerprint_404(lineq_server):
    code, body = get_status(lineq_server, "/.states/12345")
    assert code == 404 and "Unable to find state" in body["error"]


def test_unparseable_fingerprint_404(lineq_server):
    code, body = get_status(lineq_server, "/.states/zzz")
    assert code == 404 and "Unable to parse" in body["error"]


def test_ui_is_served(lineq_server):
    with urllib.request.urlopen(f"http://{lineq_server.addr}/") as r:
        html = r.read().decode()
    assert "State Space Explorer" in html
    with urllib.request.urlopen(f"http://{lineq_server.addr}/app.js") as r:
        assert "pollStatus" in r.read().decode()


def test_discovery_path_resolves_through_states_endpoint():
    server = serve(TwoPhaseSys(3).checker(), "localhost:0", block=False)
    try:
        server.checker.join()
        s = get(server, "/.status")
        disc = dict((n, d) for _, n, d in s["properties"])
        fps = disc["commit agreement"].split("/")
        # every prefix of the discovery path resolves
        for i in range(len(fps)):
            views = get(server, "/.states/" + "/".join(fps[: i + 1]))
            assert isinstance(views, list)
        # the recent-path snapshot was populated by the visitor
        assert s["recent_path"] is None or s["recent_path"].startswith("[")
    finally:
        server.shutdown()
