"""Raft leader election — the actor compiler's GENERAL fragment.

Beyond the reference's example set.  Pins: the host state space, election
safety (always) + leader-elected witness (sometimes), and full
device/host parity for a timeout-driven, history-free actor system whose
twin is compiled mechanically (timer bits, Timeout actions, factored
property tables — ``parallel/actor_compiler.py`` general mode).
"""

import pytest

from stateright_tpu.actor import ActorModel, Network
from stateright_tpu.actor.device_props import exists_actor
from stateright_tpu.core import Expectation
from stateright_tpu.models.raft import LEADER, RaftServer, raft_model

RAFT3_UNIQUE = 5_725  # 3 servers, max_term=2, unordered non-duplicating


def test_raft3_host_pinned_count_and_properties():
    c = raft_model(3).checker().spawn_bfs().join()
    assert c.unique_state_count() == RAFT3_UNIQUE
    # election safety holds (no counterexample); a leader is reachable
    assert sorted(c.discoveries()) == ["a leader is elected"]
    c.assert_properties()


def test_raft3_twin_crawl_equivalence():
    """Per-level successor/fingerprint/property parity of the compiled
    twin, incl. Timeout actions and timer-bit round-trips."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent))
    from test_paxos_tensor import crawl_and_check

    m = raft_model(3)
    tm = m.tensor_model()
    assert tm is not None and tm._has_timers
    crawl_and_check(m, tm, max_levels=4)


def test_raft3_engine_full_parity():
    """Full-space device enumeration matches the host oracle, and the
    leader-election witness re-executes."""
    m = raft_model(3)
    c = m.checker().spawn_tpu(
        sync=True, capacity=1 << 15, frontier_capacity=1 << 9
    )
    assert c.unique_state_count() == RAFT3_UNIQUE
    assert sorted(c.discoveries()) == ["a leader is elected"]
    path = c.discoveries()["a leader is elected"]
    c.assert_discovery("a leader is elected", list(path.actions()))
    assert path.final_state().actor_states[int(path.actions()[-1].dst)].role == LEADER


# re-tiered fast->slow (PR 2): the fast tier blew the 870s tier-1 budget
@pytest.mark.slow
def test_raft3_lossy_engine_parity():
    """Message loss adds Drop actions; host and device agree on the
    enlarged space and still find a leader (drops are optional)."""
    m = raft_model(3)
    m.lossy_network(True)
    h = m.checker().spawn_bfs().join()
    c = m.checker().spawn_tpu(
        sync=True, capacity=1 << 16, frontier_capacity=1 << 10
    )
    assert h.unique_state_count() == c.unique_state_count()
    assert sorted(h.discoveries()) == sorted(c.discoveries())


@pytest.mark.parametrize("net", ["ordered", "unordered_duplicating"])
def test_raft2_engine_parity_across_network_semantics(net):
    """Timer-fragment compilation composes with every network semantics:
    host and device enumerate the same space under ordered FIFO and
    duplicating redelivery too."""
    m = raft_model(2, network=Network.from_name(net))
    h = m.checker().spawn_bfs().join()
    c = m.checker().spawn_tpu(sync=True, capacity=1 << 13)
    assert h.unique_state_count() == c.unique_state_count() > 0
    assert sorted(h.discoveries()) == sorted(c.discoveries())


def test_raft2_no_split_brain_two_servers():
    """With 2 servers a majority is 2: no term can elect two leaders, and
    the safety property discovers nothing on host or device."""
    m = raft_model(2)
    h = m.checker().spawn_bfs().join()
    c = m.checker().spawn_tpu(sync=True, capacity=1 << 13)
    assert h.unique_state_count() == c.unique_state_count()
    assert "election safety" not in h.discoveries()
    assert "election safety" not in c.discoveries()


def test_factored_within_boundary_compiles_and_agrees():
    """A factored ``within_boundary`` compiles: the device engine masks
    out-of-boundary successors exactly like the host checkers (boundary
    filter before counting; fully-masked states are terminal)."""
    from stateright_tpu.actor.device_props import forall_actors

    m = raft_model(3)
    m.within_boundary_(forall_actors(lambda i, s: s.term <= 1))
    h = m.checker().spawn_bfs().join()
    c = m.checker().spawn_tpu(sync=True, capacity=1 << 13)
    assert h.unique_state_count() == c.unique_state_count()
    assert 0 < h.unique_state_count() < RAFT3_UNIQUE
    assert sorted(h.discoveries()) == sorted(c.discoveries())


RAFT3_SYM_FIFO = 2_926  # BFS-order symmetry-reduced classes (FIFO oracle)


def test_mechanical_symmetry_partition_matches_host():
    """The compiled twin's mechanical canonicalizer (permutation tables
    over the union state universe) induces EXACTLY the host
    ``representative()`` partition — checked state-by-state over a
    bounded crawl."""
    import numpy as np
    import jax.numpy as jnp

    from stateright_tpu.fingerprint import stable_hash
    from stateright_tpu.ops import row_hash

    m = raft_model(3)
    tm = m.tensor_model()
    tm.init_rows()
    assert hasattr(tm, "representative_rows")
    # bounded BFS sample of the space
    states, frontier = [], list(m.init_states())
    seen = set(frontier)
    for _ in range(5):
        states += frontier
        nxt = []
        for s in frontier:
            for t in m.next_states(s):
                if t not in seen:
                    seen.add(t)
                    nxt.append(t)
        frontier = nxt
    states += frontier
    hkeys = [stable_hash(s.representative()) for s in states]
    rows = np.asarray([tm.encode_state(s) for s in states], np.uint64)
    dkeys = np.asarray(row_hash(tm.representative_rows(jnp.asarray(rows))))
    # identical partitions: same-key pairs agree in both directions
    import collections

    hgroup = collections.defaultdict(set)
    dgroup = collections.defaultdict(set)
    for i, (h, d) in enumerate(zip(hkeys, dkeys)):
        hgroup[h].add(i)
        dgroup[int(d)].add(i)
    assert sorted(map(sorted, hgroup.values())) == sorted(
        map(sorted, dgroup.values())
    )


def test_mechanical_symmetry_engine_matches_fifo_oracle():
    """Device symmetry reduction on the compiled Raft twin: counts match
    the engine-independent FIFO oracle, the reduced search still finds
    the leader witness, and the trace reconstructs through the
    class-matching walk."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent))
    from test_tensor_models import host_fifo_sym_oracle

    m = raft_model(3)
    assert host_fifo_sym_oracle(m) == RAFT3_SYM_FIFO
    c = m.checker().symmetry().spawn_tpu(sync=True, capacity=1 << 14)
    assert c.unique_state_count() == RAFT3_SYM_FIFO
    assert sorted(c.discoveries()) == ["a leader is elected"]
    path = c.discoveries()["a leader is elected"]
    assert len(path.actions()) >= 3  # timeout + vote round trip


from stateright_tpu.models.raft import (  # single source of the pin table
    RAFT3_SYM_SHARDED_BY_WIDTH as RAFT3_SYM_SHARDED,
)


def test_mechanical_symmetry_sharded_engine_pinned_per_mesh_width():
    """Sharded-engine symmetry on the compiled twin: reduced counts are
    visit-order-dependent when the representative is not class-invariant,
    but for a FIXED mesh width the schedule is deterministic — so the
    count is pinned EXACTLY per width (a canonicalization tie-break or
    routing regression cannot hide inside a range).  Width 1 equals the
    host FIFO oracle (2,926)."""
    m = raft_model(3)
    c = m.checker().symmetry().spawn_tpu(
        sync=True, devices=8, capacity=1 << 14, frontier_capacity=1 << 9
    )
    assert c.unique_state_count() == RAFT3_SYM_SHARDED[8]
    assert sorted(c.discoveries()) == ["a leader is elected"]
    c2 = m.checker().symmetry().spawn_tpu(
        sync=True, devices=2, capacity=1 << 14, frontier_capacity=1 << 9
    )
    assert c2.unique_state_count() == RAFT3_SYM_SHARDED[2]


def test_eventually_property_parity_general_fragment():
    """Liveness bookkeeping (ebits) composes with the general fragment:
    with a single term two servers can split their votes and stop
    campaigning, a terminal path electing nobody — host and device both
    discover the 'eventually' counterexample on the same space."""
    m = raft_model(2, max_term=1)
    m.property(
        Expectation.EVENTUALLY,
        "eventually elects",
        exists_actor(lambda i, s: s.role == LEADER),
    )
    h = m.checker().spawn_bfs().join()
    c = m.checker().spawn_tpu(sync=True, capacity=1 << 13)
    assert h.unique_state_count() == c.unique_state_count() == 25
    assert "eventually elects" in h.discoveries()
    assert "eventually elects" in c.discoveries()
    # the counterexample ends terminal with no leader (reference ebits
    # semantics: bits still set at a terminal state flush as discoveries)
    final = h.discoveries()["eventually elects"].final_state()
    assert all(s.role != LEADER for s in final.actor_states)


def test_history_free_model_requires_factored_properties():
    from stateright_tpu.parallel.actor_compiler import (
        CompileError,
        compile_actor_model,
    )

    m = ActorModel(cfg=None, init_history=None)
    m.actor(RaftServer(peers=[], cluster=1, max_term=1))
    m.init_network_(Network.new_unordered_nonduplicating())
    m.property(
        Expectation.ALWAYS, "opaque", lambda model, s: True  # not factored
    )
    with pytest.raises(CompileError, match="factored"):
        compile_actor_model(m)


def test_factored_predicates_evaluate_on_host():
    """The same predicate object drives host checking directly."""
    m = raft_model(3)
    checker = m.checker().spawn_dfs().join()
    assert checker.unique_state_count() == RAFT3_UNIQUE
    # exists_actor works as a plain condition
    cond = exists_actor(lambda i, s: s.role == LEADER)
    final = checker.discoveries()["a leader is elected"].final_state()
    assert cond(m, final)


def test_exists_actor_pair_quantifier():
    """Coverage for the fourth factored quantifier: a sometimes-property
    over actor PAIRS (two servers granted to the same candidate) agrees
    host=device."""
    from stateright_tpu.actor.device_props import exists_actor_pair

    m = raft_model(3)
    m.property(
        Expectation.SOMETIMES,
        "two granted the same candidate",
        exists_actor_pair(
            lambda i, si, j, sj: si.voted_for != -1
            and si.voted_for == sj.voted_for
        ),
    )
    h = m.checker().spawn_bfs().join()
    c = m.checker().spawn_tpu(sync=True, capacity=1 << 14)
    assert "two granted the same candidate" in h.discoveries()
    assert "two granted the same candidate" in c.discoveries()


def test_too_tight_compile_bound_fails_loudly():
    """A state_bound that cuts REACHABLE states must fail the run, not
    silently truncate the space (poisoned rows previously deduped onto
    self-loops and produced a plausible-looking wrong count)."""
    from stateright_tpu.parallel.actor_compiler import compile_actor_model

    m = raft_model(3)  # reaches term 2; bound it at 1
    tm = compile_actor_model(
        m,
        state_bound=lambda i, s: s.term <= 1,
        env_bound=lambda e: e.msg[1] <= 1,
    )
    m.tensor_model = lambda: tm
    with pytest.raises(RuntimeError, match="poisoned"):
        m.checker().spawn_tpu(sync=True, capacity=1 << 14)
    with pytest.raises(RuntimeError, match="poisoned"):
        m.checker().spawn_tpu(
            sync=True, devices=8, capacity=1 << 14,
            frontier_capacity=1 << 9,
        )
