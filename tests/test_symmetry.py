"""Symmetry reduction tests (reference ``rewrite_plan.rs:115-194``,
``model_state.rs:120-196``, ``dfs.rs:394-483``)."""

from stateright_tpu import Expectation, Model, Property
from stateright_tpu.actor import ActorModelState, Envelope, Id, Network
from stateright_tpu.symmetry import RewritePlan, rewrite_value
from stateright_tpu.utils import DenseNatMap


def test_rewrite_plan_double_argsort():
    # values [B, C, A] -> sorted [A, B, C]; old->new mapping: B(0)->1, C(1)->2, A(2)->0
    plan = RewritePlan.from_values_to_sort(["B", "C", "A"])
    assert plan.mapping == [1, 2, 0]
    assert plan.reindex(["B", "C", "A"]) == ["A", "B", "C"]
    assert plan.rewrite_id(Id(2)) == Id(0)


def test_rewrite_value_structural():
    plan = RewritePlan([1, 0])  # swap ids 0 and 1
    env = Envelope(src=Id(0), dst=Id(1), msg=("hello", Id(0)))
    out = rewrite_value(env, plan)
    assert out == Envelope(src=Id(1), dst=Id(0), msg=("hello", Id(1)))
    assert rewrite_value({Id(0): [Id(1)]}, plan) == {Id(1): [Id(0)]}
    assert rewrite_value(frozenset([Id(0)]), plan) == frozenset([Id(1)])
    assert rewrite_value("Id(0)", plan) == "Id(0)"  # strings untouched


def test_network_not_rewritten_messages_keep_payload():
    plan = RewritePlan([1, 0])
    n = Network.new_unordered_nonduplicating(
        [Envelope(src=Id(0), dst=Id(1), msg="m")] * 2
    )
    rw = rewrite_value(n, plan)
    envs = list(rw.iter_all())
    assert len(envs) == 2
    assert all(e == Envelope(src=Id(1), dst=Id(0), msg="m") for e in envs)


def test_actor_model_state_representative_sorts_actor_states():
    s = ActorModelState(
        actor_states=("z", "a"),
        network=Network.new_unordered_duplicating(
            [Envelope(src=Id(0), dst=Id(1), msg="m")]
        ),
        is_timer_set=(True, False),
        history=None,
    )
    rep = s.representative()
    assert rep == rep.representative()  # canonical is a fixed point
    # equivalent permuted state maps to the same representative
    s2 = ActorModelState(
        actor_states=("a", "z"),
        network=Network.new_unordered_duplicating(
            [Envelope(src=Id(1), dst=Id(0), msg="m")]
        ),
        is_timer_set=(False, True),
        history=None,
    )
    assert s2.representative() == rep


def test_dfs_symmetry_reduces_state_count_and_keeps_paths_valid():
    """Two interchangeable tokens stepping 0->1->2 independently; symmetric
    states (a,b) ~ (b,a).  Also pins the reference's path-validity
    regression: the search must continue from the ORIGINAL state, not the
    representative (``dfs.rs:394-483``)."""

    class Tokens(Model):
        def init_states(self):
            return [(0, 0)]

        def actions(self, state):
            return [0, 1]

        def next_state(self, state, i):
            if state[i] >= 2:
                return None
            lst = list(state)
            lst[i] += 1
            return tuple(lst)

        def properties(self):
            return [
                Property.sometimes("both max", lambda m, s: s == (2, 2)),
                # never-discovered property forces full enumeration
                Property.always("bounded", lambda m, s: max(s) <= 2),
            ]

    full = Tokens().checker().spawn_dfs().join()
    assert full.unique_state_count() == 9  # 3x3 grid
    sym = (
        Tokens()
        .checker()
        .symmetry_with(lambda s: tuple(sorted(s)))
        .spawn_dfs()
        .join()
    )
    assert sym.unique_state_count() == 6  # multisets {a<=b}
    path = sym.assert_any_discovery("both max")
    # path must be executable in the un-reduced model
    assert path.final_state() == (2, 2)
    assert len(path.actions()) == 4


def test_densenatmap_rewrite():
    plan = RewritePlan([1, 0])
    m = DenseNatMap([("owner", Id(0)), ("owner", Id(1))])
    rw = m.rewrite(plan)
    assert rw.values() == [("owner", Id(0)), ("owner", Id(1))][::-1] or rw.values() == [
        ("owner", Id(0)),
        ("owner", Id(1)),
    ]
    # reindexed: position swapped AND inner ids rewritten
    assert rw[0] == ("owner", Id(0)) or rw[0] == ("owner", Id(1))
