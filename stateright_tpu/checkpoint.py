"""Crash-safe autosave checkpoints: rotating snapshot generations.

The device engines' whole run state is already a host-serializable
snapshot dict (``TpuChecker.checkpoint()`` / ``_carry_to_snapshot``);
this module gives those snapshots a DURABLE, self-describing home so a
SIGKILL/OOM/power-cut run can resume from its last saved generation
(``docs/robustness.md``).

Directory layout under the autosave root (``CheckerBuilder.autosave`` /
``STATERIGHT_TPU_AUTOSAVE``):

    <root>/gen-000007/snapshot.npz    # the engine snapshot (np.savez)
    <root>/gen-000007/MANIFEST.json   # written LAST = the commit point

Both files land via the atomic write discipline
(``telemetry/_atomic.py``: tmp + fsync + ``os.replace``), and the
manifest is written after the npz — a generation without a parseable
manifest is by definition incomplete (torn mid-write) and
:func:`latest_generation` skips it with a loud warning instead of
resuming from garbage.  Rotation keeps the newest ``keep`` complete
generations; pruning deletes older ones only after a newer complete
generation exists, so there is always at least one resumable state on
disk once the first save lands.

The manifest additionally carries the run's identity and progress
(``run_id``, ``config`` — the report's canonical config block — totals,
per-property discovery flags), which lets the supervisor register a
**stub report** for a run that was killed before it could archive
itself: the run registry then has a parent record for PR 12's lineage
gate even though the parent process died mid-flight
(``supervisor.py``).
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
from typing import Optional

CKPT_V = 1

ENV_AUTOSAVE = "STATERIGHT_TPU_AUTOSAVE"
ENV_AUTOSAVE_SECS = "STATERIGHT_TPU_AUTOSAVE_SECS"
ENV_AUTOSAVE_KEEP = "STATERIGHT_TPU_AUTOSAVE_KEEP"

DEFAULT_EVERY_SECS = 60.0
DEFAULT_KEEP = 3

_GEN_RE = re.compile(r"^gen-(\d{6,})$")


def resolve_autosave(builder_opts: Optional[dict]) -> Optional[dict]:
    """The effective autosave config: the builder's ``autosave(DIR,...)``
    wins, else the ``STATERIGHT_TPU_AUTOSAVE`` env knob (cadence/keep
    from their env siblings); None = autosave off."""
    if builder_opts:
        return dict(builder_opts)
    root = os.environ.get(ENV_AUTOSAVE, "").strip()
    if not root:
        return None
    out = {"dir": root, "every_secs": DEFAULT_EVERY_SECS,
           "keep": DEFAULT_KEEP}
    for env, key, cast in ((ENV_AUTOSAVE_SECS, "every_secs", float),
                           (ENV_AUTOSAVE_KEEP, "keep", int)):
        raw = os.environ.get(env, "").strip()
        if not raw:
            continue
        try:
            out[key] = cast(raw)
        except ValueError:
            print(
                f"stateright-tpu: autosave: ignoring malformed "
                f"{env}={raw!r}; using the default",
                file=sys.stderr,
            )
    return out


def _gen_dirs(root: str) -> list:
    """``[(gen, path)]`` ascending; tolerates an absent root."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    out = []
    for name in names:
        m = _GEN_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(root, name)))
    out.sort()
    return out


def next_generation(root: str) -> int:
    """The next generation number (numbering continues across restarts
    so a resumed run never overwrites its parent's generations)."""
    gens = _gen_dirs(root)
    return (gens[-1][0] + 1) if gens else 0


def _read_manifest(gen_path: str) -> Optional[dict]:
    try:
        with open(os.path.join(gen_path, "MANIFEST.json")) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def save_generation(
    root: str, gen: int, snap: dict, manifest: dict, keep: int = DEFAULT_KEEP,
) -> str:
    """Write one complete generation (npz first, manifest LAST — the
    commit point), then prune to the newest ``keep`` complete
    generations.  Returns the generation directory.  Raises ``OSError``
    on write failure with prior generations untouched."""
    from .testing import faults

    faults.fire("snapshot_write", gen=gen)
    from .telemetry._atomic import atomic_write_json, atomic_write_npz

    gen_dir = os.path.join(root, f"gen-{gen:06d}")
    os.makedirs(gen_dir, exist_ok=True)
    atomic_write_npz(os.path.join(gen_dir, "snapshot.npz"), snap)
    atomic_write_json(
        os.path.join(gen_dir, "MANIFEST.json"),
        {"v": CKPT_V, "gen": gen, **manifest},
    )
    prune_generations(root, keep)
    return gen_dir


def prune_generations(root: str, keep: int) -> None:
    """Delete everything but the newest ``keep`` COMPLETE generations.
    Incomplete (torn) generations older than the newest complete one are
    also removed — they can never be resumed from."""
    import shutil

    gens = _gen_dirs(root)
    complete = [(g, p) for g, p in gens if _read_manifest(p) is not None]
    if not complete:
        return  # never delete the only thing on disk, torn or not
    keep_paths = {p for _, p in complete[-max(keep, 1):]}
    newest_complete = complete[-1][0]
    for g, p in gens:
        if p in keep_paths:
            continue
        if _read_manifest(p) is None and g > newest_complete:
            continue  # a torn WRITE IN PROGRESS may still be committing
        try:
            shutil.rmtree(p)
        except OSError:
            pass


def list_generations(root: str) -> list:
    """``[{gen, path, complete, manifest?}]`` ascending — the
    operational view (``supervise`` verb, tests)."""
    out = []
    for g, p in _gen_dirs(root):
        man = _read_manifest(p)
        out.append({
            "gen": g, "path": p, "complete": man is not None,
            **({"manifest": man} if man is not None else {}),
        })
    return out


def latest_gen_number(root: str) -> Optional[int]:
    """The newest COMPLETE generation number, manifest-only — no npz
    load.  The fleet scheduler's preempt/resume records read this (a
    preempted job's resume point) without paying a snapshot
    deserialization per bookkeeping line."""
    for g, p in reversed(_gen_dirs(root)):
        if _read_manifest(p) is not None:
            return g
    return None


def latest_generation(root: str) -> Optional[tuple]:
    """``(snapshot_dict, manifest)`` of the newest COMPLETE generation,
    or None when the directory holds no resumable state.  A generation
    with a missing/corrupt manifest or an unloadable npz is TORN: it is
    skipped with a loud warning and the next-newest complete one is
    used — atomic writes make this the crashed-mid-save case, and prior
    generations are exactly the durability being paid for."""
    import numpy as np

    for g, p in reversed(_gen_dirs(root)):
        man = _read_manifest(p)
        npz = os.path.join(p, "snapshot.npz")
        if man is None:
            print(
                f"stateright-tpu: autosave: skipping torn generation "
                f"{p} (no complete MANIFEST.json — the writer died "
                "mid-save; resuming from the previous generation)",
                file=sys.stderr,
            )
            continue
        try:
            with np.load(npz, allow_pickle=False) as z:
                snap = {k: z[k] for k in z.files}
        except (OSError, ValueError, KeyError) as e:
            print(
                f"stateright-tpu: autosave: skipping unreadable "
                f"generation {p} ({type(e).__name__}: {e})",
                file=sys.stderr,
            )
            continue
        return snap, man
    return None


class AutosaveService:
    """Per-run autosave driver: owns the cadence clock, the generation
    counter, and the write/rotate/record plumbing.  The engines call
    :meth:`due` at every host sync and :meth:`save` with a snapshot when
    it returns True (``every_secs=0`` saves at EVERY host sync — the
    chaos-test cadence).  A failed write degrades loudly (warn once,
    keep running): losing a checkpoint must never kill the run the
    checkpoints exist to protect."""

    def __init__(self, root: str, every_secs: float, keep: int,
                 recorder=None):
        self.root = str(root)
        self.every_secs = float(every_secs)
        self.keep = int(keep)
        self.recorder = recorder
        self.generations_written = 0
        self.failures = 0
        self.last_gen: Optional[int] = None
        self.last_save_monotonic: Optional[float] = None
        self._warned = False
        os.makedirs(self.root, exist_ok=True)
        self._gen = next_generation(self.root)
        self._clock = time.monotonic()

    def due(self) -> bool:
        return time.monotonic() - self._clock >= self.every_secs

    def checkpoint_age_secs(self) -> Optional[float]:
        if self.last_save_monotonic is None:
            return None
        return time.monotonic() - self.last_save_monotonic

    def note_failure(self, gen: int, e: BaseException) -> None:
        """Account one failed generation write: warn ONCE, bump the
        failure counter, and disclose an ``ok=false`` checkpoint record.
        Shared by :meth:`save` (OSError from the atomic write) and the
        engines' outer guard (non-OSError failures, e.g. a snapshot
        materialization error) so every failure mode reaches the
        durability block's disclosure."""
        self.failures += 1
        if not self._warned:
            self._warned = True
            print(
                f"stateright-tpu: autosave: generation write failed "
                f"({type(e).__name__}: {e}); the run continues "
                "WITHOUT fresh checkpoints (durability degraded)",
                file=sys.stderr,
            )
        if self.recorder is not None:
            self.recorder.record(
                "checkpoint", v=CKPT_V, gen=gen, ok=False,
                error=f"{type(e).__name__}: {e}",
            )

    def save(self, snap: dict, manifest: dict) -> Optional[str]:
        """Write one generation; returns its path, or None on a degraded
        (failed) write.  Resets the cadence clock either way — a failing
        disk must not turn every subsequent sync into a write attempt."""
        t0 = time.monotonic()
        self._clock = t0
        gen = self._gen
        try:
            path = save_generation(
                self.root, gen, snap, manifest, keep=self.keep
            )
        except OSError as e:
            self.note_failure(gen, e)
            return None
        self._gen = gen + 1
        self.generations_written += 1
        self.last_gen = gen
        self.last_save_monotonic = time.monotonic()
        if self.recorder is not None:
            self.recorder.record(
                "checkpoint", v=CKPT_V, gen=gen, ok=True,
                unique=int(manifest.get("totals", {}).get("unique") or 0),
                states=int(manifest.get("totals", {}).get("states") or 0),
                secs=round(self.last_save_monotonic - t0, 6),
            )
        return path

    def status(self) -> dict:
        """The live autosave half of the durability block."""
        out = {
            "dir": self.root,
            "every_secs": self.every_secs,
            "keep": self.keep,
            "generations": self.generations_written,
            "failures": self.failures,
        }
        if self.last_gen is not None:
            out["last_gen"] = self.last_gen
        age = self.checkpoint_age_secs()
        if age is not None:
            out["last_checkpoint_age_secs"] = round(age, 3)
        return out


def stub_report_doc(manifest: dict) -> Optional[dict]:
    """A registry-archivable report document reconstructed from an
    autosave manifest — the parent record for a run that was killed
    before it could archive itself (``RunRegistry.record_doc``).  The
    totals carry ``done: false`` + ``interrupted: true``: this is a
    checkpoint of a run in flight, honestly labelled.  None when the
    manifest predates the config-carrying format."""
    from .telemetry.report import REPORT_V

    if not manifest.get("run_id") or not manifest.get("config"):
        return None
    totals = dict(manifest.get("totals") or {})
    totals["done"] = False
    totals["interrupted"] = True
    doc = {
        "generated_at": manifest.get("written_at"),
        "run_id": manifest["run_id"],
        "v": REPORT_V,
        "model": manifest.get("model"),
        "engine": manifest.get("engine"),
        "config": manifest["config"],
        "totals": totals,
        "properties": list(manifest.get("properties") or []),
    }
    if manifest.get("parent_run_id"):
        doc["parent_run_id"] = manifest["parent_run_id"]
    return doc


# -- live progress heartbeat (docs/observability.md) --------------------------

PROGRESS_V = 1
PROGRESS_FILE = "progress.json"

# a "running" heartbeat older than beats_every * this factor means the
# writer is gone (SIGKILLed) or wedged — the post-mortem verdict the
# ``status`` CLI verb renders
STALE_FACTOR = 5.0


class ProgressHeartbeat:
    """Atomic ``progress.json`` writer next to the autosave generations.

    The engines beat it at every host sync they already make (throttled
    to ``every_secs``), so ``python -m stateright_tpu.models._cli status
    <run_dir>`` can tail ANY headless run — including one that was
    SIGKILLed mid-flight: the file survives with the last beaten
    counters and a wall-clock ``ts``, and a stale ``ts`` on a
    ``running`` status IS the post-mortem ("where did it stall").
    Every write rides the atomic discipline (``telemetry/_atomic.py``) —
    a reader never sees a torn file.  Write failures degrade silently
    (drop the beat, keep the run): liveness reporting must never kill
    the run it reports on."""

    def __init__(self, root: str, every_secs: float = 1.0,
                 meta: Optional[dict] = None):
        self.path = os.path.join(str(root), PROGRESS_FILE)
        self.every_secs = float(every_secs)
        self.meta = dict(meta or {})
        self._clock: Optional[float] = None
        self.beats = 0

    def beat(self, recorder=None, status: str = "running",
             force: bool = False, **extra) -> bool:
        """One heartbeat (dropped unless due or ``force``).  The payload
        samples the recorder's last step record + health snapshot —
        host-side values already in hand, zero device work.  Returns
        True when a write landed."""
        now = time.monotonic()
        if not force and self._clock is not None:
            if now - self._clock < self.every_secs:
                return False
        self._clock = now
        doc = {
            "v": PROGRESS_V,
            "status": str(status),
            "ts": round(time.time(), 3),
            "every_secs": self.every_secs,
            **self.meta,
        }
        if recorder is not None:
            step = recorder.last_step()
            if step is not None:
                for k in ("states", "unique", "dt", "queue", "frontier",
                          "load_factor", "depth"):
                    if step.get(k) is not None:
                        doc[k] = step[k]
                doc["steps"] = recorder.kind_count("step")
            health = recorder.health()
            for k in ("phase", "stalled", "stall_reason",
                      "ewma_states_per_sec", "eta_secs", "oom_risk"):
                if health.get(k) is not None:
                    doc[k] = health[k]
        doc.update({k: v for k, v in extra.items() if v is not None})
        try:
            from .telemetry._atomic import atomic_write_json

            atomic_write_json(self.path, doc)
        except Exception:  # noqa: BLE001 - liveness reporting must never
            return False  # kill the run it reports on
        self.beats += 1
        return True


def read_progress(run_dir: str) -> Optional[dict]:
    """Parse ``<run_dir>/progress.json`` and attach the liveness
    verdict: ``fresh`` (the writer beat recently), ``age_secs``, and
    ``verdict`` — ``running`` / ``done`` / ``failed`` straight from the
    file, or ``dead`` when a ``running`` heartbeat went stale (the
    writer was SIGKILLed or wedged).  None when no heartbeat exists."""
    path = os.path.join(str(run_dir), PROGRESS_FILE)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(doc, dict):
        return None
    out = dict(doc)
    ts = doc.get("ts")
    if isinstance(ts, (int, float)):
        age = max(time.time() - float(ts), 0.0)
        out["age_secs"] = round(age, 3)
        every = float(doc.get("every_secs") or 1.0)
        out["fresh"] = age <= max(every * STALE_FACTOR, 5.0)
    else:
        out["fresh"] = False
    status = str(doc.get("status") or "running")
    if status == "running" and not out["fresh"]:
        out["verdict"] = "dead"
    else:
        out["verdict"] = status
    return out
