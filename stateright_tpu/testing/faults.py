"""Deterministic fault injection — the chaos layer behind
``docs/robustness.md``.

A :class:`FaultPlan` is a seed-driven, JSON-serializable list of
:class:`Fault` entries, each naming a **site** (an injection seam the
engines/stores expose), a **trigger** (the ``at``-th occurrence of that
site), and an **action** (what failure to manufacture).  Installing a
plan (``plan.install()`` / ``with plan:``) arms the process-global hook;
the seams call :func:`fire` with their occurrence context and the plan
decides, deterministically, whether this occurrence fails.

Sites (the seams wired in this package):

 - ``host_sync``       — every device-engine host sync (wavefront + sharded)
 - ``growth``          — every growth boundary (device engines)
 - ``spill_flush``     — a :class:`~stateright_tpu.spill.SpillStore` disk
   segment flush
 - ``snapshot_write``  — an autosave generation write
   (``stateright_tpu/checkpoint.py``)
 - ``atomic_write``    — every durable write in the package
   (``telemetry/_atomic.py``)

Actions:

 - ``kill``    — raise :class:`InjectedKill` (preemption-shaped: the
   supervisor classifies it transient, like SIGTERM/SIGINT)
 - ``oom``     — raise :class:`InjectedOOM` (message carries
   ``RESOURCE_EXHAUSTED``, the XLA device-OOM shape)
 - ``io``      — raise ``OSError(EIO)``
 - ``enospc``  — raise ``OSError(ENOSPC)`` (disk full)
 - ``sigterm`` / ``sigkill`` — deliver the real signal to this process
   (the cross-process chaos smoke: SIGKILL is not catchable, the run
   dies exactly as a preempted job does)

Contract (pinned by the chaos suite): with no plan installed the hooks
are inert host-side checks — the engines' step jaxpr is bit-identical
and the engine cache unkeyed whether this module was ever imported or a
plan was installed; injection happens in host loops only, never in
compiled code.

Every firing is appended to the plan's ``fired`` log and — when the seam
passed its flight recorder — emitted as a versioned ``fault`` ring
record, so chaos runs leave an auditable trail (the CI smoke uploads the
plan + log as an artifact via :meth:`FaultPlan.to_jsonl`).
"""

from __future__ import annotations

import errno
import json
import threading
from dataclasses import dataclass, field
from typing import Optional

FAULT_V = 1

SITES = ("host_sync", "growth", "spill_flush", "snapshot_write",
         "atomic_write")
ACTIONS = ("kill", "oom", "io", "enospc", "sigterm", "sigkill")


class InjectedFault(Exception):
    """Base class for manufactured failures (so tests can catch the
    whole family)."""


class InjectedKill(InjectedFault):
    """Preemption-shaped kill: the supervised-run classifier treats it
    exactly like SIGTERM/SIGINT (transient; resume from autosave)."""


class InjectedOOM(InjectedFault):
    """Device-OOM-shaped failure: the message carries
    ``RESOURCE_EXHAUSTED`` so the supervisor's classifier matches it by
    the same rule that matches a real ``XlaRuntimeError``."""


@dataclass
class Fault:
    """One scheduled failure: fire ``action`` at the ``at``-th occurrence
    (0-based) of ``site``.  One-shot: ``fired`` flips on delivery."""

    site: str
    action: str = "kill"
    at: int = 0
    fired: bool = False

    def to_json(self) -> dict:
        return {"site": self.site, "action": self.action, "at": self.at,
                "fired": self.fired}

    @classmethod
    def from_json(cls, d: dict) -> "Fault":
        return cls(
            site=str(d["site"]), action=str(d.get("action", "kill")),
            at=int(d.get("at", 0)), fired=bool(d.get("fired", False)),
        )


@dataclass
class FaultPlan:
    """A deterministic chaos schedule.  ``seed`` names the plan (and
    drives :meth:`scheduled`'s trigger derivation); ``faults`` is the
    explicit schedule; ``fired`` logs deliveries in order."""

    faults: list
    seed: int = 0
    fired: list = field(default_factory=list)

    def __post_init__(self):
        self._counts: dict = {}
        self._lock = threading.Lock()
        for f in self.faults:
            if f.site not in SITES:
                raise ValueError(
                    f"unknown fault site {f.site!r} (sites: {SITES})"
                )
            if f.action not in ACTIONS:
                raise ValueError(
                    f"unknown fault action {f.action!r} "
                    f"(actions: {ACTIONS})"
                )

    # -- construction --------------------------------------------------------

    @classmethod
    def scheduled(
        cls, seed: int, site: str, action: str = "kill",
        lo: int = 1, hi: int = 16,
    ) -> "FaultPlan":
        """Seed-driven single-fault plan: the trigger step is derived
        deterministically from ``seed`` in ``[lo, hi)`` — same seed, same
        schedule, every run (no wall clock, no global RNG)."""
        import random

        at = random.Random(seed).randrange(lo, max(hi, lo + 1))
        return cls([Fault(site=site, action=action, at=at)], seed=seed)

    # -- (de)serialization: the CI artifact --------------------------------

    def to_json(self) -> dict:
        return {
            "v": FAULT_V,
            "seed": self.seed,
            "faults": [f.to_json() for f in self.faults],
        }

    @classmethod
    def from_json(cls, d: dict) -> "FaultPlan":
        return cls(
            [Fault.from_json(f) for f in d.get("faults", [])],
            seed=int(d.get("seed", 0)),
        )

    @classmethod
    def from_file(cls, path: str) -> "FaultPlan":
        with open(path) as f:
            return cls.from_json(json.load(f))

    def to_jsonl(self, path: str) -> None:
        """One plan header line + one line per delivered fault — the
        chaos run's auditable trail (CI uploads it)."""
        lines = [json.dumps({"kind": "plan", **self.to_json()})]
        lines += [json.dumps({"kind": "fired", **e}) for e in self.fired]
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")

    # -- arming --------------------------------------------------------------

    def install(self) -> "FaultPlan":
        global _ACTIVE
        _ACTIVE = self
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        if _ACTIVE is self:
            _ACTIVE = None

    def __enter__(self) -> "FaultPlan":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- delivery ------------------------------------------------------------

    def _fire(self, site: str, recorder=None, **ctx) -> None:
        with self._lock:
            n = self._counts.get(site, 0)
            self._counts[site] = n + 1
            hit = None
            for f in self.faults:
                if f.site == site and not f.fired and f.at == n:
                    hit = f
                    f.fired = True
                    break
            if hit is not None:
                self.fired.append({
                    "site": site, "action": hit.action, "at": n, **ctx,
                })
        if hit is None:
            return
        if recorder is not None:
            recorder.record(
                "fault", v=FAULT_V, site=site, action=hit.action, at=n,
            )
        _deliver(hit.action, site, n)


def _deliver(action: str, site: str, at: int):
    msg = f"injected {action!r} fault at {site}[{at}] (FaultPlan)"
    if action == "kill":
        raise InjectedKill(msg)
    if action == "oom":
        raise InjectedOOM(f"RESOURCE_EXHAUSTED: {msg}")
    if action == "io":
        raise OSError(errno.EIO, msg)
    if action == "enospc":
        raise OSError(errno.ENOSPC, msg)
    if action in ("sigterm", "sigkill"):
        import os
        import signal

        sig = signal.SIGTERM if action == "sigterm" else signal.SIGKILL
        os.kill(os.getpid(), sig)
        return  # SIGTERM may be handled; SIGKILL never returns
    raise ValueError(action)


_ACTIVE: Optional[FaultPlan] = None


def active() -> Optional[FaultPlan]:
    """The installed plan, or None (the default, and the fast path)."""
    return _ACTIVE


def fire(site: str, recorder=None, **ctx) -> None:
    """The seam hook: a no-op unless a plan is installed AND schedules
    this occurrence.  Called from HOST loops only — never from traced
    code — so arming a plan cannot change a jaxpr (pinned)."""
    plan = _ACTIVE
    if plan is not None:
        plan._fire(site, recorder=recorder, **ctx)
