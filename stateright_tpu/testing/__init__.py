"""Test-support machinery shipped with the package (not test code).

``stateright_tpu.testing.faults`` is the deterministic fault-injection
layer the chaos suite and the CI chaos smoke drive
(``docs/robustness.md``).
"""

from . import faults  # noqa: F401

__all__ = ["faults"]
