"""The Explorer: a web UI for interactively browsing a state space.

HTTP surface mirrors the reference server (``src/checker/explorer.rs``):

 - ``GET /.status`` — JSON ``{done, model, state_count, unique_state_count,
   properties: [[expectation, name, encoded_discovery_path|null], ...],
   recent_path}`` (reference ``StatusView``, ``explorer.rs:12-22,133-157``).
 - ``GET /.states/`` — one view per init state (``explorer.rs:186-198``).
 - ``GET /.states/{fp1}/{fp2}/...`` — follows the fingerprint path by
   re-executing the model (``Path.from_fingerprints``), then returns one view
   per enabled action of the final state: ``{action, outcome, state,
   fingerprint, svg}``; ignored (no-op) actions are returned with no state,
   "as it may be useful for debugging" (``explorer.rs:199-232``); unknown
   fingerprints give 404 (``explorer.rs:233-237``).
 - ``GET /.metrics`` — live flight-recorder telemetry (beyond the
   reference): ``{summary, series, occupancy, counters, health,
   cartography, memory}`` for runs spawned with ``.telemetry()``
   (``stateright_tpu/telemetry/``); telemetry off returns a stable JSON
   error body ``{"error": "telemetry_disabled", "hint": ...}`` with 404.
   The UI draws throughput/occupancy sparklines and the cartography
   panel (depth/action histograms, property tallies, shard loads) from
   it.
 - ``GET /.runs`` — the persistent run registry's index + per-config
   trends (``telemetry/registry.py``; serve with ``runs_dir=`` or
   ``STATERIGHT_TPU_RUN_DIR``).  ``GET /.runs/{run_id}`` returns one
   archived report document; ``GET /.runs/diff/{a}/{b}`` the
   contract-aware diff of two archived runs (``telemetry/diff.py``).
   Every error on these endpoints uses the SAME stable shape as the
   telemetry-off body — ``{"error": <token>, "hint": <prose>}`` — never
   an ad-hoc string (pinned by the schema test): ``registry_disabled``
   when no registry is configured, ``unknown_run`` for an unindexed id.
   The UI's multi-run dashboard (run list, two-run diff panel,
   per-config trend sparklines) reads these.
 - ``GET /metrics`` — Prometheus text exposition of the live metrics
   bus (``telemetry/metrics.py``; docs/observability.md): the engine
   families published at host syncs plus the fleet pool families.
   Always 200; an empty exposition just means nothing published yet.
 - ``GET /.progress`` / ``GET /.progress/{job}`` — the atomic
   ``progress.json`` heartbeats (``checkpoint.ProgressHeartbeat``) of
   the served root / one fleet job, with the liveness verdict attached
   (``running`` / ``done`` / ``failed`` / ``dead``).  Serve with
   ``progress_root=`` (defaults to the builder's autosave dir).
 - ``GET /`` — the bundled single-page UI (``ui/``; ours, not the
   reference's).

Checking runs concurrently: ``serve()`` attaches a rate-limited snapshot
visitor that records the most recently visited path (reference
``explorer.rs:57-88``), spawns a BFS check, and serves HTTP over it.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path as FsPath
from typing import Optional

from .checker.path import Path
from .checker.visitor import CheckerVisitor
from .core import Expectation

_UI_DIR = FsPath(__file__).parent / "ui"
_SNAPSHOT_INTERVAL = 4.0  # seconds between recent-path refreshes


def _error_body(error: str, hint: str) -> dict:
    """The ONE stable machine-readable error shape every JSON endpoint
    returns: tooling keys on ``error``, humans read ``hint``.  The
    ``/.metrics`` telemetry-off body set the precedent; the ``/.runs``
    family reuses it verbatim (no ad-hoc strings — pinned by the schema
    test in tests/test_run_ledger.py)."""
    return {"error": error, "hint": hint}


class _Snapshot(CheckerVisitor):
    """Keeps the most recently visited path, refreshed at most every
    :data:`_SNAPSHOT_INTERVAL` seconds (reference ``explorer.rs:57-84``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._last_update = 0.0
        self.recent_path: Optional[str] = None

    def visit(self, model, path) -> None:
        now = time.monotonic()
        with self._lock:
            if now - self._last_update < _SNAPSHOT_INTERVAL and self.recent_path:
                return
            self._last_update = now
            self.recent_path = (
                "[" + ", ".join(model.format_action(a) for a in path.actions()) + "]"
            )


_EXPECTATION_NAME = {
    Expectation.ALWAYS: "always",
    Expectation.SOMETIMES: "sometimes",
    Expectation.EVENTUALLY: "eventually",
}


def _path_cache(checker) -> dict:
    """Per-checker encoded-path cache, stored ON the checker object so it
    dies with it.  A module-level dict keyed on ``id(checker)`` would go
    stale when CPython reuses the address of a collected checker — a later
    server in the same process could then serve a previous run's path for a
    same-named property."""
    return checker.__dict__.setdefault("_explorer_encoded_cache", {})


def _status_view(model, checker, snapshot: _Snapshot) -> dict:
    # Discoveries are read live, while the check is still running, as in the
    # reference (``explorer.rs:133-157`` reads the live discovery map):
    # BfsChecker's discovery map and parent pointers are safely readable
    # mid-run, so counterexample links appear in the UI as soon as found.
    # Encoded paths are cached per discovery fingerprint — reconstruction
    # re-executes the model along the whole trace, and the UI polls /.status
    # continuously.
    raw = getattr(checker, "_discoveries", None)
    if raw is not None:
        cache = _path_cache(checker)
        encoded = {}
        for name, fp in dict(raw).items():
            key = (name, fp)
            if key not in cache:
                cache[key] = Path.from_fingerprints(
                    model, checker._trace(fp)
                ).encode(model)
            encoded[name] = cache[key]
    elif hasattr(checker, "live_discoveries"):
        # device engines: discovery fps ride the per-sync stats, paths
        # parent-walk a checkpointed table + re-execute the object form.
        # First-wins discovery fps never change, so reconstruction happens
        # once per discovery: cached names are passed as ``skip`` and the
        # engine takes no checkpoint at all when nothing new is recorded.
        cache = _path_cache(checker)
        encoded = {
            name: cache[name]
            for name in (p.name for p in model.properties())
            if name in cache
        }
        fresh = checker.live_discoveries(skip=frozenset(encoded))
        for name, path in fresh.items():
            cache[name] = path.encode(model)
            encoded[name] = cache[name]
    else:  # other strategies: full (joining) reconstruction
        encoded = {
            name: path.encode(model)
            for name, path in checker.discoveries().items()
        }
    props = []
    for prop in model.properties():
        props.append(
            [
                _EXPECTATION_NAME[prop.expectation],
                prop.name,
                encoded.get(prop.name),
            ]
        )
    # Last preflight audit report (stateright_tpu/analysis/): populated by
    # the spawn_tpu preflight or an explicit builder.audit(); None when no
    # audit ran (e.g. the BFS strategy on an un-audited model).  Device
    # runs additionally expose the visited-table bucket-occupancy counters
    # (ops/buckets.occupancy_stats) once the run has results.
    audit = getattr(model, "_audit_report", None)
    table = None
    occ = getattr(checker, "occupancy_stats", None)
    if occ is not None:
        table = occ()
    # soundness-sanitizer verdict (docs/analysis.md JX2xx): the interval
    # pass's site counts + fired rules from the model's last audit, plus
    # whether this run executed under checkify instrumentation
    sanitizer = None
    if audit is not None:
        sanitizer = (audit.metrics or {}).get("sanitizer")
        if sanitizer is not None:
            sanitizer = dict(sanitizer)
            sanitizer["checked_run"] = bool(
                getattr(checker, "_checked", False)
            )
    # partial-order reduction (docs/analysis.md): whether por() is active
    # on this run, the fallback reason when not, and the live
    # reduced-vs-full tallies; None when never requested
    por = None
    por_fn = getattr(checker, "por_status", None)
    if por_fn is not None:
        por = por_fn()
    # independence summary, when a pass was folded into the model's
    # report (independence.fold_into_report) — the audit tiers do not run
    # it (it re-traces every kernel; see analysis/audit.py)
    independence = None
    if audit is not None:
        independence = (audit.metrics or {}).get("independence")
    return {
        "done": checker.is_done(),
        "model": type(model).__name__,
        "state_count": checker.state_count(),
        "unique_state_count": checker.unique_state_count(),
        "properties": props,
        "recent_path": snapshot.recent_path,
        "audit": audit.to_json() if audit is not None else None,
        "sanitizer": sanitizer,
        "por": por,
        "independence": independence,
        "table": table,
    }


def _metrics_view(checker) -> Optional[dict]:
    """``GET /.metrics``: the run's flight-recorder telemetry
    (``stateright_tpu/telemetry/``) — summary + the recent per-step series
    the UI sparklines draw, the live health snapshot
    (``telemetry/health.py``), and the search-cartography block
    (``ops/cartography.py``; null unless the run was spawned with
    ``.telemetry(cartography=True)``).  None (-> the stable
    ``telemetry_disabled`` error body) when the run has no recorder: the
    endpoint never fabricates numbers."""
    rec = getattr(checker, "flight_recorder", None)
    if rec is None:
        return None
    dur_fn = getattr(checker, "durability_status", None)
    steps = rec.records("step")[-120:]
    series: dict = {
        "t": [], "states_per_sec": [], "unique": [], "load_factor": [],
        "dedup": [],
    }
    for r in steps:
        series["t"].append(r["t"])
        dt = r.get("dt") or 0.0
        series["states_per_sec"].append(
            round(r.get("d_states", 0) / dt, 1) if dt > 0 else None
        )
        series["unique"].append(r.get("unique"))
        series["load_factor"].append(r.get("load_factor"))
        series["dedup"].append(r.get("dedup"))
    occ = rec.records("occupancy")
    return {
        "summary": rec.summary(),
        "series": series,
        "occupancy": occ[-1] if occ else None,
        "counters": rec.counters(),
        "health": rec.health(),
        "cartography": rec.cartography(),
        # HBM ledger block (telemetry/memory.py): analytic footprint +
        # growth forecast + live device stats; null unless the run was
        # spawned with .telemetry(memory=True).  The UI's headroom panel
        # reads it.
        "memory": rec.memory(),
        # spill-tier block (stateright_tpu/spill/, docs/spill.md):
        # per-tier bytes, Bloom load, deferral tallies; null unless the
        # run was spawned with .spill()
        "spill": rec.spill(),
        # roofline cost ledger (telemetry/roofline.py, docs/roofline.md):
        # per-stage FLOPs/bytes, op classes, reconciliation verdict,
        # MXU-candidate ranking; null unless the run was spawned with
        # .telemetry(roofline=True).  The UI's stage-roofline panel
        # reads it.
        "roofline": rec.roofline(),
        # durability block (stateright_tpu/checkpoint.py + supervisor.py,
        # docs/robustness.md): autosave cadence/generations/last-
        # checkpoint-age + supervised restart count; null unless the run
        # has autosave armed or a supervision trail.  Read LIVE off the
        # checker (the age ticks between autosaves); the recorder's
        # snapshot is the fallback for replayed recorders.
        "durability": (
            (dur_fn() if callable(dur_fn) else None) or rec.durability()
        ),
        # fleet pool/queue block (stateright_tpu/fleet/, docs/fleet.md):
        # slots, running/queued job keys, completion + preemption
        # tallies; null unless the recorder belongs to a fleet
        # scheduler (the UI's pool panel reads it)
        "fleet": rec.fleet(),
    }


def _runs_view(registry) -> dict:
    """``GET /.runs``: the registry index + per-config trend series
    (``telemetry/registry.py``) — the multi-run dashboard's data."""
    from .telemetry.registry import REGISTRY_V

    records = registry.index()  # one ledger parse serves both views
    return {
        "v": REGISTRY_V,
        "root": registry.root,
        "runs": records,
        "trends": registry.trends(records),
    }


def _runs_diff_view(registry, a_id: str, b_id: str):
    """``GET /.runs/diff/{a}/{b}``: the contract-aware diff of two
    archived runs (``telemetry/diff.py``), with the index headlines
    attached so throughput deltas render too.  Returns ``(code, body)``."""
    from .telemetry.diff import diff_reports

    docs = {}
    for rid in (a_id, b_id):
        doc = registry.find(rid)
        if doc is None:
            return 404, _error_body(
                "unknown_run",
                f"run {rid!r} is not archived in this registry "
                "(GET /.runs lists the known ids)",
            )
        docs[rid] = doc
    records = registry.index()  # one ledger parse for both headlines
    return 200, diff_reports(
        docs[a_id],
        docs[b_id],
        a_headline=registry.headline(a_id, records),
        b_headline=registry.headline(b_id, records),
    )


def _pretty(state) -> str:
    return _indent_repr(repr(state))


def _indent_repr(text: str, max_width: int = 100) -> str:
    """Break a long repr into an indented multi-line form (stands in for
    Rust's ``{:#?}`` pretty debug formatting, ``explorer.rs:47``)."""
    if len(text) <= max_width:
        return text
    out: list[str] = []
    depth = 0
    at_line_start = False
    for ch in text:
        if at_line_start and ch == " ":
            continue  # swallow pre-existing spacing after our line breaks
        at_line_start = False
        if ch in ")]}":
            depth = max(depth - 1, 0)
            out.append("\n" + "  " * depth)
        out.append(ch)
        if ch in "([{":
            depth += 1
            out.append("\n" + "  " * depth)
            at_line_start = True
        elif ch == ",":
            out.append("\n" + "  " * depth)
            at_line_start = True
    return "".join(out)


def _state_views(model, fingerprints: list[int]) -> Optional[list[dict]]:
    """Build the step views for ``/.states``; None means 404."""
    views: list[dict] = []
    if not fingerprints:
        for state in model.init_states():
            fp = model.fingerprint_state(state)
            svg = model.as_svg(Path([(state, None)]))
            view = {"state": _pretty(state), "fingerprint": str(fp)}
            if svg:
                view["svg"] = svg
            views.append(view)
        return views
    try:
        path = Path.from_fingerprints(model, fingerprints)
    except RuntimeError:
        return None
    last_state = path.final_state()
    prefix = path.into_vec()[:-1]  # [(state, action), ...] up to last_state
    for action in model.actions(last_state):
        outcome = model.format_step(last_state, action)
        nxt = model.next_state(last_state, action)
        if nxt is not None:
            fp = model.fingerprint_state(nxt)
            view = {
                "action": model.format_action(action),
                "state": _pretty(nxt),
                "fingerprint": str(fp),
            }
            if outcome is not None:
                view["outcome"] = outcome
            # child path built by appending, not by re-executing from init
            svg = model.as_svg(Path(prefix + [(last_state, action), (nxt, None)]))
            if svg:
                view["svg"] = svg
        else:
            # ignored action: still listed, for debugging (explorer.rs:225)
            view = {"action": model.format_action(action)}
        views.append(view)
    return views


def _make_handler(model, checker, snapshot: _Snapshot, registry=None,
                  progress_root=None):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet by default
            pass

        def _send(self, code: int, body: bytes, ctype: str):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, obj, code: int = 200):
            self._send(code, json.dumps(obj).encode(), "application/json")

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path == "/.status":
                self._send_json(_status_view(model, checker, snapshot))
                return
            if path == "/metrics":
                # Prometheus text exposition (docs/observability.md): the
                # run recorder's attached bus when there is one, else the
                # process-wide default bus (the fleet scheduler and any
                # .telemetry(metrics=True) run publish into it).  An
                # empty exposition is a valid scrape, not an error.
                rec = getattr(checker, "flight_recorder", None)
                bus = getattr(rec, "metrics_bus", None) if rec else None
                if bus is None:
                    from .telemetry.metrics import default_bus

                    bus = default_bus()
                self._send(
                    200, bus.expose().encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
                return
            if path == "/.progress" or path.startswith("/.progress/"):
                # per-job live progress (checkpoint.ProgressHeartbeat):
                # /.progress reads the root heartbeat (a fleet pool, or
                # a standalone autosaved run); /.progress/<job> reads
                # <root>/jobs/<job>/progress.json
                if progress_root is None:
                    self._send_json(
                        _error_body(
                            "progress_disabled",
                            "serve with progress_root=DIR (a fleet "
                            "root or an autosave dir) to enable the "
                            "live-progress endpoints",
                        ),
                        404,
                    )
                    return
                from .checkpoint import read_progress

                job = path[len("/.progress"):].strip("/")
                if not job:
                    doc = read_progress(progress_root)
                else:
                    import os as _os

                    if "/" in job or ".." in job:
                        self._send_json(
                            _error_body(
                                "bad_job_key",
                                "use /.progress/<job-slug> (one path "
                                "segment)",
                            ),
                            404,
                        )
                        return
                    doc = read_progress(
                        _os.path.join(progress_root, "jobs", job)
                    )
                if doc is None:
                    self._send_json(
                        _error_body(
                            "no_heartbeat",
                            "no progress.json here yet — the run has "
                            "not reached its first host sync, or the "
                            "job key is unknown",
                        ),
                        404,
                    )
                    return
                self._send_json(doc)
                return
            if path == "/.metrics":
                view = _metrics_view(checker)
                if view is None:
                    # STABLE machine-readable body (_error_body):
                    # telemetry off is an expected state, not a routing
                    # failure — downstream pollers must be able to
                    # distinguish it from a typo'd URL without parsing
                    # prose
                    self._send_json(
                        _error_body(
                            "telemetry_disabled",
                            "spawn the run with .telemetry() "
                            "(add cartography=True for the search "
                            "counters) to enable /.metrics",
                        ),
                        404,
                    )
                    return
                self._send_json(view)
                return
            if path == "/.runs" or path.startswith("/.runs/"):
                if registry is None:
                    # same stable shape as telemetry_disabled: a server
                    # without a registry is an expected state
                    self._send_json(
                        _error_body(
                            "registry_disabled",
                            "serve with runs_dir=DIR (or set "
                            "STATERIGHT_TPU_RUN_DIR) to enable the "
                            "multi-run endpoints",
                        ),
                        404,
                    )
                    return
                rest = path[len("/.runs"):].strip("/")
                if not rest:
                    self._send_json(_runs_view(registry))
                    return
                parts = rest.split("/")
                if parts[0] == "diff":
                    if len(parts) != 3:
                        self._send_json(
                            _error_body(
                                "bad_diff_request",
                                "use /.runs/diff/{run_id_a}/{run_id_b}",
                            ),
                            404,
                        )
                        return
                    code, body = _runs_diff_view(
                        registry, parts[1], parts[2]
                    )
                    self._send_json(body, code)
                    return
                doc = registry.find(parts[0])
                if doc is None:
                    self._send_json(
                        _error_body(
                            "unknown_run",
                            f"run {parts[0]!r} is not archived in this "
                            "registry (GET /.runs lists the known ids)",
                        ),
                        404,
                    )
                    return
                self._send_json(doc)
                return
            if path == "/.states" or path.startswith("/.states/"):
                raw = path[len("/.states") :].strip("/")
                fps: list[int] = []
                if raw:
                    for part in raw.split("/"):
                        try:
                            fps.append(int(part))
                        except ValueError:
                            self._send_json(
                                {"error": f"Unable to parse fingerprints {raw}"},
                                404,
                            )
                            return
                views = _state_views(model, fps)
                if views is None:
                    self._send_json(
                        {
                            "error": "Unable to find state following "
                            f"fingerprints {raw}"
                        },
                        404,
                    )
                    return
                self._send_json(views)
                return
            # static UI
            name = {
                "/": "index.html",
                "/app.js": "app.js",
                "/app.css": "app.css",
            }.get(path)
            if name is None:
                self._send(404, b"not found", "text/plain")
                return
            f = _UI_DIR / name
            ctype = {
                "index.html": "text/html",
                "app.js": "application/javascript",
                "app.css": "text/css",
            }[name]
            self._send(200, f.read_bytes(), ctype)

    return Handler


class ExplorerServer:
    """A running Explorer; ``addr`` like ``"localhost:3000"``.

    ``strategy`` — ``"bfs"`` (default; reference parity: the reference
    Explorer wraps only ``BfsChecker``, ``explorer.rs:85-88``) or ``"tpu"``:
    the device wavefront engine, with live ``/.status`` counters and
    discovery paths reconstructed by parent-walk + object-form re-execution
    (``/.states`` re-executes the object model either way, so browsing is
    identical)."""

    def __init__(
        self,
        builder,
        addr: str = "localhost:3000",
        strategy: str = "bfs",
        runs_dir: Optional[str] = None,
        progress_root: Optional[str] = None,
        **spawn_kw,
    ):
        host, _, port = addr.partition(":")
        self.snapshot = _Snapshot()
        # persistent run registry (telemetry/registry.py): the multi-run
        # dashboard's data source — explicit runs_dir wins, else the
        # builder's .runs(DIR), else STATERIGHT_TPU_RUN_DIR; absent =
        # the /.runs endpoints answer registry_disabled
        from .telemetry.registry import RunRegistry, resolve_run_dir

        root = resolve_run_dir(
            runs_dir or getattr(builder, "run_dir", None)
        )
        self.registry = RunRegistry(root) if root else None
        if strategy == "tpu":
            # no per-state visitor on device (states never materialize);
            # recent_path stays empty, the counters are live
            self.checker = builder.spawn_tpu(**spawn_kw)
        elif strategy == "bfs":
            if spawn_kw:
                raise TypeError(
                    "spawn keyword arguments are only supported with "
                    f"strategy='tpu' (got {sorted(spawn_kw)})"
                )
            self.checker = builder.visitor(self.snapshot).spawn_bfs()
        else:
            raise ValueError(f"unknown Explorer strategy {strategy!r}")
        self.model = builder.model
        # live-progress root (docs/observability.md): explicit wins,
        # else the builder's autosave dir (the heartbeat lives next to
        # the generations); absent = /.progress answers
        # progress_disabled
        if progress_root is None:
            aopts = getattr(builder, "autosave_opts", None)
            if aopts and aopts.get("dir"):
                progress_root = str(aopts["dir"])
        self.progress_root = progress_root
        handler = _make_handler(
            self.model, self.checker, self.snapshot,
            registry=self.registry, progress_root=progress_root,
        )
        self.httpd = ThreadingHTTPServer((host, int(port or "3000")), handler)
        self.addr = f"{self.httpd.server_address[0]}:{self.httpd.server_address[1]}"

    def serve_forever(self):
        print(f"Exploring state space at http://{self.addr}")
        self.httpd.serve_forever()

    def start_background(self) -> "ExplorerServer":
        t = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        t.start()
        return self

    def shutdown(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def serve(
    builder,
    addr: str = "localhost:3000",
    block: bool = True,
    strategy: str = "bfs",
    runs_dir: Optional[str] = None,
    progress_root: Optional[str] = None,
    **spawn_kw,
):
    """Spawn a check over ``builder`` and serve the Explorer UI
    (reference ``checker.rs:108-114``).  ``strategy="tpu"`` serves a device
    wavefront run instead of host BFS; with it, extra keyword arguments pass
    through to ``spawn_tpu`` (e.g. ``batch=...``).  ``runs_dir`` (or
    ``STATERIGHT_TPU_RUN_DIR`` / a builder ``.runs(DIR)``) arms the
    multi-run dashboard: ``/.runs`` endpoints + run list / two-run diff /
    trend panels over the persistent run registry."""
    server = ExplorerServer(
        builder, addr, strategy=strategy, runs_dir=runs_dir,
        progress_root=progress_root, **spawn_kw,
    )
    if block:
        server.serve_forever()
        return server
    return server.start_background()
