"""Unreplicated single-copy register (reference
``examples/single-copy-register.rs``): each server exposes its own register
with no consensus.  One server is linearizable; two servers are not — the
checker finds the violating trace, demonstrating counterexample discovery
through the linearizability tester.

Pinned counts (reference ``single-copy-register.rs:100,121``): 93 unique
states @ 2 clients / 1 server; 20 @ 2 clients / 2 servers (violation found
early).
"""

from __future__ import annotations

from typing import Optional

from .. import Expectation
from ..actor import Actor, ActorModel, Id, Network, Out
from ..actor.register import (
    NULL_VALUE,
    GetOk,
    PutOk,
    RegisterClient,
    record_invocations,
    record_returns,
    value_chosen,
)
from ..parallel.tensor_model import TensorBackedModel
from ..semantics import LinearizabilityTester, Register
from ._cli import (
    apply_encoding,
    apply_perf,
    default_threads,
    make_audit_cmd,
    make_profile_cmd,
    make_capacity_cmd,
    make_compare_cmd,
    make_costmodel_cmd,
    make_report_cmd,
    make_independence_cmd,
    make_sanitize_cmd,
    pop_checked,
    pop_perf,
    pop_watch,
    run_cli,
    spawn_watched,
)


class SingleCopyServer(Actor):
    """State is just the stored value (reference
    ``single-copy-register.rs:16-37``)."""

    def on_start(self, id: Id, out: Out):
        return NULL_VALUE

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        kind = msg[0]
        if kind == "put":
            out.send(src, PutOk(msg[1]))
            return msg[2]
        if kind == "get":
            out.send(src, GetOk(msg[1], state))
            return state
        return None


class SingleCopyModel(TensorBackedModel, ActorModel):
    """ActorModel with a mechanically compiled device twin; single-copy
    server state is just the stored value, so no closure bounds are needed."""

    def tensor_model(self):
        from ..parallel.actor_compiler import CompileError, compile_actor_model

        try:
            return compile_actor_model(self)
        except (CompileError, ValueError):
            return None


def single_copy_model(
    client_count: int,
    server_count: int = 1,
    network: Optional[Network] = None,
    put_count: int = 1,
) -> ActorModel:
    if network is None:
        network = Network.new_unordered_nonduplicating()
    m = SingleCopyModel(
        cfg=None, init_history=LinearizabilityTester(Register(NULL_VALUE))
    )
    for _ in range(server_count):
        m.actor(SingleCopyServer())
    for _ in range(client_count):
        m.actor(RegisterClient(put_count=put_count, server_count=server_count))
    m.init_network_(network)
    m.property(
        Expectation.ALWAYS,
        "linearizable",
        lambda model, s: s.history.is_consistent(),
    )
    m.property(Expectation.SOMETIMES, "value chosen", value_chosen)
    m.record_msg_in(record_returns)
    m.record_msg_out(record_invocations)
    return m


def _audit_models(rest=()):
    """Default configurations for the static auditor (``audit`` verb and
    the fleet runner, ``_cli.fleet_audit``)."""
    c = int(rest[0]) if rest else 1
    return [(f"single_copy_register clients={c}", single_copy_model(c))]


def main(argv=None):
    def check(rest):
        client_count = int(rest[0]) if rest else 2
        network = (
            Network.from_name(rest[1])
            if len(rest) > 1
            else Network.new_unordered_nonduplicating()
        )
        print(f"Model checking a single-copy register with {client_count} clients.")
        single_copy_model(client_count, 1, network).checker().threads(
            default_threads()
        ).spawn_dfs().report()

    def check_tpu(rest):
        checked, rest = pop_checked(rest)
        perf, rest = pop_perf(rest)
        watch, rest = pop_watch(rest)
        client_count = int(rest[0]) if rest else 2
        network = (
            Network.from_name(rest[1])
            if len(rest) > 1
            else Network.new_unordered_nonduplicating()
        )
        print(
            f"Model checking a single-copy register with {client_count} "
            "clients on the device wavefront engine."
        )
        m = apply_encoding(single_copy_model(client_count, 1, network), perf)
        if m.tensor_model() is None:
            print("this configuration has no device twin; use `check` (CPU)")
            return
        spawn_watched(
            apply_perf(m.checker().checked(checked), perf), watch,
            lambda b: b.spawn_tpu(),
        ).report()

    def check_auto(rest):
        client_count = int(rest[0]) if rest else 2
        network = (
            Network.from_name(rest[1])
            if len(rest) > 1
            else Network.new_unordered_nonduplicating()
        )
        print(
            f"Model checking a single-copy register with {client_count} "
            "clients (auto engine selection)."
        )
        single_copy_model(client_count, 1, network).checker().threads(
            default_threads()
        ).spawn_auto().report()

    def explore(rest):
        client_count = int(rest[0]) if rest else 2
        addr = rest[1] if len(rest) > 1 else "localhost:3000"
        single_copy_model(client_count, 1).checker().serve(addr)

    def spawn_cmd(rest):
        from ..actor import spawn

        id = Id.from_addr("127.0.0.1", 3000)
        print(f"  Server listening on {id.to_addr()}")
        spawn([(id, SingleCopyServer())], background=False)

    run_cli(
        "  single_copy_register check [CLIENT_COUNT] [NETWORK]\n"
        "  single_copy_register check-tpu [CLIENT_COUNT] [NETWORK]\n"
        "  single_copy_register check-auto [CLIENT_COUNT] [NETWORK]\n"
        "  single_copy_register explore [CLIENT_COUNT] [ADDRESS]\n"
        "  single_copy_register spawn",
        check,
        check_tpu=check_tpu,
        check_auto=check_auto,
        explore=explore,
        spawn=spawn_cmd,
        audit=make_audit_cmd(_audit_models),
        sanitize=make_sanitize_cmd(_audit_models),
        independence=make_independence_cmd(_audit_models),
        profile=make_profile_cmd(_audit_models),
        report=make_report_cmd(_audit_models),
        capacity=make_capacity_cmd(_audit_models),
        costmodel=make_costmodel_cmd(_audit_models),
        compare=make_compare_cmd(),
        argv=argv,
    )


if __name__ == "__main__":
    main()
