"""Raft leader election, model-checked on host and device.

Beyond the reference's example set (it ships no Raft): this model
demonstrates the actor compiler's *general* fragment — timeout-driven
actors with no auxiliary history, checked against factored properties
(``actor/device_props.py``) — compiling mechanically to a TPU twin with
zero hand-written device code.

The protocol is the election core of Raft (Ongaro & Ousterhout §5.2):
followers time out and become candidates, candidates solicit votes for a
fresh term, a majority elects a leader.  Terms are bounded by
``max_term`` so the space is finite: a server whose election timer fires
at the cap simply stops campaigning (its timer clears and is never
re-armed — the reference's timeout semantics make that a real
transition, not a pruned no-op).

Checked properties:

 - **election safety** (always): at most one leader per term — the
   Figure 3 safety property, as a ``forall_actor_pairs`` predicate;
 - **liveness witness** (sometimes): some execution elects a leader.

CLI: ``python -m stateright_tpu.models.raft check [n] [network]``,
``check-tpu``, ``explore`` — like the reference's example binaries
(``examples/paxos.rs:314-395``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..actor import Actor, ActorModel, Id, Network, Out, majority, model_peers
from ..actor.device_props import exists_actor, forall_actor_pairs
from ..core import Expectation
from ..parallel.tensor_model import TensorBackedModel
from ._cli import (
    apply_encoding,
    apply_perf,
    default_threads,
    make_audit_cmd,
    make_profile_cmd,
    make_capacity_cmd,
    make_compare_cmd,
    make_costmodel_cmd,
    make_report_cmd,
    make_independence_cmd,
    make_sanitize_cmd,
    pop_checked,
    pop_perf,
    pop_watch,
    run_cli,
    spawn_watched,
)

FOLLOWER, CANDIDATE, LEADER = 0, 1, 2


@dataclass(frozen=True)
class RaftState:
    role: int = FOLLOWER
    term: int = 0
    #: candidate Id this server voted for in `term` (-1: none).  Stored as
    #: Id (not int) so symmetry reduction rewrites them under actor
    #: permutations, on host and in the compiled twin's tables alike
    voted_for: int = -1
    #: granter Ids (candidates only); a frozenset rather than a bitmask so
    #: runtime sockaddr ids (~2^47) work as well as dense model ids
    votes: frozenset = frozenset()


class RaftServer(Actor):
    """Election-only Raft server.

    Messages: ``("req_vote", term)`` solicits, ``("grant", term)``
    grants.  A server votes at most once per term; a candidate counting a
    majority becomes leader and stops campaigning.
    """

    def __init__(
        self,
        peers: list[Id],
        cluster: int,
        max_term: int,
        timer_range=(0.0, 0.0),
    ):
        self.peers = peers
        self.cluster = cluster
        self.max_term = max_term
        # model checking ignores durations (any set timer may fire); a real
        # deployment passes Raft's randomized election timeout here
        self.timer_range = timer_range

    def on_start(self, id: Id, out: Out):
        out.set_timer(self.timer_range)  # election timer
        return RaftState()

    def on_timeout(self, id: Id, state: RaftState, out: Out):
        if state.role == LEADER or state.term >= self.max_term:
            return None  # stop campaigning (timer stays cleared)
        term = state.term + 1
        out.broadcast(self.peers, ("req_vote", term))
        out.set_timer(self.timer_range)  # elections may time out and retry
        return RaftState(
            role=CANDIDATE,
            term=term,
            voted_for=Id(id),
            votes=frozenset((Id(id),)),
        )

    def on_msg(self, id: Id, state: RaftState, src: Id, msg, out: Out):
        kind, term = msg
        if kind == "req_vote":
            if term > state.term:
                # newer term: step down and grant
                out.send(src, ("grant", term))
                return RaftState(term=term, voted_for=Id(src))
            if (
                term == state.term
                and state.role == FOLLOWER
                and state.voted_for in (-1, int(src))
            ):
                out.send(src, ("grant", term))
                if state.voted_for == int(src):
                    return None  # duplicate request, vote already recorded
                return RaftState(term=term, voted_for=Id(src))
            return None  # stale or already voted: ignore
        if kind == "grant":
            if state.role != CANDIDATE or term != state.term:
                return None  # stale grant
            if int(src) in state.votes:
                return None  # duplicate grant
            votes = state.votes | {Id(src)}
            role = (
                LEADER
                if len(votes) >= majority(self.cluster)
                else CANDIDATE
            )
            return RaftState(
                role=role,
                term=state.term,
                voted_for=state.voted_for,
                votes=votes,
            )
        return None


class RaftModel(TensorBackedModel, ActorModel):
    """ActorModel with a mechanically compiled device twin (general
    fragment: timers + factored properties, no history)."""

    max_term = 2

    def tensor_model(self):
        from ..parallel.actor_compiler import CompileError, compile_actor_model

        try:
            return compile_actor_model(
                self,
                # cut the closure's over-approximation at the term cap
                # (reachable states never cross it; poison pins that)
                state_bound=lambda i, s: s.term <= self.max_term,
                env_bound=lambda e: e.msg[1] <= self.max_term,
            )
        except (CompileError, ValueError):
            return None


def raft_model(
    server_count: int = 3,
    max_term: int = 2,
    network: Optional[Network] = None,
) -> ActorModel:
    """Election-safety model: ``server_count`` servers, terms bounded by
    ``max_term``."""
    if network is None:
        network = Network.new_unordered_nonduplicating()
    m = RaftModel(cfg=None, init_history=None)
    m.max_term = max_term
    for i in range(server_count):
        m.actor(
            RaftServer(
                peers=model_peers(i, server_count),
                cluster=server_count,
                max_term=max_term,
            )
        )
    m.init_network_(network)
    m.property(
        Expectation.ALWAYS,
        "election safety",
        forall_actor_pairs(
            lambda i, si, j, sj: not (
                si.role == LEADER and sj.role == LEADER and si.term == sj.term
            )
        ),
    )
    m.property(
        Expectation.SOMETIMES,
        "a leader is elected",
        exists_actor(lambda i, s: s.role == LEADER),
    )
    return m


# Sharded-engine symmetry-reduced unique counts for raft_model(3), pinned
# EXACTLY per mesh width (the schedule is deterministic for a fixed
# width; representative-based reduction is visit-order-sensitive, so the
# numbers differ per width).  Width 1 equals the host FIFO oracle
# (tests/test_tensor_models.py::host_fifo_sym_oracle).  Measured round 5;
# re-measure when the canonicalizer or routing changes — this table is
# the single source for tests/test_raft.py AND __graft_entry__.py's
# multichip dryrun gate.
RAFT3_SYM_SHARDED_BY_WIDTH = {1: 2926, 2: 2960, 4: 3010, 8: 3015}


def _audit_models(rest=()):
    """Default configurations for the static auditor (``audit`` verb and
    the fleet runner, ``_cli.fleet_audit``)."""
    n = int(rest[0]) if rest else 3
    return [(f"raft servers={n} max_term=2", raft_model(n))]


def main(argv=None) -> None:
    def parse(rest):
        n = int(rest[0]) if rest else 3
        network = (
            Network.from_name(rest[1])
            if len(rest) > 1
            else Network.new_unordered_nonduplicating()
        )
        return n, network

    def check(rest):
        n, network = parse(rest)
        print(f"Model checking Raft leader election with {n} servers.")
        raft_model(n, network=network).checker().threads(
            default_threads()
        ).spawn_bfs().report()

    def check_sym(rest):
        n, network = parse(rest)
        print(
            f"Model checking Raft leader election with {n} servers "
            "(symmetry-reduced DFS)."
        )
        raft_model(n, network=network).checker().symmetry().threads(
            default_threads()
        ).spawn_dfs().report()

    def check_sym_tpu(rest):
        checked, rest = pop_checked(rest)
        perf, rest = pop_perf(rest)
        watch, rest = pop_watch(rest)
        n, network = parse(rest)
        print(
            f"Model checking Raft leader election with {n} servers on the "
            "device wavefront engine (mechanical symmetry reduction)."
        )
        m = apply_encoding(raft_model(n, network=network), perf)
        if m.tensor_model() is None:
            print("this configuration has no device twin; use `check-sym`")
            return
        spawn_watched(
            apply_perf(m.checker().checked(checked).symmetry(), perf),
            watch, lambda b: b.spawn_tpu(),
        ).report()

    def check_tpu(rest):
        checked, rest = pop_checked(rest)
        perf, rest = pop_perf(rest)
        watch, rest = pop_watch(rest)
        n, network = parse(rest)
        print(
            f"Model checking Raft leader election with {n} servers on the "
            "device wavefront engine."
        )
        m = apply_encoding(raft_model(n, network=network), perf)
        if m.tensor_model() is None:
            print("this configuration has no device twin; use `check` (CPU)")
            return
        spawn_watched(
            apply_perf(m.checker().checked(checked), perf), watch,
            lambda b: b.spawn_tpu(),
        ).report()

    def check_auto(rest):
        n, network = parse(rest)
        print(
            f"Model checking Raft leader election with {n} servers "
            "(auto engine selection)."
        )
        raft_model(n, network=network).checker().threads(
            default_threads()
        ).spawn_auto().report()

    def explore(rest):
        n = int(rest[0]) if rest else 3
        addr = rest[1] if len(rest) > 1 else "localhost:3000"
        raft_model(n).checker().serve(addr)

    def spawn_cmd(rest):
        from ..actor.spawn import spawn

        n = int(rest[0]) if rest else 3
        base = int(rest[1]) if len(rest) > 1 else 3000
        ids = [Id.from_addr("127.0.0.1", base + i) for i in range(n)]
        print(f"Spawning a {n}-server Raft cluster on 127.0.0.1:"
              f"{base}..{base + n - 1} (ctrl-c to stop)")
        spawn(
            [
                (
                    ids[i],
                    RaftServer(
                        peers=[x for x in ids if x != ids[i]],
                        cluster=n,
                        max_term=1 << 20,
                        timer_range=(0.15, 0.5),
                    ),
                )
                for i in range(n)
            ],
            background=False,
        )

    run_cli(
        "raft [SERVER_COUNT] [NETWORK]",
        check,
        check_sym=check_sym,
        check_tpu=check_tpu,
        check_sym_tpu=check_sym_tpu,
        check_auto=check_auto,
        explore=explore,
        spawn=spawn_cmd,
        audit=make_audit_cmd(_audit_models),
        sanitize=make_sanitize_cmd(_audit_models),
        independence=make_independence_cmd(_audit_models),
        profile=make_profile_cmd(_audit_models),
        report=make_report_cmd(_audit_models),
        capacity=make_capacity_cmd(_audit_models),
        costmodel=make_costmodel_cmd(_audit_models),
        compare=make_compare_cmd(),
        argv=argv,
    )


if __name__ == "__main__":
    main()
