"""Dining philosophers — deadlock detection on the device engines.

Beyond the reference's example set: the classic circular-wait deadlock,
found by the checker as an ``eventually``-property counterexample whose
trace ends in the deadlocked terminal state (every philosopher holding
their left fork, each waiting on the right).  Philosophers and forks are
plain Python actors; the general compiler fragment gives them a device
twin, so the deadlock hunt runs on the TPU wavefront engines too.

System: ``n`` philosophers (actors ``0..n-1``) and ``n`` forks (actors
``n..2n-1``).  Philosopher ``i`` uses forks ``n+i`` (left) and
``n+(i+1)%n`` (right), acquires left-then-right, eats once, releases
both.  Forks grant FIFO-free (lowest pending id first) — determinism the
checker needs, not fairness the protocol needs.

CLI: ``python -m stateright_tpu.models.dining check [n]``, ``check-tpu``,
``explore``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..actor import Actor, ActorModel, Id, Network, Out
from ..actor.device_props import exists_actor, forall_actors
from ..core import Expectation
from ..parallel.tensor_model import TensorBackedModel
from ._cli import (
    apply_encoding,
    apply_perf,
    default_threads,
    make_audit_cmd,
    make_profile_cmd,
    make_capacity_cmd,
    make_compare_cmd,
    make_costmodel_cmd,
    make_report_cmd,
    make_independence_cmd,
    make_sanitize_cmd,
    pop_checked,
    pop_perf,
    pop_watch,
    run_cli,
    spawn_watched,
)

HUNGRY, HAS_LEFT, DONE = 0, 1, 2


@dataclass(frozen=True)
class PhilosopherState:
    phase: int = HUNGRY


@dataclass(frozen=True)
class ForkState:
    #: Id of the current holder, or -1
    holder: int = -1
    #: Ids waiting for the fork
    pending: frozenset = frozenset()


class Philosopher(Actor):
    def __init__(self, left: Id, right: Id):
        self.left = left
        self.right = right

    def on_start(self, id: Id, out: Out):
        out.send(self.left, ("acquire",))
        return PhilosopherState(HUNGRY)

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        if msg[0] != "granted":
            return None
        if state.phase == HUNGRY:
            out.send(self.right, ("acquire",))
            return PhilosopherState(HAS_LEFT)
        if state.phase == HAS_LEFT:
            # both forks held: eat, then release both
            out.send(self.left, ("release",))
            out.send(self.right, ("release",))
            return PhilosopherState(DONE)
        return None


class Fork(Actor):
    def on_start(self, id: Id, out: Out):
        return ForkState()

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        if msg[0] == "acquire":
            if state.holder == -1:
                out.send(src, ("granted",))
                return ForkState(holder=Id(src), pending=state.pending)
            return ForkState(
                holder=state.holder, pending=state.pending | {Id(src)}
            )
        if msg[0] == "release":
            if state.pending:
                nxt = Id(min(state.pending))
                out.send(nxt, ("granted",))
                return ForkState(
                    holder=nxt, pending=state.pending - {nxt}
                )
            return ForkState()
        return None


def dining_model(n: int = 3, network: Optional[Network] = None) -> ActorModel:
    """``n`` philosophers, ``n`` forks; the famous deadlock is reachable
    (and discovered) for every ``n >= 2``."""
    if network is None:
        network = Network.new_unordered_nonduplicating()

    class DiningModel(TensorBackedModel, ActorModel):
        def tensor_model(self):
            from ..parallel.actor_compiler import (
                CompileError,
                compile_actor_model,
            )

            try:
                return compile_actor_model(self)
            except (CompileError, ValueError):
                return None

    m = DiningModel(cfg=None, init_history=None)
    for i in range(n):
        m.actor(Philosopher(left=Id(n + i), right=Id(n + (i + 1) % n)))
    for _ in range(n):
        m.actor(Fork())
    m.init_network_(network)
    phil = lambda i: i < n  # noqa: E731 - actors 0..n-1 are philosophers
    m.property(
        Expectation.EVENTUALLY,
        "everyone eats",
        forall_actors(lambda i, s: not phil(i) or s.phase == DONE),
    )
    m.property(
        Expectation.SOMETIMES,
        "someone eats",
        exists_actor(lambda i, s: phil(i) and s.phase == DONE),
    )
    return m


def _audit_models(rest=()):
    """Default configurations for the static auditor (``audit`` verb and
    the fleet runner, ``_cli.fleet_audit``)."""
    n = int(rest[0]) if rest else 3
    return [(f"dining n={n}", dining_model(n))]


def main(argv=None) -> None:
    def parse(rest):
        return int(rest[0]) if rest else 3

    def check(rest):
        n = parse(rest)
        print(f"Model checking {n} dining philosophers.")
        c = (
            dining_model(n)
            .checker()
            .threads(default_threads())
            .spawn_bfs()
            .report()
        )
        trace = c.discovery("everyone eats")
        if trace is not None:
            print(f"deadlock after {len(trace.actions())} steps:")
            print(trace)

    def check_tpu(rest):
        checked, rest = pop_checked(rest)
        perf, rest = pop_perf(rest)
        watch, rest = pop_watch(rest)
        n = parse(rest)
        print(
            f"Model checking {n} dining philosophers on the device "
            "wavefront engine."
        )
        m = apply_encoding(dining_model(n), perf)
        if m.tensor_model() is None:
            print("this configuration has no device twin; use `check` (CPU)")
            return
        spawn_watched(
            apply_perf(m.checker().checked(checked), perf), watch,
            lambda b: b.spawn_tpu(),
        ).report()

    def check_auto(rest):
        n = parse(rest)
        print(f"Model checking {n} dining philosophers (auto engine).")
        dining_model(n).checker().threads(
            default_threads()
        ).spawn_auto().report()

    def explore(rest):
        n = parse(rest)
        addr = rest[1] if len(rest) > 1 else "localhost:3000"
        dining_model(n).checker().serve(addr)

    run_cli(
        "dining [PHILOSOPHER_COUNT]",
        check,
        check_tpu=check_tpu,
        check_auto=check_auto,
        explore=explore,
        audit=make_audit_cmd(_audit_models),
        sanitize=make_sanitize_cmd(_audit_models),
        independence=make_independence_cmd(_audit_models),
        profile=make_profile_cmd(_audit_models),
        report=make_report_cmd(_audit_models),
        capacity=make_capacity_cmd(_audit_models),
        costmodel=make_costmodel_cmd(_audit_models),
        compare=make_compare_cmd(),
        argv=argv,
    )


if __name__ == "__main__":
    main()
