"""Example systems (reference L8, ``examples/*.rs``) — the benchmark and
validation workloads.  Each module exposes a model builder and a CLI
(``python -m stateright_tpu.models.<name> check ...``) matching the
reference's argument shapes (e.g. ``examples/paxos.rs:314-395``).

| module | system | pinned unique states |
|---|---|---|
| two_phase_commit | abstract 2PC (Gray/Lamport TLA model) | 288 @ 3 RMs; 8,832 @ 5; 665 @ 5 w/ symmetry |
| paxos | single-decree Paxos + linearizability | 16,668 @ 2 clients / 3 servers |
| linearizable_register | ABD quorum register | 544 @ 2 clients / 2 servers |
| single_copy_register | unreplicated register (violation demo) | 93 @ 1 server; 20 @ 2 servers |
| increment | racy shared counter | 13 / 8 with symmetry (2 threads) |
| increment_lock | counter with lock | mutex + fin hold |
| raft | Raft leader election (beyond the reference; compiled general fragment) | 5,725 @ 3 servers / 2 terms |
| dining | dining philosophers; deadlock found as a liveness counterexample | 359 @ 3 (full space) |
| quickstart | sliding puzzle, Lamport + vector clocks | doctest-scale |
"""

__all__ = [
    "two_phase_commit",
    "paxos",
    "linearizable_register",
    "single_copy_register",
    "increment",
    "increment_lock",
    "raft",
    "dining",
    "quickstart",
]
