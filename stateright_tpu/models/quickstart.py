"""Executable quick-start examples — the package's "front page".

The reference teaches its API through three doctest-sized specs: a sliding
puzzle solved by the checker (``src/lib.rs:40-116``), Lamport logical clocks
as a two-actor system (``src/actor.rs:11-78``), and a served toy model
(``src/checker.rs:60-97``).  These are this package's equivalents, written
as runnable functions (``python -m stateright_tpu.models.quickstart``) and
executed by ``tests/test_quickstart.py`` so they double as specs here too.
"""

from __future__ import annotations

from dataclasses import dataclass

from .. import Expectation, Property
from ..actor import Actor, ActorModel, Id, Network, Out
from ..core import Model
from ..utils.vector_clock import VectorClock

GOAL = (0, 1, 2, 3, 4, 5, 6, 7, 8)


class SlidingPuzzle(Model):
    """3×3 sliding puzzle: find a solve sequence with the BFS checker.

    The *sometimes* property turns the checker into a solver: the discovery
    trace for "solved" is a shortest move sequence (BFS order), exactly the
    reference's front-page example (``src/lib.rs:40-116``).
    """

    def __init__(self, start=(1, 4, 2, 3, 5, 8, 6, 7, 0)):
        super().__init__()
        self.start = tuple(start)

    def init_states(self):
        return [self.start]

    def actions(self, state):
        return ["down", "up", "right", "left"]

    def next_state(self, state, action):
        empty = state.index(0)
        ey, ex = divmod(empty, 3)
        src = {
            "down": empty - 3 if ey > 0 else None,   # tile above slides down
            "up": empty + 3 if ey < 2 else None,     # tile below slides up
            "right": empty - 1 if ex > 0 else None,  # tile left slides right
            "left": empty + 1 if ex < 2 else None,   # tile right slides left
        }[action]
        if src is None:
            return None
        board = list(state)
        board[empty], board[src] = board[src], 0
        return tuple(board)

    def properties(self):
        return [Property.sometimes("solved", lambda m, s: s == GOAL)]


class LogicalClock(Actor):
    """Lamport-clock actor: each message carries a timestamp; receivers
    advance past it and reply (``src/actor.rs:11-78`` behavior parity —
    the checker finds how large the clocks can grow)."""

    def __init__(self, bootstrap_to: Id | None = None):
        self.bootstrap_to = bootstrap_to

    def on_start(self, id: Id, out: Out):
        if self.bootstrap_to is not None:
            out.send(self.bootstrap_to, 1)
            return 1
        return 0

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        if msg > state:
            out.send(src, msg + 1)
            return msg + 1
        return None


def solve_puzzle():
    """Returns the shortest solve trace for the default puzzle."""
    checker = SlidingPuzzle().checker().spawn_bfs().join()
    checker.assert_properties()
    return checker.discovery("solved")


def clock_model(limit: int = 3) -> ActorModel:
    m = ActorModel(cfg=None)
    m.actor(LogicalClock())
    m.actor(LogicalClock(bootstrap_to=Id(0)))
    m.property(
        Expectation.ALWAYS,
        "less than max",
        lambda model, s: all(ts < limit for ts in s.actor_states),
    )
    return m


def clock_counterexample(limit: int = 3):
    """Returns the trace on which a clock first reaches ``limit``."""
    checker = clock_model(limit).checker().spawn_bfs().join()
    return checker.discovery("less than max")


# -- the served toy model (reference ``checker.rs:60-97``) --------------------


class FizzBuzz(Model):
    """The reference's ``serve`` doctest model: states are the emitted
    prefix of the fizz-buzz sequence, bounded by ``max``; serving it gives
    a browsable state space (``FizzBuzz(30).checker().serve(addr)``)."""

    def __init__(self, max: int = 30):
        super().__init__()
        self.max = max

    def init_states(self):
        return [()]

    def actions(self, state):
        n = len(state)
        if n % 15 == 0:
            return ["fizzbuzz"]
        if n % 5 == 0:
            return ["buzz"]
        if n % 3 == 0:
            return ["fizz"]
        return [None]

    def next_state(self, state, action):
        return state + ((len(state), action),)

    def within_boundary(self, state) -> bool:
        return len(state) <= self.max

    def properties(self):
        return [
            Property.sometimes(
                "reaches the bound", lambda m, s: len(s) == m.max
            )
        ]


def serve_fizzbuzz(addr: str = "localhost:3000", block: bool = True):
    """``FizzBuzz(30).checker().serve(addr)`` — the reference's front-page
    Explorer example (``checker.rs:60-97``)."""
    return FizzBuzz(30).checker().serve(addr, block=block)


# -- vector clocks: detecting concurrency -------------------------------------


@dataclass(frozen=True)
class ObserverState:
    """The observer's merged clock plus whether any delivery was causally
    concurrent with what it had already seen."""

    clock: VectorClock
    saw_concurrent: bool = False


class StampedSender(Actor):
    """Emits a single event stamped with its vector clock
    (``VectorClock.incremented``, reference ``vector_clock.rs:34-40``)."""

    def __init__(self, observer: Id):
        self.observer = observer

    def on_start(self, id: Id, out: Out):
        clock = VectorClock().incremented(int(id))
        out.send(self.observer, clock)
        return clock


class ClockObserver(Actor):
    """Merges incoming clocks (``merge_max``) and flags deliveries that are
    incomparable with its current knowledge (``partial_cmp`` → ``None``),
    i.e. causally concurrent events."""

    def on_start(self, id: Id, out: Out):
        return ObserverState(VectorClock())

    def on_msg(self, id: Id, state: ObserverState, src: Id, msg, out: Out):
        concurrent = msg.partial_cmp(state.clock) is None
        merged = state.clock.merge_max(msg).incremented(int(id))
        return ObserverState(merged, state.saw_concurrent or concurrent)


def vector_clock_model() -> ActorModel:
    """Two independent senders + one observer: the checker proves the two
    events are concurrent (neither causally precedes the other) by
    discovering an observer state with ``saw_concurrent`` set."""
    m = ActorModel(cfg=None)
    m.actor(StampedSender(observer=Id(2)))
    m.actor(StampedSender(observer=Id(2)))
    m.actor(ClockObserver())
    # non-duplicating: the observer bumps its clock per delivery, so under
    # the (default) duplicating network redelivery would grow states forever
    m.init_network_(Network.new_unordered_nonduplicating())
    m.property(
        Expectation.SOMETIMES,
        "concurrency detected",
        lambda model, s: s.actor_states[2].saw_concurrent,
    )
    return m


def _audit_models(rest=()):
    """Default configurations for the static auditor (the fleet runner,
    ``_cli.fleet_audit``).  The Lamport clock model is expected to carry
    an AH205 finding: logical clocks grow without bound — exactly the
    growing-domain pattern the rule exists for (the model itself is
    bounded by ``within_boundary``, which device compilation would still
    need as a ``state_bound``)."""
    return [
        ("quickstart sliding_puzzle", SlidingPuzzle()),
        ("quickstart lamport_clocks", clock_model()),
        ("quickstart vector_clocks", vector_clock_model()),
    ]


def main() -> None:
    path = solve_puzzle()
    moves = path.actions()
    print(f"puzzle solved in {len(moves)} moves:")
    for step in moves:
        print(f"  slide {step}")
    trace = clock_counterexample()
    n = len(trace.actions())
    print(f"logical clocks exceed the bound after {n} deliveries;")
    print(f"final clocks: {list(trace.final_state().actor_states)}")


if __name__ == "__main__":
    main()
