"""Tensor (device) twin of the Paxos register system — the benchmark model.

Encodes the full :class:`~stateright_tpu.actor.model.ActorModelState` of
``paxos_model(C, 3)`` — three server actor states, C register clients, the
in-flight message multiset, and the linearizability-tester history — into
fixed-width ``uint64`` rows, with the complete protocol step (deliver →
handler → sends → history update) as one vectorized jittable kernel
(SURVEY §7.1 "the hard part": actor systems compiled to tensor form).

Design notes:

 - **Network**: sorted-slot multiset (``parallel/actor_tensor.py``); one
   deliver action per occupied slot, matching the object model's
   one-``Deliver``-per-distinct-envelope actions (``actor/model.py``,
   reference ``src/actor/model.rs:214-239``).
 - **Message universe**: every Paxos message is determined by a handful of
   small fields (kind, src, dst, ballot round/leader, and an aux payload:
   a proposal's client index, a ``last_accepted`` code, or a read value).
   Field widths are C-dependent (~21 bits at C ≤ 3, ~26 at C = 7), far
   inside the slot codec's 58-bit envelope budget.  Request ids and values
   are derivable: client ``i``'s put is always ``Put(3+i, chr(65+i))`` and
   its get ``Get(2*(3+i))`` (``actor/register.py``).
 - **History**: with ``put_count=1`` clients, the linearizability tester's
   state is a function of (per-thread phase, read return value, and the
   read-invocation snapshot of peer completion counts).
 - **Linearizable property**: evaluated *on device* by the closure strategy
   (``parallel/history_tensor.py::closure_verdict``): the exhaustive
   interleaving search of the reference
   (``src/semantics/linearizability.rs:178-240``) reduces exactly, for this
   workload, to an acyclicity check on a C×C write-precedence graph —
   O(C³ log C) vectorized boolean ops per state, which is what lets the
   twin scale to the reference's ``paxos check 6`` bench configuration
   (an earlier revision used a (2C)! permutation table, capped at C = 3).
 - **Field widths** are computed from C (ballot rounds ≤ C since each put
   starts exactly one ballot; ``last_accepted`` codes grow with C·rnd), so
   one row layout serves C = 1..7; the C ≤ 7 cap comes from the 3-bit read
   value code and the closure strategy's own cap.
 - **No-op pruning** parity: deliveries whose handler returns None with no
   sends are masked invalid, exactly mirroring the object model's prune
   (reference ``model.rs:253-260``); equality-returning handlers (e.g. a
   duplicate ``Accepted``) still count as transitions.
"""

from __future__ import annotations

import numpy as np

from ..actor import Id
from ..actor.network import Envelope, UnorderedNonDuplicatingNetwork
from ..actor.register import NULL_VALUE
from ..actor.model import ActorModelState
from ..parallel.actor_tensor import (
    COUNT_BITS,
    COUNT_MASK,
    SLOT_EMPTY,
    SlotCodec,
    slot_canonicalize,
    slot_send,
)
from ..parallel.tensor_model import BitPacker, FieldWriter, TensorModel
from ..semantics.linearizability import LinearizabilityTester
from ..semantics.register import READ, Register, write

S = 3  # servers (the benchmark configuration is fixed at 3)

# message kinds
PUT, GET, PUT_OK, GET_OK = 1, 2, 3, 4
PREPARE, PREPARED, ACCEPT, ACCEPTED, DECIDED = 5, 6, 7, 8, 9

MAX_CLIENTS = 7  # 3-bit read-value code + the closure strategy's own cap


class PaxosTensor(TensorModel):
    """Device twin of ``paxos_model(client_count, 3)`` on an unordered
    non-duplicating network (the reference benchmark configuration,
    ``examples/paxos.rs:323-338``)."""

    #: this hand-tuned twin packs the network as ONE sorted slot multiset
    #: too, so the independence analysis's JX305 escape-hatch pointer
    #: applies: ``PaxosModel.per_channel_()`` routes to the mechanical
    #: compiler's per-channel layout (docs/analysis.md)
    network_encoding = "slot-multiset"

    def __init__(self, model, client_count: int, n_slots: int | None = None):
        if client_count > MAX_CLIENTS:
            raise ValueError(
                f"tensor paxos supports <={MAX_CLIENTS} clients"
            )
        self.model = model
        self.C = C = client_count
        self.n_slots = n_slots if n_slots is not None else max(16, 10 * C)
        self.max_actions = self.n_slots

        # -- C-dependent widths --------------------------------------------
        # Each put starts exactly one ballot (k_put consumes one of the C PUT
        # envelopes on a non-duplicating network), so rounds never exceed C.
        self.max_rnd = max_rnd = max(C, 1)
        la_max = 1 + ((max_rnd - 1) * S + (S - 1)) * C + (C - 1)
        self._aux_b = max(6, la_max.bit_length())
        self._rnd_b = max(3, max_rnd.bit_length())
        self._id_b = max(3, (S + C - 1).bit_length())
        # envelope code bit layout: kind | src | dst | rnd | ldr | aux
        self._ldr_s = self._aux_b
        self._rnd_s = self._ldr_s + 2
        self._dst_s = self._rnd_s + self._rnd_b
        self._src_s = self._dst_s + self._id_b
        self._kind_s = self._src_s + self._id_b
        self._la_max = la_max
        prep_b = (la_max + 1).bit_length()
        prop_b = max(3, (C + 1).bit_length())

        fields = []
        for s in range(S):
            fields += [
                (f"s{s}_rnd", self._rnd_b),
                (f"s{s}_ldr", 2),
                (f"s{s}_prop", prop_b),
                (f"s{s}_prep0", prep_b),
                (f"s{s}_prep1", prep_b),
                (f"s{s}_prep2", prep_b),
                (f"s{s}_acc", 3),
                (f"s{s}_accd", self._aux_b),
                (f"s{s}_dec", 1),
            ]
        for c in range(C):
            fields += [
                (f"c{c}_phase", 2),
                (f"c{c}_rval", 3),
                (f"c{c}_snap", 2 * C),
            ]
        fields += [("hvalid", 1), ("overflow", 1)]
        self.pk = BitPacker(fields)
        self.pw = self.pk.width
        self.width = self.pw + self.n_slots
        self.codec = SlotCodec(self.n_slots, self._encode_env, self._decode_env)

    # ------------------------------------------------------------------
    # host-side: la / proposal / envelope codes
    # ------------------------------------------------------------------

    def _la_code(self, la) -> int:
        """Option<(Ballot, Proposal)> -> ``_aux_b``-bit code; numeric order
        matches the tuple order used by the prepare-quorum ``max``
        (``paxos.py``)."""
        if la is None:
            return 0
        (rnd, ldr), proposal = la
        ci = int(proposal[1]) - S
        code = 1 + ((rnd - 1) * S + int(ldr)) * self.C + ci
        assert 0 < code <= self._la_max, la
        return code

    def _la_decode(self, code: int):
        if code == 0:
            return None
        x = code - 1
        ci = x % self.C
        x //= self.C
        ldr = x % S
        rnd = x // S + 1
        return ((rnd, Id(ldr)), self._proposal(ci))

    def _proposal(self, ci: int) -> tuple:
        return (S + ci, Id(S + ci), chr(ord("A") + ci))

    def _encode_env(self, env: Envelope) -> int:
        kind = src = dst = rnd = ldr = aux = 0
        src, dst = int(env.src), int(env.dst)
        m = env.msg
        if m[0] == "put":
            kind = PUT
        elif m[0] == "get":
            kind = GET
        elif m[0] == "put_ok":
            kind = PUT_OK
        elif m[0] == "get_ok":
            kind, aux = GET_OK, self._value_code(m[2])
        else:  # internal
            im = m[1]
            (rnd, ldr_id) = im[1]
            ldr = int(ldr_id)
            if im[0] == "prepare":
                kind = PREPARE
            elif im[0] == "prepared":
                kind, aux = PREPARED, self._la_code(im[2])
            elif im[0] == "accept":
                kind, aux = ACCEPT, int(im[2][1]) - S
            elif im[0] == "accepted":
                kind = ACCEPTED
            elif im[0] == "decided":
                kind, aux = DECIDED, int(im[2][1]) - S
            else:
                raise ValueError(f"unknown internal message {im!r}")
        assert rnd <= self.max_rnd and aux < (1 << self._aux_b), env
        return (
            (kind << self._kind_s)
            | (src << self._src_s)
            | (dst << self._dst_s)
            | (rnd << self._rnd_s)
            | (ldr << self._ldr_s)
            | aux
        )

    def _decode_env(self, code: int) -> Envelope:
        idm = (1 << self._id_b) - 1
        aux = code & ((1 << self._aux_b) - 1)
        ldr = (code >> self._ldr_s) & 3
        rnd = (code >> self._rnd_s) & ((1 << self._rnd_b) - 1)
        dst = (code >> self._dst_s) & idm
        src = (code >> self._src_s) & idm
        kind = code >> self._kind_s
        ballot = (rnd, Id(ldr))
        if kind == PUT:
            ci = src - S
            msg = ("put", S + ci, chr(ord("A") + ci))
        elif kind == GET:
            msg = ("get", 2 * src)
        elif kind == PUT_OK:
            msg = ("put_ok", dst)
        elif kind == GET_OK:
            msg = ("get_ok", 2 * dst, self._value_decode(aux))
        elif kind == PREPARE:
            msg = ("internal", ("prepare", ballot))
        elif kind == PREPARED:
            msg = ("internal", ("prepared", ballot, self._la_decode(aux)))
        elif kind == ACCEPT:
            msg = ("internal", ("accept", ballot, self._proposal(aux)))
        elif kind == ACCEPTED:
            msg = ("internal", ("accepted", ballot))
        elif kind == DECIDED:
            msg = ("internal", ("decided", ballot, self._proposal(aux)))
        else:
            raise ValueError(f"bad envelope code {code:#x}")
        return Envelope(src=Id(src), dst=Id(dst), msg=msg)

    def _value_code(self, v: str) -> int:
        return 0 if v == NULL_VALUE else ord(v) - ord("A") + 1

    def _value_decode(self, code: int) -> str:
        return NULL_VALUE if code == 0 else chr(ord("A") + code - 1)

    # ------------------------------------------------------------------
    # host-side: state <-> row
    # ------------------------------------------------------------------

    def encode_state(self, st: ActorModelState) -> tuple:
        C = self.C
        vals: dict[str, int] = {}
        for s in range(S):
            a = st.actor_states[s]
            rnd, ldr = a.ballot
            assert rnd <= self.max_rnd, a
            vals[f"s{s}_rnd"] = rnd
            vals[f"s{s}_ldr"] = int(ldr)
            vals[f"s{s}_prop"] = (
                0 if a.proposal is None else int(a.proposal[1]) - S + 1
            )
            prep = dict(a.prepares)
            for j in range(S):
                la = prep.get(Id(j), "absent")
                vals[f"s{s}_prep{j}"] = (
                    0 if la == "absent" else 1 + self._la_code(la)
                )
            vals[f"s{s}_acc"] = sum(1 << int(i) for i in a.accepts)
            vals[f"s{s}_accd"] = self._la_code(a.accepted)
            vals[f"s{s}_dec"] = int(a.is_decided)

        tester: LinearizabilityTester = st.history
        for c in range(C):
            thread = S + c
            cs = st.actor_states[thread]
            completed = tester.history_by_thread.get(thread, ())
            in_flight = tester.in_flight_by_thread.get(thread)
            phase = len(completed)
            assert (phase == 2) == (in_flight is None), (c, tester)
            # client actor state is in lockstep with the tester phase
            expect = {
                0: (thread, 1),
                1: (2 * thread, 2),
                2: (None, 3),
            }[phase]
            assert (cs.awaiting, cs.op_count) == expect, (c, cs, phase)
            vals[f"c{c}_phase"] = phase
            rval = 0
            snap_src = None
            if phase == 2:
                snap_src, op, ret = completed[1]
                assert op == READ and ret[0] == "read_ok", completed
                rval = self._value_code(ret[1])
            elif phase == 1:
                snap_src, op = in_flight
                assert op == READ, in_flight
            if phase >= 1:
                assert completed[0][0] == () and completed[0][1] == write(
                    chr(ord("A") + c)
                ), completed
            snap = 0
            if snap_src is not None:
                for peer, idx in snap_src:
                    t = int(peer) - S
                    assert 0 <= t < C and 0 <= idx <= 1, snap_src
                    snap |= (idx + 1) << (2 * t)
            vals[f"c{c}_rval"] = rval
            vals[f"c{c}_snap"] = snap
        vals["hvalid"] = int(tester.valid)
        vals["overflow"] = 0

        counts = st.network._counts
        return self.pk.pack(**vals) + self.codec.pack(
            (env, cnt) for env, cnt in counts.items()
        )

    def decode_state(self, row) -> ActorModelState:
        from ..models.paxos import PaxosState

        C = self.C
        d = self.pk.unpack(row[: self.pw])
        if d["overflow"]:
            raise RuntimeError(
                "network slot overflow: raise n_slots on PaxosTensor"
            )
        actors = []
        for s in range(S):
            prepares = tuple(
                sorted(
                    (Id(j), self._la_decode(d[f"s{s}_prep{j}"] - 1))
                    for j in range(S)
                    if d[f"s{s}_prep{j}"] > 0
                )
            )
            prop = d[f"s{s}_prop"]
            actors.append(
                PaxosState(
                    ballot=(d[f"s{s}_rnd"], Id(d[f"s{s}_ldr"])),
                    proposal=None if prop == 0 else self._proposal(prop - 1),
                    prepares=prepares,
                    accepts=frozenset(
                        Id(i) for i in range(S) if d[f"s{s}_acc"] & (1 << i)
                    ),
                    accepted=self._la_decode(d[f"s{s}_accd"]),
                    is_decided=bool(d[f"s{s}_dec"]),
                )
            )

        from ..actor.register import RegisterClientState

        history: dict[int, tuple] = {}
        in_flight: dict[int, tuple] = {}
        for c in range(C):
            thread = S + c
            phase = d[f"c{c}_phase"]
            snap = tuple(
                sorted(
                    (S + t, ((d[f"c{c}_snap"] >> (2 * t)) & 3) - 1)
                    for t in range(C)
                    if (d[f"c{c}_snap"] >> (2 * t)) & 3
                )
            )
            w_complete = ((), write(chr(ord("A") + c)), ("write_ok",))
            if phase == 0:
                history[thread] = ()
                in_flight[thread] = ((), write(chr(ord("A") + c)))
                cs = RegisterClientState(awaiting=thread, op_count=1)
            elif phase == 1:
                history[thread] = (w_complete,)
                in_flight[thread] = (snap, READ)
                cs = RegisterClientState(awaiting=2 * thread, op_count=2)
            else:
                rv = self._value_decode(d[f"c{c}_rval"])
                history[thread] = (w_complete, (snap, READ, ("read_ok", rv)))
                cs = RegisterClientState(awaiting=None, op_count=3)
            actors.append(cs)

        tester = LinearizabilityTester(
            Register(NULL_VALUE),
            history,
            in_flight,
            valid=bool(d["hvalid"]),
        )
        network = UnorderedNonDuplicatingNetwork(
            dict(self.codec.unpack(row[self.pw :]))
        )
        return ActorModelState(
            actor_states=tuple(actors),
            network=network,
            is_timer_set=(False,) * (S + C),
            history=tester,
        )

    def init_rows(self) -> np.ndarray:
        return np.asarray(
            [self.encode_state(s) for s in self.model.init_states()],
            np.uint64,
        )

    # ------------------------------------------------------------------
    # device-side
    # ------------------------------------------------------------------

    def step_rows(self, rows):
        return self._step_rows_impl(rows, coalesce=False)

    def step_rows_coalesced(self, rows):
        """Expand-scatter-coalesced step (``ops/mxu.py``, docs/roofline.md):
        the same transition function with the packed-word write-backs
        assembled as ONE word-stacked block (``FieldWriter`` coalesced
        mode) instead of 37 per-field scatters — the JX400 #1 expand hot
        spot on paxos-3.  Successors and validity are bit-identical to
        :meth:`step_rows` (whole-space parity pinned in tests); only the
        assembly shape changes.  Selected by the engines under
        ``CheckerBuilder.mxu()`` / ``--mxu``."""
        return self._step_rows_impl(rows, coalesce=True)

    def _step_rows_impl(self, rows, coalesce):
        import jax.numpy as jnp

        C, NS, pk = self.C, self.n_slots, self.pk
        i32 = jnp.int32
        u64 = jnp.uint64
        B = rows.shape[0]
        A = NS
        W = self.width

        slots = rows[:, self.pw :]  # [B, NS]
        code = slots >> u64(COUNT_BITS)
        count = (slots & u64(COUNT_MASK)).astype(i32)
        occupied = slots != u64(SLOT_EMPTY)

        # envelope fields per slot (= per action)  [B, A]
        idm = u64((1 << self._id_b) - 1)
        aux = (code & u64((1 << self._aux_b) - 1)).astype(i32)
        ldr = ((code >> u64(self._ldr_s)) & u64(3)).astype(i32)
        rnd = (
            (code >> u64(self._rnd_s)) & u64((1 << self._rnd_b) - 1)
        ).astype(i32)
        dst = ((code >> u64(self._dst_s)) & idm).astype(i32)
        src = ((code >> u64(self._src_s)) & idm).astype(i32)
        kind = (code >> u64(self._kind_s)).astype(i32)
        eb = rnd * 4 + ldr  # env ballot, lexicographic key

        def gi(name):  # packed field as [B, 1] int32 (broadcasts over A)
            return pk.get(rows, name).astype(i32)[:, None]

        # server fields stacked [B, S]; then gathered at dst -> [B, A]
        srv = {
            f: jnp.concatenate([gi(f"s{s}_{f}") for s in range(S)], axis=1)
            for f in (
                "rnd", "ldr", "prop", "prep0", "prep1", "prep2",
                "acc", "accd", "dec",
            )
        }
        dstc = jnp.clip(dst, 0, S - 1)

        def at_dst(f):  # [B, A]
            return jnp.take_along_axis(srv[f], dstc, axis=1)

        srnd, sldr = at_dst("rnd"), at_dst("ldr")
        sprop, sacc, saccd, sdec = (
            at_dst("prop"), at_dst("acc"), at_dst("accd"), at_dst("dec"),
        )
        sprep = [at_dst(f"prep{j}") for j in range(S)]
        sb = srnd * 4 + sldr
        is_server = dst < S
        undecided = is_server & (sdec == 0)

        # client fields at dst  [B, A]
        if C > 0:
            cph = jnp.concatenate([gi(f"c{c}_phase") for c in range(C)], axis=1)
            clic = jnp.clip(dst - S, 0, C - 1)
            cphase = jnp.take_along_axis(cph, clic, axis=1)
            # peer phases for the read-invocation snapshot: snap bits over all
            # threads (self slot left 0)
            allph = cph  # [B, C]
        is_client = dst >= S

        def la_code(r, l, ci):
            return 1 + ((r - 1) * S + l) * C + ci

        def ci_of_la(la):
            return (la - 1) % C

        # -- branch masks ---------------------------------------------------
        k_put = (kind == PUT) & undecided & (sprop == 0)
        k_prepare = (kind == PREPARE) & undecided & (sb < eb)
        k_prepared = (kind == PREPARED) & undecided & (eb == sb)
        k_accept = (kind == ACCEPT) & undecided & (sb <= eb)
        k_accepted = (kind == ACCEPTED) & undecided & (eb == sb)
        k_decided = (kind == DECIDED) & undecided
        k_getdec = (kind == GET) & is_server & (sdec == 1)
        k_cputok = (kind == PUT_OK) & is_client & (cphase == 0)
        k_cgetok = (kind == GET_OK) & is_client & (cphase == 1)
        valid = occupied & (
            k_put | k_prepare | k_prepared | k_accept | k_accepted
            | k_decided | k_getdec | k_cputok | k_cgetok
        )

        # -- server successor fields (computed "at dst") --------------------
        ci_src = src - S  # for put: the client index
        put_rnd = srnd + 1

        # prepared bookkeeping
        la_in = aux
        prep_new = [
            jnp.where(
                k_prepared & (src == j),
                1 + la_in,
                jnp.where(k_put, jnp.where(dst == j, 1 + saccd, 0), sprep[j]),
            )
            for j in range(S)
        ]
        prep_count = sum((p > 0).astype(i32) for p in prep_new)
        best_la = (
            jnp.maximum(jnp.maximum(prep_new[0], prep_new[1]), prep_new[2]) - 1
        )
        quorum_p = k_prepared & (prep_count == 2)
        # adopt the most recently accepted proposal from the quorum, else keep
        prop_adopt = jnp.where(best_la > 0, ci_of_la(best_la) + 1, sprop)

        acc_new = jnp.where(
            quorum_p,
            1 << dstc,
            jnp.where(k_put, 0, jnp.where(k_accepted, sacc | (1 << src), sacc)),
        )
        acc_pop = (
            (acc_new & 1) + ((acc_new >> 1) & 1) + ((acc_new >> 2) & 1)
        )
        quorum_a = k_accepted & (acc_pop == 2)

        new_rnd = jnp.where(
            k_put,
            put_rnd,
            jnp.where(k_prepare | k_accept | k_decided, rnd, srnd),
        )
        new_ldr = jnp.where(
            k_put, dstc, jnp.where(k_prepare | k_accept | k_decided, ldr, sldr)
        )
        new_prop = jnp.where(
            k_put, ci_src + 1, jnp.where(quorum_p, prop_adopt, sprop)
        )
        new_accd = jnp.where(
            quorum_p,
            la_code(srnd, sldr, prop_adopt - 1),
            jnp.where(
                k_accept | k_decided, la_code(rnd, ldr, aux), saccd
            ),
        )
        new_dec = jnp.where(quorum_a | k_decided, 1, sdec)

        # -- client successor fields ----------------------------------------
        if C > 0:
            new_phase = jnp.where(
                k_cputok, 1, jnp.where(k_cgetok, 2, cphase)
            )
            new_rval = jnp.where(k_cgetok, aux, 0)
            # snapshot at get-invocation: peer completed counts == phases
            snap_val = jnp.zeros_like(dst)
            for t in range(C):
                peer_phase = jnp.minimum(allph[:, t : t + 1], 2)
                contrib = jnp.where(clic == t, 0, peer_phase) << (2 * t)
                snap_val = snap_val + jnp.where(k_cputok, contrib, 0)

        # -- sends (3 channels) ---------------------------------------------
        def env_code(knd, esrc, edst, ernd, eldr, eaux):
            z = jnp.zeros_like(dst)
            return (
                ((z + knd).astype(u64) << u64(self._kind_s))
                | (esrc.astype(u64) << u64(self._src_s))
                | (edst.astype(u64) << u64(self._dst_s))
                | (ernd.astype(u64) << u64(self._rnd_s))
                | (eldr.astype(u64) << u64(self._ldr_s))
                | eaux.astype(u64)
            )

        z = jnp.zeros_like(dst)
        p1 = jnp.where(dstc + 1 >= S, dstc + 1 - S, dstc + 1)
        p2 = jnp.where(dstc + 2 >= S, dstc + 2 - S, dstc + 2)

        # ch0: single-target sends
        ch0_en = k_prepare | k_accept | quorum_a | k_getdec | k_cputok
        ch0_code = jnp.where(
            k_prepare,
            env_code(PREPARED, dst, src, rnd, ldr, saccd),
            jnp.where(
                k_accept,
                env_code(ACCEPTED, dst, src, rnd, ldr, z),
                jnp.where(
                    quorum_a,
                    env_code(PUT_OK, dst, S + sprop - 1, z, z, z),
                    jnp.where(
                        k_getdec,
                        env_code(
                            GET_OK, dst, src, z, z, ci_of_la(saccd) + 1
                        ),
                        # k_cputok: the follow-up get, to server
                        # (index + op_count) % S with op_count == 1
                        env_code(GET, dst, (dst + 1) % S, z, z, z),
                    ),
                ),
            ),
        )

        # ch1/ch2: peer broadcasts (prepare / accept / decided)
        bcast = k_put | quorum_p | quorum_a
        bc_kind = jnp.where(k_put, PREPARE, jnp.where(quorum_p, ACCEPT, DECIDED))
        bc_rnd = jnp.where(k_put, put_rnd, srnd)
        bc_ldr = jnp.where(k_put, dstc, sldr)
        bc_aux = jnp.where(
            quorum_p, prop_adopt - 1, jnp.where(quorum_a, sprop - 1, z)
        )
        ch1_code = env_code(bc_kind, dst, p1, bc_rnd, bc_ldr, bc_aux)
        ch2_code = env_code(bc_kind, dst, p2, bc_rnd, bc_ldr, bc_aux)

        # -- assemble successor slot arrays ---------------------------------
        slots_b = jnp.broadcast_to(slots[:, None, :], (B, A, NS))
        diag = jnp.eye(A, NS, dtype=bool)[None]  # deliver slot a of action a
        neww = jnp.where(
            count <= 1, u64(SLOT_EMPTY), slots - u64(1)
        )  # [B, A] value for the delivered slot
        slots_d = jnp.where(diag, neww[:, :, None], slots_b)

        of = jnp.zeros((B, A), bool)
        for en, cd in (
            (ch0_en, ch0_code),
            (bcast, ch1_code),
            (bcast, ch2_code),
        ):
            slots_d, o = slot_send(slots_d, cd, en & valid)
            of = of | o
        slots_d = slot_canonicalize(slots_d)

        # -- assemble successor packed words --------------------------------
        # eager: the pre-writer broadcast + per-field pk.set trace,
        # bit-identical (pinned).  Coalesced: the base block covers only
        # the packed words and the writer assembles them as one
        # word-stacked concatenate (FieldWriter; ops/mxu.py).
        if coalesce:
            base = jnp.broadcast_to(
                rows[:, None, : self.pw], (B, A, self.pw)
            )
        else:
            base = jnp.broadcast_to(rows[:, None, :], (B, A, W))
        fw = FieldWriter(pk, base, coalesce=coalesce)

        def scatter_server(name, new_val, old_stacked):
            for s in range(S):
                old = old_stacked[:, s : s + 1]
                v = jnp.where(valid & is_server & (dst == s), new_val, old)
                fw.set(f"s{s}_{name}", v.astype(u64))

        scatter_server("rnd", new_rnd, srv["rnd"])
        scatter_server("ldr", new_ldr, srv["ldr"])
        scatter_server("prop", new_prop, srv["prop"])
        for j in range(S):
            scatter_server(f"prep{j}", prep_new[j], srv[f"prep{j}"])
        scatter_server("acc", acc_new, srv["acc"])
        scatter_server("accd", new_accd, srv["accd"])
        scatter_server("dec", new_dec, srv["dec"])

        for c in range(C):
            m = valid & is_client & (dst == S + c)
            fw.set(
                f"c{c}_phase",
                jnp.where(m, new_phase, cph[:, c : c + 1]).astype(u64),
            )
            fw.set(
                f"c{c}_rval",
                jnp.where(
                    m & k_cgetok, new_rval, gi(f"c{c}_rval")
                ).astype(u64),
            )
            fw.set(
                f"c{c}_snap",
                jnp.where(
                    m & k_cputok, snap_val, gi(f"c{c}_snap")
                ).astype(u64),
            )
        fw.set(
            "overflow",
            jnp.maximum(
                jnp.where(of, 1, 0), gi("overflow")
            ).astype(u64),
        )
        out = fw.done()

        if coalesce:
            succ = jnp.concatenate([out, slots_d], axis=-1)
        else:
            succ = jnp.concatenate(
                [out[:, :, : self.pw], slots_d], axis=-1
            )
        return succ, valid

    def property_masks(self, rows):
        import jax.numpy as jnp

        from ..parallel.history_tensor import closure_verdict

        C, pk = self.C, self.pk
        i32 = jnp.int32
        B = rows.shape[0]

        phase = jnp.stack(
            [pk.get(rows, f"c{c}_phase").astype(i32) for c in range(C)], -1
        )  # [B, C]
        rval = jnp.stack(
            [pk.get(rows, f"c{c}_rval").astype(i32) for c in range(C)], -1
        )
        snap = jnp.stack(
            [pk.get(rows, f"c{c}_snap").astype(i32) for c in range(C)], -1
        )
        hvalid = pk.get(rows, "hvalid") == jnp.uint64(1)

        # s[b, i, t] = ops thread t had completed when thread i's read was
        # invoked (the snapshot recorded at get-invocation; self slot 0)
        done = phase == 2
        s = jnp.zeros((B, C, C), i32)
        for i in range(C):
            for t in range(C):
                if t == i:
                    continue
                s = s.at[:, i, t].set((snap[:, i] >> (2 * t)) & 3)
        linearizable = closure_verdict(done, s, rval) & hvalid

        # "value chosen": some get_ok with a non-null value is in flight
        slots = rows[:, self.pw :]
        code = slots >> jnp.uint64(COUNT_BITS)
        occ = slots != jnp.uint64(SLOT_EMPTY)
        kind = (code >> jnp.uint64(self._kind_s)).astype(i32)
        aux = (code & jnp.uint64((1 << self._aux_b) - 1)).astype(i32)
        chosen = jnp.any(occ & (kind == GET_OK) & (aux > 0), axis=-1)

        return jnp.stack([linearizable, chosen], axis=-1)
